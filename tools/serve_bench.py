"""Serving-layer load generator + SERVE_r0x.json artifact.

Boots a TfidfServer in-process over a synthetic Zipf corpus (or
--input) and drives it with either a CLOSED loop (N worker threads,
back-to-back requests — peak-throughput shape) or an OPEN loop
(Poisson-ish fixed arrival rate via --rate — latency-under-load
shape, where queueing and shedding actually show). Queries draw from a
Zipf-weighted pool so the result cache sees a realistic hot tail.

Emits one JSON artifact with the SLO receipts: throughput (rps/qps),
latency p50/p99, mean batch occupancy, cache hit rate, shed rate —
plus a recompile receipt: after warmup (one search per power-of-two
query bucket), steady-state serving must trigger ZERO fresh XLA
compiles (`models.retrieval._search_bcoo` cache size is checked before
and after the run). The slow-marked smoke in tests/test_serve.py runs
this at --requests 64 and asserts the artifact schema.

Usage: python tools/serve_bench.py --requests 256 --out SERVE_r01.json
"""

from __future__ import annotations

import argparse
import collections
import json
import os
import shutil
import sys
import tempfile
import threading
import time

import _common  # noqa: E402,F401  repo-root sys.path bootstrap

import numpy as np  # noqa: E402


def make_queries(rng, pool_size, n_words, qlen):
    """Zipf-weighted query pool: a few hot queries, a long cold tail."""
    pool = [" ".join(f"w{rng.integers(0, n_words)}" for _ in range(qlen))
            for _ in range(pool_size)]

    def draw():
        idx = min(int(rng.zipf(1.3)) - 1, pool_size - 1)
        return pool[idx]
    return draw


def _percentiles(xs):
    if not xs:
        return {"p50": 0.0, "p99": 0.0, "max": 0.0}
    xs = sorted(xs)
    pick = lambda q: xs[min(len(xs) - 1, int(q * len(xs)))]  # noqa: E731
    return {"p50": round(pick(0.50), 3), "p99": round(pick(0.99), 3),
            "max": round(xs[-1], 3)}


def run_mutate(args, input_dir) -> int:
    """The --mutate workload: Zipf queries + a live add/update/delete
    stream against one SegmentedIndex-backed server. Every mutation's
    visibility lag (op issue -> epoch installed) is measured, the
    background compactor runs supervised, and the run ends with a
    from-scratch rebuild-parity verdict — the acceptance receipts of
    ROADMAP item 2 in one MUTATE_r0x.json artifact."""
    import bench as benchmod
    import jax

    from tfidf_tpu import obs
    from tfidf_tpu.config import PipelineConfig, ServeConfig, VocabMode
    from tfidf_tpu.index import (Compactor, SegmentedIndex,
                                 index_compile_cache_size)
    from tfidf_tpu.serve import ServeError, TfidfServer

    log = obs.get_log()
    cfg = PipelineConfig(vocab_mode=VocabMode.HASHED,
                         vocab_size=benchmod.VOCAB,
                         max_doc_len=args.doc_len)
    t0 = time.perf_counter()
    segidx = SegmentedIndex.from_dir(input_dir, cfg, strict=False,
                                     delta_docs=args.delta_docs,
                                     compact_at=args.compact_at)
    index_s = time.perf_counter() - t0
    # Chaos arms AFTER the warm cycle (below): the warm compactions
    # must run clean so the injected kills land in the measured
    # window, where the supervised compactor has to absorb them.
    serve_cfg = ServeConfig(
        max_batch=args.max_batch, max_wait_ms=args.max_wait_ms,
        queue_depth=args.queue_depth, cache_entries=args.cache_entries,
        default_deadline_ms=args.deadline_ms,
        delta_docs=args.delta_docs, compact_at=args.compact_at)
    server = TfidfServer(segidx.view(), serve_cfg)
    server.attach_segments(segidx)
    rng = np.random.default_rng(args.seed)
    draw = make_queries(rng, args.pool, benchmod.N_WORDS, qlen=4)
    sizes = [int(s) for s in args.queries_per_request.split(",")]

    def synth_doc():
        return " ".join(f"w{rng.integers(0, benchmod.N_WORDS)}"
                        for _ in range(16))

    buckets, b = set(), 1
    while b < max(args.max_batch, max(sizes)):
        buckets.add(b)
        b *= 2
    buckets.add(b)

    def bucket_warm():
        # Cache BYPASSED: a partial cache hit would shrink the
        # coalesced batch below nb and leave that (Q-bucket x
        # segment-count) program uncompiled — to surface as a
        # steady-state recompile the moment a real batch misses.
        for nb in sorted(buckets):
            server.submit([draw() for _ in range(nb)], args.k,
                          use_cache=False).result(timeout=120)

    # Warm-up: one full segment LIFECYCLE (delta fill -> seal ->
    # compaction, twice — the second pass runs at the post-compaction
    # merged capacity) with the query buckets touched at every
    # segment-count state, so steady-state mutation re-runs warm
    # programs only. Everything after mark_warm must be 0 recompiles.
    bucket_warm()
    warm_i = 0
    compactions_done = 0
    while compactions_done < 2:
        server.add_docs([f"warm{warm_i}"], [synth_doc()])
        warm_i += 1
        if segidx.needs_compaction:
            bucket_warm()          # warm the max-segment-count shapes
            server.compact_now()
            compactions_done += 1
            bucket_warm()          # warm the post-compaction shapes
    bucket_warm()
    compiles_warm = index_compile_cache_size()
    server.mark_warm()
    log.info("serve_bench",
             msg=f"mutate warm cycle: {warm_i} adds, "
                 f"{compactions_done} compactions, "
                 f"{compiles_warm} index programs compiled")

    armed_plan = None
    if args.chaos:
        from tfidf_tpu import faults as faults_mod
        armed_plan = faults_mod.FaultPlan.parse(args.chaos,
                                                seed=args.chaos_seed)
        faults_mod.arm(armed_plan)
    compactor = Compactor(server.compact_now, period_s=0.05,
                          restart_budget=serve_cfg.restart_budget
                          ).start()
    pauses_before = len(segidx.compactions)

    lags_ms = []
    mut_counts = {"add": 0, "update": 0, "delete": 0, "failed": 0}
    added = []
    lock = threading.Lock()
    shed = [0]
    done = [0]

    def mutator():
        i = 0
        while i < args.mutations:
            t1 = time.perf_counter()
            try:
                if i % 3 == 0 or not added:
                    name = f"mut{i}"
                    server.add_docs([name], [synth_doc()])
                    with lock:
                        added.append(name)
                        mut_counts["add"] += 1
                elif i % 3 == 1:
                    with lock:
                        name = added[i % len(added)]
                    server.add_docs([name], [synth_doc()])
                    with lock:
                        mut_counts["update"] += 1
                else:
                    with lock:
                        name = added.pop(0)
                    server.delete_docs([name])
                    with lock:
                        mut_counts["delete"] += 1
                with lock:
                    lags_ms.append((time.perf_counter() - t1) * 1e3)
            except Exception:  # noqa: BLE001 — count and keep loading
                with lock:
                    mut_counts["failed"] += 1
            i += 1
            if args.mutate > 0:
                time.sleep(1.0 / args.mutate)

    def query_worker():
        while True:
            with lock:
                if done[0] >= args.requests:
                    return
                i = done[0]
                done[0] += 1
            qs = [draw() for _ in range(sizes[i % len(sizes)])]
            try:
                server.search(qs, k=args.k)
            except ServeError:
                with lock:
                    shed[0] += 1

    t_run = time.perf_counter()
    mut_thread = threading.Thread(target=mutator)
    workers = [threading.Thread(target=query_worker)
               for _ in range(args.concurrency)]
    mut_thread.start()
    for th in workers:
        th.start()
    mut_thread.join()
    for th in workers:
        th.join()
    wall = time.perf_counter() - t_run
    # Let the supervised compactor drain any pending merge (absorbing
    # every armed kill) before stopping — the chaos receipts below
    # must reflect a settled index, not a race with shutdown.
    t_wait = time.perf_counter()
    while (segidx.needs_compaction and not compactor.dead
           and time.perf_counter() - t_wait < 10.0):
        time.sleep(0.02)
    compactor.stop()
    if armed_plan is not None:
        from tfidf_tpu import faults as faults_mod
        faults_mod.disarm()
    recompiles = index_compile_cache_size() - compiles_warm

    # Parity verdict: the quiesced live index vs a FROM-SCRATCH
    # rebuild of the live corpus — responses must map to identical
    # (name, score) rows, byte for byte.
    pinned = [draw() for _ in range(8)]
    svals, sids = server.submit(pinned, args.k,
                                use_cache=False).result(timeout=60)
    names = server.doc_names()
    # Final health: two evaluations so chaos-provoked shed windows
    # have decayed (the chaos path's discipline); the breaker must
    # have closed for the run to count as recovered.
    server.health.evaluate()
    final_health = server.health.evaluate().state
    breaker_open = int(server.breaker.state != "closed")
    # Close BEFORE the oracle search: the rebuild compiles its own
    # search program, which must not register as a steady-state serve
    # recompile on the (then-uninstalled) compile watch.
    server.close(drain=True)
    rebuild = segidx.rebuild_retriever()
    rvals, rids = rebuild.search(pinned, args.k)
    parity_ok = int(
        np.array_equal(svals, rvals)
        and [[names[i] if i >= 0 else None for i in row]
             for row in sids]
        == [[rebuild.names[i] if i >= 0 else None for i in row]
            for row in rids])

    pauses = [c["pause_s"] * 1e3
              for c in segidx.compactions[pauses_before:]]
    snap = server.metrics_snapshot()
    lat = snap["latency_s"]
    n_muts = sum(mut_counts[k] for k in ("add", "update", "delete"))
    artifact = {
        "metric": "serve_bench",
        "mode": "mutate",
        "backend": jax.default_backend(),
        "docs": segidx.num_docs,
        "k": args.k,
        "requests": args.requests,
        "concurrency": args.concurrency,
        "max_batch": args.max_batch,
        "wall_s": round(wall, 4),
        "throughput_qps": round(snap["queries"] / wall, 2),
        "throughput_rps": round(snap["requests"] / wall, 2),
        "latency_ms": {p: round(lat[p] * 1e3, 3)
                       for p in ("p50", "p95", "p99", "mean", "max")
                       if p in lat},
        "cache": snap["cache"],
        "shed": snap["shed"],
        "index_s": round(index_s, 3),
        "recompiles_after_warmup": recompiles,
        "mutate": {
            "rate": args.mutate,
            "ops": n_muts,
            "counts": dict(mut_counts),
            "mutation_qps": round(n_muts / wall, 2) if wall else 0.0,
            "visibility_lag_ms": _percentiles(lags_ms),
            "compaction": {
                "count": len(pauses),
                "pause_ms": _percentiles(pauses),
                "compactor_restarts": compactor.restarts,
                "compactor_dead": int(compactor.dead),
            },
            "delta_docs": args.delta_docs,
            "compact_at": args.compact_at,
            "xla_recompiles_after_warm": recompiles,
            "parity_ok": parity_ok,
            "final_health": final_health,
            "breaker_open_at_exit": breaker_open,
        },
    }
    if args.chaos:
        artifact["mutate"]["chaos_plan"] = args.chaos
    with open(args.out, "w") as f:
        json.dump(artifact, f, indent=2, sort_keys=True)
        f.write("\n")
    print(json.dumps(artifact, sort_keys=True))
    if recompiles:
        log.warning("serve_bench_recompiles",
                    msg=f"warning: {recompiles} recompiles after "
                        f"warmup (expected 0)", recompiles=recompiles)
        return 1
    if not parity_ok:
        log.error("serve_bench_chaos_parity",
                  msg="mutate parity FAILED: served responses diverge "
                      "from the from-scratch rebuild oracle")
        return 1
    return 0


def run_replicas(args, input_dir) -> int:
    """Replicated-tier scaling bench -> REPLICA_r0x.json.

    Sweeps the tier at 1/2/.../N replicas (same corpus, same Zipf
    load, fresh tier per point — qps, p50/p99, per-replica routed
    share), pins bit-parity of front-served responses against a
    direct single-process search, audits the recompile receipts per
    replica, and rehearses the chaos story: a replica SIGKILLed
    between prepare-ack and commit must abort the swap with every
    replica still on the OLD epoch (zero mixed-epoch responses),
    restart under the budget, and the retried swap must commit
    tier-wide. perf_ledger files the artifact as kind=replica_serve.
    """
    import jax

    import bench as benchmod

    from tfidf_tpu import obs
    from tfidf_tpu.config import PipelineConfig, ServeConfig, VocabMode
    from tfidf_tpu.models import TfidfRetriever
    from tfidf_tpu.serve import ReplicatedFront, SwapAborted

    log = obs.get_log()
    cfg = PipelineConfig(vocab_mode=VocabMode.HASHED,
                         vocab_size=benchmod.VOCAB,
                         max_doc_len=args.doc_len)
    rng = np.random.default_rng(args.seed)
    draw = make_queries(rng, args.pool, benchmod.N_WORDS, qlen=4)
    sizes = [int(s) for s in args.queries_per_request.split(",")]

    # The parity oracle: one direct single-process index over the
    # same corpus — every front-served response must be bit-identical
    # to it (same scores as float32, same names, same order).
    t0 = time.perf_counter()
    oracle = TfidfRetriever(cfg).index_dir(input_dir, strict=False)
    index_s = time.perf_counter() - t0
    names = oracle.names

    def expect(qs):
        vals, ids = oracle.search(qs, k=args.k)
        return [[[names[int(d)], float(v)]
                 for v, d in zip(vrow, irow) if d >= 0]
                for vrow, irow in zip(vals, ids)]

    # Pre-drawn request list shared by every sweep point: identical
    # work at every replica count, and the routing hash sees the same
    # keyspace — the qps column differences are the tier, not the load.
    reqs = [[draw() for _ in range(sizes[i % len(sizes)])]
            for i in range(args.requests)]
    pinned = [[draw()] for _ in range(16)]

    host_cores = os.cpu_count() or 1
    ns, n = [], 1
    while n < max(args.replicas, 1):
        ns.append(n)
        n *= 2
    ns.append(max(args.replicas, 1))

    snap_root = tempfile.mkdtemp(prefix="replica_bench_")
    sweep = []
    parity_fail = 0
    mixed_epoch = 0
    recompiles_total = 0
    try:
        for n in ns:
            serve_cfg = ServeConfig(
                max_batch=args.max_batch, max_wait_ms=args.max_wait_ms,
                queue_depth=args.queue_depth,
                cache_entries=args.cache_entries,
                default_deadline_ms=args.deadline_ms,
                snapshot_dir=os.path.join(snap_root, f"snap_n{n}"),
                replicas=n, replica_timeout_s=600.0)
            front = ReplicatedFront(input_dir, cfg, serve_cfg,
                                    k=args.k).start()
            epoch0 = front.epoch
            lats = []
            errors = [0]
            lock = threading.Lock()
            counter = [0]

            def worker():
                while True:
                    with lock:
                        if counter[0] >= len(reqs):
                            return
                        i = counter[0]
                        counter[0] += 1
                    t1 = time.perf_counter()
                    resp = front.query(reqs[i], k=args.k)
                    dt = time.perf_counter() - t1
                    with lock:
                        if "error" in resp:
                            errors[0] += 1
                        else:
                            lats.append(dt * 1e3)
                            if resp.get("epoch") != epoch0:
                                nonlocal_mixed[0] += 1

            nonlocal_mixed = [0]

            def drive_once():
                ts = [threading.Thread(target=worker)
                      for _ in range(args.concurrency)]
                t1 = time.perf_counter()
                for th in ts:
                    th.start()
                for th in ts:
                    th.join()
                return time.perf_counter() - t1

            # One discarded warm pass per point: the first closed-loop
            # drive after boot eats scheduler/page-cache noise that
            # shows up as second-long outliers on a 1-core host and
            # poisons the scaling column.
            drive_once()
            with lock:
                counter[0] = 0
                lats.clear()
                errors[0] = 0
            wall = drive_once()
            mixed_epoch += nonlocal_mixed[0]

            # Parity: pinned queries re-served with the cache
            # bypassed, compared to the oracle's direct search.
            for qs in pinned:
                resp = front.query(qs, k=args.k, use_cache=False)
                if "error" in resp:
                    parity_fail += 1
                    continue
                got = [[[nm, float(np.float32(v))] for nm, v in row]
                       for row in resp["results"]]
                want = [[[nm, float(np.float32(v))] for nm, v in row]
                        for row in expect(qs)]
                if got != want:
                    parity_fail += 1

            info = front.replica_info()
            recompiles = sum(v.get("recompiles_after_warm", 0)
                             for v in info.values())
            recompiles_total += recompiles
            desc = front.describe()
            routed_total = sum(r["routed"]
                               for r in desc["replicas"].values()) or 1
            n_queries = sum(len(q) for q in reqs)
            lat = _percentiles(lats)
            point = {
                "n_replicas": n,
                "wall_s": round(wall, 4),
                "qps": round(n_queries / wall, 2),
                "rps": round(len(reqs) / wall, 2),
                "latency_ms": lat,
                "errors": errors[0],
                "occupancy": {
                    r: round(rep["routed"] / routed_total, 4)
                    for r, rep in sorted(desc["replicas"].items())},
                "restarts": sum(r["restarts"]
                                for r in desc["replicas"].values()),
                "recompiles_after_warm": recompiles,
            }
            sweep.append(point)
            log.info("replica_bench",
                     msg=f"n={n}: {point['qps']} qps, p50 "
                         f"{lat['p50']} ms, p99 {lat['p99']} ms, "
                         f"occupancy {point['occupancy']}")
            front.close()

        # Chaos rehearsal at max width: SIGKILL replica 2 between its
        # prepare-ack and the commit — the swap must abort with EVERY
        # replica still on the old epoch and zero responses carrying
        # the aborted epoch; the replica restarts under the budget and
        # the retried swap commits tier-wide.
        n = ns[-1] if ns[-1] >= 2 else 2
        serve_cfg = ServeConfig(
            max_batch=args.max_batch, max_wait_ms=args.max_wait_ms,
            queue_depth=args.queue_depth,
            cache_entries=args.cache_entries,
            snapshot_dir=os.path.join(snap_root, "snap_chaos"),
            replicas=n, replica_timeout_s=600.0,
            faults="replica_prepare:fatal:n=1:match=replica=2 boot=0")
        front = ReplicatedFront(input_dir, cfg, serve_cfg,
                                k=args.k).start()
        chaos_mixed = [0]
        stop = threading.Event()

        def chaos_load():
            i = 0
            while not stop.is_set():
                resp = front.query(reqs[i % len(reqs)], k=args.k)
                # Valid epochs: whatever the tier currently admits —
                # pre-commit that is 0, post-commit 0->1 responses
                # may still drain. A response on an epoch the tier
                # NEVER committed is the mixed-epoch bug.
                if ("error" not in resp
                        and resp.get("epoch", 0) > front.epoch):
                    chaos_mixed[0] += 1
                i += 1

        loaders = [threading.Thread(target=chaos_load, daemon=True)
                   for _ in range(2)]
        for th in loaders:
            th.start()
        try:
            swap_aborted = 0
            try:
                front.swap_index(input_dir)
            except SwapAborted:
                swap_aborted = 1
            epochs_after_abort = sorted(
                r["epoch"]
                for r in front.describe()["replicas"].values())
            # Wait out the supervised restart — the killed replica
            # must come back at a LATER boot generation (live-count
            # alone can read the stale pre-death state), then retry.
            # The retry must commit: the fault rule was n=1 and the
            # restarted replica's boot no longer matches its match=.
            deadline = time.time() + 600
            while time.time() < deadline:
                d = front.describe()["replicas"]
                if all(r["state"] == "live" for r in d.values()) \
                        and any(r["restarts"] for r in d.values()):
                    break
                time.sleep(0.5)
            second_epoch = None
            for _ in range(5):
                try:
                    second_epoch = front.swap_index(input_dir)
                    break
                except SwapAborted:
                    # A straggling death raced this attempt; the
                    # tier is still on the old epoch — wait for the
                    # supervisor and go again, like an operator would.
                    time.sleep(2.0)
            if second_epoch is None:
                raise RuntimeError("chaos rehearsal: retried swap "
                                   "never committed")
        finally:
            stop.set()
            for th in loaders:
                th.join(timeout=60)
        post = front.describe()
        epochs_after_commit = sorted(
            r["epoch"] for r in post["replicas"].values())
        chaos_parity_fail = 0
        for qs in pinned:
            resp = front.query(qs, k=args.k, use_cache=False)
            got = ([[[nm, float(np.float32(v))] for nm, v in row]
                    for row in resp["results"]]
                   if "error" not in resp else None)
            want = [[[nm, float(np.float32(v))] for nm, v in row]
                    for row in expect(qs)]
            if got != want:
                chaos_parity_fail += 1
        chaos = {
            "plan": serve_cfg.faults,
            "swap_aborted": swap_aborted,
            "epochs_after_abort": epochs_after_abort,
            "old_epoch_everywhere_after_abort": int(
                set(epochs_after_abort) == {0}),
            "restarts": sum(r["restarts"]
                            for r in post["replicas"].values()),
            "second_swap_epoch": second_epoch,
            "epochs_after_commit": epochs_after_commit,
            "mixed_epoch_responses": chaos_mixed[0],
            "parity_mismatches": chaos_parity_fail,
        }
        mixed_epoch += chaos_mixed[0]
        parity_fail += chaos_parity_fail
        front.close()

        # Propagation-overhead A/B (round 23): the SAME 2-replica tier
        # served twice — disttrace off, then on — with identical
        # single-query requests and the cache bypassed, so the p50
        # delta is the full price of minting + carrying the trace
        # context across every hop (front mint, JSONL "trace" field,
        # replica RequestContext adoption, response echo). The on-leg
        # then pulls every span ring over the data plane
        # (front.trace_export) and merges it in memory
        # (tools.trace_merge.merge_processes): the artifact records
        # how many spans actually joined, how many process lanes the
        # merge produced, and the worst clock-offset uncertainty the
        # alignment absorbed — and pins parity + zero recompiles WITH
        # tracing on (perf_gate holds all of it).
        from tfidf_tpu.obs import disttrace as dtr
        from tools.trace_merge import merge_processes
        dt_prev_enabled = dtr.enabled()
        dt_prev_tracer = obs.get_tracer()
        ab_reqs = [[draw()] for _ in range(48)]
        dt_p50 = {}
        dt_parity_fail = 0
        dt_recompiles = 0
        dt_spans = 0
        dt_procs = 0
        dt_unc_us = 0.0
        try:
            for mode in ("off", "on"):
                dtr.configure(mode == "on")
                if mode == "on":
                    # The bench process IS the front: arm an in-memory
                    # ring so its route spans join the merged pull.
                    obs.set_tracer(obs.Tracer(), None)
                    obs.set_export_meta(process="front")
                serve_cfg = ServeConfig(
                    max_batch=args.max_batch,
                    max_wait_ms=args.max_wait_ms,
                    queue_depth=args.queue_depth,
                    cache_entries=args.cache_entries,
                    snapshot_dir=os.path.join(snap_root,
                                              f"snap_dt_{mode}"),
                    replicas=2, replica_timeout_s=600.0)
                front = ReplicatedFront(input_dir, cfg, serve_cfg,
                                        k=args.k).start()
                for qs in ab_reqs[:8]:      # warm both replicas
                    front.query(qs, k=args.k, use_cache=False)
                lats = []
                for qs in ab_reqs:
                    t1 = time.perf_counter()
                    resp = front.query(qs, k=args.k, use_cache=False)
                    lats.append((time.perf_counter() - t1) * 1e3)
                    if mode != "on":
                        continue
                    if "error" in resp:
                        dt_parity_fail += 1
                        continue
                    got = [[[nm, float(np.float32(v))]
                            for nm, v in row]
                           for row in resp["results"]]
                    want = [[[nm, float(np.float32(v))]
                             for nm, v in row]
                            for row in expect(qs)]
                    if got != want:
                        dt_parity_fail += 1
                dt_p50[mode] = _percentiles(lats)["p50"]
                if mode == "on":
                    dt_recompiles = sum(
                        v.get("recompiles_after_warm", 0)
                        for v in front.replica_info().values())
                    merged = merge_processes(
                        front.trace_export()["processes"])
                    man = merged["disttrace"]["processes"]
                    dt_procs = len(man)
                    dt_spans = sum(1 for e in merged["traceEvents"]
                                   if e.get("ph") == "X")
                    dt_unc_us = round(
                        max(p["uncertainty_ns"] for p in man) / 1e3,
                        1)
                front.close()
        finally:
            dtr.configure(dt_prev_enabled)
            obs.set_tracer(dt_prev_tracer)
        dt_overhead = (round((dt_p50["on"] - dt_p50["off"])
                             / dt_p50["off"] * 100.0, 2)
                       if dt_p50.get("off") else 0.0)
        disttrace_ab = {
            "replicas": 2,
            "requests": len(ab_reqs),
            "p50_off_ms": dt_p50.get("off", 0.0),
            "p50_on_ms": dt_p50.get("on", 0.0),
            "overhead_pct": dt_overhead,
            "processes_merged": dt_procs,
            "spans_merged": dt_spans,
            "max_clock_uncertainty_us": dt_unc_us,
            "parity_mismatches": dt_parity_fail,
            "parity_ok": int(dt_parity_fail == 0),
            "recompiles_after_warmup": dt_recompiles,
        }
        parity_fail += dt_parity_fail
    finally:
        shutil.rmtree(snap_root, ignore_errors=True)

    base = sweep[0]
    top = sweep[-1]
    scaling = (round(top["qps"] / (base["qps"] * top["n_replicas"]), 4)
               if base["qps"] else 0.0)
    cpu_bound = host_cores < top["n_replicas"] + 1
    artifact = {
        "metric": "replica_bench",
        "backend": jax.default_backend(),
        "docs": oracle._num_docs,
        "k": args.k,
        "requests": args.requests,
        "concurrency": args.concurrency,
        "index_s": round(index_s, 3),
        # The honesty context the qps columns MUST be read against:
        # each replica is a full process; with fewer host cores than
        # processes the sweep is CPU-bound and near-linear scaling is
        # not physically available — the artifact says so instead of
        # hiding it (docs/SERVING.md "Replicated tier").
        "host_cores": host_cores,
        "cpu_bound": int(cpu_bound),
        "n_replicas": top["n_replicas"],
        "replica": {"sweep": sweep},
        "throughput_qps": top["qps"],
        "qps_1": base["qps"],
        "qps_scaling_x": (round(top["qps"] / base["qps"], 3)
                          if base["qps"] else 0.0),
        "scaling_efficiency": scaling,
        "latency_ms": top["latency_ms"],
        "parity_checked": len(pinned) * (len(ns) + 1),
        "parity_mismatches": parity_fail,
        "parity_ok": int(parity_fail == 0),
        "mixed_epoch_responses": mixed_epoch,
        "recompiles_after_warmup": recompiles_total,
        "chaos": chaos,
        "disttrace": disttrace_ab,
    }
    with open(args.out, "w") as f:
        json.dump(artifact, f, indent=2, sort_keys=True)
        f.write("\n")
    print(json.dumps(artifact, sort_keys=True))
    ok = True
    if parity_fail:
        log.error("replica_bench_parity",
                  msg=f"parity FAILED: {parity_fail} front-served "
                      f"responses diverged from direct search")
        ok = False
    if mixed_epoch:
        log.error("replica_bench_mixed_epoch",
                  msg=f"{mixed_epoch} responses carried an "
                      f"uncommitted epoch — the two-phase gate leaked")
        ok = False
    if recompiles_total:
        log.warning("serve_bench_recompiles",
                    msg=f"warning: {recompiles_total} replica "
                        f"recompiles after warmup (expected 0)",
                    recompiles=recompiles_total)
        ok = False
    if not chaos["swap_aborted"] or not chaos[
            "old_epoch_everywhere_after_abort"]:
        log.error("replica_bench_chaos",
                  msg="chaos rehearsal FAILED: kill-mid-swap did not "
                      "leave the tier on the old epoch everywhere")
        ok = False
    if dt_recompiles:
        log.error("replica_bench_disttrace",
                  msg=f"{dt_recompiles} recompiles after warmup WITH "
                      f"disttrace on — carrying the trace context "
                      f"must not mint new programs")
        ok = False
    return 0 if ok else 1


def main() -> int:
    ap = argparse.ArgumentParser(
        description=__doc__.split("\n")[0],
        epilog="artifact keys: throughput_rps/qps, latency_ms "
               "(p50/p95/p99/mean), batch.mean_occupancy, "
               "cache.hit_rate, shed.rate, recompiles_after_warmup")
    ap.add_argument("--requests", type=int, default=256)
    ap.add_argument("--concurrency", type=int, default=8,
                    help="closed-loop worker threads")
    ap.add_argument("--rate", type=float, default=0.0,
                    help="open-loop arrival rate in requests/sec "
                         "(0 = closed loop)")
    ap.add_argument("--queries-per-request", default="1,2,4",
                    help="request sizes cycled through the load")
    ap.add_argument("--k", type=int, default=10)
    ap.add_argument("--pool", type=int, default=64,
                    help="distinct-query pool size (Zipf-weighted)")
    ap.add_argument("--docs", type=int, default=2048,
                    help="synthetic corpus size (ignored with --input)")
    ap.add_argument("--doc-len", type=int, default=64)
    ap.add_argument("--input", default=None,
                    help="serve an existing corpus dir instead")
    ap.add_argument("--max-batch", type=int, default=64)
    ap.add_argument("--max-wait-ms", type=float, default=2.0)
    ap.add_argument("--queue-depth", type=int, default=512)
    ap.add_argument("--cache-entries", type=int, default=4096)
    ap.add_argument("--deadline-ms", type=float, default=None)
    ap.add_argument("--seed", type=int, default=7)
    ap.add_argument("--slo-ms", type=float, default=250.0,
                    help="latency objective for the SLO burn tracker: "
                         "the artifact embeds the slo object "
                         "(compliance, fast/slow burn) and "
                         "tools/perf_gate.py gates compliance "
                         "directionally — a PR that quietly blows "
                         "the objective fails CI (0 disables)")
    ap.add_argument("--slo-target", type=float, default=0.99,
                    help="fraction of requests that must meet "
                         "--slo-ms")
    ap.add_argument("--slow-ms", type=float, default=250.0,
                    help="slow-query threshold: requests over it emit "
                         "slow_query flight events; the artifact "
                         "embeds slow_queries (0 disables)")
    ap.add_argument("--ab-reqtrace", action="store_true",
                    help="measure the request-identity overhead: run "
                         "the same load once with TFIDF_TPU_REQTRACE "
                         "off before the main (stamped) run and embed "
                         "a reqtrace object {p50_ms_on, p50_ms_off, "
                         "p50_regression} in the artifact — the "
                         "<2%% steady-state p50 bound receipt")
    ap.add_argument("--ab-slab", action="store_true",
                    help="measure the zero-allocation query slab: run "
                         "the same load twice through throwaway "
                         "cache-off servers (slab off, then on) and "
                         "embed a 'slab' artifact object — steady-"
                         "state allocs/batch (must be 0) and H2D "
                         "copies/batch (must be 1) from the slab's "
                         "own counters, p50 on/off delta, and a "
                         "parity verdict (slab-on served rows "
                         "bit-identical to slab-off direct search). "
                         "perf_gate zero-tolerates the parity and "
                         "the structural invariants")
    ap.add_argument("--ab-tiled", action="store_true",
                    help="measure the round-21 tiled scorer: drive "
                         "wide single-request batches (64/128/256 "
                         "queries, each atomic -> one coalesced "
                         "device batch) through throwaway cache-off "
                         "servers with TFIDF_TPU_SCORE_TILING off "
                         "(the legacy serial 64-query block split) "
                         "then on, and embed a 'tiling' artifact "
                         "object — per-width latency both ways, the "
                         "widest-width speedup, and a parity verdict "
                         "(tiled served rows bit-identical to the "
                         "block-split pass at EVERY width). perf_gate "
                         "zero-tolerates the parity; exit 1 on any "
                         "divergence")
    ap.add_argument("--ab-pipeline", action="store_true",
                    help="measure the round-22 pipelined execution: "
                         "drive the same cache-off load through "
                         "throwaway servers at pipeline depth 1 "
                         "(unpipelined legacy), 2 and 4, and embed a "
                         "'pipeline' artifact object — per-depth "
                         "cache-off qps, p50/p99, pipeline-bubble "
                         "fraction, per-depth recompile receipt, and "
                         "a parity verdict (every depth's served "
                         "rows bit-identical to the depth-1 pass AND "
                         "to direct search). perf_gate zero-tolerates "
                         "the parity/recompiles and gates the "
                         "depth-2-vs-depth-1 qps win directionally; "
                         "exit 1 on any divergence")
    ap.add_argument("--chaos", metavar="PLAN", default=None,
                    help="arm this fault-injection plan for the whole "
                         "load (grammar in tfidf_tpu/faults.py, e.g. "
                         "'device_dispatch:transient:n=4;"
                         "device_dispatch:fatal:match=__poison__'); "
                         "the artifact gains a 'chaos' object with "
                         "retry/restart/quarantine/shed counts and a "
                         "parity_ok verdict (every non-shed "
                         "non-poisoned response re-checked "
                         "bit-identical against direct search). "
                         "match= rules on device_dispatch make the "
                         "bench inject matching poison requests")
    ap.add_argument("--chaos-seed", type=int, default=0,
                    help="fault-plan + jitter seed (replayable chaos)")
    ap.add_argument("--mesh-shards", type=int, default=None,
                    help="serve ONE logical index doc-sharded across "
                         "this many devices (0 = all): the artifact "
                         "gains a 'mesh' object (n_shards, per-shard "
                         "bytes + imbalance, parity verdict vs the "
                         "single-device source, recompile receipt) "
                         "and perf_ledger files it as kind=mesh_serve "
                         "— MESH_SERVE_r0x.json is the committed "
                         "round artifact (default: off)")
    ap.add_argument("--replicas", type=int, default=0, metavar="N",
                    help="replicated-tier scaling sweep: bench the "
                         "front at 1/2/../N replica processes (same "
                         "corpus + Zipf load per point), pin front-vs-"
                         "direct bit parity and the per-replica "
                         "recompile receipts, and rehearse the chaos "
                         "kill-mid-swap story (aborted swap leaves "
                         "every replica on the OLD epoch, restart, "
                         "retried swap commits). REPLICA_r0x.json "
                         "artifact; perf_ledger kind=replica_serve. "
                         "0 = off")
    ap.add_argument("--mutate", type=float, default=0.0, metavar="RATE",
                    help="mixed read/write workload: serve an LSM-"
                         "segmented index and stream add/update/"
                         "delete mutations at RATE ops/sec alongside "
                         "the Zipf query load (MUTATE_r0x.json "
                         "artifact: mutation qps, visibility lag "
                         "p50/p99, compaction pause stats, recompile "
                         "receipt, rebuild-parity verdict). 0 = off")
    ap.add_argument("--mutations", type=int, default=64,
                    help="total mutation ops the --mutate stream "
                         "issues")
    ap.add_argument("--delta-docs", type=int, default=256,
                    help="--mutate: delta-segment capacity")
    ap.add_argument("--compact-at", type=int, default=2,
                    help="--mutate: sealed-segment compaction "
                         "threshold")
    ap.add_argument("--out", default="SERVE_r01.json")
    ap.add_argument("--trace", metavar="OUT.json", default=None,
                    help="record the host span timeline (request "
                         "lifecycle chain + batcher lane) and write "
                         "Chrome trace-event JSON here; also env "
                         "TFIDF_TPU_TRACE. Validate with "
                         "tools/trace_check.py")
    args = ap.parse_args()

    import bench as benchmod
    benchmod.N_DOCS = args.docs
    benchmod.DOC_LEN = args.doc_len

    import jax

    from tfidf_tpu import obs
    from tfidf_tpu.config import PipelineConfig, ServeConfig, VocabMode
    from tfidf_tpu.models import TfidfRetriever
    from tfidf_tpu.models.retrieval import _search_bcoo, _search_tiled
    from tfidf_tpu.ops.sparse import score_tiling
    from tfidf_tpu.serve import (Overloaded, PoisonQuery, ServeError,
                                 TfidfServer)

    # Structured diagnostics: the stderr echo preserves the old print
    # behavior; the events also land in the flight-recorder ring.
    log = obs.get_log()
    log.info("serve_bench", msg=f"backend={jax.default_backend()}")
    obs.configure(args.trace)  # no-op unless --trace/TFIDF_TPU_TRACE
    tmp = None
    if args.input is None:
        tmp = tempfile.mkdtemp(prefix="serve_bench_")
        log.info("serve_bench",
                 msg=f"generating {args.docs}-doc corpus...")
        input_dir = benchmod.make_corpus(tmp)
    else:
        input_dir = args.input
    try:
        if args.replicas > 0:
            return run_replicas(args, input_dir)
        if args.mutate > 0:
            return run_mutate(args, input_dir)
        cfg = PipelineConfig(vocab_mode=VocabMode.HASHED,
                             vocab_size=benchmod.VOCAB,
                             max_doc_len=args.doc_len)
        t0 = time.perf_counter()
        retriever = TfidfRetriever(cfg).index_dir(input_dir, strict=False)
        index_s = time.perf_counter() - t0
        log.info("serve_bench",
                 msg=f"indexed {retriever._num_docs} docs "
                     f"in {index_s:.2f}s")

        serve_cfg = ServeConfig(
            max_batch=args.max_batch, max_wait_ms=args.max_wait_ms,
            queue_depth=args.queue_depth, cache_entries=args.cache_entries,
            default_deadline_ms=args.deadline_ms,
            faults=args.chaos, fault_seed=args.chaos_seed,
            slo_ms=args.slo_ms or None,
            slo_target=args.slo_target,
            slow_ms=args.slow_ms if args.slow_ms > 0 else None,
            mesh_shards=args.mesh_shards)
        server = TfidfServer(retriever, serve_cfg)
        # Mesh mode (round 18): the server sharded the index across
        # the mesh; warm-up and the recompile receipt must watch the
        # SHARDED search programs, and the untouched single-device
        # `retriever` stays alive as the parity oracle.
        _, installed = server.current_index()
        if args.mesh_shards is not None:
            from tfidf_tpu.parallel.serving import mesh_search_cache_size
            compiled_programs = mesh_search_cache_size
        elif score_tiling():
            # Round 21: the tiled scan is the default search program —
            # the recompile receipt must watch ITS cache, not the
            # legacy untiled one's.
            compiled_programs = _search_tiled._cache_size
        else:
            compiled_programs = _search_bcoo._cache_size

        rng = np.random.default_rng(args.seed)
        draw = make_queries(rng, args.pool, benchmod.N_WORDS, qlen=4)
        sizes = [int(s) for s in args.queries_per_request.split(",")]

        # Chaos mode: requests matching a device_dispatch match= rule
        # are the plan's poison — inject a few deliberately so the
        # poison path (bisect -> PoisonQuery -> quarantine) actually
        # runs, and remember which requests to expect 4xx from.
        poison_tokens = []
        if args.chaos:
            from tfidf_tpu import faults as faults_mod
            plan = faults_mod.FaultPlan.parse(args.chaos,
                                              seed=args.chaos_seed)
            poison_tokens = [r.match for r in
                             plan.rules_for("device_dispatch")
                             if r.match is not None]

        # Warmup: touch every power-of-two query bucket this load can
        # produce (plus max_batch itself — full coalesced batches), so
        # steady state re-jits nothing.
        buckets, b = set(), 1
        while b < max(args.max_batch, max(sizes)):
            buckets.add(b)
            b *= 2
        buckets.add(b)
        for nb in sorted(buckets):
            installed.search([draw() for _ in range(nb)], k=args.k)
        compiles_warm = compiled_programs()
        # Round 12: the LIVE recompile signal draws the same warm line
        # — any fingerprinted compile past here is a flight event and
        # a degraded health reason, not just a post-hoc count.
        server.mark_warm()
        # Device-truth receipts for the artifact: peak HBM from the
        # monitor (absent on CPU — memory_stats() is None there) and
        # total XLA compiles from the watch.
        devmon = obs.DeviceMonitor(registry=server.metrics.registry)
        if args.mesh_shards is not None:
            # Publish the shard_bytes_d* gauges and the shard_balance
            # flight event — the doctor's shards section reads the
            # latter out of the flight dump.
            devmon.register_shards(
                lambda: getattr(server.current_index()[1],
                                "shard_stats", lambda: None)())
        devmon.sample()

        def drive(target, n_requests):
            """One full load pass against ``target``; returns (wall_s,
            shed, poisoned, failed, completed) — factored out so the
            --ab-reqtrace pass can re-drive a second server."""
            shed = [0]
            poisoned = [0]
            failed = [0]
            completed = []   # (queries, vals, ids) for the parity pass
            lock = threading.Lock()

            def one_request(i):
                qs = [draw() for _ in range(sizes[i % len(sizes)])]
                if poison_tokens and i % 16 == 3:
                    # Every 16th request carries the plan's poison
                    # token: its batch must bisect, ITS future must
                    # fail typed, and its co-batched neighbors must
                    # still be served.
                    qs = list(qs) + [
                        f"{poison_tokens[i % len(poison_tokens)]}"
                        f" q{i}"]
                try:
                    vals, ids = target.search(qs, k=args.k)
                    if args.chaos:
                        with lock:
                            completed.append((qs, vals, ids))
                except PoisonQuery:
                    with lock:
                        poisoned[0] += 1
                except (Overloaded, ServeError):
                    with lock:
                        shed[0] += 1
                except Exception:  # noqa: BLE001 — e.g. a transient
                    # fault past the retry budget: a real client would
                    # back off and retry; the bench counts it and
                    # keeps loading.
                    with lock:
                        failed[0] += 1

            t0 = time.perf_counter()
            if args.rate > 0:  # open loop: fixed arrivals
                pending = []
                for i in range(n_requests):
                    th = threading.Thread(target=one_request, args=(i,))
                    th.start()
                    pending.append(th)
                    time.sleep(1.0 / args.rate)
                for th in pending:
                    th.join()
            else:  # closed loop: workers run back-to-back requests
                counter = [0]

                def worker():
                    while True:
                        with lock:
                            if counter[0] >= n_requests:
                                return
                            i = counter[0]
                            counter[0] += 1
                        one_request(i)

                workers = [threading.Thread(target=worker)
                           for _ in range(args.concurrency)]
                for th in workers:
                    th.start()
                for th in workers:
                    th.join()
            return (time.perf_counter() - t0, shed[0], poisoned[0],
                    failed[0], completed)

        # Request-identity overhead receipt (--ab-reqtrace): the SAME
        # load driven twice through throwaway servers — once with rid
        # minting/stamping off, once on — BEFORE the main run.
        # p50-vs-p50 is the <2% bound the round-16 acceptance
        # records. Both passes run CACHE-OFF: the steady-state hot
        # path being bounded is the batched device path; a cache hit
        # is a microsecond-scale pure-host shortcut either way, and
        # its p50 would measure the Zipf pool, not the serve path.
        # Skipped under --chaos (poison quarantine would contaminate
        # the passes).
        reqtrace_ab = None
        if args.ab_reqtrace and not args.chaos:
            from tfidf_tpu.obs import reqtrace as reqtrace_mod

            def ab_pass(reqtrace_on):
                reqtrace_mod.configure(reqtrace_on)
                try:
                    ab_server = TfidfServer(retriever, ServeConfig(
                        max_batch=args.max_batch,
                        max_wait_ms=args.max_wait_ms,
                        queue_depth=args.queue_depth,
                        cache_entries=0,
                        default_deadline_ms=args.deadline_ms))
                    ab_server.mark_warm()
                    drive(ab_server, args.requests)
                    p50 = ab_server.metrics_snapshot()[
                        "latency_s"]["p50"]
                    ab_server.close(drain=True)
                finally:
                    reqtrace_mod.configure(None)
                return p50

            off_p50 = ab_pass(False)
            on_p50 = ab_pass(True)
            # The A/B servers uninstalled the process compile watch
            # on close; re-install the main server's.
            from tfidf_tpu.obs import devmon as obs_devmon
            obs_devmon.set_watch(server.compile_watch)
            reqtrace_ab = {
                "p50_ms_off": round(off_p50 * 1e3, 3),
                "p50_ms_on": round(on_p50 * 1e3, 3),
                "p50_regression": (round(on_p50 / off_p50 - 1.0, 4)
                                   if off_p50 else 0.0),
            }

        # Query-slab receipt (--ab-slab): the SAME load driven twice
        # through throwaway cache-off servers — slab OFF then ON —
        # BEFORE the main run. Cache off for the same reason as
        # --ab-reqtrace: the bounded path is the batched device path.
        # The ON pass reads the slab's own counters over a post-warm
        # window (the batcher serializes device dispatch, so the ring
        # holds one buffer per bucket and steady-state allocs must be
        # ZERO with exactly ONE H2D copy per batch), and pins parity:
        # slab-served rows bit-identical to slab-off direct search.
        slab_ab = None
        if args.ab_slab and not args.chaos and args.mesh_shards is None:
            pinned_slab = [draw() for _ in range(8)]

            def slab_pass(slab_on):
                prior = retriever.query_slab
                retriever.query_slab = slab_on
                try:
                    ab_server = TfidfServer(retriever, ServeConfig(
                        max_batch=args.max_batch,
                        max_wait_ms=args.max_wait_ms,
                        queue_depth=args.queue_depth,
                        cache_entries=0,
                        default_deadline_ms=args.deadline_ms,
                        query_slab=slab_on))
                    ab_server.mark_warm()
                    for nb in sorted(buckets):  # ring slots allocate
                        ab_server.submit(
                            [draw() for _ in range(nb)], args.k,
                            use_cache=False).result(timeout=120)
                    stats0 = (retriever._slab.stats() if slab_on
                              else None)
                    drive(ab_server, args.requests)
                    served = ab_server.submit(
                        pinned_slab, args.k,
                        use_cache=False).result(timeout=60)
                    p50 = ab_server.metrics_snapshot()[
                        "latency_s"]["p50"]
                    stats1 = (retriever._slab.stats() if slab_on
                              else None)
                    ab_server.close(drain=True)
                finally:
                    retriever.query_slab = prior
                return p50, served, stats0, stats1

            off_p50, off_rows, _, _ = slab_pass(False)
            on_p50, on_rows, s0, s1 = slab_pass(True)
            batches = s1["packs"] - s0["packs"]
            parity = int(
                np.array_equal(on_rows[0], off_rows[0])
                and np.array_equal(on_rows[1], off_rows[1]))
            slab_ab = {
                "parity_ok": parity,
                "batches": batches,
                "allocs_per_batch": round(
                    (s1["allocs"] - s0["allocs"]) / batches, 4)
                if batches else None,
                "h2d_copies_per_batch": round(
                    (s1["h2d_copies"] - s0["h2d_copies"]) / batches, 4)
                if batches else None,
                "staging_buffers": s1["buffers"],
                "bytes_h2d": s1["bytes_h2d"] - s0["bytes_h2d"],
                "fallbacks": s1["fallbacks"] - s0["fallbacks"],
                "p50_ms_off": round(off_p50 * 1e3, 3),
                "p50_ms_on": round(on_p50 * 1e3, 3),
                "p50_delta": (round(on_p50 / off_p50 - 1.0, 4)
                              if off_p50 else 0.0),
            }
            from tfidf_tpu.obs import devmon as obs_devmon2
            obs_devmon2.set_watch(server.compile_watch)
            log.info("serve_bench",
                     msg=f"slab A/B: allocs/batch "
                         f"{slab_ab['allocs_per_batch']}, h2d/batch "
                         f"{slab_ab['h2d_copies_per_batch']}, p50 "
                         f"{slab_ab['p50_ms_off']:.3f} ms off -> "
                         f"{slab_ab['p50_ms_on']:.3f} ms on "
                         f"({slab_ab['p50_delta']:+.1%})")

        # Tiled-scoring receipt (--ab-tiled): wide SINGLE-request
        # batches (each atomic, so the batcher coalesces exactly that
        # width) through throwaway cache-off servers — tiling OFF
        # (the legacy serial 64-query block split) then ON — BEFORE
        # the main run. Cache off for the same reason as --ab-slab:
        # the column being measured is the batched device path. The
        # SAME pinned queries feed both passes at every width, so the
        # parity verdict is a bit-compare of identical workloads.
        tiled_ab = None
        if args.ab_tiled and not args.chaos and args.mesh_shards is None:
            ab_widths = [w for w in (64, 128, 256)
                         if w <= max(args.max_batch, 256)]
            pinned_tiled = {w: [draw() for _ in range(w)]
                            for w in ab_widths}

            def tiled_pass(tiling_on):
                prior = os.environ.get("TFIDF_TPU_SCORE_TILING")
                os.environ["TFIDF_TPU_SCORE_TILING"] = (
                    "on" if tiling_on else "off")
                try:
                    ab_server = TfidfServer(retriever, ServeConfig(
                        max_batch=max(args.max_batch, max(ab_widths)),
                        max_wait_ms=args.max_wait_ms,
                        queue_depth=max(args.queue_depth,
                                        2 * max(ab_widths)),
                        cache_entries=0,
                        default_deadline_ms=args.deadline_ms))
                    ab_server.mark_warm()
                    lat_ms, rows = {}, {}
                    for w in ab_widths:
                        ab_server.submit(pinned_tiled[w], args.k,
                                         use_cache=False
                                         ).result(timeout=300)  # warm
                        best = float("inf")
                        for _ in range(3):
                            t1 = time.perf_counter()
                            got = ab_server.submit(
                                pinned_tiled[w], args.k,
                                use_cache=False).result(timeout=300)
                            best = min(best,
                                       time.perf_counter() - t1)
                        lat_ms[w] = round(best * 1e3, 3)
                        rows[w] = got
                    ab_server.close(drain=True)
                finally:
                    if prior is None:
                        os.environ.pop("TFIDF_TPU_SCORE_TILING", None)
                    else:
                        os.environ["TFIDF_TPU_SCORE_TILING"] = prior
                return lat_ms, rows

            off_lat, off_rows = tiled_pass(False)
            on_lat, on_rows = tiled_pass(True)
            parity = all(
                np.array_equal(on_rows[w][0], off_rows[w][0])
                and np.array_equal(on_rows[w][1], off_rows[w][1])
                for w in ab_widths)
            widest = ab_widths[-1]
            tiled_ab = {
                "parity_ok": int(parity),
                "widths": ab_widths,
                "lat_ms_off": {str(w): off_lat[w] for w in ab_widths},
                "lat_ms_on": {str(w): on_lat[w] for w in ab_widths},
                "speedup_widest": (round(off_lat[widest]
                                         / on_lat[widest], 3)
                                   if on_lat[widest] else None),
            }
            from tfidf_tpu.obs import devmon as obs_devmon3
            obs_devmon3.set_watch(server.compile_watch)
            log.info("serve_bench",
                     msg=f"tiled A/B: parity "
                         f"{'ok' if parity else 'MISMATCH'}; width "
                         f"{widest}: {off_lat[widest]:.1f} ms block-"
                         f"split -> {on_lat[widest]:.1f} ms tiled "
                         f"({tiled_ab['speedup_widest']}x)")
            # The throwaway passes compiled wide buckets and the
            # off-path's legacy programs AFTER the main warm line —
            # re-draw it so recompiles_after_warmup measures the main
            # load only, as it does without --ab-tiled.
            compiles_warm = compiled_programs()

        # Pipelined-execution receipt (--ab-pipeline): the same
        # cache-off query pool through throwaway servers at depth
        # 1/2/4 — BEFORE the main run. The load is an OPEN-loop burst
        # (a sliding window of outstanding futures, not the closed
        # loop `drive` runs): a closed loop's whole client population
        # rides one batch, so the in-flight window would never hold
        # two batches and every depth would measure the same thing.
        # Sustained backlog is the regime the window exists for —
        # execution overlap between one batch's drain and the next
        # batch's form/pack/dispatch. Depth 1 is the unpipelined
        # legacy path (the baseline the depth-2 qps win is measured
        # against); every depth's pinned rows must be bit-identical
        # to the depth-1 pass AND to direct search, per-depth
        # steady-state recompiles must be zero, and the bubble
        # fraction says how often the device still idled between
        # dispatches (the gap the window exists to close).
        pipeline_ab = None
        if (args.ab_pipeline and not args.chaos
                and args.mesh_shards is None):
            ab_depths = [1, 2, 4]
            pinned_pipe = [draw() for _ in range(8)]
            # Outstanding-future bound: deep enough to keep batches
            # forming behind a full window, comfortably inside the
            # admission bound (single-query requests).
            ab_window = max(8, min(96, args.queue_depth - 8))

            def pipeline_burst(ab_server):
                outstanding = collections.deque()
                t0 = time.perf_counter()
                for _ in range(args.requests):
                    if len(outstanding) >= ab_window:
                        outstanding.popleft().result(timeout=120)
                    outstanding.append(ab_server.submit(
                        [draw()], args.k, use_cache=False))
                while outstanding:
                    outstanding.popleft().result(timeout=120)
                return time.perf_counter() - t0

            def pipeline_pass(depth):
                ab_server = TfidfServer(retriever, ServeConfig(
                    max_batch=args.max_batch,
                    max_wait_ms=args.max_wait_ms,
                    queue_depth=args.queue_depth,
                    cache_entries=0,
                    default_deadline_ms=args.deadline_ms,
                    pipeline_depth=depth))
                ab_server.mark_warm()
                for nb in sorted(buckets):  # warm every bucket
                    ab_server.submit(
                        [draw() for _ in range(nb)], args.k,
                        use_cache=False).result(timeout=120)
                reg0 = ab_server.metrics.registry.snapshot()
                snap0 = ab_server.metrics_snapshot()
                pre_compiles = compiled_programs()
                wall = pipeline_burst(ab_server)
                served = ab_server.submit(
                    pinned_pipe, args.k,
                    use_cache=False).result(timeout=60)
                snap1 = ab_server.metrics_snapshot()
                reg1 = ab_server.metrics.registry.snapshot()
                ab_server.close(drain=True)
                queries = snap1["queries"] - snap0["queries"]
                batches = (snap1["batch"]["count"]
                           - snap0["batch"]["count"])
                bubbles = (
                    reg1.get("serve_pipeline_bubbles_total", 0)
                    - reg0.get("serve_pipeline_bubbles_total", 0))
                lat_ab = snap1["latency_s"]
                return {
                    "wall_s": round(wall, 4),
                    "qps": round(queries / wall, 2) if wall else 0.0,
                    "p50_ms": round(lat_ab["p50"] * 1e3, 3),
                    "p99_ms": round(lat_ab["p99"] * 1e3, 3),
                    "batches": batches,
                    "bubble_fraction": round(bubbles / batches, 4)
                    if batches else None,
                    "recompiles": compiled_programs() - pre_compiles,
                }, served

            # Best-of-5, trials INTERLEAVED across depths: closed-loop
            # qps at this scale is box-noise-bound, and interleaving
            # spreads warm-state drift evenly instead of crediting it
            # to whichever depth ran last. Rows from every trial feed
            # the parity check; the qps column keeps each depth's best.
            stats_by_depth, rows_by_depth = {}, {}
            parity = True
            for _trial in range(5):
                for d in ab_depths:
                    stats, served = pipeline_pass(d)
                    if (d not in stats_by_depth
                            or stats["qps"]
                            > stats_by_depth[d]["qps"]):
                        stats_by_depth[d] = stats
                    if d in rows_by_depth:
                        parity = parity and (
                            np.array_equal(served[0],
                                           rows_by_depth[d][0])
                            and np.array_equal(served[1],
                                               rows_by_depth[d][1]))
                    else:
                        rows_by_depth[d] = served
            base_rows = rows_by_depth[ab_depths[0]]
            dvals_p, dids_p = retriever.search(pinned_pipe, k=args.k)
            parity = parity and all(
                np.array_equal(rows_by_depth[d][0], base_rows[0])
                and np.array_equal(rows_by_depth[d][1], base_rows[1])
                for d in ab_depths) and (
                np.array_equal(base_rows[0], dvals_p)
                and np.array_equal(base_rows[1], dids_p))
            q1 = stats_by_depth[1]["qps"]
            q2 = stats_by_depth[2]["qps"]
            pipeline_ab = {
                "parity_ok": int(parity),
                "depths": ab_depths,
                "qps": {str(d): stats_by_depth[d]["qps"]
                        for d in ab_depths},
                "p50_ms": {str(d): stats_by_depth[d]["p50_ms"]
                           for d in ab_depths},
                "p99_ms": {str(d): stats_by_depth[d]["p99_ms"]
                           for d in ab_depths},
                "bubble_fraction": {
                    str(d): stats_by_depth[d]["bubble_fraction"]
                    for d in ab_depths},
                "recompiles": {
                    str(d): stats_by_depth[d]["recompiles"]
                    for d in ab_depths},
                "qps_gain_depth2": (round(q2 / q1 - 1.0, 4)
                                    if q1 else None),
            }
            from tfidf_tpu.obs import devmon as obs_devmon4
            obs_devmon4.set_watch(server.compile_watch)
            log.info("serve_bench",
                     msg=f"pipeline A/B: parity "
                         f"{'ok' if parity else 'MISMATCH'}; qps "
                         f"{q1} @depth1 -> {q2} @depth2 "
                         f"({pipeline_ab['qps_gain_depth2']:+.1%}), "
                         f"{stats_by_depth[4]['qps']} @depth4; "
                         f"bubbles "
                         f"{pipeline_ab['bubble_fraction']}")
            # Throwaway passes ran after the main warm line — re-draw
            # so recompiles_after_warmup measures the main load only.
            compiles_warm = compiled_programs()

        wall, n_shed, n_poisoned, n_failed, completed = drive(
            server, args.requests)
        shed = [n_shed]
        poisoned = [n_poisoned]
        failed = [n_failed]
        devmon.sample()
        watch = server.compile_watch
        # Bench honesty (round 20): the closed-loop latency above is
        # mostly CACHE-HIT latency — the Zipf pool re-draws its hot
        # head and the result cache absorbs those requests at
        # microsecond scale (the artifact's cache.hit_rate says how
        # many). Freeze the main-load snapshot FIRST, then sample the
        # same pool with the cache bypassed: the explicit cache-off
        # column is the device-path latency a cold query actually
        # pays. Skipped under --chaos (quarantine would contaminate
        # the sample).
        snap = server.metrics_snapshot()
        cache_off = None
        if not args.chaos:
            lat_off = []
            for i in range(min(args.requests, 64)):
                qs = [draw() for _ in range(sizes[i % len(sizes)])]
                t1 = time.perf_counter()
                try:
                    server.submit(qs, args.k,
                                  use_cache=False).result(timeout=120)
                except (Overloaded, ServeError):
                    continue
                lat_off.append(time.perf_counter() - t1)
            if lat_off:
                p_off = _percentiles([x * 1e3 for x in lat_off])
                cache_off = {
                    "requests": len(lat_off),
                    "p50_ms": p_off["p50"],
                    "p99_ms": p_off["p99"],
                }
                log.info("serve_bench",
                         msg=f"cache-off: p50 {p_off['p50']:.3f} ms, "
                             f"p99 {p_off['p99']:.3f} ms over "
                             f"{len(lat_off)} requests (closed-loop "
                             f"hit rate "
                             f"{snap['cache'].get('hit_rate', 0)})")
        chaos = None
        if args.chaos:
            # Final health: two evaluations so the shed-rate window
            # the chaos itself provoked has decayed (the health tests
            # pin that recovery shape); the breaker must have closed.
            server.health.evaluate()
            final = server.health.evaluate()
            reg = server.metrics.registry.snapshot()
            # Parity: every non-shed non-poisoned response must be
            # bit-identical to a direct (unfaulted, unbatched) search
            # — retries, bisection and restarts may cost time, never
            # bytes.
            mismatches = 0
            for qs, vals, ids in completed:
                dvals, dids = retriever.search(qs, k=args.k)
                if not (np.array_equal(vals, dvals)
                        and np.array_equal(ids, dids)):
                    mismatches += 1
            chaos = {
                "plan": args.chaos,
                "seed": args.chaos_seed,
                "retries": reg.get("serve_dispatch_retries_total", 0),
                "worker_restarts": reg.get(
                    "serve_worker_restarts_total", 0),
                "breaker_trips": reg.get("serve_breaker_trips_total",
                                         0),
                "breaker_open_at_exit": int(
                    server.breaker.state != "closed"),
                "quarantined": reg.get("serve_quarantined_total", 0),
                "poisoned_requests": poisoned[0],
                "shed_requests": shed[0],
                "failed_requests": failed[0],
                "final_health": final.state,
                "parity_checked": len(completed),
                "parity_mismatches": mismatches,
                "parity_ok": int(mismatches == 0 and len(completed) > 0),
            }
        # Mesh receipts: pinned queries replayed through the full
        # sharded serve path (cache bypassed, before close) must be
        # bit-identical to the single-device source's direct search —
        # the sharded-vs-single parity verdict perf_gate
        # zero-tolerates — plus the per-shard HBM balance. The oracle
        # search runs AFTER close (mutate-bench discipline): it
        # compiles its own single-device program, which must not
        # register as a steady-state serve recompile on the
        # then-uninstalled compile watch.
        mesh = None
        mesh_served = None
        if args.mesh_shards is not None:
            pinned = [draw() for _ in range(16)]
            mesh_served = server.submit(
                pinned, args.k, use_cache=False).result(timeout=60)
            stats = installed.shard_stats()
        server.close(drain=True)
        recompiles = compiled_programs() - compiles_warm
        if mesh_served is not None:
            mvals, mids = mesh_served
            dvals, dids = retriever.search(pinned, k=args.k)
            mesh_mismatch = int(not (np.array_equal(mvals, dvals)
                                     and np.array_equal(mids, dids)))
            mesh = {
                "n_shards": stats["n_shards"],
                "shard_bytes": stats["shard_bytes"],
                "shard_imbalance": stats["imbalance"],
                "parity_checked": len(pinned),
                "parity_ok": int(mesh_mismatch == 0),
            }

        lat = snap["latency_s"]
        artifact = {
            "metric": "serve_bench",
            "mode": "open" if args.rate > 0 else "closed",
            "backend": jax.default_backend(),
            "docs": retriever._num_docs,
            "k": args.k,
            "requests": args.requests,
            "queries": snap["queries"],
            "concurrency": args.concurrency,
            "rate_rps": args.rate,
            "max_batch": args.max_batch,
            "max_wait_ms": args.max_wait_ms,
            # Comparability context (round 22): runs at different
            # pipeline depths are different experiments — the ledger
            # matches baselines on this key.
            "pipeline_depth": serve_cfg.pipeline_depth,
            "wall_s": round(wall, 4),
            "throughput_rps": round(snap["requests"] / wall, 2),
            "throughput_qps": round(snap["queries"] / wall, 2),
            "latency_ms": {p: round(lat[p] * 1e3, 3)
                           for p in ("p50", "p95", "p99", "mean", "max")
                           if p in lat},
            "batch": snap["batch"],
            "cache": snap["cache"],
            "shed": snap["shed"],
            "queue_peak": snap["queue"]["peak"],
            "index_s": round(index_s, 3),
            "recompiles_after_warmup": recompiles,
            "xla_compiles": watch.compiles,
            # Round 16 forensics receipts: the SLO snapshot (windowed
            # objective compliance + burn rates — perf_gate gates
            # compliance directionally) and the slow-query count.
            "slo": snap["slo"],
            "slow_queries": snap.get("slow_queries", 0),
        }
        if cache_off is not None:
            artifact["cache_off"] = cache_off
        if reqtrace_ab is not None:
            artifact["reqtrace"] = reqtrace_ab
            log.info("serve_bench",
                     msg=f"reqtrace overhead: p50 "
                         f"{reqtrace_ab['p50_ms_off']:.3f} ms off -> "
                         f"{reqtrace_ab['p50_ms_on']:.3f} ms on "
                         f"({reqtrace_ab['p50_regression']:+.1%})")
        if slab_ab is not None:
            artifact["slab"] = slab_ab
        if tiled_ab is not None:
            artifact["tiling"] = tiled_ab
        if pipeline_ab is not None:
            artifact["pipeline"] = pipeline_ab
        if chaos is not None:
            artifact["chaos"] = chaos
        if mesh is not None:
            artifact["mesh"] = mesh
        if devmon.peak_bytes:   # backends without memory stats omit
            artifact["peak_hbm_bytes"] = devmon.peak_bytes
            artifact["memory_pressure"] = devmon.memory_pressure
        trace_path = obs.export()
        if trace_path:
            artifact["trace_path"] = trace_path
            log.info("serve_bench",
                     msg=f"trace written to {trace_path}")
        with open(args.out, "w") as f:
            json.dump(artifact, f, indent=2, sort_keys=True)
            f.write("\n")
        print(json.dumps(artifact, sort_keys=True))
        if recompiles:
            log.warning("serve_bench_recompiles",
                        msg=f"warning: {recompiles} recompiles after "
                            f"warmup (expected 0)",
                        recompiles=recompiles)
            return 1
        if slab_ab is not None and not slab_ab["parity_ok"]:
            log.error("serve_bench_slab_parity",
                      msg="slab parity FAILED: slab-on served rows "
                          "diverge from slab-off direct search")
            return 1
        if tiled_ab is not None and not tiled_ab["parity_ok"]:
            log.error("serve_bench_tiled_parity",
                      msg="tiled parity FAILED: tiled served rows "
                          "diverge from the block-split pass")
            return 1
        if pipeline_ab is not None:
            if not pipeline_ab["parity_ok"]:
                log.error("serve_bench_pipeline_parity",
                          msg="pipeline parity FAILED: some depth's "
                              "served rows diverge from the depth-1 "
                              "pass or direct search")
                return 1
            bad_rc = {d: n for d, n in
                      pipeline_ab["recompiles"].items() if n}
            if bad_rc:
                log.error("serve_bench_pipeline_recompiles",
                          msg=f"pipeline A/B recompiled in steady "
                              f"state: {bad_rc} (expected 0 at every "
                              f"depth)")
                return 1
        if chaos is not None and not chaos["parity_ok"]:
            log.error("serve_bench_chaos_parity",
                      msg=f"chaos parity FAILED: "
                          f"{chaos['parity_mismatches']}/"
                          f"{chaos['parity_checked']} responses "
                          f"diverged from direct search",
                      mismatches=chaos["parity_mismatches"])
            return 1
        if mesh is not None and not mesh["parity_ok"]:
            log.error("serve_bench_mesh_parity",
                      msg="mesh parity FAILED: sharded serve responses "
                          "diverge from the single-device source")
            return 1
        return 0
    finally:
        if tmp is not None:
            shutil.rmtree(tmp, ignore_errors=True)


if __name__ == "__main__":
    sys.exit(main())
