"""Serving-layer load generator + SERVE_r0x.json artifact.

Boots a TfidfServer in-process over a synthetic Zipf corpus (or
--input) and drives it with either a CLOSED loop (N worker threads,
back-to-back requests — peak-throughput shape) or an OPEN loop
(Poisson-ish fixed arrival rate via --rate — latency-under-load
shape, where queueing and shedding actually show). Queries draw from a
Zipf-weighted pool so the result cache sees a realistic hot tail.

Emits one JSON artifact with the SLO receipts: throughput (rps/qps),
latency p50/p99, mean batch occupancy, cache hit rate, shed rate —
plus a recompile receipt: after warmup (one search per power-of-two
query bucket), steady-state serving must trigger ZERO fresh XLA
compiles (`models.retrieval._search_bcoo` cache size is checked before
and after the run). The slow-marked smoke in tests/test_serve.py runs
this at --requests 64 and asserts the artifact schema.

Usage: python tools/serve_bench.py --requests 256 --out SERVE_r01.json
"""

from __future__ import annotations

import argparse
import json
import shutil
import sys
import tempfile
import threading
import time

import _common  # noqa: E402,F401  repo-root sys.path bootstrap

import numpy as np  # noqa: E402


def make_queries(rng, pool_size, n_words, qlen):
    """Zipf-weighted query pool: a few hot queries, a long cold tail."""
    pool = [" ".join(f"w{rng.integers(0, n_words)}" for _ in range(qlen))
            for _ in range(pool_size)]

    def draw():
        idx = min(int(rng.zipf(1.3)) - 1, pool_size - 1)
        return pool[idx]
    return draw


def main() -> int:
    ap = argparse.ArgumentParser(
        description=__doc__.split("\n")[0],
        epilog="artifact keys: throughput_rps/qps, latency_ms "
               "(p50/p95/p99/mean), batch.mean_occupancy, "
               "cache.hit_rate, shed.rate, recompiles_after_warmup")
    ap.add_argument("--requests", type=int, default=256)
    ap.add_argument("--concurrency", type=int, default=8,
                    help="closed-loop worker threads")
    ap.add_argument("--rate", type=float, default=0.0,
                    help="open-loop arrival rate in requests/sec "
                         "(0 = closed loop)")
    ap.add_argument("--queries-per-request", default="1,2,4",
                    help="request sizes cycled through the load")
    ap.add_argument("--k", type=int, default=10)
    ap.add_argument("--pool", type=int, default=64,
                    help="distinct-query pool size (Zipf-weighted)")
    ap.add_argument("--docs", type=int, default=2048,
                    help="synthetic corpus size (ignored with --input)")
    ap.add_argument("--doc-len", type=int, default=64)
    ap.add_argument("--input", default=None,
                    help="serve an existing corpus dir instead")
    ap.add_argument("--max-batch", type=int, default=64)
    ap.add_argument("--max-wait-ms", type=float, default=2.0)
    ap.add_argument("--queue-depth", type=int, default=512)
    ap.add_argument("--cache-entries", type=int, default=4096)
    ap.add_argument("--deadline-ms", type=float, default=None)
    ap.add_argument("--seed", type=int, default=7)
    ap.add_argument("--slo-ms", type=float, default=250.0,
                    help="latency objective for the SLO burn tracker: "
                         "the artifact embeds the slo object "
                         "(compliance, fast/slow burn) and "
                         "tools/perf_gate.py gates compliance "
                         "directionally — a PR that quietly blows "
                         "the objective fails CI (0 disables)")
    ap.add_argument("--slo-target", type=float, default=0.99,
                    help="fraction of requests that must meet "
                         "--slo-ms")
    ap.add_argument("--slow-ms", type=float, default=250.0,
                    help="slow-query threshold: requests over it emit "
                         "slow_query flight events; the artifact "
                         "embeds slow_queries (0 disables)")
    ap.add_argument("--ab-reqtrace", action="store_true",
                    help="measure the request-identity overhead: run "
                         "the same load once with TFIDF_TPU_REQTRACE "
                         "off before the main (stamped) run and embed "
                         "a reqtrace object {p50_ms_on, p50_ms_off, "
                         "p50_regression} in the artifact — the "
                         "<2%% steady-state p50 bound receipt")
    ap.add_argument("--chaos", metavar="PLAN", default=None,
                    help="arm this fault-injection plan for the whole "
                         "load (grammar in tfidf_tpu/faults.py, e.g. "
                         "'device_dispatch:transient:n=4;"
                         "device_dispatch:fatal:match=__poison__'); "
                         "the artifact gains a 'chaos' object with "
                         "retry/restart/quarantine/shed counts and a "
                         "parity_ok verdict (every non-shed "
                         "non-poisoned response re-checked "
                         "bit-identical against direct search). "
                         "match= rules on device_dispatch make the "
                         "bench inject matching poison requests")
    ap.add_argument("--chaos-seed", type=int, default=0,
                    help="fault-plan + jitter seed (replayable chaos)")
    ap.add_argument("--out", default="SERVE_r01.json")
    ap.add_argument("--trace", metavar="OUT.json", default=None,
                    help="record the host span timeline (request "
                         "lifecycle chain + batcher lane) and write "
                         "Chrome trace-event JSON here; also env "
                         "TFIDF_TPU_TRACE. Validate with "
                         "tools/trace_check.py")
    args = ap.parse_args()

    import bench as benchmod
    benchmod.N_DOCS = args.docs
    benchmod.DOC_LEN = args.doc_len

    import jax

    from tfidf_tpu import obs
    from tfidf_tpu.config import PipelineConfig, ServeConfig, VocabMode
    from tfidf_tpu.models import TfidfRetriever
    from tfidf_tpu.models.retrieval import _search_bcoo
    from tfidf_tpu.serve import (Overloaded, PoisonQuery, ServeError,
                                 TfidfServer)

    # Structured diagnostics: the stderr echo preserves the old print
    # behavior; the events also land in the flight-recorder ring.
    log = obs.get_log()
    log.info("serve_bench", msg=f"backend={jax.default_backend()}")
    obs.configure(args.trace)  # no-op unless --trace/TFIDF_TPU_TRACE
    tmp = None
    if args.input is None:
        tmp = tempfile.mkdtemp(prefix="serve_bench_")
        log.info("serve_bench",
                 msg=f"generating {args.docs}-doc corpus...")
        input_dir = benchmod.make_corpus(tmp)
    else:
        input_dir = args.input
    try:
        cfg = PipelineConfig(vocab_mode=VocabMode.HASHED,
                             vocab_size=benchmod.VOCAB,
                             max_doc_len=args.doc_len)
        t0 = time.perf_counter()
        retriever = TfidfRetriever(cfg).index_dir(input_dir, strict=False)
        index_s = time.perf_counter() - t0
        log.info("serve_bench",
                 msg=f"indexed {retriever._num_docs} docs "
                     f"in {index_s:.2f}s")

        serve_cfg = ServeConfig(
            max_batch=args.max_batch, max_wait_ms=args.max_wait_ms,
            queue_depth=args.queue_depth, cache_entries=args.cache_entries,
            default_deadline_ms=args.deadline_ms,
            faults=args.chaos, fault_seed=args.chaos_seed,
            slo_ms=args.slo_ms or None,
            slo_target=args.slo_target,
            slow_ms=args.slow_ms if args.slow_ms > 0 else None)
        server = TfidfServer(retriever, serve_cfg)

        rng = np.random.default_rng(args.seed)
        draw = make_queries(rng, args.pool, benchmod.N_WORDS, qlen=4)
        sizes = [int(s) for s in args.queries_per_request.split(",")]

        # Chaos mode: requests matching a device_dispatch match= rule
        # are the plan's poison — inject a few deliberately so the
        # poison path (bisect -> PoisonQuery -> quarantine) actually
        # runs, and remember which requests to expect 4xx from.
        poison_tokens = []
        if args.chaos:
            from tfidf_tpu import faults as faults_mod
            plan = faults_mod.FaultPlan.parse(args.chaos,
                                              seed=args.chaos_seed)
            poison_tokens = [r.match for r in
                             plan.rules_for("device_dispatch")
                             if r.match is not None]

        # Warmup: touch every power-of-two query bucket this load can
        # produce (plus max_batch itself — full coalesced batches), so
        # steady state re-jits nothing.
        buckets, b = set(), 1
        while b < max(args.max_batch, max(sizes)):
            buckets.add(b)
            b *= 2
        buckets.add(b)
        for nb in sorted(buckets):
            retriever.search([draw() for _ in range(nb)], k=args.k)
        compiles_warm = _search_bcoo._cache_size()
        # Round 12: the LIVE recompile signal draws the same warm line
        # — any fingerprinted compile past here is a flight event and
        # a degraded health reason, not just a post-hoc count.
        server.mark_warm()
        # Device-truth receipts for the artifact: peak HBM from the
        # monitor (absent on CPU — memory_stats() is None there) and
        # total XLA compiles from the watch.
        devmon = obs.DeviceMonitor(registry=server.metrics.registry)
        devmon.sample()

        def drive(target, n_requests):
            """One full load pass against ``target``; returns (wall_s,
            shed, poisoned, failed, completed) — factored out so the
            --ab-reqtrace pass can re-drive a second server."""
            shed = [0]
            poisoned = [0]
            failed = [0]
            completed = []   # (queries, vals, ids) for the parity pass
            lock = threading.Lock()

            def one_request(i):
                qs = [draw() for _ in range(sizes[i % len(sizes)])]
                if poison_tokens and i % 16 == 3:
                    # Every 16th request carries the plan's poison
                    # token: its batch must bisect, ITS future must
                    # fail typed, and its co-batched neighbors must
                    # still be served.
                    qs = list(qs) + [
                        f"{poison_tokens[i % len(poison_tokens)]}"
                        f" q{i}"]
                try:
                    vals, ids = target.search(qs, k=args.k)
                    if args.chaos:
                        with lock:
                            completed.append((qs, vals, ids))
                except PoisonQuery:
                    with lock:
                        poisoned[0] += 1
                except (Overloaded, ServeError):
                    with lock:
                        shed[0] += 1
                except Exception:  # noqa: BLE001 — e.g. a transient
                    # fault past the retry budget: a real client would
                    # back off and retry; the bench counts it and
                    # keeps loading.
                    with lock:
                        failed[0] += 1

            t0 = time.perf_counter()
            if args.rate > 0:  # open loop: fixed arrivals
                pending = []
                for i in range(n_requests):
                    th = threading.Thread(target=one_request, args=(i,))
                    th.start()
                    pending.append(th)
                    time.sleep(1.0 / args.rate)
                for th in pending:
                    th.join()
            else:  # closed loop: workers run back-to-back requests
                counter = [0]

                def worker():
                    while True:
                        with lock:
                            if counter[0] >= n_requests:
                                return
                            i = counter[0]
                            counter[0] += 1
                        one_request(i)

                workers = [threading.Thread(target=worker)
                           for _ in range(args.concurrency)]
                for th in workers:
                    th.start()
                for th in workers:
                    th.join()
            return (time.perf_counter() - t0, shed[0], poisoned[0],
                    failed[0], completed)

        # Request-identity overhead receipt (--ab-reqtrace): the SAME
        # load driven twice through throwaway servers — once with rid
        # minting/stamping off, once on — BEFORE the main run.
        # p50-vs-p50 is the <2% bound the round-16 acceptance
        # records. Both passes run CACHE-OFF: the steady-state hot
        # path being bounded is the batched device path; a cache hit
        # is a microsecond-scale pure-host shortcut either way, and
        # its p50 would measure the Zipf pool, not the serve path.
        # Skipped under --chaos (poison quarantine would contaminate
        # the passes).
        reqtrace_ab = None
        if args.ab_reqtrace and not args.chaos:
            from tfidf_tpu.obs import reqtrace as reqtrace_mod

            def ab_pass(reqtrace_on):
                reqtrace_mod.configure(reqtrace_on)
                try:
                    ab_server = TfidfServer(retriever, ServeConfig(
                        max_batch=args.max_batch,
                        max_wait_ms=args.max_wait_ms,
                        queue_depth=args.queue_depth,
                        cache_entries=0,
                        default_deadline_ms=args.deadline_ms))
                    ab_server.mark_warm()
                    drive(ab_server, args.requests)
                    p50 = ab_server.metrics_snapshot()[
                        "latency_s"]["p50"]
                    ab_server.close(drain=True)
                finally:
                    reqtrace_mod.configure(None)
                return p50

            off_p50 = ab_pass(False)
            on_p50 = ab_pass(True)
            # The A/B servers uninstalled the process compile watch
            # on close; re-install the main server's.
            from tfidf_tpu.obs import devmon as obs_devmon
            obs_devmon.set_watch(server.compile_watch)
            reqtrace_ab = {
                "p50_ms_off": round(off_p50 * 1e3, 3),
                "p50_ms_on": round(on_p50 * 1e3, 3),
                "p50_regression": (round(on_p50 / off_p50 - 1.0, 4)
                                   if off_p50 else 0.0),
            }

        wall, n_shed, n_poisoned, n_failed, completed = drive(
            server, args.requests)
        shed = [n_shed]
        poisoned = [n_poisoned]
        failed = [n_failed]
        devmon.sample()
        watch = server.compile_watch
        chaos = None
        if args.chaos:
            # Final health: two evaluations so the shed-rate window
            # the chaos itself provoked has decayed (the health tests
            # pin that recovery shape); the breaker must have closed.
            server.health.evaluate()
            final = server.health.evaluate()
            reg = server.metrics.registry.snapshot()
            # Parity: every non-shed non-poisoned response must be
            # bit-identical to a direct (unfaulted, unbatched) search
            # — retries, bisection and restarts may cost time, never
            # bytes.
            mismatches = 0
            for qs, vals, ids in completed:
                dvals, dids = retriever.search(qs, k=args.k)
                if not (np.array_equal(vals, dvals)
                        and np.array_equal(ids, dids)):
                    mismatches += 1
            chaos = {
                "plan": args.chaos,
                "seed": args.chaos_seed,
                "retries": reg.get("serve_dispatch_retries_total", 0),
                "worker_restarts": reg.get(
                    "serve_worker_restarts_total", 0),
                "breaker_trips": reg.get("serve_breaker_trips_total",
                                         0),
                "breaker_open_at_exit": int(
                    server.breaker.state != "closed"),
                "quarantined": reg.get("serve_quarantined_total", 0),
                "poisoned_requests": poisoned[0],
                "shed_requests": shed[0],
                "failed_requests": failed[0],
                "final_health": final.state,
                "parity_checked": len(completed),
                "parity_mismatches": mismatches,
                "parity_ok": int(mismatches == 0 and len(completed) > 0),
            }
        server.close(drain=True)
        recompiles = _search_bcoo._cache_size() - compiles_warm

        snap = server.metrics_snapshot()
        lat = snap["latency_s"]
        artifact = {
            "metric": "serve_bench",
            "mode": "open" if args.rate > 0 else "closed",
            "backend": jax.default_backend(),
            "docs": retriever._num_docs,
            "k": args.k,
            "requests": args.requests,
            "queries": snap["queries"],
            "concurrency": args.concurrency,
            "rate_rps": args.rate,
            "max_batch": args.max_batch,
            "max_wait_ms": args.max_wait_ms,
            "wall_s": round(wall, 4),
            "throughput_rps": round(snap["requests"] / wall, 2),
            "throughput_qps": round(snap["queries"] / wall, 2),
            "latency_ms": {p: round(lat[p] * 1e3, 3)
                           for p in ("p50", "p95", "p99", "mean", "max")
                           if p in lat},
            "batch": snap["batch"],
            "cache": snap["cache"],
            "shed": snap["shed"],
            "queue_peak": snap["queue"]["peak"],
            "index_s": round(index_s, 3),
            "recompiles_after_warmup": recompiles,
            "xla_compiles": watch.compiles,
            # Round 16 forensics receipts: the SLO snapshot (windowed
            # objective compliance + burn rates — perf_gate gates
            # compliance directionally) and the slow-query count.
            "slo": snap["slo"],
            "slow_queries": snap.get("slow_queries", 0),
        }
        if reqtrace_ab is not None:
            artifact["reqtrace"] = reqtrace_ab
            log.info("serve_bench",
                     msg=f"reqtrace overhead: p50 "
                         f"{reqtrace_ab['p50_ms_off']:.3f} ms off -> "
                         f"{reqtrace_ab['p50_ms_on']:.3f} ms on "
                         f"({reqtrace_ab['p50_regression']:+.1%})")
        if chaos is not None:
            artifact["chaos"] = chaos
        if devmon.peak_bytes:   # backends without memory stats omit
            artifact["peak_hbm_bytes"] = devmon.peak_bytes
            artifact["memory_pressure"] = devmon.memory_pressure
        trace_path = obs.export()
        if trace_path:
            artifact["trace_path"] = trace_path
            log.info("serve_bench",
                     msg=f"trace written to {trace_path}")
        with open(args.out, "w") as f:
            json.dump(artifact, f, indent=2, sort_keys=True)
            f.write("\n")
        print(json.dumps(artifact, sort_keys=True))
        if recompiles:
            log.warning("serve_bench_recompiles",
                        msg=f"warning: {recompiles} recompiles after "
                            f"warmup (expected 0)",
                        recompiles=recompiles)
            return 1
        if chaos is not None and not chaos["parity_ok"]:
            log.error("serve_bench_chaos_parity",
                      msg=f"chaos parity FAILED: "
                          f"{chaos['parity_mismatches']}/"
                          f"{chaos['parity_checked']} responses "
                          f"diverged from direct search",
                      mismatches=chaos["parity_mismatches"])
            return 1
        return 0
    finally:
        if tmp is not None:
            shutil.rmtree(tmp, ignore_errors=True)


if __name__ == "__main__":
    sys.exit(main())
