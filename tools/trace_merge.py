#!/usr/bin/env python3
"""Merge N per-process Chrome traces into ONE clock-aligned timeline.

The fleet's span evidence is born scattered: the front exports its own
ring, every replica's ring is pulled over the data plane
(``{"op": "trace_export"}`` → a ``tfidf-trace/1`` bundle), and a
multihost ingest run leaves one exported trace per rank. Each file's
timestamps are microseconds relative to THAT process's
``perf_counter_ns`` epoch — loading two of them side by side in
Perfetto shows two unrelated clocks, and "did the front's route span
actually contain the replica's request?" is unanswerable.

This tool answers it. Each process's export carries a ``disttrace``
metadata block (:meth:`tfidf_tpu.obs.tracer.Tracer.export_meta`):
identity (``process``, ``os_pid``), the tracer epoch ``t0_ns``, and a
``clock`` offset estimate measured against the fleet reference over
the live transport (the front's ctrl plane, or mpi_lite tag -106 —
RTT-midpoint, min-RTT filtered; tfidf_tpu/obs/disttrace.py). Capture
never rewrites timestamps; the merge is where the offsets are applied:

    aligned_ts_us = ts + (t0_ns - offset_ns - t0_ref_ns) / 1000

``offset_ns`` is the process's clock MINUS the reference's at the same
instant, so subtracting it folds every lane onto the reference
timeline. The output is one Perfetto-loadable doc
(schema ``tfidf-trace-merged/1``): one Chrome ``pid`` lane group per
process (front first), each process's offset/uncertainty recorded in
the top-level ``disttrace`` key — ``tools/trace_check.py`` validates
the merged form, ``tools/doctor.py --request <trace-id>`` renders the
cross-process causal timeline from it.

Usage::

    python -m tools.trace_merge bundle.json [more.json ...] \
        -o merged.json [--reference front]

Inputs may be ``tfidf-trace/1`` bundles (the trace_export pull — many
processes per file) or single-process exported traces (``--trace`` /
``TFIDF_TPU_TRACE`` files, whose ``disttrace`` key identifies them).
Exit 0 on success, 2 on unusable input. Stdlib-only, importable with
no jax at all (the doctor/trace_check discipline).
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Any, Dict, List, Optional, Tuple

_BUNDLE_SCHEMA = "tfidf-trace/1"
MERGED_SCHEMA = "tfidf-trace-merged/1"

__all__ = ["MERGED_SCHEMA", "load_processes", "merge_processes", "main"]


def _norm_clock(raw: Any) -> Dict[str, int]:
    """A process entry's clock estimate, zero-filled: the reference
    process exports zeros (it IS the timeline) and a missing block
    aligns as offset 0 — the merge still loads, trace_check's merged
    mode is what flags a non-front lane with no measured offset."""
    out = {"offset_ns": 0, "uncertainty_ns": 0, "rtt_ns": 0,
           "samples": 0}
    if isinstance(raw, dict):
        for k in out:
            v = raw.get(k)
            if isinstance(v, (int, float)):
                out[k] = int(v)
    return out


def _entry(process: Any, os_pid: Any, t0_ns: Any, clock: Any,
           events: Any, src: str) -> Dict[str, Any]:
    if not isinstance(events, list):
        raise ValueError(f"{src}: traceEvents is not a list")
    if not isinstance(t0_ns, int):
        raise ValueError(f"{src}: missing tracer epoch t0_ns — "
                         f"re-export with a disttrace-aware build")
    return {"process": str(process or "host"),
            "os_pid": int(os_pid or 0), "t0_ns": t0_ns,
            "clock": _norm_clock(clock), "traceEvents": events}


def load_processes(path: str) -> List[Dict[str, Any]]:
    """Normalize one input file into process entries. Accepts the
    ``tfidf-trace/1`` bundle (N processes) or a single exported Chrome
    trace whose ``disttrace`` key carries the identity."""
    with open(path) as f:
        doc = json.load(f)
    if not isinstance(doc, dict):
        raise ValueError(f"{path}: not a JSON object (bare event "
                         f"arrays carry no disttrace identity)")
    if doc.get("schema") == _BUNDLE_SCHEMA:
        procs = doc.get("processes")
        if not isinstance(procs, list) or not procs:
            raise ValueError(f"{path}: bundle has no processes")
        return [_entry(p.get("process"), p.get("os_pid"),
                       p.get("t0_ns"), p.get("clock"),
                       p.get("traceEvents"), f"{path}[{i}]")
                for i, p in enumerate(procs)]
    meta = doc.get("disttrace")
    if not isinstance(meta, dict):
        raise ValueError(f"{path}: no disttrace metadata — exported "
                         f"by a pre-fleet-tracing build?")
    return [_entry(meta.get("process"), meta.get("os_pid"),
                   meta.get("t0_ns"), meta.get("clock"),
                   doc.get("traceEvents"), path)]


def _pick_reference(entries: List[Dict[str, Any]],
                    name: Optional[str]) -> int:
    if name is not None:
        for i, e in enumerate(entries):
            if e["process"] == name:
                return i
        raise ValueError(f"reference process {name!r} not in inputs "
                         f"({[e['process'] for e in entries]})")
    for i, e in enumerate(entries):
        if e["process"] == "front":
            return i
    return 0


def merge_processes(entries: List[Dict[str, Any]],
                    reference: Optional[str] = None) -> Dict[str, Any]:
    """The pure merge: align every entry onto the reference process's
    timeline and emit one Chrome doc with per-process ``pid`` lane
    groups. Library form — serve_bench and the tests call this on
    in-memory ``trace_export`` pulls without touching disk."""
    if not entries:
        raise ValueError("no process entries to merge")
    ref = _pick_reference(entries, reference)
    t0_ref = entries[ref]["t0_ns"]
    # Reference first, then input order — the Perfetto top lane is the
    # front (or rank 0), where every fleet trace starts.
    order = [ref] + [i for i in range(len(entries)) if i != ref]
    seen: Dict[str, int] = {}
    events: List[dict] = []
    manifest: List[dict] = []
    for lane, i in enumerate(order, start=1):
        e = entries[i]
        label = e["process"]
        n = seen.get(label, 0)
        seen[label] = n + 1
        if n:  # two pulls of the same process: keep both, uniquely
            label = f"{label}#{n + 1}"
        clock = e["clock"]
        shift_us = (e["t0_ns"] - clock["offset_ns"] - t0_ref) / 1e3
        events.append({"ph": "M", "pid": lane, "tid": 0,
                       "name": "process_name",
                       "args": {"name": label}})
        events.append({"ph": "M", "pid": lane, "tid": 0,
                       "name": "process_sort_index",
                       "args": {"sort_index": lane}})
        n_ev = 0
        for ev in e["traceEvents"]:
            if not isinstance(ev, dict):
                continue
            if ev.get("ph") == "M" and ev.get("name") in (
                    "process_name", "process_sort_index"):
                continue  # replaced by the lane-group identity above
            ev = dict(ev)
            ev["pid"] = lane
            ts = ev.get("ts")
            if isinstance(ts, (int, float)):
                ev["ts"] = ts + shift_us
            events.append(ev)
            n_ev += 1
        manifest.append({"process": label, "pid": lane,
                         "os_pid": e["os_pid"], "t0_ns": e["t0_ns"],
                         "reference": i == ref,
                         "shift_us": round(shift_us, 3),
                         "events": n_ev, **clock})
    return {"traceEvents": events, "displayTimeUnit": "ms",
            "schema": MERGED_SCHEMA,
            "disttrace": {"schema": MERGED_SCHEMA,
                          "reference": manifest[0]["process"],
                          "processes": manifest}}


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        description="merge per-process Chrome traces into one "
                    "clock-aligned fleet timeline")
    ap.add_argument("inputs", nargs="+",
                    help="tfidf-trace/1 bundles (the trace_export "
                         "pull) and/or single-process exported traces")
    ap.add_argument("-o", "--out", required=True,
                    help="merged Perfetto-loadable JSON to write")
    ap.add_argument("--reference", default=None, metavar="NAME",
                    help="process whose clock is the merged timeline "
                         "(default: 'front' if present, else the "
                         "first process)")
    args = ap.parse_args(argv)
    entries: List[Dict[str, Any]] = []
    try:
        for path in args.inputs:
            entries.extend(load_processes(path))
        merged = merge_processes(entries, reference=args.reference)
    except (OSError, ValueError, json.JSONDecodeError) as e:
        sys.stderr.write(f"trace_merge: {e}\n")
        return 2
    with open(args.out, "w") as f:
        json.dump(merged, f)
    m = merged["disttrace"]["processes"]
    worst = max((p["uncertainty_ns"] for p in m), default=0)
    print(f"merged {len(m)} process(es), "
          f"{sum(p['events'] for p in m)} events onto "
          f"{m[0]['process']}'s clock "
          f"(max offset uncertainty {worst / 1e3:.1f} us) "
          f"-> {args.out}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
