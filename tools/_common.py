"""Shared bootstrap for the ``tools/`` scripts.

Every tool used to carry its own copy-pasted ``sys.path.insert`` so
``import tfidf_tpu`` works when run as ``python tools/<name>.py`` from
anywhere; this module is the single copy. Importing it is enough —
the script's own directory (``tools/``) is already on ``sys.path``
when Python runs the file, so ``import _common`` resolves, and the
import side effect puts the repo root ahead of it::

    import _common  # noqa: F401  repo-root sys.path bootstrap
    from _common import REPO

``REPO`` is the absolute repo root for tools that build paths off it.
"""

from __future__ import annotations

import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)


def repo_root() -> str:
    return REPO
