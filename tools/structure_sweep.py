"""A/B sweep of resident-ingest device structures on the real chip.

The round-3 ingest design (per-chunk programs, ~4 chunks, ragged flat
uint16 wire, single packed fetch) came out of this sweep; keep it
runnable so future link/backend changes can be re-decided from
measurements instead of lore. Variants, all computing the identical
(df, scores, topk) result on the same synthetic batch:

  fused-1xfer       one upload, one fused program    (round-2 design)
  fused-Nxfer       chunked uploads, one fused program
  chunked-N         per-chunk sort+fold programs + final score_pack,
                    padded [chunk, L] uploads
  chunked-N-ragged  same programs on the ragged flat uint16 wire —
                    the round-3 PRODUCTION structure, via the SAME
                    ingest call sites production uses

Interleave repeats across variants: the tunnel jitters +-20-40%, so
sequential per-variant timing confounds drift with structure.

    python tools/structure_sweep.py
"""

import functools
import time

import numpy as np

import jax
import jax.numpy as jnp

import _common  # noqa: E402,F401  repo-root sys.path bootstrap

from tfidf_tpu.config import PipelineConfig, VocabMode
from tfidf_tpu.ingest import _FLAT_BUCKET, _chunk_step, _finish_wire
from tfidf_tpu.ops.sparse import sparse_forward

D, L, V, K = 32768, 256, 1 << 16, 16
REPEATS = 3


@functools.partial(jax.jit, static_argnames=("vocab_size", "topk"))
def _fused(token_ids, lengths, num_docs, *, vocab_size, topk):
    df, vals, ids = sparse_forward(token_ids, lengths, num_docs,
                                   vocab_size=vocab_size,
                                   score_dtype=jnp.float32, topk=topk)
    b = lambda a: jax.lax.bitcast_convert_type(a, jnp.uint8).reshape(-1)
    return jnp.concatenate([b(df), b(vals), b(ids)])


def run_fused(toks, lens, n_xfers):
    chunk = D // n_xfers
    parts = [jax.device_put(toks[s:s + chunk]) for s in range(0, D, chunk)]
    lparts = [jax.device_put(lens[s:s + chunk]) for s in range(0, D, chunk)]
    a = parts[0] if len(parts) == 1 else jnp.concatenate(parts, axis=0)
    b = lparts[0] if len(lparts) == 1 else jnp.concatenate(lparts, axis=0)
    return np.asarray(jax.device_get(
        _fused(a, b, jnp.int32(D), vocab_size=V, topk=K)))


def run_chunked(toks, lens, n_chunks, cfg, ragged=False):
    chunk = D // n_chunks
    df = jnp.zeros((V,), jnp.int32)
    ti, tc, th, tl = [], [], [], []
    bucket = _FLAT_BUCKET  # the production pad granularity, not a copy
    for s in range(0, D, chunk):
        ctoks, clens = toks[s:s + chunk], lens[s:s + chunk]
        if ragged:
            # The production wire: flat stream, no padding bytes
            # (ingest.make_flat_packer's python fallback, inlined).
            mask = np.arange(L)[None, :] < clens[:, None]
            flat = np.ascontiguousarray(ctoks[mask], dtype=np.uint16)
            pad = max(flat.size + (-flat.size % bucket), bucket) - flat.size
            wire_arr = np.pad(flat, (0, pad))
        else:
            wire_arr = ctoks
        a = jax.device_put(wire_arr)
        b = jax.device_put(clens)
        i_, c_, h_, df = _chunk_step(a, b, df, cfg, L, ragged=ragged)
        ti.append(i_)
        tc.append(c_)
        th.append(h_)
        tl.append(b)
    _, wire = _finish_wire((ti, tc, th), tl, df, D, K, jnp.float32, cfg,
                           wire_vals=True)
    return np.asarray(jax.device_get(wire))


def main():
    rng = np.random.default_rng(0)
    toks = rng.integers(0, V, (D, L)).astype(np.uint16)
    lens = rng.integers(L // 2, L + 1, D).astype(np.int32)
    cfg = PipelineConfig(vocab_mode=VocabMode.HASHED, vocab_size=V,
                         max_doc_len=L, doc_chunk=L, topk=K,
                         engine="sparse")
    variants = [("fused-1xfer", lambda: run_fused(toks, lens, 1)),
                ("fused-16xfer", lambda: run_fused(toks, lens, 16)),
                ("chunked-4", lambda: run_chunked(toks, lens, 4, cfg)),
                ("chunked-16", lambda: run_chunked(toks, lens, 16, cfg)),
                ("chunked-4-ragged",  # the production wire
                 lambda: run_chunked(toks, lens, 4, cfg, ragged=True))]
    best = {name: float("inf") for name, _ in variants}
    for name, fn in variants:
        fn()  # compile
    for _ in range(REPEATS):  # interleaved: drift hits all variants
        for name, fn in variants:
            t0 = time.perf_counter()
            fn()
            best[name] = min(best[name], time.perf_counter() - t0)
    for name, _ in variants:
        print(f"{name:>14}: {best[name]:.3f}s")


if __name__ == "__main__":
    main()
