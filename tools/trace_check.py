"""CI-friendly validator of an emitted host trace.

The bench artifacts CLAIM overlap (``overlap.pack_hidden_frac``,
``downlink.fetch_hidden_frac``); a trace lets a human SEE it — and
this tool lets CI assert it. It loads a Chrome trace-event file
written by ``--trace`` / ``TFIDF_TPU_TRACE`` (``tfidf_tpu.obs``) and
checks the structural invariants the pipeline is built around:

schema (always):
  * every complete event has a name, numeric ``ts`` and ``dur >= 0``;
  * every lane used by a span carries ``thread_name`` metadata;
  * at least ``--min-threads`` distinct lanes recorded spans (the
    overlap machinery IS threads — a single-lane trace means the
    instrumentation or the workers are broken);
  * cost-annotated spans (round 12) are sane: a ``bytes`` stamp is a
    non-negative number and the exported ``gb_s`` is finite.

ingest traces (auto-detected by ``pack`` spans):
  * pack spans live on a non-main lane, dispatch/phase_b on main;
  * with ``pack_ahead`` on (``TFIDF_TPU_PACK_AHEAD`` >= 2, the
    default) and >= 2 chunks: some packer-lane ``pack`` span overlaps
    a main-lane ``dispatch``/``phase_b`` span in wall time — the
    double-buffered upload actually double-buffered;
  * with ``fetch_ahead`` on (``TFIDF_TPU_FETCH_AHEAD`` >= 1, the
    default) and >= 2 drain + >= 2 ``phase_b`` spans (the chunked
    finish): some drainer-lane ``drain`` span overlaps a later
    chunk's ``phase_b`` — the async drain actually hid behind
    scoring. (The scanned finish emits ONE drain; the check is then
    vacuous and says so.)
  * every ``dispatch`` / ``fetch`` / ``drain`` span carries its
    ``bytes`` stamp — the cost attribution tools/doctor.py reads;
  * bytes-wire runs (round 14): ``slab`` spans (host slab assembly)
    and ``device_tokenize`` spans (on-device tokenize+hash dispatch)
    carry byte stamps too, and slab assembly rides the packer lane —
    so the "host pack became a copy that overlaps dispatch" claim is
    checkable the same way the id-wire pack overlap is.

serve traces (auto-detected by ``request`` spans):
  * every ``request`` span carries an ``outcome`` in the known set —
    the span-chain parity the serving layer promises (each submitted
    request appears exactly once as drained / cache_hit / shed /
    poisoned / ...; ``poisoned`` is the quarantined-query terminal
    state — a quarantined request must END that way, never hang);
  * every ``queued`` span that reached a batch carries its batch id;
  * recovery spans nest (round 13): every ``dispatch_retry`` span
    lies inside a ``batched`` span on the same lane (retries happen
    INSIDE the batch serving the requests, so the timeline attributes
    the added latency to the right batch).

merged fleet timelines (``tools/trace_merge.py`` output, schema
``tfidf-trace-merged/1``, auto-detected by its ``disttrace`` key):
  * one UNIQUE lane group per process (manifest labels, chrome pids
    and ``process_name`` metadata all consistent);
  * every non-reference process was merged with a MEASURED clock
    offset (``samples > 0`` in the manifest — an unaligned lane is an
    error, not a shrug);
  * post-alignment causality: a front ``route`` span contains the
    owning replica's ``request`` span in wall time, to within the two
    processes' summed offset uncertainty;
  * cross-process join integrity: rids unique fleet-wide, every
    traced replica request joins a front-minted route.

flight recorder (``--flight DUMP.jsonl``, round 11):
  * line 1 is a ``tfidf-flight/1`` schema header whose ``events`` /
    ``digests`` counts match the body exactly (an atomic dump is
    complete or absent — a mismatch means a torn writer);
  * every event line carries ``t``/``level``/``event`` with a known
    level; every digest line carries ``t`` and an ``outcome``.

Pure stdlib — runnable under ``JAX_PLATFORMS=cpu`` (or no jax at
all). Exit 0 = all checks passed/vacuous, 1 = a violated invariant,
2 = unreadable input.

Usage: python tools/trace_check.py TRACE.json [--mode auto|ingest|serve]
                                              [--flight DUMP.jsonl]
"""

from __future__ import annotations

import argparse
import os
import sys
from typing import Dict, List, Tuple

import _common  # noqa: E402,F401  repo-root sys.path bootstrap

# The shared Chrome-trace reader lives in tfidf_tpu/obs/tracer.py, but
# importing it THROUGH the package would pull in jax (the package
# __init__ imports the pipeline). The tracer module itself is stdlib-
# only by design, so load it standalone — this tool stays runnable in
# a bare CI interpreter with no jax at all.
import importlib.util as _ilu  # noqa: E402

_spec = _ilu.spec_from_file_location(
    "_obs_tracer", os.path.join(_common.REPO, "tfidf_tpu", "obs",
                                "tracer.py"))
_tracer = _ilu.module_from_spec(_spec)
_spec.loader.exec_module(_tracer)
load_chrome_trace = _tracer.load_chrome_trace
spans_by_thread = _tracer.spans_by_thread

_OUTCOMES = {"drained", "cache_hit", "shed_overload", "shed_deadline",
             "rejected", "error", "empty", "poisoned"}

_MERGED_SCHEMA = "tfidf-trace-merged/1"


def _load_doc(path: str):
    """The raw exported doc — merged-trace validation needs the
    top-level ``disttrace`` manifest, not just the event list."""
    import gzip
    import json
    opener = (lambda p: gzip.open(p, "rt")) if path.endswith(".gz") \
        else open
    with opener(path) as f:
        return json.load(f)


def _overlaps(a: dict, b: dict) -> bool:
    return (a["ts"] < b["ts"] + b.get("dur", 0.0)
            and b["ts"] < a["ts"] + a.get("dur", 0.0))


def check_trace(path: str, mode: str = "auto",
                min_threads: int = 1) -> Tuple[List[str], List[str]]:
    """Returns ``(errors, notes)`` — empty errors == pass."""
    errors: List[str] = []
    notes: List[str] = []
    doc = _load_doc(path)
    events = doc if isinstance(doc, list) \
        else doc.get("traceEvents", [])
    xs = [e for e in events if e.get("ph") == "X"]
    if not xs:
        return ["trace contains no complete (ph=X) span events"], notes

    # --- schema ---
    for e in xs:
        if not e.get("name"):
            errors.append(f"span without a name: {e!r}")
            break
        if not isinstance(e.get("ts"), (int, float)) \
                or not isinstance(e.get("dur"), (int, float)) \
                or e["dur"] < 0:
            errors.append(f"span with bad ts/dur: {e!r}")
            break
    # Cost-annotated spans (round 12): any span carrying a byte stamp
    # must carry a sane one, and the exported gb_s — computed by the
    # tracer from bytes/dur — must be a finite number (a bare
    # Infinity would not even be JSON; a negative byte count is an
    # instrumentation bug).
    for e in xs:
        a = e.get("args") or {}
        if "bytes" in a and (not isinstance(a["bytes"], (int, float))
                             or a["bytes"] < 0):
            errors.append(f"span with bad bytes stamp: {e!r}")
            break
        if "gb_s" in a and (not isinstance(a["gb_s"], (int, float))
                            or a["gb_s"] != a["gb_s"]
                            or a["gb_s"] < 0):
            errors.append(f"span with non-finite gb_s: {e!r}")
            break
    lanes = spans_by_thread(events)
    named = {(e.get("pid"), e.get("tid"))
             for e in events
             if e.get("ph") == "M" and e.get("name") == "thread_name"}
    for e in xs:
        if (e.get("pid"), e.get("tid")) not in named:
            errors.append(
                f"lane {e.get('pid')}/{e.get('tid')} has spans but no "
                f"thread_name metadata")
            break
    if len(lanes) < min_threads:
        errors.append(f"{len(lanes)} lane(s) recorded spans; expected "
                      f">= {min_threads}")
    notes.append(f"lanes: {sorted(lanes)} "
                 f"({sum(len(v) for v in lanes.values())} spans)")

    by_name: Dict[str, List[dict]] = {}
    for label, evs in lanes.items():
        for e in evs:
            by_name.setdefault(e["name"], []).append(e)

    if mode == "auto":
        if isinstance(doc, dict) and (
                doc.get("schema") == _MERGED_SCHEMA
                or (doc.get("disttrace") or {}).get("schema")
                == _MERGED_SCHEMA):
            mode = "merged"
        else:
            mode = ("serve" if "request" in by_name
                    else "ingest" if "pack" in by_name else "schema")
        notes.append(f"mode: {mode} (auto)")

    if mode == "ingest":
        errors += _check_ingest(lanes, by_name, notes)
    elif mode == "serve":
        errors += _check_serve(by_name, notes)
    elif mode == "merged":
        errors += _check_merged(doc, xs, by_name, notes)
    return errors, notes


def _check_ingest(lanes, by_name, notes) -> List[str]:
    errors: List[str] = []
    packs = [e for e in lanes.get("packer", [])
             if e["name"] == "pack"]
    main_disp = [e for e in lanes.get("main", [])
                 if e["name"] in ("dispatch", "phase_b")]
    drains = [e for e in lanes.get("drainer", [])
              if e["name"] == "drain"]
    phase_b = by_name.get("phase_b", [])
    if by_name.get("pack") and not packs:
        errors.append("pack spans exist but none on a 'packer' lane "
                      "(worker thread not labeled / pack on main?)")
    if not main_disp:
        errors.append("no dispatch/phase_b spans on the 'main' lane")
    # Round 12 cost contract: the wire-moving spans carry their byte
    # stamps (obs/costmodel.py turns them into per-span GB/s at
    # export) — a dispatch/fetch/drain span without one regressed the
    # instrumentation. Round 14 adds the bytes-wire spans: every
    # `slab` (host slab assembly, packer lane) and `device_tokenize`
    # (on-device tokenize+hash dispatch, main lane) span must carry
    # the chunk's byte payload too — the doctor attributes the moved
    # host pack through exactly these stamps.
    for name in ("dispatch", "fetch", "drain", "slab",
                 "device_tokenize"):
        for e in by_name.get(name, []):
            if not isinstance((e.get("args") or {}).get("bytes"),
                              (int, float)):
                errors.append(
                    f"{name} span without a bytes stamp (cost "
                    f"attribution regressed): {e.get('args')!r}")
                break
    if by_name.get("slab"):
        notes.append(f"bytes wire: {len(by_name['slab'])} slab "
                     f"span(s), "
                     f"{len(by_name.get('device_tokenize', []))} "
                     f"device_tokenize span(s), byte stamps present")
        # The bytes wire's slab copy must ride the packer lane — the
        # overlap claim (_PackAhead hides slab assembly behind
        # dispatch) is only meaningful off the main thread.
        slab_main = [e for e in lanes.get("main", [])
                     if e["name"] == "slab"]
        if slab_main and not [e for e in lanes.get("packer", [])
                              if e["name"] == "slab"]:
            errors.append("slab spans exist but none on the 'packer' "
                          "lane (slab assembly on main — _PackAhead "
                          "not engaged?)")

    # Overlap checks arm only when some span carries chunk >= 1: a
    # trace may hold SEVERAL sequential single-chunk runs (bench
    # warmup + timed runs), whose spans can never overlap each other —
    # only a genuinely multi-chunk run makes the claim testable.
    def multi_chunk(evs):
        return any((e.get("args") or {}).get("chunk", 0) >= 1
                   for e in evs)

    pack_ahead = int(os.environ.get("TFIDF_TPU_PACK_AHEAD", "2"))
    if pack_ahead >= 2 and multi_chunk(packs) and main_disp:
        hit = any(_overlaps(p, d) for p in packs for d in main_disp)
        if not hit:
            errors.append(
                "pack_ahead is on but NO packer-lane pack span "
                "overlaps a main-lane dispatch/phase_b span — the "
                "double-buffered upload did not overlap")
        else:
            notes.append("ok: pack spans overlap dispatch/scoring "
                         "(pack_ahead)")
    else:
        notes.append("pack-overlap check vacuous "
                     f"(pack_ahead={pack_ahead}, packs={len(packs)})")

    fetch_ahead = int(os.environ.get("TFIDF_TPU_FETCH_AHEAD", "2"))
    if fetch_ahead >= 1 and multi_chunk(drains) and len(phase_b) >= 2:
        hit = any(_overlaps(d, s) for d in drains for s in phase_b)
        if not hit:
            errors.append(
                "fetch_ahead is on but NO drainer-lane drain span "
                "overlaps a phase_b scoring span — the async drain "
                "did not hide behind compute")
        else:
            notes.append("ok: drain spans overlap phase-B scoring "
                         "(fetch_ahead)")
    else:
        notes.append(
            "drain-overlap check vacuous (scanned finish emits one "
            f"drain; drains={len(drains)}, phase_b={len(phase_b)})")
    return errors


def _contained(inner: dict, outer: dict, slack: float = 1.0) -> bool:
    """inner's [ts, ts+dur] within outer's, to ``slack`` us."""
    return (inner["ts"] >= outer["ts"] - slack
            and inner["ts"] + inner.get("dur", 0.0)
            <= outer["ts"] + outer.get("dur", 0.0) + slack)


def _check_serve(by_name, notes) -> List[str]:
    errors: List[str] = []
    requests = by_name.get("request", [])
    for e in requests:
        outcome = (e.get("args") or {}).get("outcome")
        if outcome not in _OUTCOMES:
            errors.append(f"request span without a known outcome: "
                          f"{e.get('args')!r}")
            break
    from collections import Counter
    outcomes = Counter((e.get("args") or {}).get("outcome")
                       for e in requests)
    notes.append(f"request outcomes: {dict(outcomes)}")
    for e in by_name.get("queued", []):
        args = e.get("args") or {}
        if args.get("outcome") == "batched" and "batch" not in args:
            errors.append("queued span reached a batch without a "
                          "batch id")
            break
    batches = by_name.get("batched", [])
    if batches:
        bids = {(e.get("args") or {}).get("batch") for e in batches}
        notes.append(f"batches: {len(batches)} ({len(bids)} ids)")
    # Round 13 recovery nesting: a dispatch retry happens INSIDE the
    # batch it is retrying — its span must be contained in a batched
    # span on the same lane (same pid/tid), so the timeline charges
    # the backoff to the right batch and never floats free. Pipelined
    # execution (round 22) moves retries to the drain worker: there
    # the container is a ``drain`` span on the retry's lane instead.
    drains = by_name.get("drain", [])
    retries = by_name.get("dispatch_retry", [])
    for r in retries:
        lane = (r.get("pid"), r.get("tid"))
        containers = [b for b in batches + drains
                      if (b.get("pid"), b.get("tid")) == lane]
        if not any(_contained(r, b) for b in containers):
            errors.append(
                f"dispatch_retry span (batch "
                f"{(r.get('args') or {}).get('batch')!r}) not nested "
                f"inside any batched or drain span on its lane")
            break
    if retries:
        notes.append(f"dispatch retries: {len(retries)} "
                     f"(all nested in batches or drains)")
    # Round 22 pipeline shape: every drain span resolves a batch some
    # batched span dispatched (same id — the window is FIFO over real
    # batches, not phantoms), and the resolution it times lies inside
    # the batched span's interval (dispatch-to-resolve is one
    # overlapped lifetime, so a drain that ends after its batched
    # span closed would be a torn pipeline).
    if drains:
        bid_spans = {}
        for b in batches:
            bid = (b.get("args") or {}).get("batch")
            if bid is not None:
                bid_spans.setdefault(bid, []).append(b)
        for d in drains:
            bid = (d.get("args") or {}).get("batch")
            if bid is None:
                errors.append("drain span without a batch id")
                break
            owners = bid_spans.get(bid)
            if not owners:
                errors.append(f"drain span resolves batch {bid!r} "
                              f"but no batched span dispatched it")
                break
            if not any(_contained(d, b, slack=5e3) for b in owners):
                errors.append(
                    f"drain span for batch {bid!r} not contained in "
                    f"its batched span — resolution outlived the "
                    f"dispatch-to-deliver lifetime")
                break
        else:
            notes.append(f"pipeline drains: {len(drains)} "
                         f"(each inside its batched span)")
    # Round 16 request identity: when the trace carries rids, every
    # request span's rid is unique (a reused id would alias two
    # requests' forensics), and every rid-stamped queued span names a
    # rid some request span owns — the span chain joins on one key.
    req_rids = [(e.get("args") or {}).get("rid") for e in requests]
    stamped = [r for r in req_rids if r]
    if stamped:
        if len(set(stamped)) != len(stamped):
            dupes = sorted({r for r in stamped
                            if stamped.count(r) > 1})
            errors.append(f"duplicate request ids in trace: {dupes} "
                          f"— rids must be unique per request")
        rid_set = set(stamped)
        for e in by_name.get("queued", []):
            qrid = (e.get("args") or {}).get("rid")
            if qrid is not None and qrid not in rid_set:
                errors.append(
                    f"queued span carries rid {qrid!r} but no "
                    f"request span owns it (orphaned stamp)")
                break
        notes.append(f"request ids: {len(stamped)}/{len(requests)} "
                     f"stamped, unique")
    return errors


def _check_merged(doc, xs, by_name, notes) -> List[str]:
    """Merged fleet timeline (``tools/trace_merge.py`` output, schema
    ``tfidf-trace-merged/1``): one unique lane group per process,
    measured clock metadata on every non-reference process, and the
    CAUSAL invariant the alignment exists to make checkable — after
    the offsets are applied, a front ``route`` span contains the
    owning replica's ``request`` span in wall time (slack: the two
    processes' summed offset uncertainty plus scheduling grace).
    Cross-process joins (rid / trace id) must be sound: rids unique
    fleet-wide, every traced replica request joined to a front
    route."""
    errors: List[str] = []
    meta = (doc.get("disttrace") or {}) if isinstance(doc, dict) else {}
    procs = meta.get("processes")
    if not isinstance(procs, list) or not procs:
        return ["merged trace carries no disttrace process manifest"]

    # -- unique process lanes --
    labels = [p.get("process") for p in procs]
    pids = [p.get("pid") for p in procs]
    if len(set(labels)) != len(labels):
        errors.append(f"duplicate process labels in manifest: "
                      f"{sorted(labels)}")
    if len(set(pids)) != len(pids):
        errors.append(f"duplicate chrome pids in manifest: {pids}")
    name_meta = {}
    for e in (doc.get("traceEvents") or []):
        if e.get("ph") == "M" and e.get("name") == "process_name":
            name_meta[e.get("pid")] = \
                (e.get("args") or {}).get("name", "")
    for p in procs:
        if name_meta.get(p.get("pid")) != p.get("process"):
            errors.append(
                f"process {p.get('process')!r} (pid {p.get('pid')}) "
                f"has no matching process_name lane metadata")
            break
    stray = {e.get("pid") for e in xs} - set(pids)
    if stray:
        errors.append(f"spans on pids outside the manifest: "
                      f"{sorted(stray)}")
    notes.append(f"processes: {labels} "
                 f"(reference {meta.get('reference')!r})")

    # -- measured clock metadata on every non-reference process --
    for p in procs:
        if p.get("reference"):
            continue
        if not p.get("samples"):
            errors.append(
                f"process {p.get('process')!r} merged with NO "
                f"measured clock offset (samples=0) — its lane is "
                f"aligned on faith")
    unc_us = {p.get("pid"): (p.get("uncertainty_ns") or 0) / 1e3
              for p in procs}

    # -- post-alignment containment: route contains its request --
    routes = [e for e in by_name.get("route", [])
              if (e.get("args") or {}).get("trace")]
    req_by_rid = {}
    req_by_trace = {}
    for e in by_name.get("request", []):
        a = e.get("args") or {}
        if a.get("rid"):
            req_by_rid[a["rid"]] = e
        if a.get("trace"):
            req_by_trace[a["trace"]] = e
    checked = 0
    for r in routes:
        a = r.get("args") or {}
        req = req_by_rid.get(a.get("rid")) \
            or req_by_trace.get(a.get("trace"))
        if req is None:
            continue  # error-outcome route, or the ring dropped it
        slack = unc_us.get(r.get("pid"), 0.0) \
            + unc_us.get(req.get("pid"), 0.0) + 250.0
        if not _contained(req, r, slack=slack):
            errors.append(
                f"route span (trace {a.get('trace')!r}, rid "
                f"{a.get('rid')!r}) does NOT contain its replica's "
                f"request span after clock alignment "
                f"(route [{r['ts']:.1f}, "
                f"{r['ts'] + r.get('dur', 0.0):.1f}] us, request "
                f"[{req['ts']:.1f}, "
                f"{req['ts'] + req.get('dur', 0.0):.1f}] us, slack "
                f"{slack:.1f} us) — offset estimate or span "
                f"semantics regressed")
            break
        checked += 1
    if routes and not checked and (req_by_rid or req_by_trace):
        errors.append(
            f"{len(routes)} traced route span(s) and "
            f"{len(req_by_rid) or len(req_by_trace)} traced request "
            f"span(s) share NO rid/trace join — cross-process "
            f"propagation is broken")
    if checked:
        notes.append(f"containment: {checked}/{len(routes)} routed "
                     f"request(s) inside their route span after "
                     f"alignment")

    # -- join integrity --
    rids = [(e.get("args") or {}).get("rid")
            for e in by_name.get("request", [])]
    stamped = [r for r in rids if r]
    if len(set(stamped)) != len(stamped):
        dupes = sorted({r for r in stamped if stamped.count(r) > 1})
        errors.append(f"rids reused ACROSS processes: {dupes} — "
                      f"federated evidence aliases")
    route_traces = {(e.get("args") or {}).get("trace") for e in routes}
    orphans = [t for t in req_by_trace if t not in route_traces]
    if routes and orphans:
        errors.append(
            f"request span(s) carry trace id(s) no route span "
            f"minted: {sorted(orphans)[:3]} — the join key leaked "
            f"or the front's ring dropped the route")
    return errors


_FLIGHT_SCHEMA = "tfidf-flight/1"
_FLIGHT_LEVELS = {"debug", "info", "warning", "error"}


def check_flight(path: str) -> Tuple[List[str], List[str]]:
    """Validate a flight-recorder dump (``--flight`` /
    ``TFIDF_TPU_FLIGHT`` / ``<trace>.flight.jsonl``): header schema,
    header counts == body counts (completeness — the atomicity
    contract's observable half), per-line event/digest shape. Returns
    ``(errors, notes)``."""
    import json
    errors: List[str] = []
    notes: List[str] = []
    with open(path) as f:
        lines = [l for l in (ln.strip() for ln in f) if l]
    if not lines:
        return ["flight dump is empty"], notes
    try:
        header = json.loads(lines[0])
    except ValueError as e:
        return [f"flight header is not JSON: {e}"], notes
    if header.get("schema") != _FLIGHT_SCHEMA:
        return [f"flight schema {header.get('schema')!r} != "
                f"{_FLIGHT_SCHEMA!r}"], notes
    n_events = n_digests = 0
    for i, line in enumerate(lines[1:], 2):
        try:
            rec = json.loads(line)
        except ValueError as e:
            errors.append(f"line {i}: not JSON: {e}")
            break
        kind = rec.get("kind")
        if kind == "event":
            n_events += 1
            if not isinstance(rec.get("t"), (int, float)) \
                    or rec.get("level") not in _FLIGHT_LEVELS \
                    or not rec.get("event"):
                errors.append(f"line {i}: malformed event: {rec!r}")
                break
        elif kind == "digest":
            n_digests += 1
            if not isinstance(rec.get("t"), (int, float)) \
                    or not rec.get("outcome"):
                errors.append(f"line {i}: malformed digest: {rec!r}")
                break
        else:
            errors.append(f"line {i}: unknown kind {kind!r}")
            break
    if (n_events, n_digests) != (header.get("events"),
                                 header.get("digests")):
        errors.append(
            f"header promises {header.get('events')} events / "
            f"{header.get('digests')} digests, body carries "
            f"{n_events} / {n_digests} — torn dump")
    notes.append(f"flight: {n_events} events, {n_digests} digests, "
                 f"suppressed={header.get('suppressed', {})}")
    return errors, notes


def _cross_check_quarantine(trace_path: str, flight_path: str,
                            notes: List[str]) -> List[str]:
    """Trace + flight are one incident's evidence: when the flight
    dump records quarantines, the trace's request spans must show the
    ``poisoned`` terminal outcome — a quarantined request that never
    ENDS poisoned either hung or was misreported."""
    import json
    with open(flight_path) as f:
        lines = [l for l in (ln.strip() for ln in f) if l]
    quarantines = sum(
        1 for line in lines[1:]
        if json.loads(line).get("event") == "query_quarantined")
    if not quarantines:
        return []
    events = load_chrome_trace(trace_path)
    requests = [e for e in events if e.get("ph") == "X"
                and e.get("name") == "request"]
    if not requests:
        return []    # not a serve trace: nothing to cross-check
    poisoned = sum(1 for e in requests
                   if (e.get("args") or {}).get("outcome")
                   == "poisoned")
    if poisoned == 0:
        return [f"flight records {quarantines} quarantine(s) but no "
                f"request span ends with outcome 'poisoned' — "
                f"quarantined requests must terminate typed"]
    notes.append(f"quarantine cross-check: {quarantines} event(s), "
                 f"{poisoned} poisoned request span(s)")
    return []


def main() -> int:
    ap = argparse.ArgumentParser(
        description=__doc__.split("\n")[0],
        epilog="exit 0 = invariants hold, 1 = violated, 2 = unreadable")
    ap.add_argument("trace", help="Chrome trace-event JSON "
                                  "(--trace / TFIDF_TPU_TRACE output)")
    ap.add_argument("--mode", choices=["auto", "ingest", "serve",
                                       "schema", "merged"],
                    default="auto")
    ap.add_argument("--min-threads", type=int, default=3,
                    help="fewest distinct lanes the trace must carry "
                         "(default 3: main + packer + drainer, or "
                         "main + submitters + batcher)")
    ap.add_argument("--flight", metavar="DUMP.jsonl", default=None,
                    help="also validate this flight-recorder dump "
                         "(schema header, completeness, event/digest "
                         "shape)")
    args = ap.parse_args()
    try:
        errors, notes = check_trace(args.trace, args.mode,
                                    args.min_threads)
    except (OSError, ValueError) as e:
        print(f"trace_check: cannot read {args.trace}: {e}",
              file=sys.stderr)
        return 2
    if args.flight:
        try:
            ferrors, fnotes = check_flight(args.flight)
        except OSError as e:
            print(f"trace_check: cannot read {args.flight}: {e}",
                  file=sys.stderr)
            return 2
        errors += ferrors
        notes += fnotes
        if not ferrors:
            errors += _cross_check_quarantine(args.trace, args.flight,
                                              notes)
    for n in notes:
        print(f"  {n}")
    if errors:
        for err in errors:
            print(f"FAIL: {err}", file=sys.stderr)
        return 1
    print(f"trace_check: {args.trace} OK"
          + (f" (+ flight {args.flight})" if args.flight else ""))
    return 0


if __name__ == "__main__":
    sys.exit(main())
