"""Top-k term recall vs the exact-string oracle — the second half of the
north-star metric (BASELINE.md: "identical top-k terms").

The native bit-reference (``native/tfidf_ref.cc``) emits the reference's
exact per-(doc, word) score lines (``doc@word\\t%.16f``, ``TFIDF.c:245,
274-282``) with string-keyed exact vocabulary. The TPU path hashes words
into a fixed vocab (``ops.hashing``), so its top-k is a set of *bucket*
ids. Recall here is therefore computed collision-aware, in bucket space
(SURVEY §7 "hard parts"):

* the oracle's positive-score top-k words are mapped through the same
  FNV-1a + fold hash the TPU path used;
* ties at the k-th score are all *acceptable* (either side's ordering
  among equal scores is arbitrary — the reference itself breaks ties by
  insertion order, ``TFIDF.c:303-317``);
* two oracle words that collide into one bucket count once in the
  denominator — the TPU path cannot distinguish them by construction.

``recall == 1.0`` on a collision-free corpus is pinned by
``tests/test_recall.py``; the benchmark reports the measured value on
its Zipf corpus alongside docs/sec.
"""

from __future__ import annotations

from typing import Dict, Iterable, List, Optional, Sequence, Tuple

import numpy as np

from tfidf_tpu.ops.hashing import words_to_ids

DocTerms = List[Tuple[bytes, float]]


def parse_oracle_output(path: str, docs: Optional[Iterable[str]] = None
                        ) -> Dict[str, DocTerms]:
    """Parse reference-format output into per-doc (word, score) lists.

    ``docs``: optional doc-name filter — with a 1M-doc corpus the file
    has one line per (doc, word) record, so recall is usually sampled on
    a subset without holding the full parse in memory.
    """
    want = set(docs) if docs is not None else None
    per: Dict[str, DocTerms] = {}
    with open(path, "rb") as f:
        for line in f:
            line = line.rstrip(b"\n")
            if not line:
                continue
            key, score = line.rsplit(b"\t", 1)
            doc, word = key.split(b"@", 1)  # strict names hold no '@'
            name = doc.decode()
            if want is not None and name not in want:
                continue
            per.setdefault(name, []).append((word, float(score)))
    return per


def doc_recall(ref_terms: DocTerms, got_ids: Sequence[int],
               got_vals: Sequence[float], k: int, vocab_size: int,
               seed: int = 0) -> Optional[float]:
    """Collision-aware recall@k of hashed top-k ids vs exact oracle terms.

    Returns None when the oracle has no positive-score terms for the doc
    (every term appears in all docs -> IDF 0; recall is undefined, and
    both sides agree nothing is informative).
    """
    pos = sorted((t for t in ref_terms if t[1] > 0.0), key=lambda t: -t[1])
    if not pos:
        return None
    kk = min(k, len(pos))
    thresh = pos[kk - 1][1]
    buckets = words_to_ids([w for w, _ in pos], vocab_size, seed)
    required = {int(b) for b in buckets[:kk]}
    # Buckets strictly above the k-th score are mandatory; buckets tied
    # AT the k-th score are interchangeable (either side's ordering among
    # equal scores is arbitrary — the reference itself breaks ties by
    # insertion order, TFIDF.c:303-317). A hit on a tied bucket may only
    # fill a tie slot, never substitute for a missed mandatory bucket.
    above = {int(b) for b, (_, s) in zip(buckets, pos) if s > thresh}
    tied = {int(b) for b, (_, s) in zip(buckets, pos) if s == thresh}
    got = {int(i) for i, v in zip(got_ids, got_vals) if i >= 0 and v > 0.0}
    tie_slots = len(required) - len(required & above)
    hit = len(got & above & required) + min(tie_slots, len(got & tied))
    return min(1.0, hit / len(required))


def exact_doc_recall(ref_terms: DocTerms, got_words: Sequence[bytes],
                     k: int) -> Optional[float]:
    """Recall@k of exact-string terms (rerank.exact_topk output) vs the
    oracle — same tie semantics as :func:`doc_recall`, no bucketing."""
    pos = sorted((t for t in ref_terms if t[1] > 0.0), key=lambda t: -t[1])
    if not pos:
        return None
    kk = min(k, len(pos))
    thresh = pos[kk - 1][1]
    required = {w for w, _ in pos[:kk]}
    above = {w for w, s in pos if s > thresh}
    tied = {w for w, s in pos if s == thresh}
    got = set(got_words)
    tie_slots = len(required) - len(required & above)
    hit = len(got & above & required) + min(tie_slots, len(got & tied))
    return min(1.0, hit / len(required))


def retrieval_recall_at_k(got_ids: np.ndarray, oracle_ids: np.ndarray,
                          k: int) -> float:
    """Mean per-query recall@k of RETRIEVED DOC ids vs an oracle
    ranking — the scoring-family suite's metric (round 23): each
    scorer's device top-k is recalled against ITS OWN NumPy-oracle
    top-k (``scoring.oracle.oracle_topk``), so 1.0 is the bit-parity
    expectation, not a vocabulary accident. ``-1`` slots (fewer than k
    positive-score docs) are empty on both sides and drop out of the
    denominator; a query where the oracle retrieves nothing is skipped
    (recall undefined — both sides agree nothing matches)."""
    got = np.asarray(got_ids)
    ora = np.asarray(oracle_ids)
    if got.shape[0] != ora.shape[0]:
        raise ValueError(f"query-count mismatch: {got.shape[0]} vs "
                         f"{ora.shape[0]}")
    scores = []
    for qi in range(ora.shape[0]):
        want = {int(d) for d in ora[qi][:k] if d >= 0}
        if not want:
            continue
        have = {int(d) for d in got[qi][:k] if d >= 0}
        scores.append(len(have & want) / len(want))
    if not scores:
        raise ValueError("no queries with defined recall")
    return float(np.mean(scores))


def scorer_overlap_at_k(ids_a: np.ndarray, ids_b: np.ndarray,
                        k: int) -> float:
    """Mean Jaccard overlap of two scorers' top-k doc sets over the
    same queries — how DIFFERENT two family members' rankings are
    (bm25 vs tfidf in the scoring artifact: well below 1.0 on a Zipf
    corpus, or the bm25 face derivation is secretly the tfidf one).
    Queries where both sides retrieve nothing are skipped."""
    a, b = np.asarray(ids_a), np.asarray(ids_b)
    if a.shape[0] != b.shape[0]:
        raise ValueError(f"query-count mismatch: {a.shape[0]} vs "
                         f"{b.shape[0]}")
    scores = []
    for qi in range(a.shape[0]):
        sa = {int(d) for d in a[qi][:k] if d >= 0}
        sb = {int(d) for d in b[qi][:k] if d >= 0}
        if not sa and not sb:
            continue
        scores.append(len(sa & sb) / len(sa | sb))
    if not scores:
        raise ValueError("no queries with any retrieved docs")
    return float(np.mean(scores))


def corpus_recall(per_doc_ref: Dict[str, DocTerms], names: Sequence[str],
                  topk_ids: np.ndarray, topk_vals: np.ndarray, k: int,
                  vocab_size: int, seed: int = 0) -> float:
    """Mean doc_recall over every doc present in ``per_doc_ref``.

    ``names[d]`` aligns row d of ``topk_ids``/``topk_vals`` with its
    oracle terms; docs with undefined recall are excluded from the mean.
    """
    scores = []
    for d, name in enumerate(names):
        ref = per_doc_ref.get(name)
        if ref is None:
            continue
        r = doc_recall(ref, topk_ids[d], topk_vals[d], k, vocab_size, seed)
        if r is not None:
            scores.append(r)
    if not scores:
        raise ValueError("no overlapping docs with defined recall")
    return float(np.mean(scores))
