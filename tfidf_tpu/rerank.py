"""Exact-string re-rank of the hashed top-k (SURVEY §7 "hard parts").

The scalable TPU path hashes words into a fixed vocab, so its per-doc
top-k is a set of *bucket* ids: two words colliding into one bucket are
scored on their merged counts and DF, and the emitted "term" is only a
bucket representative. The reference keys everything by exact strings
(``TFIDF.c:26-42``), so its top-k is exact — the north-star metric asks
for *identical top-k terms* (BASELINE.md).

This module closes the gap with a host-side post-pass over the TPU
selection, the design SURVEY §7 sketches ("a host-side exact-string
re-rank of the top-k"):

1. Re-tokenize the selected documents and keep, per doc, the exact
   words whose hash bucket landed in that doc's TPU top-k. Hashing
   restricts the candidate set to ~k buckets per doc — the pass stays
   O(tokens) with tiny constant state, never O(V) strings.
2. One pass over the *whole* corpus counts exact document frequencies
   for the global candidate-word set only.
3. Exact TF-IDF (float64, the reference's op order) re-scores each
   doc's candidates and re-ranks.

What it can and cannot fix: bucket *merging* (the dominant hashed-vocab
error — wrong DF, wrong ordering, wrong representative word) is fully
undone for every word whose bucket made the device top-k. A word whose
bucket was pushed *out* of the device top-k by a collision partner
stays lost; widening the device k (`margin`) shrinks that window.
"""

from __future__ import annotations

import os
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

import numpy as np

from tfidf_tpu.config import PipelineConfig, TokenizerKind
from tfidf_tpu.io import fast_tokenizer
from tfidf_tpu.obs import log as obs_log
from tfidf_tpu.ops.hashing import words_to_ids
from tfidf_tpu.ops.tokenize import whitespace_tokenize

DocTerms = List[Tuple[bytes, float]]


def margin_check(df, margin: int, *, occupied: Optional[int] = None,
                 vocab_size: Optional[int] = None) -> Optional[str]:
    """Collision-pressure guard for the exact-terms margin.

    Estimates the vocab load factor from the occupied-bucket fraction
    (alpha = -ln(1 - B/V) under uniform hashing) and returns a
    human-readable warning when ``margin`` is below the measured-safe
    level for it — margin 4 up to alpha 0.25, margin 8 beyond (the
    sweep in docs/EXACT.md). Returns None when the margin is safe.
    Library-level so every exact-terms entry point (CLI, bench, direct
    :func:`exact_topk` callers) shares one rule.

    Pass either a DF vector (``df``) or the precomputed
    ``occupied``/``vocab_size`` scalar pair — the ingest wire carries
    the scalar (``IngestResult.df_occupied``) precisely so this check
    never forces a D2H fetch of a device-resident DF (advisor r3).
    """
    import math

    if df is not None:
        df = np.asarray(df)
        occupied, vocab_size = int((df > 0).sum()), df.size
    occ = float(occupied) / vocab_size
    alpha = -math.log(max(1.0 - min(occ, 0.999999), 1e-12))
    suggested = 4 if alpha <= 0.25 else 8
    if margin >= suggested:
        return None
    return (f"vocab load factor ~{alpha:.2f} (occupancy {occ:.2f}): "
            f"exact-terms margin {margin} may miss exact top-k words — "
            f"measured-safe margin here is {suggested} (docs/EXACT.md)")


def exact_topk_from_wire(exact, k: int, input_dir: str,
                         cfg: PipelineConfig,
                         max_tokens: Optional[int] = None
                         ) -> Dict[str, DocTerms]:
    """Float64 rescore of an exact-ids device selection — document
    re-reads only for boundary-tie docs (the device-exact half of the
    exact-terms mode).

    ``exact`` is an :class:`~tfidf_tpu.ingest.ExactIngest`: because the
    intern ids are collision-free, the wire's (count, df) integers are
    word-exact, and the reference's score (tf = count/docSize,
    idf = ln(N/df), float64 op order — ``TFIDF.c:202,243``) is computed
    right here from integers. Same output contract as
    :func:`exact_topk`: score-desc then word-asc, at most k entries,
    positive scores only.

    Boundary ties: a tie group (equal exact scores — e.g. a doc's
    corpus-hapax words all score ln(N)/docSize) can extend past the
    device's K'-candidate wire, and its word-asc members cannot then be
    chosen from the wire alone. Such docs are DETECTED exactly (full
    wire whose tail score equals the would-be k-th score) and resolved
    with a doc-local exact pass: tokenize that one document, join
    counts against the device's exact [V] DF — no corpus scan.
    """
    lens = np.maximum(exact.lengths.astype(np.float64), 1.0)
    valid = exact.topk_counts > 0
    tf = exact.topk_counts.astype(np.float64) / lens[:, None]
    dfsel = np.where(valid, exact.df[np.maximum(exact.topk_ids, 0)], 1)
    idf = np.log(float(exact.num_docs) / dfsel.astype(np.float64))
    scores = np.where(valid, tf * idf, 0.0)
    # Reference tie order (score desc, word asc): precompute each id's
    # rank in byte-lex word order, then one vectorized lexsort per row.
    words = exact.words
    rank = np.empty(max(len(words), 1), dtype=np.int64)
    rank[np.asarray(sorted(range(len(words)), key=words.__getitem__),
                    dtype=np.int64)] = np.arange(len(words))
    wr = rank[np.maximum(exact.topk_ids, 0)]
    sel = np.lexsort((wr, -scores), axis=1)
    sc = np.take_along_axis(scores, sel, axis=1)
    ids = np.take_along_axis(exact.topk_ids, sel, axis=1)
    kprime = sc.shape[1]
    kk = min(k, kprime)
    # Boundary-tie detection (vectorized): the wire is full AND its
    # worst candidate's positive score ties the k-th entry — the tie
    # group may continue past the wire, so the word-asc choice is
    # undecidable from the wire alone. Two refinements (advisor r4):
    #  * "ties" means within float32 rounding distance (4e-6 relative),
    #    not only exact float64 equality — the device ranked by float32,
    #    so a near-tie group can collapse there and be truncated in
    #    intern-id order even when the float64 scores are distinct;
    #  * a doc with lengths <= kprime tokens cannot have more distinct
    #    terms than the wire holds — its full wire IS the complete term
    #    set, so the heuristic must not fire (otherwise doc_len <= k
    #    degrades every dense doc to a doc-local re-read).
    full = valid.all(axis=1)
    if kprime > 0:
        near = (sc[:, kk - 1] - sc[:, kprime - 1]) \
            <= sc[:, kk - 1] * 4e-6
        tied = full & near & (sc[:, kprime - 1] > 0.0) \
            & (exact.lengths > kprime)
    else:
        tied = np.zeros(sc.shape[0], bool)
    # Bulk-convert once (C-speed) — the per-doc loop then touches only
    # Python floats/ints, which halves dict-build time at 1M rows.
    sc_l = sc[:, :kk].tolist()
    id_l = ids[:, :kk].tolist()
    out: Dict[str, DocTerms] = {}
    for d, name in enumerate(exact.names):
        if tied[d]:
            continue  # resolved below from the document itself
        row_sc, row_id, row = sc_l[d], id_l[d], []
        for j in range(kk):
            s = row_sc[j]
            if s <= 0.0:
                break  # sorted desc: the rest are zero/invalid
            row.append((words[row_id[j]], s))
        out[name] = row
    n_tied = int(tied.sum())
    if n_tied:
        # Doc-local exact resolution: one tokenize per affected doc,
        # DF joined from the wire's exact [V] vector — no corpus scan.
        word2id = {w: i for i, w in enumerate(words)}
        n = float(exact.num_docs)
        for d in np.flatnonzero(tied):
            name = exact.names[d]
            toks, size = _doc_words(input_dir, name, cfg, max_tokens)
            counts: Dict[bytes, int] = {}
            for w in toks:
                counts[w] = counts.get(w, 0) + 1
            scored = []
            for w, c in counts.items():
                s = (c / max(size, 1)) \
                    * float(np.log(n / exact.df[word2id[w]]))
                if s > 0.0:
                    scored.append((w, s))
            scored.sort(key=lambda t: (-t[1], t[0]))
            out[name] = scored[:k]
    return out


def exact_terms(input_dir: str, cfg: PipelineConfig, k: int, *,
                doc_len: Optional[int] = None, chunk_docs: int = 8192,
                strict: bool = True):
    """One-call exact-terms mode with automatic engine choice.

    Tries the device-exact fast path (``ingest.run_overlapped_exact``:
    collision-free intern ids, host rescore from wire integers, no
    corpus re-pass) and falls back to the hashed+margin+rerank engine
    when the corpus cannot be served exactly — more distinct words than
    ``cfg.vocab_size``, no native build, or past the resident budget.

    ``cfg.topk`` is the device margin selection (margin*k). The device-
    exact path clamps it to 2k: with no collisions the margin only has
    to absorb float32-vs-float64 rank-boundary rounding, not collision
    displacement (docs/EXACT.md) — recall is pinned by the bench.

    Returns ``(per_doc, engine)`` where engine is "device-exact" or
    "hashed-rerank".
    """
    from tfidf_tpu.io import fast_tokenizer as ft

    # The truncation the ingest applies (ingest length rule) — the
    # rescore must re-tokenize with the SAME cap or tied docs would
    # score terms the device never saw.
    length = doc_len or cfg.max_doc_len
    exact = None
    if ft.intern_available():
        from tfidf_tpu.ingest import run_overlapped_exact
        try:
            # Narrow try: only the ingest may legitimately fail over
            # (overflow / resident budget / vocab width). A bug in the
            # rescore below must surface, not silently re-run the
            # corpus on the slow engine.
            exact = run_overlapped_exact(input_dir,
                                         _device_cfg(cfg, k),
                                         chunk_docs=chunk_docs,
                                         doc_len=doc_len, strict=strict)
        except (ft.ExactVocabOverflow, ValueError) as e:
            obs_log.log_event(
                "info", "exact_engine_fallback",
                msg=f"exact-terms: device-exact path unavailable "
                    f"({e}); using hashed re-rank engine",
                error=str(e))
    else:
        obs_log.log_event(
            "info", "exact_engine_fallback",
            msg="exact-terms: native intern table not built; using "
                "hashed re-rank engine", error="no-intern")
    if exact is not None:
        return (exact_topk_from_wire(exact, k, input_dir, cfg,
                                     max_tokens=length),
                "device-exact")
    return _exact_terms_fallback(input_dir, cfg, k, doc_len=doc_len,
                                 chunk_docs=chunk_docs, strict=strict)


def _device_cfg(cfg: PipelineConfig, k: int) -> PipelineConfig:
    """The device-exact selection config: margin k+8, the SINGLE margin
    rule for both exact-terms entry points. With collision-free ids the
    spare slots exist only to EXPOSE a boundary tie (which then
    resolves doc-locally) — correctness holds for any margin > k, so
    the margin does not scale with cfg.topk the way the hashed
    engine's collision margin must (docs/EXACT.md)."""
    import dataclasses as _dc

    # The margin must STRICTLY exceed k: with kprime == k the tie
    # detector's condition (tail score == k-th score on a full wire) is
    # trivially true and every dense doc degrades to the doc-local
    # re-read (review r4: measured 50/50 docs re-read at cfg.topk == k).
    dev_topk = k + 8 if cfg.topk is None \
        else max(k + 1, min(cfg.topk, k + 8))
    return _dc.replace(cfg, topk=dev_topk)


def exact_terms_lines(input_dir: str, cfg: PipelineConfig, k: int, *,
                      doc_len: Optional[int] = None,
                      chunk_docs: int = 8192, strict: bool = True,
                      spill: str = "auto"):
    """Exact-terms mode producing the FINAL sorted output bytes — the
    complete job (ingest + float64 rescore + per-doc and global sort +
    reference formatting), which is what the CPU oracle's wall clock
    also covers.

    Fast path: device-exact ingest + the native ``exact_emit`` finish
    (rescore/format/sort all in C++, boundary ties resolved doc-locally
    against the live intern table). Falls back to :func:`exact_terms` +
    Python line assembly when the corpus can't be served exactly.

    Returns ``(lines, engine, sample_fn)``: ``lines`` is the sorted
    output bytes (trailing newline included), and ``sample_fn(names)``
    lazily builds the per-doc ``[(word, score), ...]`` lists for a doc
    subset (recall measurement) without paying the full-corpus dict.
    """
    from tfidf_tpu.io import fast_tokenizer as ft

    length = doc_len or cfg.max_doc_len  # the ingest truncation cap
    if ft.intern_available():
        from tfidf_tpu.ingest import run_overlapped_exact
        with ft.InternSession(cfg.vocab_size) as sess:
            try:
                # Narrow try (see exact_terms): only the ingest may
                # legitimately fail over to the hashed engine.
                exact = run_overlapped_exact(input_dir,
                                             _device_cfg(cfg, k),
                                             chunk_docs=chunk_docs,
                                             doc_len=doc_len,
                                             strict=strict, session=sess)
            except (ft.ExactVocabOverflow, ValueError) as e:
                obs_log.log_event(
                    "info", "exact_engine_fallback",
                    msg=f"exact-terms: device-exact path unavailable "
                        f"({e}); using hashed re-rank engine",
                    error=str(e))
                exact = None
            if exact is not None:
                lines, per_doc, offs, lens, scores, wblob = sess.emit(
                    input_dir, exact.names, exact.topk_ids,
                    exact.topk_counts, exact.df, exact.lengths,
                    exact.num_docs, k, cfg.truncate_tokens_at, length,
                    seed=cfg.hash_seed)

                def sample_fn(names):
                    want = set(names)
                    starts = np.zeros(len(per_doc) + 1, dtype=np.int64)
                    np.cumsum(per_doc, out=starts[1:])
                    out: Dict[str, DocTerms] = {}
                    for d, name in enumerate(exact.names):
                        if name not in want:
                            continue
                        lo, hi = int(starts[d]), int(starts[d + 1])
                        out[name] = [(wblob[offs[j]:offs[j] + lens[j]],
                                      float(scores[j]))
                                     for j in range(lo, hi)]
                    return out

                return lines, "device-exact", sample_fn
    else:
        obs_log.log_event(
            "info", "exact_engine_fallback",
            msg="exact-terms: native intern table not built; using "
                "hashed re-rank engine", error="no-intern")

    per_doc_dict, engine = _exact_terms_fallback(input_dir, cfg, k,
                                                 doc_len=doc_len,
                                                 chunk_docs=chunk_docs,
                                                 strict=strict, spill=spill)
    lines_list = [b"%s@%s\t%.16f" % (name.encode(), w, s)
                  for name, terms in per_doc_dict.items() if name
                  for w, s in terms]
    lines_list.sort()
    lines = b"".join(l + b"\n" for l in lines_list)
    return lines, engine, (lambda names: {n: per_doc_dict[n]
                                          for n in names
                                          if n in per_doc_dict})


def _exact_terms_fallback(input_dir: str, cfg: PipelineConfig, k: int, *,
                          doc_len: Optional[int], chunk_docs: int,
                          strict: bool, spill: str = "auto"):
    """The hashed+margin+rerank engine (shared by the two entry points).
    ``spill`` applies when the ingest runs the streaming regime — the
    device-exact path is resident-only, so only this engine reads it."""
    from tfidf_tpu.ingest import run_overlapped

    r = run_overlapped(input_dir, cfg, chunk_docs=chunk_docs,
                       doc_len=doc_len, strict=strict, wire_vals=False,
                       spill=spill)
    # max_tokens mirrors the ingest truncation rule (doc_len or
    # cfg.max_doc_len) so the re-rank's TF/docSize stay device-parity.
    return (exact_topk(input_dir, r.names, r.topk_ids, r.num_docs, cfg,
                       k=k, max_tokens=doc_len or cfg.max_doc_len,
                       df_occupied=r.df_occupied), "hashed-rerank")


def _doc_words(input_dir: str, name: str, cfg: PipelineConfig,
               max_tokens: Optional[int]) -> Tuple[List[bytes], int]:
    """Exact host tokenization of one document, mirroring the packer:
    tokens past ``max_tokens`` are truncated away (count and content),
    matching the fixed-L device batch the TPU selection came from."""
    with open(os.path.join(input_dir, name), "rb") as f:
        data = f.read()
    if cfg.truncate_tokens_at is None:
        from tfidf_tpu.io import fast_tokenizer
        words = fast_tokenizer.tokenize_spans(data)  # native when built
        if words is None:
            words = whitespace_tokenize(data, None)
    else:
        words = whitespace_tokenize(data, cfg.truncate_tokens_at)
    if max_tokens is not None:
        words = words[:max_tokens]
    return words, len(words)


def exact_topk(input_dir: str, names: Sequence[str], topk_ids: np.ndarray,
               num_docs: int, cfg: PipelineConfig, k: int,
               docs: Optional[Iterable[str]] = None,
               max_tokens: Optional[int] = None,
               df: Optional[np.ndarray] = None,
               df_occupied: Optional[int] = None) -> Dict[str, DocTerms]:
    """Exact-string top-k for ``docs`` from a hashed TPU selection.

    Args:
      input_dir: the corpus directory the selection was computed from.
      names: row order of ``topk_ids`` (e.g. ``IngestResult.names``).
      topk_ids: [D, K'] device top-k bucket ids (-1 = padding).
      num_docs: corpus document count (drives exact IDF).
      cfg: the pipeline config the selection used (hash seed/vocab).
      k: how many exact terms to return per doc (k <= K' margin).
      docs: optional doc-name subset (default: all rows of ``names``).
      max_tokens: the static L of the device batch, when one was used
        (e.g. ``run_overlapped(doc_len=...)``) — keeps TF/docSize parity
        with what the device scored.
      df: the run's measured DF vector, when available — enables the
        :func:`margin_check` collision-pressure warning (stderr) for
        every caller, not just the CLI.
      df_occupied: the occupied-bucket count instead of the vector
        (``IngestResult.df_occupied``) — same warning, no DF fetch
        from a device-resident run.

    Returns:
      name -> [(word, score), ...] exact float64 TF-IDF, score-desc then
      word-asc, at most k entries, only positive-scoring words.
    """
    if (df is not None or df_occupied is not None) \
            and np.asarray(topk_ids).ndim == 2 and k > 0:
        m = max(np.asarray(topk_ids).shape[1] // k, 1)
        if df_occupied is not None:
            warn = margin_check(None, m, occupied=df_occupied,
                                vocab_size=cfg.vocab_size)
        else:
            warn = margin_check(df, m)
        if warn is not None:
            obs_log.log_event("warning", "margin_pressure",
                              msg=f"warning: {warn}")

    # Padding rows (mesh/chunk pad_docs_to) carry '' names and all -1
    # topk ids — skip them everywhere, like pass 2 always did; opening
    # os.path.join(input_dir, '') is the directory itself.
    want = [n for n in (docs if docs is not None else names) if n]
    rows = {n: i for i, n in enumerate(names)}

    # Native fast path (native/rerank.cc): the full three-pass re-rank
    # runs in the loader's thread pool — document bytes never enter
    # Python. Round 2 measured the Python passes at 0.39x the CPU
    # oracle; this path is what makes exact-terms mode beat it. The
    # Python implementation below remains the semantics oracle (parity
    # pinned by tests/test_rerank.py) and covers doc subsets and
    # missing-native builds.
    if docs is None and cfg.tokenizer is TokenizerKind.WHITESPACE \
            and fast_tokenizer.rerank_available():
        live = [n for n in names if n]
        idx = [rows[n] for n in live]
        native = fast_tokenizer.exact_rerank_paths(
            [os.path.join(input_dir, n) for n in live],
            np.asarray(topk_ids)[idx], num_docs, cfg.vocab_size,
            cfg.hash_seed, cfg.truncate_tokens_at, max_tokens, k)
        if native is not None:
            return dict(zip(live, native))

    # Pass 1 (selected docs): exact counts of candidate words — words
    # whose bucket made that doc's device top-k.
    per_doc: Dict[str, Tuple[Dict[bytes, int], int]] = {}
    candidates: set = set()
    for name in want:
        words, size = _doc_words(input_dir, name, cfg, max_tokens)
        buckets = set(int(b) for b in topk_ids[rows[name]] if b >= 0)
        if not words or not buckets:
            per_doc[name] = ({}, size)
            continue
        uniq = sorted(set(words))
        ids = words_to_ids(uniq, cfg.vocab_size, cfg.hash_seed)
        keep = {w for w, b in zip(uniq, ids) if int(b) in buckets}
        counts: Dict[bytes, int] = {}
        for w in words:
            if w in keep:
                counts[w] = counts.get(w, 0) + 1
        per_doc[name] = (counts, size)
        candidates.update(keep)

    # Pass 2 (whole corpus): exact DF for the candidate set only.
    df: Dict[bytes, int] = {w: 0 for w in candidates}
    if candidates:
        for name in names:
            if not name:
                continue  # padding rows
            words, _ = _doc_words(input_dir, name, cfg, max_tokens)
            for w in set(words) & candidates:
                df[w] += 1

    # Exact scoring in the reference's op order (float64, natural log).
    out: Dict[str, DocTerms] = {}
    for name in want:
        counts, size = per_doc[name]
        scored = []
        for w, c in counts.items():
            tf = 1.0 * c / size
            idf = np.log(1.0 * num_docs / df[w])
            if tf * idf > 0.0:
                scored.append((w, float(tf * idf)))
        scored.sort(key=lambda t: (-t[1], t[0]))
        out[name] = scored[:k]
    return out
