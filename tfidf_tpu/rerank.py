"""Exact-string re-rank of the hashed top-k (SURVEY §7 "hard parts").

The scalable TPU path hashes words into a fixed vocab, so its per-doc
top-k is a set of *bucket* ids: two words colliding into one bucket are
scored on their merged counts and DF, and the emitted "term" is only a
bucket representative. The reference keys everything by exact strings
(``TFIDF.c:26-42``), so its top-k is exact — the north-star metric asks
for *identical top-k terms* (BASELINE.md).

This module closes the gap with a host-side post-pass over the TPU
selection, the design SURVEY §7 sketches ("a host-side exact-string
re-rank of the top-k"):

1. Re-tokenize the selected documents and keep, per doc, the exact
   words whose hash bucket landed in that doc's TPU top-k. Hashing
   restricts the candidate set to ~k buckets per doc — the pass stays
   O(tokens) with tiny constant state, never O(V) strings.
2. One pass over the *whole* corpus counts exact document frequencies
   for the global candidate-word set only.
3. Exact TF-IDF (float64, the reference's op order) re-scores each
   doc's candidates and re-ranks.

What it can and cannot fix: bucket *merging* (the dominant hashed-vocab
error — wrong DF, wrong ordering, wrong representative word) is fully
undone for every word whose bucket made the device top-k. A word whose
bucket was pushed *out* of the device top-k by a collision partner
stays lost; widening the device k (`margin`) shrinks that window.
"""

from __future__ import annotations

import os
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

import numpy as np

from tfidf_tpu.config import PipelineConfig, TokenizerKind
from tfidf_tpu.io import fast_tokenizer
from tfidf_tpu.ops.hashing import words_to_ids
from tfidf_tpu.ops.tokenize import whitespace_tokenize

DocTerms = List[Tuple[bytes, float]]


def margin_check(df, margin: int, *, occupied: Optional[int] = None,
                 vocab_size: Optional[int] = None) -> Optional[str]:
    """Collision-pressure guard for the exact-terms margin.

    Estimates the vocab load factor from the occupied-bucket fraction
    (alpha = -ln(1 - B/V) under uniform hashing) and returns a
    human-readable warning when ``margin`` is below the measured-safe
    level for it — margin 4 up to alpha 0.25, margin 8 beyond (the
    sweep in docs/EXACT.md). Returns None when the margin is safe.
    Library-level so every exact-terms entry point (CLI, bench, direct
    :func:`exact_topk` callers) shares one rule.

    Pass either a DF vector (``df``) or the precomputed
    ``occupied``/``vocab_size`` scalar pair — the ingest wire carries
    the scalar (``IngestResult.df_occupied``) precisely so this check
    never forces a D2H fetch of a device-resident DF (advisor r3).
    """
    import math

    if df is not None:
        df = np.asarray(df)
        occupied, vocab_size = int((df > 0).sum()), df.size
    occ = float(occupied) / vocab_size
    alpha = -math.log(max(1.0 - min(occ, 0.999999), 1e-12))
    suggested = 4 if alpha <= 0.25 else 8
    if margin >= suggested:
        return None
    return (f"vocab load factor ~{alpha:.2f} (occupancy {occ:.2f}): "
            f"exact-terms margin {margin} may miss exact top-k words — "
            f"measured-safe margin here is {suggested} (docs/EXACT.md)")


def _doc_words(input_dir: str, name: str, cfg: PipelineConfig,
               max_tokens: Optional[int]) -> Tuple[List[bytes], int]:
    """Exact host tokenization of one document, mirroring the packer:
    tokens past ``max_tokens`` are truncated away (count and content),
    matching the fixed-L device batch the TPU selection came from."""
    with open(os.path.join(input_dir, name), "rb") as f:
        data = f.read()
    if cfg.truncate_tokens_at is None:
        from tfidf_tpu.io import fast_tokenizer
        words = fast_tokenizer.tokenize_spans(data)  # native when built
        if words is None:
            words = whitespace_tokenize(data, None)
    else:
        words = whitespace_tokenize(data, cfg.truncate_tokens_at)
    if max_tokens is not None:
        words = words[:max_tokens]
    return words, len(words)


def exact_topk(input_dir: str, names: Sequence[str], topk_ids: np.ndarray,
               num_docs: int, cfg: PipelineConfig, k: int,
               docs: Optional[Iterable[str]] = None,
               max_tokens: Optional[int] = None,
               df: Optional[np.ndarray] = None,
               df_occupied: Optional[int] = None) -> Dict[str, DocTerms]:
    """Exact-string top-k for ``docs`` from a hashed TPU selection.

    Args:
      input_dir: the corpus directory the selection was computed from.
      names: row order of ``topk_ids`` (e.g. ``IngestResult.names``).
      topk_ids: [D, K'] device top-k bucket ids (-1 = padding).
      num_docs: corpus document count (drives exact IDF).
      cfg: the pipeline config the selection used (hash seed/vocab).
      k: how many exact terms to return per doc (k <= K' margin).
      docs: optional doc-name subset (default: all rows of ``names``).
      max_tokens: the static L of the device batch, when one was used
        (e.g. ``run_overlapped(doc_len=...)``) — keeps TF/docSize parity
        with what the device scored.
      df: the run's measured DF vector, when available — enables the
        :func:`margin_check` collision-pressure warning (stderr) for
        every caller, not just the CLI.
      df_occupied: the occupied-bucket count instead of the vector
        (``IngestResult.df_occupied``) — same warning, no DF fetch
        from a device-resident run.

    Returns:
      name -> [(word, score), ...] exact float64 TF-IDF, score-desc then
      word-asc, at most k entries, only positive-scoring words.
    """
    if (df is not None or df_occupied is not None) \
            and np.asarray(topk_ids).ndim == 2 and k > 0:
        m = max(np.asarray(topk_ids).shape[1] // k, 1)
        if df_occupied is not None:
            warn = margin_check(None, m, occupied=df_occupied,
                                vocab_size=cfg.vocab_size)
        else:
            warn = margin_check(df, m)
        if warn is not None:
            import sys
            sys.stderr.write(f"warning: {warn}\n")

    # Padding rows (mesh/chunk pad_docs_to) carry '' names and all -1
    # topk ids — skip them everywhere, like pass 2 always did; opening
    # os.path.join(input_dir, '') is the directory itself.
    want = [n for n in (docs if docs is not None else names) if n]
    rows = {n: i for i, n in enumerate(names)}

    # Native fast path (native/rerank.cc): the full three-pass re-rank
    # runs in the loader's thread pool — document bytes never enter
    # Python. Round 2 measured the Python passes at 0.39x the CPU
    # oracle; this path is what makes exact-terms mode beat it. The
    # Python implementation below remains the semantics oracle (parity
    # pinned by tests/test_rerank.py) and covers doc subsets and
    # missing-native builds.
    if docs is None and cfg.tokenizer is TokenizerKind.WHITESPACE \
            and fast_tokenizer.rerank_available():
        live = [n for n in names if n]
        idx = [rows[n] for n in live]
        native = fast_tokenizer.exact_rerank_paths(
            [os.path.join(input_dir, n) for n in live],
            np.asarray(topk_ids)[idx], num_docs, cfg.vocab_size,
            cfg.hash_seed, cfg.truncate_tokens_at, max_tokens, k)
        if native is not None:
            return dict(zip(live, native))

    # Pass 1 (selected docs): exact counts of candidate words — words
    # whose bucket made that doc's device top-k.
    per_doc: Dict[str, Tuple[Dict[bytes, int], int]] = {}
    candidates: set = set()
    for name in want:
        words, size = _doc_words(input_dir, name, cfg, max_tokens)
        buckets = set(int(b) for b in topk_ids[rows[name]] if b >= 0)
        if not words or not buckets:
            per_doc[name] = ({}, size)
            continue
        uniq = sorted(set(words))
        ids = words_to_ids(uniq, cfg.vocab_size, cfg.hash_seed)
        keep = {w for w, b in zip(uniq, ids) if int(b) in buckets}
        counts: Dict[bytes, int] = {}
        for w in words:
            if w in keep:
                counts[w] = counts.get(w, 0) + 1
        per_doc[name] = (counts, size)
        candidates.update(keep)

    # Pass 2 (whole corpus): exact DF for the candidate set only.
    df: Dict[bytes, int] = {w: 0 for w in candidates}
    if candidates:
        for name in names:
            if not name:
                continue  # padding rows
            words, _ = _doc_words(input_dir, name, cfg, max_tokens)
            for w in set(words) & candidates:
                df[w] += 1

    # Exact scoring in the reference's op order (float64, natural log).
    out: Dict[str, DocTerms] = {}
    for name in want:
        counts, size = per_doc[name]
        scored = []
        for w, c in counts.items():
            tf = 1.0 * c / size
            idf = np.log(1.0 * num_docs / df[w])
            if tf * idf > 0.0:
                scored.append((w, float(tf * idf)))
        scored.sort(key=lambda t: (-t[1], t[0]))
        out[name] = scored[:k]
    return out
