"""Device-truth telemetry: HBM accounting + XLA compile watchdog.

Rounds 10–11 made every host thread observable; the chip itself stayed
a black box — nothing reported live/peak HBM, and the only compile
signal was serve_bench's one-shot ``_cache_size()`` pin. This module
is the device-side half of ``tfidf_tpu/obs``:

* :class:`DeviceMonitor` — samples per-device ``memory_stats()``
  (bytes-in-use, peak, limit) into registry gauges, takes a live-
  buffer census over ``jax.live_arrays()`` attributed by shape/dtype
  to named OWNERS (resident index, wire buffers, serve cache — any
  component that registers one), emits flight-recorder watermark
  events when HBM pressure crosses configurable thresholds, and
  exposes :meth:`health_signal` so a
  :class:`~tfidf_tpu.obs.health.HealthMonitor` degrades — and
  admission control sheds — *before* the allocator OOMs, the same way
  it already sheds on queue saturation.
* :class:`CompileWatch` — counts and fingerprints every XLA
  compilation: a process-global ``jax.monitoring`` listener counts
  real backend compiles (count + wall), and product call sites
  fingerprint the programs they know (:func:`note_compile` with
  shapes/dtype/k — ``TfidfRetriever.search`` stamps its bucketed
  search programs). After :meth:`mark_warm`, any further compile is a
  flight event + a windowed ``degraded`` health reason — the live
  generalization of round 9's post-hoc recompile pin.

Graceful degradation is a hard contract (tier-1 runs on
``JAX_PLATFORMS=cpu``): CPU devices return ``memory_stats() = None``
— the monitor still runs its FULL path (census, watermarks vacuous,
pressure 0.0, health signal clean) with the per-device gauges simply
absent, and partial stats dicts publish only the keys they carry.
jax imports lazily, at sample time — constructing a monitor costs no
backend init.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

from tfidf_tpu.obs import log as obs_log

__all__ = [
    "DeviceMonitor", "CompileWatch", "configure", "get_monitor",
    "set_monitor", "get_watch", "set_watch", "note_compile",
    "DEFAULT_WATERMARKS",
]

# HBM pressure fractions (in-use / limit) at which the monitor emits
# flight watermark events and reports a degraded health reason. Two
# rungs: the first is the shed-early line (health degrades, admission
# shrinks — drain while there is still headroom), the second the
# near-OOM alarm. Env override: TFIDF_TPU_HBM_WATERMARKS="0.8,0.95".
DEFAULT_WATERMARKS = (0.80, 0.95)


def _env_watermarks() -> Tuple[float, ...]:
    raw = os.environ.get("TFIDF_TPU_HBM_WATERMARKS")
    if not raw:
        return DEFAULT_WATERMARKS
    marks = tuple(sorted(float(p) for p in raw.split(",") if p.strip()))
    for m in marks:
        if not 0 < m <= 1:
            raise ValueError(
                f"TFIDF_TPU_HBM_WATERMARKS fractions must be in (0, 1], "
                f"got {m}")
    return marks or DEFAULT_WATERMARKS


class DeviceMonitor:
    """Samples device memory truth into gauges, events and a signal.

    Args:
      registry: optional :class:`~tfidf_tpu.obs.registry.
        MetricsRegistry`; per-device gauges (``hbm_bytes_in_use_d0``,
        ``hbm_peak_bytes_d0``, ``hbm_bytes_limit_d0``) are created
        lazily, only for stats keys the backend actually reports —
        on CPU no gauge ever appears.
      period_s: background sampling cadence for :meth:`start`; the
        monitor also works purely on-demand (:meth:`sample`).
      watermarks: ascending HBM pressure fractions; crossing one
        upward emits a ``hbm_watermark`` flight event (level
        ``warning`` for the first rung, ``error`` past it) and arms
        the degraded health reason until pressure drops back below.
      stats_fn: test seam — ``stats_fn(device) -> Optional[dict]``
        replaces ``device.memory_stats()`` (fault injection: a forced
        low watermark must shed, tests/test_devmon.py).
    """

    def __init__(self, registry=None, period_s: Optional[float] = None,
                 watermarks: Optional[Tuple[float, ...]] = None,
                 stats_fn: Optional[Callable] = None) -> None:
        if period_s is not None and period_s <= 0:
            raise ValueError("period_s must be positive (None = manual)")
        self._registry = registry
        self.period_s = period_s
        self.watermarks = tuple(sorted(watermarks if watermarks is not None
                                       else _env_watermarks()))
        self._stats_fn = stats_fn
        self._owners: Dict[str, Callable] = {}
        self._lock = threading.Lock()
        self._gauges: Dict[str, object] = {}
        self._pressure = 0.0            # last sampled max fraction
        self._peak_bytes = 0            # max peak_bytes_in_use seen
        self._shards_fn: Optional[Callable] = None
        self._last_shard_bytes: Optional[Tuple[int, ...]] = None
        self._armed_mark: Optional[float] = None  # highest rung crossed
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._samples = 0

    # --- owners -------------------------------------------------------
    def register_owner(self, name: str, arrays_fn: Callable) -> None:
        """Attribute device buffers to a named owner. ``arrays_fn()``
        returns the owner's live arrays (anything with ``.nbytes``;
        None entries are skipped). Re-registering a name replaces its
        callable — the index owner survives a hot swap that way."""
        with self._lock:
            self._owners[name] = arrays_fn

    def unregister_owner(self, name: str) -> None:
        with self._lock:
            self._owners.pop(name, None)

    def register_shards(self, shards_fn: Optional[Callable]) -> None:
        """Attach a mesh-shard balance feed: ``shards_fn()`` returns
        the :meth:`~tfidf_tpu.parallel.serving.MeshShardedRetriever.
        shard_stats` dict (``n_shards`` / ``shard_bytes`` /
        ``imbalance``) or None while the index is not sharded. Every
        :meth:`sample` publishes the ``shard_bytes_d*`` gauge family
        plus ``shard_imbalance_milli``, and logs an edge-triggered
        ``shard_balance`` flight event when the per-shard bytes
        change (index installs are the only thing that moves them) —
        the record ``tools/doctor.py``'s shards section and
        ``--shard-imbalance`` budget read."""
        with self._lock:
            self._shards_fn = shards_fn
            self._last_shard_bytes = None

    # --- sampling -----------------------------------------------------
    def _device_stats(self, dev):
        if self._stats_fn is not None:
            return self._stats_fn(dev)
        try:
            return dev.memory_stats()
        except Exception:   # backends without the API at all
            return None

    def sample(self) -> dict:
        """One monitor pass: read every device's memory stats, publish
        gauges for the keys present, update pressure + watermark state.
        Returns the snapshot dict (the ``devmon`` op payload). Never
        raises on missing/partial stats — that IS the CPU path.

        Serialized under ``self._lock``: the ``devmon`` op calls this
        from a protocol thread while the background monitor samples on
        its own cadence, and the peak/gauge/watermark updates are
        read-modify-writes."""
        import jax
        with self._lock:
            shards_fn = self._shards_fn
        shard_stats = None
        if shards_fn is not None:
            try:
                shard_stats = shards_fn()
            except Exception:   # a mid-swap index must not kill sampling
                shard_stats = None
        with self._lock:
            devices = []
            pressure = 0.0
            for i, dev in enumerate(jax.devices()):
                stats = self._device_stats(dev) or {}
                in_use = stats.get("bytes_in_use")
                peak = stats.get("peak_bytes_in_use")
                limit = stats.get("bytes_limit")
                rec = {"device": i, "kind": dev.device_kind,
                       "platform": dev.platform}
                if in_use is not None:
                    rec["bytes_in_use"] = int(in_use)
                    self._gauge(f"hbm_bytes_in_use_d{i}",
                                "live HBM bytes in use").set(int(in_use))
                if peak is not None:
                    rec["peak_bytes_in_use"] = int(peak)
                    self._peak_bytes = max(self._peak_bytes, int(peak))
                    self._gauge(f"hbm_peak_bytes_d{i}",
                                "allocator peak HBM bytes").set(int(peak))
                if limit is not None:
                    rec["bytes_limit"] = int(limit)
                    self._gauge(f"hbm_bytes_limit_d{i}",
                                "HBM capacity the allocator sees"
                                ).set(int(limit))
                if in_use is not None and limit:
                    frac = in_use / limit
                    rec["pressure"] = round(frac, 4)
                    pressure = max(pressure, frac)
                devices.append(rec)
            self._pressure = pressure
            self._samples += 1
            self._watermark_check(pressure)
            snap = {"devices": devices,
                    "memory_pressure": round(pressure, 4),
                    "peak_bytes": self._peak_bytes,
                    "samples": self._samples}
        if shard_stats:
            self._publish_shards(shard_stats)
            snap["shards"] = shard_stats
        return snap

    def _publish_shards(self, stats: dict) -> None:
        """Gauge + flight publication of one shard-balance reading
        (takes the lock itself — the gauge map and the edge state are
        the same cross-thread RMWs :meth:`sample` serializes). Per-
        shard bytes move only when an index installs, so the
        ``shard_balance`` event is edge-triggered on the bytes vector
        — sparse by construction."""
        per = stats.get("shard_bytes") or []
        imbalance = stats.get("imbalance", 1.0)
        with self._lock:
            for i, b in enumerate(per):
                self._gauge(f"shard_bytes_d{i}",
                            "index bytes resident on this docs-shard"
                            ).set(int(b))
            self._gauge("shard_imbalance_milli",
                        "max/mean per-shard index bytes, in 1/1000"
                        ).set(int(round(imbalance * 1000)))
            key = tuple(int(b) for b in per)
            changed = key != self._last_shard_bytes
            self._last_shard_bytes = key
        if changed:
            obs_log.log_event(
                "info", "shard_balance",
                msg=f"index sharded {len(per)} ways: "
                    f"{[round(b / 1e6, 2) for b in per]} MB/shard, "
                    f"imbalance {imbalance:.3f}",
                n_shards=stats.get("n_shards", len(per)),
                shard_bytes=list(key),
                imbalance=imbalance)

    def _gauge(self, name: str, help: str):
        g = self._gauges.get(name)
        if g is None:
            if self._registry is None:
                class _Null:
                    def set(self, v):
                        pass
                g = _Null()
            else:
                g = self._registry.gauge(name, help)
            self._gauges[name] = g
        return g

    def _watermark_check(self, pressure: float) -> None:
        """Edge-triggered watermark events: crossing a rung upward
        logs once (warning at the first rung, error past it) and
        remembers the rung; dropping below the lowest crossed rung
        logs the recovery and disarms."""
        crossed = [m for m in self.watermarks if pressure >= m]
        highest = crossed[-1] if crossed else None
        if highest is not None and highest != self._armed_mark:
            level = ("warning" if highest == self.watermarks[0]
                     else "error")
            obs_log.log_event(
                level, "hbm_watermark",
                msg=f"HBM pressure {pressure:.2f} crossed watermark "
                    f"{highest:.2f}",
                pressure=round(pressure, 4), watermark=highest)
            self._armed_mark = highest
        elif highest is None and self._armed_mark is not None:
            obs_log.log_event(
                "info", "hbm_watermark_clear",
                msg=f"HBM pressure {pressure:.2f} back below "
                    f"{self.watermarks[0]:.2f}",
                pressure=round(pressure, 4))
            self._armed_mark = None

    # --- census -------------------------------------------------------
    def census(self, top_shapes: int = 8) -> dict:
        """Live-buffer census: every ``jax.live_arrays()`` buffer
        grouped by (shape, dtype), with registered owners' bytes
        attributed by buffer identity and the remainder reported as
        ``other``. The "where did the HBM go" answer the doctor
        prints. Owner callables that raise are skipped (a swapped-out
        retriever must not break the monitor)."""
        import jax
        live = jax.live_arrays()
        total = 0
        by_shape: Dict[Tuple, int] = {}
        ids = {}
        for arr in live:
            try:
                nb = int(arr.nbytes)
                key = (str(arr.dtype), tuple(arr.shape))
            except Exception:
                continue
            total += nb
            by_shape[key] = by_shape.get(key, 0) + nb
            ids[id(arr)] = nb
        with self._lock:
            owners_fns = list(self._owners.items())
        owners = {}
        claimed = 0
        for name, fn in owners_fns:
            bytes_ = n = 0
            try:
                arrays = fn() or ()
            except Exception:
                continue
            for arr in arrays:
                if arr is None:
                    continue
                try:
                    nb = int(arr.nbytes)
                except Exception:
                    continue
                n += 1
                bytes_ += nb
                if id(arr) in ids:
                    claimed += ids.pop(id(arr))
            owners[name] = {"bytes": bytes_, "arrays": n}
        owners["other"] = {"bytes": max(0, total - claimed),
                           "arrays": len(ids)}
        shapes = sorted(by_shape.items(), key=lambda kv: -kv[1])
        return {
            "total_bytes": total,
            "buffers": len(live),
            "owners": owners,
            "top_shapes": [
                {"dtype": d, "shape": list(s), "bytes": b}
                for (d, s), b in shapes[:top_shapes]],
        }

    def log_census(self) -> dict:
        """Take a census and record it as an ``hbm_census`` flight
        event — how a census reaches the doctor through a dump."""
        c = self.census()
        obs_log.log_event(
            "info", "hbm_census",
            msg=f"hbm census: {c['total_bytes'] / 1e6:.1f} MB across "
                f"{c['buffers']} buffers",
            total_bytes=c["total_bytes"], buffers=c["buffers"],
            owners=c["owners"], top_shapes=c["top_shapes"])
        return c

    # --- signals ------------------------------------------------------
    @property
    def memory_pressure(self) -> float:
        """Last sampled max in-use/limit fraction across devices
        (0.0 when the backend reports no memory stats)."""
        return self._pressure

    @property
    def peak_bytes(self) -> int:
        """Highest allocator peak seen across all samples/devices."""
        return self._peak_bytes

    def health_signal(self) -> Tuple[float, Optional[str]]:
        """The :meth:`HealthMonitor.add_signal` hook: (pressure,
        degraded-reason-or-None). Reason arms past the FIRST watermark
        — shedding early is the point — and clears as soon as a sample
        sees pressure back below it."""
        p = self._pressure
        if self.watermarks and p >= self.watermarks[0]:
            return p, (f"memory pressure {p:.2f} >= watermark "
                       f"{self.watermarks[0]:.2f}")
        return p, None

    # --- background sampling ------------------------------------------
    def start(self) -> "DeviceMonitor":
        """Start the sampling thread (idempotent; needs ``period_s``)."""
        if self.period_s is None:
            raise ValueError("DeviceMonitor(period_s=...) required "
                             "for background sampling")
        if self._thread is not None and self._thread.is_alive():
            return self
        self._stop.clear()

        def run():
            while not self._stop.wait(self.period_s):
                try:
                    self.sample()
                except Exception as e:  # monitor must never kill serve
                    obs_log.log_event("warning", "devmon_error",
                                      msg=f"devmon sample failed: {e!r}")

        self._thread = threading.Thread(
            target=run, daemon=True, name="tfidf-devmon")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=5)
            self._thread = None


class CompileWatch:
    """Counts + fingerprints XLA compilations; flags recompiles after
    warm-up.

    Two feeds:

    * the process-global ``jax.monitoring`` listener (installed once,
      lazily, by :func:`set_watch`) reports every real backend compile
      — count and wall seconds, no identity;
    * :func:`note_compile` calls from product call sites that KNOW the
      program identity (``TfidfRetriever.search`` stamps
      ``program="search_bcoo"`` with the query bucket, k and docs) —
      the fingerprints an operator needs to see *which* shape leaked
      into steady state.

    :meth:`mark_warm` draws the line: fingerprinted compiles after it
    are recorded as recompiles (flight event ``xla_recompile``,
    counter ``xla_recompiles_after_warm``) and :meth:`health_signal`
    reports a degraded reason for ``recent_s`` after the last one —
    windowed, so health recovers once the storm passes.
    """

    def __init__(self, registry=None, recent_s: float = 30.0) -> None:
        self.recent_s = recent_s
        self._lock = threading.Lock()
        self._compiles = 0
        self._compile_s = 0.0
        self._warm = False
        self._recompiles: List[dict] = []
        self._last_recompile: Optional[float] = None
        self._c_total = self._c_seconds = self._c_recompiles = None
        if registry is not None:
            self._c_total = registry.counter(
                "xla_compiles_total", "XLA backend compilations")
            self._c_seconds = registry.counter(
                "xla_compile_seconds_total",
                "wall seconds spent in XLA backend compilation")
            self._c_recompiles = registry.counter(
                "xla_recompiles_after_warm",
                "fingerprinted compilations after mark_warm()")

    # --- feeds ---
    def on_backend_compile(self, seconds: float) -> None:
        """The jax.monitoring feed: one real backend compile."""
        with self._lock:
            self._compiles += 1
            self._compile_s += seconds
        if self._c_total is not None:
            self._c_total.inc()
            self._c_seconds.inc(seconds)

    def note(self, program: str, **fingerprint) -> None:
        """A product call site reports a program it just compiled
        (shapes/dtype/k/wire/finish — whatever identifies it). Before
        warm-up this is a debug breadcrumb; after, it is a recompile:
        flight warning + counter + the degraded-reason window."""
        fp = {"program": program, **fingerprint}
        with self._lock:
            warm = self._warm
            if warm:
                self._recompiles.append(fp)
                self._last_recompile = time.monotonic()
        if warm:
            if self._c_recompiles is not None:
                self._c_recompiles.inc()
            obs_log.log_event(
                "warning", "xla_recompile",
                msg=f"XLA recompile after warm-up: {fp}", **fp)
        else:
            obs_log.log_event("debug", "xla_compile", **fp)

    # --- state ---
    def mark_warm(self) -> None:
        """Declare warm-up complete: every fingerprinted compile from
        here on is a steady-state recompile — the thing the serve loop
        promised would never happen."""
        with self._lock:
            self._warm = True
        obs_log.log_event("info", "compile_warm",
                          msg=f"compile warm-up complete "
                              f"({self._compiles} compiles, "
                              f"{self._compile_s:.2f}s)",
                          compiles=self._compiles,
                          compile_s=round(self._compile_s, 3))

    @property
    def warm(self) -> bool:
        return self._warm

    @property
    def compiles(self) -> int:
        return self._compiles

    @property
    def recompile_count(self) -> int:
        """Recompiles noted since :meth:`mark_warm` (len is atomic
        under the GIL — cheap enough for the serve loop's per-batch
        delta check)."""
        return len(self._recompiles)

    @property
    def compile_seconds(self) -> float:
        return self._compile_s

    def recompiles_after_warm(self) -> List[dict]:
        with self._lock:
            return list(self._recompiles)

    def health_signal(self) -> Tuple[int, Optional[str]]:
        """(recompile count after warm, degraded-reason-or-None). The
        reason stays armed for ``recent_s`` after the newest recompile,
        then decays — a single stray shape degrades the server briefly
        instead of forever."""
        with self._lock:
            n = len(self._recompiles)
            last = self._last_recompile
        if last is not None and time.monotonic() - last < self.recent_s:
            return n, (f"{n} XLA recompile(s) after warm-up "
                       f"(last {time.monotonic() - last:.1f}s ago)")
        return n, None


# --- module-level seams ----------------------------------------------
#
# One global monitor + one global compile watch, tracer-style: product
# call sites (retrieval.search, the serve batcher) report through
# these so the disabled path is a global load + None test, and the
# jax.monitoring listener — which can never be unregistered piecemeal
# — is installed once and dispatches to whatever watch is current.

_monitor: Optional[DeviceMonitor] = None
_watch: Optional[CompileWatch] = None
_listener_installed = False
_install_lock = threading.Lock()


def _ensure_listener() -> None:
    global _listener_installed
    with _install_lock:
        if _listener_installed:
            return
        try:
            import jax.monitoring as jm

            def _on_duration(key: str, seconds: float, **kw) -> None:
                w = _watch
                if w is not None and key.endswith(
                        "backend_compile_duration"):
                    w.on_backend_compile(seconds)

            jm.register_event_duration_secs_listener(_on_duration)
            _listener_installed = True
        except Exception:   # ancient jax: counts stay note()-only
            _listener_installed = True


def set_watch(watch: Optional[CompileWatch]) -> None:
    """Install (or with None disarm) the process compile watch. The
    jax.monitoring listener is registered on first install and stays
    registered (jax offers no piecemeal removal); it forwards to the
    CURRENT watch only."""
    global _watch
    if watch is not None:
        _ensure_listener()
    _watch = watch


def get_watch() -> Optional[CompileWatch]:
    return _watch


def note_compile(program: str, **fingerprint) -> None:
    """Product call-site hook: no-op unless a watch is installed."""
    w = _watch
    if w is not None:
        w.note(program, **fingerprint)


def set_monitor(monitor: Optional[DeviceMonitor]) -> None:
    global _monitor
    _monitor = monitor


def get_monitor() -> Optional[DeviceMonitor]:
    return _monitor


def configure(period_ms: Optional[float] = None,
              registry=None) -> Optional[DeviceMonitor]:
    """Arm the global device monitor the way ``tracer.configure`` arms
    tracing: explicit ``period_ms`` wins, else ``TFIDF_TPU_DEVMON``
    (any non-empty value, with the cadence from
    ``TFIDF_TPU_DEVMON_PERIOD_MS``, default 500 ms); unset leaves
    device monitoring OFF and returns None. Idempotent — an armed
    monitor is kept."""
    global _monitor
    if _monitor is not None:
        return _monitor
    if period_ms is None:
        if not os.environ.get("TFIDF_TPU_DEVMON"):
            return None
        period_ms = float(os.environ.get("TFIDF_TPU_DEVMON_PERIOD_MS",
                                         "500"))
    if period_ms <= 0:
        return None
    _monitor = DeviceMonitor(registry=registry,
                             period_s=period_ms / 1e3).start()
    return _monitor
