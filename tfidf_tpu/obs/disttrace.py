"""Fleet-wide trace identity: one causal id from front socket to device.

Round 16's rids (:mod:`tfidf_tpu.obs.reqtrace`) made a request's
lifecycle joinable *within* one process; the replicated tier (round
20), multihost ingest (round 14) and the background compactor all run
as separate OS processes with separate monotonic clocks, so a slow
front-routed query still dissolves into N disjoint per-process
timelines at the process boundary. This module is the Dapper move
(Sigelman et al., 2010) applied to that boundary — three pieces:

* **Trace context** — :func:`mint` creates a compact fleet-global
  identity at the front's admission point: a trace id
  (``t<16hex>``, 64 random bits — the ``t`` prefix keeps it
  distinguishable from a ``r<pid16><t16>-<seq>`` rid, so
  ``doctor --request`` can take either) plus the parent span id of
  the front's ``route`` span. :func:`to_wire` / :func:`from_wire`
  serialize it as the ``"trace"`` field of data-plane JSONL requests
  and control-plane ctrl ops. ``from_wire`` is deliberately paranoid:
  ANY malformed/missing/alien value degrades to ``None`` (the request
  then runs under its local rid exactly as before) — propagation must
  never be able to fail a request.
* **Kill switch** — ``TFIDF_TPU_DISTTRACE=off`` (default ON,
  mirroring reqtrace's ``TFIDF_TPU_REQTRACE``): :func:`enabled` is
  one cached env read, :func:`configure` the runtime/A-B toggle
  (``ServeConfig.disttrace`` / ``--disttrace``).
* **Clock alignment** — :class:`ClockOffsetEstimator` turns N
  request/reply round trips over the existing ctrl plane into a
  peer-clock offset: each sample is the RTT-midpoint estimate
  ``t_peer - (t_send + t_recv)/2`` and the estimator keeps the sample
  with the smallest RTT (asymmetric network delay biases the midpoint
  by at most ±RTT/2, so min-RTT is the least-biased sample — NTP's
  popcorn filter, one line). The offset and its ``±rtt/2``
  uncertainty are recorded in each process's trace-export *metadata*
  (``tools/trace_merge.py`` applies them at merge time); captured
  timestamps are NEVER rewritten, so a bad estimate is re-appliable,
  not baked in. :meth:`reset` discards the state on replica restart —
  a new process is a new clock.

Stdlib-only; importable with no jax at all (the doctor/trace_check
discipline).
"""

from __future__ import annotations

import os
from typing import Any, Dict, Optional

__all__ = ["TraceContext", "ClockOffsetEstimator", "enabled",
           "configure", "mint", "child", "to_wire", "from_wire",
           "is_trace_id"]

_enabled: Optional[bool] = None  # None = derive from env on next call


def enabled() -> bool:
    """Fleet-trace propagation on? Default ON; ``TFIDF_TPU_DISTTRACE``
    set to ``off``/``0``/``false``/``no`` disables. The env read is
    cached — :func:`configure` is the runtime toggle."""
    e = _enabled
    if e is None:
        raw = os.environ.get("TFIDF_TPU_DISTTRACE", "on").lower()
        e = raw not in ("off", "0", "false", "no", "")
        globals()["_enabled"] = e
    return e


def configure(enabled_: Optional[bool]) -> Optional[bool]:
    """Force fleet tracing on/off for this process (the serve_bench
    A/B seam); ``None`` resets to the env-derived default."""
    global _enabled
    _enabled = None if enabled_ is None else bool(enabled_)
    return _enabled


class TraceContext:
    """One fleet-global trace identity: the trace id every hop stamps
    on its spans, plus the span id of the hop that forwarded it (the
    causal parent — the front's ``route`` span for data-plane hops,
    the ``epoch_swap`` span for control-plane ops)."""

    __slots__ = ("trace", "parent")

    def __init__(self, trace: str, parent: str) -> None:
        self.trace = trace
        self.parent = parent

    def __repr__(self) -> str:  # forensics-friendly
        return f"TraceContext({self.trace}, parent={self.parent})"


def is_trace_id(s: Any) -> bool:
    """``t<16hex>``? The shape check ``doctor --request`` uses to tell
    a front-minted trace id from a replica-local rid."""
    if not isinstance(s, str) or len(s) != 17 or s[0] != "t":
        return False
    try:
        int(s[1:], 16)
    except ValueError:
        return False
    return True


def mint() -> Optional[TraceContext]:
    """Mint a fresh trace context at the admission point; None when
    fleet tracing is off (every consumer takes ``ctx is None`` as the
    disabled path). 64 random bits per id: collision across a tier's
    lifetime is negligible and minting stays allocation-cheap."""
    if not enabled():
        return None
    return TraceContext("t" + os.urandom(8).hex(),
                        "s" + os.urandom(4).hex())


def child(ctx: Optional[TraceContext],
          parent: str) -> Optional[TraceContext]:
    """The same trace id under a new causal parent — what a hop passes
    to the NEXT hop once it has opened its own span."""
    if ctx is None:
        return None
    return TraceContext(ctx.trace, parent)


def to_wire(ctx: Optional[TraceContext]) -> Optional[Dict[str, str]]:
    """The compact JSONL form of a context (the ``"trace"`` field on
    data-plane requests and ctrl ops); None when there is nothing to
    propagate."""
    if ctx is None:
        return None
    return {"id": ctx.trace, "parent": ctx.parent}


def from_wire(obj: Any) -> Optional[TraceContext]:
    """Parse a ``"trace"`` wire field back into a context.

    Degrades, never raises: a missing field, a non-dict, a non-string
    or malformed id — anything short of a well-formed context —
    returns ``None`` and the request proceeds under its local rid
    (pinned by tests/test_disttrace.py). A propagation bug must never
    be able to fail live traffic."""
    if not enabled():
        return None
    if not isinstance(obj, dict):
        return None
    trace = obj.get("id")
    if not is_trace_id(trace):
        return None
    parent = obj.get("parent")
    if not isinstance(parent, str) or not (1 <= len(parent) <= 64):
        parent = ""
    return TraceContext(trace, parent)


class ClockOffsetEstimator:
    """Peer-clock offset from request/reply round trips (min-RTT
    filtered RTT-midpoint — the NTP estimate).

    One estimator per (local, peer) clock pair, fed by
    :meth:`add_sample` with three ``perf_counter_ns`` readings: the
    local send instant, the peer's clock read while holding the
    request, and the local receive instant. Each sample estimates

        ``offset = t_peer - (t_send + t_recv) / 2``

    i.e. *peer minus local* at the RTT midpoint; the error is bounded
    by ±RTT/2 (worst-case asymmetric delay), so the estimator keeps
    the sample with the smallest RTT seen and reports that bound as
    :attr:`uncertainty_ns`. Offsets are *recorded in export metadata*
    and applied by ``tools/trace_merge.py`` — capture-side timestamps
    are never rewritten.
    """

    __slots__ = ("offset_ns", "uncertainty_ns", "rtt_ns", "n_samples")

    def __init__(self) -> None:
        self.reset()

    def reset(self) -> None:
        """Discard all state — MUST be called when the peer process
        restarts (a new process is a new clock epoch; stale offsets
        would silently misalign every span it records)."""
        self.offset_ns: Optional[int] = None
        self.uncertainty_ns: Optional[int] = None
        self.rtt_ns: Optional[int] = None
        self.n_samples = 0

    def add_sample(self, t_send_ns: int, t_peer_ns: int,
                   t_recv_ns: int) -> None:
        """Fold one round trip in; keeps the minimum-RTT sample."""
        rtt = int(t_recv_ns) - int(t_send_ns)
        if rtt < 0:
            return  # a non-causal reading is instrumentation noise
        self.n_samples += 1
        if self.rtt_ns is not None and rtt >= self.rtt_ns:
            return
        self.rtt_ns = rtt
        self.offset_ns = int(t_peer_ns) - (int(t_send_ns)
                                           + int(t_recv_ns)) // 2
        self.uncertainty_ns = (rtt + 1) // 2

    def as_meta(self) -> Dict[str, Any]:
        """The export-metadata record ``trace_merge`` consumes."""
        return {"offset_ns": self.offset_ns,
                "uncertainty_ns": self.uncertainty_ns,
                "rtt_ns": self.rtt_ns,
                "samples": self.n_samples}
