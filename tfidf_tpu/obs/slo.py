"""SLO burn gauges: windowed latency-objective compliance + burn rates.

A latency histogram says what the distribution IS; an SLO tracker says
whether the service is KEEPING ITS PROMISE and how fast it is spending
the error budget — the multiwindow burn-rate formulation of the SRE
workbook, scaled down to one process. The objective is
"``target`` fraction of requests complete within ``objective_ms``"
(``TFIDF_TPU_SLO_MS`` / ``TFIDF_TPU_SLO_TARGET``, CLI ``--slo-ms`` /
``--slo-target``); the error budget is ``1 - target``, and the burn
rate over a window is::

    burn = (bad requests / total requests in window) / (1 - target)

``burn == 1`` spends the budget exactly at the sustainable rate;
``burn >> 1`` over the FAST window means the objective is being blown
right now. The tracker keeps two windows (fast ~1 min, slow ~10 min
by default) over O(window) per-second buckets, publishes three gauges
(``serve_slo_fast_burn_milli`` / ``serve_slo_slow_burn_milli`` /
``serve_slo_compliance_milli``), and exposes the
:meth:`SloTracker.health_signal` hook: a fast burn past
``fast_burn_degraded`` (with enough samples to mean anything) is a
DEGRADED reason — the same admission-feedback path memory pressure
and the circuit breaker already drive, so a server blowing its latency
objective sheds at the gate instead of queueing more doomed work.

Stdlib-only, thread-safe; the clock is injectable for tests.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Callable, Optional, Tuple

__all__ = ["SloTracker"]


class SloTracker:
    """Windowed latency-SLO compliance + fast/slow burn rates.

    Args:
      objective_ms: the latency objective (a request slower than this
        is "bad").
      target: fraction of requests that must meet the objective
        (0.99 = a 1% error budget).
      fast_window_s / slow_window_s: the two burn windows (fast = the
        paging signal, slow = the trend).
      fast_burn_degraded: fast-window burn rate at/past which
        :meth:`health_signal` reports a degraded reason.
      min_count: fewest fast-window requests before the signal may
        degrade — one slow request in an idle minute is not an
        incident.
      registry: optional :class:`~tfidf_tpu.obs.registry.
        MetricsRegistry` for the three gauges.
      clock: monotonic-seconds source (test seam).
    """

    def __init__(self, objective_ms: float, target: float = 0.99,
                 fast_window_s: float = 60.0,
                 slow_window_s: float = 600.0,
                 fast_burn_degraded: float = 2.0,
                 min_count: int = 10,
                 registry=None,
                 clock: Callable[[], float] = time.monotonic) -> None:
        if objective_ms <= 0:
            raise ValueError("objective_ms must be positive")
        if not 0.0 < target < 1.0:
            raise ValueError("target must be in (0, 1)")
        if not 0 < fast_window_s <= slow_window_s:
            raise ValueError("need 0 < fast_window_s <= slow_window_s")
        if fast_burn_degraded <= 0:
            raise ValueError("fast_burn_degraded must be positive")
        self.objective_ms = objective_ms
        self.target = target
        self.fast_window_s = fast_window_s
        self.slow_window_s = slow_window_s
        self.fast_burn_degraded = fast_burn_degraded
        self.min_count = min_count
        self._clock = clock
        self._lock = threading.Lock()
        # Per-second buckets [sec, good, bad], trimmed to slow_window.
        self._buckets: deque = deque()
        self._good_total = 0
        self._bad_total = 0
        self._g_fast = self._g_slow = self._g_comp = None
        if registry is not None:
            self._g_fast = registry.gauge(
                "serve_slo_fast_burn_milli",
                "SLO error-budget burn rate over the fast window, "
                "in 1/1000 (1000 = sustainable)")
            self._g_slow = registry.gauge(
                "serve_slo_slow_burn_milli",
                "SLO error-budget burn rate over the slow window, "
                "in 1/1000")
            self._g_comp = registry.gauge(
                "serve_slo_compliance_milli",
                "fraction of slow-window requests inside the latency "
                "objective, in 1/1000")

    # --- recording ---
    def record(self, latency_s: float) -> bool:
        """Fold one completed request in; returns True when it met the
        objective."""
        ok = latency_s * 1e3 <= self.objective_ms
        sec = int(self._clock())
        with self._lock:
            if self._buckets and self._buckets[-1][0] == sec:
                b = self._buckets[-1]
            else:
                b = [sec, 0, 0]
                self._buckets.append(b)
            if ok:
                b[1] += 1
                self._good_total += 1
            else:
                b[2] += 1
                self._bad_total += 1
            self._trim(sec)
        return ok

    def _trim(self, now_sec: int) -> None:
        floor = now_sec - self.slow_window_s
        while self._buckets and self._buckets[0][0] < floor:
            self._buckets.popleft()

    # --- reading ---
    def _window(self, window_s: float,
                now: Optional[float] = None) -> Tuple[int, int]:
        """(good, bad) over the trailing window."""
        now_sec = int(self._clock() if now is None else now)
        floor = now_sec - window_s
        good = bad = 0
        with self._lock:
            self._trim(now_sec)
            for sec, g, b in self._buckets:
                if sec >= floor:
                    good += g
                    bad += b
        return good, bad

    def burn_rate(self, window_s: float) -> float:
        """Error-budget burn multiple over the window (0.0 when the
        window saw no traffic — an idle service burns nothing)."""
        good, bad = self._window(window_s)
        total = good + bad
        if not total:
            return 0.0
        return (bad / total) / (1.0 - self.target)

    def compliance(self, window_s: Optional[float] = None) -> float:
        """Fraction of windowed requests inside the objective (1.0
        when idle — no traffic is no violation)."""
        good, bad = self._window(window_s or self.slow_window_s)
        total = good + bad
        return good / total if total else 1.0

    def snapshot(self) -> dict:
        """The ``metrics`` op's ``slo`` object — the "SLO snapshot"
        the serve CLI docstring promises (tests pin the keys)."""
        good, bad = self._window(self.slow_window_s)
        fast = self.burn_rate(self.fast_window_s)
        slow = self.burn_rate(self.slow_window_s)
        total = good + bad
        comp = good / total if total else 1.0
        self._publish(fast, slow, comp)
        return {
            "configured": True,
            "objective_ms": self.objective_ms,
            "target": self.target,
            "good": good,
            "total": total,
            "compliance": round(comp, 6),
            "fast_burn": round(fast, 4),
            "slow_burn": round(slow, 4),
            "fast_window_s": self.fast_window_s,
            "slow_window_s": self.slow_window_s,
        }

    def _publish(self, fast: float, slow: float, comp: float) -> None:
        if self._g_fast is not None:
            self._g_fast.set(int(fast * 1000))
            self._g_slow.set(int(slow * 1000))
            self._g_comp.set(int(comp * 1000))

    # --- health feedback ---
    def health_signal(self) -> Tuple[object, Optional[str]]:
        """:meth:`~tfidf_tpu.obs.health.HealthMonitor.add_signal`
        hook: (fast burn, degraded-reason-or-None). Degrades only when
        the fast window carries at least ``min_count`` requests AND
        burns the budget at/past ``fast_burn_degraded`` — and
        recovers by itself once the fast window rolls clean."""
        good, bad = self._window(self.fast_window_s)
        total = good + bad
        fast = ((bad / total) / (1.0 - self.target)) if total else 0.0
        self._publish(fast, self.burn_rate(self.slow_window_s),
                      self.compliance())
        if total >= self.min_count and fast >= self.fast_burn_degraded:
            return round(fast, 3), (
                f"SLO fast burn {fast:.1f}x budget "
                f"({bad}/{total} over {self.objective_ms:.0f} ms in "
                f"the last {self.fast_window_s:.0f}s, target "
                f"{self.target})")
        return round(fast, 3), None
