"""Unified metrics registry: counters, gauges, histograms, one sink.

Before this module every layer kept private dicts — ``ServeMetrics``
its ``_counts``, the bench its phase dicts — with no common way to
read them out. The registry is the single sink: product code creates
named instruments once (get-or-create, so shared components can't
collide) and every instrument renders two ways:

* :meth:`MetricsRegistry.snapshot` — the JSON-artifact form the bench
  and the CLI ``metrics`` op embed;
* :meth:`MetricsRegistry.render_prom` — Prometheus text exposition
  (``# TYPE``/``# HELP`` + samples, histogram ``le`` buckets included)
  for the ``serve`` CLI's ``metrics_prom`` op and anything scraping a
  long-running server.

Instruments are individually lock-protected (mutators are a few ns;
contention is per-instrument, not global). Histograms reuse
:class:`~tfidf_tpu.utils.timing.LatencyHistogram` — O(1) memory at 2%
resolution, the shape a server that lives for millions of requests
needs — and expose a coarse fixed ``le`` ladder for Prometheus (the
geometric buckets themselves would be hundreds of lines).

Gauges track a resettable PEAK next to the current value — the fix for
the round-9 queue-depth wart where ``ServeMetrics`` could never reset
its high-water mark between snapshots (``snapshot(reset_peaks=True)``).
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional

from tfidf_tpu.utils.timing import LatencyHistogram

__all__ = ["Counter", "Gauge", "Histogram", "MetricsRegistry",
           "DEFAULT_BUCKETS"]

# Prometheus ``le`` ladder for latency histograms: 100 µs to 10 s, the
# band online retrieval actually lives in; +Inf is appended at render.
DEFAULT_BUCKETS = (0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005,
                   0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0,
                   10.0)


def _fmt(v: float) -> str:
    """Prometheus sample value: integers render bare, floats as repr."""
    if isinstance(v, bool):
        return "1" if v else "0"
    if isinstance(v, int) or (isinstance(v, float) and v.is_integer()):
        return str(int(v))
    return repr(float(v))


class Counter:
    """Monotonically-increasing count (floats allowed — occupancy sums
    ride one too)."""

    __slots__ = ("name", "help", "_v", "_lock")

    def __init__(self, name: str, help: str = ""):
        self.name = name
        self.help = help
        self._v = 0
        self._lock = threading.Lock()

    def inc(self, n=1) -> None:
        if n < 0:
            raise ValueError(f"counter {self.name} cannot decrease")
        with self._lock:
            self._v += n

    @property
    def value(self):
        return self._v

    def prom_lines(self) -> List[str]:
        return [f"# HELP {self.name} {self.help}" if self.help else
                f"# HELP {self.name} {self.name}",
                f"# TYPE {self.name} counter",
                f"{self.name} {_fmt(self._v)}"]

    def snapshot_value(self):
        return self._v

    def merge(self, other: "Counter") -> None:
        """Fold another replica's count in (totals add)."""
        self.inc(other.value)

    def state_dict(self) -> dict:
        return {"kind": "counter", "help": self.help,
                "value": self._v}

    def load_state(self, state: dict) -> None:
        with self._lock:
            self._v = state["value"]

    def reset(self) -> None:
        with self._lock:
            self._v = 0


class Gauge:
    """Point-in-time value with a resettable high-water mark."""

    __slots__ = ("name", "help", "_v", "_peak", "_lock")

    def __init__(self, name: str, help: str = ""):
        self.name = name
        self.help = help
        self._v = 0
        self._peak = 0
        self._lock = threading.Lock()

    def set(self, v) -> None:
        with self._lock:
            self._v = v
            if v > self._peak:
                self._peak = v

    def add(self, n) -> None:
        with self._lock:
            self._v += n
            if self._v > self._peak:
                self._peak = self._v

    @property
    def value(self):
        return self._v

    @property
    def peak(self):
        return self._peak

    def reset_peak(self) -> None:
        """Restart the high-water mark AT the current value — the next
        snapshot's peak reflects only what happened since this one."""
        with self._lock:
            self._peak = self._v

    def prom_lines(self) -> List[str]:
        h = self.help or self.name
        return [f"# HELP {self.name} {h}",
                f"# TYPE {self.name} gauge",
                f"{self.name} {_fmt(self._v)}",
                f"# HELP {self.name}_peak peak of {self.name} since "
                f"the last reset",
                f"# TYPE {self.name}_peak gauge",
                f"{self.name}_peak {_fmt(self._peak)}"]

    def snapshot_value(self):
        return {"value": self._v, "peak": self._peak}

    def merge(self, other: "Gauge") -> None:
        """Fold another replica's gauge in: values and peaks SUM (the
        aggregated queue depth across N replicas is the sum of theirs;
        the summed peak is an upper bound on the true peak of the sum —
        the per-replica peaks need not have coincided in time)."""
        with self._lock:
            self._v += other._v
            self._peak += other._peak
            if self._v > self._peak:
                self._peak = self._v

    def state_dict(self) -> dict:
        return {"kind": "gauge", "help": self.help,
                "value": self._v, "peak": self._peak}

    def load_state(self, state: dict) -> None:
        with self._lock:
            self._v = state["value"]
            self._peak = state["peak"]

    def reset(self) -> None:
        with self._lock:
            self._v = 0
            self._peak = 0


class Histogram:
    """Latency distribution: a locked :class:`LatencyHistogram` plus a
    fixed ``le`` ladder for Prometheus exposition."""

    __slots__ = ("name", "help", "_h", "_lock", "buckets", "_geometry")

    def __init__(self, name: str, help: str = "",
                 buckets=DEFAULT_BUCKETS, lo: float = 1e-6,
                 hi: float = 1e3, resolution: float = 0.02,
                 exemplars: bool = False):
        self.name = name
        self.help = help
        self.buckets = tuple(sorted(buckets))
        # Kept so a registry merge can create a compatible twin.
        self._geometry = {"lo": lo, "hi": hi, "resolution": resolution,
                          "exemplars": exemplars}
        self._h = LatencyHistogram(lo=lo, hi=hi, resolution=resolution,
                                   exemplars=exemplars)
        self._lock = threading.Lock()

    def observe(self, seconds: float,
                exemplar: Optional[str] = None) -> None:
        with self._lock:
            self._h.record(seconds, exemplar=exemplar)

    @property
    def count(self) -> int:
        return self._h.count

    def percentile(self, p: float) -> float:
        with self._lock:
            return self._h.percentile(p)

    def prom_lines(self) -> List[str]:
        h = self.help or self.name
        with self._lock:
            cum = self._h.cumulative(list(self.buckets))
            count, total = self._h.count, self._h.sum_seconds
            exemplars = self._h.exemplars()
        # OpenMetrics exemplar exposition: each ``le`` bucket line may
        # carry `# {rid="..."} value` naming the LAST request id that
        # landed under that bound — "p99 got worse" links straight to
        # one replayable trace (tools/doctor.py --request RID). An
        # exemplar attaches to the smallest ladder bound that covers
        # it, the bucket it is an example OF.
        by_le = {}
        for secs, rid in exemplars:
            for le in self.buckets:
                if secs <= le:
                    by_le[le] = (rid, secs)
                    break
            else:
                by_le[float("inf")] = (rid, secs)
        lines = [f"# HELP {self.name} {h}",
                 f"# TYPE {self.name} histogram"]
        for le, c in zip(self.buckets, cum):
            line = f'{self.name}_bucket{{le="{_fmt(le)}"}} {c}'
            if le in by_le:
                rid, secs = by_le[le]
                line += f' # {{rid="{rid}"}} {repr(float(secs))}'
            lines.append(line)
        inf_line = f'{self.name}_bucket{{le="+Inf"}} {count}'
        if float("inf") in by_le:
            rid, secs = by_le[float("inf")]
            inf_line += f' # {{rid="{rid}"}} {repr(float(secs))}'
        lines.append(inf_line)
        lines.append(f"{self.name}_sum {repr(float(total))}")
        lines.append(f"{self.name}_count {count}")
        return lines

    def snapshot_value(self):
        with self._lock:
            out = self._h.as_dict()
            exemplars = self._h.exemplars()
        if exemplars:
            out["exemplars"] = [{"rid": rid, "value": round(secs, 6)}
                                for secs, rid in exemplars]
        return out

    def merge(self, other: "Histogram") -> None:
        """Fold another replica's distribution in
        (:meth:`LatencyHistogram.merge` — identical geometry required,
        bucket counts add, count/sum/min/max exact; exemplars ride
        along per bucket)."""
        with self._lock, other._lock:
            self._h.merge(other._h)

    def state_dict(self) -> dict:
        with self._lock:
            return {"kind": "histogram", "help": self.help,
                    "buckets": list(self.buckets),
                    "state": self._h.state_dict()}

    def load_state(self, state: dict) -> None:
        with self._lock:
            self._h = LatencyHistogram.from_state(state["state"])

    def reset(self) -> None:
        with self._lock:
            self._h.reset()


class MetricsRegistry:
    """Named instruments behind one get-or-create map.

    Creation takes the registry lock; mutation takes only the
    instrument's own. Re-requesting a name returns the SAME instrument
    (shared components converge on one counter) — asking for an
    existing name as a different kind raises.
    """

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._instruments: "Dict[str, object]" = {}

    def _get(self, name: str, kind, factory):
        with self._lock:
            inst = self._instruments.get(name)
            if inst is None:
                inst = factory()
                self._instruments[name] = inst
            elif not isinstance(inst, kind):
                raise ValueError(
                    f"metric {name!r} already registered as "
                    f"{type(inst).__name__}, not {kind.__name__}")
            return inst

    def counter(self, name: str, help: str = "") -> Counter:
        return self._get(name, Counter, lambda: Counter(name, help))

    def gauge(self, name: str, help: str = "") -> Gauge:
        return self._get(name, Gauge, lambda: Gauge(name, help))

    def histogram(self, name: str, help: str = "",
                  buckets=DEFAULT_BUCKETS, **kw) -> Histogram:
        return self._get(name, Histogram,
                         lambda: Histogram(name, help, buckets, **kw))

    def get(self, name: str):
        return self._instruments.get(name)

    def snapshot(self, reset_peaks: bool = False) -> dict:
        """JSON-serializable view of every instrument, keyed by name.
        ``reset_peaks=True`` restarts every gauge's high-water mark at
        its current value AFTER reading — peaks become per-snapshot-
        window, the semantics a scraped dashboard expects."""
        with self._lock:
            items = list(self._instruments.items())
        out = {}
        for name, inst in items:
            out[name] = inst.snapshot_value()
            if reset_peaks and isinstance(inst, Gauge):
                inst.reset_peak()
        return out

    def merge(self, other: "MetricsRegistry") -> "MetricsRegistry":
        """Fold another registry's instruments into this one by name —
        the per-replica aggregation ROADMAP item 5 needs (one front
        merging N worker registries into a fleet view) and what lets
        the perf gate pool multi-run samples. Counters add, gauges sum
        values and peaks, histograms merge bucket-wise; instruments
        missing here are created as same-kind twins first. A name
        registered as a DIFFERENT kind on the two sides raises (same
        contract as get-or-create). Returns ``self``."""
        with other._lock:
            items = list(other._instruments.items())
        for name, inst in items:
            if isinstance(inst, Counter):
                self.counter(name, inst.help).merge(inst)
            elif isinstance(inst, Gauge):
                self.gauge(name, inst.help).merge(inst)
            elif isinstance(inst, Histogram):
                self.histogram(name, inst.help, inst.buckets,
                               **inst._geometry).merge(inst)
        return self

    def export_state(self) -> dict:
        """Wire-format state of every instrument, keyed by name — the
        ``obs_export`` bundle's ``registry`` object. Unlike
        :meth:`snapshot` (lossy percentiles), this carries full
        histogram bucket state + exemplars, so a receiver can
        :meth:`import_state` an equivalent registry and :meth:`merge`
        it — the cross-process federation transport
        ``tools/obs_agg.py`` rides."""
        with self._lock:
            items = list(self._instruments.items())
        return {name: inst.state_dict() for name, inst in items}

    @classmethod
    def import_state(cls, state: dict) -> "MetricsRegistry":
        """Rebuild a registry from :meth:`export_state` output (e.g.
        parsed from another process's ``obs_export`` bundle)."""
        reg = cls()
        for name, s in state.items():
            kind = s.get("kind")
            if kind == "counter":
                reg.counter(name, s.get("help", "")).load_state(s)
            elif kind == "gauge":
                reg.gauge(name, s.get("help", "")).load_state(s)
            elif kind == "histogram":
                inner = s["state"]
                h = reg.histogram(
                    name, s.get("help", ""), s["buckets"],
                    lo=inner["lo"], hi=inner["hi"],
                    resolution=inner["resolution"],
                    exemplars="exemplars" in inner)
                h.load_state(s)
            else:
                raise ValueError(
                    f"unknown instrument kind {kind!r} for {name!r}")
        return reg

    def render_prom(self) -> str:
        """Prometheus text exposition format 0.0.4 of every
        instrument (ends with a newline, as scrapers expect)."""
        with self._lock:
            items = sorted(self._instruments.items())
        lines: List[str] = []
        for _name, inst in items:
            lines.extend(inst.prom_lines())
        return "\n".join(lines) + "\n"

    def reset(self) -> None:
        with self._lock:
            items = list(self._instruments.values())
        for inst in items:
            inst.reset()
