"""Request identity + per-request forensics: "why was THIS query slow?"

The obs stack through round 15 answers aggregate questions (p99,
overlap, health) but has no request identity: a p99 bucket is a number
with no trace behind it, and the flight recorder's request digests
cannot be joined to the spans that produced them. This module is the
join key and the forensic layer on top of it — the Dapper / "Tail at
Scale" move: tail latency is caused by co-occupants (queue wait, batch
mates, a recompile, HBM pressure), so every request carries a compact
process-unique **request id** (``rid``) from admission to resolution,
and the spans, flight events, digests and JSONL responses all carry
the same key.

Three pieces:

* :func:`next_rid` — compact process-unique ids
  (``r<pid16><t16>-<seq>``: a per-process hex prefix folding the pid
  and boot instant, then a counter — unique across the replica fleet
  ``tools/obs_agg.py`` aggregates, cheap enough for the admission hot
  path). ``TFIDF_TPU_REQTRACE=off`` disables minting entirely (the
  serve_bench A/B lever for the <2% p50 overhead bound); the disabled
  path is one module-global load + truthiness test, tracer-style.
* :class:`RequestContext` — the per-request carrier riding the request
  object through batcher → cache → supervisor → device dispatch →
  drain. Instrumentation marks phase durations at the SAME code points
  that end the request's spans, so the resolved breakdown
  ``{queue_wait, batch_wait, device, drain, cache, total}`` (ms)
  reconciles with the trace within measurement noise (the 5%+5ms pin
  in tests/test_reqtrace.py). Anomalies that struck the request's
  batch (``dispatch_retry`` deltas, ``recompile_in_batch``) are noted
  by the batcher; overlapping ``hbm_watermark`` flight events are
  folded in at resolution.
* :func:`finish` — the slow-query log: a request whose total exceeds
  ``TFIDF_TPU_SLOW_MS`` (``ServeConfig.slow_ms``), or every Nth
  resolved request when ``TFIDF_TPU_SLOW_SAMPLE`` (``slow_sample``)
  tail-samples, emits a ``slow_query`` flight event carrying the
  breakdown, batch id, co-occupant count, epoch and anomalies — the
  record ``tools/doctor.py --request RID`` renders into a causal
  timeline.

Stdlib-only; importable with no jax at all (the doctor/trace_check
discipline).
"""

from __future__ import annotations

import itertools
import os
import threading
import time
from typing import Any, Dict, List, Optional

from tfidf_tpu.obs import log as obs_log

__all__ = ["RequestContext", "enabled", "configure", "next_rid",
           "start", "finish", "PHASES"]

#: Phase keys of a resolved breakdown, in lifecycle order. Values are
#: milliseconds; phases a request never entered report 0.0 (a cache
#: hit has no device phase; an admission shed has only total).
PHASES = ("cache", "queue_wait", "batch_wait", "device", "drain",
          "total")

_seq = itertools.count(1)        # rid counter (GIL-atomic)
_resolved = itertools.count(1)   # tail-sample counter
_prefix_lock = threading.Lock()
_PREFIX: Optional[str] = None
_enabled: Optional[bool] = None  # None = derive from env on next call


def _prefix() -> str:
    """Process-unique rid prefix: 16 pid bits + 16 boot-instant bits,
    hex. Two replicas (or a restart of the same pid slot) mint
    disjoint rid spaces, so federated evidence never aliases."""
    global _PREFIX
    if _PREFIX is None:
        with _prefix_lock:
            if _PREFIX is None:
                _PREFIX = (f"{os.getpid() & 0xffff:04x}"
                           f"{time.time_ns() & 0xffff:04x}")
    return _PREFIX


def next_rid() -> str:
    return f"r{_prefix()}-{next(_seq):x}"


def enabled() -> bool:
    """Request-identity minting on? Default ON; ``TFIDF_TPU_REQTRACE``
    set to ``off``/``0``/``false``/``no`` disables. The env read is
    cached — :func:`configure` is the runtime toggle."""
    e = _enabled
    if e is None:
        raw = os.environ.get("TFIDF_TPU_REQTRACE", "on").lower()
        e = raw not in ("off", "0", "false", "no", "")
        globals()["_enabled"] = e
    return e


def configure(enabled_: Optional[bool]) -> Optional[bool]:
    """Force request tracing on/off for this process (the serve_bench
    A/B seam); ``None`` resets to the env-derived default."""
    global _enabled
    _enabled = None if enabled_ is None else bool(enabled_)
    return _enabled


class RequestContext:
    """Per-request forensic carrier (one per admitted request when
    :func:`enabled`). Written by the submit thread, the batcher thread
    and the resolving callback in lifecycle order — each field has one
    writer at a time, so plain attribute writes are safe under the
    GIL (the same discipline as the tracer's ring)."""

    __slots__ = ("rid", "trace", "n", "k", "t0", "t0_wall", "epoch",
                 "batch", "co_occupants", "phases", "anomalies",
                 "_t_dev_end")

    def __init__(self, rid: str, n: int, k: int,
                 trace: Optional[str] = None) -> None:
        self.rid = rid
        # Fleet-global trace id (round 23): a front-routed request
        # arrives with the front-minted ``t<16hex>`` id and every
        # local span/digest/response carries it next to the rid, so
        # cross-process evidence joins on one key. None = locally
        # submitted (or disttrace off) — rid-only, exactly as before.
        self.trace = trace
        self.n = n
        self.k = k
        self.t0 = time.monotonic()
        self.t0_wall = time.time()
        self.epoch: Optional[int] = None
        self.batch: Optional[int] = None
        self.co_occupants = 0
        self.phases: Dict[str, float] = {}   # phase -> seconds
        self.anomalies: List[dict] = []
        self._t_dev_end: Optional[float] = None

    def mark(self, phase: str, seconds: float) -> None:
        """Fold one measured phase duration in (accumulating — a
        bisected batch may dispatch a request's queries twice)."""
        self.phases[phase] = self.phases.get(phase, 0.0) + seconds

    def mark_device_end(self, t: float) -> None:
        """The instant the request's device call returned — the drain
        phase (slice rows, fill cache, resolve the future) runs from
        here to resolution."""
        self._t_dev_end = t

    def note(self, kind: str, **fields: Any) -> None:
        """Record one anomaly that struck this request's batch."""
        self.anomalies.append({"kind": kind, **fields})

    def breakdown(self) -> Dict[str, float]:
        """The resolved phase breakdown in milliseconds, every
        :data:`PHASES` key present."""
        return {p: round(self.phases.get(p, 0.0) * 1e3, 3)
                for p in PHASES}


def start(n: int, k: int,
          trace: Optional[str] = None) -> Optional[RequestContext]:
    """Mint a request identity at admission; None when request tracing
    is off (every consumer takes ``ctx is None`` as the disabled
    path). ``trace`` adopts a front-minted fleet trace id onto the
    context (:mod:`tfidf_tpu.obs.disttrace`)."""
    if not enabled():
        return None
    return RequestContext(next_rid(), n, k, trace=trace)


def _overlapping_watermarks(ctx: RequestContext) -> List[dict]:
    """``hbm_watermark`` flight events whose timestamp falls inside
    the request's lifetime — the "co-occupant pressure" evidence. Only
    scanned for requests already judged slow/sampled (bounded work)."""
    out: List[dict] = []
    for e in obs_log.get_log().events()[-256:]:
        if e.get("event") == "hbm_watermark" \
                and e.get("t", 0.0) >= ctx.t0_wall - 0.001:
            out.append({"kind": "hbm_watermark",
                        "pressure": e.get("pressure"),
                        "watermark": e.get("watermark")})
    return out


def finish(ctx: Optional[RequestContext], outcome: str,
           slow_ms: Optional[float] = None,
           sample_every: int = 0) -> Optional[str]:
    """Resolve one request's forensics: close the drain/total phases
    and emit a ``slow_query`` flight event when the request is over
    the ``slow_ms`` objective (level ``warning``) or hit the 1-in-N
    tail sample (level ``info``, ``sampled: true``). Returns
    ``"slow"`` / ``"sampled"`` / None — the server counts
    ``serve_slow_queries_total`` off the first."""
    if ctx is None:
        return None
    now = time.monotonic()
    total = now - ctx.t0
    ctx.phases["total"] = total
    if ctx._t_dev_end is not None:
        ctx.mark("drain", now - ctx._t_dev_end)
    total_ms = total * 1e3
    slow = slow_ms is not None and total_ms >= slow_ms
    sampled = (not slow and sample_every > 0
               and next(_resolved) % sample_every == 0)
    if not (slow or sampled):
        return None
    anomalies = list(ctx.anomalies) + _overlapping_watermarks(ctx)
    obs_log.log_event(
        "warning" if slow else "info", "slow_query",
        msg=(f"slow query {ctx.rid}: {total_ms:.1f} ms "
             f"({outcome}, batch {ctx.batch}, "
             f"{ctx.co_occupants} co-occupant queries)"
             if slow else
             f"sampled query {ctx.rid}: {total_ms:.1f} ms ({outcome})"),
        rid=ctx.rid, outcome=outcome, breakdown=ctx.breakdown(),
        batch=ctx.batch, co_occupants=ctx.co_occupants,
        epoch=ctx.epoch, queries=ctx.n, k=ctx.k,
        sampled=sampled, anomalies=anomalies,
        **({"trace": ctx.trace} if ctx.trace else {}))
    return "slow" if slow else "sampled"
