"""Analytic bytes/bandwidth model: the roofline, next to the trace.

Until round 12 the bytes model lived as a private block inside
``tools/roofline.py`` — a one-shot table a human ran by hand, while
the tracer (``obs/tracer.py``) recorded *time* with no notion of how
many bytes each span should have moved. This module is the shared
home: the per-stage HBM traffic model of the resident device program,
the per-chip HBM peak table, and the achieved-GB/s arithmetic that
turns a byte-stamped span into a roofline fraction. Consumers:

* ``obs/tracer.py`` — spans stamped with a ``bytes`` arg get an
  ``gb_s`` computed at export time, so the Perfetto timeline shows
  achieved bandwidth per span directly;
* ``tools/roofline.py`` / ``tools/dispatch_probe.py`` /
  ``tools/df_probe.py`` — the probes report model-vs-measured through
  ONE copy of the model instead of three private ones;
* ``tools/doctor.py`` — the one-shot diagnosis quotes roofline
  fractions per phase from the same arithmetic.

Stdlib-only by design (like the tracer): the doctor and trace_check
must run in a bare CI interpreter with no jax or numpy at all.
"""

from __future__ import annotations

import math
from typing import Dict, Optional

__all__ = [
    "HBM_PEAK_GBS_DEFAULT", "hbm_peak_gbs", "stage_bytes",
    "bytes_model", "achieved_gbps", "span_gbps",
]

# Public per-chip HBM peak bandwidth (GB/s). Keyed by substrings of
# ``jax.Device.device_kind``; first match wins, order matters (the
# more specific names first). The default is the v5e the bench
# hardware exposes — tools that know better pass their own peak.
HBM_PEAK_GBS_DEFAULT = 819.0  # v5e: 819 GB/s HBM2 per chip
_HBM_PEAK_TABLE = (
    ("v5p", 2765.0),
    ("v5 lite", 819.0), ("v5e", 819.0),
    ("v4", 1228.0),
    ("v3", 900.0),
    ("v2", 700.0),
)


def hbm_peak_gbs(device_kind: Optional[str]) -> Optional[float]:
    """HBM peak (GB/s) for a ``device_kind`` string, or None when the
    chip is unknown (CPU backends have no meaningful HBM roofline —
    callers print "n/a" rather than a made-up fraction)."""
    if not device_kind:
        return None
    kind = device_kind.lower()
    for key, peak in _HBM_PEAK_TABLE:
        if key in kind:
            return peak
    if "tpu" in kind:
        return HBM_PEAK_GBS_DEFAULT
    return None


def stage_bytes(docs: int, length: int, topk: int = 16,
                itemsize: int = 4) -> Dict[str, int]:
    """HBM traffic per stage of the resident phase-B program, in bytes.

    The model the round-4 roofline derived and the engine bench
    validated (docs/ENGINES.md): a bitonic row sort reads+writes the
    [D, L] block once per compare-exchange layer (lg·(lg+1)/2 layers),
    the RLE term-count pass makes ~6 full passes (prev/head/cummin/
    counts), the global DF sort is the same bitonic model over the
    flattened D·L slots, and score+topk is ~4 passes plus the [D, K]
    result. All in ``itemsize``-byte elements (int32/float32 = 4).
    """
    n = docs * length
    lg = max(1, math.ceil(math.log2(max(length, 2))))
    lgn = max(1, math.ceil(math.log2(max(n, 2))))
    return {
        "row_sort": n * itemsize * 2 * (lg * (lg + 1) // 2),
        "rle": n * itemsize * 6,
        "df_global_sort": n * itemsize * 2 * (lgn * (lgn + 1) // 2),
        "score_topk": n * itemsize * 4 + docs * topk * 2 * itemsize,
    }


def bytes_model(docs: int, length: int, topk: int = 16,
                hbm_gbs: Optional[float] = HBM_PEAK_GBS_DEFAULT
                ) -> Dict[str, float]:
    """The roofline table: per-stage GB, total, and the HBM-bound
    floor in seconds at ``hbm_gbs`` (omitted when the peak is None —
    no roofline without a chip)."""
    stages = stage_bytes(docs, length, topk)
    model = {f"{name}_gb": b / 1e9 for name, b in stages.items()}
    total_gb = sum(model.values())
    model["total_gb"] = total_gb
    if hbm_gbs:
        model["hbm_bound_s"] = total_gb / hbm_gbs
    return model


def achieved_gbps(nbytes: float, seconds: float) -> Optional[float]:
    """Realized bandwidth, or None when the interval is degenerate
    (zero/negative duration must not export an Infinity that breaks a
    JSON reader)."""
    if not seconds or seconds <= 0 or nbytes < 0:
        return None
    return nbytes / seconds / 1e9


def span_gbps(event: dict) -> Optional[float]:
    """Achieved GB/s of one Chrome trace-event dict: a complete span
    whose ``args.bytes`` says what it moved (``ts``/``dur`` are in
    microseconds). None when the span carries no byte stamp."""
    args = event.get("args") or {}
    b = args.get("bytes")
    dur_us = event.get("dur")
    if not isinstance(b, (int, float)) \
            or not isinstance(dur_us, (int, float)):
        return None
    return achieved_gbps(float(b), dur_us / 1e6)
