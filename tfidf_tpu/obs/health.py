"""Health surface: the watchdog that turns telemetry into a verdict.

Round 10 made the serving layer observable (spans, counters,
histograms); nothing CONSUMED them — the server could not say whether
it was healthy, and an operator (or an admission gate) had to eyeball
raw gauges. This module closes the loop the way Google's serving
fleets do (Monarch-style derived signals, Autopilot-style feedback):
a :class:`HealthMonitor` folds worker liveness heartbeats and windowed
SLO rates into one typed status —

* ``ok`` — all signals inside thresholds;
* ``degraded`` — queue-depth saturation, shed rate, or deadline-miss
  rate past its threshold: the server is shedding or about to; the
  admission bound SHRINKS (``admission_bound``) so the backlog drains
  instead of compounding;
* ``unhealthy`` — a worker with pending work has not heartbeat within
  ``stall_after_s``: the pipeline is wedged, readiness goes false.

Heartbeats come from the worker threads themselves — the serve
batcher beats through an explicit callback, the ingest
``_PackAhead``/``_DrainAhead`` workers beat through the module-level
:func:`beat` hook (a no-op ``is None`` test unless a monitor is
installed, same discipline as the tracer's disabled path). Rates come
from successive :class:`~tfidf_tpu.serve.metrics.ServeMetrics`
snapshots, so the monitor needs no new counters of its own.

Exposure: ``healthz``/``readyz`` ops on the serve CLI (JSONL + TCP),
registry gauges (``serve_health_state`` 0/1/2,
``serve_admission_bound``, per-signal check gauges) for Prometheus,
and an optional background thread (``period_s``) that re-evaluates on
a fixed cadence — the "within one watchdog period" detection bound
tests/test_health.py pins.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Callable, Dict, List, Optional

from tfidf_tpu.obs import log as obs_log

__all__ = ["HealthThresholds", "HealthStatus", "HealthMonitor",
           "beat", "set_monitor", "get_monitor",
           "OK", "DEGRADED", "UNHEALTHY"]

OK, DEGRADED, UNHEALTHY = "ok", "degraded", "unhealthy"
_STATE_NO = {OK: 0, DEGRADED: 1, UNHEALTHY: 2}


@dataclasses.dataclass(frozen=True)
class HealthThresholds:
    """Degradation thresholds; defaults are the measured-sane knee for
    the bench serving shapes (docs/OBSERVABILITY.md)."""

    queue_saturation_degraded: float = 0.8   # inflight / queue_depth
    shed_rate_degraded: float = 0.05         # sheds / (requests+sheds)
    deadline_miss_rate_degraded: float = 0.05
    stall_after_s: float = 1.0               # busy worker, no beat
    degraded_admission_factor: float = 0.5   # bound shrink while !ok

    def __post_init__(self):
        if not 0 < self.queue_saturation_degraded <= 1:
            raise ValueError("queue_saturation_degraded must be in (0, 1]")
        if self.stall_after_s <= 0:
            raise ValueError("stall_after_s must be positive")
        if not 0 < self.degraded_admission_factor <= 1:
            raise ValueError("degraded_admission_factor must be in (0, 1]")


@dataclasses.dataclass
class HealthStatus:
    """One evaluation's verdict: the typed state, why, and the raw
    check values the verdict derived from (the ``healthz`` payload)."""

    state: str
    reasons: List[str]
    checks: Dict[str, object]

    @property
    def ok(self) -> bool:
        return self.state == OK

    def as_dict(self) -> dict:
        return {"status": self.state, "reasons": list(self.reasons),
                "checks": dict(self.checks)}


class _Worker:
    __slots__ = ("name", "busy_fn", "last_beat", "beats")

    def __init__(self, name: str, busy_fn=None):
        self.name = name
        self.busy_fn = busy_fn
        self.last_beat = time.monotonic()
        self.beats = 0


class HealthMonitor:
    """Derives ``ok | degraded | unhealthy`` from heartbeats + metrics.

    Args:
      snapshot_fn: zero-arg callable returning the ``ServeMetrics``
        snapshot dict (``requests``, ``shed``, ``queue`` keys); rates
        are windowed over successive calls. None = liveness-only.
      queue_bound: the configured admission bound (queries) saturation
        is measured against. None disables the saturation check.
      thresholds: :class:`HealthThresholds`.
      period_s: background watchdog cadence for :meth:`start`; also the
        default rate window. The monitor works without the thread —
        :meth:`evaluate` is on-demand (the ``healthz`` op calls it).
      registry: optional :class:`~tfidf_tpu.obs.registry.
        MetricsRegistry` to publish the health gauges on.
    """

    def __init__(self, snapshot_fn: Optional[Callable[[], dict]] = None,
                 queue_bound: Optional[int] = None,
                 thresholds: Optional[HealthThresholds] = None,
                 period_s: float = 0.25,
                 registry=None) -> None:
        if period_s <= 0:
            raise ValueError("period_s must be positive")
        self.thresholds = thresholds or HealthThresholds()
        self.period_s = period_s
        self._snapshot_fn = snapshot_fn
        self._queue_bound = queue_bound
        self._workers: Dict[str, _Worker] = {}
        self._signals: Dict[str, Callable] = {}
        self._lock = threading.Lock()
        self._eval_lock = threading.Lock()  # healthz op vs watchdog
        self._status = HealthStatus(OK, [], {})
        self._prev: Optional[tuple] = None   # (t, requests, over, dead)
        self._rates = {"shed_rate": 0.0, "deadline_miss_rate": 0.0}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._g_state = self._g_bound = self._g_sat = self._g_shed = None
        if registry is not None:
            self._g_state = registry.gauge(
                "serve_health_state",
                "derived health: 0=ok, 1=degraded, 2=unhealthy")
            self._g_bound = registry.gauge(
                "serve_admission_bound",
                "effective admission bound (shrinks while degraded)")
            self._g_sat = registry.gauge(
                "serve_queue_saturation_milli",
                "queue depth / bound, in 1/1000")
            self._g_shed = registry.gauge(
                "serve_shed_rate_window_milli",
                "windowed shed rate, in 1/1000")

    # --- heartbeats ---
    def register(self, name: str, busy_fn: Optional[Callable[[], bool]]
                 = None) -> None:
        """Track a worker thread. ``busy_fn`` answers "does this worker
        have pending work right now?" — stall detection only arms for
        busy workers (an idle batcher legitimately never beats)."""
        with self._lock:
            w = self._workers.get(name)
            if w is None:
                self._workers[name] = _Worker(name, busy_fn)
            elif busy_fn is not None:
                w.busy_fn = busy_fn

    def heartbeat(self, name: str) -> None:
        w = self._workers.get(name)
        if w is None:
            self.register(name)
            w = self._workers[name]
        w.last_beat = time.monotonic()
        w.beats += 1

    # --- pluggable signals ---
    def add_signal(self, name: str,
                   fn: Callable[[], tuple]) -> None:
        """Register an external degradation signal: ``fn()`` returns
        ``(value, reason_or_None)``; the value lands in the status
        checks under ``name`` and a non-None reason marks the server
        ``degraded`` (never ``unhealthy`` — only a stalled worker is
        a wedge). How the device monitor's memory pressure and the
        compile watchdog's recompile window reach admission control
        without the health core knowing either exists."""
        with self._lock:
            self._signals[name] = fn

    def remove_signal(self, name: str) -> None:
        with self._lock:
            self._signals.pop(name, None)

    # --- evaluation ---
    def evaluate(self, now: Optional[float] = None) -> HealthStatus:
        """One watchdog pass: read heartbeat ages + a metrics snapshot,
        derive the typed status, publish gauges. Thread-safe; callable
        on demand (the ``healthz`` op) or by the background thread."""
        with self._eval_lock:
            return self._evaluate(
                time.monotonic() if now is None else now)

    def _evaluate(self, now: float) -> HealthStatus:
        reasons: List[str] = []
        checks: Dict[str, object] = {}
        thr = self.thresholds

        workers: Dict[str, dict] = {}
        stalled = []
        with self._lock:
            items = list(self._workers.values())
        for w in items:
            busy = bool(w.busy_fn()) if w.busy_fn is not None else False
            age = now - w.last_beat
            is_stalled = busy and age > thr.stall_after_s
            workers[w.name] = {"age_s": round(age, 3), "busy": busy,
                               "beats": w.beats, "stalled": is_stalled}
            if is_stalled:
                stalled.append(w.name)
                reasons.append(
                    f"worker {w.name!r} busy but silent for "
                    f"{age:.2f}s (> stall_after_s={thr.stall_after_s})")
        checks["workers"] = workers

        snap = self._snapshot_fn() if self._snapshot_fn else None
        saturation = 0.0
        if snap is not None and self._queue_bound:
            saturation = snap["queue"]["depth"] / self._queue_bound
            checks["queue_saturation"] = round(saturation, 4)
            if saturation >= thr.queue_saturation_degraded:
                reasons.append(
                    f"queue saturation {saturation:.2f} >= "
                    f"{thr.queue_saturation_degraded}")
        if snap is not None:
            served = snap["requests"]
            over = snap["shed"]["overload"]
            dead = snap["shed"]["deadline"]
            if self._prev is not None:
                pt, ps, po, pd = self._prev
                d_served = served - ps
                d_over, d_dead = over - po, dead - pd
                d_total = d_served + d_over + d_dead
                if now > pt and d_total > 0:
                    self._rates = {
                        "shed_rate": (d_over + d_dead) / d_total,
                        "deadline_miss_rate": d_dead / d_total,
                    }
                elif d_total == 0:
                    # No traffic in the window: rates decay to clean.
                    self._rates = {"shed_rate": 0.0,
                                   "deadline_miss_rate": 0.0}
            self._prev = (now, served, over, dead)
            checks.update({k: round(v, 4)
                           for k, v in self._rates.items()})
            if self._rates["shed_rate"] >= thr.shed_rate_degraded:
                reasons.append(
                    f"shed rate {self._rates['shed_rate']:.3f} >= "
                    f"{thr.shed_rate_degraded}")
            if (self._rates["deadline_miss_rate"]
                    >= thr.deadline_miss_rate_degraded):
                reasons.append(
                    f"deadline miss rate "
                    f"{self._rates['deadline_miss_rate']:.3f} >= "
                    f"{thr.deadline_miss_rate_degraded}")

        with self._lock:
            signals = list(self._signals.items())
        for name, fn in signals:
            try:
                value, reason = fn()
            except Exception:   # a broken signal must not wedge health
                continue
            if value is not None:
                checks[name] = (round(value, 4)
                                if isinstance(value, float) else value)
            if reason:
                reasons.append(reason)

        state = UNHEALTHY if stalled else (DEGRADED if reasons else OK)
        status = HealthStatus(state, reasons, checks)
        prev_state = self._status.state
        self._status = status
        if state != prev_state:
            obs_log.log_event(
                "warning" if state != OK else "info",
                "health_state_change",
                msg=f"health: {prev_state} -> {state}"
                    + (f" ({'; '.join(reasons)})" if reasons else ""),
                fr=prev_state, to=state)
        if self._g_state is not None:
            self._g_state.set(_STATE_NO[state])
            if self._queue_bound:
                self._g_bound.set(self.admission_bound(self._queue_bound))
            self._g_sat.set(int(saturation * 1000))
            self._g_shed.set(int(self._rates["shed_rate"] * 1000))
        return status

    def status(self) -> HealthStatus:
        """The LAST evaluated status (no re-evaluation — the watchdog
        thread or an explicit :meth:`evaluate` keeps it fresh)."""
        return self._status

    def admission_bound(self, configured: int) -> int:
        """The effective admission bound: ``configured`` while ok,
        shrunk by ``degraded_admission_factor`` while degraded or
        unhealthy — backpressure instead of falling over (never below
        1, so the server keeps making progress and can recover)."""
        if self._status.state == OK:
            return configured
        return max(1, int(configured
                          * self.thresholds.degraded_admission_factor))

    # --- background watchdog ---
    def start(self) -> "HealthMonitor":
        """Start the watchdog thread (idempotent): one
        :meth:`evaluate` per ``period_s`` — the detection latency
        bound (a stall or saturation shows up within one period)."""
        if self._thread is not None and self._thread.is_alive():
            return self
        self._stop.clear()

        def run():
            while not self._stop.wait(self.period_s):
                self.evaluate()

        self._thread = threading.Thread(
            target=run, daemon=True, name="tfidf-health-watchdog")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=5)
            self._thread = None


# --- module-level hook ----------------------------------------------
#
# Ingest worker threads beat through here so one installed monitor
# sees the WHOLE process (serve batcher + any reindex's pack/drain
# workers) without plumbing a monitor through every constructor.
# Disabled cost: one global load + None test, tracer-style.

_monitor: Optional[HealthMonitor] = None


def set_monitor(monitor: Optional[HealthMonitor]) -> None:
    global _monitor
    _monitor = monitor


def get_monitor() -> Optional[HealthMonitor]:
    return _monitor


def beat(name: str) -> None:
    m = _monitor
    if m is not None:
        m.heartbeat(name)
