"""Span tracer: one timeline from submit to drain (Chrome trace JSON).

The reference MPI program has literally zero timing or logging (SURVEY
§6); four rounds of overlap machinery later the repro has *invisible*
concurrency — ``_PackAhead``/``_DrainAhead`` worker threads, a scanned
finish, and a concurrent serving layer whose interleavings the bench
can only summarize as derived scalars (``overlap``,
``fetch_hidden_frac``). This module records what actually happened:
named spans on every participating thread, exported as Chrome
trace-event JSON that Perfetto / ``chrome://tracing`` opens directly —
one ``pid`` (the host process), one ``tid`` lane per thread (``main``,
``packer``, ``drainer``, ``batcher``, ...).

Design constraints, in priority order:

* **Near-zero overhead when disabled.** Product code calls the
  module-level :func:`span`/:func:`begin`/:func:`end` unconditionally;
  with no tracer configured they cost one global load, one ``is None``
  test and (for ``span``) a shared no-op context manager — pinned
  below 150 ns/span by tests/test_obs.py. No locks, no allocation.
* **Thread-safe when enabled.** Events append to a bounded ring buffer
  (``collections.deque(maxlen=...)`` — appends are atomic under the
  GIL, so the hot path takes no lock; only tid assignment and export
  do). When the ring overflows, the OLDEST spans drop — a long serve
  session keeps its most recent window instead of dying of memory.
* **Cross-thread spans.** ``with span(...)`` covers the common
  same-thread case; :func:`begin`/:func:`end` pair across threads for
  lifecycles like a served request (begun on the submitting thread,
  finished on the batcher's callback thread). The event lands on the
  lane of the thread that BEGAN it — the lifecycle reads top-to-bottom
  on the submitter's lane.
* **Device correlation.** :func:`device_span` additionally enters a
  ``jax.profiler.TraceAnnotation``, so the same names show up on the
  device lanes of a real ``jax.profiler.trace`` capture
  (tools/trace_capture.py ``--host-trace`` merges both).

Wire-up: ``--trace out.json`` on the CLI subcommands, or the
``TFIDF_TPU_TRACE`` env var (path), both through :func:`configure`;
ring capacity via ``TFIDF_TPU_TRACE_CAP`` (spans, default 2^16).
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from typing import Any, Dict, Iterable, List, Optional, Tuple

__all__ = [
    "Tracer", "SpanHandle", "configure", "enabled", "export",
    "get_tracer", "set_tracer", "span", "begin", "end", "instant",
    "device_span", "name_thread", "span_totals", "trace_path",
    "set_export_meta", "load_chrome_trace", "device_op_table",
    "spans_by_thread",
]

_DEFAULT_CAP = 1 << 16


class _NullSpan:
    """The shared disabled-path context manager. Stateless, so one
    instance serves every caller; explicit 3-arg ``__exit__`` keeps it
    the cheapest pure-Python ``with`` target."""

    __slots__ = ()

    def __enter__(self):
        return None

    def __exit__(self, et, ev, tb):
        return False


_NULL = _NullSpan()


class SpanHandle:
    """Open span returned by :meth:`Tracer.begin` — carries the start
    stamp, the beginning thread's lane, and the args dict that
    :meth:`Tracer.end` may extend (e.g. the request outcome, known
    only at resolution time)."""

    __slots__ = ("name", "t0", "tid", "args")

    def __init__(self, name: str, t0: int, tid: int,
                 args: Optional[Dict[str, Any]]):
        self.name = name
        self.t0 = t0
        self.tid = tid
        self.args = args


class _Span:
    """Same-thread ``with`` span (one allocation per enabled span)."""

    __slots__ = ("_tracer", "_name", "_args", "_t0", "_tid")

    def __init__(self, tracer: "Tracer", name: str,
                 args: Optional[Dict[str, Any]]):
        self._tracer = tracer
        self._name = name
        self._args = args

    def __enter__(self):
        self._tid = self._tracer._tid()
        self._t0 = time.perf_counter_ns()
        return self

    def __exit__(self, et, ev, tb):
        t = self._tracer
        t._events.append((self._name, self._tid, self._t0,
                          time.perf_counter_ns() - self._t0, self._args))
        return False


class _DeviceSpan:
    """Host span + ``jax.profiler.TraceAnnotation`` under one name, so
    the host lane and the device lanes of a profiler capture carry the
    same marker. jax imports lazily — only when tracing is on."""

    __slots__ = ("_span", "_ann", "_name")

    def __init__(self, tracer: "Tracer", name: str,
                 args: Optional[Dict[str, Any]]):
        self._span = _Span(tracer, name, args)
        self._name = name

    def __enter__(self):
        self._span.__enter__()
        try:
            import jax.profiler
            self._ann = jax.profiler.TraceAnnotation(self._name)
            self._ann.__enter__()
        except Exception:  # jax absent/old: host span still records
            self._ann = None
        return self

    def __exit__(self, et, ev, tb):
        if self._ann is not None:
            self._ann.__exit__(et, ev, tb)
        return self._span.__exit__(et, ev, tb)


class Tracer:
    """Thread-safe span recorder with a bounded ring buffer.

    Events are ``(name, tid, t0_ns, dur_ns, args)`` tuples relative to
    the tracer's construction instant; :meth:`chrome_events` converts
    to Chrome trace-event dicts (µs timestamps) and :meth:`export`
    writes the ``{"traceEvents": [...]}`` JSON Perfetto loads.
    """

    def __init__(self, capacity: int = _DEFAULT_CAP):
        if capacity < 1:
            raise ValueError("capacity must be >= 1")
        self.capacity = capacity
        self._events: deque = deque(maxlen=capacity)
        self._t0 = time.perf_counter_ns()
        self._lock = threading.Lock()
        self._next_tid = 0
        self._names: Dict[int, str] = {}     # tid -> thread name
        self._labels: Dict[int, str] = {}    # tid -> explicit lane label
        self._local = threading.local()
        # Fleet-trace export metadata (round 23): process identity and
        # the clock-offset estimate tools/trace_merge.py aligns lanes
        # with. Written by set_export_meta, embedded under the
        # "disttrace" key of the exported doc — timestamps themselves
        # are NEVER rewritten (docs/OBSERVABILITY.md "fleet tracing").
        self.meta: Dict[str, Any] = {}

    # --- recording ---
    def _tid(self) -> int:
        """Lane id of the calling thread (cached thread-locally; the
        lock is taken once per thread's lifetime). Lanes are NOT keyed
        on ``thread.ident`` — the OS reuses idents of dead threads
        (e.g. the pass-B packer after the pass-A packer exits), and a
        reused ident must not splice two threads onto one lane."""
        try:
            return self._local.tid
        except AttributeError:
            pass
        th = threading.current_thread()
        with self._lock:
            tid = self._next_tid
            self._next_tid += 1
            name = th.name
            if name == "MainThread":
                name = "main"
            self._names[tid] = name
        self._local.tid = tid
        return tid

    def name_thread(self, label: str) -> None:
        """Give the calling thread's lane an explicit label (``packer``,
        ``drainer``, ``batcher``...). Idempotent and cheap enough to
        call from a worker's per-item job."""
        tid = self._tid()
        if self._labels.get(tid) != label:
            with self._lock:
                self._labels[tid] = label

    def span(self, name: str, **args) -> _Span:
        return _Span(self, name, args or None)

    def device_span(self, name: str, **args) -> _DeviceSpan:
        return _DeviceSpan(self, name, args or None)

    def begin(self, name: str, **args) -> SpanHandle:
        return SpanHandle(name, time.perf_counter_ns(), self._tid(),
                          args or None)

    def end(self, handle: SpanHandle, **args) -> None:
        dur = time.perf_counter_ns() - handle.t0
        merged = handle.args
        if args:
            merged = dict(merged or ()); merged.update(args)
        self._events.append((handle.name, handle.tid, handle.t0, dur,
                             merged))

    def instant(self, name: str, **args) -> None:
        """Zero-duration marker on the calling thread's lane."""
        self._events.append((name, self._tid(),
                             time.perf_counter_ns(), -1, args or None))

    # --- reading ---
    def events(self) -> List[Tuple]:
        """Snapshot of the raw ring (name, tid, t0_ns, dur_ns, args)."""
        return list(self._events)

    def span_totals(self) -> Dict[str, float]:
        """Total seconds per span name — the tracer-side twin of
        ``PhaseTimer.as_dict`` (bench cross-check; instants excluded)."""
        out: Dict[str, float] = {}
        for name, _tid, _t0, dur, _args in list(self._events):
            if dur >= 0:
                out[name] = out.get(name, 0.0) + dur / 1e9
        return out

    def thread_label(self, tid: int) -> str:
        return self._labels.get(tid) or self._names.get(tid, f"t{tid}")

    def chrome_events(self, pid: int = 1) -> List[dict]:
        """Chrome trace-event dicts: ``M`` metadata naming the process
        and each thread lane, then one ``X`` (complete) event per span
        (``ts``/``dur`` in microseconds) and ``i`` events for instants.
        """
        with self._lock:
            labels = {tid: self.thread_label(tid) for tid in self._names}
        events: List[dict] = [{
            "ph": "M", "pid": pid, "tid": 0, "name": "process_name",
            "args": {"name": "tfidf_tpu host"},
        }]
        for tid in sorted(labels):
            events.append({"ph": "M", "pid": pid, "tid": tid,
                           "name": "thread_name",
                           "args": {"name": labels[tid]}})
            events.append({"ph": "M", "pid": pid, "tid": tid,
                           "name": "thread_sort_index",
                           "args": {"sort_index": tid}})
        for name, tid, t0, dur, args in list(self._events):
            ev = {"ph": "X" if dur >= 0 else "i", "pid": pid, "tid": tid,
                  "name": name, "ts": (t0 - self._t0) / 1e3}
            if dur >= 0:
                ev["dur"] = dur / 1e3
            else:
                ev["s"] = "t"  # instant scope: thread
            if args:
                # Cost-annotated spans (round 12): a span stamped with
                # the bytes it moved exports its achieved bandwidth —
                # bytes/ns IS GB/s — so the Perfetto timeline reads
                # roofline fractions directly. Degenerate durations
                # export no gb_s (json.dump would emit bare Infinity,
                # which is not JSON). The ring's args dict is shared
                # with the recording thread — copy, never mutate.
                b = args.get("bytes")
                if isinstance(b, (int, float)) and dur > 0:
                    args = dict(args)
                    args["gb_s"] = round(b / dur, 4)
                ev["args"] = args
            events.append(ev)
        return events

    def set_export_meta(self, **kv: Any) -> None:
        """Merge fleet-trace metadata into the export doc (process
        identity, clock offset — see module ``set_export_meta``)."""
        self.meta.update(kv)

    def export_meta(self) -> Dict[str, Any]:
        """The per-process ``disttrace`` metadata block: identity +
        the tracer's epoch (``t0_ns``, the perf_counter_ns instant
        Chrome ``ts`` values are relative to) + whatever
        :meth:`set_export_meta` recorded (clock offset/uncertainty)."""
        return {"process": self.meta.get("process", "host"),
                "os_pid": os.getpid(), "t0_ns": self._t0, **self.meta}

    def export(self, path: str) -> str:
        """Write the Chrome trace JSON; returns ``path``. Load it in
        Perfetto (https://ui.perfetto.dev) or ``chrome://tracing``.
        The doc carries a ``disttrace`` metadata key (Perfetto ignores
        unknown top-level keys) so ``tools/trace_merge.py`` can align
        this process's lanes against a peer's."""
        doc = {"traceEvents": self.chrome_events(),
               "displayTimeUnit": "ms",
               "disttrace": self.export_meta()}
        d = os.path.dirname(os.path.abspath(path))
        os.makedirs(d, exist_ok=True)
        with open(path, "w") as f:
            json.dump(doc, f)
        return path

    def clear(self) -> None:
        self._events.clear()


# --- module-level global tracer -------------------------------------
#
# Product code traces through THESE functions so the disabled path is
# one global load + None test. ``_tracer is None`` == tracing off.

_tracer: Optional[Tracer] = None
_path: Optional[str] = None


def configure(path: Optional[str] = None,
              capacity: Optional[int] = None) -> Optional[str]:
    """Arm the global tracer. ``path`` is where :func:`export` will
    write (``None`` falls back to ``TFIDF_TPU_TRACE``; empty/absent
    leaves tracing OFF). Idempotent: re-configuring with the same or
    no path keeps the live tracer and its recorded spans — the entry
    points call this the way they call ``apply_compile_cache``."""
    global _tracer, _path
    resolved = path or os.environ.get("TFIDF_TPU_TRACE")
    if not resolved:
        return _path
    if _tracer is not None and resolved == _path:
        return _path
    if capacity is None:
        capacity = int(os.environ.get("TFIDF_TPU_TRACE_CAP",
                                      str(_DEFAULT_CAP)))
    _path = resolved
    _tracer = Tracer(capacity)
    return _path


def enabled() -> bool:
    return _tracer is not None


def get_tracer() -> Optional[Tracer]:
    return _tracer


def set_tracer(tracer: Optional[Tracer],
               path: Optional[str] = None) -> None:
    """Install (or, with ``None``, disarm) the global tracer — the
    test seam, and how embedders route spans into their own sink."""
    global _tracer, _path
    _tracer = tracer
    _path = path


def trace_path() -> Optional[str]:
    """The armed export path, or None when tracing is off."""
    return _path if _tracer is not None else None


def export(path: Optional[str] = None) -> Optional[str]:
    """Write the global tracer's trace to ``path`` (default: the
    configured path). Returns the written path, or None when tracing
    is off — callers can report it unconditionally."""
    t = _tracer
    if t is None:
        return None
    resolved = path or _path
    if not resolved:
        return None
    return t.export(resolved)


def span(name: str, **args):
    """Context manager recording one span on the calling thread's lane
    (no-op when tracing is off)."""
    t = _tracer
    if t is None:
        return _NULL
    return _Span(t, name, args or None)


def device_span(name: str, **args):
    """Like :func:`span`, additionally wrapped in a
    ``jax.profiler.TraceAnnotation`` so a concurrent profiler capture
    carries the same name on its device lanes."""
    t = _tracer
    if t is None:
        return _NULL
    return _DeviceSpan(t, name, args or None)


def begin(name: str, **args) -> Optional[SpanHandle]:
    """Open a cross-thread span; pair with :func:`end`. Returns None
    when tracing is off (``end(None)`` is a no-op)."""
    t = _tracer
    if t is None:
        return None
    return t.begin(name, **args)


def end(handle: Optional[SpanHandle], **args) -> None:
    t = _tracer
    if t is None or handle is None:
        return
    t.end(handle, **args)


def instant(name: str, **args) -> None:
    t = _tracer
    if t is not None:
        t.instant(name, **args)


def name_thread(label: str) -> None:
    t = _tracer
    if t is not None:
        t.name_thread(label)


def span_totals() -> Dict[str, float]:
    t = _tracer
    return t.span_totals() if t is not None else {}


def set_export_meta(**kv) -> None:
    """Record fleet-trace metadata (``process`` identity, ``clock``
    offset estimate) on the global tracer for the next export; no-op
    when tracing is off."""
    t = _tracer
    if t is not None:
        t.set_export_meta(**kv)


# --- Chrome-trace reading (shared by tools/trace_capture.py,
#     tools/trace_check.py and the tests) --------------------------------

def load_chrome_trace(path: str) -> List[dict]:
    """Load a Chrome trace-event file — ours, or a ``jax.profiler``
    ``*.trace.json.gz`` — and return its ``traceEvents`` list."""
    if path.endswith(".gz"):
        import gzip
        with gzip.open(path, "rt") as f:
            doc = json.load(f)
    else:
        with open(path) as f:
            doc = json.load(f)
    if isinstance(doc, list):  # bare event-array form is also legal
        return doc
    return doc.get("traceEvents", [])


def spans_by_thread(events: Iterable[dict]) -> Dict[str, List[dict]]:
    """Group ``X`` events by their lane's ``thread_name`` metadata
    (falling back to ``pid/tid``)."""
    names: Dict[Tuple[Any, Any], str] = {}
    for e in events:
        if e.get("ph") == "M" and e.get("name") == "thread_name":
            names[(e.get("pid"), e.get("tid"))] = \
                e.get("args", {}).get("name", "")
    out: Dict[str, List[dict]] = {}
    for e in events:
        if e.get("ph") != "X":
            continue
        key = (e.get("pid"), e.get("tid"))
        label = names.get(key) or f"{key[0]}/{key[1]}"
        out.setdefault(label, []).append(e)
    return out


def device_op_table(events: Iterable[dict], top: int = 25):
    """Aggregate device-lane op durations from a ``jax.profiler``
    capture: ``(rows, total_us)`` where rows are ``(name, total_us,
    calls)`` sorted by total — the table tools/trace_capture.py
    prints. Device lanes are pids whose ``process_name`` mentions the
    accelerator."""
    import collections
    proc_names: Dict[Any, str] = {}
    for e in events:
        if e.get("ph") == "M" and e.get("name") == "process_name":
            proc_names[e["pid"]] = e.get("args", {}).get("name", "")
    dev_pids = {p for p, n in proc_names.items()
                if "TPU" in n or "/device" in n.lower() or "Device" in n}
    agg: Dict[str, float] = collections.defaultdict(float)
    cnt: Dict[str, int] = collections.defaultdict(int)
    total = 0.0
    for e in events:
        if e.get("ph") != "X" or e.get("pid") not in dev_pids:
            continue
        name = e.get("name", "?")
        dur = float(e.get("dur", 0.0))  # microseconds
        agg[name] += dur
        cnt[name] += 1
        total += dur
    rows = [(name, us, cnt[name])
            for name, us in sorted(agg.items(), key=lambda kv: -kv[1])]
    return rows[:top], total
