"""Structured event log + flight recorder: the incident's own evidence.

The tracer (``obs/tracer.py``) answers "where did the time go"; this
module answers "what happened" — discrete, leveled, structured events
(a wire fallback, a shed burst, a health-state change) recorded into a
bounded ring next to the last-N served-request digests, and dumped
ATOMICALLY to JSONL when the process ends badly (crash, SIGTERM) or a
server closes. The reference program's only diagnostics were two debug
``printf``s (SURVEY §6); four rounds of concurrency later a silent
failure leaves nothing behind — this ring means every incident ships
its own flight recording.

Design constraints:

* **Always recording, cheap.** The ring exists from first use (no
  arming step — a crash is exactly when you discover you wanted it);
  one event is a dict append under the GIL plus a token-bucket check.
  Hot paths that only MIGHT log go through the module-level helpers,
  which are a singleton load + method call.
* **Rate-limited per event name.** A misbehaving loop logging
  ``wire_fallback`` 10k times/sec keeps its budget (default 20/s,
  burst 40 — ``TFIDF_TPU_LOG_RATE``) and the ring keeps its window;
  suppressed counts are tracked and surface on the next admitted
  event and in the dump header, so throttling is itself visible.
* **stderr echo.** Events at or above the echo level (default
  ``info`` — ``TFIDF_TPU_LOG_ECHO``, ``off`` to silence) also write
  one human line to stderr, which is how the library's old ad-hoc
  ``sys.stderr.write`` diagnostics (rerank engine fallbacks, margin
  warnings, bench progress) keep their visible behavior after moving
  onto structured events.
* **Atomic dump.** :meth:`EventLog.dump` writes ``path + ".tmp"`` then
  ``os.replace`` — a reader never sees a torn file, and a dump that
  dies mid-write leaves the previous dump intact.

Wire-up: ``--flight OUT.jsonl`` on the serve CLI or the
``TFIDF_TPU_FLIGHT`` env var arm the dump path; when only ``--trace``
is armed the flight dump rides next to the trace as
``<trace>.flight.jsonl`` (the two are one incident's evidence).
``tools/trace_check.py --flight`` validates a dump's schema in CI.
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

__all__ = [
    "EventLog", "get_log", "set_log", "log_event", "record_digest",
    "configure_flight", "flight_path", "dump_flight", "FLIGHT_SCHEMA",
]

FLIGHT_SCHEMA = "tfidf-flight/1"

_LEVELS = {"debug": 10, "info": 20, "warning": 30, "error": 40}
_DEFAULT_CAP = 4096
_DEFAULT_DIGESTS = 256
_DEFAULT_RATE = 20.0     # admitted events/sec per event name
_DEFAULT_BURST = 40.0


def _level_no(level: str) -> int:
    try:
        return _LEVELS[level]
    except KeyError:
        raise ValueError(f"unknown log level {level!r} "
                         f"(choose from {sorted(_LEVELS)})") from None


class EventLog:
    """Bounded ring of structured events + last-N request digests.

    Args:
      capacity: event-ring size (oldest drop past it).
      digests: request-digest ring size.
      rate_per_s / burst: per-event-name token bucket; events past the
        budget are counted as suppressed, not recorded.
      echo: minimum level echoed as one human line to stderr
        (``"off"`` disables echoing entirely).
    """

    def __init__(self, capacity: int = _DEFAULT_CAP,
                 digests: int = _DEFAULT_DIGESTS,
                 rate_per_s: float = _DEFAULT_RATE,
                 burst: float = _DEFAULT_BURST,
                 echo: str = "info") -> None:
        if capacity < 1 or digests < 1:
            raise ValueError("capacity/digests must be >= 1")
        if rate_per_s <= 0 or burst < 1:
            raise ValueError("need rate_per_s > 0 and burst >= 1")
        self.capacity = capacity
        self._events: deque = deque(maxlen=capacity)
        self._digests: deque = deque(maxlen=digests)
        self._rate = rate_per_s
        self._burst = burst
        self._echo_no = (10**9 if echo == "off" else _level_no(echo))
        self._lock = threading.Lock()          # token buckets only
        self._buckets: Dict[str, List[float]] = {}  # name -> [tokens, t]
        self._suppressed: Dict[str, int] = {}

    # --- recording ---
    def log(self, level: str, event: str, msg: Optional[str] = None,
            **fields: Any) -> bool:
        """Record one structured event; returns False when the event's
        rate budget suppressed it. ``msg`` is the optional human form
        (used verbatim by the stderr echo); ``fields`` must be
        JSON-serializable."""
        no = _level_no(level)
        now = time.monotonic()
        with self._lock:
            bucket = self._buckets.get(event)
            if bucket is None:
                bucket = self._buckets[event] = [self._burst, now]
            tokens = min(self._burst,
                         bucket[0] + (now - bucket[1]) * self._rate)
            bucket[1] = now
            if tokens < 1.0:
                bucket[0] = tokens
                self._suppressed[event] = \
                    self._suppressed.get(event, 0) + 1
                return False
            bucket[0] = tokens - 1.0
            dropped = self._suppressed.pop(event, 0)
        rec = {"t": round(time.time(), 6), "level": level,
               "event": event}
        if msg is not None:
            rec["msg"] = msg
        if fields:
            # "kind" is RESERVED by the dump protocol (the
            # event/digest discriminator each JSONL line leads with);
            # a payload field with that name would clobber it and
            # tear the dump. Store it under "field_kind" instead of
            # silently corrupting the recorder.
            if "kind" in fields:
                fields = dict(fields)
                fields["field_kind"] = fields.pop("kind")
            rec.update(fields)
        if dropped:
            rec["suppressed"] = dropped  # events throttled since last
        self._events.append(rec)
        if no >= self._echo_no:
            text = msg if msg is not None else " ".join(
                [event] + [f"{k}={v}" for k, v in fields.items()])
            try:
                sys.stderr.write(f"{text}\n")
            except (OSError, ValueError):   # stderr gone (daemonized)
                pass
        return True

    def debug(self, event: str, msg: Optional[str] = None, **fields):
        return self.log("debug", event, msg, **fields)

    def info(self, event: str, msg: Optional[str] = None, **fields):
        return self.log("info", event, msg, **fields)

    def warning(self, event: str, msg: Optional[str] = None, **fields):
        return self.log("warning", event, msg, **fields)

    def error(self, event: str, msg: Optional[str] = None, **fields):
        return self.log("error", event, msg, **fields)

    def digest(self, **fields: Any) -> None:
        """Record one served-request digest (outcome, latency, sizes —
        never query text) into the last-N ring. Not rate-limited: one
        digest per request is already bounded by the serve rate, and a
        gappy digest ring would defeat its purpose."""
        rec = {"t": round(time.time(), 6)}
        if "kind" in fields:   # reserved by the dump protocol
            fields = dict(fields)
            fields["field_kind"] = fields.pop("kind")
        rec.update(fields)
        self._digests.append(rec)

    # --- reading ---
    def events(self) -> List[dict]:
        return list(self._events)

    def digests(self) -> List[dict]:
        return list(self._digests)

    def suppressed(self) -> Dict[str, int]:
        """Per-event counts throttled since their last admitted event."""
        with self._lock:
            return dict(self._suppressed)

    def clear(self) -> None:
        self._events.clear()
        self._digests.clear()
        with self._lock:
            self._buckets.clear()
            self._suppressed.clear()

    # --- dumping ---
    def dump(self, path: str) -> str:
        """Atomic JSONL dump: a schema header line, then every ring
        event as ``{"kind": "event", ...}``, then every digest as
        ``{"kind": "digest", ...}``. Written to ``path + ".tmp"`` and
        renamed into place, so a dump interrupted mid-write (the crash
        case) never corrupts an earlier complete dump."""
        events = list(self._events)
        digests = list(self._digests)
        header = {"schema": FLIGHT_SCHEMA, "pid": os.getpid(),
                  "dumped_at": round(time.time(), 6),
                  "events": len(events), "digests": len(digests),
                  "suppressed": self.suppressed()}
        d = os.path.dirname(os.path.abspath(path))
        os.makedirs(d, exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            f.write(json.dumps(header) + "\n")
            for rec in events:
                f.write(json.dumps({"kind": "event", **rec}) + "\n")
            for rec in digests:
                f.write(json.dumps({"kind": "digest", **rec}) + "\n")
        os.replace(tmp, path)
        return path


# --- module-level singleton -----------------------------------------
#
# Product code logs through these helpers; the singleton builds itself
# from the env on first use so a crash dump always has a ring to read.

_log: Optional[EventLog] = None
_log_lock = threading.Lock()
_flight: Optional[str] = None


def get_log() -> EventLog:
    global _log
    if _log is None:
        with _log_lock:
            if _log is None:
                _log = EventLog(
                    capacity=int(os.environ.get(
                        "TFIDF_TPU_LOG_CAP", str(_DEFAULT_CAP))),
                    rate_per_s=float(os.environ.get(
                        "TFIDF_TPU_LOG_RATE", str(_DEFAULT_RATE))),
                    echo=os.environ.get("TFIDF_TPU_LOG_ECHO", "info"))
    return _log


def set_log(log: Optional[EventLog]) -> None:
    """Install (or, with ``None``, reset to lazy-default) the global
    event log — the test seam."""
    global _log
    _log = log


def log_event(level: str, event: str, msg: Optional[str] = None,
              **fields: Any) -> bool:
    return get_log().log(level, event, msg, **fields)


def record_digest(**fields: Any) -> None:
    get_log().digest(**fields)


def configure_flight(path: Optional[str] = None) -> Optional[str]:
    """Arm the flight-recorder dump path (``None`` falls back to
    ``TFIDF_TPU_FLIGHT``; empty/absent leaves the explicit path unset —
    the dump may still derive one from an armed tracer, see
    :func:`flight_path`). Idempotent like ``tracer.configure``."""
    global _flight
    resolved = path or os.environ.get("TFIDF_TPU_FLIGHT")
    if resolved:
        _flight = resolved
    return _flight


def flight_path() -> Optional[str]:
    """Where a dump would land: the configured path, else — when the
    span tracer is armed — ``<trace>.flight.jsonl`` next to it (one
    incident, one directory of evidence). None when neither is armed."""
    if _flight:
        return _flight
    from tfidf_tpu.obs import tracer
    tp = tracer.trace_path()
    return f"{tp}.flight.jsonl" if tp else None


def dump_flight(path: Optional[str] = None) -> Optional[str]:
    """Dump the global ring to ``path`` (default: :func:`flight_path`).
    Returns the written path, or None when no path is armed — callers
    (the CLI exit path, ``TfidfServer.close``, the SIGTERM handler)
    invoke it unconditionally."""
    resolved = path or flight_path()
    if not resolved:
        return None
    return get_log().dump(resolved)
