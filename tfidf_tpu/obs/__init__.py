"""Observability layer: end-to-end tracing + unified telemetry.

The reference program has zero timing or logging (SURVEY §6). This
package is the production answer the ROADMAP's serve-heavy-traffic
north star requires — one timeline from submit to drain:

* :mod:`~tfidf_tpu.obs.tracer` — thread-safe, near-zero-overhead-when-
  disabled span tracer recording to a ring buffer and exporting Chrome
  trace-event JSON (one ``tid`` lane per thread: main, packer,
  drainer, batcher) that Perfetto / ``chrome://tracing`` opens
  directly. Armed by ``--trace out.json`` on the CLI subcommands or
  ``TFIDF_TPU_TRACE``.
* :mod:`~tfidf_tpu.obs.registry` — unified counter/gauge/histogram
  registry with Prometheus text exposition and JSON snapshot;
  ``ServeMetrics`` lives on one, and ``serve``'s ``metrics_prom`` op
  renders it.
* :mod:`~tfidf_tpu.obs.log` — rate-limited structured event log +
  flight recorder: a bounded ring of leveled events and last-N
  request digests, dumped atomically (with the trace) on
  crash/SIGTERM/close — every incident ships its own evidence.
* :mod:`~tfidf_tpu.obs.health` — the consumption layer: a watchdog
  deriving ``ok | degraded | unhealthy`` from worker heartbeats and
  windowed SLO rates, feeding back into serve admission control.
* :mod:`~tfidf_tpu.obs.devmon` — the device-truth layer: per-device
  HBM accounting (gauges, live-buffer census, watermark events, a
  memory-pressure health signal) and the XLA compile watchdog that
  flags any recompile after warm-up.
* :mod:`~tfidf_tpu.obs.costmodel` — the analytic bytes/bandwidth
  model (stdlib-only): byte-stamped spans export achieved GB/s, and
  ``tools/doctor.py`` quotes roofline fractions from the same
  arithmetic.

The tracer API is re-exported here (``from tfidf_tpu import obs;
obs.span(...)``) because product code calls it on hot paths, and the
flight-recorder dump helpers ride along (stdlib-only); the registry
and health modules load lazily to keep ``import tfidf_tpu.obs`` free
of any further dependencies.

Validation tooling: ``tools/trace_check.py`` asserts a captured
trace's structural invariants (the overlap the bench artifacts claim);
``tools/trace_capture.py --host-trace`` merges host spans with a real
``jax.profiler`` device capture. docs/OBSERVABILITY.md walks a trace.
"""

from tfidf_tpu.obs.log import (EventLog, configure_flight, dump_flight,
                               flight_path, get_log, log_event,
                               record_digest, set_log)
from tfidf_tpu.obs.tracer import (SpanHandle, Tracer, begin, configure,
                                  device_op_table, device_span, enabled,
                                  end, export, get_tracer, instant,
                                  load_chrome_trace, name_thread,
                                  set_export_meta, set_tracer, span,
                                  span_totals, spans_by_thread,
                                  trace_path)

__all__ = [
    "Tracer", "SpanHandle", "configure", "enabled", "export",
    "get_tracer", "set_tracer", "span", "device_span", "begin", "end",
    "instant", "name_thread", "span_totals", "trace_path",
    "set_export_meta",
    "load_chrome_trace", "spans_by_thread", "device_op_table",
    "EventLog", "get_log", "set_log", "log_event", "record_digest",
    "configure_flight", "flight_path", "dump_flight",
    # lazy (tfidf_tpu.obs.registry / tfidf_tpu.obs.health /
    # tfidf_tpu.obs.devmon / tfidf_tpu.obs.slo):
    "MetricsRegistry", "Counter", "Gauge", "Histogram",
    "HealthMonitor", "HealthThresholds", "HealthStatus",
    "DeviceMonitor", "CompileWatch", "SloTracker",
]


def __getattr__(name):  # PEP 562: heavier members load on demand
    if name in ("MetricsRegistry", "Counter", "Gauge", "Histogram",
                "DEFAULT_BUCKETS"):
        from tfidf_tpu.obs import registry
        return getattr(registry, name)
    if name in ("HealthMonitor", "HealthThresholds", "HealthStatus"):
        from tfidf_tpu.obs import health
        return getattr(health, name)
    if name in ("DeviceMonitor", "CompileWatch"):
        from tfidf_tpu.obs import devmon
        return getattr(devmon, name)
    if name == "SloTracker":
        from tfidf_tpu.obs import slo
        return slo.SloTracker
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
