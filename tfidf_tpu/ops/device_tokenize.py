"""On-device tokenize+hash: the device half of the bytes wire.

The bytes wire (``--wire=bytes``, round 14) ships each chunk as ONE
flat slab of raw document bytes — the host never tokenizes, hashes or
packs ids at all (the reference's "extra" variant parallelizes exactly
that host loop with five OpenMP pragmas, ``TFIDF_extra.c:69-302``; we
delete the loop from the host instead) — and this module turns the
slab back into the SAME padded ``[D, L]`` id batch the host packers
emit, on device, bit-identical by contract:

* whitespace semantics are the fixed ASCII isspace set
  (``native/tokenize_common.h IsSpace``, = ``bytes.split()``);
* the hash is seeded FNV-1a64 → xor-fold → mod-vocab
  (``ops.hashing.words_to_ids`` / ``tokenize_common.h HashWord``),
  emulated in paired uint32 limbs because TPU jax runs without 64-bit
  types enabled;
* per-token byte truncation (``truncate_tokens_at``) and the
  ``max_per_doc`` token cap apply exactly as in ``TokenizeHashInto``.

Parity with both host packers is pinned by tests/test_bytes_wire.py
over random byte corpora (multi-byte UTF-8, all-whitespace docs,
truncation, bucket-boundary tokens).

Slab layout contract (mirrored by ``ingest.make_bytes_packer`` and
``native/loader.cc loader_fill_slab``): doc d's raw bytes start at
``offs[d] = sum_{e<d} ceil((blen[e] + 1) / align) * align`` — the
``+ 1`` guarantees at least one fill byte between documents — and
every non-document byte of the slab (inter-doc fill, bucket pad) is
``0x20`` (space), so the flat stream tokenizes globally with NO
doc-boundary special case: fill bytes are whitespace, tokens can never
straddle documents, and a document's token starts fall out of one
vectorized scan over the whole slab.

Two hash lowerings, selected by ``TFIDF_TPU_DEVICE_TOKENIZE``
(trace-time static, like ``TFIDF_TPU_REBUILD``): ``"xla"`` — the
portable default, a masked ``lax.while_loop`` whose trip count is the
longest live token in the chunk — and ``"pallas"`` (the Mosaic kernel
``ops.pallas_kernels.tokenize_hash_pallas``, doc-tile grid with the
slab resident in VMEM — the in-tree A/B probe, same scope doctrine as
``ragged_rebuild_pallas``). The token-start derivation (scan + offsets
+ scatter) is shared XLA code under both, so the lowerings cannot
drift on tokenization; only the per-byte hash loop differs.

The fold-to-vocab requires ``vocab_size <= 2^16`` (the 32-limb modular
reduction's products must fit uint32) — the same bound as the ragged
uint16 wire, and ``ingest.use_bytes_wire`` degrades wider runs the
same way.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

__all__ = [
    "FNV_OFFSET", "FNV_PRIME", "is_space", "fnv1a_step", "fold_mod",
    "aligned_byte_lengths", "token_starts", "tokenize_hash_device",
    "tokenize_method",
]

FNV_OFFSET = 14695981039346656037  # tokenize_common.h kFnvOffset
FNV_PRIME = 1099511628211          # tokenize_common.h kFnvPrime

_PRIME_HI = np.uint32(FNV_PRIME >> 32)          # 0x100
_PRIME_LO = np.uint32(FNV_PRIME & 0xFFFFFFFF)   # 0x1B3
_U16 = np.uint32(0xFFFF)
_SHIFT16 = np.uint32(16)


def tokenize_method(explicit=None) -> str:
    """Resolve the device tokenize+hash lowering: ``"xla"`` (portable
    default) or ``"pallas"`` (``ops.pallas_kernels.
    tokenize_hash_pallas``). Override via ``TFIDF_TPU_DEVICE_TOKENIZE``;
    resolved at trace time like :func:`ingest.rebuild_method`."""
    if explicit is not None:
        return explicit
    method = os.environ.get("TFIDF_TPU_DEVICE_TOKENIZE") or "xla"
    if method not in ("xla", "pallas"):
        raise ValueError(
            f"unknown TFIDF_TPU_DEVICE_TOKENIZE method {method!r} "
            f"(choose 'xla' or 'pallas')")
    return method


def is_space(b):
    """The fixed ASCII whitespace set over int byte values — exactly
    ``tokenize_common.h IsSpace`` / ``bytes.split()``: space, \\t, \\n,
    \\v, \\f, \\r. Works on any integer dtype array."""
    return (b == 32) | ((b >= 9) & (b <= 13))


def _mul32(a, b):
    """uint32 × uint32 → (hi, lo) uint32 — the 64-bit product in two
    limbs, via 16-bit partials (no 64-bit types on the TPU path)."""
    a0, a1 = a & _U16, a >> _SHIFT16
    b0, b1 = b & _U16, b >> _SHIFT16
    p00 = a0 * b0
    p01 = a0 * b1
    p10 = a1 * b0
    mid = (p00 >> _SHIFT16) + (p01 & _U16) + (p10 & _U16)
    lo = (p00 & _U16) | ((mid & _U16) << _SHIFT16)
    hi = a1 * b1 + (p01 >> _SHIFT16) + (p10 >> _SHIFT16) \
        + (mid >> _SHIFT16)
    return hi, lo


def fnv1a_step(hi, lo, byte_u32):
    """One FNV-1a64 byte step on (hi, lo) uint32 limb pairs:
    ``h = (h ^ byte) * FNV_PRIME mod 2^64``. The ``h_hi * P_hi`` term
    falls off the top (× 2^64), so the 64-bit product reduces to three
    32-bit multiplies plus one carry."""
    lo = lo ^ byte_u32
    carry_hi, new_lo = _mul32(lo, _PRIME_LO)
    new_hi = hi * _PRIME_LO + lo * _PRIME_HI + carry_hi
    return new_hi, new_lo


def seed_state(seed: int):
    """Initial (hi, lo) limbs: ``FNV_OFFSET ^ seed`` (the seeded offset
    basis every host path uses)."""
    h = FNV_OFFSET ^ (int(seed) & 0xFFFFFFFFFFFFFFFF)
    return np.uint32(h >> 32), np.uint32(h & 0xFFFFFFFF)


def fold_mod(hi, lo, vocab_size: int):
    """xor-fold + mod-vocab on limb pairs — ``hash_to_vocab`` /
    ``FoldToVocab`` exactly: ``f = h ^ (h >> 32); f % V``. Requires
    ``V <= 2^16`` so every partial stays inside uint32:
    ``f mod V = ((f_hi mod V) * (2^32 mod V) + f_lo mod V) mod V``,
    and ``(V-1) * (2^32 mod V) < 2^32`` at that bound."""
    if vocab_size > (1 << 16):
        raise ValueError(
            f"device fold-to-vocab carries vocab_size <= 2^16, got "
            f"{vocab_size} (the bytes wire degrades to ragged there — "
            f"ingest.use_bytes_wire)")
    v = np.uint32(vocab_size)
    m32 = np.uint32((1 << 32) % vocab_size)
    f_lo = lo ^ hi  # folded low limb; the high limb is hi unchanged
    return (((hi % v) * m32 + (f_lo % v)) % v).astype(jnp.int32)


def aligned_byte_lengths(blens, align: int):
    """Slab bytes each doc occupies: ``ceil((blen + 1) / align) *
    align`` — the ``+ 1`` reserves the guaranteed inter-doc fill byte
    (a space), so adjacent documents can never concatenate into one
    token. THE layout rule; both packers and the device decode call
    this (numpy and jnp arrays both work)."""
    mod = jnp if isinstance(blens, jax.Array) else np
    return (mod.maximum(blens, 0) + align) // align * align


def token_starts(slab, blens, *, length: int, align: int):
    """Shared tokenization stage of both hash lowerings: one
    vectorized scan over the slab derives, per document, the byte
    positions of its first ``length`` tokens.

    Args:
      slab: uint8/int32 ``[N]`` byte slab (layout contract above).
      blens: int32 ``[D]`` raw byte length per doc.
      length: static token cap L (``max_per_doc``).
      align: the slab granule (``ingest._wire_align``).

    Returns ``(starts, valid, lengths, bytes_i32)``: int32 ``[D, L]``
    token start positions (invalid slots point at slab pad — a space,
    so the hash loop consumes nothing there), bool ``[D, L]`` validity,
    int32 ``[D]`` per-doc token counts capped at L (the host packers'
    ``lengths`` contract), and the upcast ``[N]`` byte array for the
    hash stage to gather from.
    """
    n = slab.shape[0]
    d = blens.shape[0]
    b = slab.astype(jnp.int32)
    sp = is_space(b)
    # Token starts: a non-space byte whose predecessor is whitespace
    # (position 0 is doc 0's first byte — the layout guarantees it).
    start = (~sp) & jnp.concatenate(
        [jnp.ones((1,), jnp.bool_), sp[:-1]])
    start_i = start.astype(jnp.int32)
    albl = aligned_byte_lengths(blens, align)
    offs_ext = jnp.concatenate(
        [jnp.zeros((1,), jnp.int32),
         jnp.cumsum(albl, dtype=jnp.int32)])          # [D + 1]
    cum = jnp.cumsum(start_i)                          # inclusive [N]
    # starts strictly before byte i, extended so index N is legal
    # (a doc whose offset equals the slab total holds no bytes).
    cum_ex = jnp.concatenate([cum - start_i, cum[-1:]])  # [N + 1]
    base = cum_ex[jnp.minimum(offs_ext, n)]            # [D + 1]
    lengths = jnp.minimum(base[1:] - base[:-1], length)
    # Per-byte doc id (only consulted at start bytes; fill bytes are
    # whitespace so pad/tail positions never carry a start). Among
    # equal offsets — empty docs — searchsorted(right) lands on the
    # last, which is exactly the doc that owns the bytes there.
    did = jnp.clip(
        jnp.searchsorted(offs_ext[:-1],
                         jnp.arange(n, dtype=jnp.int32),
                         side="right") - 1, 0, d - 1)
    k = cum - 1 - base[did]   # 0-based token ordinal within its doc
    # Scatter the first L start positions into [D, L]; everything else
    # (non-starts, ordinals past L) collides on the sentinel slot that
    # the slice below discards. Default n - 1 points at slab pad — a
    # space — so invalid slots hash nothing even without the mask.
    tgt = jnp.where(start & (k < length), did * length + k, d * length)
    flat = jnp.full((d * length + 1,), n - 1, jnp.int32) \
        .at[tgt].set(jnp.arange(n, dtype=jnp.int32))
    starts = flat[:d * length].reshape(d, length)
    valid = jnp.arange(length, dtype=jnp.int32)[None, :] \
        < lengths[:, None]
    return starts, valid, lengths, b


def hash_tokens_xla(bytes_i32, starts, valid, *, vocab_size: int,
                    seed: int, truncate_at):
    """The portable hash stage: a masked ``lax.while_loop`` whose trip
    count is the longest live token in the chunk (exact for ANY token
    length — no static byte cap). Each iteration gathers one byte per
    (doc, slot), folds it into the FNV limbs where the token is still
    alive, and kills tokens at their first whitespace byte (or at
    ``truncate_at`` bytes — the host packers hash the truncated
    prefix, ``TokenizeHashInto``)."""
    n = bytes_i32.shape[0]
    hi0, lo0 = seed_state(seed)
    hi = jnp.full(starts.shape, hi0, jnp.uint32)
    lo = jnp.full(starts.shape, lo0, jnp.uint32)

    def cond(c):
        return jnp.any(c[1])

    def body(c):
        j, alive, hi, lo = c
        pos = starts + j
        byte = bytes_i32[jnp.minimum(pos, n - 1)]
        consume = alive & ~is_space(byte) & (pos < n)
        if truncate_at:
            consume &= j < truncate_at
        nhi, nlo = fnv1a_step(hi, lo, byte.astype(jnp.uint32))
        return (j + 1, consume, jnp.where(consume, nhi, hi),
                jnp.where(consume, nlo, lo))

    _, _, hi, lo = lax.while_loop(
        cond, body, (jnp.int32(0), valid, hi, lo))
    ids = fold_mod(hi, lo, vocab_size)
    # Padding slots zero-filled — the host packers' buffer contract
    # (np.zeros / memset), so whole-batch comparisons are exact.
    return jnp.where(valid, ids, 0)


@functools.partial(jax.jit,
                   static_argnames=("length", "vocab_size", "seed",
                                    "truncate_at", "align", "method",
                                    "interpret"))
def tokenize_hash_device(slab, blens, *, length: int, vocab_size: int,
                         seed: int = 0, truncate_at=None,
                         align: int = 16, method: str = "xla",
                         interpret: bool = False):
    """Raw byte slab -> the host packer's ``(token_ids [D, L] int32,
    lengths [D] int32)`` pair, entirely on device. ``method`` selects
    the hash lowering (:func:`tokenize_method`); tokenization itself
    (:func:`token_starts`) is shared, so the lowerings agree by
    construction on everything but the per-byte loop."""
    starts, valid, lengths, b = token_starts(slab, blens,
                                             length=length, align=align)
    if method == "pallas":
        from tfidf_tpu.ops.pallas_kernels import tokenize_hash_pallas
        ids = tokenize_hash_pallas(b, starts, lengths,
                                   vocab_size=vocab_size, seed=seed,
                                   truncate_at=truncate_at or 0,
                                   interpret=interpret)
    else:
        ids = hash_tokens_xla(b, starts, valid, vocab_size=vocab_size,
                              seed=seed, truncate_at=truncate_at)
    return ids, lengths
