"""Zero-allocation query staging: a pinned host ring feeding one
donated H2D copy per search batch.

The serve hot path used to pay three avoidable costs per dispatch: a
fresh ``np.zeros((V, Q))`` query block (16 MB at the 64-query bucket),
per-query ``bincount``/temporary arrays inside ``query_matrix``, and
an untracked ``jnp.asarray`` upload. Steady state should pay none of
them: the pow2 query-count bucketing (round 9) means there are only
``log2(block)+1`` distinct block shapes per index, so the staging
buffers are perfectly reusable.

:class:`QuerySlab` holds, per pow2 bucket, a small FIFO ring of host
staging buffers (plus one ``[V]`` float32 norm scratch each). A search
checks a slot out, fills it IN PLACE through
:func:`~tfidf_tpu.models.retrieval.fill_query_matrix` (the same
float-op sequence ``query_matrix`` runs — bit-identical columns, one
implementation), uploads it with EXACTLY ONE ``jax.device_put`` inside
a byte-stamped ``h2d`` span, and releases the slot once the result has
materialized (by which point the copy is provably consumed — the
use-after-donate guard). The device side of the slab is the donated
``qmat`` argument of the search program: donation recycles the same
device allocation batch over batch, so steady-state serving holds one
persistent device block per bucket and allocates nothing on either
side of the link.

Ring behavior: slots are reused FIFO; when every slot of a bucket is
checked out (N concurrent searches), a fresh slot is allocated and the
``allocs`` counter ticks — so after warm-up ``allocs`` goes flat and
``serve_bench --ab-slab`` can print ``allocs/batch = 0`` as a measured
receipt, not a promise. Batches wider than ``max_bucket`` fall back to
the legacy allocating path (callers check :attr:`max_bucket`).

Env knob ``TFIDF_TPU_QUERY_SLAB`` (CLI ``--query-slab``): ``0``/
``off``/``false`` disables, anything else (and unset) enables.
"""

from __future__ import annotations

import collections
import os
import threading
from typing import Dict, List, Tuple

import numpy as np


def use_query_slab(explicit=None) -> bool:
    """Resolve the slab knob: explicit setting > env > on."""
    if explicit is not None:
        return bool(explicit)
    raw = os.environ.get("TFIDF_TPU_QUERY_SLAB", "").strip().lower()
    return raw not in ("0", "off", "false", "no")


class QuerySlab:
    """Per-bucket host staging rings + the slab counters.

    Thread-safe: checkout/release take the slab lock; the fill and the
    upload happen OUTSIDE it on the checked-out slot, so concurrent
    searches at the same bucket stage through distinct buffers.
    """

    def __init__(self, vocab_size: int, max_bucket: int,
                 min_depth: int = 1):
        if max_bucket < 1:
            raise ValueError("max_bucket must be >= 1")
        if min_depth < 1:
            raise ValueError("min_depth must be >= 1")
        self.vocab_size = int(vocab_size)
        # Next pow2 at or above the query-block bound, so every bucket
        # the search path can produce has a ring.
        self.max_bucket = 1 << max(0, int(max_bucket) - 1).bit_length()
        # Pipelined serving (round 22) keeps up to ``pipeline_depth``
        # batches checked out at once; a ring provisioned to that
        # depth on FIRST touch makes the concurrent steady state
        # allocation-free too (allocs stays flat after warm-up even
        # with the window full).
        self.min_depth = int(min_depth)
        self._lock = threading.Lock()
        self._free: Dict[int, collections.deque] = {}
        self._slots: Dict[int, List[Tuple[np.ndarray, np.ndarray]]] = {}
        # Receipts (read by serve_bench --ab-slab and the tests):
        self.allocs = 0       # fresh staging-buffer allocations
        self.packs = 0        # checkouts = batches staged via the slab
        self.h2d_copies = 0   # device_put calls (must equal packs)
        self.bytes_h2d = 0
        self.fallbacks = 0    # oversize batches the caller routed away

    def checkout(self, bucket: int):
        """-> (buf [V, bucket] f32, scratch [V] f32, slot key).

        Reuses the oldest FREE slot of the bucket's ring (FIFO — the
        wraparound order the tests pin) or allocates a fresh one when
        every slot is in flight."""
        if bucket > self.max_bucket:
            raise ValueError(f"bucket {bucket} > max_bucket "
                             f"{self.max_bucket} — caller must take "
                             f"the legacy path (note_fallback)")
        with self._lock:
            free = self._free.setdefault(bucket, collections.deque())
            slots = self._slots.setdefault(bucket, [])
            if not slots:
                self._top_up(bucket, self.min_depth)
            if free:
                idx = free.popleft()
            else:
                self._top_up(bucket, len(slots) + 1)
                idx = free.popleft()
            self.packs += 1
            buf, scratch = slots[idx]
        return buf, scratch, (bucket, idx)

    def _top_up(self, bucket: int, depth: int) -> None:
        """Grow the bucket's ring to ``depth`` slots (lock held)."""
        free = self._free[bucket]
        slots = self._slots[bucket]
        while len(slots) < depth:
            slots.append((
                np.zeros((self.vocab_size, bucket), np.float32),
                np.zeros((self.vocab_size,), np.float32)))
            free.append(len(slots) - 1)
            self.allocs += 1

    def reserve(self, depth: int) -> None:
        """Raise :attr:`min_depth` to ``depth`` and top every
        already-touched ring up to it — the serve layer calls this
        with its pipeline depth so the in-flight window never forces
        a mid-stream allocation."""
        if depth < 1:
            raise ValueError("depth must be >= 1")
        with self._lock:
            self.min_depth = max(self.min_depth, int(depth))
            for bucket in self._slots:
                self._top_up(bucket, self.min_depth)

    def release(self, slot) -> None:
        bucket, idx = slot
        with self._lock:
            self._free[bucket].append(idx)

    def note_h2d(self, nbytes: int) -> None:
        with self._lock:
            self.h2d_copies += 1
            self.bytes_h2d += int(nbytes)

    def note_fallback(self) -> None:
        with self._lock:
            self.fallbacks += 1

    def ring_depth(self, bucket: int) -> int:
        with self._lock:
            return len(self._slots.get(bucket, ()))

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {
                "allocs": self.allocs,
                "packs": self.packs,
                "h2d_copies": self.h2d_copies,
                "bytes_h2d": self.bytes_h2d,
                "fallbacks": self.fallbacks,
                "buffers": sum(len(s) for s in self._slots.values()),
            }
