"""Term-frequency and document-frequency histograms.

The reference builds TF with a per-token linear scan over an append-only
table (``TFIDF.c:150-167``) — O(tokens x distinct-words) per document —
and DF with a second linear-scan table deduplicated by a ``currDoc``
field (``TFIDF.c:169-188``). On TPU both collapse into one masked
scatter-add over the hashed vocab: O(tokens), fixed shapes, and the DF
"dedup by document" falls out of thresholding the TF histogram
(``df = sum_d [tf[d, v] > 0]``) instead of being tracked token-by-token.

All shapes here are static (XLA requirement): token batches are padded to
``[D, L]`` and padding is masked via a sentinel bucket that is sliced off,
never branched on.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def tf_counts_masked(token_ids: jax.Array, valid: jax.Array,
                     vocab_size: int, id_offset=0) -> jax.Array:
    """Histogram of ``token_ids - id_offset`` where ``valid``, else dropped.

    The workhorse behind both the dense path and the sharded path: with a
    vocab-sharded mesh each shard passes its own ``id_offset`` and width
    ``vocab_size``; out-of-range ids (another shard's words) and padding
    both fall into the sentinel bucket and are sliced off.
    """
    d, _ = token_ids.shape
    # Normalize the wire format here, the one entry point every histogram
    # path funnels through: uint16-packed batches cannot represent the
    # sentinel bucket V when V == 2^16, and id - id_offset must not wrap.
    local = token_ids.astype(jnp.int32) - id_offset
    in_range = valid & (local >= 0) & (local < vocab_size)
    safe = jnp.where(in_range, local, vocab_size)
    counts = jnp.zeros((d, vocab_size + 1), jnp.int32)
    counts = counts.at[jnp.arange(d)[:, None], safe].add(1)
    return counts[:, :vocab_size]


def tf_counts(token_ids: jax.Array, lengths: jax.Array, vocab_size: int) -> jax.Array:
    """Per-document term-frequency histogram.

    Args:
      token_ids: int32 [D, L] vocab ids, padded arbitrarily past each
        document's length.
      lengths: int32 [D] live token counts.
      vocab_size: static vocabulary size V.

    Returns:
      int32 [D, V] counts; ``counts[d].sum() == lengths[d]`` (property
      test pins this — the reference's ``docSize`` invariant,
      ``TFIDF.c:141-143``).

    Padding handling: padded positions are redirected to a sentinel
    bucket V which is sliced away — no data-dependent control flow, so
    the op stays a single fused scatter under ``jit``.
    """
    _, length = token_ids.shape
    mask = jnp.arange(length, dtype=lengths.dtype)[None, :] < lengths[:, None]
    return tf_counts_masked(token_ids, mask, vocab_size)


def presence(counts: jax.Array) -> jax.Array:
    """int32 [D, V] -> int32 [D, V] 0/1 presence matrix (word-in-doc)."""
    return (counts > 0).astype(jnp.int32)


def df_from_counts(counts: jax.Array) -> jax.Array:
    """Local document-frequency vector from a shard's TF counts.

    int32 [D, V] -> int32 [V]: number of *local* documents containing
    each word. The global DF is the mesh-wide ``lax.psum`` of this
    (``parallel.collectives.global_df``) — the one-collective replacement
    for the reference's CustomReduce+Bcast pair (``TFIDF.c:215,220``).
    """
    return presence(counts).sum(axis=0)


def tf_counts_chunked(token_ids: jax.Array, lengths: jax.Array, vocab_size: int,
                      chunk: int) -> jax.Array:
    """TF histogram with the token axis processed in fixed chunks.

    Same result as :func:`tf_counts`, but the [D, L] batch is folded to
    [D, L/chunk, chunk] and reduced with ``lax.scan`` over chunks —
    bounding live memory at [D, V] + [D, chunk] regardless of L. This is
    the single-device half of the long-document story (SURVEY §5): a doc
    whose token stream exceeds one chip's memory shards its *chunks*
    across a mesh axis and psums the partial histograms
    (``parallel.longdoc``).
    """
    d, length = token_ids.shape
    if length % chunk != 0:
        raise ValueError(f"token axis {length} not divisible by chunk {chunk}")
    n_chunks = length // chunk
    toks = token_ids.reshape(d, n_chunks, chunk).transpose(1, 0, 2)
    offsets = jnp.arange(n_chunks, dtype=lengths.dtype) * chunk

    def step(acc, inp):
        toks_c, off = inp
        rem = jnp.clip(lengths - off, 0, chunk)
        acc = acc + tf_counts(toks_c, rem, vocab_size)
        return acc, None

    init = jnp.zeros((d, vocab_size), jnp.int32)
    out, _ = jax.lax.scan(step, init, (toks, offsets))
    return out
