"""Word -> vocabulary-id hashing.

The reference keys every table by raw strings and resolves ids by linear
scan (``TFIDF.c:150-188``), which makes its DF aggregation a string-keyed
set union (``CustomReduce``, ``TFIDF.c:291-319``). Hashing words to a
fixed integer vocabulary up front collapses all of that: TF/DF tables
become dense (or sparse) arrays, and the set-union-with-sum becomes a
plain vector add that ``lax.psum`` handles over ICI (SURVEY §2.4).

Two hash paths:

* ``fnv1a_hash_words``: host-side, vectorized NumPy FNV-1a-64 over a list
  of byte-string tokens. Used by the whitespace-tokenizer loader.
* ``device_ngram_ids``: device-side polynomial rolling hash over raw
  document bytes, producing char n-gram ids without ever materializing
  n-gram strings on host (BASELINE config 4).
"""

from __future__ import annotations

from typing import Sequence

import jax.numpy as jnp
import numpy as np

_FNV_OFFSET = np.uint64(14695981039346656037)
_FNV_PRIME = np.uint64(1099511628211)


def fnv1a_hash_words(words: Sequence[bytes], seed: int = 0) -> np.ndarray:
    """64-bit FNV-1a of each byte-string, vectorized across words.

    The per-word byte loop is vectorized across the word axis: words are
    packed into a padded [N, max_len] byte matrix and the hash state is
    updated column-by-column, masked by word length — O(max_len) NumPy
    steps regardless of N. ``seed`` perturbs the offset basis so collision
    structure can be re-rolled.
    """
    if len(words) == 0:
        return np.zeros((0,), dtype=np.uint64)
    lens = np.fromiter((len(w) for w in words), count=len(words), dtype=np.int64)
    max_len = int(lens.max(initial=0))
    mat = np.zeros((len(words), max_len), dtype=np.uint8)
    for i, w in enumerate(words):
        mat[i, : len(w)] = np.frombuffer(w, dtype=np.uint8)
    h = np.full(len(words), _FNV_OFFSET ^ np.uint64(seed), dtype=np.uint64)
    with np.errstate(over="ignore"):
        for j in range(max_len):
            live = j < lens
            hj = (h ^ mat[:, j].astype(np.uint64)) * _FNV_PRIME
            h = np.where(live, hj, h)
    return h


def hash_to_vocab(hashes: np.ndarray, vocab_size: int) -> np.ndarray:
    """Fold 64-bit hashes into [0, vocab_size) with an xor-fold.

    Plain ``% vocab_size`` on a power-of-two vocab keeps only the low
    bits; xor-folding the high word in first preserves entropy from the
    full hash (FNV's low bits alone are weak for power-of-two tables).
    """
    folded = hashes ^ (hashes >> np.uint64(32))
    return (folded % np.uint64(vocab_size)).astype(np.int32)


def words_to_ids(words: Sequence[bytes], vocab_size: int, seed: int = 0) -> np.ndarray:
    """Convenience: FNV-1a + fold, the hashed-vocab loader path."""
    return hash_to_vocab(fnv1a_hash_words(words, seed), vocab_size)


# ---------------------------------------------------------------------------
# Device-side char n-gram ids (BASELINE config 4).
# ---------------------------------------------------------------------------

# Multiplier for the polynomial rolling hash; odd so it is invertible
# mod 2^32 and entropy is not lost as windows accumulate.
_POLY = np.uint32(0x01000193)  # FNV-32 prime reused as the polynomial base


def device_ngram_ids(doc_bytes, doc_len, n: int, vocab_size: int, seed: int = 0):
    """Ids of all length-``n`` byte windows of a document batch, on device.

    Args:
      doc_bytes: uint8/int32 array [..., L] — raw documents, zero-padded.
      doc_len: int array broadcastable to [...] — live byte counts.
      n: window size (static).
      vocab_size: fold target (static).
      seed: hash seed (static).

    Returns:
      (ids, valid): int32 [..., L] window ids (position i = window
      starting at i) and bool [..., L] validity (windows inside doc_len).
      Shapes stay static; invalid tail windows are masked — the TPU idiom
      for the ragged output (SURVEY §7 "ragged docs").

    The hash is a polynomial rolling hash (NOT FNV-1a: Horner form maps
    to n fused multiply-xor vector steps with no per-window inner loop),
    so hashed-chargram ids differ from the host FNV path's — both are
    valid "hashed vocab" universes; tests pin each against its own
    reference.
    """
    return device_ngram_ids_multi(doc_bytes, doc_len, n, n, vocab_size,
                                  seed)[0]


def device_ngram_ids_multi(doc_bytes, doc_len, lo: int, hi: int,
                           vocab_size: int, seed: int = 0):
    """:func:`device_ngram_ids` for EVERY n in [lo, hi] from ONE Horner
    sweep — the fused chargram id generator (VERDICT r4 item 6).

    The length-(n+1) window's Horner state extends the length-n one by
    a single (shift, xor, multiply) step: ``h_{n+1} = (h_n ^ b[i+n]) *
    POLY``. Emitting each requested n from the shared sweep costs ``hi``
    elementwise passes over the byte batch instead of the per-n loops'
    ``lo + ... + hi`` — e.g. 12 -> 5 for the 3..5 default — and shares
    every ``jnp.roll``. Outputs are bit-identical to per-n calls (the
    finalizer ``h ^= h >> 16`` is applied to a copy at each emit), so
    the two entry points can never drift; pinned by tests.

    Returns: list of (ids, valid) pairs, index 0 = n == lo.
    """
    b = doc_bytes.astype(jnp.uint32)
    length = b.shape[-1]
    h = jnp.full(b.shape, np.uint32(seed) ^ np.uint32(0x811C9DC5),
                 dtype=jnp.uint32)
    pos = jnp.arange(length)
    dl = jnp.asarray(doc_len)[..., None]
    out = []
    for j in range(hi):
        shifted = jnp.roll(b, -j, axis=-1)  # window byte j per start pos
        h = (h ^ shifted) * _POLY
        n = j + 1
        if n >= lo:
            f = h ^ (h >> 16)
            out.append(((f % np.uint32(vocab_size)).astype(jnp.int32),
                        pos + n <= dl))
    return out
