"""Sparse term-document scoring (BASELINE config 3).

At 1M docs x 2^16 vocab the dense [D, V] counts/score matrices are
~260 GB — but each document holds at most L distinct terms, so the
information content is O(D x L). This module computes TF-IDF entirely in
a row-sparse layout: per document, a padded list of (term id, count)
pairs derived by sort + run-length encoding — never materializing [D, V].

This is also where the reference's asymptotics get fixed a second time:
its per-token linear probe is O(T x V_doc) (``TFIDF.c:150-167``); the
dense path here is O(T) scatter but O(D x V) memory; the sparse path is
O(T log T) compute and O(T) memory.

Interop: :func:`to_bcoo` exports the same data as a
``jax.experimental.sparse.BCOO`` matrix for downstream sparse matmuls
(e.g. term-document similarity against a query matrix on the MXU).

All ops are batch-first with static shapes; rows are independent, so the
document axis shards exactly like the dense path (``parallel``).
"""

from __future__ import annotations

import functools
import os
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import sparse as jsparse


def sorted_term_counts(token_ids: jax.Array, lengths: jax.Array
                       ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Row-sparse term counts via sort + run-length encoding.

    Args:
      token_ids: int32 [D, L] vocab ids (any values past lengths).
      lengths: int32 [D].

    Returns:
      (ids, counts, head): each [D, L].
      ``head[d, i]`` marks the first slot of each distinct term's run in
      the sorted row; at head slots ``ids`` is the term and ``counts``
      its in-document frequency. Non-head slots must be masked by
      consumers. Padding sorts to the row tail as id ``INT32_MAX``.
    """
    token_ids = token_ids.astype(jnp.int32)  # ids may arrive as uint16
    pos = jnp.arange(token_ids.shape[1], dtype=jnp.int32)[None, :]
    return _sorted_counts_core(token_ids, pos < lengths[:, None], lengths)


def sorted_term_counts_masked(token_ids: jax.Array, valid: jax.Array
                              ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """:func:`sorted_term_counts` for NON-CONTIGUOUS validity — e.g.
    the concatenated n-gram id streams of the device chargram, where
    each n contributes its own masked tail. Returns the same (ids,
    counts, head) triple; post-sort the live entries occupy each row's
    prefix regardless of where the mask's holes were."""
    return _sorted_counts_core(token_ids.astype(jnp.int32), valid,
                               valid.sum(axis=1, dtype=jnp.int32))


def sorted_term_counts_host(token_ids, lengths):
    """Numpy mirror of :func:`sorted_term_counts`, bit-identical by
    construction (pure integer sort/compare/cumulative ops — pinned by
    tests/test_index.py). The segmented index (``tfidf_tpu/index``)
    derives each delta document's triple on HOST with this, so a
    streaming add never traces a fresh device program per batch size —
    the zero-recompiles-under-mutation contract rides on it."""
    import numpy as np
    token_ids = np.asarray(token_ids, np.int32)
    lengths = np.asarray(lengths, np.int32)
    d, length = token_ids.shape
    pos = np.arange(length, dtype=np.int32)[None, :]
    live = pos < lengths[:, None]
    sentinel = np.iinfo(np.int32).max
    sorted_ids = np.sort(
        np.where(live, token_ids, sentinel), axis=1).astype(np.int32)
    prev = np.concatenate(
        [np.full((d, 1), -1, np.int32), sorted_ids[:, :-1]], axis=1)
    head = live & (sorted_ids != prev)
    hpos = np.where(head, pos, length).astype(np.int32)
    suffix_min = np.minimum.accumulate(hpos[:, ::-1], axis=1)[:, ::-1]
    next_head = np.concatenate(
        [suffix_min[:, 1:], np.full((d, 1), length, np.int32)], axis=1)
    counts = (np.minimum(next_head, lengths[:, None]) - pos).astype(
        np.int32)
    return sorted_ids, counts, head


def _sorted_counts_core(token_ids, valid, lengths):
    d, length = token_ids.shape
    pos = jnp.arange(length, dtype=jnp.int32)[None, :]
    sentinel = jnp.iinfo(jnp.int32).max
    sorted_ids = jnp.sort(jnp.where(valid, token_ids, sentinel), axis=1)
    # Post-sort validity: sentinels sort to the tail, so the first
    # lengths[d] (= live count) slots are exactly the live ones — true
    # for BOTH the contiguous-prefix and the masked entry paths.
    live = pos < lengths[:, None]
    prev = jnp.concatenate(
        [jnp.full((d, 1), -1, sorted_ids.dtype), sorted_ids[:, :-1]], axis=1)
    head = live & (sorted_ids != prev)
    # Run length at a head slot = (next head position, clipped to the
    # live prefix) - own position: an exclusive suffix-min over head
    # positions. Pure cumulative/elementwise ops — no scatter, which on
    # TPU serializes (counts at non-head slots are garbage by contract).
    hpos = jnp.where(head, pos, length)
    suffix_min = lax.cummin(hpos[:, ::-1], axis=1)[:, ::-1]
    next_head = jnp.concatenate(
        [suffix_min[:, 1:], jnp.full((d, 1), length, jnp.int32)], axis=1)
    counts = jnp.minimum(next_head, lengths[:, None]) - pos
    return sorted_ids, counts, head


def sparse_df(ids: jax.Array, head: jax.Array, vocab_size: int,
              method: Optional[str] = None) -> jax.Array:
    """Document-frequency vector from row-sparse terms.

    The ``currDoc`` dedup (``TFIDF.c:171-188``) is already encoded in
    ``head`` (one head per distinct term per doc), so DF is a histogram
    of the head-masked ids. Two lowerings:

    * ``"scatter"`` — one scatter-add. Fine on CPU; on TPU a
      non-unique-index scatter serializes into sorted runs.
    * ``"sort"`` — globally sort the masked ids and take the difference
      of ``searchsorted`` bin edges: only sort + vectorized binary
      search, the ops the TPU backend is actually good at.

    ``method=None`` picks by backend (sort on TPU, scatter elsewhere),
    overridable via ``TFIDF_TPU_DF_METHOD``; both produce identical
    counts (pinned by tests). The choice is resolved at *trace* time:
    callers that jit this (ingest, retrieval) bake it into their cached
    executable, so set the env var before the first call of a shape.
    """
    if method is None:
        method = os.environ.get("TFIDF_TPU_DF_METHOD") or (
            "sort" if jax.default_backend() == "tpu" else "scatter")
    if method == "scatter":
        safe = jnp.where(head, ids, vocab_size)
        df = jnp.zeros((vocab_size + 1,), jnp.int32)
        df = df.at[safe.reshape(-1)].add(head.reshape(-1).astype(jnp.int32))
        return df[:vocab_size]
    if method != "sort":
        raise ValueError(f"unknown sparse_df method {method!r}")
    masked = jnp.where(head, ids, jnp.iinfo(jnp.int32).max).reshape(-1)
    srt = jnp.sort(masked)
    edges = jnp.arange(vocab_size + 1, dtype=jnp.int32)
    pos = jnp.searchsorted(srt, edges)
    return (pos[1:] - pos[:-1]).astype(jnp.int32)


def sparse_scores(ids: jax.Array, counts: jax.Array, head: jax.Array,
                  lengths: jax.Array, idf: jax.Array) -> jax.Array:
    """Row-sparse TF-IDF: [D, L] scores aligned with ``ids``.

    ``score[d, i] = counts[d, i]/docSize[d] * idf[ids[d, i]]`` at head
    slots, 0 elsewhere. The DF join that the reference does by linear
    string search per record (``TFIDF.c:227-234``) is one gather.
    """
    dtype = idf.dtype
    lens = jnp.maximum(lengths, 1).astype(dtype)[:, None]
    safe = jnp.where(head, ids, 0)
    score = counts.astype(dtype) / lens * idf[safe]
    return jnp.where(head, score, jnp.zeros((), dtype))


def join_method(explicit: Optional[str] = None) -> str:
    """Resolve the DF->score join lowering: ``"sort"`` (sort-join, the
    measured TPU default) or ``"gather"`` ([V]-table gather, the CPU
    default and the mesh/streaming path where the DF vector is NOT
    derivable from the local triples). Override via ``TFIDF_TPU_JOIN``.
    Resolved at trace time — same doctrine as :func:`sparse_df`."""
    if explicit is not None:
        return explicit
    method = os.environ.get("TFIDF_TPU_JOIN") or (
        "sort" if jax.default_backend() == "tpu" else "gather")
    if method not in ("sort", "gather"):
        raise ValueError(f"unknown join method {method!r}")
    return method


def df_slot_sorted(ids: jax.Array, head: jax.Array
                   ) -> Tuple[jax.Array, jax.Array]:
    """Per-slot DF join from ONE global sort (no [V] table) — see
    :func:`df_join_sorted`. Returns ``(df_slot [D, L], srt)`` where
    ``srt`` is the sorted head-masked id stream (reusable for the
    :func:`sparse_df` searchsorted lowering)."""
    d, length = ids.shape
    n = d * length
    # Total-slots int32 bound (ADVICE round 5): the resident finish
    # program calls this over EVERY chunk's concatenated rows, and both
    # sorts key on int32 slot indices over the full [D*L] stream — a
    # bound the per-chunk guard (ingest._check_chunk_fits_int32) cannot
    # see. The HBM budget subsumes it in practice (2^31 slots carry
    # ~19 GB of triples), but past it the failure mode would be silent
    # index wraparound, so the entry points raise by name
    # (ingest._check_total_slots_fit_int32) and the bound is
    # re-asserted here at trace time.
    if n >= (1 << 31):
        raise ValueError(
            f"df_slot_sorted over {d} x {length} slots overflows the "
            f"int32 sort-join slot indices (>= 2^31)")
    sentinel = jnp.iinfo(jnp.int32).max
    hm = jnp.where(head, ids, sentinel).reshape(-1)
    slot = jnp.arange(n, dtype=jnp.int32)
    srt, orig = lax.sort((hm, slot), num_keys=1, is_stable=True)
    # Per-element run length: start position via cummax, next start via
    # the exclusive suffix-min (elements between starts hold n).
    start = srt != jnp.concatenate(
        [jnp.full((1,), -1, srt.dtype), srt[:-1]])
    spos = lax.cummax(jnp.where(start, slot, -1))
    nstart = jnp.where(start, slot, n)
    smin = lax.cummin(nstart[::-1])[::-1]
    next_start = jnp.concatenate([smin[1:], jnp.full((1,), n, jnp.int32)])
    df_elem = next_start - spos
    _, df_slot = lax.sort((orig, df_elem), num_keys=1, is_stable=False)
    return df_slot.reshape(d, length), srt


def df_join_sorted(ids: jax.Array, head: jax.Array, vocab_size: int,
                   ) -> Tuple[jax.Array, jax.Array]:
    """DF vector AND per-slot DF join from ONE global sort — the
    TPU-shaped replacement for ``idf[ids]`` (round-5 trace: the [V]-
    table gather over [D*L] slots ran at ~1.7 GB/s, 59.8 ms at the
    bench shape, the single largest device cost; an equal-width sort
    measured 12.5 ms).

    Method: stable-sort the head-masked ids WITH their slot index. In
    sorted order every id's occurrences are one run, so each element's
    run length IS its document frequency (heads are per-doc-distinct —
    the currDoc dedup). Run lengths come from the same cummin trick as
    :func:`sorted_term_counts`; a second sort by slot index inverts the
    permutation, landing each element's DF back on its slot. The [V]
    DF vector falls out of the same sorted array via ``searchsorted``
    bin edges (the :func:`sparse_df` "sort" lowering — identical
    counts).

    Returns ``(df [V], df_slot [D, L])``; ``df_slot`` is garbage at
    non-head slots (the sentinel run's length) — consumers mask by
    ``head``, exactly like the counts contract.
    """
    df_slot, srt = df_slot_sorted(ids, head)
    edges = jnp.arange(vocab_size + 1, dtype=jnp.int32)
    pos = jnp.searchsorted(srt, edges)
    return (pos[1:] - pos[:-1]).astype(jnp.int32), df_slot


def sparse_scores_joined(counts: jax.Array, head: jax.Array,
                         lengths: jax.Array, df_slot: jax.Array,
                         num_docs, dtype) -> jax.Array:
    """:func:`sparse_scores` on a pre-joined per-slot DF (sort-join
    path). Identical values: same integer DF, the same ``idf_from_df``
    formula applied elementwise to the [D, L] join instead of the [V]
    table."""
    from tfidf_tpu.ops.scoring import idf_from_df

    idf_slot = idf_from_df(jnp.where(head, df_slot, 0), num_docs, dtype)
    lens = jnp.maximum(lengths, 1).astype(dtype)[:, None]
    score = counts.astype(dtype) / lens * idf_slot
    return jnp.where(head, score, jnp.zeros((), dtype))


def score_method(explicit: Optional[str] = None) -> str:
    """Resolve the phase-B score+select lowering: ``"xla"`` (the
    measured default — ``sparse_scores`` feeding ``sparse_topk``, which
    XLA fuses into the scoring program) or ``"pallas"`` (the fused
    Mosaic score/top-k kernel, ``ops.pallas_kernels.
    fused_score_topk_pallas`` — in-tree A/B probe: IDF gather, tf*idf,
    and k max-reduce selection rounds in one kernel, no [D, L] score
    materialization outside VMEM and no L-wide top_k sort network).
    Override via ``TFIDF_TPU_SCORE``; trace-time static like
    :func:`join_method`."""
    if explicit is not None:
        return explicit
    method = os.environ.get("TFIDF_TPU_SCORE") or "xla"
    if method not in ("xla", "pallas"):
        raise ValueError(f"unknown TFIDF_TPU_SCORE method {method!r}")
    return method


def score_topk(ids: jax.Array, counts: jax.Array, head: jax.Array,
               lengths: jax.Array, idf: jax.Array, k: int,
               method: Optional[str] = None
               ) -> Tuple[jax.Array, jax.Array]:
    """THE phase-B score+select step (single definition, traceable):
    sorted triples + the final IDF -> per-doc top-k ``(vals, tids)``
    per the :func:`sparse_topk` contract. Routed by
    :func:`score_method`: the XLA lowering or the fused Pallas kernel
    (ids bit-identical, scores allclose — pinned by
    tests/test_finish.py). Every phase-B call site of the overlapped
    ingest and the streaming scorer goes through here, so the
    ``TFIDF_TPU_SCORE`` knob covers the whole stack; mesh bodies keep
    the explicit XLA pair (a Pallas call inside shard_map is not part
    of the probe's scope)."""
    if score_method(method) == "pallas":
        from tfidf_tpu.ops.pallas_kernels import (default_interpret,
                                                  fused_score_topk_pallas)
        return fused_score_topk_pallas(ids, counts, head, lengths, idf,
                                       k=k, interpret=default_interpret())
    scores = sparse_scores(ids, counts, head, lengths, idf)
    return sparse_topk(scores, ids, head, k)


def sparse_topk(scores: jax.Array, ids: jax.Array, head: jax.Array, k: int
                ) -> Tuple[jax.Array, jax.Array]:
    """Per-doc top-k over the row-sparse axis (L candidates, not V)."""
    k = min(k, scores.shape[1])
    neg = jnp.finfo(scores.dtype).min
    vals, sel = lax.top_k(jnp.where(head, scores, neg), k)
    picked = jnp.take_along_axis(ids, sel, axis=1)
    # Mask sub-k docs: a -inf survivor means fewer than k terms existed.
    ok = vals > neg
    return jnp.where(ok, vals, 0), jnp.where(ok, picked, -1)


def sparse_topk_counts(scores: jax.Array, ids: jax.Array,
                       counts: jax.Array, head: jax.Array, k: int
                       ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """:func:`sparse_topk` that also gathers the selected slots' integer
    counts — the exact-ids wire's payload (``ingest._score_pack_wire``):
    with collision-free ids, (count, df) per selection is everything the
    host needs to rescore in exact float64. Invalid slots come back
    (0, -1, 0); a real selection always has count >= 1."""
    k = min(k, scores.shape[1])
    neg = jnp.finfo(scores.dtype).min
    vals, sel = lax.top_k(jnp.where(head, scores, neg), k)
    picked = jnp.take_along_axis(ids, sel, axis=1)
    cnt = jnp.take_along_axis(counts, sel, axis=1)
    ok = vals > neg
    return (jnp.where(ok, vals, 0), jnp.where(ok, picked, -1),
            jnp.where(ok, cnt, 0))


def to_bcoo(ids: jax.Array, counts: jax.Array, head: jax.Array,
            vocab_size: int) -> jsparse.BCOO:
    """Export row-sparse counts as a BCOO [D, V] term-document matrix.

    Dead (non-head) slots become explicit zeros at column 0 — harmless
    for matmul/reduction semantics. nse per row is the static L.
    """
    d, length = ids.shape
    cols = jnp.where(head, ids, 0)[..., None]
    data = jnp.where(head, counts, 0)
    return jsparse.BCOO((data, cols), shape=(d, vocab_size))


# --- tiled retrieval scoring (round 21) ------------------------------
#
# The retrieval score path used to materialize the full [nse, Q] BCOO
# dot intermediate, forcing callers to split query batches host-side
# (the serial 64-wide block loop) — so measured QPS DROPPED as Q grew,
# exactly backwards for a batcher built to coalesce. The tiled lowering
# below scans fixed-size DOC tiles against the full [V, Q] query block:
# the peak intermediate is [tile * L, Q] (bounded regardless of D or
# Q), the whole sweep is ONE compiled dispatch (a lax.scan), and a
# streaming top-k folds across tiles via ops.topk.merge_topk.
#
# Bit-parity with the untiled path is by construction, not luck:
# * rows never split across tiles, so each row's float dot is the
#   same reduction over the same L slots;
# * lax.top_k breaks equal scores by LOWEST index; tiles scan in
#   ascending global-row order and every fold concatenates the carry
#   (lower rows) BEFORE the new tile's candidates (ids ascending
#   within), so lowest-position == lowest-global-row at every step;
# * per-tile retention min(k, tile) keeps every row that could reach
#   the global top-k (a global winner is a winner of its own tile);
# * tail-padding rows score 0 (unmasked; weights and query columns are
#   both >= 0) or the tombstone sentinel (masked) AND sit at the
#   highest global positions, so with >= k real rows they can never
#   displace one.

_TILE_DEFAULT = 4096


def score_tiling(explicit: Optional[str] = None) -> bool:
    """Resolve the tiled-scoring knob: ``TFIDF_TPU_SCORE_TILING``
    (CLI ``--score-tiling``), default ON. ``off`` restores the legacy
    untiled dot + host-side serial query-block split — kept as the
    bit-identical fallback and the A/B baseline (serve_bench
    ``--ab-tiled``). Resolved at CALL time, deliberately NOT trace
    time: the knob selects between two distinct jitted programs, so an
    env toggle flips paths even for already-compiled shapes."""
    raw = (explicit if explicit is not None
           else os.environ.get("TFIDF_TPU_SCORE_TILING", "on"))
    val = str(raw).strip().lower()
    if val in ("on", "1", "true", "yes", ""):
        return True
    if val in ("off", "0", "false", "no"):
        return False
    raise ValueError(
        f"unknown TFIDF_TPU_SCORE_TILING value {raw!r} (on|off)")


def score_tile_rows(d: int, explicit: Optional[int] = None) -> int:
    """Resolve the document-axis tile width (rows per scan step):
    ``TFIDF_TPU_QUERY_BLOCK``, repurposed (round 21) — it used to
    split QUERIES host-side, now it tiles DOCS on device — clamped to
    [1, d]. Default 4096 rows: at the 100k x 256 bench shape the
    per-tile [tile * L, Q] intermediate is ~1 GB at Q=256, inside the
    budget the old 64-query block was chosen for."""
    if explicit is None:
        raw = os.environ.get("TFIDF_TPU_QUERY_BLOCK", "")
        explicit = int(raw) if raw.strip() else _TILE_DEFAULT
    return max(1, min(int(explicit), max(1, int(d))))


def _tile_scores(data_t: jax.Array, cols_t: jax.Array, qmat: jax.Array,
                 method: str) -> jax.Array:
    """One tile's [tile, Q] similarity block: the BCOO sparse x dense
    MXU dot (``"xla"``, bit-identical to the untiled kernel) or the
    fused Mosaic gather-accumulate (``"pallas"`` — the
    ``TFIDF_TPU_SCORE`` probe's scope extended to retrieval; ids
    bit-identical, scores allclose, same contract as phase B)."""
    if method == "pallas":
        from tfidf_tpu.ops.pallas_kernels import (default_interpret,
                                                  tile_scores_pallas)
        return tile_scores_pallas(data_t, cols_t, qmat,
                                  interpret=default_interpret())
    mat = jsparse.BCOO((data_t, cols_t[..., None]),
                       shape=(data_t.shape[0], qmat.shape[0]))
    return jsparse.bcoo_dot_general(
        mat, qmat, dimension_numbers=(((1,), (0,)), ((), ())))


def score_topk_tiled_trace(data: jax.Array, cols: jax.Array,
                           live: Optional[jax.Array], qmat: jax.Array,
                           *, k: int, tile: int, masked: bool,
                           method: str) -> Tuple[jax.Array, jax.Array]:
    """The traceable tiled score+top-k body (see the section comment
    for the parity argument) — embedded by :func:`score_topk_tiled`,
    the retriever's flat-path jit and the mesh shard_map body, so all
    four consumers run ONE definition.

    [D, L] triple x [V, Q] queries -> ([Q, k'], [Q, k']) with
    k' = min(k, D), ids int32 global row indices, columns sorted by
    (score desc, row asc). ``live`` ([D] bool, ``masked=True``) applies
    the tombstone sentinel before selection; padding the caller did NOT
    provide is added here (ragged last tile)."""
    from tfidf_tpu.ops.topk import _DEAD, merge_topk

    d, length = data.shape
    k = min(k, d)
    tile = max(1, min(tile, d))
    n_tiles = -(-d // tile)
    pad = n_tiles * tile - d
    if pad:
        data = jnp.pad(data, ((0, pad), (0, 0)))
        cols = jnp.pad(cols, ((0, pad), (0, 0)))
        if masked:
            live = jnp.pad(live, (0, pad))
    data3 = data.reshape(n_tiles, tile, length)
    cols3 = cols.reshape(n_tiles, tile, length)
    bases = jnp.arange(n_tiles, dtype=jnp.int32) * tile
    kt = min(k, tile)

    def step(carry, xs):
        cvals, cids = carry
        if masked:
            data_t, cols_t, live_t, base = xs
        else:
            data_t, cols_t, base = xs
        sims = _tile_scores(data_t, cols_t, qmat, method).T  # [Q, tile]
        if masked:
            sims = jnp.where(live_t[None, :], sims, _DEAD)
        v, i = lax.top_k(sims, kt)
        # Carry first: its rows precede this tile's globally, so the
        # merge's lowest-position tie-break IS lowest-global-row.
        nv, ni = merge_topk(jnp.concatenate([cvals, v], axis=1),
                            jnp.concatenate([cids, i + base], axis=1),
                            k=k)
        return (nv, ni), None

    q = qmat.shape[1]
    init = (jnp.full((q, k), -jnp.inf, qmat.dtype),
            jnp.zeros((q, k), jnp.int32))
    xs = ((data3, cols3, live.reshape(n_tiles, tile), bases) if masked
          else (data3, cols3, bases))
    (vals, ids), _ = lax.scan(step, init, xs)
    return vals, ids


@functools.partial(jax.jit,
                   static_argnames=("k", "tile", "masked", "method"))
def _score_topk_tiled(data, cols, live, qmat, *, k: int, tile: int,
                      masked: bool, method: str):
    return score_topk_tiled_trace(data, cols, live, qmat, k=k,
                                  tile=tile, masked=masked,
                                  method=method)


def score_topk_tiled(data: jax.Array, cols: jax.Array,
                     live: Optional[jax.Array], qmat: jax.Array,
                     k: int, tile: Optional[int] = None,
                     method: Optional[str] = None
                     ) -> Tuple[jax.Array, jax.Array]:
    """ONE-dispatch tiled score+top-k over a row-sparse block — the
    round-21 retrieval kernel (segmented views stack every sealed
    segment into this single scan: K segments = one dispatch + merge,
    not K). Resolves the tile width (:func:`score_tile_rows`) and the
    score lowering (:func:`score_method`) at call time, then runs the
    jitted :func:`score_topk_tiled_trace`."""
    d = data.shape[0]
    return _score_topk_tiled(data, cols, live, qmat,
                             k=min(int(k), d),
                             tile=score_tile_rows(d, tile),
                             masked=live is not None,
                             method=score_method(method))


def score_topk_tiled_cache_size() -> int:
    """Compiled-program count of the shared tiled search jit — summed
    into ``index_compile_cache_size`` (the mutate bench's recompile
    receipt) and read by the retrieval bench's zero-recompile pin."""
    return _score_topk_tiled._cache_size()


def sparse_forward(token_ids, lengths, num_docs, *, vocab_size: int,
                   score_dtype, topk: Optional[int], df_reduce=None,
                   join: Optional[str] = None):
    """Full sparse pipeline step: tokens -> (df, topk | row-sparse scores).

    Mirrors ``pipeline._forward`` but never builds [D, V]. Returns
    (df, vals, ids) with topk, else (df, ids, counts, head, scores).

    ``df_reduce`` (static): optional collective applied to the local DF
    vector — identity on a single device, a ``lax.psum`` over the docs
    axis inside a shard_map body (``parallel.collectives``). Keeping it a
    parameter means the single-device and sharded engines share this one
    definition and cannot drift.

    ``join`` (static): the DF->score join lowering — ``"sort"``
    (sort-join, measured TPU default) or ``"gather"``; ``None``
    resolves via :func:`join_method`. The sort-join derives each
    slot's DF from the batch's own triples, so it only applies when
    the scoring DF IS the local batch's DF — i.e. ``df_reduce is
    None``; a reduced (mesh-global) DF always takes the gather path.
    """
    from tfidf_tpu.ops.scoring import idf_from_df  # cycle-free late import

    ids, counts, head = sorted_term_counts(token_ids, lengths)
    if df_reduce is None and join_method(join) == "sort":
        df, df_slot = df_join_sorted(ids, head, vocab_size)
        scores = sparse_scores_joined(counts, head, lengths, df_slot,
                                      num_docs, score_dtype)
    else:
        df = sparse_df(ids, head, vocab_size)
        if df_reduce is not None:
            df = df_reduce(df)
        idf = idf_from_df(df, num_docs, score_dtype)
        scores = sparse_scores(ids, counts, head, lengths, idf)
    if topk is not None:
        vals, out_ids = sparse_topk(scores, ids, head, topk)
        return df, vals, out_ids
    return df, ids, counts, head, scores
