"""Device-side top-k selection.

The reference gathers *every* scored line to rank 0 over a serial
``MPI_Recv`` loop and qsorts on host (``TFIDF.c:256-283``) — O(ranks)
latency and O(total records) host memory. At 1M docs that gather dominates
runtime (SURVEY §7 "hard parts"). Here selection happens on device:
``lax.top_k`` per document (and/or globally), so only K records per doc
ever cross the PCIe/host boundary.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
from jax import lax


def topk_per_doc(scores: jax.Array, k: int) -> Tuple[jax.Array, jax.Array]:
    """Top-k (value, vocab-id) per document. [D, V] -> ([D, K], [D, K])."""
    return lax.top_k(scores, k)


def topk_global(scores: jax.Array, k: int) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Global top-k (value, doc-id, vocab-id) over all [D, V] records."""
    d, v = scores.shape
    vals, flat = lax.top_k(scores.reshape(-1), k)
    return vals, flat // v, flat % v


def topk_terms(scores: jax.Array, k: int) -> Tuple[jax.Array, jax.Array]:
    """Top-k *terms* by corpus-summed TF-IDF mass — the recall metric's
    term ranking (BASELINE "top-k term recall vs MPI ref")."""
    per_term = scores.sum(axis=0)
    return lax.top_k(per_term, k)
