"""Device-side top-k selection.

The reference gathers *every* scored line to rank 0 over a serial
``MPI_Recv`` loop and qsorts on host (``TFIDF.c:256-283``) — O(ranks)
latency and O(total records) host memory. At 1M docs that gather dominates
runtime (SURVEY §7 "hard parts"). Here selection happens on device:
``lax.top_k`` per document (and/or globally), so only K records per doc
ever cross the PCIe/host boundary.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import sparse as jsparse

# lax.top_k over a flattened [D*V] stream returns int32 indices, and the
# doc/vocab split (flat // v, flat % v) silently wraps past 2^31 slots —
# the same int32 bound ingest._check_chunk_fits_int32 guards on the
# upload side. Past it topk_global switches to a two-stage selection
# that never builds the D*V flat index (see below).
_INT32_SLOTS = 1 << 31


def topk_per_doc(scores: jax.Array, k: int) -> Tuple[jax.Array, jax.Array]:
    """Top-k (value, vocab-id) per document. [D, V] -> ([D, K], [D, K])."""
    return lax.top_k(scores, k)


def topk_global(scores: jax.Array, k: int
                ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Global top-k (value, doc-id, vocab-id) over all [D, V] records.

    Within the int32 flat bound the lowering is one ``lax.top_k`` over
    the flattened scores. At ``D*V >= 2^31`` that flat index would wrap
    silently, so the selection runs in two stages instead: a per-doc
    top-k first (each document can contribute at most k records to the
    global winners), then a global top-k over the [D, k'] survivors —
    doc ids come from the small k'-wide flat index and vocab ids ride
    along from the per-doc stage, so no D*V index is ever built. Values
    are identical; among EQUAL scores the survivor order may differ
    from the single-stage lowering (both are valid top-k sets).
    """
    d, v = scores.shape
    k = min(k, d * v)
    if d * v < _INT32_SLOTS:
        vals, flat = lax.top_k(scores.reshape(-1), k)
        return vals, flat // v, flat % v
    return _topk_global_two_stage(scores, k)


def _topk_global_two_stage(scores: jax.Array, k: int
                           ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """The beyond-int32 lowering of :func:`topk_global` (also unit-
    tested directly at small shapes, where allocating 2^31 slots is
    impossible). Raises when even the per-doc survivors overflow the
    int32 flat index — a corpus that large must shard the docs axis
    (``parallel``) before selecting globally."""
    d, v = scores.shape
    kk = min(k, v)
    if d * kk >= _INT32_SLOTS:
        raise ValueError(
            f"topk_global over {d} x {v} records: even the per-doc "
            f"top-{kk} survivors ({d * kk} slots) overflow the int32 "
            f"flat selection index (>= 2^31); shard the docs axis "
            f"(parallel) or lower k")
    per_vals, per_ids = lax.top_k(scores, kk)        # [D, kk]
    vals, flat = lax.top_k(per_vals.reshape(-1), k)  # over D*kk < 2^31
    return vals, flat // kk, per_ids.reshape(-1)[flat]


# --- segmented retrieval (round 17): mask, per-segment select, merge.
#
# The LSM-style index (tfidf_tpu/index) scores each segment with the
# SAME BCOO-dot kernel the retriever uses, masks tombstoned rows to a
# sub-zero sentinel BEFORE selection (a deleted doc must never displace
# a live one from the top-k), and merges the per-segment winners with
# one more device top-k. Tie discipline: lax.top_k breaks equal scores
# by LOWEST index, per-segment candidates are concatenated in segment
# (= insertion) order, and within a segment ties already sit in row
# order — so equal-score winners come out in global insertion order,
# exactly the order a from-scratch rebuild of the live corpus (which
# compacts positions but preserves relative order) would pick. That is
# the tie half of the bit-parity contract tests/test_index.py pins.

_DEAD = -1.0  # below any cosine score (>= 0); masked rows lose to all


@functools.partial(jax.jit, static_argnames=("k",))
def masked_topk(scores: jax.Array, live: jax.Array, k: int
                ) -> Tuple[jax.Array, jax.Array]:
    """Top-k over [Q, D] scores with dead docs masked out first.

    ``live`` is the [D] bool tombstone complement; dead columns score
    ``_DEAD`` so they only surface when a row has fewer than k live
    candidates — and then with a negative value the caller's
    ``vals > 0`` result mask drops, same as rebuild padding."""
    masked = jnp.where(live[None, :], scores, _DEAD)
    return lax.top_k(masked, k)


@functools.partial(jax.jit, static_argnames=("k",))
def segment_score_topk(data: jax.Array, cols: jax.Array,
                       live: jax.Array, qmat: jax.Array, k: int
                       ) -> Tuple[jax.Array, jax.Array]:
    """One segment's fused score/top-k: the retriever's BCOO sparse x
    dense MXU matmul (PR 3's kernel, unchanged math) over this
    segment's rows, tombstone mask applied, per-query top-k selected
    on device. [D, L] triple x [V, Q] queries -> ([Q, k], [Q, k])
    with SEGMENT-LOCAL row indices (the caller globalizes by base)."""
    d = data.shape[0]
    mat = jsparse.BCOO((data, cols[..., None]),
                       shape=(d, qmat.shape[0]))
    sims = jsparse.bcoo_dot_general(
        mat, qmat, dimension_numbers=(((1,), (0,)), ((), ())))  # [D, Q]
    masked = jnp.where(live[None, :], sims.T, _DEAD)            # [Q, D]
    return lax.top_k(masked, k)


@functools.partial(jax.jit, static_argnames=("k",))
def merge_topk(vals: jax.Array, ids: jax.Array, k: int
               ) -> Tuple[jax.Array, jax.Array]:
    """Top-k-of-top-k: merge per-segment candidate lists (already
    concatenated along axis 1, in segment order, ids globalized) into
    the final [Q, k] selection — the same primitive the mesh-sharded
    serving of ROADMAP item 1 rides after its all_gather."""
    best, sel = lax.top_k(vals, k)
    return best, jnp.take_along_axis(ids, sel, axis=1)


def topk_terms(scores: jax.Array, k: int) -> Tuple[jax.Array, jax.Array]:
    """Top-k *terms* by corpus-summed TF-IDF mass — the recall metric's
    term ranking (BASELINE "top-k term recall vs MPI ref")."""
    per_term = scores.sum(axis=0)
    return lax.top_k(per_term, k)
