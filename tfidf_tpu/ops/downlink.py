"""Compact device→host result wire: one uint32 word per top-k slot.

The downlink twin of the ragged upload wire (round 6). The reference's
output phase is inherently serial (``TFIDF.c:273-282``); here the
per-doc selection leaves the device as a single contiguous ``[D, K]``
uint32 buffer — a 16-bit score in the high half and the uint16 vocab id
in the low half — so the 32k-doc bench drain ships ~2 MB where the
(int32 id, float32 score) pair wire ships ~4 MB, and the whole buffer
can ride ``copy_to_host_async`` per chunk (``ingest._DrainAhead``).

Word layout (little-endian on the host, XLA bitcast on the device)::

    bits 31..16   score as float16 (bfloat16 when score_dtype is
                  bfloat16 — then the bits are exactly the high half
                  of the float32 score)
    bits 15..0    vocab id as uint16

Validity contract (the same one the pair wire encodes with score -1,
``ingest._score_pack_wire``): valid scores are >= 0 by construction
(idf >= 0, tf > 0 — the reference's invariant, ``TFIDF.c:243``), so a
set SIGN BIT in the score half marks an invalid slot (sub-k docs /
padding rows) and decodes back to the ``(0, -1)`` contract. A
legitimate 0.0 score (word in every doc) survives; NaN scores pass
through as NaN (sign test is False) rather than being misread as
invalid. Scores round to the 16-bit wire format — the packed wire is
bit-exact on ids and within fp16/bf16 rounding on scores; runs that
need full-precision scores select the pair wire
(``--result-wire=pair`` / ``TFIDF_TPU_RESULT_WIRE=pair``), which stays
bit-identical to the pre-packed-wire behavior.

The wire is valid whenever the vocab fits uint16 (``vocab_size <=
2^16``, the bench default) and the canonical score dtype is 16/32-bit
float; :func:`use_packed_result_wire` resolves the auto-fallback to the
pair wire outside that envelope.
"""

from __future__ import annotations

import os
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

# Bytes per selected slot on each wire: the packed word, and the
# (int32 id, score_dtype score) pair the streaming/mesh fetches ship —
# the denominators of the bench's result_wire_ratio artifact field.
PACKED_SLOT_BYTES = 4


def pair_slot_bytes(score_dtype) -> int:
    """Bytes per slot of the (int32 id, score) pair wire — the
    ``bytes_off_wire_pair`` accounting denominator."""
    return 4 + jnp.dtype(jax.dtypes.canonicalize_dtype(
        jnp.dtype(score_dtype))).itemsize


def wire16_dtype(score_dtype):
    """The 16-bit score format of the packed word: bfloat16 when the
    (canonical) score dtype is bfloat16 — its bits are then exactly the
    float32 high half — else float16, whose 10 mantissa bits carry the
    tighter rounding for float32/float16 runs."""
    dt = jax.dtypes.canonicalize_dtype(jnp.dtype(score_dtype))
    return jnp.bfloat16 if dt == jnp.bfloat16 else jnp.float16


def use_packed_result_wire(cfg, vocab_size: Optional[int] = None) -> bool:
    """Resolve one run's device→host result wire from
    ``config.result_wire`` (env override ``TFIDF_TPU_RESULT_WIRE``):
    True = the packed uint32 word wire, False = the (id, score) pair
    wire. ``"packed"`` (the default) degrades to the pair wire when the
    word cannot carry the run: no top-k selection, vocab past 2^16
    (ids overflow the uint16 half), or a 64-bit score ask under
    ``jax_enable_x64`` (a 16-bit score half would butcher it).
    ``"pair"`` forces the bit-identical legacy wire everywhere."""
    choice = (os.environ.get("TFIDF_TPU_RESULT_WIRE")
              or getattr(cfg, "result_wire", "packed"))
    if choice not in ("packed", "pair"):
        raise ValueError(
            f"unknown result wire {choice!r} (TFIDF_TPU_RESULT_WIRE / "
            f"--result-wire: choose 'packed' or 'pair')")
    if choice == "pair" or cfg.topk is None:
        return False
    if (vocab_size if vocab_size is not None
            else cfg.vocab_size) > (1 << 16):
        return False  # the uint16 id half cannot carry the ids
    dt = np.dtype(jax.dtypes.canonicalize_dtype(jnp.dtype(cfg.score_dtype)))
    return dt.itemsize <= 4 and dt.kind == "f"


def downlink_method(explicit: Optional[str] = None) -> str:
    """The device-side word-pack lowering: ``"xla"`` (shift+or, the
    default) or ``"pallas"`` (the Mosaic elementwise kernel,
    ``ops.pallas_kernels.pack_words_pallas`` — in-tree A/B probe).
    Override via ``TFIDF_TPU_DOWNLINK``; trace-time static like
    ``ingest.rebuild_method``."""
    if explicit is not None:
        return explicit
    method = os.environ.get("TFIDF_TPU_DOWNLINK") or "xla"
    if method not in ("xla", "pallas"):
        raise ValueError(f"unknown TFIDF_TPU_DOWNLINK method {method!r}")
    return method


def pack_result_words(vals: jax.Array, tids: jax.Array) -> jax.Array:
    """Device-side pack (traceable): ``(vals, tids)`` per the
    sparse_topk contract → uint32 words. Invalid slots (``tids < 0``)
    pack as (score -1, id 0) — the sign-bit sentinel above."""
    if downlink_method() == "pallas":
        from tfidf_tpu.ops.pallas_kernels import (default_interpret,
                                                  pack_words_pallas)
        return pack_words_pallas(vals, tids,
                                 interpret=default_interpret())
    w16 = wire16_dtype(vals.dtype)
    ok = tids >= 0
    v16 = jnp.where(ok, vals, jnp.asarray(-1, vals.dtype)).astype(w16)
    hi = lax.bitcast_convert_type(v16, jnp.uint16).astype(jnp.uint32)
    lo = jnp.where(ok, tids, 0).astype(jnp.uint16).astype(jnp.uint32)
    return (hi << jnp.uint32(16)) | lo


# Module-level jit so every caller (ingest drain, pipeline fetch,
# streaming score, mesh pre-fetch pack) shares one compiled program per
# shape. Elementwise with no collectives, so it runs as-is on sharded
# global arrays — each device packs its own rows.
pack_words = jax.jit(pack_result_words)


def unpack_result_words(words: np.ndarray, *, score_dtype=np.float32):
    """Host-side decode of the packed word buffer (numpy, runs on the
    drain worker thread): uint32 ``[..., K]`` → ``(vals, tids)`` with
    vals in the canonical ``score_dtype`` and int32 ids. Invalid slots
    (sign bit set in the score half) decode to ``(0, -1)`` — the same
    contract as ``ingest._decode_wire``."""
    words = np.ascontiguousarray(np.asarray(words))
    dt = np.dtype(jax.dtypes.canonicalize_dtype(jnp.dtype(score_dtype)))
    hi = (words >> np.uint32(16)).astype(np.uint16)
    if wire16_dtype(score_dtype) == jnp.bfloat16:
        # bf16 bits ARE the float32 high half: widen by shifting back.
        vals = (hi.astype(np.uint32) << np.uint32(16)).view(np.float32)
    else:
        vals = hi.view(np.float16).astype(np.float32)
    tids = (words & np.uint32(0xFFFF)).astype(np.int32)
    bad = vals < 0  # sign-bit sentinel; NaN compares False and survives
    vals = vals.astype(dt)
    vals[bad] = 0
    tids[bad] = -1
    return vals, tids
