"""IDF and TF-IDF scoring.

Reference semantics (``TFIDF.c:227-246``): for every (word, document)
record, ``TF = wordCount / docSize``, ``IDF = log(numDocs / DF)`` (natural
log, no smoothing — a word present in all documents scores exactly 0,
SURVEY §2.5-10), ``score = TF * IDF``. The reference resolves DF per
record by linear-searching the broadcast table (``TFIDF.c:229-234``);
here the join is a vectorized gather over the dense DF vector.

Device math runs in ``score_dtype`` (float32 by default). Byte-identical
doubles vs the C reference are produced on *host* by the golden formatter
(:mod:`tfidf_tpu.golden`) from the exact integer counts, so the device
never needs float64.

Truncation contract (round 21, VERDICT weak-6): where x64 is
unavailable (``jax_enable_x64`` off — every rig this repo targets), a
``score_dtype="float64"`` request computes, ships and returns
CANONICALIZED float32, bit-identical to asking for float32 outright,
and emits ZERO truncation warnings — every entry point canonicalizes
via :func:`canonical_score_dtype` before the first traced op, so jax's
per-op "will be truncated" UserWarning can never fire. Pinned by
tests/test_tiled_score.py::TestFloat64Truncation.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def canonical_score_dtype(dtype) -> jnp.dtype:
    """The dtype device score math actually runs in: ``dtype`` under
    ``jax_enable_x64``, its truncated twin (float64 -> float32)
    otherwise — resolved silently, before any traced op can warn."""
    return jax.dtypes.canonicalize_dtype(jnp.dtype(dtype))


def idf_from_df(df: jax.Array, num_docs, dtype=jnp.float32) -> jax.Array:
    """``idf[v] = log(num_docs / df[v])``, 0 where df == 0.

    The df==0 guard has no reference analog (impossible by construction
    there, SURVEY §2.5-10) but is required here: the hashed vocab has
    empty buckets.
    """
    dtype = canonical_score_dtype(dtype)
    dff = df.astype(dtype)
    n = jnp.asarray(num_docs, dtype)
    return jnp.where(df > 0, jnp.log(n / jnp.maximum(dff, 1)), jnp.zeros((), dtype))


def tf_matrix(counts: jax.Array, lengths: jax.Array, dtype=jnp.float32) -> jax.Array:
    """``tf[d, v] = counts[d, v] / docSize[d]`` (``TFIDF.c:202``)."""
    dtype = canonical_score_dtype(dtype)
    lens = jnp.maximum(lengths, 1).astype(dtype)
    return counts.astype(dtype) / lens[:, None]


def tfidf_dense(counts: jax.Array, lengths: jax.Array, df: jax.Array,
                num_docs, dtype=jnp.float32) -> jax.Array:
    """Dense [D, V] TF-IDF scores = TF ⊙ broadcast(IDF)."""
    return tf_matrix(counts, lengths, dtype) * idf_from_df(df, num_docs, dtype)[None, :]
