"""Host-side tokenization.

The reference tokenizes with a ``fscanf("%s")`` loop (``TFIDF.c:142-147``):
tokens are maximal runs of non-whitespace bytes, where whitespace is the C
locale's ``isspace`` set (space, \\t, \\n, \\v, \\f, \\r). Python's
``bytes.split()`` with no argument splits on exactly that set, so
``whitespace_tokenize`` is semantics-identical to the reference's scanner
(including treating runs of whitespace as one separator and ignoring
leading/trailing whitespace).

Tokenization is host-side by design: it is IO-bound string work, the one
part of the pipeline that does not belong on the MXU. A native C++
implementation of the same contract lives in ``native/fast_tokenizer.cc``
for the high-throughput loader path; this module is the portable fallback
and the semantics oracle for it.

Char n-grams (BASELINE config 4) have two paths: :func:`char_ngrams`
here materializes n-gram byte-strings on host (the semantics reference,
and what ``pack_corpus`` uses for EXACT-vocab n-gram runs), while the
scalable path ships raw document bytes to device and computes n-gram
*ids* there (``ops.hashing.device_ngram_ids``) — a length-L document
yields ~3L overlapping n-grams, so host materialization triples the
host->device traffic the device path avoids.
"""

from __future__ import annotations

from typing import List, Optional


def whitespace_tokenize(data: bytes, truncate_at: Optional[int] = None) -> List[bytes]:
    """Split a document into whitespace-delimited tokens.

    Matches the reference scanner ``fscanf("%s")`` (``TFIDF.c:142-147``).
    ``truncate_at`` optionally clips each token to that many bytes
    (see ``PipelineConfig.truncate_tokens_at``).
    """
    toks = data.split()
    if truncate_at is not None:
        toks = [t[:truncate_at] for t in toks]
    return toks


def char_ngrams(data: bytes, lo: int, hi: int) -> List[bytes]:
    """All character n-grams of sizes lo..hi, in document order.

    Host reference implementation for tests; the production path computes
    n-gram *ids* on device from the raw byte array
    (``ops.hashing.device_ngram_ids``) without materializing strings.
    N-grams are taken over the raw byte stream including whitespace, which
    matches the common hashing-vectorizer convention rather than any
    reference behaviour (the reference has no n-gram mode).
    """
    if not (0 < lo <= hi):
        raise ValueError(f"bad ngram range ({lo}, {hi})")
    out: List[bytes] = []
    n_bytes = len(data)
    for i in range(n_bytes):
        for n in range(lo, hi + 1):
            if i + n <= n_bytes:
                out.append(data[i : i + n])
    return out
