"""Device and host compute kernels for the TF-IDF pipeline.

Layout mirrors the logical ops layer of the reference (SURVEY §1):
tokenize (``TFIDF.c:142-147``), TF/DF accumulation (``TFIDF.c:147-191``),
scoring (``TFIDF.c:227-246``) — each re-designed as an array op rather
than a linear-scan loop.
"""

from tfidf_tpu.ops.histogram import tf_counts, df_from_counts, presence
from tfidf_tpu.ops.scoring import idf_from_df, tfidf_dense, tf_matrix
from tfidf_tpu.ops.hashing import fnv1a_hash_words, hash_to_vocab
from tfidf_tpu.ops.tokenize import whitespace_tokenize, char_ngrams

__all__ = [
    "tf_counts",
    "df_from_counts",
    "presence",
    "idf_from_df",
    "tfidf_dense",
    "tf_matrix",
    "fnv1a_hash_words",
    "hash_to_vocab",
    "whitespace_tokenize",
    "char_ngrams",
]
