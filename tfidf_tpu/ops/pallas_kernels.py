"""Pallas TPU kernels for the histogram hot path.

The TF histogram is the pipeline's hot op (the reference spends its time
in the equivalent token-scan loop, ``TFIDF.c:147-191``, SURVEY §3.1
"HOT LOOP"). The XLA lowering of the scatter-add in ``ops.histogram`` is
serviceable but scatter on TPU serializes; this kernel reformulates the
histogram as a **compare-and-reduce** over vocab tiles — a dense VPU
pattern with no scatter at all:

    counts[d, v] = sum_l valid[d, l] * (tokens[d, l] == v)

tiled (TILE_D docs x TILE_V vocab lanes) over a grid, streaming the
token axis through VMEM in CHUNK_L slices. DF falls out in the same
pass: the df output block is revisited by every doc-tile grid step and
accumulated in place — TPU grids iterate sequentially, which is exactly
the revisit-and-accumulate idiom.

Lane/sublane shapes follow the TPU tiling table (pallas_guide.md): the
vocab axis rides the 128-wide lane dimension, docs ride sublanes.

MEASURED SCOPE (docs/ENGINES.md, real-TPU engine bench): the compare-
and-reduce work is O(L*V) per doc, so this kernel is competitive only
at small vocab — it ties the scatter lowering at 2^10 and is ~58x
slower than the sort+RLE engine at the BASELINE 2^16 vocab. It exists
as the in-tree Mosaic histogram demonstration and the small-vocab
option; large-vocab production runs use ``engine="sparse"``.
``tf_df_pallas`` warns when called above TFIDF_TPU_PALLAS_MAX_VOCAB
(default 4096).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

TILE_D = 8      # doc rows per program (sublane dimension)
TILE_V = 128    # vocab lanes per program (lane dimension)
CHUNK_L = 128   # token-axis VMEM streaming chunk


def _tile_counts(tokens_ref, len_ref):
    """Compare-and-reduce counts for one (vocab-tile, doc-tile) program."""
    v_start = pl.program_id(0) * TILE_V        # vocab tile (major)
    lens = len_ref[:]                          # [TILE_D, 1]
    length = tokens_ref.shape[1]

    vids = v_start + jax.lax.broadcasted_iota(jnp.int32, (1, 1, TILE_V), 2)

    def body(c, acc):
        toks_c = tokens_ref[:, pl.ds(c * CHUNK_L, CHUNK_L)]  # [TILE_D, CHUNK_L]
        pos = c * CHUNK_L + jax.lax.broadcasted_iota(
            jnp.int32, (1, CHUNK_L), 1)
        valid = pos < lens                     # [TILE_D, CHUNK_L]
        # Mask via a 2D where (padding slots -> -1, matching no vocab id)
        # BEFORE the 3D broadcast: Mosaic only supports minor-dim
        # insertion on 32-bit types, so the i1 `valid` must not grow a
        # trailing dim.
        toks_c = jnp.where(valid, toks_c, -1)
        eq = toks_c[:, :, None] == vids
        return acc + jnp.sum(eq.astype(jnp.int32), axis=1)

    return jax.lax.fori_loop(0, length // CHUNK_L, body,
                             jnp.zeros((TILE_D, TILE_V), jnp.int32))


def _hist_kernel(tokens_ref, len_ref, counts_ref, df_ref):
    """One (vocab-tile, doc-tile) program: counts block + df accumulation.

    Grid order is (vocab major, docs MINOR): Pallas TPU keeps an output
    block resident only across *consecutive* grid steps, and the df
    block (0, j) must accumulate across all doc tiles — so the doc
    dimension has to be innermost for the revisits to be back-to-back.
    """
    i = pl.program_id(1)                       # doc tile (minor)
    counts = _tile_counts(tokens_ref, len_ref)
    counts_ref[:] = counts

    # DF: the same (0, j) df block is revisited by every doc-tile step i;
    # initialize on the first visit, accumulate presence afterwards.
    @pl.when(i == 0)
    def _():
        df_ref[:] = jnp.zeros_like(df_ref)
    df_ref[:] += jnp.sum((counts > 0).astype(jnp.int32), axis=0,
                         keepdims=True)


def _hist_kernel_counts_only(tokens_ref, len_ref, counts_ref):
    """Counts-only variant: no df output block, no accumulate revisits.

    Used where presence must be taken after a cross-shard psum anyway
    (the seq-sharded path) — the fused df would be dead device work.
    """
    counts_ref[:] = _tile_counts(tokens_ref, len_ref)


def _pad_to(x: int, m: int) -> int:
    return ((x + m - 1) // m) * m


@functools.partial(jax.jit,
                   static_argnames=("vocab_size", "interpret", "with_df"))
def tf_df_pallas(token_ids: jax.Array, lengths: jax.Array, *,
                 vocab_size: int, id_offset=0, interpret: bool = False,
                 with_df: bool = True):
    """Fused TF histogram + DF via the Pallas kernel.

    Drop-in equivalent of ``tf_counts`` + ``df_from_counts`` (tests pin
    exact equality). Pads D/L/V up to tile multiples and slices back.
    ``interpret=True`` runs the kernel in interpreter mode (CPU tests).

    ``id_offset`` makes the kernel vocab-shardable (mirroring
    ``tf_counts_masked``): ids are shifted so this call histograms only
    ``[id_offset, id_offset + vocab_size)``; out-of-range ids match no
    vocab lane (negative) or a sliced-off padding lane (>= vocab_size).
    It may be a traced scalar (``lax.axis_index`` under ``shard_map``).

    ``with_df=False`` returns ``(counts, None)`` via the counts-only
    kernel — callers that re-derive presence after a cross-shard psum
    skip the fused df's accumulate work entirely.
    """
    import os
    import warnings
    max_vocab = int(os.environ.get("TFIDF_TPU_PALLAS_MAX_VOCAB", 4096))
    if vocab_size > max_vocab:
        warnings.warn(
            f"tf_df_pallas at vocab_size={vocab_size}: the compare-and-"
            f"reduce kernel is O(L*V) and measured ~58x slower than "
            f"engine='sparse' at 2^16 vocab (docs/ENGINES.md); prefer the "
            f"sort+RLE engine above {max_vocab} vocab",
            RuntimeWarning, stacklevel=2)
    d, length = token_ids.shape
    dp, lp, vp = _pad_to(d, TILE_D), _pad_to(length, CHUNK_L), _pad_to(
        vocab_size, TILE_V)
    # Shift BEFORE padding; padding slots (0 - id_offset) are masked by
    # the in-kernel length test regardless of value. Padding *vocab*
    # lanes [vocab_size, vp) can collect out-of-shard ids — they are
    # sliced off below, counts and df both.
    local = token_ids.astype(jnp.int32) - id_offset
    toks = jnp.zeros((dp, lp), jnp.int32).at[:d, :length].set(local)
    lens = jnp.zeros((dp, 1), jnp.int32).at[:d, 0].set(lengths)

    in_specs = [
        pl.BlockSpec((TILE_D, lp), lambda j, i: (i, 0)),
        pl.BlockSpec((TILE_D, 1), lambda j, i: (i, 0)),
    ]
    grid = (vp // TILE_V, dp // TILE_D)  # docs minor: see _hist_kernel
    if not with_df:
        counts = pl.pallas_call(
            _hist_kernel_counts_only,
            grid=grid, in_specs=in_specs,
            out_specs=pl.BlockSpec((TILE_D, TILE_V), lambda j, i: (i, j)),
            out_shape=jax.ShapeDtypeStruct((dp, vp), jnp.int32),
            interpret=interpret,
        )(toks, lens)
        return counts[:d, :vocab_size], None
    counts, df = pl.pallas_call(
        _hist_kernel,
        grid=grid,
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((TILE_D, TILE_V), lambda j, i: (i, j)),
            pl.BlockSpec((1, TILE_V), lambda j, i: (0, j)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((dp, vp), jnp.int32),
            jax.ShapeDtypeStruct((1, vp), jnp.int32),
        ],
        interpret=interpret,
    )(toks, lens)
    return counts[:d, :vocab_size], df[0, :vocab_size]


def default_interpret() -> bool:
    """Interpret mode unless we are actually on TPU hardware."""
    return jax.default_backend() != "tpu"


# --- ragged wire rebuild ---------------------------------------------
#
# The ingest wire ships each chunk as ONE flat granule-aligned uint16
# stream (ingest.flatten_aligned); the padded [D, L] batch is rebuilt
# on device. The production lowering is an XLA granule gather
# (ingest._ragged_to_padded); this kernel is the Mosaic variant
# (TFIDF_TPU_REBUILD=pallas): the flat stream is viewed as [N/G, G]
# granules, and each (doc, granule) grid step copies granule
# offsets[d] + j of the stream into block (d, j) of the output — the
# per-row dynamic start rides BlockSpec index_maps over a scalar-
# prefetched offset vector, so the copy is pure block DMA with no
# gather instruction at all. Out-of-range granules clamp to the last
# one; their values land in masked slots (the sorted_term_counts
# contract, same as the XLA lowering's clamp).
#
# MEASURED SCOPE: one G-id block per grid step is far below the
# 128-lane tile the DMA engine likes, so this exists as the in-tree
# demonstration and an A/B probe for the rebuild path; the XLA granule
# gather stays the measured default (docs/SCALING.md round 5).


def _rebuild_kernel(offs_ref, gran_ref, out_ref):
    # All movement happens in the index_maps; the body is the copy.
    del offs_ref
    out_ref[...] = gran_ref[...]


@functools.partial(jax.jit,
                   static_argnames=("length", "align", "interpret"))
def ragged_rebuild_pallas(flat: jax.Array, lengths: jax.Array, *,
                          length: int, align: int,
                          interpret: bool = False) -> jax.Array:
    """Pallas twin of ``ingest._ragged_to_padded`` (aligned layout).

    Args:
      flat: [N] uint16/int32 granule-aligned flat id stream, N a
        multiple of ``align`` (the bucket-padded wire guarantees it).
      lengths: int32 [D] live tokens per doc.
      length: static L of the rebuilt batch.
      align: the wire granule G (>= 8 — smaller granules make no sense
        as blocks; callers fall back to the XLA gather below that).
      interpret: run in interpreter mode (CPU tests).

    Returns int32 [D, length] — value-identical at live slots to the
    XLA lowering (pinned by tests/test_wire.py); padding slots carry
    clamped granule values that every consumer masks by ``lengths``.
    """
    from jax.experimental.pallas import tpu as pltpu

    g = align
    lg = -(-length // g)
    d = lengths.shape[0]
    gran = flat.reshape(-1, g).astype(jnp.int32)
    ngran = gran.shape[0]
    al = (jnp.maximum(lengths, 0) + g - 1) // g  # granules per doc
    offg = jnp.concatenate([jnp.zeros((1,), jnp.int32),
                            jnp.cumsum(al[:-1], dtype=jnp.int32)])

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(d, lg),
        in_specs=[pl.BlockSpec(
            (1, g),
            lambda di, j, offs: (jnp.minimum(offs[di] + j, ngran - 1), 0))],
        out_specs=pl.BlockSpec((1, g), lambda di, j, offs: (di, j)),
    )
    out = pl.pallas_call(
        _rebuild_kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((d, lg * g), jnp.int32),
        interpret=interpret,
    )(offg, gran)
    return out[:, :length]


# --- packed result wire ----------------------------------------------
#
# The downlink wire (ops/downlink.py) packs each top-k (score, id)
# pair into one uint32 word on device before the drain. The production
# lowering is the XLA shift+or (ops.downlink.pack_result_words); this
# kernel is the Mosaic variant (TFIDF_TPU_DOWNLINK=pallas): a purely
# elementwise pack over doc-tile blocks, the minimal demonstration of
# emitting a compacted wire straight from a Pallas program.
#
# MEASURED SCOPE: the pack is a handful of VPU ops over [D, K] — XLA
# fuses it into the scoring program for free, so this exists as the
# in-tree A/B probe for the downlink path, like ragged_rebuild_pallas
# for the uplink.


def _pack_words_kernel(v_ref, t_ref, out_ref, *, w16):
    ok = t_ref[...] >= 0
    v16 = jnp.where(ok, v_ref[...],
                    jnp.asarray(-1, v_ref.dtype)).astype(w16)
    hi = jax.lax.bitcast_convert_type(v16, jnp.uint16).astype(jnp.uint32)
    lo = jnp.where(ok, t_ref[...], 0).astype(jnp.uint16) \
        .astype(jnp.uint32)
    out_ref[...] = (hi << jnp.uint32(16)) | lo


@functools.partial(jax.jit, static_argnames=("interpret",))
def pack_words_pallas(vals: jax.Array, tids: jax.Array, *,
                      interpret: bool = False) -> jax.Array:
    """Pallas twin of ``ops.downlink.pack_result_words`` (bit-identical
    words, pinned by tests/test_downlink.py). Tiles the doc axis; the
    [TILE_D, K] blocks keep whole rows per program."""
    from tfidf_tpu.ops.downlink import wire16_dtype

    d, k = vals.shape
    dp = _pad_to(d, TILE_D)
    v = jnp.zeros((dp, k), vals.dtype).at[:d].set(vals)
    # Padding rows carry tid -1 so they pack as the invalid sentinel,
    # identical to what the XLA pack emits for them.
    t = jnp.full((dp, k), -1, jnp.int32).at[:d].set(tids)
    out = pl.pallas_call(
        functools.partial(_pack_words_kernel,
                          w16=wire16_dtype(vals.dtype)),
        grid=(dp // TILE_D,),
        in_specs=[pl.BlockSpec((TILE_D, k), lambda i: (i, 0)),
                  pl.BlockSpec((TILE_D, k), lambda i: (i, 0))],
        out_specs=pl.BlockSpec((TILE_D, k), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((dp, k), jnp.uint32),
        interpret=interpret,
    )(v, t)
    return out[:d]


# --- fused score + top-k ---------------------------------------------
#
# The phase-B scoring step (ingest round 8): XLA lowers it as
# sparse_scores (a [D, L] tf*idf materialization) feeding a separate
# lax.top_k sort network over all L slots. This kernel fuses the whole
# per-doc selection into one Mosaic program per doc tile: gather IDF
# for the sorted triple stream, form tf*idf, and select the top k by k
# rounds of max-reduce + mask — never materializing the score array
# outside VMEM and never running the L-wide sort network (k << L).
# Selection semantics are EXACTLY sparse_topk's: scores masked to
# finfo.min off-head, ties broken toward the lower slot index (what
# lax.top_k does), invalid survivors decode to (0, -1).
#
# MEASURED SCOPE: like ragged_rebuild_pallas and pack_words_pallas this
# ships as the in-tree A/B probe (TFIDF_TPU_SCORE=pallas), pinned
# bit-identical on ids / allclose on scores against the XLA lowering by
# tests/test_finish.py. The in-kernel [V]-table gather is the op class
# the round-5 trace indicted on this backend, so the XLA path (whose
# sort-join avoids the gather entirely) stays the measured default.


# --- bytes-wire tokenize+hash -----------------------------------------
#
# The bytes wire (round 14) ships raw document bytes; the device
# derives the padded [D, L] id batch itself (ops/device_tokenize.py).
# The token-start derivation is shared XLA code; this kernel is the
# Mosaic variant of the HASH stage (TFIDF_TPU_DEVICE_TOKENIZE=pallas):
# per doc-tile, the per-token FNV-1a64 byte loop runs as a masked
# lax.while_loop over (TILE_D, L) lanes with the whole byte slab
# resident in VMEM (a 2^17-byte bucket is 512 KB as int32 — well under
# the ~16 MB budget), gathering one byte per live token per step —
# the device twin of the reference's OpenMP-parallel per-token loop
# (TFIDF_extra.c:69-302), bit-identical ids to the XLA lowering and
# both host packers (tests/test_bytes_wire.py).
#
# MEASURED SCOPE: in-tree A/B probe like ragged_rebuild_pallas — the
# in-kernel slab gather is the op class the round-5 trace indicted on
# this backend, so the XLA while_loop stays the portable default; the
# kernel exists to measure whether VMEM-resident gathers beat it, and
# needs the whole chunk slab to fit VMEM (multi-bucket slabs fall back
# to XLA — ops.device_tokenize.tokenize_hash_device's caller scope).


def _tokenize_hash_kernel(slab_ref, starts_ref, len_ref, ids_ref, *,
                          vocab_size, seed, truncate_at, n):
    from tfidf_tpu.ops.device_tokenize import (fnv1a_step, fold_mod,
                                               is_space, seed_state)

    starts = starts_ref[...]                     # [TILE_D, L] int32
    lens = len_ref[...]                          # [TILE_D, 1] int32
    length = starts.shape[1]
    valid = jax.lax.broadcasted_iota(
        jnp.int32, starts.shape, 1) < lens       # first lens[d] slots
    hi0, lo0 = seed_state(seed)
    hi = jnp.full(starts.shape, hi0, jnp.uint32)
    lo = jnp.full(starts.shape, lo0, jnp.uint32)
    del length

    def cond(c):
        return jnp.any(c[1])

    def body(c):
        j, alive, hi, lo = c
        pos = starts + j
        byte = jnp.take(slab_ref[0, :], jnp.minimum(pos, n - 1))
        consume = alive & ~is_space(byte) & (pos < n)
        if truncate_at:
            consume &= j < truncate_at
        nhi, nlo = fnv1a_step(hi, lo, byte.astype(jnp.uint32))
        return (j + 1, consume, jnp.where(consume, nhi, hi),
                jnp.where(consume, nlo, lo))

    _, _, hi, lo = jax.lax.while_loop(
        cond, body, (jnp.int32(0), valid, hi, lo))
    ids_ref[...] = jnp.where(valid, fold_mod(hi, lo, vocab_size), 0)


@functools.partial(jax.jit,
                   static_argnames=("vocab_size", "seed", "truncate_at",
                                    "interpret"))
def tokenize_hash_pallas(bytes_i32: jax.Array, starts: jax.Array,
                         lengths: jax.Array, *, vocab_size: int,
                         seed: int = 0, truncate_at: int = 0,
                         interpret: bool = False) -> jax.Array:
    """Pallas twin of ``ops.device_tokenize.hash_tokens_xla``
    (bit-identical ids, pinned by tests/test_bytes_wire.py).

    Args:
      bytes_i32: int32 [N] upcast slab bytes (``token_starts`` output).
      starts: int32 [D, L] token start positions (invalid slots point
        at slab pad — whitespace — and additionally mask via lengths).
      lengths: int32 [D] per-doc token counts capped at L.
      vocab_size / seed / truncate_at: the hash contract statics
        (truncate_at 0 = no truncation).

    Returns int32 [D, L] vocab ids, padding slots zero-filled.
    """
    d, k = starts.shape
    n = bytes_i32.shape[0]
    dp = _pad_to(d, TILE_D)
    # Padding rows: zero tokens -> the while mask starts dead there.
    starts_p = jnp.full((dp, k), n - 1, jnp.int32).at[:d].set(starts)
    lens_p = jnp.zeros((dp, 1), jnp.int32).at[:d, 0].set(lengths)
    slab2 = bytes_i32.reshape(1, -1)
    out = pl.pallas_call(
        functools.partial(_tokenize_hash_kernel, vocab_size=vocab_size,
                          seed=seed, truncate_at=truncate_at, n=n),
        grid=(dp // TILE_D,),
        in_specs=[pl.BlockSpec((1, n), lambda i: (0, 0)),
                  pl.BlockSpec((TILE_D, k), lambda i: (i, 0)),
                  pl.BlockSpec((TILE_D, 1), lambda i: (i, 0))],
        out_specs=pl.BlockSpec((TILE_D, k), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((dp, k), jnp.int32),
        interpret=interpret,
    )(slab2, starts_p, lens_p)
    return out[:d]


def _fused_score_topk_kernel(ids_ref, cnt_ref, head_ref, len_ref,
                             idf_ref, vals_ref, tids_ref, *, k, length):
    dtype = idf_ref.dtype
    neg = jnp.finfo(dtype).min
    ids = ids_ref[...]                          # [TILE_D, L] int32
    head = head_ref[...] != 0                   # int32 mask -> bool
    lens = jnp.maximum(len_ref[...], 1).astype(dtype)  # [TILE_D, 1]
    safe = jnp.where(head, ids, 0)
    # The IDF join, in-kernel: one gather from the [V] table resident
    # in VMEM (256 KB at 2^16 f32 — far under the ~16 MB budget).
    idf_slot = jnp.take(idf_ref[0, :], safe)
    score = cnt_ref[...].astype(dtype) / lens * idf_slot
    scores = jnp.where(head, score, neg)
    pos = jax.lax.broadcasted_iota(jnp.int32, scores.shape, 1)

    def select(j, carry):
        scores, vals, tids = carry
        m = jnp.max(scores, axis=1)             # [TILE_D]
        # lax.top_k tie order: the LOWEST index among equal scores
        # wins each round.
        hit = scores == m[:, None]
        idx = jnp.min(jnp.where(hit, pos, length), axis=1)
        one = pos == idx[:, None]
        tid = jnp.sum(jnp.where(one, ids, 0), axis=1)
        ok = m > neg
        vals = jax.lax.dynamic_update_slice(
            vals, jnp.where(ok, m, jnp.zeros((), dtype))[:, None], (0, j))
        tids = jax.lax.dynamic_update_slice(
            tids, jnp.where(ok, tid, -1)[:, None], (0, j))
        return jnp.where(one, neg, scores), vals, tids

    vals0 = jnp.zeros((scores.shape[0], k), dtype)
    tids0 = jnp.full((scores.shape[0], k), -1, jnp.int32)
    _, vals, tids = jax.lax.fori_loop(0, k, select,
                                      (scores, vals0, tids0))
    vals_ref[...] = vals
    tids_ref[...] = tids


@functools.partial(jax.jit, static_argnames=("k", "interpret"))
def fused_score_topk_pallas(ids: jax.Array, counts: jax.Array,
                            head: jax.Array, lengths: jax.Array,
                            idf: jax.Array, *, k: int,
                            interpret: bool = False):
    """Fused tf*idf scoring + per-doc top-k over the sorted triple
    stream (the ``sparse_scores`` -> ``sparse_topk`` pair as ONE Mosaic
    kernel). Returns ``(vals [D, k], tids [D, k])`` per the sparse_topk
    contract: ids bit-identical to the XLA lowering (same selection,
    same tie order), scores the same float formula (allclose; the only
    divergence is op-reassociation headroom Mosaic is allowed)."""
    d, length = ids.shape
    k = min(k, length)
    dp = _pad_to(d, TILE_D)
    pad2 = lambda a, fill: jnp.full((dp, length), fill, a.dtype) \
        .at[:d].set(a)
    ids_p = pad2(ids.astype(jnp.int32), 0)
    cnt_p = pad2(counts.astype(jnp.int32), 0)
    # head rides as int32: padding rows are all-zero = no head slots,
    # so they select nothing and decode to the (0, -1) contract.
    head_p = pad2(head.astype(jnp.int32), 0)
    lens_p = jnp.zeros((dp, 1), jnp.int32).at[:d, 0].set(lengths)
    idf2 = idf.reshape(1, -1)
    vals, tids = pl.pallas_call(
        functools.partial(_fused_score_topk_kernel, k=k, length=length),
        grid=(dp // TILE_D,),
        in_specs=[pl.BlockSpec((TILE_D, length), lambda i: (i, 0)),
                  pl.BlockSpec((TILE_D, length), lambda i: (i, 0)),
                  pl.BlockSpec((TILE_D, length), lambda i: (i, 0)),
                  pl.BlockSpec((TILE_D, 1), lambda i: (i, 0)),
                  pl.BlockSpec((1, idf2.shape[1]), lambda i: (0, 0))],
        out_specs=[pl.BlockSpec((TILE_D, k), lambda i: (i, 0)),
                   pl.BlockSpec((TILE_D, k), lambda i: (i, 0))],
        out_shape=[jax.ShapeDtypeStruct((dp, k), idf.dtype),
                   jax.ShapeDtypeStruct((dp, k), jnp.int32)],
        interpret=interpret,
    )(ids_p, cnt_p, head_p, lens_p, idf2)
    return vals[:d], tids[:d]


def _tile_scores_kernel(data_ref, cols_ref, q_ref, out_ref):
    """One doc-subtile's retrieval similarities:
    ``sims[r, q] = sum_l data[r, l] * qmat[cols[r, l], q]`` — the BCOO
    sparse x dense dot as an in-kernel gather-accumulate, the same
    VMEM-resident-table idiom as ``_fused_score_topk_kernel`` with the
    [V, Q] query block in place of the [V] IDF table. Dead slots carry
    ``data == 0`` (``to_bcoo``'s explicit-zero convention), so no head
    mask is needed: they gather column 0 and add nothing."""
    qtab = q_ref[...]                            # [V, Q] resident
    length = data_ref.shape[1]

    def body(sl, acc):
        c = cols_ref[:, sl]                      # [TILE_D] int32
        w = data_ref[:, sl]                      # [TILE_D]
        return acc + w[:, None] * jnp.take(qtab, c, axis=0)

    out_ref[...] = jax.lax.fori_loop(
        0, length, body, jnp.zeros(out_ref.shape, out_ref.dtype))


@functools.partial(jax.jit, static_argnames=("interpret",))
def tile_scores_pallas(data: jax.Array, cols: jax.Array,
                       qmat: jax.Array, *, interpret: bool = False
                       ) -> jax.Array:
    """[tile, L] row-sparse weights x [V, Q] query block -> [tile, Q]
    similarities via the Mosaic gather-accumulate kernel — the
    ``TFIDF_TPU_SCORE=pallas`` lowering of one score tile inside
    ``ops.sparse.score_topk_tiled`` (scope extended, round 21). Same
    contract as the phase-B probe: selections bit-identical, scores
    the same float formula (allclose; reassociation headroom only).
    In-tree A/B probe scope note: the whole [V, Q] block must sit in
    VMEM, which bounds Q on real hardware — interpret mode (CPU) has
    no such ceiling."""
    d, length = data.shape
    dp = _pad_to(d, TILE_D)
    # Padding rows are all-zero: they gather column 0 with weight 0
    # and score exactly 0, then slice off below.
    data_p = jnp.zeros((dp, length), data.dtype).at[:d].set(data)
    cols_p = jnp.zeros((dp, length), jnp.int32).at[:d].set(
        cols.astype(jnp.int32))
    out = pl.pallas_call(
        _tile_scores_kernel,
        grid=(dp // TILE_D,),
        in_specs=[pl.BlockSpec((TILE_D, length), lambda i: (i, 0)),
                  pl.BlockSpec((TILE_D, length), lambda i: (i, 0)),
                  pl.BlockSpec(qmat.shape, lambda i: (0, 0))],
        out_specs=pl.BlockSpec((TILE_D, qmat.shape[1]),
                               lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((dp, qmat.shape[1]),
                                       qmat.dtype),
        interpret=interpret,
    )(data_p, cols_p, qmat)
    return out[:d]
