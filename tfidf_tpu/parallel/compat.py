"""``shard_map`` compatibility shim.

Every mesh code path in this repo maps its per-shard body with one
call shape::

    shard_map(body, mesh=plan.mesh, in_specs=..., out_specs=...,
              check_vma=False)

Modern jax exports that as top-level ``jax.shard_map`` (with the
replication checker knob spelled ``check_vma``); the 0.4.x line this
environment deploys only ships ``jax.experimental.shard_map.shard_map``
(knob spelled ``check_rep``) — and until round 18 that single missing
export kept all 37 mesh tests dark. This module is the one place that
difference lives: call sites import :func:`shard_map` from here and
never touch ``jax.shard_map`` directly.

Resolution order (decided once, at import):

* ``jax.shard_map`` when the running jax exports it — the call is
  passed through untouched;
* else ``jax.experimental.shard_map.shard_map`` with ``check_vma``
  translated to ``check_rep`` (same meaning: disable the static
  replication checker where collectives make replication the checker
  cannot infer).

``tests/conftest.py`` probes THIS function at collection time; an
environment where neither spelling works still turns the mesh tests
into skips carrying the probe's error (the round-7 machinery, kept for
genuinely broken envs).
"""

from __future__ import annotations

from typing import Any, Callable

import jax

__all__ = ["shard_map", "HAS_NATIVE_SHARD_MAP"]

#: True when the running jax exports top-level ``jax.shard_map`` (the
#: passthrough path); False means the experimental fallback carries
#: every mesh program. Exposed so tests can pin which branch is live.
HAS_NATIVE_SHARD_MAP = hasattr(jax, "shard_map")


def shard_map(f: Callable, *, mesh: Any, in_specs: Any, out_specs: Any,
              check_vma: bool = True) -> Callable:
    """Map ``f`` over ``mesh`` shards — ``jax.shard_map`` everywhere.

    Keyword-only, matching how every call site in the repo spells it.
    ``check_vma=False`` disables the static replication checker on
    both lowerings (it is ``check_rep`` on the 0.4.x experimental
    export).
    """
    if HAS_NATIVE_SHARD_MAP:
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=check_vma)
    from jax.experimental.shard_map import shard_map as _shard_map
    return _shard_map(f, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, check_rep=check_vma)
