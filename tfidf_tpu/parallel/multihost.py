"""Multi-host / multi-process bring-up and sharded ingest.

Two process models live here, mirroring the reference's two runtimes:

* ``initialize`` — the JAX-native bring-up for a multi-host TPU slice
  (``jax.distributed.initialize``; one process per host, meshes span
  hosts transparently). The reference's ``MPI_Init``/``MPI_Comm_rank``
  (``TFIDF.c:82-92``) done the jax way.
* ``MpiLiteComm`` + ``run_sharded_ingest`` — the reference's
  rank-partitioned document loop (``TFIDF.c:130``) done over N OS
  processes, each owning its own host→device link: the driver launches
  workers with the SAME process model as ``native/mpirun_lite``
  (pairwise AF_UNIX socketpairs inherited through
  ``MPILITE_RANK/SIZE/FDS``) and each worker ingests a contiguous
  document shard concurrently. The only cross-worker traffic is the
  psum-shaped DF allreduce (``MPI_Reduce + MPI_Bcast`` of the DF
  table, ``TFIDF.c:215,220``) — one [V] vector per worker per run.
  ``MpiLiteComm`` speaks the exact mpi_lite wire protocol
  (``native/mpi_lite/mpi_lite.cc``: framed ``[i32 tag][u64 bytes]``
  messages, root-sequenced collectives, reserved negative tags), so a
  Python rank launched by the native ``mpirun_lite`` binary finds the
  same channels a C rank would.

Why processes and not threads: the link tax is per-process — one
process owns one transfer queue to its device, so N processes drive N
links (or N slices of one link's staging bandwidth) concurrently,
dividing the ``link_tax_s`` column of BENCH_r05 by worker count. The
merged index is BIT-identical to a single-process ingest: per-document
rows depend only on that document's tokens and the GLOBAL DF/IDF, DF
is an order-independent integer sum, and shard concatenation preserves
the global document order (docs/SCALING.md round 19).
"""

from __future__ import annotations

import dataclasses
import json
import os
import select
import socket
import struct
import subprocess
import sys
import tempfile
import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

# Reserved collective tags — the mpi_lite runtime's values
# (native/mpi_lite/mpi_lite.cc): point-to-point tags are >= 0, so the
# collectives can never collide with them.
_TAG_BCAST = -101
_TAG_BARRIER_IN = -102
_TAG_BARRIER_OUT = -103
# Python-side reduce contribution tag (the C runtime sequences its
# reductions through Send/Recv with caller tags; our allreduce uses a
# reserved one so a concurrent p2p exchange cannot interleave).
_TAG_REDUCE = -105
# Clock-alignment handshake (round 23): rank 0 brackets each peer's
# perf_counter_ns reply and estimates the offset at the RTT midpoint —
# recorded as trace-export metadata and applied only at MERGE time
# (tools/trace_merge.py); capture timestamps are never rewritten.
_TAG_CLOCK = -106
_CLOCK_SAMPLES = 8

_FRAME_HDR = struct.Struct("<iQ")  # [i32 tag][u64 nbytes]


class MpiLiteError(RuntimeError):
    """Protocol violation on an mpi_lite channel (tag mismatch, short
    read, peer gone) — aborting loudly beats silently reordering."""


class MpiLiteComm:
    """The mpi_lite runtime subset in Python, over inherited fds.

    Wire protocol per (src, dst) channel: framed messages
    ``[u32 tag][u64 bytes][payload]``, strictly ordered per channel —
    every send has exactly one program-ordered matching recv, and a
    frame whose tag differs from the one the receiver asked for raises
    :class:`MpiLiteError`. Collectives are root-sequenced (peers talk
    only to rank 0), so channel buffers bound memory, not progress —
    the same deadlock discipline as the C runtime.
    """

    def __init__(self, rank: int, size: int, fds: Sequence[int]):
        if len(fds) != size:
            raise MpiLiteError(f"fds length {len(fds)} != size {size}")
        self.rank = rank
        self.size = size
        self._fds = list(fds)

    @classmethod
    def from_env(cls) -> "MpiLiteComm":
        """Attach to the channels ``mpirun_lite`` (or
        :func:`launch_ranks`) wired up: ``MPILITE_RANK``,
        ``MPILITE_SIZE``, ``MPILITE_FDS`` (own slot -1)."""
        try:
            rank = int(os.environ["MPILITE_RANK"])
            size = int(os.environ["MPILITE_SIZE"])
            raw = os.environ["MPILITE_FDS"]
        except KeyError as e:
            raise MpiLiteError(f"not under an mpi_lite launcher "
                               f"(missing {e.args[0]})")
        fds = []
        for part in raw.split(","):
            try:
                fds.append(int(part))
            except ValueError:
                raise MpiLiteError(
                    f"malformed MPILITE_FDS entry {part!r} in {raw!r}")
        return cls(rank, size, fds)

    # --- framed point-to-point ---
    def _write_all(self, fd: int, data: bytes) -> None:
        view = memoryview(data)
        while view:
            n = os.write(fd, view)
            view = view[n:]

    def _read_all(self, fd: int, n: int) -> bytes:
        parts = []
        while n:
            chunk = os.read(fd, min(n, 1 << 20))
            if not chunk:
                raise MpiLiteError("peer closed channel mid-message")
            parts.append(chunk)
            n -= len(chunk)
        return b"".join(parts)

    def send(self, peer: int, tag: int, payload: bytes) -> None:
        fd = self._fds[peer]
        if fd < 0:
            raise MpiLiteError(f"send to self/unwired peer {peer}")
        self._write_all(fd, _FRAME_HDR.pack(tag, len(payload)))
        self._write_all(fd, payload)

    def recv(self, peer: int, tag: int) -> bytes:
        fd = self._fds[peer]
        if fd < 0:
            raise MpiLiteError(f"recv from self/unwired peer {peer}")
        got_tag, nbytes = _FRAME_HDR.unpack(
            self._read_all(fd, _FRAME_HDR.size))
        if got_tag != tag:
            raise MpiLiteError(
                f"tag mismatch on channel {peer}->{self.rank}: "
                f"expected {tag}, got {got_tag} — per-channel ordering "
                f"is the protocol; this is a bug, not a race")
        return self._read_all(fd, nbytes)

    # --- root-sequenced collectives (rank 0 is root, like the C
    # runtime's MPI_COMM_WORLD collectives) ---
    def barrier(self) -> None:
        if self.rank == 0:
            for peer in range(1, self.size):
                self.recv(peer, _TAG_BARRIER_IN)
            for peer in range(1, self.size):
                self.send(peer, _TAG_BARRIER_OUT, b"")
        else:
            self.send(0, _TAG_BARRIER_IN, b"")
            self.recv(0, _TAG_BARRIER_OUT)

    def bcast_bytes(self, payload: Optional[bytes]) -> bytes:
        if self.rank == 0:
            assert payload is not None
            for peer in range(1, self.size):
                self.send(peer, _TAG_BCAST, payload)
            return payload
        return self.recv(0, _TAG_BCAST)

    def allreduce_sum(self, arr: np.ndarray) -> np.ndarray:
        """Exact elementwise sum of every rank's array — the
        psum-shaped DF reduction (integer sums are order-independent,
        so the merged DF is bit-identical to a single-process fold).
        Root-sequenced: peers send to rank 0, root sums in RANK ORDER
        and broadcasts the merged vector back."""
        arr = np.ascontiguousarray(arr)
        if self.size == 1:
            return arr.copy()
        if self.rank == 0:
            acc = arr.copy()
            for peer in range(1, self.size):
                part = np.frombuffer(
                    self.recv(peer, _TAG_REDUCE),
                    dtype=arr.dtype).reshape(arr.shape)
                acc += part
            self.bcast_bytes(acc.tobytes())
            return acc
        self.send(0, _TAG_REDUCE, arr.tobytes())
        out = np.frombuffer(self.bcast_bytes(None),
                            dtype=arr.dtype).reshape(arr.shape)
        return out.copy()

    def poll(self, peer: int, timeout_s: Optional[float] = None) -> bool:
        """True when a frame from ``peer`` is readable within
        ``timeout_s`` (None = block) — the supervisor's bounded wait:
        a wedged child is distinguishable from a slow one without
        committing this process to an unbounded ``recv``."""
        fd = self._fds[peer]
        if fd < 0:
            raise MpiLiteError(f"poll on self/unwired peer {peer}")
        readable, _, _ = select.select([fd], [], [], timeout_s)
        return bool(readable)

    def wire(self, peer: int, fd: int) -> None:
        """Install (or replace) the channel to ``peer`` — the star-
        supervisor hook: when a dead child is respawned with a fresh
        socketpair (:func:`launch_rank`), the stale fd is closed and
        the new one takes its slot, so the same comm object keeps
        speaking to the replacement."""
        old = self._fds[peer]
        if old >= 0 and old != fd:
            try:
                os.close(old)
            except OSError:
                pass
        self._fds[peer] = fd

    def unwire(self, peer: int) -> None:
        """Close and forget the channel to ``peer`` (dead child)."""
        self.wire(peer, -1)

    def close(self) -> None:
        for fd in self._fds:
            if fd >= 0:
                try:
                    os.close(fd)
                except OSError:
                    pass
        self._fds = [-1] * self.size


def clock_handshake(comm: "MpiLiteComm",
                    samples: int = _CLOCK_SAMPLES) -> dict:
    """Estimate every rank's clock offset against rank 0, the fleet's
    reference timeline. Root-sequenced like the other collectives:
    rank 0 pings each peer ``samples`` times over the reserved
    ``_TAG_CLOCK`` channel, the peer answers with its raw
    ``perf_counter_ns``, and the root keeps the minimum-RTT estimate
    (:class:`tfidf_tpu.obs.disttrace.ClockOffsetEstimator` — the same
    math the serving front uses on its ctrl plane). Each peer receives
    its own estimate back and returns it; rank 0 returns the zero
    self-estimate. The dict is trace-export METADATA
    (``offset_ns``/``uncertainty_ns``/``rtt_ns``/``samples``): offsets
    are applied at merge time by ``tools/trace_merge.py``, never at
    capture."""
    from tfidf_tpu.obs.disttrace import ClockOffsetEstimator

    if comm.size == 1:
        return ClockOffsetEstimator().as_meta()
    if comm.rank == 0:
        for peer in range(1, comm.size):
            est = ClockOffsetEstimator()
            for _ in range(samples):
                t_send = time.perf_counter_ns()
                comm.send(peer, _TAG_CLOCK, b"")
                t_peer = struct.unpack(
                    "<q", comm.recv(peer, _TAG_CLOCK))[0]
                est.add_sample(t_send, t_peer, time.perf_counter_ns())
            comm.send(peer, _TAG_CLOCK,
                      json.dumps(est.as_meta()).encode())
        return ClockOffsetEstimator().as_meta()
    for _ in range(samples):
        comm.recv(0, _TAG_CLOCK)
        comm.send(0, _TAG_CLOCK,
                  struct.pack("<q", time.perf_counter_ns()))
    return json.loads(comm.recv(0, _TAG_CLOCK).decode())


def launch_ranks(n: int, argv_for_rank: Callable[[int], List[str]],
                 env: Optional[dict] = None,
                 stderr=subprocess.PIPE) -> List[subprocess.Popen]:
    """The ``mpirun_lite`` process model in Python: one AF_UNIX
    socketpair per rank pair, N children each inheriting exactly its
    own row of fds through ``MPILITE_RANK/SIZE/FDS``. Children
    launched this way and children launched by the native binary see
    the identical channel environment."""
    pair_fd = [[-1] * n for _ in range(n)]
    socks = []  # keep the python socket objects alive until spawn
    for i in range(n):
        for j in range(i + 1, n):
            a, b = socket.socketpair(socket.AF_UNIX, socket.SOCK_STREAM)
            a.setblocking(True)
            b.setblocking(True)
            socks += [a, b]
            pair_fd[i][j] = a.fileno()
            pair_fd[j][i] = b.fileno()
    procs = []
    base_env = dict(os.environ if env is None else env)
    for r in range(n):
        fds = [pair_fd[r][j] for j in range(n)]
        child_env = dict(base_env,
                         MPILITE_RANK=str(r), MPILITE_SIZE=str(n),
                         MPILITE_FDS=",".join(str(f) for f in fds))
        procs.append(subprocess.Popen(
            argv_for_rank(r), env=child_env,
            pass_fds=[f for f in fds if f >= 0],
            stdout=subprocess.PIPE, stderr=stderr, text=True))
    for s in socks:  # parent's copies: children hold their own dups
        s.close()
    return procs


def launch_rank(rank: int, size: int, argv: List[str],
                env: Optional[dict] = None,
                stderr=None,
                stdin=subprocess.PIPE) -> Tuple[int, subprocess.Popen]:
    """Spawn ONE child wired to the caller over a fresh socketpair —
    the star-topology complement to :func:`launch_ranks`. The caller
    plays rank 0; the child attaches as ``rank`` of ``size`` with only
    its rank-0 channel wired (``MPILITE_FDS`` carries -1 everywhere
    else), so child<->child traffic is impossible by construction and
    every control exchange funnels through the supervisor — the
    replicated serving front's process model, where a dead replica is
    respawned with a FRESH channel instead of rebuilding the whole
    all-pairs mesh. Returns ``(parent_fd, Popen)``; install the fd
    with :meth:`MpiLiteComm.wire`. ``stderr=None`` inherits the
    caller's (a supervisor that never drains a stderr pipe would
    deadlock its children on the 64 KiB pipe buffer)."""
    if not 1 <= rank < size:
        raise ValueError(f"rank {rank} out of range for size {size}")
    a, b = socket.socketpair(socket.AF_UNIX, socket.SOCK_STREAM)
    a.setblocking(True)
    b.setblocking(True)
    fds = [-1] * size
    fds[0] = b.fileno()
    base_env = dict(os.environ if env is None else env)
    child_env = dict(base_env,
                     MPILITE_RANK=str(rank), MPILITE_SIZE=str(size),
                     MPILITE_FDS=",".join(str(f) for f in fds))
    proc = subprocess.Popen(argv, env=child_env,
                            pass_fds=[b.fileno()],
                            stdin=stdin, stdout=subprocess.PIPE,
                            stderr=stderr, text=True)
    parent_fd = os.dup(a.fileno())
    a.close()
    b.close()
    return parent_fd, proc


def shard_bounds(num_docs: int, n_workers: int) -> List[Tuple[int, int]]:
    """Contiguous document shards in global discovery order — the
    reference's ``rank * docs / size`` partition (``TFIDF.c:130``).
    The last shard is ragged when ``num_docs % n_workers != 0``."""
    if n_workers < 1:
        raise ValueError("n_workers must be >= 1")
    n_workers = min(n_workers, max(num_docs, 1))
    return [(r * num_docs // n_workers, (r + 1) * num_docs // n_workers)
            for r in range(n_workers)]


def _config_to_spec(cfg) -> dict:
    d = dataclasses.asdict(cfg)
    d["vocab_mode"] = cfg.vocab_mode.value
    d["tokenizer"] = cfg.tokenizer.value
    d["ngram_range"] = list(cfg.ngram_range)
    return d


def _config_from_spec(d: dict):
    from tfidf_tpu.config import PipelineConfig, TokenizerKind, VocabMode
    d = dict(d)
    d["vocab_mode"] = VocabMode(d["vocab_mode"])
    d["tokenizer"] = TokenizerKind(d["tokenizer"])
    d["ngram_range"] = tuple(d["ngram_range"])
    return PipelineConfig(**d)


def _worker_main(spec_path: str) -> int:
    """One ingest rank: attach to the mpi_lite channels, ingest the
    assigned contiguous shard through the SAME ``run_overlapped``
    programs a single-process run dispatches (only the IDF's
    ``num_docs`` and the merged DF differ — both global), and write
    the shard's rows for the driver to concatenate."""
    with open(spec_path) as f:
        spec = json.load(f)
    comm = MpiLiteComm.from_env()
    from tfidf_tpu import obs
    from tfidf_tpu.ingest import run_overlapped

    cfg = _config_from_spec(spec["config"])
    lo, hi = spec["shards"][comm.rank]

    def df_merge(df_host: np.ndarray) -> np.ndarray:
        return comm.allreduce_sum(np.asarray(df_host, dtype=np.int32))

    walls = []
    result = None
    for _ in range(max(1, int(spec.get("repeat", 1)))):
        # Align the timed windows: every rank starts its ingest at the
        # same barrier, so per-rank walls measure concurrent work.
        comm.barrier()
        t0 = time.perf_counter()
        result = run_overlapped(
            spec["input_dir"], cfg,
            chunk_docs=spec["chunk_docs"], doc_len=spec["doc_len"],
            strict=spec["strict"], spill=spec["spill"],
            shard=(lo, hi), total_docs=spec["total_docs"],
            df_merge=df_merge if comm.size > 1 else None)
        walls.append(time.perf_counter() - t0)
    # One more fence so no rank tears down its channels while a peer
    # is still mid-allreduce.
    comm.barrier()
    # Clock alignment (round 23): the channels are quiet here, so the
    # ping RTTs are honest. Identity + offset ride the trace-export
    # metadata — tools/trace_merge.py folds the N per-rank timelines
    # onto rank 0's clock.
    clock = clock_handshake(comm)
    obs.set_export_meta(process=f"ingest{comm.rank}", clock=clock)
    out = spec["out_paths"][comm.rank]
    arrays = {
        "topk_vals": np.asarray(result.topk_vals),
        "topk_ids": np.asarray(result.topk_ids),
        "lengths": np.asarray(result.lengths),
    }
    if comm.rank == 0:
        arrays["df"] = np.asarray(result.df)
    np.savez(out, **arrays)
    meta = {
        "rank": comm.rank, "lo": lo, "hi": hi,
        "wall_s": walls[-1], "walls_s": walls,
        "phases": result.phases or {},
        "path": result.path, "wire": result.wire,
        "finish": result.finish,
        "bytes_on_wire": result.bytes_on_wire,
        "df_occupied": result.df_occupied,
    }
    with open(out + ".meta.json", "w") as f:
        json.dump(meta, f)
    obs.export()  # no-op unless TFIDF_TPU_TRACE armed
    comm.close()
    print(f"OK {comm.rank}")
    return 0


def _upload_seconds(phases: Dict[str, float]) -> float:
    """The worker-run seconds spent driving its link: the resident
    path's ``put`` (device_put staging + dispatch) or the streaming
    passes' equivalents."""
    if "put" in phases:
        return float(phases["put"])
    return float(phases.get("pass_a", 0.0)) + float(
        phases.get("pass_b", 0.0))


@dataclasses.dataclass
class ShardedIngestInfo:
    """Per-worker receipts of a :func:`run_sharded_ingest` run."""

    n_workers: int
    shards: List[Tuple[int, int]]
    wall_s: float               # max over workers (concurrent ranks)
    worker_walls_s: List[float]
    upload_s: float             # max over workers' link-driving time
    worker_upload_s: List[float]
    # Fraction of each worker's wall spent driving its own link — the
    # per-worker link_utilization column of the bench artifact.
    link_utilization: List[float]
    worker_phases: List[Dict[str, float]]
    path: str = ""
    wire: str = ""


def run_sharded_ingest(input_dir: str, config=None, n_workers: int = 2,
                       chunk_docs: int = 8192,
                       doc_len: Optional[int] = None, strict: bool = True,
                       spill: str = "auto", repeat: int = 1,
                       timeout_s: float = 600.0,
                       keep_dir: Optional[str] = None):
    """Ingest ``input_dir`` across ``n_workers`` OS processes, each
    packing and uploading its contiguous document shard over its own
    link concurrently; returns ``(IngestResult, ShardedIngestInfo)``.

    The merged result is bit-identical to a single-process
    :func:`~tfidf_tpu.ingest.run_overlapped` of the same corpus and
    config (DF, IDF, scores, names, tie order — pinned by
    tests/test_multihost.py): per-document rows depend only on the
    document's own tokens and the global DF/IDF, the DF allreduce is
    an exact integer sum, and shards concatenate in global discovery
    order. ``repeat`` re-runs the timed ingest inside each (warm)
    worker process and reports the last run's walls — the honest
    steady-state number, with the per-process interpreter/compile
    cold-start excluded from the measured window on every side alike.
    """
    from tfidf_tpu.config import PipelineConfig, VocabMode
    from tfidf_tpu.ingest import IngestResult
    from tfidf_tpu.io.corpus import discover_names

    cfg = config or PipelineConfig(vocab_mode=VocabMode.HASHED, topk=16)
    names = discover_names(input_dir, strict)
    if not names:
        raise ValueError(f"no documents in {input_dir}")
    shards = shard_bounds(len(names), n_workers)
    n_workers = len(shards)

    tmp = keep_dir or tempfile.mkdtemp(prefix="tfidf_mh_")
    out_paths = [os.path.join(tmp, f"shard{r}.npz")
                 for r in range(n_workers)]
    spec = {
        "input_dir": input_dir,
        "config": _config_to_spec(cfg),
        "chunk_docs": chunk_docs,
        "doc_len": doc_len,
        "strict": strict,
        "spill": spill,
        "repeat": repeat,
        "total_docs": len(names),
        "shards": [list(s) for s in shards],
        "out_paths": out_paths,
    }
    spec_path = os.path.join(tmp, "spec.json")
    with open(spec_path, "w") as f:
        json.dump(spec, f)

    procs = launch_ranks(
        n_workers,
        lambda r: [sys.executable, "-m", "tfidf_tpu.parallel.multihost",
                   spec_path])
    outs = []
    try:
        for p in procs:
            outs.append(p.communicate(timeout=timeout_s))
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    for r, (p, (out, err)) in enumerate(zip(procs, outs)):
        if p.returncode != 0:
            raise RuntimeError(
                f"ingest worker {r} failed rc={p.returncode}\n"
                f"stdout: {out[-2000:]}\nstderr: {err[-2000:]}")

    parts, metas = [], []
    for r, path in enumerate(out_paths):
        parts.append(np.load(path))
        with open(path + ".meta.json") as f:
            metas.append(json.load(f))
    df = parts[0]["df"]
    vals = np.concatenate([p["topk_vals"] for p in parts])
    tids = np.concatenate([p["topk_ids"] for p in parts])
    lengths = np.concatenate([p["lengths"] for p in parts])
    walls = [m["wall_s"] for m in metas]
    uploads = [_upload_seconds(m["phases"]) for m in metas]
    info = ShardedIngestInfo(
        n_workers=n_workers, shards=shards,
        wall_s=max(walls), worker_walls_s=walls,
        upload_s=max(uploads), worker_upload_s=uploads,
        link_utilization=[round(min(1.0, u / w), 4) if w > 0 else 0.0
                          for u, w in zip(uploads, walls)],
        worker_phases=[m["phases"] for m in metas],
        path=metas[0]["path"], wire=metas[0]["wire"])
    result = IngestResult(
        df=df, topk_vals=vals, topk_ids=tids, lengths=lengths,
        names=names, num_docs=len(names),
        df_occupied=int((df > 0).sum()),
        path=f"sharded-{n_workers}proc:{metas[0]['path']}",
        phases={"upload": info.upload_s, "wall": info.wall_s},
        wire=metas[0]["wire"],
        bytes_on_wire=sum(int(m["bytes_on_wire"] or 0) for m in metas))
    if keep_dir is None:
        import shutil
        shutil.rmtree(tmp, ignore_errors=True)
    return result, info


@dataclasses.dataclass(frozen=True)
class HostTopology:
    process_id: int
    num_processes: int
    local_devices: int
    global_devices: int


def initialize(coordinator_address: Optional[str] = None,
               num_processes: Optional[int] = None,
               process_id: Optional[int] = None) -> HostTopology:
    """Bring up the multi-host runtime (idempotent, single-host safe).

    On single-host (no coordinator and no TPU cluster env) this is a
    no-op that just reports the local topology, so the same driver code
    runs everywhere — unlike the reference, which cannot run without an
    MPI runtime even on one node.
    """
    import jax
    if coordinator_address is not None or num_processes is not None:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id)
    elif os.environ.get("JAX_COORDINATOR_ADDRESS"):
        # Cluster env configured (TPU pod / k8s launcher): auto-detect.
        try:
            jax.distributed.initialize()
        except RuntimeError:  # already initialized
            pass
    return HostTopology(
        process_id=jax.process_index(),
        num_processes=jax.process_count(),
        local_devices=jax.local_device_count(),
        global_devices=jax.device_count(),
    )


if __name__ == "__main__":  # the ingest-worker entry launch_ranks spawns
    sys.exit(_worker_main(sys.argv[1]))
