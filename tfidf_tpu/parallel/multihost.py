"""Multi-host (DCN) bring-up.

The reference's runtime bring-up is ``MPI_Init``/``MPI_Finalize`` +
``MPI_Comm_size/rank`` (``TFIDF.c:82-92``); launched as one process per
rank by mpirun. The JAX equivalent for a multi-host TPU slice is
``jax.distributed.initialize`` — one process per host, all chips of all
hosts visible in ``jax.devices()`` afterwards, meshes spanning hosts
transparently (collectives ride ICI within a slice, DCN across slices).
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax


@dataclasses.dataclass(frozen=True)
class HostTopology:
    process_id: int
    num_processes: int
    local_devices: int
    global_devices: int


def initialize(coordinator_address: Optional[str] = None,
               num_processes: Optional[int] = None,
               process_id: Optional[int] = None) -> HostTopology:
    """Bring up the multi-host runtime (idempotent, single-host safe).

    On single-host (no coordinator and no TPU cluster env) this is a
    no-op that just reports the local topology, so the same driver code
    runs everywhere — unlike the reference, which cannot run without an
    MPI runtime even on one node.
    """
    import os
    if coordinator_address is not None or num_processes is not None:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id)
    elif os.environ.get("JAX_COORDINATOR_ADDRESS"):
        # Cluster env configured (TPU pod / k8s launcher): auto-detect.
        try:
            jax.distributed.initialize()
        except RuntimeError:  # already initialized
            pass
    return HostTopology(
        process_id=jax.process_index(),
        num_processes=jax.process_count(),
        local_devices=jax.local_device_count(),
        global_devices=jax.device_count(),
    )
