"""Mesh-sharded end-to-end pipeline.

Wraps the shard_map forward (``parallel.collectives``) with host-side
packing and explicit device placement. The reference's placement model —
rank r reads docs r, r+(size-1), ... from its own process
(``TFIDF.c:130-138``) — becomes: host packs the batch, ``jax.device_put``
with a NamedSharding splits the document axis across the mesh, XLA owns
all further movement.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from tfidf_tpu.config import PipelineConfig, VocabMode
from tfidf_tpu.io.corpus import Corpus, PackedBatch, pack_corpus
from tfidf_tpu.parallel.collectives import (make_sharded_forward,
                                            make_sparse_sharded_forward)
from tfidf_tpu.parallel.mesh import MeshPlan
from tfidf_tpu.pipeline import PipelineResult
from tfidf_tpu.utils.timing import PhaseTimedMixin


class ShardedPipeline(PhaseTimedMixin):
    """TF-IDF over a device mesh.

    EXACT vocab mode is supported but sized from the corpus; HASHED is
    the intended mode at scale (vocab padded to a shard multiple).
    """

    def __init__(self, plan: MeshPlan, config: Optional[PipelineConfig] = None,
                 timer=None):
        self.plan = plan
        self.config = config or PipelineConfig(vocab_mode=VocabMode.HASHED)
        self.timer = timer  # PhaseTimer; see TfidfPipeline docstring

    def pack(self, corpus: Corpus, want_words: bool = True) -> PackedBatch:
        # Doc and token axes must split evenly across the mesh;
        # _pad_to_mesh is the single place that knows how.
        with self._phase("pack"):
            return self._pad_to_mesh(
                pack_corpus(corpus, self.config, want_words=want_words))

    def _pad_to_mesh(self, batch: PackedBatch) -> PackedBatch:
        """Grow a batch to mesh-divisible [D, L] (no-op when already so).

        Lets :class:`~tfidf_tpu.pipeline.TfidfPipeline`'s mesh dispatch
        hand over batches packed without a plan; padding docs are empty
        (length 0) and the masked histogram ignores them by construction.
        """
        d, length = batch.token_ids.shape
        d_t, l_t = self.plan.pad_docs(d), self.plan.pad_tokens(length)
        if (d_t, l_t) == (d, length):
            return batch
        return dataclasses.replace(
            batch,
            token_ids=np.pad(batch.token_ids, ((0, d_t - d), (0, l_t - length))),
            lengths=np.pad(batch.lengths, (0, d_t - d)),
            names=list(batch.names) + [""] * (d_t - d))

    def run_packed(self, batch: PackedBatch) -> PipelineResult:
        cfg = self.config
        if cfg.mesh_shape:
            raise ValueError(
                "config.mesh_shape is ignored by ShardedPipeline — the "
                "MeshPlan passed to the constructor is authoritative "
                "(use TfidfPipeline for config-driven mesh dispatch)")
        batch = self._pad_to_mesh(batch)
        vocab_padded = self.plan.pad_vocab(batch.vocab_size)
        engine = cfg.engine
        if (engine == "sparse"
                and getattr(cfg, "_engine_defaulted", False)
                and (self.plan.n_seq_shards != 1
                     or self.plan.n_vocab_shards != 1)):
            # The measured default picked sparse, but the sparse lowering
            # shards the docs axis only — fall back to the dense lowering
            # for vocab/seq-sharded meshes. Explicit engine="sparse"
            # still errors below (capability, not preference).
            engine = "dense"
        with self._phase("transfer"):
            tokens = jax.device_put(batch.token_ids,
                                    self.plan.sharding(self.plan.batch_spec()))
            lengths = jax.device_put(batch.lengths,
                                     self.plan.sharding(self.plan.lengths_spec()))
            self._fence((tokens, lengths))
        if engine == "sparse":
            return self._run_sparse(batch, tokens, lengths)
        if cfg.use_pallas:
            from tfidf_tpu.ops.pallas_kernels import default_interpret
            interpret = default_interpret()
        else:
            interpret = False
        fwd = make_sharded_forward(self.plan, vocab_padded,
                                   jnp.dtype(cfg.score_dtype), cfg.topk,
                                   use_pallas=cfg.use_pallas,
                                   pallas_interpret=interpret)
        with self._phase("compute"):
            out = fwd(tokens, lengths, jnp.int32(batch.num_docs))
            self._fence(out)
        # topk mode: dense per-shard counts/scores never leave the devices.
        with self._phase("fetch"):
            if cfg.topk is not None:
                counts = None
                df = np.asarray(out[0])[:batch.vocab_size]
            else:
                counts = np.asarray(out[0])[:, :batch.vocab_size]
                df = np.asarray(out[1])[:batch.vocab_size]
            result = PipelineResult(
                counts=counts,
                lengths=np.asarray(batch.lengths),
                df=df,
                num_docs=batch.num_docs,
                names=batch.names,
                id_to_word=batch.id_to_word or {},
            )
            if cfg.topk is not None:
                result.topk_vals = np.asarray(out[1])
                result.topk_ids = np.asarray(out[2])
            else:
                result.scores = np.asarray(out[2])[:, :batch.vocab_size]
        return result

    def _run_sparse(self, batch: PackedBatch, tokens, lengths) -> PipelineResult:
        cfg = self.config
        fwd = make_sparse_sharded_forward(
            self.plan, batch.vocab_size, jnp.dtype(cfg.score_dtype), cfg.topk)
        with self._phase("compute"):
            out = fwd(tokens, lengths, jnp.int32(batch.num_docs))
            self._fence(out)
        with self._phase("fetch"):
            result = PipelineResult(
                counts=None,
                lengths=np.asarray(batch.lengths),
                df=np.asarray(out[0]),
                num_docs=batch.num_docs,
                names=batch.names,
                id_to_word=batch.id_to_word or {},
            )
            if cfg.topk is not None:
                # The round-7 packed result wire, same resolution as
                # TfidfPipeline._fetch_topk: the [D, K] selection
                # crosses the link as device-packed uint32 words —
                # HALF the pair bytes per shard, and (the part the
                # round-18 shim made visible) fp16-rounded scores
                # IDENTICAL to the single-device sparse path, which
                # has packed since round 7. The mesh path had drifted
                # to a full-precision fetch while its tests were dark.
                from tfidf_tpu.ops.downlink import (pack_words,
                                                    unpack_result_words,
                                                    use_packed_result_wire)
                if use_packed_result_wire(cfg,
                                          vocab_size=batch.vocab_size):
                    words = np.asarray(pack_words(out[1], out[2]))
                    result.topk_vals, result.topk_ids = \
                        unpack_result_words(
                            words, score_dtype=cfg.score_dtype)
                else:
                    result.topk_vals = np.asarray(out[1])
                    result.topk_ids = np.asarray(out[2])
            else:
                result.sparse_ids = np.asarray(out[1])
                result.sparse_counts = np.asarray(out[2])
                result.sparse_head = np.asarray(out[3])
        return result

    def run(self, corpus: Corpus) -> PipelineResult:
        return self.run_packed(self.pack(corpus))
