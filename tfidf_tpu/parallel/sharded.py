"""Mesh-sharded end-to-end pipeline.

Wraps the shard_map forward (``parallel.collectives``) with host-side
packing and explicit device placement. The reference's placement model —
rank r reads docs r, r+(size-1), ... from its own process
(``TFIDF.c:130-138``) — becomes: host packs the batch, ``jax.device_put``
with a NamedSharding splits the document axis across the mesh, XLA owns
all further movement.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from tfidf_tpu.config import PipelineConfig, VocabMode
from tfidf_tpu.io.corpus import Corpus, PackedBatch, pack_corpus
from tfidf_tpu.parallel.collectives import (make_sharded_forward,
                                            make_sparse_sharded_forward)
from tfidf_tpu.parallel.mesh import MeshPlan
from tfidf_tpu.pipeline import PipelineResult


class ShardedPipeline:
    """TF-IDF over a device mesh.

    EXACT vocab mode is supported but sized from the corpus; HASHED is
    the intended mode at scale (vocab padded to a shard multiple).
    """

    def __init__(self, plan: MeshPlan, config: Optional[PipelineConfig] = None):
        self.plan = plan
        self.config = config or PipelineConfig(vocab_mode=VocabMode.HASHED)

    def pack(self, corpus: Corpus, want_words: bool = True) -> PackedBatch:
        batch = pack_corpus(corpus, self.config,
                            pad_docs_to=self.plan.pad_docs(len(corpus)),
                            want_words=want_words)
        # Token axis must also split evenly across seq shards.
        lcm_target = self.plan.pad_tokens(batch.token_ids.shape[1])
        if lcm_target != batch.token_ids.shape[1]:
            pad = lcm_target - batch.token_ids.shape[1]
            batch.token_ids = np.pad(batch.token_ids, ((0, 0), (0, pad)))
        return batch

    def run_packed(self, batch: PackedBatch) -> PipelineResult:
        cfg = self.config
        if cfg.use_pallas:
            raise NotImplementedError(
                "use_pallas: Pallas histogram kernel not wired up yet")
        if cfg.mesh_shape:
            raise ValueError(
                "config.mesh_shape is ignored by ShardedPipeline — the "
                "MeshPlan passed to the constructor is authoritative")
        vocab_padded = self.plan.pad_vocab(batch.vocab_size)
        tokens = jax.device_put(batch.token_ids,
                                self.plan.sharding(self.plan.batch_spec()))
        lengths = jax.device_put(batch.lengths,
                                 self.plan.sharding(self.plan.lengths_spec()))
        if cfg.engine == "sparse":
            return self._run_sparse(batch, tokens, lengths)
        fwd = make_sharded_forward(self.plan, vocab_padded,
                                   jnp.dtype(cfg.score_dtype), cfg.topk)
        out = fwd(tokens, lengths, jnp.int32(batch.num_docs))
        # topk mode: dense per-shard counts/scores never leave the devices.
        if cfg.topk is not None:
            counts = None
            df = np.asarray(out[0])[:batch.vocab_size]
        else:
            counts = np.asarray(out[0])[:, :batch.vocab_size]
            df = np.asarray(out[1])[:batch.vocab_size]
        result = PipelineResult(
            counts=counts,
            lengths=np.asarray(batch.lengths),
            df=df,
            num_docs=batch.num_docs,
            names=batch.names,
            id_to_word=batch.id_to_word or {},
        )
        if cfg.topk is not None:
            result.topk_vals = np.asarray(out[1])
            result.topk_ids = np.asarray(out[2])
        else:
            result.scores = np.asarray(out[2])[:, :batch.vocab_size]
        return result

    def _run_sparse(self, batch: PackedBatch, tokens, lengths) -> PipelineResult:
        cfg = self.config
        fwd = make_sparse_sharded_forward(
            self.plan, batch.vocab_size, jnp.dtype(cfg.score_dtype), cfg.topk)
        out = fwd(tokens, lengths, jnp.int32(batch.num_docs))
        result = PipelineResult(
            counts=None,
            lengths=np.asarray(batch.lengths),
            df=np.asarray(out[0]),
            num_docs=batch.num_docs,
            names=batch.names,
            id_to_word=batch.id_to_word or {},
        )
        if cfg.topk is not None:
            result.topk_vals = np.asarray(out[1])
            result.topk_ids = np.asarray(out[2])
        else:
            result.sparse_ids = np.asarray(out[1])
            result.sparse_counts = np.asarray(out[2])
            result.sparse_head = np.asarray(out[3])
        return result

    def run(self, corpus: Corpus) -> PipelineResult:
        return self.run_packed(self.pack(corpus))
