"""Long-document (sequence-parallel) histogram: one doc across the mesh.

The reference streams a document token-by-token on a single rank
(``TFIDF.c:147``) — a document is bounded by one node's memory and one
core's scan speed. The TPU-native long-context capability (SURVEY §5):
split the token stream of ONE document into fixed chunks laid out across
*every* device of the mesh, histogram each chunk locally, and assemble
the document's TF vector with a single ``psum`` over all mesh axes.
This is the ring-attention-shaped pattern for this workload: the
sharded axis is the sequence, the collective rides ICI.

Composes with the batch pipeline: ``ShardedPipeline`` already seq-shards
the token axis of a whole batch (``parallel.collectives``); this module
is the degenerate-but-important case batch=1, where all mesh parallelism
is spent on sequence length.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from tfidf_tpu.ops.histogram import tf_counts_masked
from tfidf_tpu.parallel.mesh import DOCS_AXIS, MeshPlan, SEQ_AXIS, VOCAB_AXIS
from tfidf_tpu.parallel.compat import shard_map

_ALL_AXES = (DOCS_AXIS, SEQ_AXIS, VOCAB_AXIS)


def _body(tokens, length, *, vocab_size: int):
    """Per-device chunk of one document. tokens: [L / n_devices]."""
    chunk = tokens.shape[0]
    # Flat device index in the composite (docs, seq, vocab) order that
    # P(_ALL_AXES) shards the token axis by.
    idx = lax.axis_index(DOCS_AXIS)
    idx = idx * lax.psum(1, SEQ_AXIS) + lax.axis_index(SEQ_AXIS)
    idx = idx * lax.psum(1, VOCAB_AXIS) + lax.axis_index(VOCAB_AXIS)
    pos = idx * chunk + jnp.arange(chunk, dtype=jnp.int32)
    valid = pos < length
    counts = tf_counts_masked(tokens[None, :], valid[None, :], vocab_size)
    # The one collective: assemble the document histogram over ICI.
    return lax.psum(counts[0], _ALL_AXES)


def make_long_doc_histogram(plan: MeshPlan, vocab_size: int):
    """Build f(tokens [L], length) -> counts [V] for one huge document.

    L must be a multiple of the total device count (pad with any id and
    pass the true ``length``). The returned counts are replicated —
    every device holds the document's full TF vector afterwards, ready
    for scoring against a DF table.
    """
    body = functools.partial(_body, vocab_size=vocab_size)
    mapped = shard_map(body, mesh=plan.mesh,
                           in_specs=(P(_ALL_AXES), P()),
                           out_specs=P())
    return jax.jit(mapped)


def long_doc_histogram(plan: MeshPlan, tokens, length, vocab_size: int):
    """One-shot convenience wrapper over :func:`make_long_doc_histogram`."""
    return make_long_doc_histogram(plan, vocab_size)(
        jnp.asarray(tokens), jnp.asarray(length, jnp.int32))
