"""Mesh-sharded serving: ONE logical index doc-sharded across the chips.

ROADMAP item 1's serving half. The serve tier held a single-device
``TfidfRetriever`` — "millions of documents" capped by one HBM. This
module shards the *retriever* the way ``parallel.collectives`` shards
the ingest: the row-sparse BCOO index blocks live block-sharded over
the mesh's ``docs`` axis (``NamedSharding`` over
``MeshPlan.batch_spec``-shaped arrays), a query batch is replicated to
every shard, each shard runs PR 3's fused score/top-k
(``ops.topk.segment_score_topk`` — the BCOO sparse x dense MXU matmul,
unchanged) over ITS rows only, and the per-shard [Q, k] candidates
merge with a device-side top-k-of-top-k (``ops.topk.merge_topk``)
riding ONE ``all_gather`` back — the reference's serial
``MPI_Recv`` gather loop (``TFIDF.c:256-270``) done as a collective.

Parity is the contract, not a hope: every response is BIT-identical —
scores, doc indices, tie order — to the single-device
``TfidfRetriever.search`` of the same corpus (pinned by
tests/test_mesh_serve.py):

* per-row BCOO scoring is row-parallel, so a shard's rows reduce in
  the same order the full matrix would;
* ``lax.top_k`` breaks equal scores by LOWEST index; per-shard
  candidates concatenate in shard (= global row) order through the
  tiled ``all_gather``, so the merge's tie-break reproduces the
  single-device lowest-global-index discipline exactly — the same
  argument ``ops/topk.py`` makes for the segmented index, because it
  is the same primitive.

:class:`MeshShardedRetriever` duck-types the retriever search contract
(``search`` / ``names`` / ``config`` / ``indexed`` / ``_num_docs`` /
``snapshot``) the same way the segmented index's ``IndexView`` does —
which is exactly what lets ``TfidfServer`` hold one where it held a
retriever, and lets every install path (swap, add/delete mutation,
compaction, snapshot-restore) re-shard through one transform.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Sequence, Tuple, Union

import numpy as np

from tfidf_tpu.parallel.compat import shard_map
from tfidf_tpu.parallel.mesh import DOCS_AXIS, MeshPlan

__all__ = ["MeshShardedRetriever", "make_serving_plan", "shard_index",
           "mesh_search_cache_size"]


def _jax():  # deferred so tools can import the module without a backend
    import jax
    import jax.numpy as jnp
    return jax, jnp


def make_serving_plan(n_shards: int,
                      devices: Optional[Sequence] = None) -> MeshPlan:
    """A docs-only serving mesh over the first ``n_shards`` devices
    (``0`` = every device) — the ``--mesh-shards`` resolution."""
    jax, _ = _jax()
    devs = list(devices if devices is not None else jax.devices())
    if n_shards == 0:
        n_shards = len(devs)
    if n_shards < 1:
        raise ValueError("mesh_shards must be >= 1 (0 = all devices)")
    if n_shards > len(devs):
        raise ValueError(
            f"mesh_shards={n_shards} exceeds the {len(devs)} visible "
            f"device(s)")
    return MeshPlan.create(docs=n_shards, devices=devs[:n_shards])


def shard_index(index, plan: MeshPlan,
                keep_source: bool = True) -> "MeshShardedRetriever":
    """Shard any retriever-contract index over ``plan`` (idempotent:
    an already-sharded index on the same plan passes through). The one
    transform every serve install path applies under ``--mesh-shards``."""
    if isinstance(index, MeshShardedRetriever):
        if index.plan is plan:
            return index
        source = index.parity_oracle()
        if source is None:
            raise ValueError("cannot re-shard onto a different plan: "
                             "the single-device source was dropped "
                             "(keep_source=False)")
        index = source
    return MeshShardedRetriever(index, plan, keep_source=keep_source)


# One jitted sharded-search program per (plan, k); module-level so the
# cache survives server installs (steady-state mutation re-runs warm
# programs) and so the bench can read one compiled-program count.
_MESH_SEARCH_FNS: Dict[Tuple, object] = {}
_FNS_LOCK = threading.Lock()


def _make_mesh_search(plan: MeshPlan, k: int, tiled: bool,
                      method: str, tile: int):
    """The sharded serving program: per-shard fused score/top-k, one
    all_gather, device-side merge. Blocks: data/cols [D/s, L] + live
    [D/s] local rows; qmat [V, Q] replicated.

    ``tiled`` (round 21, default on): each shard scans ITS rows in doc
    tiles via ``ops.sparse.score_topk_tiled_trace`` — same per-tile
    memory bound as the flat path, unchanged gather/merge, per-shard
    results bit-identical to the untiled body (the tiled parity
    argument applies shard-locally, so the merged output is too)."""
    jax, jnp = _jax()
    from jax import lax
    from jax.sharding import PartitionSpec as P

    from tfidf_tpu.ops.sparse import score_topk_tiled_trace
    from tfidf_tpu.ops.topk import merge_topk, segment_score_topk

    def body(data, cols, live, qmat):
        d = data.shape[0]
        kk = min(k, d)
        # PR 3's fused BCOO score + tombstone mask + top-k (tiled or
        # not): this shard scores only its own rows. Ids come back
        # shard-local; the axis index globalizes them.
        if tiled:
            vals, ids = score_topk_tiled_trace(
                data, cols, live, qmat, k=kk,
                tile=max(1, min(tile, d)), masked=True, method=method)
        else:
            vals, ids = segment_score_topk(data, cols, live, qmat,
                                           k=kk)
        ids = ids + lax.axis_index(DOCS_AXIS) * d
        # The ONE collective of the query path: k-sized candidate
        # lists (never [D, Q] score rows) gather in shard order...
        vals_g = lax.all_gather(vals, DOCS_AXIS, axis=1, tiled=True)
        ids_g = lax.all_gather(ids, DOCS_AXIS, axis=1, tiled=True)
        # ...and the segmented index's top-k-of-top-k merge re-selects
        # on device. Tie discipline: candidates sit in ascending
        # global-row order among equal scores, so the merge's
        # lowest-position tie-break IS the single-device
        # lowest-doc-index tie-break.
        return merge_topk(vals_g, ids_g, k=min(k, vals_g.shape[1]))

    # check_vma=False: the all_gather+top_k merge replicates the
    # outputs in a way the static replication checker cannot infer —
    # same waiver as every mesh program in parallel/collectives.py.
    return jax.jit(shard_map(
        body, mesh=plan.mesh,
        in_specs=(P(DOCS_AXIS, None), P(DOCS_AXIS, None), P(DOCS_AXIS),
                  P(None, None)),
        out_specs=(P(None, None), P(None, None)), check_vma=False))


def _mesh_search_fn(plan: MeshPlan, k: int):
    # The tiling/lowering knobs resolve at LOOKUP time and ride the
    # cache key, so an env toggle (serve_bench A/B) selects a distinct
    # program instead of silently reusing the other path's jit.
    from tfidf_tpu.ops.sparse import (score_method, score_tile_rows,
                                      score_tiling)
    tiled = score_tiling()
    method = score_method() if tiled else "xla"
    tile = score_tile_rows(1 << 30) if tiled else 0
    key = (plan, k, tiled, method, tile)
    with _FNS_LOCK:
        fn = _MESH_SEARCH_FNS.get(key)
        if fn is None:
            fn = _MESH_SEARCH_FNS[key] = _make_mesh_search(
                plan, k, tiled, method, tile)
        return fn


def mesh_search_cache_size() -> int:
    """Total compiled-program count across every sharded-search
    function built in this process — the mesh serve bench's recompile
    receipt (must be flat after warm-up), the
    ``_search_bcoo._cache_size()`` twin."""
    with _FNS_LOCK:
        fns = list(_MESH_SEARCH_FNS.values())
    return sum(f._cache_size() for f in fns)


class MeshShardedRetriever:
    """One doc-sharded serving index across a device mesh.

    Built FROM an indexed single-device source — a plain
    :class:`~tfidf_tpu.models.retrieval.TfidfRetriever` (snapshot-
    restored ones included) or a segmented
    :class:`~tfidf_tpu.index.IndexView` — whose row-sparse blocks are
    padded to a shard multiple and re-placed block-sharded over the
    plan's ``docs`` axis. Rows keep their global order, so result
    indices (and therefore :attr:`names` positions) are the source's.

    Args:
      source: the indexed retriever-contract object to shard.
      plan: docs-only :class:`MeshPlan` (seq=1, vocab=1).
      keep_source: retain ``source`` as the live single-device parity
        oracle (:meth:`parity_oracle` — what the canary prober
        captures against) and the :meth:`snapshot` delegate. Costs the
        source's HBM on its home device; pass False on deployments
        where the whole point is that one device cannot hold it.
    """

    def __init__(self, source, plan: MeshPlan,
                 keep_source: bool = True) -> None:
        jax, jnp = _jax()
        from jax.sharding import PartitionSpec as P

        if plan.n_vocab_shards != 1 or plan.n_seq_shards != 1:
            raise ValueError("serving shards the docs axis only; build "
                             "the MeshPlan with seq=1, vocab=1")
        if not getattr(source, "indexed", False):
            raise ValueError("shard_index needs an indexed retriever "
                             "(index()/index_dir() first)")
        self.plan = plan
        self.config = source.config
        self.names: List[str] = list(source.names)
        self._num_docs = int(source._num_docs)
        # A sharded view keeps its segmented owner: the server's
        # swap-vs-mutation detach check still sees who the index
        # belongs to through the wrapper.
        self.owner = getattr(source, "owner", None)
        self._source = source if keep_source else None

        data, cols, live, idf = self._host_blocks(source)
        rows = data.shape[0]
        pad = plan.pad_docs(rows) - rows
        if pad:
            data = np.pad(data, ((0, pad), (0, 0)))
            cols = np.pad(cols, ((0, pad), (0, 0)))
            live = np.pad(live, (0, pad))
        self._rows = rows + pad
        sh2 = plan.sharding(P(DOCS_AXIS, None))
        sh1 = plan.sharding(P(DOCS_AXIS))
        self._data = jax.device_put(data, sh2)
        self._cols = jax.device_put(cols, sh2)
        self._live = jax.device_put(live, sh1)
        self._idf = jnp.asarray(idf)
        self._idf_np = np.asarray(idf)
        # Scorer family (round 23): the padded host live mask plus the
        # per-scorer sharded face and per-filter sharded live caches —
        # derived lazily from the retained source, placed once, reused
        # every search at that (scorer, filter).
        self._live_np = live
        self._scorer_cache: Dict[str, tuple] = {}
        self._filter_cache: Dict[str, object] = {}

    @staticmethod
    def _host_blocks(source):
        """Source -> host (data, cols, live, idf) row blocks, values
        byte-identical to what the source's own search scores with.

        * plain retriever: ``where(head, weights, 0)`` /
          ``where(head, ids, 0)`` — exactly the arrays
          ``_search_bcoo`` derives per call; live = the real-doc rows
          (chunk-padding tail rows are all-zero and dead).
        * segmented IndexView: the per-segment parts concatenate in
          segment (= insertion) order — the same padded positional row
          space ``names`` indexes, tombstones riding the live mask.
        """
        parts = getattr(source, "_parts", None)
        if parts is not None:   # segmented IndexView
            data = np.concatenate([np.asarray(p.data) for p in parts])
            cols = np.concatenate([np.asarray(p.cols) for p in parts])
            live = np.concatenate(
                [np.asarray(p.live, dtype=bool) for p in parts])
            return data, cols, live, np.asarray(source._idf)
        head = np.asarray(source._head)
        data = np.where(head, np.asarray(source._weights),
                        np.float32(0.0)).astype(np.float32, copy=False)
        cols = np.where(head, np.asarray(source._ids), 0)
        live = np.arange(head.shape[0]) < source._num_docs
        return data, cols, live, np.asarray(source._idf)

    # --- retriever contract -------------------------------------------
    @property
    def indexed(self) -> bool:
        return True

    @property
    def n_shards(self) -> int:
        return self.plan.n_docs_shards

    def parity_oracle(self):
        """The retained single-device source (None when dropped) — the
        bit-parity reference the canary prober captures its oracle
        from, so the live parity gauge pins sharded-vs-single-device,
        not sharded-vs-itself."""
        return self._source

    def snapshot(self, path: str, epoch: int = 0,
                 extra_meta: Optional[dict] = None) -> str:
        """Persist through the retained source (host-side protocol;
        sharding is a placement, not a format — a restore re-shards)."""
        if self._source is None:
            raise ValueError(
                "snapshot needs the retained single-device source "
                "(shard_index(..., keep_source=True))")
        return self._source.snapshot(path, epoch=epoch,
                                     extra_meta=extra_meta)

    def index_arrays(self) -> list:
        """Live device arrays for the HBM census owner registration."""
        out = [self._idf, self._data, self._cols, self._live]
        for d, c in self._scorer_cache.values():
            out += [d, c]
        out += list(self._filter_cache.values())
        return out

    def shard_stats(self) -> dict:
        """Per-shard HBM truth: bytes each docs-shard holds (summed
        over the sharded index arrays' addressable shards) and the
        max/mean imbalance ratio — what the DeviceMonitor publishes as
        the ``shard_bytes_d*`` gauge family and the doctor budgets
        with ``--shard-imbalance``."""
        dev_to_shard = {}
        devs = np.asarray(self.plan.mesh.devices).reshape(-1)
        for i, dev in enumerate(devs):
            dev_to_shard[dev.id] = i
        per = [0] * self.n_shards
        for arr in (self._data, self._cols, self._live):
            for s in arr.addressable_shards:
                i = dev_to_shard.get(s.device.id)
                if i is not None:
                    per[i] += int(s.data.nbytes)
        mean = sum(per) / max(1, len(per))
        imbalance = (max(per) / mean) if mean else 1.0
        return {"n_shards": self.n_shards, "shard_bytes": per,
                "imbalance": round(imbalance, 4),
                "total_bytes": sum(per)}

    def _scorer_blocks(self, spec) -> tuple:
        """The sharded ``(data, cols)`` face of one scorer, cached per
        key. The face derives ON THE SOURCE through its own device
        programs (``scorer_face`` — the same jits its single-device
        search scores with), pads to the shard multiple and re-places
        block-sharded: placement never touches the bytes, so the
        sharded scored search stays bit-identical to the source's."""
        jax, _ = _jax()
        from jax.sharding import PartitionSpec as P
        key = spec.key()
        blk = self._scorer_cache.get(key)
        if blk is None:
            face = getattr(self._source, "scorer_face", None)
            if face is None:
                raise ValueError(
                    "non-default scorers need the retained "
                    "single-device source (shard_index(..., "
                    "keep_source=True))")
            data, cols = face(spec)
            pad = self._rows - data.shape[0]
            if pad:
                data = np.pad(data, ((0, pad), (0, 0)))
                cols = np.pad(cols, ((0, pad), (0, 0)))
            sh2 = self.plan.sharding(P(DOCS_AXIS, None))
            blk = (jax.device_put(data, sh2),
                   jax.device_put(cols, sh2))
            self._scorer_cache[key] = blk
        return blk

    def _filter_live(self, fspec):
        """The sharded live mask ∧ one filter's allow-mask (host AND,
        then placement), cached per canonical key; no filter returns
        the default live block."""
        if fspec is None:
            return self._live
        jax, _ = _jax()
        from jax.sharding import PartitionSpec as P
        from tfidf_tpu.scoring.filters import filter_mask
        key = fspec.key()
        live = self._filter_cache.get(key)
        if live is None:
            npos = min(self._rows, len(self.names)) or self._num_docs
            mask = np.zeros((self._rows,), bool)
            mask[:npos] = filter_mask(fspec, npos, names=self.names)
            live = jax.device_put(self._live_np & mask,
                                  self.plan.sharding(P(DOCS_AXIS)))
            self._filter_cache[key] = live
        return live

    # --- querying ------------------------------------------------------
    def search(self, queries: Sequence[Union[str, bytes]], k: int = 10,
               *, scorer=None, filter=None
               ) -> Tuple[np.ndarray, np.ndarray]:
        """Ranked retrieval: (scores, doc_indices), each [Q, k'] with
        k' = min(k, num_docs) — bit-identical to the source's
        single-device ``search`` (same blocking, same query bucketing,
        same compiled-program budget discipline). ``scorer``/``filter``
        (round 23) swap in the derived sharded face / composed live
        mask; the mesh program itself is scorer-agnostic, so every
        scorer shares the one compiled sharded-search per (plan, k)."""
        _, jnp = _jax()
        from tfidf_tpu.models.retrieval import (_LEGACY_QUERY_BLOCK,
                                                query_matrix)
        from tfidf_tpu.obs import devmon
        from tfidf_tpu.ops.sparse import score_tiling
        from tfidf_tpu.scoring.family import ScorerSpec, parse_scorer
        from tfidf_tpu.scoring.filters import parse_filter

        spec = ScorerSpec() if scorer is None else parse_scorer(scorer)
        fspec = parse_filter(filter)
        # Tiled (round 21): one dispatch at any Q — the per-shard doc
        # scan bounds memory, so the legacy host-side query split only
        # applies on the --score-tiling=off fallback.
        if (not score_tiling()
                and len(queries) > _LEGACY_QUERY_BLOCK):
            parts = [self.search(queries[s:s + _LEGACY_QUERY_BLOCK], k,
                                 scorer=spec, filter=fspec)
                     for s in range(0, len(queries),
                                    _LEGACY_QUERY_BLOCK)]
            return (np.concatenate([p[0] for p in parts]),
                    np.concatenate([p[1] for p in parts]))
        nq = len(queries)
        width = min(k, self._num_docs)
        if width == 0 or nq == 0:
            return (np.zeros((nq, width), np.float32),
                    np.full((nq, width), -1, np.int64))
        if spec.is_default:
            data, cols = self._data, self._cols
        else:
            data, cols = self._scorer_blocks(spec)
        live = self._filter_live(fspec)
        bucket = 1 << max(0, nq - 1).bit_length()
        qmat = jnp.asarray(query_matrix(
            queries, self.config, self._idf_np, pad_to=bucket,
            mode="counts" if spec.kind == "bm25" else "cosine"))
        fn = _mesh_search_fn(self.plan, k)
        # Compile fingerprinting (round 12): a cache-size delta across
        # the call = a fresh sharded-search program; with a
        # CompileWatch armed past mark_warm that is a steady-state
        # recompile flight event. Same seam retrieval.search uses.
        watch = devmon.get_watch()
        before = fn._cache_size() if watch is not None else None
        vals, idx = fn(data, cols, live, qmat)
        if before is not None and fn._cache_size() > before:
            devmon.note_compile(
                "mesh_search", shards=self.n_shards,
                queries=int(qmat.shape[1]), k=k, rows=self._rows,
                dtype=str(qmat.dtype))
        vals = np.asarray(vals)[:nq, :width]
        idx = np.asarray(idx)[:nq, :width]
        # Dead/padding rows score the sub-zero sentinel and zero-score
        # rows are padding either way — the same result mask the
        # single-device paths apply, so bytes match.
        ok = vals > 0
        return np.where(ok, vals, 0.0), np.where(ok, idx, -1)
