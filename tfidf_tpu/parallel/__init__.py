"""Distributed execution: mesh plans, collectives, sharded pipelines.

The reference's distribution model is MPI ranks + explicit messages
(SURVEY §2.4). Here distribution is *sharding*: a
:class:`jax.sharding.Mesh` over the chips, `shard_map` for the
per-shard program, and XLA collectives over ICI — the reduce+bcast
pair of the reference (``TFIDF.c:215,220``) is one ``lax.psum``.
"""

from tfidf_tpu.parallel.compat import shard_map
from tfidf_tpu.parallel.mesh import MeshPlan, DOCS_AXIS, VOCAB_AXIS, SEQ_AXIS
from tfidf_tpu.parallel.sharded import ShardedPipeline
from tfidf_tpu.parallel.collectives import sharded_tf_df

__all__ = [
    "MeshPlan",
    "DOCS_AXIS",
    "VOCAB_AXIS",
    "SEQ_AXIS",
    "ShardedPipeline",
    "sharded_tf_df",
    "shard_map",
]
