"""The sharded TF-IDF compute: shard_map body + XLA collectives.

Collective mapping from the reference (SURVEY §2.4):

* ``MPI_Reduce(CustomReduce) + MPI_Bcast`` of the DF table
  (``TFIDF.c:215,220``) -> one ``lax.psum`` over the ``docs`` axis. The
  string-keyed set-union semantics are already gone: hashing made DF a
  dense vector, and union-with-sum is vector add.
* ``MPI_Bcast(numDocs)`` (``TFIDF.c:115``) -> a replicated scalar input.
* serial ``MPI_Send``/``Recv`` gather (``TFIDF.c:256-270``) ->
  device-side top-k + ``lax.all_gather`` over the vocab axis.
* six ``MPI_Barrier``s -> nothing; XLA program order is the fence.

The per-shard body computes its own (docs x seq x vocab) block with NO
redundant work: each vocab shard histograms only its own id range
(via ``tf_counts_masked``'s offset/width), each seq shard only its token
chunk, each docs shard only its documents.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from tfidf_tpu.ops.histogram import tf_counts_masked
from tfidf_tpu.parallel.compat import shard_map
from tfidf_tpu.ops.scoring import idf_from_df
from tfidf_tpu.parallel.mesh import DOCS_AXIS, MeshPlan, SEQ_AXIS, VOCAB_AXIS


def _shard_body(tokens, lengths, num_docs, *, vocab_size: int,
                score_dtype, topk: Optional[int],
                use_pallas: bool = False, pallas_interpret: bool = False):
    """Per-shard program. Blocks: tokens [Dl, Ll], lengths [Dl].

    vocab_size here is the *global* (padded) V; each shard owns
    V / n_vocab_shards contiguous ids.
    """
    n_vocab = lax.psum(1, VOCAB_AXIS)
    v_shard = vocab_size // n_vocab
    v_start = lax.axis_index(VOCAB_AXIS) * v_shard

    # Sequence shard: this block holds global token positions
    # [seq_idx*Ll, (seq_idx+1)*Ll) of each document.
    ll = tokens.shape[1]
    seq_start = lax.axis_index(SEQ_AXIS) * ll

    # TF histogram of this shard's vocab range over its token chunk,
    # then combine chunks: the long-document psum (SURVEY §5
    # long-context — a >chip doc's histogram is assembled over ICI).
    if use_pallas:
        # The Pallas kernel masks by remaining length, so translate the
        # global positions into per-shard residual lengths. Counts-only
        # variant: presence must be taken AFTER the seq psum (a chunk's
        # partial counts can undercount it), so the fused df would be
        # dead device work.
        from tfidf_tpu.ops.pallas_kernels import tf_df_pallas
        rem = jnp.clip(lengths - seq_start, 0, ll)
        counts, _ = tf_df_pallas(tokens, rem, vocab_size=v_shard,
                                 id_offset=v_start, with_df=False,
                                 interpret=pallas_interpret)
    else:
        pos = seq_start + jnp.arange(ll, dtype=lengths.dtype)
        live = pos[None, :] < lengths[:, None]
        counts = tf_counts_masked(tokens, live, v_shard, id_offset=v_start)
    counts = lax.psum(counts, SEQ_AXIS)

    # DF: local docs' presence, summed over the docs axis. This single
    # psum is the whole Phase-2 of the reference (TFIDF.c:215-220).
    df = lax.psum((counts > 0).astype(jnp.int32).sum(axis=0), DOCS_AXIS)

    idf = idf_from_df(df, num_docs, score_dtype)
    lens = jnp.maximum(lengths, 1).astype(score_dtype)
    scores = counts.astype(score_dtype) / lens[:, None] * idf[None, :]

    if topk is None:
        return counts, df, scores

    # Per-doc top-k across the vocab axis: local top-k, all_gather the
    # K-sized candidates (not the V-sized rows), re-select. In topk mode
    # the per-shard dense counts/scores never leave the device.
    k_local = min(topk, v_shard)
    vals, ids = lax.top_k(scores, k_local)
    ids = ids + v_start
    vals_g = lax.all_gather(vals, VOCAB_AXIS, axis=1, tiled=True)
    ids_g = lax.all_gather(ids, VOCAB_AXIS, axis=1, tiled=True)
    vals_k, sel = lax.top_k(vals_g, min(topk, vals_g.shape[1]))
    ids_k = jnp.take_along_axis(ids_g, sel, axis=1)
    return df, vals_k, ids_k


@functools.lru_cache(maxsize=64)
def make_sharded_forward(plan: MeshPlan, vocab_size: int, score_dtype,
                         topk: Optional[int], use_pallas: bool = False,
                         pallas_interpret: bool = False):
    """Build the jitted sharded forward for a mesh plan.

    Returns f(tokens [D, L], lengths [D], num_docs) with D a
    docs-shard multiple, L a seq-shard multiple, vocab_size a
    vocab-shard multiple (use plan.pad_*). LRU-cached so repeat runs
    with the same (plan, vocab, dtype, topk) reuse the jitted program
    instead of re-tracing. ``use_pallas`` swaps each shard's histogram
    for the Pallas kernel (``pallas_interpret`` for CPU-mesh tests).
    """
    if vocab_size % plan.n_vocab_shards:
        raise ValueError(f"vocab_size {vocab_size} not divisible by "
                         f"{plan.n_vocab_shards} vocab shards")
    body = functools.partial(_shard_body, vocab_size=vocab_size,
                             score_dtype=score_dtype, topk=topk,
                             use_pallas=use_pallas,
                             pallas_interpret=pallas_interpret)
    if topk is None:
        out_specs = (plan.counts_spec(), plan.df_spec(), plan.counts_spec())
    else:
        out_specs = (plan.df_spec(),
                     P(DOCS_AXIS, None), P(DOCS_AXIS, None))
    # check_vma=False: the top-k outputs are replicated across the vocab
    # axis by the all_gather+re-select, which the static replication
    # checker cannot infer.
    mapped = shard_map(
        body, mesh=plan.mesh,
        in_specs=(plan.batch_spec(), plan.lengths_spec(), P()),
        out_specs=out_specs, check_vma=False)
    return jax.jit(mapped)


def _psum_df(df):
    """DF collective for the sparse shard body (module-level so the jit
    cache key is stable across calls)."""
    return lax.psum(df, (DOCS_AXIS, SEQ_AXIS, VOCAB_AXIS))


def _sparse_shard_body(tokens, lengths, num_docs, *, vocab_size: int,
                       score_dtype, topk: Optional[int]):
    """Row-sparse per-shard program (docs axis only; see ops/sparse.py).

    Sorting is row-local, so only the document axis shards; the [V] DF
    vector is small enough to replicate (256 KB at 2^16 float32), which
    is exactly why the sparse engine needs no vocab sharding. The body IS
    ops/sparse.sparse_forward — only the DF reduction differs.
    """
    from tfidf_tpu.ops.sparse import sparse_forward

    return sparse_forward(tokens, lengths, num_docs, vocab_size=vocab_size,
                          score_dtype=score_dtype, topk=topk,
                          df_reduce=_psum_df)


@functools.lru_cache(maxsize=64)
def make_sparse_sharded_forward(plan: MeshPlan, vocab_size: int, score_dtype,
                                topk: Optional[int]):
    """Sharded row-sparse forward. Requires seq=1 and vocab=1 shards —
    the whole point of the sparse engine is that only the docs axis
    needs to scale (long docs route through the dense seq-sharded path)."""
    if plan.n_seq_shards != 1 or plan.n_vocab_shards != 1:
        raise ValueError("sparse engine shards the docs axis only; build "
                         "the MeshPlan with seq=1, vocab=1")
    body = functools.partial(_sparse_shard_body, vocab_size=vocab_size,
                             score_dtype=score_dtype, topk=topk)
    n_out = 3 if topk is not None else 5
    out_specs = (P(VOCAB_AXIS),) + (P(DOCS_AXIS, None),) * (n_out - 1)
    mapped = shard_map(
        body, mesh=plan.mesh,
        in_specs=(plan.batch_spec(), plan.lengths_spec(), P()),
        out_specs=out_specs, check_vma=False)
    return jax.jit(mapped)


def _chargram_df_psum(df):
    return lax.psum(df, (DOCS_AXIS, SEQ_AXIS, VOCAB_AXIS))


@functools.lru_cache(maxsize=64)
def make_chargram_sharded_forward(plan: MeshPlan, vocab_size: int,
                                  ngram_lo: int, ngram_hi: int, seed: int,
                                  score_dtype, topk: int,
                                  engine: str = "dense"):
    """Sharded device-chargram forward over the docs axis (VERDICT r2
    item 9: mesh chargram no longer detours through the host tokenizer).

    Docs axis only: an n-gram window spans adjacent bytes, so a seq
    shard would need an (n-1)-byte halo exchange — the rolling hash is
    row-local but not chunk-local; long byte streams route through the
    host tokenizer or ``parallel.longdoc``. The body IS the
    single-device ``pipeline._chargram_forward`` (``engine="dense"``)
    or the round-4 row-sparse wide-vocab lowering
    (``pipeline._chargram_sparse_forward``, ``engine="sparse"``) —
    only the DF reduction differs (the sparse engine's sharing
    contract).
    """
    if plan.n_seq_shards != 1 or plan.n_vocab_shards != 1:
        raise ValueError("device chargram shards the docs axis only; "
                         "build the MeshPlan with seq=1, vocab=1")
    if topk is None:
        raise ValueError("sharded device chargram serves topk mode only")
    if engine not in ("dense", "sparse"):
        raise ValueError(f"unknown chargram engine {engine!r}")

    def body(byte_ids, byte_lengths, num_docs):
        from tfidf_tpu.pipeline import (_chargram_forward,
                                        _chargram_sparse_forward)
        fwd = (_chargram_sparse_forward if engine == "sparse"
               else _chargram_forward)
        return fwd(
            byte_ids, byte_lengths, num_docs, vocab_size=vocab_size,
            ngram_lo=ngram_lo, ngram_hi=ngram_hi, seed=seed,
            score_dtype=score_dtype, topk=topk,
            df_reduce=_chargram_df_psum)

    out_specs = (P(VOCAB_AXIS), P(DOCS_AXIS), P(DOCS_AXIS, None),
                 P(DOCS_AXIS, None))
    mapped = shard_map(
        body, mesh=plan.mesh,
        in_specs=(plan.batch_spec(), plan.lengths_spec(), P()),
        out_specs=out_specs, check_vma=False)
    return jax.jit(mapped)


def sharded_tf_df(plan: MeshPlan, tokens, lengths, vocab_size: int
                  ) -> Tuple[jax.Array, jax.Array]:
    """Counts + global DF only (no scoring) — the minimal DP+psum path."""
    fwd = make_sharded_forward(plan, vocab_size, jnp.float32, None)
    counts, df, _ = fwd(tokens, lengths, jnp.int32(1))
    return counts, df
