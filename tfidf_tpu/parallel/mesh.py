"""Device-mesh construction and axis conventions.

Axis semantics (the TPU-native mapping of the reference's parallelism,
SURVEY §2.3):

* ``docs``  — data parallelism over documents. The reference's
  round-robin rank ownership (``TFIDF.c:130``) becomes block-sharding
  the document axis of the packed batch. Unlike the reference, *every*
  device computes — no idle coordinator (the reference wastes rank 0,
  SURVEY §2.3 "do not replicate").
* ``vocab`` — tensor-parallel analog: the hashed vocabulary axis is
  sharded when the DF table / score matrix outgrows one chip.
* ``seq``   — sequence parallelism for long documents: one document's
  token chunks spread across chips, histogram psum'd (``parallel.longdoc``).

Multi-host: the same mesh spans hosts via ``jax.distributed.initialize``
(``parallel.multihost``); mesh-axis order puts ``docs`` outermost so DF
psum segments ride ICI within a slice and only the [V]-sized partial
crosses DCN.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DOCS_AXIS = "docs"
VOCAB_AXIS = "vocab"
SEQ_AXIS = "seq"


@dataclasses.dataclass(frozen=True)
class MeshPlan:
    """A named device mesh plus the sharding rules the pipeline uses.

    Build with :meth:`create`; axis sizes must multiply to the device
    count (all devices participate — SPMD).
    """

    mesh: Mesh

    @staticmethod
    def create(docs: int = 0, vocab: int = 1, seq: int = 1,
               devices: Optional[Sequence[jax.Device]] = None) -> "MeshPlan":
        """Make a (docs, seq, vocab) mesh.

        ``docs=0`` means "all remaining devices": docs is inferred as
        n_devices / (vocab * seq).
        """
        devs = list(devices if devices is not None else jax.devices())
        n = len(devs)
        if docs == 0:
            if n % (vocab * seq) != 0:
                raise ValueError(
                    f"{n} devices not divisible by vocab*seq={vocab * seq}")
            docs = n // (vocab * seq)
        if docs * vocab * seq != n:
            raise ValueError(
                f"mesh {docs}x{seq}x{vocab} != {n} devices")
        arr = np.array(devs).reshape(docs, seq, vocab)
        return MeshPlan(Mesh(arr, (DOCS_AXIS, SEQ_AXIS, VOCAB_AXIS)))

    # --- axis sizes ---
    @property
    def n_docs_shards(self) -> int:
        return self.mesh.shape[DOCS_AXIS]

    @property
    def n_vocab_shards(self) -> int:
        return self.mesh.shape[VOCAB_AXIS]

    @property
    def n_seq_shards(self) -> int:
        return self.mesh.shape[SEQ_AXIS]

    # --- canonical shardings ---
    def batch_spec(self) -> P:
        """[D, L] token batch: docs sharded, token axis seq-sharded."""
        return P(DOCS_AXIS, SEQ_AXIS)

    def lengths_spec(self) -> P:
        return P(DOCS_AXIS)

    def counts_spec(self) -> P:
        """[D, V] counts/scores: docs x vocab sharded."""
        return P(DOCS_AXIS, VOCAB_AXIS)

    def df_spec(self) -> P:
        """[V] DF vector: vocab sharded, replicated over docs/seq."""
        return P(VOCAB_AXIS)

    def sharding(self, spec: P) -> NamedSharding:
        return NamedSharding(self.mesh, spec)

    def pad_docs(self, num_docs: int) -> int:
        """Round a document count up to a docs-shard multiple."""
        shards = self.n_docs_shards
        return int(math.ceil(max(num_docs, 1) / shards) * shards)

    def pad_vocab(self, vocab_size: int) -> int:
        shards = self.n_vocab_shards
        return int(math.ceil(max(vocab_size, 1) / shards) * shards)

    def pad_tokens(self, length: int) -> int:
        shards = self.n_seq_shards
        return int(math.ceil(max(length, 1) / shards) * shards)
