"""tfidf_tpu — a TPU-native distributed TF-IDF framework.

A ground-up JAX/XLA re-design of the capabilities of the MPI reference
(ndas7/Parallel-Systems-MPI-TFIDF, mounted at /root/reference):

* The reference shards documents round-robin across MPI worker ranks
  (``TFIDF.c:130``); here the document axis of a packed token batch is
  sharded across a :class:`jax.sharding.Mesh` axis (``parallel.mesh``).
* The reference builds per-rank term-frequency tables by linear scan
  (``TFIDF.c:147-191``); here TF is a masked scatter-add histogram over a
  hashed vocabulary (``ops.histogram``), O(tokens) instead of O(tokens x
  vocab).
* The reference aggregates document frequencies with a custom
  ``MPI_Reduce`` + ``MPI_Bcast`` pair (``TFIDF.c:215,220``); here that
  reduce-then-rebroadcast is a single ``lax.psum`` over the mesh's ICI
  links (``parallel.collectives``).
* The reference's serial ``MPI_Send``/``MPI_Recv`` gather + root qsort
  (``TFIDF.c:256-283``) is replaced by device-side top-k plus a single
  gather (``ops.topk``).

The exact byte-level semantics of the reference (output format, natural-log
IDF, lexicographic ordering) are preserved by the golden path
(:mod:`tfidf_tpu.golden`) and the clean-room native bit-reference under
``native/``, exposed as ``--backend=mpi`` in the CLI.
"""

from tfidf_tpu.config import (PipelineConfig, ServeConfig, VocabMode,
                              TokenizerKind)
from tfidf_tpu.pipeline import TfidfPipeline, PipelineResult
from tfidf_tpu.io.corpus import (Corpus, discover_corpus, PackedBatch,
                                 RaggedBatch, pack_ragged)
from tfidf_tpu.ingest import (ExactIngest, IngestResult, run_overlapped,
                              run_overlapped_exact)
from tfidf_tpu.rerank import exact_terms, exact_terms_lines, exact_topk

__version__ = "0.1.0"

__all__ = [
    "PipelineConfig",
    "ServeConfig",
    "VocabMode",
    "TokenizerKind",
    "TfidfPipeline",
    "PipelineResult",
    "Corpus",
    "discover_corpus",
    "PackedBatch",
    "RaggedBatch",
    "pack_ragged",
    "ExactIngest",
    "IngestResult",
    "run_overlapped",
    "run_overlapped_exact",
    "exact_terms",
    "exact_terms_lines",
    "exact_topk",
    "__version__",
]
