"""SegmentedIndex: live add/update/delete without rebuilding the world.

``swap_index()`` rebuilds and re-uploads the entire index to change one
document. This module is ROADMAP item 2's fix — the LSM-tree / Lucene
segment model:

* a **delta segment** absorbs streaming ``add_docs`` /
  ``update`` / ``delete_docs`` (packing rides the ``StreamingTfidf``
  machinery with its fixed-length pin; the per-doc sorted triple is
  derived on host by the bit-identical numpy mirror, so mutation never
  traces a fresh device program);
* the delta **seals** into an immutable segment when full
  (``segment_seal`` flight event);
* deletes/updates are **tombstone mask bits** applied before top-k
  (``ops.topk.segment_score_topk`` — the document-filter building
  block ROADMAP item 4 wants), with the doc's DF contribution
  subtracted in exact integer arithmetic;
* search = per-segment fused score/top-k (PR 3's BCOO kernel,
  unchanged) + device-side **top-k-of-top-k merge**
  (``ops.topk.merge_topk``), against the **corrected global DF/IDF**
  over live segments — so every response is bit-identical to a
  from-scratch rebuild of the live corpus (:meth:`rebuild_retriever`,
  pinned by tests/test_index.py);
* **compaction** merges sealed segments through one pass
  (``compaction`` flight event, rehearsable mid-merge via the ``swap``
  fault seam), dropping tombstones;
* **epoch-based visibility**: every mutation bumps :attr:`version` and
  invalidates the cached :class:`IndexView`; views are immutable
  snapshots that duck-type the ``TfidfRetriever`` search contract, so
  in-flight server queries keep the view they were admitted under.

Persistence reuses ``checkpoint.save_index`` (seq+LATEST, per-array
sha256, typed ``SnapshotMismatch``): a sealed segment *is* a
``save_index`` snapshot, flattened under per-segment key prefixes.
"""

from __future__ import annotations

import functools
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple, Union

import numpy as np

from tfidf_tpu import faults, obs
from tfidf_tpu.config import PipelineConfig, VocabMode
from tfidf_tpu.index.segment import Segment
from tfidf_tpu.io.corpus import Corpus, discover_corpus
from tfidf_tpu.models.retrieval import (_LEGACY_QUERY_BLOCK,
                                        TfidfRetriever, _build_index,
                                        config_fingerprint, query_matrix)
from tfidf_tpu.obs import log as obs_log
from tfidf_tpu.ops.sparse import (score_tile_rows, score_tiling,
                                  score_topk_tiled,
                                  score_topk_tiled_cache_size,
                                  sorted_term_counts_host, sparse_scores)
from tfidf_tpu.ops.scoring import idf_from_df
from tfidf_tpu.ops.topk import merge_topk, segment_score_topk
from tfidf_tpu.scoring.family import (ScorerSpec, avgdl_f32,
                                      parse_scorer)
from tfidf_tpu.scoring.filters import (FilterSpec, filter_mask,
                                       parse_filter)
from tfidf_tpu.streaming import StreamingTfidf

__all__ = ["SegmentedIndex", "IndexView"]


def _jax():  # deferred so tools can import the module without a backend
    import jax
    import jax.numpy as jnp
    return jax, jnp


@functools.lru_cache(maxsize=1)
def _jitted():
    """The per-visibility-change device programs, shaped only by
    (capacity, length) / vocab — steady-state mutation re-runs warm
    executables, never traces (the zero-recompiles pin). Round 23 adds
    the bm25 twins: same shapes, scorer parameters traced, so a
    scorer's face refresh joins the warm set after one trace."""
    jax, jnp = _jax()

    @jax.jit
    def idf_fn(df, num_docs):
        return idf_from_df(df, num_docs, jnp.float32)

    @jax.jit
    def refresh_weights(ids, counts, head, lengths, idf):
        # Identical float sequence to retrieval._build_index's tail:
        # gather-scored rows, L2 norm, guard — per-row ops, so a row's
        # weights match a from-scratch rebuild of the same row at the
        # same L bit-for-bit.
        scores = sparse_scores(ids, counts, head, lengths, idf)
        norm = jnp.sqrt(jnp.sum(scores * scores, axis=1, keepdims=True))
        weights = scores / jnp.maximum(norm, 1e-30)
        data = jnp.where(head, weights, 0.0)
        cols = jnp.where(head, ids, 0)
        return data, cols

    @jax.jit
    def bm25_idf_fn(df, num_docs):
        from tfidf_tpu.scoring.family import bm25_idf_from_df
        return bm25_idf_from_df(df, num_docs)

    @jax.jit
    def refresh_weights_bm25(ids, counts, head, lengths, idf, avgdl,
                             k1, b):
        # The ONE bm25 elementwise sequence (scoring.family) over a
        # segment's stored triple — the same function the flat
        # retriever's derived face traces, which is the whole
        # flat-vs-segmented bm25 bit-parity argument (avgdl/k1/b are
        # traced f32: retuning never compiles).
        from tfidf_tpu.scoring.family import bm25_weights
        return bm25_weights(ids, counts, head, lengths, idf, avgdl,
                            k1, b)

    return idf_fn, refresh_weights, bm25_idf_fn, refresh_weights_bm25


def index_compile_cache_size() -> int:
    """Total compiled-program count across the segmented search path —
    the mutate bench's recompile receipt (diffed across the measured
    window; must be flat after warm-up)."""
    return sum(f._cache_size() for f in
               _jitted() + (segment_score_topk,
                            merge_topk)) + score_topk_tiled_cache_size()


class _ViewPart:
    """One segment's device-resident face inside a view."""

    __slots__ = ("data", "cols", "live", "base", "rows")

    def __init__(self, data, cols, live, base: int, rows: int) -> None:
        self.data = data
        self.cols = cols
        self.live = live
        self.base = base
        self.rows = rows


class IndexView:
    """An immutable snapshot of the segmented index at one version.

    Duck-types the ``TfidfRetriever`` search contract (``search`` /
    ``names`` / ``config`` / ``indexed`` / ``_num_docs`` /
    ``snapshot``), which is exactly what lets ``TfidfServer`` hold a
    view where it held a retriever: in-flight requests finish on the
    view they were admitted under while mutations install newer views.

    ``names`` is positional over PADDED rows (tombstoned and unused
    rows hold ``""``); only live rows can surface in results, so the
    holes are unreachable by construction.
    """

    def __init__(self, owner: "SegmentedIndex", version: int,
                 config: PipelineConfig, parts: List[_ViewPart],
                 names: List[str], idf, idf_np: np.ndarray,
                 num_live: int,
                 triples: Optional[list] = None,
                 df_np: Optional[np.ndarray] = None,
                 total_len: int = 0) -> None:
        self.owner = owner
        self.version = version
        self.config = config
        self._parts = parts
        self.names = names
        self._idf = idf
        self._idf_np = idf_np
        self._num_docs = num_live
        # Lazily-built stacked face of every part (round 21): the
        # one-dispatch tiled search scans segments as ONE row block.
        self._stack: Optional[tuple] = None
        # Scorer family (round 23): the per-part stored triples, the
        # corrected global DF and the exact live token total this view
        # was built against — everything a non-default scorer's face
        # derivation needs — plus the per-scorer stacked faces and
        # per-filter live masks, cached lazily (views are immutable,
        # so each derives at most once).
        self._triples = triples or []
        self._df_np = df_np
        self._total_len = int(total_len)
        self._scorer_stacks: dict = {}
        self._filter_masks: dict = {}

    @property
    def indexed(self) -> bool:
        return True

    @property
    def num_segments(self) -> int:
        return len(self._parts)

    def index_arrays(self) -> list:
        """Live device arrays for the HBM census owner registration."""
        out = [self._idf]
        for p in self._parts:
            out += [p.data, p.cols, p.live]
        if self._stack is not None:
            out += list(self._stack)
        for st in self._scorer_stacks.values():
            out += list(st)
        out += list(self._filter_masks.values())
        return out

    def _stacked(self):
        """The parts stacked into ONE row block (data, cols, live),
        built lazily per view and cached: views are immutable, so the
        concatenation cost is paid once per visibility change, not per
        search (a racing double-build is benign — same values). Rows
        pad to the next power of two with dead rows so the stacked
        shape — and therefore the tiled search program — cycles within
        a log-small warmable set as segments seal and compact (the
        zero-recompiles-under-mutation contract, same discipline as
        pow2 segment capacities). Base offsets are cumulative part
        capacities (``view()``), so stacked row order IS the global
        positional row space ``names`` indexes; the lowest-index
        tie-break therefore reproduces the per-part merge exactly."""
        st = self._stack
        if st is None:
            _, jnp = _jax()
            parts = self._parts
            if len(parts) == 1:
                data, cols, live = (parts[0].data, parts[0].cols,
                                    parts[0].live)
            else:
                data = jnp.concatenate([p.data for p in parts], axis=0)
                cols = jnp.concatenate([p.cols for p in parts], axis=0)
                live = jnp.concatenate([p.live for p in parts], axis=0)
            total = data.shape[0]
            pad = _next_pow2(total) - total
            if pad:
                data = jnp.pad(data, ((0, pad), (0, 0)))
                cols = jnp.pad(cols, ((0, pad), (0, 0)))
                live = jnp.pad(live, (0, pad))
            self._stack = st = (data, cols, live)
        return st

    def snapshot(self, path: str, epoch: int = 0,
                 extra_meta: Optional[dict] = None) -> str:
        """Persist the owning index's CURRENT state (which may be a
        version or two ahead of this view — a snapshot is a restart
        artifact, not a historical one)."""
        return self.owner.save(path, epoch=epoch, extra_meta=extra_meta)

    def search(self, queries: Sequence[Union[str, bytes]], k: int = 10,
               *, scorer=None, filter=None
               ) -> Tuple[np.ndarray, np.ndarray]:
        """Ranked retrieval over the live segments: (scores, doc
        positions), each [Q, k'] with k' = min(k, live docs).
        ``doc positions`` index :attr:`names`; -1 marks padding. Same
        bucketing discipline as ``TfidfRetriever.search``, so the
        compiled-program budget is shared. ``scorer``/``filter``
        (round 23) select another scorer-family member / restrict the
        candidate set; the default combination runs the pre-round-23
        body unchanged. Filter doc ids are POSITIONS in this view's
        row space (what results return); name-prefix filters are the
        position-independent form.

        Tiled (round 21, default ON): every segment stacks into ONE
        doc-tiled scan — K segments cost one device dispatch plus the
        in-scan merge, not K dispatches. ``--score-tiling=off``
        restores the per-segment dispatch loop + host-side 64-wide
        query split; results are bit-identical either way (stacked row
        order is the per-part base order, so the tie discipline
        matches — see ``ops.sparse``'s parity argument)."""
        if scorer is not None or filter is not None:
            spec = (ScorerSpec() if scorer is None
                    else parse_scorer(scorer))
            fspec = parse_filter(filter)
            if not (spec.is_default and fspec is None):
                return self._search_scored(queries, k, spec, fspec)
        _, jnp = _jax()
        tiled = score_tiling()
        if not tiled and len(queries) > _LEGACY_QUERY_BLOCK:
            blk = _LEGACY_QUERY_BLOCK
            parts = [self.search(queries[s:s + blk], k)
                     for s in range(0, len(queries), blk)]
            return (np.concatenate([p[0] for p in parts]),
                    np.concatenate([p[1] for p in parts]))
        nq = len(queries)
        width = min(k, self._num_docs)
        if not self._parts or width == 0:
            return (np.zeros((nq, width), np.float32),
                    np.full((nq, width), -1, np.int64))
        bucket = 1 << max(0, nq - 1).bit_length()
        qmat = jnp.asarray(query_matrix(queries, self.config,
                                        self._idf_np, pad_to=bucket))
        if tiled:
            data, cols, live = self._stacked()
            rows = int(data.shape[0])
            tile = score_tile_rows(rows)
            with obs.span("score_tile", tiles=-(-rows // tile),
                          rows=rows, segments=len(self._parts),
                          queries=int(bucket)):
                vals, idx = score_topk_tiled(data, cols, live, qmat,
                                             k, tile=tile)
        else:
            vals_parts, ids_parts = [], []
            for part in self._parts:
                kk = min(k, part.rows)
                vals, idx = segment_score_topk(part.data, part.cols,
                                               part.live, qmat, k=kk)
                vals_parts.append(vals)
                ids_parts.append(idx + part.base)
            if len(vals_parts) == 1:
                vals_cat, ids_cat = vals_parts[0], ids_parts[0]
            else:
                vals_cat = jnp.concatenate(vals_parts, axis=1)
                ids_cat = jnp.concatenate(ids_parts, axis=1)
            ksel = min(k, vals_cat.shape[1])
            vals, idx = merge_topk(vals_cat, ids_cat, k=ksel)
        vals = np.asarray(vals)[:nq, :width]
        idx = np.asarray(idx)[:nq, :width]
        ok = vals > 0
        return np.where(ok, vals, 0.0), np.where(ok, idx, -1)

    def _face(self, spec: ScorerSpec):
        """The stacked ``(data, cols)`` face of one scorer, cached per
        key for this view's lifetime. tfidf IS the default stacked
        face; bm25 refreshes every part's stored triple through the
        shared ``refresh_weights_bm25`` jit against this view's global
        DF/avgdl, then stacks with the identical pow2-pad discipline —
        row order (and therefore tie order) matches the default stack
        by construction."""
        key = spec.key()
        st = self._scorer_stacks.get(key)
        if st is not None:
            return st
        if spec.kind == "tfidf":
            data, cols, _ = self._stacked()
            st = (data, cols)
        else:
            _, jnp = _jax()
            _, _, bm25_idf_fn, refresh_bm25 = _jitted()
            idf_b = bm25_idf_fn(
                jnp.asarray(self._df_np.astype(np.int32)),
                jnp.int32(self._num_docs))
            avgdl = avgdl_f32(self._total_len, self._num_docs)
            d_parts, c_parts = [], []
            for ids_d, counts_d, head_d, lens_d in self._triples:
                d_, c_ = refresh_bm25(ids_d, counts_d, head_d, lens_d,
                                      idf_b, avgdl,
                                      np.float32(spec.k1),
                                      np.float32(spec.b))
                d_parts.append(d_)
                c_parts.append(c_)
            if len(d_parts) == 1:
                data, cols = d_parts[0], c_parts[0]
            else:
                data = jnp.concatenate(d_parts, axis=0)
                cols = jnp.concatenate(c_parts, axis=0)
            total = data.shape[0]
            pad = _next_pow2(total) - total
            if pad:
                data = jnp.pad(data, ((0, pad), (0, 0)))
                cols = jnp.pad(cols, ((0, pad), (0, 0)))
            st = (data, cols)
        self._scorer_stacks[key] = st
        return st

    def scorer_face(self, spec=None) -> Tuple[np.ndarray, np.ndarray]:
        """Host copy of a scorer's stacked ``(data, cols)`` face,
        trimmed to the concatenated part rows (the pow2 search pad
        stripped) — the row space ``MeshShardedRetriever._host_blocks``
        shards, derived through the SAME device programs this view
        searches with (the sharded-vs-view bit-parity contract)."""
        spec = ScorerSpec() if spec is None else parse_scorer(spec)
        data, cols = self._face(spec)
        total = sum(p.rows for p in self._parts)
        return np.asarray(data)[:total], np.asarray(cols)[:total]

    def _filter_live(self, fspec: Optional[FilterSpec]):
        """The stacked live mask ∧ one filter's allow-mask (tombstone
        composition is literally this boolean AND), cached per
        canonical filter key; no filter returns the tombstone mask
        itself."""
        if fspec is None:
            return self._stacked()[2]
        key = fspec.key()
        live = self._filter_masks.get(key)
        if live is None:
            _, jnp = _jax()
            base = np.asarray(self._stacked()[2])
            npos = min(base.shape[0], len(self.names))
            mask = np.zeros((base.shape[0],), bool)
            mask[:npos] = filter_mask(fspec, npos, names=self.names)
            live = jnp.asarray(base & mask)
            self._filter_masks[key] = live
        return live

    def _search_scored(self, queries: Sequence[Union[str, bytes]],
                       k: int, spec: ScorerSpec,
                       fspec: Optional[FilterSpec]
                       ) -> Tuple[np.ndarray, np.ndarray]:
        """Non-default (scorer, filter) search over this view: same
        stacked kernel, derived face + composed live mask, bm25
        queries packed as raw counts. Tiled and untiled lowerings are
        bit-identical per scorer (the untiled path scores the stack as
        one segment — same rows, same tie space)."""
        _, jnp = _jax()
        tiled = score_tiling()
        if not tiled and len(queries) > _LEGACY_QUERY_BLOCK:
            blk = _LEGACY_QUERY_BLOCK
            parts = [self._search_scored(queries[s:s + blk], k, spec,
                                         fspec)
                     for s in range(0, len(queries), blk)]
            return (np.concatenate([p[0] for p in parts]),
                    np.concatenate([p[1] for p in parts]))
        nq = len(queries)
        width = min(k, self._num_docs)
        if not self._parts or width == 0:
            return (np.zeros((nq, width), np.float32),
                    np.full((nq, width), -1, np.int64))
        bucket = 1 << max(0, nq - 1).bit_length()
        qmat = jnp.asarray(query_matrix(
            queries, self.config, self._idf_np, pad_to=bucket,
            mode="counts" if spec.kind == "bm25" else "cosine"))
        data, cols = self._face(spec)
        live = self._filter_live(fspec)
        rows = int(data.shape[0])
        if tiled:
            tile = score_tile_rows(rows)
            with obs.span("score_tile", tiles=-(-rows // tile),
                          rows=rows, segments=len(self._parts),
                          queries=int(bucket)):
                vals, idx = score_topk_tiled(data, cols, live, qmat,
                                             k, tile=tile)
        else:
            vals, idx = segment_score_topk(data, cols, live, qmat,
                                           k=min(k, rows))
        vals = np.asarray(vals)[:nq, :width]
        idx = np.asarray(idx)[:nq, :width]
        ok = vals > 0
        return np.where(ok, vals, 0.0), np.where(ok, idx, -1)


def _next_pow2(n: int) -> int:
    return 1 << max(0, n - 1).bit_length()


class SegmentedIndex:
    """The mutable LSM-style index (see module docstring).

    Thread-safe: every mutation and every :meth:`view` build runs
    under one re-entrant lock. Views themselves are immutable and
    lock-free to search.

    Args:
      config: HASHED-vocab pipeline config; ``max_doc_len`` pins the
        token axis of EVERY segment (the one static L all compiled
        programs share — and the L the rebuild oracle packs at).
      delta_docs: delta-segment capacity; a full delta seals.
      compact_at: sealed-segment count at which :meth:`compact`
        actually merges (``force=True`` merges from 2).
    """

    def __init__(self, config: Optional[PipelineConfig] = None,
                 delta_docs: int = 1024, compact_at: int = 4) -> None:
        cfg = config or PipelineConfig(vocab_mode=VocabMode.HASHED)
        if cfg.vocab_mode is not VocabMode.HASHED:
            raise ValueError("SegmentedIndex requires HASHED vocab "
                             "(fixed id space across mutations)")
        if delta_docs < 1:
            raise ValueError("delta_docs must be >= 1")
        if compact_at < 2:
            raise ValueError("compact_at must be >= 2")
        self.config = cfg
        self.delta_docs = delta_docs
        self.compact_at = compact_at
        self._length = cfg.max_doc_len
        # Packing reuses the streaming ingest machinery: fixed_len pins
        # the token axis so every mutation batch shares one shape.
        self._stream = StreamingTfidf(cfg)
        self._lock = threading.RLock()
        self._sealed: List[Segment] = []
        self._delta = Segment(delta_docs, self._length, cfg.vocab_size,
                              seg_id=0)
        self._next_seg_id = 1
        self._loc: Dict[str, Tuple[Segment, int]] = {}
        self._version = 1
        self._view: Optional[IndexView] = None
        self.compactions: List[dict] = []   # last-N summaries (bench)

    # --- construction -------------------------------------------------
    @classmethod
    def from_corpus(cls, corpus: Corpus,
                    config: Optional[PipelineConfig] = None,
                    delta_docs: int = 1024,
                    compact_at: int = 4) -> "SegmentedIndex":
        """Bulk-load a corpus as ONE sealed base segment (capacity the
        next power of two — compaction keeps that discipline, so
        steady-state segment shapes cycle within a small warmable
        set), then open a fresh delta for mutations."""
        idx = cls(config, delta_docs=delta_docs, compact_at=compact_at)
        if len(corpus):
            base = Segment(
                _next_pow2(max(len(corpus), delta_docs)),
                idx._length, idx.config.vocab_size, seg_id=0)
            ids, counts, head, lengths = idx._pack_rows(
                corpus.names, corpus.docs)
            with idx._lock:
                for i, name in enumerate(corpus.names):
                    row = base.add_row(ids[i], counts[i], head[i],
                                       int(lengths[i]), name)
                    idx._loc[name] = (base, row)
                base.seal()
                idx._sealed.append(base)
                idx._delta.seg_id = idx._next_seg_id
                idx._next_seg_id += 1
                idx._bump_locked()
        return idx

    @classmethod
    def from_dir(cls, input_dir: str,
                 config: Optional[PipelineConfig] = None,
                 delta_docs: int = 1024, compact_at: int = 4,
                 strict: bool = True) -> "SegmentedIndex":
        return cls.from_corpus(discover_corpus(input_dir, strict),
                               config, delta_docs=delta_docs,
                               compact_at=compact_at)

    # --- state --------------------------------------------------------
    @property
    def version(self) -> int:
        """Visibility version: bumps on EVERY change a query could
        observe (add, update, delete, seal, compaction install). The
        server maps bumps onto its epoch, which keys the result
        cache — the no-stale-hit contract."""
        with self._lock:
            return self._version

    @property
    def num_docs(self) -> int:
        with self._lock:
            return self._live_locked()

    @property
    def sealed_count(self) -> int:
        with self._lock:
            return len(self._sealed)

    def stats(self) -> dict:
        """Gauge feed: segment/delta/tombstone counts."""
        with self._lock:
            segs = self._sealed + ([self._delta] if self._delta.used
                                   else [])
            return {
                "segments": len(segs),
                "sealed": len(self._sealed),
                "delta_used": self._delta.used,
                "delta_capacity": self._delta.capacity,
                "delta_fill": self._delta.used / self._delta.capacity,
                "tombstones": sum(s.tombstones for s in segs),
                "live_docs": self._live_locked(),
                "version": self._version,
            }

    def _live_locked(self) -> int:
        total = sum(s.live_docs for s in self._sealed)
        return total + self._delta.live_docs

    def _bump_locked(self) -> None:
        self._version += 1
        self._view = None

    # --- mutation -----------------------------------------------------
    def _pack_rows(self, names: Sequence[str], docs: Sequence[bytes]):
        """Docs -> host row-sparse triples at the pinned L, through the
        streaming packer + the numpy sorted-counts mirror."""
        docs = [d.encode() if isinstance(d, str) else bytes(d)
                for d in docs]
        batch = self._stream.pack(Corpus(names=list(names), docs=docs),
                                  fixed_len=self._length)
        ids, counts, head = sorted_term_counts_host(
            batch.token_ids, batch.lengths)
        return ids, counts, head, batch.lengths

    def add_docs(self, names: Sequence[str],
                 docs: Sequence[Union[str, bytes]]) -> dict:
        """Add (or update — same name replaces) documents. Returns
        ``{"added", "updated", "sealed", "version"}``. One visibility
        bump per call, covering any seal it triggered."""
        if len(names) != len(docs):
            raise ValueError("names and docs must align")
        if not names:
            return {"added": 0, "updated": 0, "sealed": 0,
                    "version": self.version}
        ids, counts, head, lengths = self._pack_rows(names, docs)
        added = updated = sealed = 0
        with self._lock:
            for i, name in enumerate(names):
                old = self._loc.get(name)
                if old is not None:
                    old[0].tombstone(old[1])
                    updated += 1
                else:
                    added += 1
                if self._delta.full:
                    self._seal_locked()
                    sealed += 1
                row = self._delta.add_row(ids[i], counts[i], head[i],
                                          int(lengths[i]), name)
                self._loc[name] = (self._delta, row)
            self._bump_locked()
            version = self._version
        return {"added": added, "updated": updated, "sealed": sealed,
                "version": version}

    def delete_docs(self, names: Sequence[str]) -> dict:
        """Tombstone documents by name. Returns ``{"deleted",
        "missing", "version"}``; no visibility bump when nothing was
        actually deleted (deleting a missing doc changes nothing a
        query could observe)."""
        deleted = missing = 0
        with self._lock:
            for name in names:
                loc = self._loc.pop(name, None)
                if loc is None:
                    missing += 1
                    continue
                loc[0].tombstone(loc[1])
                deleted += 1
            if deleted:
                self._bump_locked()
            version = self._version
        return {"deleted": deleted, "missing": missing,
                "version": version}

    def _seal_locked(self) -> None:
        delta = self._delta
        delta.seal()
        self._sealed.append(delta)
        self._delta = Segment(self.delta_docs, self._length,
                              self.config.vocab_size,
                              seg_id=self._next_seg_id)
        self._next_seg_id += 1
        obs_log.log_event(
            "info", "segment_seal",
            msg=f"delta sealed: segment {delta.seg_id} "
                f"({delta.live_docs}/{delta.used} live), "
                f"{len(self._sealed)} sealed segment(s)",
            seg_id=delta.seg_id, docs=delta.used,
            live=delta.live_docs, sealed_segments=len(self._sealed))

    # --- compaction ---------------------------------------------------
    @property
    def needs_compaction(self) -> bool:
        with self._lock:
            return len(self._sealed) >= self.compact_at

    def compact(self, force: bool = False) -> Optional[dict]:
        """Merge the sealed segments into one, dropping tombstones and
        preserving insertion order. Runs under the index lock:
        mutations pause (the measured ``pause_s``), searches on
        existing views do not. The merged state installs atomically
        AFTER the ``swap`` fault seam fires — a compactor killed
        mid-merge leaves the index exactly as it was (the chaos pin).
        Returns the summary dict, or None when below threshold."""
        t0 = time.monotonic()
        with self._lock:
            inputs = list(self._sealed)
            threshold = 2 if force else self.compact_at
            if len(inputs) < threshold:
                return None
            with obs.span("compact", segments=len(inputs)):
                live_total = sum(s.live_docs for s in inputs)
                dropped = sum(s.tombstones for s in inputs)
                merged = Segment(
                    _next_pow2(max(live_total, self.delta_docs)),
                    self._length, self.config.vocab_size,
                    seg_id=self._next_seg_id)
                mapping: List[Tuple[str, int]] = []
                for seg in inputs:           # insertion order
                    for row in range(seg.used):
                        if not seg.live[row]:
                            continue
                        r2 = merged.add_row(
                            seg.ids[row], seg.counts[row],
                            seg.head[row], int(seg.lengths[row]),
                            seg.names[row])
                        mapping.append((seg.names[row], r2))
                merged.seal()
                # The rehearsable crash point: a fault here kills the
                # compactor AFTER the merge work, BEFORE any state
                # changed — the supervised restart retries cleanly.
                faults.fire("swap", op="compact", segments=len(inputs),
                            docs=live_total)
                self._next_seg_id += 1
                self._sealed = [merged]
                for name, row in mapping:
                    self._loc[name] = (merged, row)
                self._bump_locked()
                version = self._version
        pause_s = time.monotonic() - t0
        summary = {"segments_in": len(inputs), "docs": live_total,
                   "dropped_tombstones": dropped,
                   "capacity": merged.capacity,
                   "pause_s": round(pause_s, 6), "version": version}
        with self._lock:
            self.compactions.append(summary)
            del self.compactions[:-64]
        obs_log.log_event(
            "info", "compaction",
            msg=f"compacted {len(inputs)} segments -> {live_total} "
                f"live docs (dropped {dropped} tombstones) in "
                f"{pause_s * 1e3:.1f} ms",
            **summary)
        return summary

    # --- visibility ---------------------------------------------------
    def view(self) -> IndexView:
        """The current immutable snapshot (cached per version). Builds
        the corrected global DF/IDF over live segments and refreshes
        every segment's weights against it — the price of scores that
        are bit-identical to a from-scratch rebuild of the live
        corpus."""
        _, jnp = _jax()
        idf_fn, refresh_weights = _jitted()[:2]
        with self._lock:
            if self._view is not None:
                return self._view
            src = self._sealed + ([self._delta] if self._delta.used
                                  else [])
            df = np.zeros((self.config.vocab_size,), np.int64)
            total_len = 0
            for seg in src:
                df += seg.df
                # Exact-integer live token total — the avgdl numerator
                # a non-default scorer's face derivation will need.
                total_len += int((seg.lengths.astype(np.int64)
                                  * seg.live).sum())
            num_live = self._live_locked()
            idf = idf_fn(jnp.asarray(df.astype(np.int32)),
                         jnp.int32(num_live))
            idf_np = np.asarray(idf)
            parts: List[_ViewPart] = []
            triples: list = []
            names: List[str] = []
            base = 0
            for seg in src:
                ids_d, counts_d, head_d, lens_d = seg.device_triple()
                data, cols = refresh_weights(ids_d, counts_d, head_d,
                                             lens_d, idf)
                parts.append(_ViewPart(data, cols,
                                       jnp.asarray(seg.live), base,
                                       seg.capacity))
                triples.append((ids_d, counts_d, head_d, lens_d))
                names += [n if n is not None else ""
                          for n in seg.names]
                base += seg.capacity
            self._view = IndexView(self, self._version, self.config,
                                   parts, names, idf, idf_np, num_live,
                                   triples=triples, df_np=df,
                                   total_len=total_len)
            return self._view

    # --- oracle / fallback --------------------------------------------
    def live_rows(self):
        """(token_rows [D_live, L], lengths, names) of the live corpus
        in insertion order. The stored SORTED ids are a valid token
        sequence for a rebuild — sorting a sorted row is the identity,
        so the rebuilt triple is bit-identical to the original's."""
        with self._lock:
            src = self._sealed + ([self._delta] if self._delta.used
                                  else [])
            toks, lens, names = [], [], []
            for seg in src:
                for row in range(seg.used):
                    if not seg.live[row]:
                        continue
                    toks.append(seg.ids[row])
                    lens.append(int(seg.lengths[row]))
                    names.append(seg.names[row])
        if not toks:
            return (np.zeros((0, self._length), np.int32),
                    np.zeros((0,), np.int32), [])
        return (np.stack(toks).astype(np.int32),
                np.asarray(lens, np.int32), names)

    def rebuild_retriever(self) -> TfidfRetriever:
        """A FROM-SCRATCH ``TfidfRetriever`` over the live corpus —
        packed at the same pinned L, built through the retriever's own
        ``_build_index`` program (fresh sort, fresh DF, fresh IDF,
        fresh weights). This is both the bit-parity oracle the tests
        hold every served response against and the ``swap_index``
        full-rebuild fallback."""
        _, jnp = _jax()
        toks, lens, names = self.live_rows()
        if not len(names):
            raise RuntimeError("rebuild_retriever needs >= 1 live doc")
        r = TfidfRetriever(self.config)
        ids, weights, head, idf = _build_index(
            jnp.asarray(toks), jnp.asarray(lens),
            jnp.int32(len(names)), vocab_size=self.config.vocab_size)
        r._ids, r._weights, r._head, r._idf = ids, weights, head, idf
        r.names = names
        r._num_docs = len(names)
        return r

    # --- persistence --------------------------------------------------
    def save(self, path: str, epoch: int = 0,
             extra_meta: Optional[dict] = None) -> str:
        """Persist every segment (sealed + delta) as ONE
        ``checkpoint.save_index`` commit — seq+LATEST atomicity and
        per-array checksums for free. A process killed at any instant
        restores the previous committed state."""
        from tfidf_tpu import checkpoint as ckpt
        with self._lock:
            segs = self._sealed + [self._delta]
            arrays: Dict[str, np.ndarray] = {}
            seg_meta = []
            for i, seg in enumerate(segs):
                arrays.update(seg.to_arrays(f"seg{i}_"))
                seg_meta.append({"used": seg.used,
                                 "sealed": seg.sealed,
                                 "seg_id": seg.seg_id})
            meta = {
                "num_docs": self._live_locked(),
                "epoch": int(epoch),
                "config_sha": config_fingerprint(self.config),
                "vocab_size": int(self.config.vocab_size),
                "segmented": {
                    "delta_docs": self.delta_docs,
                    "compact_at": self.compact_at,
                    "length": self._length,
                    "next_seg_id": self._next_seg_id,
                    "segments": seg_meta,
                },
            }
            if extra_meta:
                meta.update(extra_meta)
            return ckpt.save_index(path, arrays, meta)

    @classmethod
    def restore(cls, path: str,
                config: Optional[PipelineConfig] = None
                ) -> Tuple["SegmentedIndex", dict]:
        """Rebuild a SegmentedIndex from a committed snapshot:
        ``(index, meta)``. Raises ``checkpoint.SnapshotMismatch`` on a
        config-fingerprint mismatch or a non-segmented snapshot."""
        from tfidf_tpu import checkpoint as ckpt
        arrays, meta = ckpt.restore_index(path)
        seg_info = meta.get("segmented")
        if not isinstance(seg_info, dict):
            raise ckpt.SnapshotMismatch(
                "committed snapshot is not a segmented index "
                "(plain retriever snapshot? restore it with "
                "TfidfRetriever.restore)")
        if config is None:
            config = PipelineConfig(
                vocab_mode=VocabMode.HASHED,
                vocab_size=int(meta.get("vocab_size", 1 << 16)),
                max_doc_len=int(seg_info.get("length", 256)))
        want = config_fingerprint(config)
        if meta.get("config_sha") != want:
            raise ckpt.SnapshotMismatch(
                f"snapshot config fingerprint "
                f"{meta.get('config_sha')!r} != running config "
                f"{want!r} — rebuild instead of serving a mismatched "
                f"index")
        idx = cls(config, delta_docs=int(seg_info["delta_docs"]),
                  compact_at=int(seg_info["compact_at"]))
        segs = []
        for i, sm in enumerate(seg_info["segments"]):
            segs.append(Segment.from_arrays(
                f"seg{i}_", arrays, sm, config.vocab_size))
        with idx._lock:
            idx._sealed = segs[:-1]
            idx._delta = segs[-1]
            idx._delta.sealed = False
            idx._next_seg_id = int(seg_info.get("next_seg_id",
                                                len(segs)))
            idx._loc = {}
            for seg in segs:
                for row in range(seg.used):
                    if seg.live[row] and seg.names[row] is not None:
                        idx._loc[seg.names[row]] = (seg, row)
            idx._bump_locked()
        return idx, meta
