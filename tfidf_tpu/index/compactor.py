"""Supervised background compaction — the PR 8 restart discipline.

The compactor is a worker like the batcher loop: it runs on a cadence,
its crashes are contained by a bounded restart budget (each one a
``worker_restart`` flight event, ``worker="compactor"`` — the doctor's
faults section counts them), and past the budget it declares itself
dead LOUDLY instead of silently leaving segments to pile up. A crash
mid-merge is harmless by construction: ``SegmentedIndex.compact``
installs nothing until after the ``swap`` fault seam, so the retry
starts from exactly the pre-crash state (the chaos pin in
tests/test_index.py).
"""

from __future__ import annotations

import threading
from typing import Callable, Optional

from tfidf_tpu.obs import log as obs_log

__all__ = ["Compactor"]


class Compactor:
    """Periodic compaction driver over a tick callable.

    Args:
      tick: zero-arg callable doing one threshold-checked compaction
        pass (``TfidfServer.compact_now`` — compacts the attached
        index and installs the new view; a no-op below threshold).
      period_s: polling cadence.
      restart_budget: crashes tolerated before the compactor declares
        itself dead (``0`` = die on the first crash).
    """

    def __init__(self, tick: Callable[[], Optional[dict]],
                 period_s: float = 0.5,
                 restart_budget: int = 3) -> None:
        if period_s <= 0:
            raise ValueError("period_s must be positive")
        if restart_budget < 0:
            raise ValueError("restart_budget must be >= 0")
        self._tick = tick
        self.period_s = period_s
        self.restart_budget = restart_budget
        self._lock = threading.Lock()
        self._restarts = 0
        self._dead = False
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    @property
    def restarts(self) -> int:
        with self._lock:
            return self._restarts

    @property
    def dead(self) -> bool:
        with self._lock:
            return self._dead

    def start(self) -> "Compactor":
        if self._thread is not None and self._thread.is_alive():
            return self
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="tfidf-compactor")
        self._thread.start()
        return self

    def _run(self) -> None:
        while not self._stop.wait(self.period_s):
            try:
                self._tick()
            except Exception as e:  # noqa: BLE001 — supervision point:
                # the crash is the evidence; the budget bounds it.
                with self._lock:
                    self._restarts += 1
                    n = self._restarts
                    dead = n > self.restart_budget
                    self._dead = dead
                obs_log.log_event(
                    "error" if dead else "warning", "worker_restart",
                    msg=f"compactor crashed ({e!r}); "
                        + ("restart budget exhausted — compactor dead"
                           if dead else
                           f"restart {n}/{self.restart_budget}"),
                    worker="compactor", error=repr(e), restarts=n)
                if dead:
                    return

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=5)
            self._thread = None
