"""One segment of the LSM-style index: a fixed-capacity slab of docs.

A segment is the unit everything else composes: the **delta** is a
segment still absorbing rows; **sealing** just flips it immutable;
**compaction** copies live rows of many sealed segments into one fresh
segment; a **snapshot** is its arrays through ``checkpoint.save_index``.

Host-master representation. Each row holds one document's row-sparse
triple — ``(ids, counts, head)`` exactly as
``ops.sparse.sorted_term_counts`` would produce it (derived on host by
the bit-identical numpy mirror ``sorted_term_counts_host``, so a
streaming add never traces a fresh device program per batch size) —
plus its token count, name, and a live bit (tombstones). The per-
segment DF vector is maintained *incrementally* in exact integer
arithmetic: a row's distinct-term histogram is added on insert and
subtracted on tombstone, so the global DF over live segments is always
equal to what a from-scratch rebuild of the live corpus would count.

Device state is derived, never authoritative: the int triple uploads
once per content revision (adds/seals/compaction), and only the float
weights — which depend on the *global* IDF, i.e. on every mutation
anywhere — are recomputed per visibility change
(``segmented._refresh_weights``). All jitted shapes are pinned by the
segment's (capacity, length), so steady-state mutation re-runs warm
programs instead of tracing new ones.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

__all__ = ["Segment"]


class Segment:
    """A fixed-capacity document slab (see module docstring).

    Not thread-safe on its own: ``SegmentedIndex`` owns the lock.
    """

    def __init__(self, capacity: int, length: int, vocab_size: int,
                 seg_id: int = 0) -> None:
        if capacity < 1 or length < 1:
            raise ValueError("segment capacity/length must be >= 1")
        self.capacity = capacity
        self.length = length
        self.vocab_size = vocab_size
        self.seg_id = seg_id
        self.ids = np.zeros((capacity, length), np.int32)
        self.counts = np.zeros((capacity, length), np.int32)
        self.head = np.zeros((capacity, length), bool)
        self.lengths = np.zeros((capacity,), np.int32)
        self.live = np.zeros((capacity,), bool)
        self.names: List[Optional[str]] = [None] * capacity
        self.df = np.zeros((vocab_size,), np.int32)
        self.used = 0          # rows ever filled (append-only)
        self.sealed = False
        # content_rev: bumps on any change to the INT arrays (adds,
        # never tombstones — the live mask rides separately), the key
        # the device triple cache invalidates on.
        self.content_rev = 0
        self._dev: Optional[tuple] = None  # (rev, ids, counts, head, lens)

    # --- derived counts ---
    @property
    def live_docs(self) -> int:
        return int(self.live.sum())

    @property
    def tombstones(self) -> int:
        return self.used - self.live_docs

    @property
    def full(self) -> bool:
        return self.used >= self.capacity

    # --- mutation (delta only; SegmentedIndex holds the lock) ---
    def _row_df(self, row: int) -> np.ndarray:
        """One row's distinct-term histogram — its exact (integer) DF
        contribution, derived from the head-masked triple."""
        terms = self.ids[row][self.head[row]]
        return np.bincount(terms, minlength=self.vocab_size).astype(
            np.int32)

    def add_row(self, ids_row: np.ndarray, counts_row: np.ndarray,
                head_row: np.ndarray, length: int, name: str) -> int:
        """Append one document; returns its row. Caller checks
        :attr:`full` first and seals on overflow."""
        if self.sealed:
            raise RuntimeError("segment is sealed")
        if self.full:
            raise RuntimeError("segment is full")
        row = self.used
        self.ids[row] = ids_row
        self.counts[row] = counts_row
        self.head[row] = head_row
        self.lengths[row] = length
        self.live[row] = True
        self.names[row] = name
        self.df += self._row_df(row)
        self.used += 1
        self.content_rev += 1
        return row

    def tombstone(self, row: int) -> None:
        """Delete one document: flip its live bit and subtract its DF
        contribution — the mask half happens at search time
        (``ops.topk.segment_score_topk``), the scoring half here, so
        global IDF stays equal to a rebuild of the live corpus."""
        if not self.live[row]:
            return
        self.live[row] = False
        self.df -= self._row_df(row)

    def seal(self) -> None:
        self.sealed = True

    # --- device triple cache ---
    def device_triple(self):
        """The int triple as device arrays, uploaded once per content
        revision (tombstones do NOT re-upload — the live mask is a
        separate tiny array the view ships per visibility change)."""
        import jax.numpy as jnp
        if self._dev is None or self._dev[0] != self.content_rev:
            self._dev = (self.content_rev,
                         jnp.asarray(self.ids),
                         jnp.asarray(self.counts),
                         jnp.asarray(self.head),
                         jnp.asarray(self.lengths))
        return self._dev[1:]

    # --- persistence (checkpoint.save_index array dict) ---
    def to_arrays(self, prefix: str) -> Dict[str, np.ndarray]:
        blob = np.frombuffer(
            "\x00".join(n if n is not None else ""
                        for n in self.names).encode("utf-8"),
            dtype=np.uint8)
        return {
            f"{prefix}ids": self.ids,
            f"{prefix}counts": self.counts,
            f"{prefix}head": self.head,
            f"{prefix}lengths": self.lengths,
            f"{prefix}live": self.live,
            f"{prefix}names_blob": blob,
        }

    @classmethod
    def from_arrays(cls, prefix: str, arrays: Dict[str, np.ndarray],
                    meta: Dict, vocab_size: int) -> "Segment":
        ids = np.asarray(arrays[f"{prefix}ids"], np.int32)
        capacity, length = ids.shape
        seg = cls(capacity, length, vocab_size,
                  seg_id=int(meta.get("seg_id", 0)))
        seg.ids = ids
        seg.counts = np.asarray(arrays[f"{prefix}counts"], np.int32)
        seg.head = np.asarray(arrays[f"{prefix}head"], bool)
        seg.lengths = np.asarray(arrays[f"{prefix}lengths"], np.int32)
        seg.live = np.asarray(arrays[f"{prefix}live"], bool)
        blob = arrays[f"{prefix}names_blob"]
        names = (bytes(blob.tobytes()).decode("utf-8").split("\x00")
                 if blob.size else [""] * capacity)
        seg.names = [n if n else None for n in names]
        seg.used = int(meta["used"])
        seg.sealed = bool(meta.get("sealed", True))
        # DF is derived state: recompute from the live triples rather
        # than trusting a stored vector to stay consistent with them.
        df = np.zeros((vocab_size,), np.int64)
        for row in range(seg.used):
            if seg.live[row]:
                df += seg._row_df(row)
        seg.df = df.astype(np.int32)
        seg.content_rev = 1
        return seg
