"""LSM-style segmented index: live add/update/delete without
rebuilding the world (ROADMAP item 2; docs/SERVING.md "Live
mutation").

Composition::

    SegmentedIndex ── delta Segment (absorbing adds/updates)
        │                 └─ seals when full  -> sealed Segment
        ├─ sealed Segments (immutable, compacted in the background)
        └─ view() -> IndexView  (immutable snapshot; duck-types the
                                 TfidfRetriever search contract)

Every mutation bumps the visibility version; ``TfidfServer`` maps
bumps onto its epoch (cache keys, canary oracle re-capture, in-flight
snapshot isolation all ride the same bump). Search = per-segment fused
score/top-k + device top-k-of-top-k merge against the corrected global
DF/IDF — bit-identical to a from-scratch rebuild of the live corpus.
"""

from tfidf_tpu.index.compactor import Compactor
from tfidf_tpu.index.segment import Segment
from tfidf_tpu.index.segmented import (IndexView, SegmentedIndex,
                                       index_compile_cache_size)

__all__ = ["SegmentedIndex", "IndexView", "Segment", "Compactor",
           "index_compile_cache_size"]
