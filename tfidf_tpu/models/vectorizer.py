"""Fit/transform TF-IDF vectorizer over the TPU pipeline.

Estimator semantics:

* ``fit(corpus)`` learns the DF table and document count — the global
  state the reference computes in its reduce+bcast phase
  (``TFIDF.c:215-220``) — streaming minibatches through the incremental
  DF accumulator so corpora never need to fit in memory at once.
* ``transform(corpus)`` scores documents against the fitted DF: TF from
  each document, IDF from the fitted state — i.e. out-of-corpus
  documents get consistent scores, something the reference's single-shot
  design cannot express at all.
* ``fit_transform(corpus)`` is the reference's one-shot semantics: DF
  and scores from the same corpus.

Requires HASHED vocab (fixed id space, like the streaming engine).
"""

from __future__ import annotations

from typing import Iterable, Optional, Tuple, Union

import numpy as np

from tfidf_tpu.config import PipelineConfig, VocabMode
from tfidf_tpu.io.corpus import Corpus
from tfidf_tpu.parallel.mesh import MeshPlan
from tfidf_tpu.streaming import StreamingTfidf


class TfidfVectorizer:
    """Scikit-style TF-IDF estimator on the TPU engines.

    Args:
      config: pipeline config (must be HASHED vocab mode; default 2^16).
      plan: optional MeshPlan for sharded fitting/transform.
      batch_docs: minibatch size used when fitting from an iterable.
    """

    def __init__(self, config: Optional[PipelineConfig] = None,
                 plan: Optional[MeshPlan] = None, batch_docs: int = 1024):
        self.config = config or PipelineConfig(vocab_mode=VocabMode.HASHED)
        if self.config.vocab_mode is not VocabMode.HASHED:
            raise ValueError("TfidfVectorizer requires HASHED vocab")
        self.plan = plan
        self.batch_docs = batch_docs
        self._stream = StreamingTfidf(self.config, plan)

    # --- estimator API ---
    @property
    def fitted(self) -> bool:
        return self._stream.docs_seen > 0

    @property
    def num_docs_(self) -> int:
        return self._stream.docs_seen

    @property
    def df_(self) -> np.ndarray:
        return self._stream.df()

    @property
    def idf_(self) -> np.ndarray:
        """Fitted IDF vector (natural log, unsmoothed — ``TFIDF.c:243``)."""
        df = self._stream.df().astype(np.float64)
        n = max(self._stream.docs_seen, 1)
        out = np.zeros_like(df)
        nz = df > 0
        out[nz] = np.log(n / df[nz])
        return out

    def fit(self, corpus: Union[Corpus, Iterable[Corpus]]) -> "TfidfVectorizer":
        """Learn DF state from scratch (sklearn fit semantics: a second
        fit REPLACES the previous state; use partial_fit to accumulate)."""
        self._stream = StreamingTfidf(self.config, self.plan)
        return self.partial_fit(corpus)

    def partial_fit(self, corpus: Union[Corpus, Iterable[Corpus]]
                    ) -> "TfidfVectorizer":
        """Fold more documents into the existing DF state (streaming)."""
        for batch in self._as_batches(corpus):
            self._stream.update(self._stream.pack(batch))
        return self

    def transform(self, corpus: Corpus
                  ) -> Union[np.ndarray, Tuple[np.ndarray, np.ndarray]]:
        """Score documents against the fitted DF.

        Returns a dense [D, V] array, or — when ``config.topk`` is set —
        a ``(values [D, K], ids [D, K])`` tuple.
        """
        if not self.fitted:
            raise RuntimeError("transform before fit")
        out = self._stream.score(self._stream.pack(corpus))
        if self.config.topk is not None:
            vals, ids = out
            return np.asarray(vals)[: len(corpus)], np.asarray(ids)[: len(corpus)]
        return np.asarray(out)[: len(corpus), : self.config.vocab_size]

    def fit_transform(self, corpus: Corpus):
        return self.fit(corpus).transform(corpus)

    # --- state ---
    def state_dict(self):
        return self._stream.state_dict()

    def load_state(self, state) -> "TfidfVectorizer":
        self._stream.load_state(state)
        return self

    def _as_batches(self, corpus) -> Iterable[Corpus]:
        if isinstance(corpus, Corpus):
            for i in range(0, len(corpus), self.batch_docs):
                yield Corpus(names=corpus.names[i:i + self.batch_docs],
                             docs=corpus.docs[i:i + self.batch_docs])
        else:
            yield from corpus
