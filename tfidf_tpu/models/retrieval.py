"""TF-IDF document retrieval: cosine search over the term-doc matrix.

The reference stops at emitting per-(word, doc) scores
(``TFIDF.c:274-282``); the canonical *use* of those scores is ranked
document retrieval, and on TPU that is exactly the BCOO sparse
term-document matmul the BASELINE north star names: the indexed corpus
is a row-sparse TF-IDF matrix, a query becomes a dense [V] vector, and
similarity = one sparse x dense matmul on the MXU.

Two execution paths, same results (pinned by tests):

* single device — ``jax.experimental.sparse.bcoo_dot_general`` of the
  indexed [D, V] BCOO against the [V, Q] query block;
* docs-sharded — the row-sparse triples stay block-sharded over the
  mesh's ``docs`` axis (``shard_map``); each shard scores its rows by
  gathering query weights at its term ids, takes a *local* top-k, and
  one ``all_gather`` of k x shards candidates per query replaces any
  full [D, Q] materialization — the same serial-gather fix as the
  pipeline's top-k (SURVEY §7 "hard parts").

Scores are cosine similarities in [0, 1]: document rows and query
columns are both L2-normalized at build time.
"""

from __future__ import annotations

import functools
import hashlib
import json
import os
from typing import List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.experimental import sparse as jsparse
from jax.sharding import PartitionSpec as P

from tfidf_tpu.config import PipelineConfig, VocabMode
from tfidf_tpu.io.corpus import Corpus, discover_corpus, pack_corpus
from tfidf_tpu import obs
from tfidf_tpu.obs import devmon
from tfidf_tpu.ops.hashing import words_to_ids
from tfidf_tpu.ops.scoring import idf_from_df
from tfidf_tpu.ops.sparse import (score_method, score_tile_rows,
                                  score_tiling, score_topk_tiled,
                                  score_topk_tiled_cache_size,
                                  score_topk_tiled_trace,
                                  sorted_term_counts, sparse_df,
                                  sparse_scores)
from tfidf_tpu.scoring.family import (ScorerSpec, avgdl_f32,
                                      bm25_face_trace, doc_lengths_host,
                                      parse_scorer, resolve_scorer)
from tfidf_tpu.scoring.filters import (FilterSpec, filter_key,
                                       filter_mask, parse_filter)
from tfidf_tpu.ops.tokenize import whitespace_tokenize
from tfidf_tpu.parallel.mesh import DOCS_AXIS, MeshPlan
from tfidf_tpu.parallel.compat import shard_map


@functools.partial(jax.jit, static_argnames=("vocab_size",))
def _build_index(token_ids, lengths, num_docs, *, vocab_size: int):
    """Tokens -> (ids, weights, head, idf): L2-normalized row-sparse TF-IDF."""
    ids, counts, head = sorted_term_counts(token_ids, lengths)
    df = sparse_df(ids, head, vocab_size)
    idf = idf_from_df(df, num_docs, jnp.float32)
    scores = sparse_scores(ids, counts, head, lengths, idf)
    norm = jnp.sqrt(jnp.sum(scores * scores, axis=1, keepdims=True))
    weights = scores / jnp.maximum(norm, 1e-30)
    return ids, weights, head, idf


@jax.jit
def _finish_index(trip_i, trip_c, trip_h, len_parts, df_acc, num_docs):
    """Chunk-ingested triples -> (ids, weights, head, idf).

    The indexing twin of ``ingest._finish_wire``: the per-chunk sort +
    DF fold already ran (``ingest._chunk_step`` — the SAME compiled
    programs the overlapped ingest dispatches), so finishing is one
    gather-scored normalization against the corpus-wide IDF.
    """
    cat = (lambda parts: parts[0] if len(parts) == 1
           else jnp.concatenate(parts, axis=0))
    ids, counts, head = cat(trip_i), cat(trip_c), cat(trip_h)
    lengths = cat(len_parts)
    idf = idf_from_df(df_acc, num_docs, jnp.float32)
    scores = sparse_scores(ids, counts, head, lengths, idf)
    norm = jnp.sqrt(jnp.sum(scores * scores, axis=1, keepdims=True))
    weights = scores / jnp.maximum(norm, 1e-30)
    return ids, weights, head, idf


@functools.partial(jax.jit, static_argnames=("k",))
def _search_bcoo(data, cols, qmat, *, k: int):
    """[D, V] BCOO x [V, Q] dense on the MXU -> per-query top-k docs.

    ``qmat`` is consumed (round 19): every call site stages a fresh
    query block (the slab's ring-buffer upload or a one-shot
    ``jnp.asarray``), never touches it after the call, and the slab
    path deletes it explicitly once results land — so the allocator
    recycles ONE device block per pow2 bucket in steady-state serving.
    An actual ``donate_argnums`` entry is the measured honest negative
    (docs/SCALING.md round 19): XLA can only honor donation by
    aliasing an output, and no [Q, k] output can alias the [V, Q]
    block, so donation degrades to a per-dispatch "not usable"
    warning with zero memory effect — explicit post-dispatch delete
    gives the same one-recycled-block guarantee, silently."""
    d = data.shape[0]
    mat = jsparse.BCOO((data, cols), shape=(d, qmat.shape[0]))
    sims = jsparse.bcoo_dot_general(
        mat, qmat, dimension_numbers=(((1,), (0,)), ((), ())))  # [D, Q]
    vals, idx = lax.top_k(sims.T, k)                            # [Q, k]
    return vals, idx


# The --score-tiling=off fallback splits query batches at this fixed
# width — the measured-safe 64-query block the untiled [nse, Qb]
# intermediate demands at the 100k bench shape. No longer a knob:
# TFIDF_TPU_QUERY_BLOCK now names the tiled path's DOC tile width
# (ops.sparse.score_tile_rows), which is what bounds memory instead.
_LEGACY_QUERY_BLOCK = 64


def _start_d2h(*arrays) -> None:
    """Kick off the device-to-host copy of each result array without
    blocking (``jax.Array.copy_to_host_async``). Values that are
    already host arrays (the resolved-fallback paths) simply lack the
    method and are skipped; a backend that cannot start the copy early
    still materializes correctly at the blocking read."""
    for a in arrays:
        start = getattr(a, "copy_to_host_async", None)
        if start is not None:
            try:
                start()
            except RuntimeError:
                pass


class PendingSearch:
    """A dispatched-but-unmaterialized search (round 22).

    The handle the pipelined serve path overlaps on: the dispatch
    stage (:meth:`TfidfRetriever.search_async`) has already staged the
    query block, issued the jitted program, and started the D2H copy;
    :meth:`materialize` blocks on the result words, releases the slab
    slot, and applies the same trim/mask tail ``search`` always
    applied — so ``search_async(q, k).materialize()`` is bit-identical
    to the synchronous path by construction (it IS the synchronous
    path).

    Device failures (a poisoned dispatch, an injected fault, a real
    XLA error) surface at ``materialize()`` — the drain-time seam the
    batcher's supervisor hooks. A handle materializes at most once;
    callers that need the rows twice keep the returned pair.
    """

    __slots__ = ("_materialize", "_result")

    def __init__(self, materialize=None, result=None):
        self._materialize = materialize
        self._result = result

    @classmethod
    def resolved(cls, vals, ids) -> "PendingSearch":
        """An already-materialized handle — the eager fallback for
        paths that cannot defer (legacy block split, duck-typed
        retrievers without a dispatch stage)."""
        return cls(result=(vals, ids))

    @property
    def done(self) -> bool:
        return self._result is not None

    def materialize(self) -> Tuple[np.ndarray, np.ndarray]:
        if self._result is None:
            fn, self._materialize = self._materialize, None
            if fn is None:
                raise RuntimeError(
                    "PendingSearch already failed to materialize — "
                    "re-dispatch instead of re-reading")
            self._result = fn()
        return self._result


@functools.partial(jax.jit, static_argnames=("k", "tile", "method"))
def _search_tiled(ids, weights, head, qmat, *, k: int, tile: int,
                  method: str):
    """The round-21 flat-index search program: doc-tiled scan + on-
    device streaming top-k (``ops.sparse.score_topk_tiled_trace``),
    ONE dispatch for any Q. Takes the raw index triple so the
    data/cols masking that ``_search_bcoo`` callers staged eagerly
    (two extra device ops per search) fuses into the same program.
    ``qmat`` is consumed by convention, exactly like ``_search_bcoo``
    (same slab delete discipline, same donation honest negative)."""
    data = jnp.where(head, weights, 0.0)
    cols = jnp.where(head, ids, 0)
    return score_topk_tiled_trace(data, cols, None, qmat, k=k,
                                  tile=tile, masked=False,
                                  method=method)


@jax.jit
def _tfidf_face(ids, weights, head):
    """Stored triple -> the dense-safe ``(data, cols)`` pair the tiled
    kernel consumes — the exact two ``where`` ops ``_search_tiled``
    fuses inline, lifted out for the scorer-family path (round 23)
    where the face is cached per scorer instead of re-masked per
    dispatch. Elementwise, so bit-identical to the fused form."""
    return jnp.where(head, weights, 0.0), jnp.where(head, ids, 0)


@functools.partial(jax.jit, static_argnames=("vocab_size",))
def _bm25_face(ids, head, num_docs, avgdl, k1, b, *, vocab_size: int):
    """The BM25 derived face (round 23): everything — counts, lengths,
    df — re-derived on device from the stored ``(ids, head)`` pair via
    ``scoring.family.bm25_face_trace``, so the snapshot format and
    ``_build_index`` stay byte-identical to round 22. ``num_docs`` /
    ``avgdl`` / ``k1`` / ``b`` are TRACED scalars: retuning k1/b
    re-derives a face without compiling a new program."""
    return bm25_face_trace(ids, head, num_docs, avgdl, k1, b,
                           vocab_size=vocab_size)


def _make_search_sharded(plan: MeshPlan, k: int):
    """Docs-sharded search: local gather-score + local top-k + all_gather."""
    mesh = plan.mesh
    n_shards = plan.n_docs_shards

    def body(ids, weights, head, qmat):
        # ids/weights/head: [D/s, L] local rows; qmat: [V, Q] replicated.
        safe = jnp.where(head, ids, 0)
        w = jnp.where(head, weights, 0.0)
        # Gather+dot over fixed L-chunks: the peak intermediate is the
        # [D/s, chunk, Q] gather of one chunk, not the full [D/s, L, Q]
        # contribution tensor (L/chunk x smaller at scale).
        d, length = safe.shape
        chunk = min(length, 128)
        pad = -length % chunk
        safe_c = jnp.pad(safe, ((0, 0), (0, pad)))
        w_c = jnp.pad(w, ((0, 0), (0, pad)))
        safe_c = safe_c.reshape(d, -1, chunk).transpose(1, 0, 2)
        w_c = w_c.reshape(d, -1, chunk).transpose(1, 0, 2)

        def step(acc, xs):
            ids_k, w_k = xs                              # [D/s, chunk]
            return acc + jnp.einsum("dc,dcq->dq", w_k, qmat[ids_k]), None

        sims0 = jnp.zeros((d, qmat.shape[1]), qmat.dtype)
        sims, _ = lax.scan(step, sims0, (safe_c, w_c))   # [D/s, Q]
        local_k = min(k, sims.shape[0])
        vals, idx = lax.top_k(sims.T, local_k)           # [Q, local_k]
        base = lax.axis_index(DOCS_AXIS) * sims.shape[0]
        idx = idx + base                                 # globalize
        vals = lax.all_gather(vals, DOCS_AXIS, axis=1, tiled=True)
        idx = lax.all_gather(idx, DOCS_AXIS, axis=1, tiled=True)
        best, sel = lax.top_k(vals, min(k, local_k * n_shards))
        return best, jnp.take_along_axis(idx, sel, axis=1)

    return jax.jit(shard_map(
        body, mesh=mesh,
        in_specs=(P(DOCS_AXIS, None), P(DOCS_AXIS, None), P(DOCS_AXIS, None),
                  P(None, None)),
        out_specs=(P(None, None), P(None, None)),
        check_vma=False))


def fill_query_matrix(queries: Sequence[Union[str, bytes]],
                      config: PipelineConfig, idf: np.ndarray,
                      out: np.ndarray,
                      scratch: Optional[np.ndarray] = None,
                      mode: str = "cosine") -> np.ndarray:
    """Pack queries into the [V, Q] query block ``out`` IN PLACE.

    THE query-packing implementation — :func:`query_matrix` and the
    slab path both run this exact float-op sequence, so the
    zero-allocation path is bit-identical to the allocating one by
    construction (pinned as a property in tests/test_queryslab.py).
    ``mode="cosine"`` (the tfidf scorer): float32 term counts
    accumulated directly into the column, ``/ len(words)``, ``* idf``,
    L2-normalized via the reused ``[V]`` ``scratch`` — no per-query
    temporaries at all. ``mode="counts"`` (the bm25 scorer, round 23):
    the accumulation STOPS at raw counts — BM25 absorbs everything
    else into the doc-side weights, so its query column is the bare
    term-count vector (``idf`` is accepted and ignored). A zero/empty
    column scores 0 against every document either way.
    """
    if mode not in ("cosine", "counts"):
        raise ValueError(f"unknown query mode {mode!r}")
    out.fill(0.0)
    idf = np.asarray(idf)
    if scratch is None:
        scratch = np.empty((config.vocab_size,), np.float32)
    one = np.float32(1.0)
    for j, text in enumerate(queries):
        data = text.encode() if isinstance(text, str) else text
        words = whitespace_tokenize(data, config.truncate_tokens_at)
        if not words:
            continue
        ids = words_to_ids(words, config.vocab_size, config.hash_seed)
        col = out[:, j]
        # Exact float32 counts (integers < 2^24 are exact), same
        # values bincount+astype produced; then the same two
        # elementwise ops, in place.
        np.add.at(col, ids, one)
        if mode == "counts":
            continue
        col /= len(words)
        col *= idf
        np.multiply(col, col, out=scratch)
        norm = float(np.sqrt(scratch.sum()))
        if norm > 0:
            col /= norm
        else:
            col.fill(0.0)
    return out


def query_matrix(queries: Sequence[Union[str, bytes]],
                 config: PipelineConfig, idf: np.ndarray,
                 pad_to: Optional[int] = None,
                 mode: str = "cosine") -> np.ndarray:
    """Host-side packing of queries into a dense [V, Q] query block
    (cosine columns by default; ``mode="counts"`` for bm25).

    Shared by :meth:`TfidfRetriever.search` and the segmented index's
    views (``tfidf_tpu/index``) so both paths build byte-identical
    query columns from the same ``idf`` vector — half the segment-vs-
    rebuild bit-parity contract. ``pad_to`` widens the block with
    all-zero columns (query-count bucketing); a zero column scores 0
    against every document, so padded rows fall out of results via the
    ``vals > 0`` mask. Delegates to :func:`fill_query_matrix` — one
    packing implementation for the allocating and slab paths alike.
    """
    q = np.empty((config.vocab_size, pad_to or len(queries)), np.float32)
    return fill_query_matrix(queries, config, idf, q, mode=mode)


def config_fingerprint(cfg: PipelineConfig) -> str:
    """Stable hash over the config fields that determine index BYTES
    and query packing — the compatibility contract between a snapshot
    and the process restoring it. Fields that only choose an
    execution path promised bit-identical (wire/finish/result_wire),
    or that don't touch the retriever arrays (topk, trace,
    compile_cache, mesh placement), are deliberately excluded: a
    snapshot taken under one of those settings restores under
    another."""
    ident = {
        "vocab_mode": cfg.vocab_mode.value,
        "vocab_size": cfg.vocab_size,
        "hash_seed": cfg.hash_seed,
        "tokenizer": cfg.tokenizer.value,
        "ngram_range": list(cfg.ngram_range),
        "chargram_on_device": cfg.chargram_on_device,
        "truncate_tokens_at": cfg.truncate_tokens_at,
        "max_doc_len": cfg.max_doc_len,
        "doc_chunk": cfg.doc_chunk,
        "score_dtype": cfg.score_dtype,
    }
    return hashlib.sha256(
        json.dumps(ident, sort_keys=True).encode()).hexdigest()[:16]


class TfidfRetriever:
    """Index a corpus once, answer ranked cosine queries from device.

    Args:
      config: HASHED-vocab pipeline config (default 2^16 vocab).
      plan: optional docs-sharded MeshPlan; the index then lives
        block-sharded across the mesh and queries run SPMD.
    """

    def __init__(self, config: Optional[PipelineConfig] = None,
                 plan: Optional[MeshPlan] = None,
                 scorer=None):
        self.config = config or PipelineConfig(vocab_mode=VocabMode.HASHED)
        if self.config.vocab_mode is not VocabMode.HASHED:
            raise ValueError("TfidfRetriever requires HASHED vocab")
        if plan is not None and (plan.n_vocab_shards != 1
                                 or plan.n_seq_shards != 1):
            raise ValueError("retrieval shards the docs axis only")
        self.plan = plan
        # The index-default scorer (round 23): what search() runs when
        # a request names none. Explicit arg > TFIDF_TPU_SCORER > the
        # tfidf default. Per-scorer derived faces and per-filter live
        # masks cache here; both invalidate on every index install.
        self.scorer: ScorerSpec = resolve_scorer(scorer)
        self._faces: dict = {}
        self._filters: dict = {}
        # Fielded index (round 23): [(name, weight, start, stop)] slot
        # spans when index_fields() built this index, else None.
        self._fields: Optional[List[Tuple[str, float, int, int]]] = None
        self.names: List[str] = []
        self._idf: Optional[jax.Array] = None
        self._ids = self._weights = self._head = None
        self._num_docs = 0
        self._sharded_cache: dict = {}
        # Zero-allocation query path (round 19): tri-state knob
        # (None = env TFIDF_TPU_QUERY_SLAB, default on; the server
        # sets it from ServeConfig.query_slab), the lazily-built
        # staging slab, and the cached host IDF the slab fill reads
        # (one D2H per index install instead of one per search).
        self.query_slab: Optional[bool] = None
        # Pipelined serving (round 22): the server pushes its
        # pipeline depth here so the slab pre-provisions that many
        # slots per ring — ``depth`` batches can be staged-and-in-
        # flight at once without a mid-stream allocation.
        self.slab_depth: int = 1
        self._slab = None
        self._idf_np: Optional[np.ndarray] = None
        self._idf_src = None

    # --- indexing ---
    def index(self, corpus: Corpus) -> "TfidfRetriever":
        cfg = self.config
        pad = self.plan.pad_docs(len(corpus)) if self.plan else None
        batch = pack_corpus(corpus, cfg, pad_docs_to=pad, want_words=False)
        toks, lens = batch.token_ids, batch.lengths
        if self.plan is not None:
            toks = jax.device_put(
                toks, self.plan.sharding(P(DOCS_AXIS, None)))
            lens = jax.device_put(lens, self.plan.sharding(P(DOCS_AXIS)))
        ids, weights, head, idf = _build_index(
            toks, lens, jnp.int32(len(corpus)), vocab_size=cfg.vocab_size)
        self._ids, self._weights, self._head = ids, weights, head
        self._idf = idf
        self.names = list(corpus.names)
        self._num_docs = len(corpus)
        self._faces.clear()
        self._filters.clear()
        self._fields = None
        return self

    def index_dir(self, input_dir: str, strict: bool = True,
                  doc_len: Optional[int] = None,
                  chunk_docs: int = 8192) -> "TfidfRetriever":
        """Index a directory. ``doc_len`` opts into the overlapped
        chunked ingest (native loader, ragged uint16 wire, host packs
        chunk i+1 while the device sorts chunk i) — the same scalable
        pipeline ``run_overlapped`` uses, sharing its compiled chunk
        programs. The trade is the ingest's: documents longer than
        ``doc_len`` tokens are truncated. Default (None) packs the
        whole corpus in one batch with L grown to the longest doc;
        meshes always take the batch path (sharded placement)."""
        if doc_len is None or self.plan is not None:
            return self.index(discover_corpus(input_dir, strict))
        from tfidf_tpu.ingest import (_chunk_step, _resident_chunking,
                                      make_chunk_packer, make_flat_packer)
        from tfidf_tpu.io.corpus import discover_names

        cfg = self.config
        names = discover_names(input_dir, strict)
        if not names:
            raise ValueError(f"no documents in {input_dir}")
        num_docs = len(names)
        chunk_docs, starts = _resident_chunking(num_docs, chunk_docs)
        ragged = cfg.vocab_size <= (1 << 16)
        pack = (make_flat_packer(input_dir, cfg, chunk_docs, doc_len)
                if ragged
                else make_chunk_packer(input_dir, cfg, chunk_docs,
                                       doc_len))
        df_acc = jnp.zeros((cfg.vocab_size,), jnp.int32)
        trip_i, trip_c, trip_h, len_parts = [], [], [], []
        for start in starts:
            chunk_names = names[start:start + chunk_docs]
            packed = pack(chunk_names)
            wire_arr, lengths = packed[0], packed[1]
            lens = jax.device_put(lengths)
            i_, c_, h_, df_acc = _chunk_step(
                jax.device_put(wire_arr), lens, df_acc, cfg, doc_len,
                ragged=ragged)
            trip_i.append(i_)
            trip_c.append(c_)
            trip_h.append(h_)
            len_parts.append(lens)
        ids, weights, head, idf = _finish_index(
            tuple(trip_i), tuple(trip_c), tuple(trip_h),
            tuple(len_parts), df_acc, jnp.int32(num_docs))
        self._ids, self._weights, self._head = ids, weights, head
        self._idf = idf
        # Only the final chunk carries padding rows; real docs occupy
        # rows [0, num_docs), so the tail-padding search guard holds.
        self.names = names
        self._num_docs = num_docs
        self._faces.clear()
        self._filters.clear()
        self._fields = None
        return self

    def index_fields(self, fields) -> "TfidfRetriever":
        """Fielded indexing (round 23): ``fields`` is a sequence of
        ``(name, corpus, weight)`` — the same documents tokenized per
        field (title, body, ...), every corpus row-aligned (same
        length, same names). Each field builds its own sub-index
        (per-field DF, per-field normalization) and the sub-indexes
        STACK along the slot axis sharing one vocab, with the tfidf
        weights pre-scaled by the field weight — so one doc row's dot
        against a query IS the weighted sum over fields, and the
        default search path runs completely unchanged on the stacked
        triple. Query columns use the union IDF (every field's rows
        count as documents: N = n_fields * D). The bm25 face derives
        per field slice (own df/avgdl) scaled the same way."""
        if self.plan is not None:
            raise ValueError("fielded indexes are single-device (wrap "
                             "in MeshShardedRetriever to shard)")
        fields = list(fields)
        if not fields:
            raise ValueError(
                "index_fields needs at least one (name, corpus, weight)")
        cfg = self.config
        names: Optional[List[str]] = None
        num_docs = 0
        spans: List[Tuple[str, float, int, int]] = []
        ids_parts, w_parts, h_parts = [], [], []
        df_total = None
        start = 0
        for fname, corpus, weight in fields:
            if names is None:
                num_docs = len(corpus)
                names = list(corpus.names)
            elif len(corpus) != num_docs or list(corpus.names) != names:
                raise ValueError(
                    f"field {fname!r} is not row-aligned with "
                    f"{fields[0][0]!r} (same docs, same order)")
            batch = pack_corpus(corpus, cfg, want_words=False)
            ids, weights, head, _ = _build_index(
                batch.token_ids, batch.lengths, jnp.int32(num_docs),
                vocab_size=cfg.vocab_size)
            df_f = sparse_df(ids, head, cfg.vocab_size)
            df_total = df_f if df_total is None else df_total + df_f
            ids_parts.append(ids)
            w_parts.append(weights * jnp.float32(weight))
            h_parts.append(head)
            stop = start + int(ids.shape[1])
            spans.append((str(fname), float(weight), start, stop))
            start = stop
        self._ids = jnp.concatenate(ids_parts, axis=1)
        self._weights = jnp.concatenate(w_parts, axis=1)
        self._head = jnp.concatenate(h_parts, axis=1)
        self._idf = idf_from_df(df_total,
                                jnp.int32(len(fields) * num_docs),
                                jnp.float32)
        self.names = names
        self._num_docs = num_docs
        self._faces.clear()
        self._filters.clear()
        self._fields = spans
        return self

    @property
    def indexed(self) -> bool:
        return self._num_docs > 0

    # --- snapshot / restore (round 13) ---
    def snapshot(self, path: str, epoch: int = 0,
                 extra_meta: Optional[dict] = None) -> str:
        """Persist the built index (CSR triples + IDF + names) under
        the checkpoint root ``path`` via ``checkpoint.save_index`` —
        the crash-fast restart path: :meth:`restore` rebuilds this
        exact retriever from disk without touching the corpus.
        Single-device indexes only (a mesh-sharded index restores
        per-shard once ROADMAP item 1 lands)."""
        from tfidf_tpu import checkpoint as ckpt
        if not self.indexed:
            raise RuntimeError("index() a corpus before snapshot()")
        if self.plan is not None:
            raise ValueError("snapshot() supports single-device "
                             "indexes only")
        # Doc names ride as one NUL-joined uint8 blob: filenames
        # cannot contain NUL, and npz round-trips raw bytes exactly.
        blob = np.frombuffer(
            "\x00".join(self.names).encode("utf-8"), dtype=np.uint8)
        arrays = {
            "ids": np.asarray(self._ids),
            "weights": np.asarray(self._weights),
            "head": np.asarray(self._head),
            "idf": np.asarray(self._idf),
            "names_blob": blob,
        }
        meta = {
            "num_docs": int(self._num_docs),
            "epoch": int(epoch),
            "config_sha": config_fingerprint(self.config),
            "vocab_size": int(self.config.vocab_size),
        }
        # Scorer family (round 23): non-default scorers and fielded
        # slot spans ride the meta dict so restore() serves the same
        # family member. Default tfidf writes NOTHING — a round-22
        # snapshot and a round-23 default snapshot are byte-identical.
        if not self.scorer.is_default:
            meta["scorer"] = self.scorer.key()
        if self._fields is not None:
            meta["fields"] = [[f, w, s, e] for f, w, s, e in self._fields]
        if extra_meta:
            meta.update(extra_meta)
        return ckpt.save_index(path, arrays, meta)

    @classmethod
    def restore(cls, path: str,
                config: Optional[PipelineConfig] = None
                ) -> Tuple["TfidfRetriever", dict]:
        """Rebuild a retriever from a committed snapshot: ``(retriever,
        meta)``. The snapshot's config fingerprint must match
        ``config`` (default HASHED at the snapshot's vocab size) —
        a mismatch raises ``checkpoint.SnapshotMismatch`` rather than
        silently serving results a live rebuild would not produce."""
        from tfidf_tpu import checkpoint as ckpt
        arrays, meta = ckpt.restore_index(path)
        if config is None:
            config = PipelineConfig(
                vocab_mode=VocabMode.HASHED,
                vocab_size=int(meta.get("vocab_size", 1 << 16)))
        want = config_fingerprint(config)
        got = meta.get("config_sha")
        if got != want:
            raise ckpt.SnapshotMismatch(
                f"snapshot config fingerprint {got!r} != running "
                f"config {want!r} — rebuild instead of serving a "
                f"mismatched index")
        r = cls(config)
        r._ids = jnp.asarray(arrays["ids"])
        r._weights = jnp.asarray(arrays["weights"])
        r._head = jnp.asarray(arrays["head"])
        r._idf = jnp.asarray(arrays["idf"])
        blob = arrays["names_blob"]
        r.names = (bytes(blob.tobytes()).decode("utf-8").split("\x00")
                   if blob.size else [])
        r._num_docs = int(meta["num_docs"])
        if len(r.names) != r._num_docs:
            raise ckpt.SnapshotMismatch(
                f"snapshot names ({len(r.names)}) != num_docs "
                f"({r._num_docs})")
        r.scorer = parse_scorer(meta.get("scorer"))
        fields = meta.get("fields")
        if fields:
            r._fields = [(str(f), float(w), int(s), int(e))
                         for f, w, s, e in fields]
        return r, meta

    # --- querying ---
    def _query_matrix(self, queries: Sequence[Union[str, bytes]],
                      pad_to: Optional[int] = None) -> np.ndarray:
        """Module-level :func:`query_matrix` over this retriever's
        config and IDF (kept as a method for the round-9 callers)."""
        return query_matrix(queries, self.config, self._idf,
                            pad_to=pad_to)

    def _idf_host(self) -> np.ndarray:
        """Host copy of the IDF vector, cached per installed index —
        the slab fill must not pay a D2H round trip per search. A
        racing refresh is benign (both sides compute the same array)."""
        idf = self._idf
        if self._idf_np is None or self._idf_src is not idf:
            self._idf_np = np.asarray(idf)
            self._idf_src = idf
        return self._idf_np

    def _resolve_slab(self):
        """The query slab serving this retriever, or None when the
        path is off (mesh plans keep the legacy packing — their qmat
        replicates under shard_map, a different staging contract)."""
        from tfidf_tpu.ops.queryslab import QuerySlab, use_query_slab
        if self.plan is not None or not use_query_slab(self.query_slab):
            return None
        if (self._slab is None
                or self._slab.vocab_size != self.config.vocab_size):
            # Ring ceiling = the serve batch ceiling (round 21): with
            # tiled scoring the batcher coalesces past 64, and every
            # bucket it can produce must have a staging ring. Rings
            # allocate lazily per bucket actually seen, so an oversize
            # ceiling costs nothing until a batch that wide arrives.
            cap = max(1, int(os.environ.get("TFIDF_TPU_MAX_BATCH",
                                            "256") or "256"))
            self._slab = QuerySlab(self.config.vocab_size,
                                   max_bucket=cap,
                                   min_depth=max(1, self.slab_depth))
        elif self._slab.min_depth < self.slab_depth:
            self._slab.reserve(self.slab_depth)
        return self._slab

    def search(self, queries: Sequence[Union[str, bytes]], k: int = 10,
               *, scorer=None, filter=None
               ) -> Tuple[np.ndarray, np.ndarray]:
        """Ranked retrieval: (scores, doc_indices), each [Q, k'] with
        k' = min(k, num_docs) — the same width on both execution paths.

        ``doc_indices`` index into :attr:`names`; -1 marks padding when
        fewer than k documents score. Scores are cosine similarities
        under the default tfidf scorer; ``scorer`` selects another
        family member for this call (``"bm25"``,
        ``"bm25:k1=1.5,b=0.6"``, a dict, a :class:`ScorerSpec`) and
        ``filter`` restricts the candidate set (see
        :mod:`tfidf_tpu.scoring.filters`) — both default to the
        index-level :attr:`scorer` / no filter, and the default
        combination runs EXACTLY the pre-round-23 code path.

        One implementation with :meth:`search_async` — this is the
        dispatch stage plus an immediate materialization, so the
        pipelined serve path and the synchronous path can never
        diverge by a byte.
        """
        return self.search_async(queries, k, scorer=scorer,
                                 filter=filter).materialize()

    def _scorer_face(self, spec: ScorerSpec):
        """The derived ``(data, cols)`` doc face of one scorer, cached
        per :meth:`ScorerSpec.key` until the next index install. tfidf
        is the stored weights re-masked (``_tfidf_face`` — the same
        two elementwise ops ``_search_tiled`` fuses); bm25 re-derives
        counts/lengths/df from ``(ids, head)`` on device
        (``_bm25_face``), per field slice when the index is fielded."""
        key = spec.key()
        face = self._faces.get(key)
        if face is not None:
            return face
        if spec.kind == "tfidf":
            face = _tfidf_face(self._ids, self._weights, self._head)
        elif self._fields is None:
            n = self._num_docs
            lens = doc_lengths_host(self._ids)
            avgdl = avgdl_f32(int(lens[:n].sum()), n)
            face = _bm25_face(self._ids, self._head, jnp.int32(n),
                              avgdl, np.float32(spec.k1),
                              np.float32(spec.b),
                              vocab_size=self.config.vocab_size)
        else:
            n = self._num_docs
            data_parts, cols_parts = [], []
            for _fname, weight, start, stop in self._fields:
                ids_f = self._ids[:, start:stop]
                head_f = self._head[:, start:stop]
                lens = doc_lengths_host(ids_f)
                avgdl = avgdl_f32(int(lens[:n].sum()), n)
                d, c = _bm25_face(ids_f, head_f, jnp.int32(n), avgdl,
                                  np.float32(spec.k1),
                                  np.float32(spec.b),
                                  vocab_size=self.config.vocab_size)
                data_parts.append(d * jnp.float32(weight))
                cols_parts.append(c)
            face = (jnp.concatenate(data_parts, axis=1),
                    jnp.concatenate(cols_parts, axis=1))
        self._faces[key] = face
        return face

    def scorer_face(self, spec=None) -> Tuple[np.ndarray, np.ndarray]:
        """Host copy of a scorer's ``(data, cols)`` face, derived
        through the SAME device programs the flat search consumes —
        the bit-parity contract ``MeshShardedRetriever`` builds its
        sharded blocks on."""
        spec = self.scorer if spec is None else parse_scorer(spec)
        data, cols = self._scorer_face(spec)
        return np.asarray(data), np.asarray(cols)

    def _filter_live(self, fspec: Optional[FilterSpec]):
        """Device live mask of one filter ∧ the real-rows guard,
        cached per canonical filter key; ``None`` filter -> ``None``
        (the unmasked kernel, shared with the default path)."""
        if fspec is None:
            return None
        key = fspec.key()
        live = self._filters.get(key)
        if live is None:
            host = np.zeros((int(self._ids.shape[0]),), bool)
            host[:self._num_docs] = filter_mask(
                fspec, self._num_docs, names=self.names)
            live = jnp.asarray(host)
            self._filters[key] = live
        return live

    def search_async(self, queries: Sequence[Union[str, bytes]],
                     k: int = 10, *, scorer=None,
                     filter=None) -> "PendingSearch":
        """Dispatch stage of :meth:`search` (round 22): stage the
        query block, issue the (async) jitted search, start the D2H
        copy of the result words, and return WITHOUT blocking. The
        returned :class:`PendingSearch`'s ``materialize()`` blocks on
        the transfer, releases the slab slot (slot release stays keyed
        to result materialization — the reuse-safety guard), and
        applies the same trim/mask tail as ``search``.

        Device errors surface at ``materialize()`` — jax defers them
        to the first host read — which is exactly where the pipelined
        batcher's drain-time supervision catches them. Paths that
        cannot defer (the legacy >64-query block split, which recurses
        through synchronous searches) return an already-resolved
        handle; callers need no special case.
        """
        if not self.indexed:
            raise RuntimeError("index() a corpus before search()")
        # Scorer-family routing (round 23): the DEFAULT combination
        # (index-level tfidf, no filter) falls through to the exact
        # pre-subsystem body below — bit-identity by construction, the
        # acceptance pin. Everything else takes the derived-face path.
        spec = self.scorer if scorer is None else parse_scorer(scorer)
        fspec = parse_filter(filter)
        if not (spec.is_default and fspec is None):
            return self._search_scored(queries, k, spec, fspec)
        # Tiled scoring (round 21, default ON): the doc axis scans in
        # fixed tiles against the FULL query block, so the per-dispatch
        # intermediate is [tile * L, Q] — bounded regardless of Q — and
        # one batch is ONE dispatch at any width. OFF restores the
        # legacy untiled dot, whose [nse, Qb] intermediate (measured:
        # Q=256 over 100k x 256 docs asks 28 GB and OOMs a v5e) forces
        # the serial 64-wide query-block split below; per-query results
        # are independent, so that concatenation is exact — and tiled
        # results are bit-identical to it (scores, ids, tie order).
        tiled = self.plan is None and score_tiling()
        if (not tiled and self.plan is None
                and len(queries) > _LEGACY_QUERY_BLOCK):
            parts = [self.search(queries[s:s + _LEGACY_QUERY_BLOCK], k)
                     for s in range(0, len(queries),
                                    _LEGACY_QUERY_BLOCK)]
            return PendingSearch.resolved(
                np.concatenate([p[0] for p in parts]),
                np.concatenate([p[1] for p in parts]))
        # Query-count bucketing: the compiled search program is shaped
        # by Q, so ad-hoc repeated searches at arbitrary query counts
        # would re-jit per count. Padding Q to the next power of two
        # caps steady-state serving at log2(bucket)+1 programs per k
        # (pinned by tests/test_serve.py); the zero padding columns
        # score 0 everywhere and their rows are dropped before return.
        nq = len(queries)
        bucket = 1 << max(0, nq - 1).bit_length()
        # Deferred cleanup for the materialization stage: the donated
        # device block to delete and the slab slot to release once the
        # result rows are back on the host.
        qmat_live = None
        slab_slot = None
        slab_ref = None
        if self.plan is not None:
            qmat = jnp.asarray(self._query_matrix(queries,
                                                  pad_to=bucket))
            fn = self._sharded_fn(k)
            vals, idx = fn(self._ids, self._weights, self._head, qmat)
        else:
            rows = int(self._ids.shape[0])
            kk = min(k, rows)
            if tiled:
                tile = score_tile_rows(rows)
                method = score_method()
                n_tiles = -(-rows // tile)

                def dispatch(qmat):
                    with obs.span("score_tile", tiles=n_tiles,
                                  rows=rows, queries=int(bucket)):
                        return _search_tiled(
                            self._ids, self._weights, self._head,
                            qmat, k=kk, tile=tile, method=method)
            else:
                data = jnp.where(self._head, self._weights, 0.0)
                cols = jnp.where(self._head, self._ids, 0)[..., None]

                def dispatch(qmat):
                    return _search_bcoo(data, cols, qmat, k=kk)

            # Compile fingerprinting (round 12): with a CompileWatch
            # armed, a cache-size delta across this call means a fresh
            # search program — note it with the shape identity the
            # watch's flight event needs. Disabled cost: one global
            # load + None test (the hot-path discipline of obs).
            fn = _search_tiled if tiled else _search_bcoo
            watch = devmon.get_watch()
            before = (fn._cache_size()
                      if watch is not None
                      and hasattr(fn, "_cache_size") else None)
            slab = self._resolve_slab()
            if slab is not None and bucket <= slab.max_bucket:
                # Zero-allocation hot path (round 19): fill a reused
                # staging-ring buffer in place, EXACTLY ONE H2D copy
                # (the byte-stamped span is the trace receipt), then
                # delete the uploaded block the moment results land —
                # the allocator recycles one device block per bucket.
                # The slot releases only after the result rows
                # materialize: host rows back means the copy was
                # consumed, so the next batch can safely refill this
                # buffer (the reuse-safety guard the 8-thread stress
                # pins).
                buf, scratch, slot = slab.checkout(bucket)
                try:
                    fill_query_matrix(queries, self.config,
                                      self._idf_host(), buf,
                                      scratch=scratch)
                    with obs.span("h2d", bytes=int(buf.nbytes)):
                        qmat = jax.device_put(buf)
                    slab.note_h2d(buf.nbytes)
                    vals, idx = dispatch(qmat)
                except BaseException:
                    # Dispatch-stage failure: nothing in flight, so
                    # the slot frees immediately instead of leaking.
                    slab.release(slot)
                    raise
                # Slot release stays keyed to RESULT materialization
                # (host rows back == the H2D copy provably consumed),
                # now deferred into the PendingSearch below.
                qmat_live = qmat
                slab_ref, slab_slot = slab, slot
            else:
                # Oversize-batch fallback (bucket past the slab's
                # ring shapes — a raised TFIDF_TPU_MAX_BATCH) or
                # slab off: the legacy one-shot allocation. Same
                # programs, same bytes.
                if slab is not None:
                    slab.note_fallback()
                qmat = jnp.asarray(self._query_matrix(queries,
                                                      pad_to=bucket))
                vals, idx = dispatch(qmat)
            if (before is not None
                    and fn._cache_size() > before):
                devmon.note_compile(
                    "search_tiled" if tiled else "search_bcoo",
                    queries=int(bucket), k=kk, docs=rows,
                    dtype="float32")
        # Start the D2H transfer NOW (jax runs it concurrently with
        # whatever the host does next); the blocking np.asarray moves
        # into materialize(). Snapshot num_docs at dispatch time so a
        # racing index install cannot skew the trim/mask of a batch
        # already in flight.
        _start_d2h(vals, idx)
        num_docs = self._num_docs
        width = min(k, num_docs)

        def materialize(vals=vals, idx=idx):
            # Both paths produce >= min(k, num_docs) sorted columns
            # (the sharded one up to min(k, local_k * n_shards)); trim
            # to the path-independent width so callers see the same
            # shape. Rows past nq are the bucketing pad — dropped
            # first.
            try:
                v = np.asarray(vals)[:nq, :width]
                i = np.asarray(idx)[:nq, :width]
            finally:
                if qmat_live is not None:
                    try:
                        qmat_live.delete()
                    except RuntimeError:
                        pass  # already deleted with a failed dispatch
                if slab_ref is not None:
                    slab_ref.release(slab_slot)
            ok = (v > 0) & (i < num_docs)
            return np.where(ok, v, 0.0), np.where(ok, i, -1)

        return PendingSearch(materialize)

    def _search_scored(self, queries: Sequence[Union[str, bytes]],
                       k: int, spec: ScorerSpec,
                       fspec: Optional[FilterSpec]) -> "PendingSearch":
        """Any non-default (scorer, filter) combination (round 23).

        Same kernel, different precomputation: the cached derived face
        replaces the inline masking, the filter folds into the live
        mask the tombstone machinery already owns (sub-zero sentinel
        before top-k), and bm25 queries pack as RAW counts. The result
        contract — shapes, ``vals > 0`` masking, tie order — is
        exactly :meth:`search`'s; tiled and untiled lowerings stay
        bit-identical per scorer (pinned against the NumPy oracle in
        tests/test_scoring_family.py). The tfidf face and every bm25
        face share ONE tiled-search jit (same shapes, same statics),
        so scorer switching compiles nothing after warm — the grown
        compile pin."""
        if self.plan is not None:
            raise ValueError(
                "plan-sharded TfidfRetriever serves the default scorer "
                "only — shard non-default scorers via "
                "MeshShardedRetriever")
        nq = len(queries)
        tiled = score_tiling()
        if not tiled and nq > _LEGACY_QUERY_BLOCK:
            # The untiled [nse, Qb] intermediate forces the same
            # serial 64-wide block split as the default path; per-
            # query independence makes the concatenation exact.
            parts = [self.search(queries[s:s + _LEGACY_QUERY_BLOCK],
                                 k, scorer=spec, filter=fspec)
                     for s in range(0, nq, _LEGACY_QUERY_BLOCK)]
            return PendingSearch.resolved(
                np.concatenate([p[0] for p in parts]),
                np.concatenate([p[1] for p in parts]))
        rows = int(self._ids.shape[0])
        num_docs = self._num_docs
        kk = min(k, rows)
        bucket = 1 << max(0, nq - 1).bit_length()
        data, cols = self._scorer_face(spec)
        live = self._filter_live(fspec)
        qmat = jnp.asarray(query_matrix(
            queries, self.config, self._idf_host(), pad_to=bucket,
            mode="counts" if spec.kind == "bm25" else "cosine"))
        if tiled:
            watch = devmon.get_watch()
            before = (score_topk_tiled_cache_size()
                      if watch is not None else None)
            tile = score_tile_rows(rows)
            with obs.span("score_tile", tiles=-(-rows // tile),
                          rows=rows, queries=int(bucket)):
                vals, idx = score_topk_tiled(data, cols, live, qmat, kk)
            if (before is not None
                    and score_topk_tiled_cache_size() > before):
                devmon.note_compile("search_scored",
                                    queries=int(bucket), k=kk,
                                    docs=rows, dtype="float32")
        else:
            from tfidf_tpu.ops.topk import segment_score_topk
            if live is None:
                # The untiled kernel masks unconditionally; the
                # no-filter live vector is the real-rows guard,
                # cached under the empty filter key.
                live = self._filters.get("")
                if live is None:
                    live = jnp.asarray(np.arange(rows) < num_docs)
                    self._filters[""] = live
            vals, idx = segment_score_topk(data, cols, live, qmat, kk)
        _start_d2h(vals, idx)
        width = min(k, num_docs)

        def materialize(vals=vals, idx=idx):
            v = np.asarray(vals)[:nq, :width]
            i = np.asarray(idx)[:nq, :width]
            ok = (v > 0) & (i < num_docs)
            return np.where(ok, v, 0.0), np.where(ok, i, -1)

        return PendingSearch(materialize)

    def _sharded_fn(self, k: int):
        if k not in self._sharded_cache:
            self._sharded_cache[k] = _make_search_sharded(self.plan, k)
        return self._sharded_cache[k]
