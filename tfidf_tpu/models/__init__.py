"""Model-level APIs built on the pipeline: the TF-IDF vectorizer.

The reference's "model" is the TF-IDF statistic itself (SURVEY §1:
"no model layer"). This package gives it the standard estimator shape —
fit (learn DF over a corpus), transform (score documents against it) —
so the framework slots into feature-extraction workflows, not just the
batch job the reference hardcodes.
"""

from tfidf_tpu.models.vectorizer import TfidfVectorizer

__all__ = ["TfidfVectorizer"]
