"""Model-level APIs built on the pipeline.

The reference's "model" is the TF-IDF statistic itself (SURVEY §1:
"no model layer"). This package gives it the standard shapes built on
that statistic: the estimator (fit DF over a corpus / transform new
documents) and ranked cosine retrieval over the indexed term-document
matrix — feature-extraction and search workflows, not just the batch
job the reference hardcodes.
"""

from tfidf_tpu.models.retrieval import TfidfRetriever
from tfidf_tpu.models.vectorizer import TfidfVectorizer

__all__ = ["TfidfRetriever", "TfidfVectorizer"]
