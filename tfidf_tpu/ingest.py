"""Overlapped corpus ingest: host packing pipelined against device compute.

The reference interleaves file IO and compute on the same rank, serially
per document (``TFIDF.c:130-205``) — every byte of IO stalls compute.
Here ingest is a two-phase chunked pipeline built on JAX's async
dispatch: the host thread packs chunk ``i+1`` (native parallel loader)
while the device is still executing chunk ``i``'s program — ``device_put``
and jitted calls return before the work completes, so the Python loop
runs ahead of the device and the transfer/compute of one chunk hides the
host tokenize/hash of the next.

Because DF is corpus-global but chunks stream, the run is two device
passes (same shape as classic out-of-core TF-IDF, and of the reference's
own reduce-then-rebroadcast choreography, ``TFIDF.c:215-220``):

  A. per chunk: partial DF, folded into a single device-resident [V]
     accumulator. Nothing else survives the chunk.
  B. per chunk: re-derive the row-sparse triples and score them against
     the final corpus-wide IDF; keep only the [chunk, K] top-k.

Both passes run ONE compiled program each, reused for every chunk
(static [chunk, L] shapes; the last chunk is padded with empty docs), so
compile time and device memory are FLAT in the number of chunks: device
residency is one [chunk, L] batch + the [V] DF + the accumulated
[D, K] top-k. Pass B re-sorts each chunk instead of keeping pass-A
triples resident — sort is cheap on device next to the transfer it
would take to spill triples, and it is what makes 1M-doc corpora fit.

Between passes the packed host arrays are either kept in host RAM
(``spill="host"``) or re-packed from disk in pass B (``spill="reread"``,
the reference's own two-scan idiom, ``TFIDF.c:141-147`` — it fseeks and
re-reads every doc). ``spill="auto"`` keeps chunks in RAM up to a byte
budget and re-reads beyond it.
"""

from __future__ import annotations

import dataclasses
import functools
import os
from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from tfidf_tpu.config import PipelineConfig, TokenizerKind, VocabMode
from tfidf_tpu.io import fast_tokenizer
from tfidf_tpu.io.corpus import discover_names, pack_corpus
from tfidf_tpu.ops.scoring import idf_from_df
from tfidf_tpu.ops.sparse import (sorted_term_counts, sparse_df,
                                  sparse_forward, sparse_scores, sparse_topk)

# spill="auto": keep packed chunks in host RAM up to this many bytes,
# re-read from disk beyond. Read at call time (TFIDF_TPU_SPILL_BYTES)
# so tests/tuning can override after import, like TFIDF_TPU_DF_METHOD.
_DEFAULT_SPILL_BYTES = 1 << 30

# Host-ahead floor: the dispatch loops may always run at least this many
# chunks ahead of the device. The effective bound is byte-budgeted
# (TFIDF_TPU_INFLIGHT_BYTES / chunk bytes — see max_ahead in
# run_overlapped): each sync costs a full link round trip on the
# tunneled backend, so throttling should be rare, not per-chunk.
_LOOKAHEAD = 2


@functools.partial(jax.jit, static_argnames=("vocab_size",))
def _phase_a(token_ids, lengths, df_acc, *, vocab_size: int):
    """Fold one chunk's partial DF into the device-resident accumulator."""
    ids, _, head = sorted_term_counts(token_ids, lengths)
    return df_acc + sparse_df(ids, head, vocab_size)


# The fused one-program path (used whenever the packed corpus fits on
# device, see _RESIDENT_ELEMS): sort once, score once — the two-pass
# choreography re-sorts every chunk in each pass. Chunked host packing
# and async chunk uploads still overlap in front of it.
@functools.partial(jax.jit,
                   static_argnames=("vocab_size", "score_dtype", "topk"))
def _fused_compact(token_ids, lengths, num_docs, *, vocab_size: int,
                   score_dtype, topk: int):
    """Fused forward with a compact wire format for the result fetch.

    The tunneled single-chip link runs ~60 MB/s, so the [D, K] result
    transfer is material: scores travel as bfloat16 (same exponent range
    as float32 — sign and zero are preserved, which is all the recall
    accounting reads) and ids as uint16 when the vocab fits. Scoring
    itself stays in ``score_dtype``; only the fetched bytes shrink.
    """
    df, vals, ids = sparse_forward(token_ids, lengths, num_docs,
                                   vocab_size=vocab_size,
                                   score_dtype=score_dtype, topk=topk)
    if vocab_size < (1 << 16):
        # Strictly-less: 65535 is then reserved as the -1 sentinel's
        # two's-complement image, so host decode is unambiguous.
        ids = ids.astype(jnp.uint16)
    return df, vals.astype(jnp.bfloat16), ids


@jax.jit
def _concat_rows(parts):
    """Device-side concat of uploaded chunks along the doc axis."""
    return jnp.concatenate(parts, axis=0)


# Largest packed corpus (doc slots x token length) the fused resident
# path will hold on device; beyond it the two-pass streaming pipeline
# takes over. 268M tokens measured working on one v5e chip (1M x 256
# docs: 31.8 s warm, the [1M, 256] sort + workspace fit 16 GB HBM with
# room; docs/SCALING.md). Override down for smaller parts.
_RESIDENT_ELEMS = 1 << 28


@functools.partial(jax.jit, static_argnames=("topk",))
def _phase_b(token_ids, lengths, idf, *, topk: int):
    """Score one chunk against the final corpus-wide IDF -> top-k."""
    ids, counts, head = sorted_term_counts(token_ids, lengths)
    scores = sparse_scores(ids, counts, head, lengths, idf)
    return sparse_topk(scores, ids, head, topk)


@functools.partial(jax.jit, static_argnames=("score_dtype",))
def _final_idf(df_total, num_docs, *, score_dtype):
    return idf_from_df(df_total, num_docs, score_dtype)


@dataclasses.dataclass
class IngestResult:
    """Corpus-wide outputs of an overlapped ingest run.

    On the resident fused path, ``topk_vals`` crossed the wire as
    bfloat16 (~2^-8 relative precision; sign/zero exact) — the selection
    itself was computed in ``config.score_dtype``. The streaming path
    returns full-precision scores. Exact-value consumers should use
    :class:`~tfidf_tpu.pipeline.TfidfPipeline`.
    """

    df: np.ndarray            # [V] corpus document frequencies
    topk_vals: np.ndarray     # [D, K] per-doc top-k TF-IDF scores
    topk_ids: np.ndarray      # [D, K] matching vocab ids (-1 = no term)
    lengths: np.ndarray       # [D] docSize per document
    names: List[str]
    num_docs: int
    path: str = ""            # which regime ran: "resident" | "streaming"


def make_chunk_packer(input_dir: str, cfg: PipelineConfig, chunk_docs: int,
                      length: int):
    """The host packing path of one chunk: names -> (token_ids, lengths).

    Native parallel loader when built (document bytes never enter
    Python), else the Python pack path — the exact code
    :func:`run_overlapped` runs, exposed so benchmarks/diagnostics time
    the same workload instead of re-implementing it.
    """
    use_native = (cfg.tokenizer is TokenizerKind.WHITESPACE
                  and fast_tokenizer.loader_available())

    def pack_chunk_native(chunk_names: List[str]
                          ) -> Tuple[np.ndarray, np.ndarray]:
        packed = fast_tokenizer.load_pack_paths(
            [os.path.join(input_dir, n) for n in chunk_names],
            cfg.vocab_size, cfg.hash_seed, cfg.truncate_tokens_at,
            min_len=length, chunk=length, fixed_len=length,
            pad_docs_to=chunk_docs)
        assert packed is not None  # loader_available() checked above
        return packed

    def pack_chunk_python(chunk_names: List[str]
                          ) -> Tuple[np.ndarray, np.ndarray]:
        from tfidf_tpu.io.corpus import Corpus
        docs = []
        for n in chunk_names:
            with open(os.path.join(input_dir, n), "rb") as f:
                docs.append(f.read())
        batch = pack_corpus(Corpus(names=list(chunk_names), docs=docs),
                            cfg, pad_docs_to=chunk_docs, want_words=False)
        ids = batch.token_ids[:, :length]
        if batch.token_ids.shape[1] < length:
            pad = np.zeros((ids.shape[0], length - ids.shape[1]), ids.dtype)
            ids = np.concatenate([ids, pad], axis=1)
        return ids, np.minimum(batch.lengths, length).astype(np.int32)

    return pack_chunk_native if use_native else pack_chunk_python


def run_overlapped(input_dir: str, config: Optional[PipelineConfig] = None,
                   chunk_docs: int = 8192, doc_len: Optional[int] = None,
                   strict: bool = True, spill: str = "auto") -> IngestResult:
    """Stream a directory through the overlapped two-pass pipeline.

    ``doc_len`` fixes the static token length L for every chunk (defaults
    to ``config.max_doc_len``); documents longer than L are truncated to
    L tokens — the fixed-shape tradeoff for never recompiling. Use
    ``TfidfPipeline`` (single batch, L grows to the longest doc) when
    truncation is unacceptable, or ``parallel.longdoc`` for documents
    beyond any single chip.

    ``spill`` controls where packed chunks live between pass A and B:
    ``"host"`` (RAM), ``"reread"`` (re-pack from disk), or ``"auto"``
    (RAM up to a budget). Device memory is flat in corpus size either
    way; see the module docstring.

    Requires HASHED vocab (fixed id space across chunks) and a top-k
    selection (full per-term output would defeat the streaming design).
    Works with or without the native loader; the native path keeps
    document bytes out of Python entirely.
    """
    cfg = config or PipelineConfig(vocab_mode=VocabMode.HASHED, topk=16)
    if cfg.vocab_mode is not VocabMode.HASHED:
        raise ValueError("overlapped ingest requires VocabMode.HASHED")
    if cfg.topk is None:
        raise ValueError("overlapped ingest requires a topk selection")
    if spill not in ("auto", "host", "reread"):
        raise ValueError(f"unknown spill policy {spill!r}")
    length = doc_len or cfg.max_doc_len
    names = discover_names(input_dir, strict)
    num_docs = len(names)
    if num_docs == 0:
        raise ValueError(f"no documents in {input_dir}")

    use_native = (cfg.tokenizer is TokenizerKind.WHITESPACE
                  and fast_tokenizer.loader_available())
    score_dtype = jnp.dtype(cfg.score_dtype)
    k = min(cfg.topk, length)
    # Wire bytes per token id: the native loader packs uint16 when the
    # vocab fits (fast_tokenizer), else int32. Drives both the spill
    # estimate and the in-flight upload budget.
    itemsize = 2 if (use_native and cfg.vocab_size <= (1 << 16)) else 4
    if spill == "auto":
        est = num_docs * length * itemsize
        budget = int(os.environ.get("TFIDF_TPU_SPILL_BYTES",
                                    _DEFAULT_SPILL_BYTES))
        spill = "host" if est <= budget else "reread"

    pack_chunk = make_chunk_packer(input_dir, cfg, chunk_docs, length)
    starts = list(range(0, num_docs, chunk_docs))

    resident = int(os.environ.get("TFIDF_TPU_RESIDENT_ELEMS",
                                  _RESIDENT_ELEMS))
    if num_docs * length <= resident:
        # Resident fused path: the host packs chunk i+1 while chunk i's
        # upload is still in flight (device_put is async — on the
        # tunneled backend the link runs ~60 MB/s, so hiding uploads
        # behind packing matters more than anything else). The device
        # concats the chunks, runs ONE fused program (a single sort,
        # where the two-pass pipeline sorts every chunk twice), and the
        # host pays a single synchronizing fetch. Only the final chunk
        # carries padding rows, so real documents are rows [0, num_docs).
        tok_parts, len_parts, all_lengths = [], [], []
        for start in starts:
            chunk_names = names[start:start + chunk_docs]
            token_ids, lengths = pack_chunk(chunk_names)
            all_lengths.append(lengths[:len(chunk_names)])
            tok_parts.append(jax.device_put(token_ids))
            len_parts.append(jax.device_put(lengths))
        toks = tok_parts[0] if len(tok_parts) == 1 else _concat_rows(tok_parts)
        lens = len_parts[0] if len(len_parts) == 1 else _concat_rows(len_parts)
        out = _fused_compact(toks, lens, jnp.int32(num_docs),
                             vocab_size=cfg.vocab_size,
                             score_dtype=score_dtype, topk=k)
        df_host, vals, tids = jax.device_get(out)
        # Decode the compact wire: bf16 scores widen losslessly in sign/
        # zero (what downstream reads); uint16 65535 is the -1 sentinel.
        vals = np.asarray(vals).astype(np.float32)
        tids = np.asarray(tids)
        if tids.dtype == np.uint16:
            tids = np.where(tids == np.uint16(0xFFFF), -1,
                            tids.astype(np.int32)).astype(np.int32)
        return IngestResult(df=df_host, topk_vals=vals[:num_docs],
                            topk_ids=tids[:num_docs],
                            lengths=np.concatenate(all_lengths),
                            names=names, num_docs=num_docs,
                            path="resident")

    # Pass A: fold every chunk's partial DF into one device accumulator.
    # The loop packs chunk i+1 while the device still runs chunk i
    # (async dispatch), but never runs more than max_ahead chunks
    # ahead — blocking on the oldest in-flight result bounds HBM
    # residency even when host packing outpaces the device. The bound is
    # byte-budgeted (TFIDF_TPU_INFLIGHT_BYTES, default 512 MB): each
    # sync costs a full link round trip on the tunneled backend, so it
    # should trigger rarely, not per chunk.
    chunk_bytes = max(chunk_docs * length * itemsize, 1)
    max_ahead = max(_LOOKAHEAD,
                    int(os.environ.get("TFIDF_TPU_INFLIGHT_BYTES", 1 << 29))
                    // chunk_bytes)
    df_acc = jnp.zeros((cfg.vocab_size,), jnp.int32)
    cached: List[Tuple[np.ndarray, np.ndarray]] = []
    all_lengths: List[np.ndarray] = []
    in_flight: List[jax.Array] = []
    for start in starts:
        chunk_names = names[start:start + chunk_docs]
        token_ids, lengths = pack_chunk(chunk_names)
        all_lengths.append(lengths[:len(chunk_names)])
        if spill == "host":
            cached.append((token_ids, lengths))
        toks = jax.device_put(token_ids)
        lens = jax.device_put(lengths)
        df_acc = _phase_a(toks, lens, df_acc, vocab_size=cfg.vocab_size)
        in_flight.append(df_acc)
        if len(in_flight) > max_ahead:
            in_flight.pop(0).block_until_ready()

    idf = _final_idf(df_acc, jnp.int32(num_docs), score_dtype=score_dtype)

    # Pass B: rescore each chunk against the corpus-wide IDF. Same
    # overlap structure; only the [chunk, K] selections accumulate on
    # device, fetched in one transfer at the end.
    vals_parts, ids_parts = [], []
    for ci, start in enumerate(starts):
        if spill == "host":
            token_ids, lengths = cached[ci]
        else:
            token_ids, lengths = pack_chunk(names[start:start + chunk_docs])
        toks = jax.device_put(token_ids)
        lens = jax.device_put(lengths)
        v, t = _phase_b(toks, lens, idf, topk=k)
        vals_parts.append(v)
        ids_parts.append(t)
        if ci >= max_ahead:  # same byte-budgeted lookahead as pass A
            vals_parts[ci - max_ahead].block_until_ready()

    df_host, vals, tids = jax.device_get(
        (df_acc, jnp.concatenate(vals_parts), jnp.concatenate(ids_parts)))
    return IngestResult(df=df_host, topk_vals=vals[:num_docs],
                        topk_ids=tids[:num_docs],
                        lengths=np.concatenate(all_lengths), names=names,
                        num_docs=num_docs, path="streaming")
