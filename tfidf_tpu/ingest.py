"""Overlapped corpus ingest: host packing pipelined against device compute.

The reference interleaves file IO and compute on the same rank, serially
per document (``TFIDF.c:130-205``) — every byte of IO stalls compute.
Here ingest is chunked and overlapped, shaped by the *measured* behavior
of the link (tools/link_probe.py + tools/structure_sweep.py):
``device_put`` stages bytes and only moves them when a consuming program
executes, and every D2H fetch costs ~100 ms of latency — so each chunk's
program is dispatched the moment its wire buffer is staged (transfer +
sort run behind the host's packing of the next chunk). When the vocab
fits uint16, the upload wire is a ragged FLAT id stream (no padding
bytes; ~25% smaller on the measured corpus) rebuilt into the padded
batch by a single device gather, and the RESULT wire is its downlink
twin (round 7, ``ops/downlink``): each top-k (score, id) pair packs
into ONE uint32 word on device — half the drain bytes — and each
chunk's word buffer rides ``copy_to_host_async`` while the next chunk
scores (``_DrainAhead``), so the drain pipelines behind phase-B compute
instead of serializing after the last FLOP. ``--result-wire=pair``
keeps the bit-identical legacy wire: one fused finish program, one
unfenced fetch.

Two regimes, chosen by corpus size vs ``TFIDF_TPU_RESIDENT_ELEMS``:

* **Resident** (fits on device): per chunk, one program sorts the rows
  into sparse triples and folds partial DF into a [V] accumulator; the
  triples stay device-resident. Once the corpus-wide DF/IDF is final,
  per-chunk scoring programs emit packed word buffers that drain
  asynchronously (packed wire), or one fused program scores everything
  for a single fetch (pair wire). Nothing is ever re-read or re-sorted.
* **Streaming** (arbitrarily large): two passes, the reference's own
  reduce-then-rebroadcast choreography (``TFIDF.c:215-220``) —
  pass A folds each chunk's partial DF and keeps NOTHING else (device
  memory flat in corpus size); pass B re-derives triples and scores
  against the final IDF, accumulating only [chunk, K] selections.
  Between passes the packed flat chunks either stay in host RAM
  (``spill="host"`` — pass B re-packs nothing) or are re-read from
  disk (``spill="reread"``, the reference's two-scan idiom,
  ``TFIDF.c:141-147``); ``spill="auto"`` picks by a byte budget.
"""

from __future__ import annotations

import dataclasses
import functools
import os
import time
import warnings
from typing import TYPE_CHECKING, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from tfidf_tpu import faults, obs
from tfidf_tpu.config import (PipelineConfig, TokenizerKind, VocabMode,
                              apply_compile_cache)
from tfidf_tpu.parallel.compat import shard_map
from tfidf_tpu.io import fast_tokenizer
from tfidf_tpu.io.corpus import discover_names, pack_corpus
from tfidf_tpu.obs.health import beat as _health_beat
from tfidf_tpu.ops.device_tokenize import tokenize_method
from tfidf_tpu.ops.downlink import (pack_result_words, pack_words,
                                    pair_slot_bytes, unpack_result_words,
                                    use_packed_result_wire)
from tfidf_tpu.ops.scoring import idf_from_df
from tfidf_tpu.ops.sparse import (score_topk, sorted_term_counts,
                                  sparse_df, sparse_scores,
                                  sparse_topk)

if TYPE_CHECKING:  # parallel imports stay lazy for single-device runs
    from tfidf_tpu.parallel.mesh import MeshPlan

# spill="auto": keep packed chunks in host RAM up to this many bytes,
# re-read from disk beyond. Read at call time (TFIDF_TPU_SPILL_BYTES)
# so tests/tuning can override after import, like TFIDF_TPU_DF_METHOD.
_DEFAULT_SPILL_BYTES = 1 << 30

# Host-ahead floor: the dispatch loops may always run at least this many
# chunks ahead of the device. The effective bound is byte-budgeted
# (TFIDF_TPU_INFLIGHT_BYTES / chunk bytes — see max_ahead in
# run_overlapped): each sync costs a full link round trip on the
# tunneled backend, so throttling should be rare, not per-chunk.
_LOOKAHEAD = 2


# The wire buffer donations below can never alias an output (a uint16
# [N] wire has no int32/float output twin), so XLA's "donated buffers
# were not usable" compile-time warning is EXPECTED — donation here
# buys early HBM release of dead wire buffers, not aliasing. Silence
# that exact message; any other donation warning still surfaces.
warnings.filterwarnings(
    "ignore", message="Some donated buffers were not usable")


# Wire buffer (arg 0) donated: streaming dispatch sites device_put a
# fresh buffer per chunk — see the ragged twins' donation note.
@functools.partial(jax.jit, donate_argnums=(0,),
                   static_argnames=("vocab_size",))
def _phase_a(token_ids, lengths, df_acc, *, vocab_size: int):
    """Fold one chunk's partial DF into the device-resident accumulator."""
    ids, _, head = sorted_term_counts(token_ids, lengths)
    return df_acc + sparse_df(ids, head, vocab_size)


# Per-chunk kernel of the resident path: row-sort into sparse triples
# and fold the chunk's partial DF into the accumulator. Dispatched as
# each chunk's upload lands, so the transfer+sort of chunk i runs while
# the host is still packing chunk i+1 (the lazily-staged tunnel link
# only moves bytes when a consuming program executes — tools/ab probes).
@functools.partial(jax.jit, static_argnames=("vocab_size", "fold_df"))
def _chunk_sort_fold(token_ids, lengths, df_acc, *, vocab_size: int,
                     fold_df: bool = True):
    ids, counts, head = sorted_term_counts(token_ids, lengths)
    if not fold_df:  # finish program derives DF (see _chunk_step)
        return ids, counts, head, df_acc
    return ids, counts, head, df_acc + sparse_df(ids, head, vocab_size)


# Granule alignment of the flat wire: every doc starts at a multiple
# of this many ids (zero fill between docs, both packers). The round-4
# trace (tools/trace_capture.py) showed the per-id rebuild gather at
# 67.5 ms/chunk for the 32k bench shape — scalar random access is the
# one thing the TPU memory system cannot stream. Aligned offsets turn
# the rebuild into a granule gather ([D, L/G] rows of G contiguous
# ids), ~G x fewer gather elements for ~G/2 wasted ids per doc on the
# wire (+4% bytes at G=16, L=256). 1 = legacy back-to-back layout.
# This module constant is an import-time SNAPSHOT kept for
# introspection; every packer/rebuild entry point resolves the knob
# through :func:`_wire_align` at CALL time, which is also where it is
# VALIDATED — so a bad value fails loudly at the entry point naming
# the env knob instead of poisoning module import (ADVICE round 5).
_WIRE_ALIGN = max(1, int(os.environ.get("TFIDF_TPU_WIRE_ALIGN", "16")))


def _wire_align() -> int:
    """The validated wire-granule alignment, read from the environment
    at call time (the packer and rebuild entry points: flatten_aligned,
    make_flat_packer, _chunk_step, the streaming kernel call sites).

    Must be a power of two — the decode reshapes the bucket-padded
    stream into ``[*, align]`` granules — and no larger than
    ``_FLAT_BUCKET``, so the bucket pad stays a whole number of
    granules. Raising HERE names the knob for every misconfiguration;
    the old import-time check missed the over-bucket case and a bare
    trace-time reshape error named nothing (ADVICE round 5)."""
    align = max(1, int(os.environ.get("TFIDF_TPU_WIRE_ALIGN", "16")))
    if align & (align - 1):
        raise ValueError(f"TFIDF_TPU_WIRE_ALIGN must be a power of two, "
                         f"got {align}")
    if align > _FLAT_BUCKET:
        raise ValueError(
            f"TFIDF_TPU_WIRE_ALIGN ({align}) must not exceed the flat "
            f"wire bucket (_FLAT_BUCKET = {_FLAT_BUCKET}): the "
            f"bucket-padded stream must hold a whole number of granules")
    return align


def flatten_aligned(ids, lengths, align: int = None, dtype=np.uint16):
    """Host-side flat wire from a padded [D, L] id batch, in THE
    (granule-aligned) layout both native packers emit: each doc's live
    ids back to back, zero-filled up to the next ``align`` multiple,
    then bucket-padded (``_bucket_pad_flat``). The single Python
    definition of the layout — ``make_flat_packer``'s fallback, the
    minibatch ragged packer (``io.corpus.pack_ragged``), and the
    measurement tools (roofline/trace capture) all call this, so the
    wire contract cannot drift between them. ``dtype`` is the wire id
    width — uint16 for vocabs within 2^16, int32 beyond (the same rule
    the native packers apply). Returns ``(flat, total)`` where
    ``total`` is the live (pre-bucket-pad) aligned id count."""
    if align is None:
        align = _wire_align()
    d, width = ids.shape
    mask = np.arange(width)[None, :] < lengths[:d, None]
    if align > 1:
        wc = -(-width // align) * align
        z = np.where(mask, ids, 0)
        if wc != width:
            z = np.pad(z, ((0, 0), (0, wc - width)))
        al = -(-np.maximum(lengths[:d], 0) // align) * align
        amask = np.arange(wc)[None, :] < al[:, None]
        flat = np.ascontiguousarray(z[amask].astype(dtype))
    else:
        flat = np.ascontiguousarray(ids[mask].astype(dtype))
    total = flat.size
    return _bucket_pad_flat(flat, total), total


def _ragged_to_padded(flat, lengths, length: int, align: int = 1,
                      rebuild: str = "xla"):
    """Rebuild the padded [D, L] batch from a flat id stream with one
    gather. Out-of-range slots are clamped — their values are masked by
    ``lengths`` in every consumer (sorted_term_counts contract).
    ``align`` must match the packer's wire layout (``_wire_align``).

    ``rebuild`` selects the lowering: ``"xla"`` (the measured default,
    a granule gather) or ``"pallas"`` (the Mosaic copy kernel,
    ``ops.pallas_kernels.ragged_rebuild_pallas`` — scalar-prefetched
    granule DMA, one program per [doc, granule] block). The Pallas
    variant needs a granule of at least 8 ids to be a sane block; below
    that (or off-TPU without interpret) the XLA gather serves."""
    if rebuild == "pallas" and align >= 8:
        from tfidf_tpu.ops.pallas_kernels import (default_interpret,
                                                  ragged_rebuild_pallas)
        return ragged_rebuild_pallas(flat, lengths, length=length,
                                     align=align,
                                     interpret=default_interpret())
    if align > 1:
        g = align
        lg = -(-length // g)
        al = (jnp.maximum(lengths, 0) + (g - 1)) // g  # granules/doc
        offg = jnp.concatenate([jnp.zeros((1,), jnp.int32),
                                jnp.cumsum(al[:-1], dtype=jnp.int32)])
        gran = flat.reshape(-1, g)
        idx = offg[:, None] + jnp.arange(lg, dtype=jnp.int32)[None, :]
        tok = gran[jnp.minimum(idx, gran.shape[0] - 1)]
        return tok.reshape(tok.shape[0], lg * g)[:, :length] \
            .astype(jnp.int32)
    off = jnp.concatenate([jnp.zeros((1,), jnp.int32),
                           jnp.cumsum(lengths[:-1], dtype=jnp.int32)])
    idx = off[:, None] + jnp.arange(length, dtype=jnp.int32)[None, :]
    return flat[jnp.minimum(idx, flat.shape[0] - 1)].astype(jnp.int32)


# Standalone device rebuild for the minibatch API layers
# (pipeline.run_packed / streaming accepting io.corpus.RaggedBatch):
# one small program turns the flat wire into the padded [D, L] batch
# ON DEVICE, so those layers get the same bytes-on-wire saving as the
# overlapped ingest without restructuring their forward programs.
# Rebuilt padding slots are masked by ``lengths`` in every consumer
# (sorted_term_counts / tf_counts contract), so the clamp garbage the
# gather leaves past each doc's length is immaterial. NOT donated: a
# public-ish entry point may be handed a device buffer the caller
# still holds.
@functools.partial(jax.jit,
                   static_argnames=("length", "align", "rebuild"))
def rebuild_padded(flat, lengths, *, length: int, align: int,
                   rebuild: str = "xla"):
    """Device-side ragged→padded rebuild (jitted ``_ragged_to_padded``).
    Returns int32 [D, length]."""
    return _ragged_to_padded(flat, lengths, length, align, rebuild)


# Ragged variant: the chunk arrives as a FLAT id stream (granule-
# aligned, ~25% fewer bytes through the link than padded on the
# measured corpus) and the padded [chunk, L] batch is rebuilt on
# device before the same sort+fold. NOT donated: profile_resident
# re-dispatches the same resident wire buffers through this kernel to
# measure the pipelined marginal, and donation would delete them after
# the first call (the profiler-cache-sharing doctrine pins one
# executable for production and profiler alike).
@functools.partial(jax.jit,
                   static_argnames=("length", "vocab_size", "align",
                                    "fold_df", "rebuild"))
def _chunk_ragged(flat, lengths, df_acc, *, length: int, vocab_size: int,
                  align: int, fold_df: bool = True, rebuild: str = "xla"):
    tok = _ragged_to_padded(flat, lengths, length, align, rebuild)
    ids, counts, head = sorted_term_counts(tok, lengths)
    if not fold_df:  # finish program derives DF (see _chunk_step)
        return ids, counts, head, df_acc
    return ids, counts, head, df_acc + sparse_df(ids, head, vocab_size)


# Streaming (two-pass) ragged kernels: pass A keeps NOTHING but the DF
# accumulator (memory flat in corpus size); pass B re-derives triples
# and scores against the final IDF. Same flat wire as the resident
# path. The wire buffer (arg 0) is DONATED: streaming call sites
# always device_put a fresh buffer per chunk and never touch it again,
# so XLA may reuse its HBM for the outputs — the upload pipeline's
# steady-state residency stays at two in-flight wire buffers. (On
# non-TPU backends donation is a no-op with a one-time warning.)
@functools.partial(jax.jit, donate_argnums=(0,),
                   static_argnames=("length", "vocab_size", "align",
                                    "rebuild"))
def _phase_a_ragged(flat, lengths, df_acc, *, length: int, vocab_size: int,
                    align: int, rebuild: str = "xla"):
    tok = _ragged_to_padded(flat, lengths, length, align, rebuild)
    ids, _, head = sorted_term_counts(tok, lengths)
    return df_acc + sparse_df(ids, head, vocab_size)


# Every packed-wire phase-B kernel scores+selects through ONE
# definition (ops.sparse.score_topk): the XLA sparse_scores→sparse_topk
# pair by default, or the fused Mosaic score/top-k kernel under
# TFIDF_TPU_SCORE=pallas — resolved at trace time, ids bit-identical
# either way (tests/test_finish.py).
@functools.partial(jax.jit, donate_argnums=(0,),
                   static_argnames=("length", "topk", "align", "rebuild"))
def _phase_b_ragged(flat, lengths, idf, *, length: int, topk: int,
                    align: int, rebuild: str = "xla"):
    tok = _ragged_to_padded(flat, lengths, length, align, rebuild)
    ids, counts, head = sorted_term_counts(tok, lengths)
    return score_topk(ids, counts, head, lengths, idf, topk)


# Pass-B kernel for triple-cached chunks: score pre-sorted triples
# against the final IDF — no re-pack, no upload, no re-sort. The
# device-side answer to the reference's two-scan idiom
# (``TFIDF.c:141-147``): scan once, keep the sorted form.
@functools.partial(jax.jit, static_argnames=("topk",))
def _phase_b_cached(ids, counts, head, lengths, idf, *, topk: int):
    return score_topk(ids, counts, head, lengths, idf, topk)


# Packed-wire twins of the pass-B kernels: same scoring, but the
# (vals, tids) selection leaves the program as ONE [chunk, K] uint32
# word buffer (ops/downlink) — contiguous, half the pair bytes, and
# the unit the chunked async drain ships per chunk (_DrainAhead).
@functools.partial(jax.jit, static_argnames=("topk",))
def _phase_b_cached_packed(ids, counts, head, lengths, idf, *, topk: int):
    return pack_result_words(*score_topk(ids, counts, head, lengths,
                                         idf, topk))


@functools.partial(jax.jit, donate_argnums=(0,),
                   static_argnames=("length", "topk", "align", "rebuild"))
def _phase_b_ragged_packed(flat, lengths, idf, *, length: int, topk: int,
                           align: int, rebuild: str = "xla"):
    tok = _ragged_to_padded(flat, lengths, length, align, rebuild)
    ids, counts, head = sorted_term_counts(tok, lengths)
    return pack_result_words(*score_topk(ids, counts, head, lengths,
                                         idf, topk))


@functools.partial(jax.jit, donate_argnums=(0,),
                   static_argnames=("topk",))
def _phase_b_padded_packed(token_ids, lengths, idf, *, topk: int):
    ids, counts, head = sorted_term_counts(token_ids, lengths)
    return pack_result_words(*score_topk(ids, counts, head, lengths,
                                         idf, topk))


# THE one-dispatch finish (round 8, --finish=scan): where the chunked
# finish pays one program launch/re-entry per chunk — measured at ~⅔
# of warm phase-B device time at the bench shape (docs/SCALING.md
# round 8) — this program stacks the chunk-major resident triples and
# lax.scan's ONE compiled body over them, emitting the full
# [n_chunks, D, K] packed word buffer from a single dispatch. The
# device analog of the reference's single scoring pass over all
# records (TFIDF.c:227-246). Triples (args 0-2) are donated — they are
# dead after the finish, and donation lets XLA reuse their HBM for the
# stacked scan operands; lengths are NOT (profile_resident re-passes
# the same length buffers through every re-dispatch).
@functools.partial(jax.jit, donate_argnums=(0, 1, 2),
                   static_argnames=("topk",))
def _phase_b_scan_packed(ids_parts, cnt_parts, head_parts, lens_parts,
                         idf, *, topk: int):
    stack = (lambda parts: parts[0][None] if len(parts) == 1
             else jnp.stack(parts))
    ids, cnt = stack(ids_parts), stack(cnt_parts)
    head, lens = stack(head_parts), stack(lens_parts)

    def body(carry, chunk):
        i_, c_, h_, l_ = chunk
        words = pack_result_words(*score_topk(i_, c_, h_, l_, idf, topk))
        return carry, words

    _, words = lax.scan(body, 0, (ids, cnt, head, lens))
    return words  # [n_chunks, chunk_docs, K] uint32


# DF finisher of the packed-drain resident path when the chunk folds
# were skipped (the sort-join fold-skip, _resident_df_mode): one global
# sort over the concatenated triples derives the [V] DF vector —
# identical counts to the per-chunk folds (DF is additive over chunks).
# The fused _finish_wire derived this inside its own sort; with the
# finish split back into per-chunk scoring dispatches, the derivation
# stands alone.
@functools.partial(jax.jit, static_argnames=("vocab_size",))
def _df_from_trips(ids_parts, head_parts, *, vocab_size: int):
    cat = (lambda parts: parts[0] if len(parts) == 1
           else jnp.concatenate(parts, axis=0))
    return sparse_df(cat(ids_parts), cat(head_parts), vocab_size)


# Streaming triple cache budget: pass A keeps each chunk's sorted
# triples (ids+counts int32 + head bool = 9 B/slot) device-resident up
# to this many bytes, so pass B re-derives nothing for cached chunks.
# Past the budget the regime degrades gracefully to the pure two-pass
# flow — device memory stays bounded at budget + in-flight chunks.
# Default 4 GiB: a quarter of a v4/v5e chip's HBM, leaving the wire
# buffers and sort workspace ample room (the 1M x 256 corpus measured
# 2.3 GB of triples, docs/SCALING.md).
_TRIPLE_CACHE_BYTES = 4 << 30


# Flat-stream padding granularity: chunks' flat sizes are rounded up to
# this many ids so XLA sees a handful of shapes (compile cache), not one
# per chunk. Default 2^17 u16 ids = 256 KB on the wire. The round-5
# bucket (2^19) silently ATE the ragged wire's entire byte saving at
# the bench shape: an 8192-doc chunk's ~1.64M live ids rounded up to
# 2.10M — exactly the padded [D, L] size — so bytes-on-wire never
# dropped. 2^17 keeps the round-up waste under ~8% of a bench chunk
# while chunk totals still concentrate tightly enough (law of large
# numbers over thousands of docs) that a run sees only a couple of
# distinct flat shapes, i.e. a couple of compiles, amortized by the
# warmup. Tunable for the compile-count-vs-bytes trade; must be a
# power of two >= the wire granule (the bucket pad is whole granules).
_FLAT_BUCKET = int(os.environ.get("TFIDF_TPU_FLAT_BUCKET", str(1 << 17)))
if _FLAT_BUCKET <= 0 or _FLAT_BUCKET & (_FLAT_BUCKET - 1):
    raise ValueError(f"TFIDF_TPU_FLAT_BUCKET must be a positive power "
                     f"of two, got {_FLAT_BUCKET}")


def _bucket_pad_flat(flat: np.ndarray, total: int) -> np.ndarray:
    """Round a flat id stream up to a ``_FLAT_BUCKET`` multiple with
    zero fill. At least one bucket even for an all-empty chunk: a
    zero-size operand would fail the device gather's trace (and one
    bucket is the shape small chunks land on anyway). The native flat
    packers now allocate bucket-rounded capacity (``cap_ids``), so the
    in-place branch is the only one they ever take — the ``np.pad``
    copy remains for under-sized callers only."""
    pad = max(total + (-total % _FLAT_BUCKET), _FLAT_BUCKET) - total
    if total + pad <= flat.size:
        flat[total:total + pad] = 0  # never ship np.empty garbage
        return flat[:total + pad]
    return np.pad(flat[:total], (0, pad))


def _bucket_cap_ids(chunk_docs: int, length: int, align: int) -> int:
    """Staging capacity (in ids) of one chunk's flat wire buffer:
    worst-case aligned content rounded up to whole ``_FLAT_BUCKET``\\ s
    (minimum one), so ``_bucket_pad_flat`` always pads in place — the
    wire leaves the packer with no re-pad copy."""
    per_doc = -(-length // align) * align
    cap = max(chunk_docs * per_doc, 1)
    return cap + (-cap % _FLAT_BUCKET)


# Ragged flat offsets are int32 and the stream ships in whole
# _FLAT_BUCKET granules, so a chunk's aligned flat capacity must stay
# below the last int32-addressable bucket boundary. Past it the padded
# wire (which has no flat offsets) is selected automatically — the
# same parity fallback --wire=padded forces.
_RAGGED_MAX_IDS = (1 << 31) - _FLAT_BUCKET


def resolve_wire(cfg: PipelineConfig) -> str:
    """The run's ASKED wire format: ``TFIDF_TPU_WIRE`` env override,
    else ``config.wire``. What actually carries the run is resolved by
    :func:`use_bytes_wire` / :func:`use_ragged_wire` — the degradation
    chain is bytes → ragged → padded."""
    choice = os.environ.get("TFIDF_TPU_WIRE") or getattr(cfg, "wire",
                                                         "ragged")
    if choice not in ("ragged", "padded", "bytes"):
        raise ValueError(
            f"unknown wire {choice!r} (TFIDF_TPU_WIRE / --wire: choose "
            f"'ragged', 'padded' or 'bytes')")
    return choice


def use_bytes_wire(cfg: PipelineConfig, chunk_docs: int,
                   length: int) -> bool:
    """True when this run ships raw document bytes and tokenizes +
    hashes ON DEVICE (``--wire=bytes``, round 14 —
    ``ops/device_tokenize.py``). The bytes wire degrades to the ragged
    id wire when the device tokenizer cannot carry the run: vocab past
    2^16 (the 32-bit-limb fold bound — same bound as the uint16 id
    wire), a non-whitespace tokenizer (chargram ids are already
    computed on device from bytes, a different wire), or a chunk whose
    token slots overflow int32. Exact-vocab ingest never asks (the
    intern table is host-side by construction); mesh ingest ignores
    the knob (its block-sharded ``device_put`` needs the padded
    wire)."""
    if resolve_wire(cfg) != "bytes":
        return False
    if cfg.vocab_size > (1 << 16):
        return False  # fold_mod's 32-bit partial products bound
    if cfg.tokenizer is not TokenizerKind.WHITESPACE:
        return False
    return chunk_docs * length < (1 << 31)


def use_ragged_wire(cfg: PipelineConfig, chunk_docs: int,
                    length: int) -> bool:
    """Resolve one run's chunk wire format from ``config.wire``:
    True = the ragged (CSR-style) flat uint16 stream, False = the
    padded [D, L] batch. ``"ragged"`` (the default) degrades to the
    padded parity wire when the uint16 stream cannot carry the run:
    vocab past 2^16, or a chunk whose aligned flat capacity would
    cross the int32/_FLAT_BUCKET offset bound (``_RAGGED_MAX_IDS``).
    ``"padded"`` forces the legacy bit-identical path everywhere. A
    ``"bytes"`` ask that :func:`use_bytes_wire` declined lands here —
    the middle link of the bytes → ragged → padded chain."""
    if resolve_wire(cfg) == "padded":
        return False
    if cfg.vocab_size > (1 << 16):
        return False  # the uint16 wire cannot carry the ids
    per_doc = -(-length // _wire_align()) * _wire_align()
    return chunk_docs * per_doc <= _RAGGED_MAX_IDS


def resolve_finish(cfg: PipelineConfig) -> str:
    """Resolve one run's phase-B finish structure from ``config.finish``
    (env override ``TFIDF_TPU_FINISH``): ``"scan"`` — one donated
    ``lax.scan`` dispatch over the stacked chunk triples emitting the
    whole packed word buffer — or ``"chunked"`` — the round-7
    per-chunk scoring dispatches with the interleaved async drain, the
    bit-identical fallback."""
    choice = (os.environ.get("TFIDF_TPU_FINISH")
              or getattr(cfg, "finish", "scan"))
    if choice not in ("scan", "chunked"):
        raise ValueError(
            f"unknown finish {choice!r} (TFIDF_TPU_FINISH / --finish: "
            f"choose 'scan' or 'chunked')")
    return choice


def use_scan_finish(cfg: PipelineConfig, packed_wire: bool) -> bool:
    """True when this run's phase-B finish is the single scanned
    dispatch. Only the packed result wire has a multi-dispatch finish
    to collapse — the pair wire's fused ``_finish_wire`` program is
    already one dispatch — so ``--finish=scan`` quietly rides the
    chunked/fused structure there (the cli warns when that fallback
    bites an explicit ask)."""
    return packed_wire and resolve_finish(cfg) == "scan"


def rebuild_method(explicit: Optional[str] = None) -> str:
    """Resolve the device-side ragged→padded rebuild lowering:
    ``"xla"`` (granule gather — the measured default) or ``"pallas"``
    (the Mosaic granule-DMA kernel, ops/pallas_kernels). Override via
    ``TFIDF_TPU_REBUILD``; resolved at trace time like
    :func:`ops.sparse.join_method`."""
    if explicit is not None:
        return explicit
    method = os.environ.get("TFIDF_TPU_REBUILD") or "xla"
    if method not in ("xla", "pallas"):
        raise ValueError(f"unknown TFIDF_TPU_REBUILD method {method!r}")
    return method


# Test/diagnostic hook: when set to a callable, the overlapped loops
# report ("event", chunk_index) tuples as work is ISSUED — the
# ordering contract of the double-buffered upload pipeline
# (tests/test_wire.py pins that chunk i+1's pack is in flight before
# chunk i's dispatch returns, and every upload precedes the fetch).
_overlap_trace = None


def _trace(event: str, idx: int = -1) -> None:
    if _overlap_trace is not None:
        _overlap_trace((event, idx))


def _restart_budget() -> int:
    """Worker-job restarts tolerated before an ingest worker's crash
    surfaces to the dispatch loop (``TFIDF_TPU_RESTART_BUDGET``; the
    serve batcher honors the same knob through ``ServeConfig``)."""
    return max(0, int(os.environ.get("TFIDF_TPU_RESTART_BUDGET", "3")))


def _supervised_job(worker: str, idx: int, body):
    """Run one worker job under restart supervision: a crash —
    including an injected ``pack_worker``/``drain`` transient fault —
    retries the (pure, per-chunk) job with jittered backoff inside
    the restart budget, logging a ``worker_restart`` flight event per
    retry; a :class:`~tfidf_tpu.faults.FatalFault` or an exhausted
    budget propagates to the dispatch loop (whose checkpoint/resume
    story is the next recovery layer). Pack/drain jobs are pure
    functions of their chunk (the exact-path intern table is
    append-only), so re-running one is safe."""
    from tfidf_tpu.obs import log as obs_log
    budget = _restart_budget()
    attempt = 0
    while True:
        try:
            faults.fire("pack_worker" if worker == "packer"
                        else "drain", chunk=idx)
            return body()
        except faults.FatalFault:
            raise
        except Exception as e:  # noqa: BLE001 — supervised restart
            attempt += 1
            if attempt > budget:
                raise
            obs_log.log_event(
                "warning", "worker_restart",
                msg=f"{worker} job for chunk {idx} crashed "
                    f"({type(e).__name__}: {e}); restart "
                    f"{attempt}/{budget}",
                worker=worker, chunk=idx, restart=attempt,
                error=type(e).__name__)
            obs.instant("worker_restart", worker=worker, chunk=idx,
                        restart=attempt)
            time.sleep(faults.backoff_s(attempt, 20.0))


class _PackAhead:
    """Double-buffered host packing: ONE worker thread runs the chunk
    packer ahead of the dispatch loop, so chunk i+1's tokenize+hash
    overlaps chunk i's ``device_put`` staging and program dispatch on
    the main thread (the native packers release the GIL for the whole
    per-token pass). Depth 2 (``TFIDF_TPU_PACK_AHEAD``) is the classic
    double buffer: one chunk being consumed, one being packed.

    Buffers are per-chunk numpy arrays rather than a reused ping-pong
    pair: ``device_put`` may alias host memory zero-copy (and the
    tunneled backend stages lazily), so rewriting a staging buffer
    before its consuming program runs would corrupt the wire. True
    pinned-memory staging needs allocator support numpy does not
    expose; allocation is micro-seconds next to the pack itself.

    ``get(i)`` blocks until chunk i's pack lands (the loop's only
    stall), then immediately queues the next chunk. Exceptions from
    the packer surface at ``get``. Single worker = packs retire in
    submission order, which the exact-id intern table requires.

    A context manager: ``with _PackAhead(...) as packer`` joins the
    worker thread and cancels queued packs even when a chunk step
    raises mid-loop — otherwise an in-flight pack could outlive the
    loop holding its wire buffer (and, on the exact path, keep
    mutating the shared intern table)."""

    def __init__(self, fn, items, depth: Optional[int] = None):
        import concurrent.futures as cf
        if depth is None:
            depth = max(1, int(os.environ.get("TFIDF_TPU_PACK_AHEAD",
                                              "2")))
        self._fn = fn
        self._items = list(items)
        self._host_s = 0.0
        # The thread name is the packer's trace lane (obs.tracer keys
        # Chrome-trace tids on thread identity).
        self._ex = cf.ThreadPoolExecutor(max_workers=1,
                                         thread_name_prefix="tfidf-packer")
        self._futs = {}
        self._next = 0
        for _ in range(min(depth, len(self._items))):
            self._submit()

    def _submit(self) -> None:
        i = self._next
        if i >= len(self._items):
            return
        _trace("pack_submit", i)

        def job(item=self._items[i], i=i):
            obs.name_thread("packer")
            _health_beat("packer")  # no-op unless a monitor is armed

            def body():
                t0 = time.perf_counter()
                with obs.span("pack", chunk=i):
                    out = self._fn(item)
                self._host_s += time.perf_counter() - t0
                return out

            return _supervised_job("packer", i, body)

        self._futs[i] = self._ex.submit(job)
        self._next += 1

    def get(self, i: int):
        out = self._futs.pop(i).result()
        _trace("pack_done", i)
        self._submit()
        return out

    @property
    def host_seconds(self) -> float:
        """Wall-clock the worker spent packing (thread time — overlaps
        the main thread's staging/dispatch; phases report it as
        ``pack_host`` next to the stall-only ``pack``)."""
        return self._host_s

    def close(self) -> None:
        self._ex.shutdown(wait=True, cancel_futures=True)

    def __enter__(self) -> "_PackAhead":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


class _DrainAhead:
    """Bounded asynchronous device→host result drain — the downlink
    twin of :class:`_PackAhead`. ``put(i, words)`` starts the packed
    buffer's ``copy_to_host_async`` on the main thread (so the transfer
    of chunk i's words rides behind the device's scoring of chunk i+1)
    and queues the host-side materialize+unpack on ONE worker thread;
    ``results()`` returns the unpacked ``(vals, ids)`` per chunk.

    Depth (``TFIDF_TPU_FETCH_AHEAD``, default 2 — one buffer landing
    while the next chunk scores) bounds the copies in flight: past it,
    ``put`` blocks on the oldest outstanding drain, which also bounds
    the device-side dispatch queue (a chunk's copy can only complete
    after its scoring does). The single worker retires chunks in
    submission order, so results land CHUNK-MAJOR regardless of
    completion order — the drain's ordering contract
    (tests/test_downlink.py).

    A context manager for the same exception-safety reason as
    ``_PackAhead``: ``close()`` joins the worker and cancels queued
    unpacks when the dispatch loop raises mid-drain."""

    def __init__(self, unpack, depth: Optional[int] = None):
        import concurrent.futures as cf
        if depth is None:
            depth = int(os.environ.get("TFIDF_TPU_FETCH_AHEAD", "2"))
        if depth < 1:
            raise ValueError(
                f"TFIDF_TPU_FETCH_AHEAD must be >= 1, got {depth}")
        self._unpack = unpack
        self._depth = depth
        # The thread name is the drainer's trace lane (obs.tracer).
        self._ex = cf.ThreadPoolExecutor(max_workers=1,
                                         thread_name_prefix="tfidf-drainer")
        self._futs: List = []
        self._waited = 0
        self._host_s = 0.0

    def put(self, idx: int, words) -> None:
        # Start the D2H copy NOW (async): the tunneled link moves the
        # bytes while the device scores later chunks; the worker's
        # np.asarray then mostly finds them already on host.
        words.copy_to_host_async()
        _trace("drain_submit", idx)
        nbytes = int(words.nbytes)

        def job(words=words, idx=idx):
            obs.name_thread("drainer")
            _health_beat("drainer")  # no-op unless a monitor is armed

            def body():
                t0 = time.perf_counter()
                with obs.span("drain", chunk=idx, bytes=nbytes):
                    out = self._unpack(np.asarray(words))
                self._host_s += time.perf_counter() - t0
                return out

            out = _supervised_job("drainer", idx, body)
            _trace("drain_done", idx)
            return out

        self._futs.append(self._ex.submit(job))
        # Depth guard: never more than `depth` drains outstanding.
        while len(self._futs) - self._waited > self._depth:
            self._futs[self._waited].result()
            self._waited += 1

    def results(self) -> List:
        """Block until every submitted drain lands; chunk-major."""
        return [f.result() for f in self._futs]

    @property
    def host_seconds(self) -> float:
        """Wall-clock the worker spent materializing+unpacking (thread
        time — overlaps the main thread's scoring dispatches; phases
        report it as ``fetch_host`` next to the stall-only ``fetch``)."""
        return self._host_s

    def close(self) -> None:
        self._ex.shutdown(wait=True, cancel_futures=True)

    def __enter__(self) -> "_DrainAhead":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def _unpack_words_rows(words: np.ndarray, score_dtype):
    """The drain worker's host decode: packed words of ANY leading
    shape -> row-major 2-D ``(vals, tids)``. A per-chunk [D, K] buffer
    decodes unchanged; the scanned finish's single [n_chunks, D, K]
    buffer flattens to chunk-major [n_chunks*D, K] rows — the same
    concatenation order the chunked drain produces, so both finishes
    feed one result-assembly path."""
    vals, tids = unpack_result_words(words, score_dtype=score_dtype)
    return (vals.reshape(-1, vals.shape[-1]),
            tids.reshape(-1, tids.shape[-1]))


def _chunk_step(wire_arr, lens, df_acc, cfg: PipelineConfig, length: int,
                ragged: bool, fold_df: bool = True):
    """THE per-chunk dispatch of the resident path — the single call
    site of the chunk kernels, shared by :func:`run_overlapped` and
    :func:`profile_resident` so both hit one jit cache entry (two
    textually-identical call sites measurably compiled twice).

    ``fold_df=False`` (round 5): skip the per-chunk DF fold entirely —
    valid ONLY when the caller's finish program derives the [V] DF
    vector from the concatenated triples (``_finish_wire`` with the
    sort-join lowering, which already globally sorts the head-masked
    ids). Saves a ~12.5 ms global sort + ~10.6 ms searchsorted PER
    CHUNK (the dominant chunk-program cost after the wire alignment);
    the finish pays the searchsorted once. Streaming/mesh/retrieval
    paths keep the fold — their DF accumulator IS the point."""
    if ragged:
        return _chunk_ragged(wire_arr, lens, df_acc, length=length,
                             vocab_size=cfg.vocab_size,
                             align=_wire_align(), fold_df=fold_df,
                             rebuild=rebuild_method())
    return _chunk_sort_fold(wire_arr, lens, df_acc,
                            vocab_size=cfg.vocab_size, fold_df=fold_df)


# --- mesh (multi-chip) resident ingest -------------------------------
#
# The composition of the two flagship paths (VERDICT r3 item 1): the
# overlapped chunked ingest running over a docs-sharded device mesh —
# the TPU-native form of the reference's distributed ingest, where
# every rank independently processes its own document shard
# (TFIDF.c:130-138). Docs axis only, the sparse-engine doctrine
# (parallel/collectives.make_sparse_sharded_forward): row sorting is
# doc-local, and the [V] DF vector is cheap to replicate.
#
# DF protocol: each shard folds its own partial DF into its row of a
# [S, V] docs-sharded accumulator — the per-chunk step has NO
# collective. The finish program performs the run's single lax.psum
# (the reference's entire Phase 2, TFIDF.c:215-220) and scores each
# shard's resident triples against the corpus-wide IDF.

@functools.lru_cache(maxsize=32)
def _mesh_chunk_step_fn(plan: "MeshPlan", vocab_size: int):
    from jax.sharding import PartitionSpec as P

    from tfidf_tpu.parallel.mesh import DOCS_AXIS

    def body(tokens, lengths, df_part):
        # Blocks: tokens [Dl, L], lengths [Dl], df_part [1, V] (this
        # shard's row of the partial-DF accumulator).
        ids, counts, head = sorted_term_counts(tokens, lengths)
        return ids, counts, head, \
            df_part + sparse_df(ids, head, vocab_size)[None, :]

    sharded = (P(DOCS_AXIS, None), P(DOCS_AXIS), P(DOCS_AXIS, None))
    mapped = shard_map(body, mesh=plan.mesh, in_specs=sharded,
                           out_specs=(P(DOCS_AXIS, None),) * 4)
    return jax.jit(mapped)


# Mesh streaming kernels (two-pass, beyond the resident budget): pass
# A folds shard-local DF partials with NO collective; one tiny program
# reduces them to the corpus-wide IDF (the run's single psum); pass B
# scores each chunk per shard against the replicated IDF.
@functools.lru_cache(maxsize=32)
def _mesh_phase_a_fn(plan: "MeshPlan", vocab_size: int):
    from jax.sharding import PartitionSpec as P

    from tfidf_tpu.parallel.mesh import DOCS_AXIS

    def body(tokens, lengths, df_part):
        ids, _, head = sorted_term_counts(tokens, lengths)
        return df_part + sparse_df(ids, head, vocab_size)[None, :]

    mapped = shard_map(
        body, mesh=plan.mesh,
        in_specs=(P(DOCS_AXIS, None), P(DOCS_AXIS), P(DOCS_AXIS, None)),
        out_specs=P(DOCS_AXIS, None))
    return jax.jit(mapped)


@functools.lru_cache(maxsize=32)
def _mesh_idf_fn(plan: "MeshPlan", score_dtype):
    from jax.sharding import PartitionSpec as P

    from tfidf_tpu.parallel.mesh import DOCS_AXIS

    def body(df_part, num_docs):
        df_total = lax.psum(df_part[0], DOCS_AXIS)  # the ONE collective
        return df_total, idf_from_df(df_total, num_docs, score_dtype)

    mapped = shard_map(body, mesh=plan.mesh,
                           in_specs=(P(DOCS_AXIS, None), P()),
                           out_specs=(P(), P()), check_vma=False)
    return jax.jit(mapped)


@functools.lru_cache(maxsize=32)
def _mesh_phase_b_fn(plan: "MeshPlan", topk: int):
    from jax.sharding import PartitionSpec as P

    from tfidf_tpu.parallel.mesh import DOCS_AXIS

    def body(tokens, lengths, idf):
        ids, counts, head = sorted_term_counts(tokens, lengths)
        scores = sparse_scores(ids, counts, head, lengths, idf)
        return sparse_topk(scores, ids, head, topk)

    mapped = shard_map(
        body, mesh=plan.mesh,
        in_specs=(P(DOCS_AXIS, None), P(DOCS_AXIS), P()),
        out_specs=(P(DOCS_AXIS, None), P(DOCS_AXIS, None)),
        check_vma=False)
    return jax.jit(mapped)


@functools.lru_cache(maxsize=32)
def _mesh_phase_b_cached_fn(plan: "MeshPlan", topk: int):
    from jax.sharding import PartitionSpec as P

    from tfidf_tpu.parallel.mesh import DOCS_AXIS

    def body(ids, counts, head, lengths, idf):
        scores = sparse_scores(ids, counts, head, lengths, idf)
        return sparse_topk(scores, ids, head, topk)

    mapped = shard_map(
        body, mesh=plan.mesh,
        in_specs=(P(DOCS_AXIS, None),) * 3 + (P(DOCS_AXIS), P()),
        out_specs=(P(DOCS_AXIS, None), P(DOCS_AXIS, None)),
        check_vma=False)
    return jax.jit(mapped)


# Pass-A sort+cache variant: same as _mesh_chunk_step_fn (triples kept
# for the streaming triple cache) — reused directly.


def _run_overlapped_mesh_streaming(input_dir: str, cfg: PipelineConfig,
                                   plan: "MeshPlan", chunk_docs: int,
                                   length: int, names: List[str],
                                   spill: str) -> IngestResult:
    """Two-pass streaming ingest over a docs-sharded mesh — the
    beyond-HBM regime of the multi-chip composition. Same structure as
    the single-device streaming path (pass A folds DF, pass B rescores
    against the final IDF; device triple cache up to a byte budget that
    scales with the shard count), with every program under shard_map
    and exactly ONE collective per run (the DF psum in ``_mesh_idf_fn``).
    Value parity with the single-device streaming path is pinned by
    tests/test_ingest.py."""
    from jax.sharding import PartitionSpec as P

    from tfidf_tpu.parallel.mesh import DOCS_AXIS

    num_docs = len(names)
    score_dtype = jax.dtypes.canonicalize_dtype(jnp.dtype(cfg.score_dtype))
    k = min(cfg.topk, length)
    shards = plan.n_docs_shards
    chunk_docs += -chunk_docs % shards  # rows must block-shard evenly
    _check_chunk_fits_int32(chunk_docs, length)
    starts = list(range(0, num_docs, chunk_docs))
    pack_chunk = make_chunk_packer(input_dir, cfg, chunk_docs, length)
    if spill == "auto":
        est = num_docs * length * 4
        budget = int(os.environ.get("TFIDF_TPU_SPILL_BYTES",
                                    _DEFAULT_SPILL_BYTES))
        spill = "host" if est <= budget else "reread"

    batch_sh = plan.sharding(P(DOCS_AXIS, None))
    lens_sh = plan.sharding(plan.lengths_spec())
    step = _mesh_chunk_step_fn(plan, cfg.vocab_size)  # sort + DF fold
    phase_a = _mesh_phase_a_fn(plan, cfg.vocab_size)

    # Triple cache: per-shard HBM holds 1/S of each cached chunk, so
    # the budget scales with the shard count.
    cache_budget = shards * int(os.environ.get(
        "TFIDF_TPU_TRIPLE_CACHE_BYTES", _TRIPLE_CACHE_BYTES))
    trip_cache: Dict[int, tuple] = {}
    cache_bytes = 0
    chunk_cache_bytes = chunk_docs * length * 9 + chunk_docs * 4

    ph = {"pack_a": 0.0, "pack_b": 0.0}
    # Multi-process composition: _put_sharded / _fetch_global make this
    # regime process-spanning like the resident one. Unlike the
    # resident path, every process packs the FULL chunk and the
    # callback slices its addressable rows — acceptable for the
    # beyond-HBM regime (host pack overlaps device pass A), noted as
    # duplicated host work.
    df_acc = _put_sharded(np.zeros((shards, cfg.vocab_size), np.int32),
                          batch_sh)
    cached: List[Optional[Tuple[np.ndarray, np.ndarray]]] = []
    all_lengths: List[np.ndarray] = []
    t_pass = time.perf_counter()
    for ci, start in enumerate(starts):
        chunk_names = names[start:start + chunk_docs]
        t0 = time.perf_counter()
        token_ids, lengths = pack_chunk(chunk_names)
        ph["pack_a"] += time.perf_counter() - t0
        all_lengths.append(lengths[:len(chunk_names)])
        toks = _put_sharded(token_ids, batch_sh)
        lens = _put_sharded(lengths, lens_sh)
        if cache_bytes + chunk_cache_bytes <= cache_budget:
            i_, c_, h_, df_acc = step(toks, lens, df_acc)
            trip_cache[ci] = (i_, c_, h_, lens)
            cache_bytes += chunk_cache_bytes
            if spill == "host":
                cached.append(None)
        else:
            if spill == "host":
                cached.append((token_ids, lengths))
            df_acc = phase_a(toks, lens, df_acc)
    df_acc.block_until_ready()
    ph["pass_a"] = time.perf_counter() - t_pass
    ph["triple_cached_chunks"] = float(len(trip_cache))

    df_total, idf = _mesh_idf_fn(plan, score_dtype)(df_acc,
                                                    jnp.int32(num_docs))

    phase_b = _mesh_phase_b_fn(plan, k)
    phase_b_cached = _mesh_phase_b_cached_fn(plan, k)
    vals_parts, ids_parts = [], []
    t_pass = time.perf_counter()
    for ci, start in enumerate(starts):
        if ci in trip_cache:
            i_, c_, h_, lens = trip_cache.pop(ci)
            v, t = phase_b_cached(i_, c_, h_, lens, idf)
        else:
            if spill == "host":
                token_ids, lengths = cached[ci]
            else:
                t0 = time.perf_counter()
                token_ids, lengths = pack_chunk(
                    names[start:start + chunk_docs])
                ph["pack_b"] += time.perf_counter() - t0
            v, t = phase_b(_put_sharded(token_ids, batch_sh),
                           _put_sharded(lengths, lens_sh), idf)
        vals_parts.append(v)
        ids_parts.append(t)
    jax.block_until_ready((vals_parts, ids_parts))
    ph["pass_b"] = time.perf_counter() - t_pass

    t0 = time.perf_counter()
    cat_v, cat_t = jnp.concatenate(vals_parts), jnp.concatenate(ids_parts)
    bytes_pair = cat_t.size * pair_slot_bytes(score_dtype)
    # Packed result wire: the per-shard selections cross the link as
    # uint32 words (elementwise device pack, no collective) — half the
    # pair bytes on the same batched fetch.
    if use_packed_result_wire(cfg):
        words = pack_words(cat_v, cat_t)
        df_host, words_h = _fetch_global((df_total, words))
        vals, tids = unpack_result_words(words_h, score_dtype=score_dtype)
        rw, bytes_off = "packed", words_h.nbytes
    else:
        df_host, vals, tids = _fetch_global((df_total, cat_v, cat_t))
        rw, bytes_off = "pair", vals.nbytes + tids.nbytes
    ph["fetch"] = time.perf_counter() - t0
    return IngestResult(df=df_host, topk_vals=vals[:num_docs],
                        topk_ids=tids[:num_docs],
                        lengths=np.concatenate(all_lengths), names=names,
                        num_docs=num_docs,
                        df_occupied=int((df_host > 0).sum()),
                        path="streaming-mesh", phases=ph,
                        result_wire=rw, bytes_off_wire=int(bytes_off),
                        bytes_off_wire_pair=int(bytes_pair))


def _put_sharded(arr: np.ndarray, sh) -> jax.Array:
    """``device_put`` with a sharding that may span processes.

    Single-process: a plain ``device_put`` (every shard addressable).
    Multi-process (``jax.distributed`` initialized — the DCN analog of
    the reference's N-rank deployment, ``TFIDF.c:130``): build the
    global array from per-shard callbacks, so THIS process only
    materializes device buffers for its own addressable rows."""
    if jax.process_count() == 1:
        return jax.device_put(arr, sh)
    return jax.make_array_from_callback(arr.shape, sh,
                                        lambda idx: arr[idx])


def _fetch_global(tree):
    """Host copy of a (tree of) possibly process-spanning global
    arrays. Single-process: ONE batched ``device_get`` — one link
    round trip, same as always (the tunnel charges ~100 ms per fetch
    regardless of size, docs/SCALING.md). Multi-process: fully-
    replicated leaves (the post-psum DF) read locally; docs-sharded
    leaves ride ``process_allgather`` — the all-to-all replacement for
    the reference's serial rank-0 gather (``TFIDF.c:256-270``): every
    process ends with the full result, no coordinator bottleneck."""
    if jax.process_count() == 1:
        return jax.device_get(tree)
    from jax.experimental import multihost_utils

    def one(arr):
        if arr.is_fully_replicated:
            return jax.device_get(arr)
        return np.asarray(multihost_utils.process_allgather(arr,
                                                            tiled=True))

    return jax.tree_util.tree_map(one, tree)


@functools.lru_cache(maxsize=32)
def _mesh_finish_fn(plan: "MeshPlan", n_chunks: int, topk: int, score_dtype):
    from jax.sharding import PartitionSpec as P

    from tfidf_tpu.parallel.mesh import DOCS_AXIS

    def body(trip_i, trip_c, trip_h, lens_parts, df_part, num_docs):
        cat = (lambda parts: parts[0] if len(parts) == 1
               else jnp.concatenate(parts, axis=0))
        ids, counts, head = cat(trip_i), cat(trip_c), cat(trip_h)
        lengths = cat(lens_parts)
        # THE one collective of the whole run (reference Phase 2:
        # reduce-then-rebroadcast == allreduce, TFIDF.c:215-220).
        df_total = lax.psum(df_part[0], DOCS_AXIS)
        idf = idf_from_df(df_total, num_docs, score_dtype)
        scores = sparse_scores(ids, counts, head, lengths, idf)
        vals, tids = sparse_topk(scores, ids, head, topk)
        return df_total, vals, tids

    chunks = lambda spec: (spec,) * n_chunks
    in_specs = (chunks(P(DOCS_AXIS, None)), chunks(P(DOCS_AXIS, None)),
                chunks(P(DOCS_AXIS, None)), chunks(P(DOCS_AXIS)),
                P(DOCS_AXIS, None), P())
    # df_total is replicated by the psum — out_spec P(); vals/ids stay
    # docs-sharded. check_vma=False: the static replication checker
    # cannot infer the psum-made replication.
    out_specs = (P(), P(DOCS_AXIS, None), P(DOCS_AXIS, None))
    mapped = shard_map(body, mesh=plan.mesh, in_specs=in_specs,
                           out_specs=out_specs, check_vma=False)
    return jax.jit(mapped)


def _run_overlapped_mesh(input_dir: str, cfg: PipelineConfig,
                         plan: "MeshPlan", chunk_docs: int, length: int,
                         names: List[str],
                         wire_vals: bool = True) -> IngestResult:
    """Resident overlapped ingest over a docs-sharded device mesh.

    Same overlap structure as the single-device resident path — the
    host packs chunk i+1 while chunk i's sharded upload + sort is in
    flight — but every program runs under ``shard_map``: each shard
    sorts only its own document rows and folds only its own DF
    partial. The wire is the PADDED [chunk, L] batch (not the ragged
    flat stream): a block-sharded ``device_put`` sends each device
    exactly its rows, where a flat ragged stream cannot split evenly
    without per-shard sub-wires.

    Value contract: identical outputs to the single-device resident
    path on the same corpus (df exact, topk ids exact, scores same
    float ops) — pinned by tests/test_ingest.py.
    """
    if plan.n_seq_shards != 1 or plan.n_vocab_shards != 1:
        raise ValueError("mesh ingest shards the docs axis only; build "
                         "the MeshPlan with seq=1, vocab=1 (sparse-engine "
                         "doctrine)")
    num_docs = len(names)
    score_dtype = jax.dtypes.canonicalize_dtype(jnp.dtype(cfg.score_dtype))
    k = min(cfg.topk, length)
    shards = plan.n_docs_shards

    chunk_docs, _ = _resident_chunking(num_docs, chunk_docs)
    chunk_docs += -chunk_docs % shards  # rows must block-shard evenly
    starts = list(range(0, num_docs, chunk_docs))
    _check_chunk_fits_int32(chunk_docs, length)
    pack_chunk = make_chunk_packer(input_dir, cfg, chunk_docs, length)

    from jax.sharding import PartitionSpec as P

    from tfidf_tpu.parallel.mesh import DOCS_AXIS

    step = _mesh_chunk_step_fn(plan, cfg.vocab_size)
    batch_sh = plan.sharding(P(DOCS_AXIS, None))
    lens_sh = plan.sharding(plan.lengths_spec())

    ph = {"pack": 0.0, "put": 0.0}
    df_acc = _put_sharded(np.zeros((shards, cfg.vocab_size), np.int32),
                          batch_sh)
    # Multi-process composition (VERDICT r4 item 4): with a process-
    # spanning mesh (jax.distributed), each process packs ONLY the
    # document rows of its own shards — per-process chunk ingest, the
    # reference's per-rank file loop (TFIDF.c:130-138) — and the run's
    # single DF psum crosses the process boundary in the finish
    # program. Global lengths ride a tiny per-chunk allgather.
    multi = jax.process_count() > 1
    dl = chunk_docs // shards
    pack_block = (make_chunk_packer(input_dir, cfg, dl, length)
                  if multi else None)
    trip_i, trip_c, trip_h, len_parts, all_lengths = [], [], [], [], []
    for start in starts:
        chunk_names = names[start:start + chunk_docs]
        t0 = time.perf_counter()
        if multi:
            cache: Dict[int, tuple] = {}

            def block(r0, chunk_names=chunk_names, cache=cache):
                if r0 not in cache:
                    cache[r0] = pack_block(chunk_names[r0:r0 + dl])
                return cache[r0]

            toks = jax.make_array_from_callback(
                (chunk_docs, length), batch_sh,
                lambda idx: block(idx[0].start or 0)[0])
            lens = jax.make_array_from_callback(
                (chunk_docs,), lens_sh,
                lambda idx: block(idx[0].start or 0)[1])
            ph["pack"] += time.perf_counter() - t0
            lengths = _fetch_global(lens)
        else:
            token_ids, lengths = pack_chunk(chunk_names)
            ph["pack"] += time.perf_counter() - t0
        all_lengths.append(lengths[:len(chunk_names)])
        t0 = time.perf_counter()
        if not multi:
            lens = jax.device_put(lengths, lens_sh)
            toks = jax.device_put(token_ids, batch_sh)
        i_, c_, h_, df_acc = step(toks, lens, df_acc)
        trip_i.append(i_)
        trip_c.append(c_)
        trip_h.append(h_)
        len_parts.append(lens)
        ph["put"] += time.perf_counter() - t0

    t0 = time.perf_counter()
    finish = _mesh_finish_fn(plan, len(starts), k, score_dtype)
    df_dev, vals, tids = finish(tuple(trip_i), tuple(trip_c), tuple(trip_h),
                                tuple(len_parts), df_acc,
                                jnp.int32(num_docs))
    # wire_vals=False (the exact-terms fetch diet): the re-rank reads
    # only candidate buckets, so the [D, K] float scores stay on
    # device — same contract as _score_pack_wire's ids-only wire,
    # except invalid slots keep their -1 (no bucket-0 stand-in). The
    # occupied-bucket scalar joins the same fetch (margin_check feed).
    #
    # Round 7: the [V] DF vector joins the SAME batched _fetch_global,
    # so IngestResult.df is one type (a host ndarray) on every ingest
    # path — the old mesh result held a live device array no other
    # path produced. On the packed result wire the (vals, tids)
    # selection crosses the link as uint32 words, packed ON DEVICE per
    # shard (pack_words is elementwise, so each shard packs its own
    # rows — no collective); the host-side shard-major reorder is
    # unchanged, it just follows the unpack.
    occ_dev = (df_dev > 0).sum(dtype=jnp.int32)
    packed_wire = wire_vals and use_packed_result_wire(cfg)
    bytes_pair = tids.size * pair_slot_bytes(score_dtype)
    if packed_wire:
        words = pack_words(vals, tids)
        df_host, words_h, occ = _fetch_global((df_dev, words, occ_dev))
        vals, tids = unpack_result_words(words_h, score_dtype=score_dtype)
        bytes_off = words_h.nbytes
    elif wire_vals:
        df_host, vals, tids, occ = _fetch_global((df_dev, vals, tids,
                                                  occ_dev))
        bytes_off = vals.nbytes + tids.nbytes
    else:
        vals = None
        df_host, tids, occ = _fetch_global((df_dev, tids, occ_dev))
        bytes_off = tids.nbytes
    ph["fetch"] = time.perf_counter() - t0

    # The sharded outputs come back shard-major (shard s's chunks are
    # contiguous); restore the chunk-major document order the names
    # list uses. dl = rows per shard per chunk.
    n_chunks, dl = len(starts), chunk_docs // shards
    reorder = (lambda a: a.reshape(shards, n_chunks, dl, -1)
               .transpose(1, 0, 2, 3).reshape(n_chunks * chunk_docs, -1))
    vals = reorder(vals) if vals is not None else None
    tids = reorder(tids)
    return IngestResult(df=df_host,
                        topk_vals=(vals[:num_docs]
                                   if vals is not None else None),
                        topk_ids=tids[:num_docs],
                        lengths=np.concatenate(all_lengths), names=names,
                        num_docs=num_docs, df_occupied=int(occ),
                        path="resident-mesh", phases=ph,
                        result_wire="packed" if packed_wire else "pair",
                        bytes_off_wire=int(bytes_off),
                        bytes_off_wire_pair=int(bytes_pair))


def _check_chunk_fits_int32(chunk_docs: int, length: int) -> None:
    """Chunk-shape int32 guard (advisor r3): the ragged rebuild builds
    int32 flat offsets and the row sort builds int32 slot positions,
    so a single chunk must hold < 2^31 token slots on EITHER wire.
    (The ragged wire's slightly tighter aligned-capacity bound no
    longer raises — :func:`use_ragged_wire` degrades those chunks to
    the padded wire instead.) Also revalidates the wire alignment so
    a bad ``TFIDF_TPU_WIRE_ALIGN`` fails at this entry point by name."""
    _wire_align()
    if chunk_docs * length >= (1 << 31):
        raise ValueError(
            f"chunk of {chunk_docs} docs x {length} tokens overflows "
            f"int32 flat offsets; lower --chunk-docs or raise "
            f"TFIDF_TPU_MAX_CHUNKS")


def _check_total_slots_fit_int32(total_rows: int, length: int) -> None:
    """Total-resident-slots int32 guard (ADVICE round 5): the resident
    finish program concatenates EVERY chunk's triples, and the
    sort-join (``ops.sparse.df_slot_sorted``) builds int32 slot
    indices over that concatenated [D_total * L] stream — a bound the
    per-chunk check cannot see. In practice the HBM budget subsumes it
    (2^31 slots carry ≈19 GB of triples before any sort workspace),
    but past it the failure mode would be silent index wraparound, so
    the bound is explicit here and re-asserted inside df_slot_sorted."""
    if total_rows * length >= (1 << 31):
        raise ValueError(
            f"resident corpus of {total_rows} doc slots x {length} tokens "
            f"overflows the finish program's int32 sort-join slot "
            f"indices; lower TFIDF_TPU_RESIDENT_ELEMS so the streaming "
            f"regime takes over, or reduce --doc-len")


def _resident_df_mode() -> Tuple[str, bool]:
    """(join, derive_df) for the resident/exact fused path, resolved
    once per run at trace time: with the sort-join lowering the finish
    derives the [V] DF vector from its own global sort, so the chunk
    programs skip their per-chunk fold (``fold_df = not derive_df``)."""
    from tfidf_tpu.ops.sparse import join_method

    join = join_method()
    return join, join == "sort"


def _finish_wire(trips, len_parts, df_acc, num_docs: int, k: int,
                 score_dtype, cfg: PipelineConfig, wire_vals: bool,
                 exact_wire: bool = False):
    """THE final score+pack dispatch (single call site, as above).
    Precondition for the sort-join lowering: ``df_acc`` must be the DF
    of exactly these triples' heads — either accumulated by the chunk
    folds, or (derive_df) zeros that this program REPLACES with the
    derived vector from its own sort (DF is additive over chunks, so
    both produce identical counts)."""
    join, derive = _resident_df_mode()
    trip_i, trip_c, trip_h = trips
    return _score_pack_wire(
        tuple(trip_i), tuple(trip_c), tuple(trip_h), tuple(len_parts),
        df_acc, jnp.int32(num_docs), topk=k, score_dtype=score_dtype,
        wide_ids=cfg.vocab_size > (1 << 16), include_vals=wire_vals,
        include_counts=exact_wire, join=join, derive_df=derive)


def _resident_chunking(num_docs: int, chunk_docs: int):
    """Resident-path chunk rule, shared by :func:`run_overlapped` and
    :func:`profile_resident` so the profiler always measures the same
    program structure production dispatches. Caps the chunk count
    (default 32, ``TFIDF_TPU_MAX_CHUNKS``): every chunk costs a program
    dispatch through the tunnel (~8 ms each, measured) and a slot in
    the final program's arg list — but staging cost grows superlinearly
    with chunk bytes on this link, so very large corpora may tune this
    up."""
    cap = max(1, int(os.environ.get("TFIDF_TPU_MAX_CHUNKS", 32)))
    starts = list(range(0, num_docs, chunk_docs))
    if len(starts) > cap:
        chunk_docs = -(-num_docs // cap)
        chunk_docs += -chunk_docs % 256
        starts = list(range(0, num_docs, chunk_docs))
    return chunk_docs, starts


def make_flat_packer(input_dir: str, cfg: PipelineConfig, chunk_docs: int,
                     length: int):
    """Ragged host packing: names -> (flat ids, lengths, total).

    The flat stream is bucket-padded (``_FLAT_BUCKET``) so repeated
    chunks reuse compiled programs. Native single-pass packer when
    built; Python fallback flattens the padded batch (mask-select keeps
    row-major token order). Only valid for vocab <= 2^16 (uint16 wire).
    """
    use_native = (cfg.tokenizer is TokenizerKind.WHITESPACE
                  and fast_tokenizer.flat_available())
    padded = make_chunk_packer(input_dir, cfg, chunk_docs, length)
    # Resolved (and validated) ONCE per packer so a whole run's layout
    # is self-consistent; the rebuild side re-reads the same knob.
    align = _wire_align()
    # Bucket-rounded staging capacity: the native fill emits the wire
    # ragged AND bucket-padded in one buffer (no host-side re-pad copy
    # — _bucket_pad_flat always pads in place at this capacity).
    cap = _bucket_cap_ids(chunk_docs, length, align)

    def pack_native(chunk_names: List[str]):
        out = fast_tokenizer.load_pack_flat(
            [os.path.join(input_dir, n) for n in chunk_names],
            cfg.vocab_size, cfg.hash_seed, cfg.truncate_tokens_at,
            max_per_doc=length, pad_docs_to=chunk_docs,
            n_threads=getattr(cfg, "pack_threads", None),
            align=align, cap_ids=cap)
        assert out is not None
        flat, lengths, total = out
        return _bucket_pad_flat(flat, total), lengths, total

    def pack_python(chunk_names: List[str]):
        ids, lengths = padded(chunk_names)
        # Aligned layout, identical to the native packer (the one
        # Python definition of the wire — flatten_aligned).
        flat, total = flatten_aligned(ids, lengths, align)
        return flat, lengths, total

    return pack_native if use_native else pack_python


# Bytes-wire slab padding granularity — the byte-stream twin of
# _FLAT_BUCKET (same compile-cache purpose: a handful of slab shapes,
# not one per chunk). Default = _FLAT_BUCKET bytes (2^17 = 128 KB): at
# ~3-6 B/token the round-up waste stays in the same few-percent band
# the id bucket was sized for. Read at import like _FLAT_BUCKET.
_BYTE_BUCKET = int(os.environ.get("TFIDF_TPU_BYTE_BUCKET",
                                  str(_FLAT_BUCKET)))
if _BYTE_BUCKET <= 0 or _BYTE_BUCKET & (_BYTE_BUCKET - 1):
    raise ValueError(f"TFIDF_TPU_BYTE_BUCKET must be a positive power "
                     f"of two, got {_BYTE_BUCKET}")


def make_bytes_packer(input_dir: str, cfg: PipelineConfig,
                      chunk_docs: int, length: int,
                      stats: Optional[Dict[str, float]] = None):
    """Bytes-wire host packing: names -> (slab, blens, total) — raw
    document bytes at aligned offsets, 0x20 fill, bucket-padded
    capacity. The host's ENTIRE per-chunk work is a parallel file read
    plus a memcpy; tokenize/hash/pack-ids moved to the device
    (``ops/device_tokenize.py`` has the layout contract). Native slab
    loader when built, contract-identical Python fallback otherwise.

    ``stats`` (optional dict) accumulates the two host sub-phases the
    bench splits pack into — ``load`` (file reads) and ``slab`` (slab
    assembly/copy) — in seconds; the native path measures the same
    boundary (loader_open2 = load, loader_fill_slab = slab). Each pack
    also records a ``slab`` span stamped with the chunk's byte payload
    (tools/trace_check.py validates the stamp)."""
    align = _wire_align()
    use_native = (cfg.tokenizer is TokenizerKind.WHITESPACE
                  and fast_tokenizer.slab_available())

    def add(key: str, secs: float) -> None:
        if stats is not None:
            stats[key] = stats.get(key, 0.0) + secs

    def pack_native(chunk_names: List[str]):
        paths = [os.path.join(input_dir, n) for n in chunk_names]
        t0 = time.perf_counter()
        out = fast_tokenizer.load_slab_paths(
            paths, pad_docs_to=chunk_docs,
            n_threads=getattr(cfg, "pack_threads", None), align=align,
            cap_round=_BYTE_BUCKET)
        assert out is not None  # slab_available() checked above
        slab, blens, total = out
        # The native path reads+fills in one call; the whole wall is
        # the slab phase (its internal read IS the load, but the
        # boundary is not observable through one ctypes call).
        dt = time.perf_counter() - t0
        add("slab", dt)
        with obs.span("slab", bytes=int(slab.nbytes)):
            pass  # native work already done; stamp the payload
        return slab, blens, total

    def pack_python(chunk_names: List[str]):
        t0 = time.perf_counter()
        docs = []
        for n in chunk_names:
            with open(os.path.join(input_dir, n), "rb") as f:
                docs.append(f.read())
        add("load", time.perf_counter() - t0)
        t0 = time.perf_counter()
        d_padded = max(chunk_docs, len(docs))
        blens = np.zeros((d_padded,), np.int32)
        blens[:len(docs)] = [len(d) for d in docs]
        from tfidf_tpu.ops.device_tokenize import aligned_byte_lengths
        albl = aligned_byte_lengths(blens[:len(docs)], align)
        total = int(albl.sum())
        cap = max(total + (-total % _BYTE_BUCKET), _BYTE_BUCKET)
        slab = np.full((cap,), 0x20, np.uint8)
        off = 0
        for doc, a in zip(docs, albl.tolist()):
            slab[off:off + len(doc)] = np.frombuffer(doc, np.uint8)
            off += int(a)
        add("slab", time.perf_counter() - t0)
        with obs.span("slab", bytes=int(slab.nbytes)):
            pass
        return slab, blens, total

    return pack_native if use_native else pack_python


def _check_slab_fits_int32(total: int) -> None:
    """Bytes-wire offset guard: the device tokenizer's byte positions
    and cumulative token counts are int32, so one chunk's slab must
    stay under 2^31 bytes (an absurd chunk — lower --chunk-docs)."""
    if total >= (1 << 31):
        raise ValueError(
            f"bytes-wire chunk slab of {total} bytes overflows int32 "
            f"offsets; lower --chunk-docs")


# Bytes-wire chunk kernels: the slab arrives as raw uint8 document
# bytes; tokenize + FNV-1a64 + fold run ON DEVICE
# (ops/device_tokenize.py — bit-identical to the host packers by
# contract) before the same sort+fold every other wire feeds. The
# kernels RETURN the device-derived [D] lengths (the host never
# tokenizes, so it never knows them): callers keep the device array
# for the finish programs and ride a copy_to_host_async for the
# IngestResult.lengths bookkeeping. _chunk_bytes is NOT donated for
# the same reason as _chunk_ragged — profile_resident re-dispatches
# the same resident slabs through it (cache-sharing doctrine); the
# streaming kernels below donate their always-fresh slabs.
@functools.partial(jax.jit,
                   static_argnames=("length", "vocab_size", "seed",
                                    "truncate_at", "align", "fold_df",
                                    "method"))
def _chunk_bytes(slab, blens, df_acc, *, length: int, vocab_size: int,
                 seed: int, truncate_at, align: int,
                 fold_df: bool = True, method: str = "xla"):
    from tfidf_tpu.ops.device_tokenize import tokenize_hash_device
    from tfidf_tpu.ops.pallas_kernels import default_interpret
    tok, lens = tokenize_hash_device(
        slab, blens, length=length, vocab_size=vocab_size, seed=seed,
        truncate_at=truncate_at, align=align, method=method,
        interpret=default_interpret() if method == "pallas" else False)
    ids, counts, head = sorted_term_counts(tok, lens)
    if not fold_df:  # finish program derives DF (see _chunk_step)
        return ids, counts, head, df_acc, lens
    return ids, counts, head, \
        df_acc + sparse_df(ids, head, vocab_size), lens


@functools.partial(jax.jit, donate_argnums=(0,),
                   static_argnames=("length", "vocab_size", "seed",
                                    "truncate_at", "align", "method"))
def _phase_a_bytes(slab, blens, df_acc, *, length: int, vocab_size: int,
                   seed: int, truncate_at, align: int,
                   method: str = "xla"):
    from tfidf_tpu.ops.device_tokenize import tokenize_hash_device
    from tfidf_tpu.ops.pallas_kernels import default_interpret
    tok, lens = tokenize_hash_device(
        slab, blens, length=length, vocab_size=vocab_size, seed=seed,
        truncate_at=truncate_at, align=align, method=method,
        interpret=default_interpret() if method == "pallas" else False)
    ids, _, head = sorted_term_counts(tok, lens)
    return df_acc + sparse_df(ids, head, vocab_size), lens


@functools.partial(jax.jit, donate_argnums=(0,),
                   static_argnames=("length", "vocab_size", "seed",
                                    "truncate_at", "align", "topk",
                                    "method", "packed"))
def _phase_b_bytes(slab, blens, idf, *, length: int, vocab_size: int,
                   seed: int, truncate_at, align: int, topk: int,
                   method: str = "xla", packed: bool = True):
    from tfidf_tpu.ops.device_tokenize import tokenize_hash_device
    from tfidf_tpu.ops.pallas_kernels import default_interpret
    tok, lens = tokenize_hash_device(
        slab, blens, length=length, vocab_size=vocab_size, seed=seed,
        truncate_at=truncate_at, align=align, method=method,
        interpret=default_interpret() if method == "pallas" else False)
    ids, counts, head = sorted_term_counts(tok, lens)
    out = score_topk(ids, counts, head, lens, idf, topk)
    return pack_result_words(*out) if packed else out


# Final program of the resident path: score the cached triples against
# the corpus-wide IDF and pack (scores, topk ids) into ONE uint8
# buffer — a single unfenced device_get is one link round trip. Scores
# ship in score_dtype itself, full precision (the round-2 bf16
# compaction cost tie precision — advisor finding — and the bf16
# bitcast lowering measured pathological on this backend anyway). Ids
# travel as uint16 when the vocab fits 16 bits; invalid slots carry
# score -1 on the wire (valid scores are >= 0 by construction), so a
# legitimate 0.0 score survives. DF is returned as a device array — no
# hot-path consumer reads it, so its fetch is lazy (np.asarray at the
# caller's leisure).
@functools.partial(jax.jit,
                   static_argnames=("topk", "score_dtype", "wide_ids",
                                    "include_vals", "include_counts",
                                    "join", "derive_df"))
def _score_pack_wire(ids, counts, head, lengths, df, num_docs, *,
                     topk: int, score_dtype, wide_ids: bool,
                     include_vals: bool = True,
                     include_counts: bool = False,
                     join: str = "gather", derive_df: bool = False):
    cat = (lambda parts: parts[0] if len(parts) == 1
           else jnp.concatenate(parts, axis=0))
    ids, counts, head = cat(ids), cat(counts), cat(head)
    lengths = cat(lengths)
    if join == "sort":
        # Sort-join: each slot's DF from the concatenated triples
        # themselves (ops/sparse.df_slot_sorted) — valid because this
        # program's callers pass ``df`` computed from exactly these
        # triples' heads (resident fold / exact fold), so the join IS
        # the accumulator's DF. Replaces the [V]-table gather the
        # round-5 trace measured at 59.8 ms/call with two equal-width
        # sorts (~25 ms). The mesh finish (psum'd DF != local triples)
        # never takes this path.
        from tfidf_tpu.ops.sparse import (df_slot_sorted,
                                          sparse_scores_joined)
        df_slot, srt = df_slot_sorted(ids, head)
        if derive_df:
            # The [V] DF vector from the SAME global sort (the chunk
            # programs skipped their per-chunk fold, fold_df=False):
            # one searchsorted here replaces a sort+searchsorted PER
            # CHUNK. Identical counts — this is the sparse_df "sort"
            # lowering applied to the concatenated heads.
            edges = jnp.arange(df.shape[0] + 1, dtype=jnp.int32)
            pos = jnp.searchsorted(srt, edges)
            df = (pos[1:] - pos[:-1]).astype(jnp.int32)
        scores = sparse_scores_joined(counts, head, lengths, df_slot,
                                      num_docs, score_dtype)
    else:
        idf = idf_from_df(df, num_docs, score_dtype)
        scores = sparse_scores(ids, counts, head, lengths, idf)
    as_bytes = lambda a: lax.bitcast_convert_type(a, jnp.uint8).reshape(-1)
    if include_counts:
        # Exact-ids wire (collision-free intern ids): the host rescores
        # the selection in float64 from integers alone, so ship
        # (id u16/i32, count u16) per selected slot plus ONE copy of
        # the full [V] DF vector (256 KB at 2^16 — far smaller than a
        # per-slot df column, and it doubles as the boundary-tie
        # fallback's exact DF). No scores, no document re-pass
        # (rerank.exact_topk_from_wire). count 0 marks invalid slots
        # (a real selection has count >= 1).
        from tfidf_tpu.ops.sparse import sparse_topk_counts
        if ids.shape[1] > (1 << 16) - 1:
            raise ValueError("exact-ids wire carries uint16 counts: "
                             "doc_len must be < 65536")
        _, tids, tcnt = sparse_topk_counts(scores, ids, counts, head, topk)
        ok = tids >= 0
        safe = jnp.maximum(tids, 0)
        body = [as_bytes(safe if wide_ids else safe.astype(jnp.uint16)),
                as_bytes(jnp.where(ok, tcnt, 0).astype(jnp.uint16)),
                as_bytes(df.astype(jnp.int32))]
        return df, jnp.concatenate(body)
    vals, tids = sparse_topk(scores, ids, head, topk)
    # Occupied-bucket count rides the wire as a 4-byte tail: the
    # exact-terms margin warning (rerank.margin_check) needs only this
    # scalar, and folding it here keeps the DF vector itself on device
    # with NO hot-path D2H round trip (advisor r3 finding: the old
    # np.asarray(df) inside exact_topk cost a full link latency).
    occ = as_bytes((df > 0).sum(dtype=jnp.int32).reshape(1))
    if not include_vals:
        # Ids-only wire (exact-terms mode: the host re-rank reads only
        # the candidate buckets, so scores would be dead fetch bytes —
        # 2/3 of a [1M, 64] result). Invalid slots map to bucket 0,
        # which is harmless by construction: a doc with fewer than k'
        # distinct terms already has ALL its terms selected, so the
        # spurious bucket can only add out-of-doc candidates the
        # re-rank scores exactly and discards.
        tids = jnp.maximum(tids, 0)
        body = as_bytes(tids if wide_ids else tids.astype(jnp.uint16))
        return df, jnp.concatenate([body, occ])
    # Valid scores are >= 0 by construction (idf >= 0, tf > 0 — the
    # reference's invariant, TFIDF.c:243); -1 marks invalid slots so a
    # legitimate 0.0 score (word in every doc) survives the u16 ids.
    # Scores ship in score_dtype itself — full precision on every path
    # (the IngestResult contract).
    ok = tids >= 0
    vals_wire = jnp.where(ok, vals, jnp.asarray(-1, vals.dtype))
    tid_wire = tids if wide_ids else jnp.maximum(tids, 0).astype(jnp.uint16)
    return df, jnp.concatenate([as_bytes(vals_wire), as_bytes(tid_wire),
                                occ])


def _decode_wire_exact(buf: np.ndarray, d_padded: int, k: int,
                       wide_ids: bool):
    """Decode the exact-ids wire: (ids, counts) int32 [D, K] plus the
    full [V] DF vector from the tail. Invalid slots have count 0 (ids
    there are don't-care)."""
    id_t = "<i4" if wide_ids else "<u2"
    id_bytes = d_padded * k * (4 if wide_ids else 2)
    cnt_bytes = d_padded * k * 2
    tids = buf[:id_bytes].view(id_t).reshape(d_padded, k).astype(np.int32)
    cnt = buf[id_bytes:id_bytes + cnt_bytes].view("<u2") \
        .reshape(d_padded, k).astype(np.int32)
    df_vec = buf[id_bytes + cnt_bytes:].view("<i4")
    return tids, cnt, df_vec


def _decode_wire(buf: np.ndarray, d_padded: int, k: int, wide_ids: bool,
                 score_dtype=np.float32, include_vals: bool = True):
    """Host decode of ``_score_pack_wire``'s buffer (XLA bitcast puts
    the least-significant byte at minor index 0 = little-endian).
    Invalid slots (sub-k docs / padding rows) carry vals == -1 on the
    wire; they decode back to the (0, -1) contract. Ids-only wires
    (``include_vals=False``) return vals None and leave invalid slots
    at bucket 0 (see ``_score_pack_wire``'s harmlessness note).

    Returns ``(vals, tids, occupied)`` — the occupied-DF-bucket count
    from the wire's 4-byte tail."""
    occupied = int(buf[-4:].view("<i4")[0])
    buf = buf[:-4]
    id_t = "<i4" if wide_ids else "<u2"
    if not include_vals:
        tids = buf.view(id_t).reshape(d_padded, k).astype(np.int32)
        return None, tids, occupied
    sdt = np.dtype(score_dtype).newbyteorder("<")
    s_bytes = d_padded * k * sdt.itemsize
    vals = buf[:s_bytes].view(sdt).reshape(d_padded, k).copy()
    if wide_ids:
        tids = buf[s_bytes:].view(id_t).reshape(d_padded, k).copy()
    else:
        tids = buf[s_bytes:].view(id_t).reshape(d_padded, k) \
            .astype(np.int32)
    bad = vals < 0
    vals[bad] = 0
    tids[bad] = -1
    return vals, tids, occupied


@jax.jit
def _concat_rows(parts):
    """Device-side concat of uploaded chunks along the doc axis."""
    return jnp.concatenate(parts, axis=0)


# Largest packed corpus (doc slots x token length) the fused resident
# path will hold on device; beyond it the two-pass streaming pipeline
# takes over. 268M tokens measured working on one v5e chip (1M x 256
# docs: 31.8 s warm, the [1M, 256] sort + workspace fit 16 GB HBM with
# room; docs/SCALING.md). Override down for smaller parts.
_RESIDENT_ELEMS = 1 << 28


@functools.partial(jax.jit, donate_argnums=(0,),
                   static_argnames=("topk",))
def _phase_b(token_ids, lengths, idf, *, topk: int):
    """Score one chunk against the final corpus-wide IDF -> top-k.
    Wire buffer donated (fresh per chunk at every call site)."""
    ids, counts, head = sorted_term_counts(token_ids, lengths)
    scores = sparse_scores(ids, counts, head, lengths, idf)
    return sparse_topk(scores, ids, head, topk)


@functools.partial(jax.jit, static_argnames=("score_dtype",))
def _final_idf(df_total, num_docs, *, score_dtype):
    return idf_from_df(df_total, num_docs, score_dtype)


@dataclasses.dataclass
class IngestResult:
    """Corpus-wide outputs of an overlapped ingest run.

    ``topk_vals`` are full ``config.score_dtype`` precision on both the
    resident and streaming paths (the round-2 bf16 wire compaction is
    gone — the link is latency-bound, not bandwidth-bound, so it bought
    nothing and cost tie precision). Exception: a ``wire_vals=False``
    run (the exact-terms fetch diet) returns ``topk_vals=None`` and its
    ``topk_ids`` invalid slots read bucket 0 instead of -1 — only the
    exact re-rank, which is insensitive to both (``_score_pack_wire``),
    should consume such results.
    """

    df: np.ndarray            # [V] corpus DF — a host ndarray on every
                              # path except the pair-wire resident run,
                              # which keeps its pre-round-7 device-
                              # resident jax.Array (np.asarray fetches)
    topk_vals: Optional[np.ndarray]  # [D, K] top-k TF-IDF scores
                                     # (None when wire_vals=False)
    topk_ids: np.ndarray      # [D, K] matching vocab ids (-1 = no term;
                              # bucket 0 stands in when wire_vals=False)
    lengths: np.ndarray       # [D] docSize per document
    names: List[str]
    num_docs: int
    # Occupied-DF-bucket count, decoded from the wire tail (or counted
    # host-side on the streaming path). Feeds rerank.margin_check
    # without ever fetching the [V] DF vector from device.
    df_occupied: Optional[int] = None
    path: str = ""            # regime: "resident" | "streaming" |
                              # "resident-mesh" | "streaming-mesh"
    # Wall-clock phase breakdown of the run (seconds). Overlapped phases
    # don't sum to the wall. Resident path: "pack" (stall waiting on
    # the double-buffered packer thread — the only synchronous pack
    # cost), "pack_host" (the packer thread's own wall, overlapped),
    # "put" (upload/dispatch staging), "fetch" (the single unfenced
    # result round trip — transfer/compute drain included).
    # Streaming path: pack_a/pack_b (stalls), pack_host, pass_a/pass_b,
    # fetch. Bytes-wire runs add load_host/slab_host — the packer
    # thread's file-read and slab-assembly walls (there is no host
    # tokenize at all). Values are numeric only (cli --timing feeds
    # them to PhaseTimer.add verbatim).
    phases: Optional[Dict[str, float]] = None
    # Chunk wire format this run resolved to ("ragged" | "padded" —
    # use_ragged_wire; mesh paths are always "padded" by design) and
    # the actual host->device payload: bytes_on_wire counts every
    # shipped wire buffer (flat stream or padded batch, plus lengths);
    # bytes_on_wire_padded is what the SAME run would have shipped on
    # the padded wire — the denominator of the bench's wire-ratio
    # artifact field.
    wire: str = ""
    bytes_on_wire: Optional[int] = None
    bytes_on_wire_padded: Optional[int] = None
    # Device→host result wire this run resolved to ("packed" | "pair" —
    # ops.downlink.use_packed_result_wire) and the actual result
    # payload drained off the device: bytes_off_wire counts every
    # shipped top-k result buffer (uint32 words, or the pair wire's
    # packed byte buffer / raw (vals, ids) fetch); bytes_off_wire_pair
    # is what the SAME selection costs as (int32 id, score_dtype
    # score) pairs — the denominator of the bench's result_wire_ratio.
    # On the packed wire, IngestResult.df is ALWAYS a host ndarray
    # (the [V] vector rides an async copy overlapped with phase-B
    # scoring); the pair-wire resident path keeps its device-resident
    # lazy df, bit-identical to pre-packed-wire behavior.
    result_wire: str = ""
    bytes_off_wire: Optional[int] = None
    bytes_off_wire_pair: Optional[int] = None
    # Phase-B finish structure this run resolved to ("scan" = the
    # single lax.scan dispatch actually ran; "chunked" = per-chunk
    # dispatches; "fused" = the pair wire's single _finish_wire
    # program; "" on paths the knob does not reach, e.g. mesh) and the
    # number of phase-B scoring dispatches the finish issued — the
    # bench artifact's dispatch.n_phase_b_dispatches field.
    finish: str = ""
    n_finish_dispatches: Optional[int] = None


def make_chunk_packer(input_dir: str, cfg: PipelineConfig, chunk_docs: int,
                      length: int):
    """The host packing path of one chunk: names -> (token_ids, lengths).

    Native parallel loader when built (document bytes never enter
    Python), else the Python pack path — the exact code
    :func:`run_overlapped` runs, exposed so benchmarks/diagnostics time
    the same workload instead of re-implementing it.
    """
    use_native = (cfg.tokenizer is TokenizerKind.WHITESPACE
                  and fast_tokenizer.loader_available())

    def pack_chunk_native(chunk_names: List[str]
                          ) -> Tuple[np.ndarray, np.ndarray]:
        packed = fast_tokenizer.load_pack_paths(
            [os.path.join(input_dir, n) for n in chunk_names],
            cfg.vocab_size, cfg.hash_seed, cfg.truncate_tokens_at,
            min_len=length, chunk=length, fixed_len=length,
            pad_docs_to=chunk_docs,
            n_threads=getattr(cfg, "pack_threads", None))
        assert packed is not None  # loader_available() checked above
        return packed

    def pack_chunk_python(chunk_names: List[str]
                          ) -> Tuple[np.ndarray, np.ndarray]:
        from tfidf_tpu.io.corpus import Corpus
        docs = []
        for n in chunk_names:
            with open(os.path.join(input_dir, n), "rb") as f:
                docs.append(f.read())
        batch = pack_corpus(Corpus(names=list(chunk_names), docs=docs),
                            cfg, pad_docs_to=chunk_docs, want_words=False)
        ids = batch.token_ids[:, :length]
        if batch.token_ids.shape[1] < length:
            pad = np.zeros((ids.shape[0], length - ids.shape[1]), ids.dtype)
            ids = np.concatenate([ids, pad], axis=1)
        return ids, np.minimum(batch.lengths, length).astype(np.int32)

    return pack_chunk_native if use_native else pack_chunk_python


def run_overlapped(input_dir: str, config: Optional[PipelineConfig] = None,
                   chunk_docs: int = 8192, doc_len: Optional[int] = None,
                   strict: bool = True, spill: str = "auto",
                   wire_vals: bool = True,
                   plan: Optional["MeshPlan"] = None,
                   shard: Optional[Tuple[int, int]] = None,
                   df_merge=None,
                   total_docs: Optional[int] = None) -> IngestResult:
    """Stream a directory through the overlapped two-pass pipeline.

    ``doc_len`` fixes the static token length L for every chunk (defaults
    to ``config.max_doc_len``); documents longer than L are truncated to
    L tokens — the fixed-shape tradeoff for never recompiling. Use
    ``TfidfPipeline`` (single batch, L grows to the longest doc) when
    truncation is unacceptable, or ``parallel.longdoc`` for documents
    beyond any single chip.

    ``spill`` controls where packed chunks live between pass A and B:
    ``"host"`` (RAM), ``"reread"`` (re-pack from disk), or ``"auto"``
    (RAM up to a budget). Device memory is flat in corpus size either
    way; see the module docstring.

    ``wire_vals=False`` drops scores from the result wire on the
    resident path: ``topk_vals`` comes back None and invalid id slots
    read as bucket 0 — the exact-terms mode's fetch diet (the re-rank
    reads only candidate buckets; see ``_score_pack_wire``). Advisory:
    the streaming regime ignores it and returns full scores (a strict
    superset of the contract); the mesh path honors it but keeps -1
    in invalid id slots (no bucket-0 stand-in).

    ``plan`` (a ``parallel.mesh.MeshPlan``, docs axis only) runs the
    ingest docs-sharded over the device mesh — each shard sorts its
    own rows, DF partials fold shard-locally, and a single ``lax.psum``
    is the run's only collective. Within the shard-scaled resident
    budget the fused resident path runs (``_run_overlapped_mesh``);
    beyond it the two-pass streaming regime takes over with the same
    triple cache (``_run_overlapped_mesh_streaming``).

    ``shard``/``df_merge``/``total_docs`` are the multi-process ingest
    hooks (``parallel.multihost.run_sharded_ingest``): ``shard=(lo,
    hi)`` ingests only that contiguous slice of the global discovery
    order, ``df_merge`` (a callable ``[V] int32 host DF -> merged
    DF``, typically ``MpiLiteComm.allreduce_sum``) replaces the local
    DF with the cross-worker sum at the one DF->IDF boundary, and
    ``total_docs`` is the GLOBAL document count the IDF must use.
    Per-document rows depend only on the document's own tokens plus
    the (merged) DF/IDF, so a shard's rows are bit-identical to the
    same rows of a single-process run. The merge forces the gather DF
    join on the pair-wire finish — the sort-join derives per-slot DF
    from the local triples, which a merged run must not (the same rule
    the mesh path follows).

    Requires HASHED vocab (fixed id space across chunks) and a top-k
    selection (full per-term output would defeat the streaming design).
    Works with or without the native loader; the native path keeps
    document bytes out of Python entirely.
    """
    cfg = config or PipelineConfig(vocab_mode=VocabMode.HASHED, topk=16)
    if cfg.vocab_mode is not VocabMode.HASHED:
        raise ValueError("overlapped ingest requires VocabMode.HASHED")
    if cfg.topk is None:
        raise ValueError("overlapped ingest requires a topk selection")
    # Persistent XLA compile cache (round 8): repeat CLI runs at the
    # same (bucketed) wire shapes load executables from disk instead of
    # re-paying every cold-start compile. No-op when unconfigured.
    apply_compile_cache(getattr(cfg, "compile_cache", None))
    # Arm the span tracer the same way (config.trace / TFIDF_TPU_TRACE;
    # no-op when unconfigured). Export stays with the caller.
    obs.configure(getattr(cfg, "trace", None))
    if spill not in ("auto", "host", "reread"):
        raise ValueError(f"unknown spill policy {spill!r}")
    length = doc_len or cfg.max_doc_len
    if plan is not None and (shard is not None or df_merge is not None
                             or total_docs is not None):
        raise ValueError("shard/df_merge/total_docs are the "
                         "multi-PROCESS ingest hooks; a mesh plan "
                         "shards across devices of one process — "
                         "compose by giving each worker its own plan")
    if plan is not None:
        # Multi-chip composition: route to the docs-sharded resident
        # path. Per-shard HBM holds corpus/S, so the resident budget
        # scales with the docs-shard count.
        resident = int(os.environ.get("TFIDF_TPU_RESIDENT_ELEMS",
                                      _RESIDENT_ELEMS))
        mesh_names = discover_names(input_dir, strict)
        if not mesh_names:
            raise ValueError(f"no documents in {input_dir}")
        if len(mesh_names) * length > resident * plan.n_docs_shards:
            # Beyond the (shard-scaled) resident budget: the two-pass
            # streaming regime, docs-sharded. wire_vals is advisory
            # here like the single-device streaming path.
            return _run_overlapped_mesh_streaming(
                input_dir, cfg, plan, chunk_docs, length, mesh_names,
                spill)
        return _run_overlapped_mesh(input_dir, cfg, plan, chunk_docs,
                                    length, mesh_names, wire_vals)
    names = discover_names(input_dir, strict)
    if shard is not None:
        lo, hi = shard
        if not (0 <= lo <= hi <= len(names)):
            raise ValueError(f"shard {shard} outside corpus "
                             f"[0, {len(names)}]")
        names = names[lo:hi]
    num_docs = len(names)
    if num_docs == 0:
        raise ValueError(f"no documents in {input_dir}"
                         + (f" shard {shard}" if shard else ""))
    # The IDF's num_docs: global under a sharded multi-process run
    # (every worker scores against the same corpus-wide weights),
    # local otherwise. Chunking/guards stay local either way.
    num_docs_idf = total_docs if total_docs is not None else num_docs

    use_native = (cfg.tokenizer is TokenizerKind.WHITESPACE
                  and fast_tokenizer.loader_available())
    # Canonicalized: without jax_enable_x64 a float64 request computes
    # (and ships) float32 — decode must agree with what XLA emits.
    score_dtype = jax.dtypes.canonicalize_dtype(jnp.dtype(cfg.score_dtype))
    k = min(cfg.topk, length)
    # Wire bytes per token id: the native loader packs uint16 when the
    # vocab fits (fast_tokenizer), else int32. Drives both the spill
    # estimate and the in-flight upload budget.
    itemsize = 2 if (use_native and cfg.vocab_size <= (1 << 16)) else 4
    if spill == "auto":
        est = num_docs * length * itemsize
        budget = int(os.environ.get("TFIDF_TPU_SPILL_BYTES",
                                    _DEFAULT_SPILL_BYTES))
        spill = "host" if est <= budget else "reread"

    _check_chunk_fits_int32(chunk_docs, length)
    pack_chunk = make_chunk_packer(input_dir, cfg, chunk_docs, length)
    starts = list(range(0, num_docs, chunk_docs))

    resident = int(os.environ.get("TFIDF_TPU_RESIDENT_ELEMS",
                                  _RESIDENT_ELEMS))
    if num_docs * length <= resident:
        # Resident fused path: the host packs chunk i+1 while chunk i's
        # upload is still in flight (device_put is async — on the
        # tunneled backend the link runs ~60 MB/s, so hiding uploads
        # behind packing matters more than anything else). The device
        # concats the chunks, runs ONE fused program (a single sort,
        # where the two-pass pipeline sorts every chunk twice), and the
        # host pays a single synchronizing fetch. Only the final chunk
        # carries padding rows, so real documents are rows [0, num_docs).
        new_chunk, starts = _resident_chunking(num_docs, chunk_docs)
        if new_chunk != chunk_docs:
            chunk_docs = new_chunk
            pack_chunk = make_chunk_packer(input_dir, cfg, chunk_docs,
                                           length)
        _check_chunk_fits_int32(chunk_docs, length)
        _check_total_slots_fit_int32(len(starts) * chunk_docs, length)
        bwire = use_bytes_wire(cfg, chunk_docs, length)
        ragged = (not bwire) and use_ragged_wire(cfg, chunk_docs, length)
        pack_stats: Dict[str, float] = {}
        if bwire:
            chunk_pack = make_bytes_packer(input_dir, cfg, chunk_docs,
                                           length, stats=pack_stats)
            tok_method = tokenize_method()
        elif ragged:
            chunk_pack = make_flat_packer(input_dir, cfg, chunk_docs,
                                          length)
        else:
            chunk_pack = pack_chunk

        ph = {"pack": 0.0, "put": 0.0}
        padded_chunk_bytes = chunk_docs * length * itemsize
        bytes_wire = bytes_padded = 0
        df_acc = jnp.zeros((cfg.vocab_size,), jnp.int32)
        trip_i, trip_c, trip_h, len_parts, all_lengths = [], [], [], [], []
        # Double-buffered upload pipeline: the packer thread runs one
        # chunk ahead, so chunk i+1's tokenize+hash — or, on the bytes
        # wire, its read+slab copy — overlaps chunk i's device_put
        # staging and dispatch (which themselves overlap the device's
        # transfer+sort of earlier chunks — see _PackAhead).
        with _PackAhead(chunk_pack,
                        [names[s:s + chunk_docs] for s in starts]) \
                as packer:
            for ci in range(len(starts)):
                n_chunk = len(names[starts[ci]:starts[ci] + chunk_docs])
                t0 = time.perf_counter()
                with obs.span("pack_wait", chunk=ci):
                    packed = packer.get(ci)  # stall; pack rides ahead
                ph["pack"] += time.perf_counter() - t0
                wire_arr, lengths = packed[0], packed[1]
                if not bwire:
                    all_lengths.append(lengths[:n_chunk])
                bytes_wire += wire_arr.nbytes + lengths.nbytes
                bytes_padded += padded_chunk_bytes + lengths.nbytes
                t0 = time.perf_counter()
                # bytes stamp (round 12): the trace export turns it
                # into achieved GB/s on the span (obs/costmodel.py).
                with obs.span("dispatch", chunk=ci,
                              bytes=int(wire_arr.nbytes
                                        + lengths.nbytes)):
                    lens = jax.device_put(lengths)
                    # Sort + DF-fold this chunk NOW (async dispatch):
                    # the transfer+sort runs behind the host's packing
                    # of the next chunk, and the wire buffer is dead
                    # once consumed.
                    _trace("upload", ci)
                    if bwire:
                        # lengths here are BYTE lengths; the kernel
                        # tokenizes on device and returns the token
                        # lengths the host packers would have computed
                        # — fetched asynchronously for the result's
                        # bookkeeping, device-resident for the finish.
                        with obs.span("device_tokenize", chunk=ci,
                                      bytes=int(wire_arr.nbytes)):
                            i_, c_, h_, df_acc, lens = _chunk_bytes(
                                jax.device_put(wire_arr), lens, df_acc,
                                length=length,
                                vocab_size=cfg.vocab_size,
                                seed=cfg.hash_seed,
                                truncate_at=cfg.truncate_tokens_at,
                                align=_wire_align(),
                                fold_df=not _resident_df_mode()[1],
                                method=tok_method)
                        lens.copy_to_host_async()
                    else:
                        i_, c_, h_, df_acc = _chunk_step(
                            jax.device_put(wire_arr), lens, df_acc, cfg,
                            length, ragged=ragged,
                            fold_df=not _resident_df_mode()[1])
                    _trace("dispatch", ci)
                trip_i.append(i_)
                trip_c.append(c_)
                trip_h.append(h_)
                len_parts.append(lens)
                ph["put"] += time.perf_counter() - t0
        ph["pack_host"] = packer.host_seconds
        if bwire:
            # Token lengths are device truth on the bytes wire; their
            # async copies were started at dispatch, so these reads
            # find them landed.
            all_lengths = [
                np.asarray(lp)[:len(names[s:s + chunk_docs])]
                for lp, s in zip(len_parts, starts)]
            for key, secs in pack_stats.items():
                ph[f"{key}_host"] = secs
        d_padded = len(starts) * chunk_docs
        common = dict(lengths=np.concatenate(all_lengths), names=names,
                      num_docs=num_docs, path="resident",
                      wire="bytes" if bwire
                      else ("ragged" if ragged else "padded"),
                      bytes_on_wire=bytes_wire,
                      bytes_on_wire_padded=bytes_padded,
                      bytes_off_wire_pair=(d_padded * k
                                           * pair_slot_bytes(score_dtype)))
        if wire_vals and use_packed_result_wire(cfg):
            # Packed-wire finish. --finish=scan (round 8, the default):
            # ONE donated lax.scan dispatch scores every resident chunk
            # and emits the whole [n_chunks, D, K] word buffer, fetched
            # by a single copy_to_host_async the drain worker unpacks
            # chunk-major — the per-chunk launch/re-entry tax (measured
            # ~⅔ of warm phase-B device time, docs/SCALING.md round 8)
            # collapses to one program. --finish=chunked keeps the
            # round-7 per-chunk dispatches, whose drains interleave
            # with later chunks' scoring (_DrainAhead).
            scan_finish = use_scan_finish(cfg, True)
            t0 = time.perf_counter()
            df_dev = (_df_from_trips(tuple(trip_i), tuple(trip_h),
                                     vocab_size=cfg.vocab_size)
                      if _resident_df_mode()[1] else df_acc)
            if df_merge is not None:
                # THE cross-worker rendezvous: one [V] allreduce — the
                # reference's MPI_Reduce+Bcast of the DF table
                # (TFIDF.c:215,220). A host round trip by design: the
                # workers' links are the thing being divided, and the
                # [V] vector is 256 KB against the corpus's GBs.
                with obs.span("link_sync", bytes=int(df_dev.nbytes)):
                    df_dev = jnp.asarray(df_merge(np.asarray(df_dev)))
            idf = _final_idf(df_dev, jnp.int32(num_docs_idf),
                             score_dtype=score_dtype)
            # The [V] DF rides its own async copy behind the scoring
            # queue — the host read at the end finds it landed, where a
            # synchronous fetch would charge a full link round trip.
            df_dev.copy_to_host_async()
            bytes_off = 0
            with _DrainAhead(functools.partial(
                    _unpack_words_rows, score_dtype=score_dtype)) \
                    as drain:
                if scan_finish:
                    with obs.device_span("phase_b", finish="scan",
                                         chunks=len(starts)):
                        words = _phase_b_scan_packed(
                            tuple(trip_i), tuple(trip_c), tuple(trip_h),
                            tuple(len_parts), idf, topk=k)
                    bytes_off += words.nbytes
                    drain.put(0, words)
                else:
                    for ci in range(len(starts)):
                        with obs.device_span("phase_b", chunk=ci):
                            words = _phase_b_cached_packed(
                                trip_i[ci], trip_c[ci], trip_h[ci],
                                len_parts[ci], idf, topk=k)
                        bytes_off += words.nbytes
                        drain.put(ci, words)
                ph["score_b"] = time.perf_counter() - t0
                t0 = time.perf_counter()
                _trace("fetch_start")
                with obs.span("fetch_wait"):
                    parts = drain.results()  # chunk-major by constr.
                _trace("fetch_done")
            df_host = np.asarray(df_dev)
            ph["fetch"] = time.perf_counter() - t0  # stall only
            ph["fetch_host"] = drain.host_seconds
            vals = np.concatenate([p[0] for p in parts])
            tids = np.concatenate([p[1] for p in parts])
            return IngestResult(df=df_host, topk_vals=vals[:num_docs],
                                topk_ids=tids[:num_docs],
                                df_occupied=int((df_host > 0).sum()),
                                phases=ph, result_wire="packed",
                                bytes_off_wire=bytes_off,
                                finish="scan" if scan_finish
                                else "chunked",
                                n_finish_dispatches=(1 if scan_finish
                                                     else len(starts)),
                                **common)
        t0 = time.perf_counter()
        wide = cfg.vocab_size > (1 << 16)
        if df_merge is not None:
            # Merged DF cannot take the sort-join finish (its per-slot
            # DF comes from the LOCAL triples — the mesh rule): fold
            # the local DF, allreduce it, and score through the gather
            # join against the merged table.
            df_local = (_df_from_trips(tuple(trip_i), tuple(trip_h),
                                       vocab_size=cfg.vocab_size)
                        if _resident_df_mode()[1] else df_acc)
            with obs.span("link_sync", bytes=int(df_local.nbytes)):
                df_acc = jnp.asarray(df_merge(np.asarray(df_local)))
            with obs.device_span("phase_b", finish="fused"):
                df_dev, wire = _score_pack_wire(
                    tuple(trip_i), tuple(trip_c), tuple(trip_h),
                    tuple(len_parts), df_acc, jnp.int32(num_docs_idf),
                    topk=k, score_dtype=score_dtype, wide_ids=wide,
                    include_vals=wire_vals, join="gather",
                    derive_df=False)
        else:
            with obs.device_span("phase_b", finish="fused"):
                df_dev, wire = _finish_wire((trip_i, trip_c, trip_h),
                                            len_parts, df_acc,
                                            num_docs_idf, k,
                                            score_dtype, cfg, wire_vals)
        # ONE unfenced fetch = one link round trip: drain + transfer.
        # DF stays on device (jax.Array acts array-like; np.asarray
        # fetches it on first real read — no hot-path consumer does).
        _trace("fetch_start")
        with obs.span("fetch", bytes=int(wire.nbytes)):
            buf = np.asarray(jax.device_get(wire))
        _trace("fetch_done")
        ph["fetch"] = time.perf_counter() - t0
        vals, tids, occ = _decode_wire(buf, d_padded, k, wide, score_dtype,
                                       include_vals=wire_vals)
        return IngestResult(df=df_dev,
                            topk_vals=(vals[:num_docs]
                                       if vals is not None else None),
                            topk_ids=tids[:num_docs],
                            df_occupied=occ,
                            phases=ph, result_wire="pair",
                            bytes_off_wire=buf.nbytes,
                            finish="fused", n_finish_dispatches=1,
                            **common)

    # Pass A: fold every chunk's partial DF into one device accumulator.
    # The loop packs chunk i+1 while the device still runs chunk i
    # (async dispatch), but never runs more than max_ahead chunks
    # ahead — blocking on the oldest in-flight result bounds HBM
    # residency even when host packing outpaces the device. The bound is
    # byte-budgeted (TFIDF_TPU_INFLIGHT_BYTES, default 512 MB): each
    # sync costs a full link round trip on the tunneled backend, so it
    # should trigger rarely, not per chunk.
    chunk_bytes = max(chunk_docs * length * itemsize, 1)
    max_ahead = max(_LOOKAHEAD,
                    int(os.environ.get("TFIDF_TPU_INFLIGHT_BYTES", 1 << 29))
                    // chunk_bytes)
    # Ragged flat wire by default (config.wire) — same ~25% byte saving
    # as the resident path, and spill="host" then caches the FLAT
    # arrays, so pass B never re-packs at all (round-2 streaming paid a
    # full second pack+pad per chunk even from RAM). use_ragged_wire
    # degrades to padded for wide vocabs / over-bucket chunks; the
    # bytes wire (round 14) ships raw slabs and tokenizes on device —
    # spill="host" then caches the SLABS, so pass B re-reads nothing
    # and re-tokenizes on device only for cache-missed chunks.
    bwire = use_bytes_wire(cfg, chunk_docs, length)
    ragged = (not bwire) and use_ragged_wire(cfg, chunk_docs, length)
    pack_stats: Dict[str, float] = {}
    bytes_pack = (make_bytes_packer(input_dir, cfg, chunk_docs, length,
                                    stats=pack_stats) if bwire else None)
    tok_method = tokenize_method() if bwire else "xla"
    flat_pack = (make_flat_packer(input_dir, cfg, chunk_docs, length)
                 if ragged else None)
    align = _wire_align()
    rebuild = rebuild_method()
    # Result-wire format, resolved once per run like the upload wire
    # (streaming treats wire_vals as advisory and always ships scores).
    packed_wire = use_packed_result_wire(cfg)
    ph = {"pack_a": 0.0, "pack_b": 0.0}
    padded_chunk_bytes = chunk_docs * length * itemsize
    bytes_wire = bytes_padded = 0
    df_acc = jnp.zeros((cfg.vocab_size,), jnp.int32)
    cached: List[Optional[Tuple[np.ndarray, np.ndarray]]] = []
    all_lengths: List[np.ndarray] = []
    in_flight: List[jax.Array] = []
    # Device triple cache (VERDICT r3 item 5): chunk idx -> sorted
    # triples + device lengths, bounded by TFIDF_TPU_TRIPLE_CACHE_BYTES.
    cache_budget = int(os.environ.get("TFIDF_TPU_TRIPLE_CACHE_BYTES",
                                      _TRIPLE_CACHE_BYTES))
    trip_cache: Dict[int, Tuple[jax.Array, jax.Array, jax.Array,
                                jax.Array]] = {}
    cache_bytes = 0
    chunk_cache_bytes = chunk_docs * length * 9 + chunk_docs * 4

    def pack_any(chunk_names):
        if bytes_pack is not None:
            slab, blens, _ = bytes_pack(chunk_names)
            return slab, blens
        if flat_pack is not None:
            flat, lengths, _ = flat_pack(chunk_names)
            return flat, lengths
        return pack_chunk(chunk_names)

    def phase_a_any(wire_arr, lens, df_acc):
        # bytes wire: handled at the call site (_phase_a_bytes also
        # returns the device-derived token lengths).
        if flat_pack is not None:
            return _phase_a_ragged(wire_arr, lens, df_acc, length=length,
                                   vocab_size=cfg.vocab_size,
                                   align=align, rebuild=rebuild)
        return _phase_a(wire_arr, lens, df_acc, vocab_size=cfg.vocab_size)

    def phase_b_any(wire_arr, lens, idf):
        if bwire:
            return _phase_b_bytes(wire_arr, lens, idf, length=length,
                                  vocab_size=cfg.vocab_size,
                                  seed=cfg.hash_seed,
                                  truncate_at=cfg.truncate_tokens_at,
                                  align=align, topk=k,
                                  method=tok_method, packed=packed_wire)
        if flat_pack is not None:
            fn = _phase_b_ragged_packed if packed_wire else _phase_b_ragged
            return fn(wire_arr, lens, idf, length=length,
                      topk=k, align=align, rebuild=rebuild)
        fn = _phase_b_padded_packed if packed_wire else _phase_b
        return fn(wire_arr, lens, idf, topk=k)

    t_pass = time.perf_counter()
    # Pass A rides the same double-buffered packer thread as the
    # resident path: chunk i+1 packs while chunk i stages/dispatches.
    with _PackAhead(pack_any,
                    [names[s:s + chunk_docs] for s in starts]) as packer:
        for ci, start in enumerate(starts):
            chunk_names = names[start:start + chunk_docs]
            t0 = time.perf_counter()
            with obs.span("pack_wait", chunk=ci):
                wire_arr, lengths = packer.get(ci)
            ph["pack_a"] += time.perf_counter() - t0  # stall only
            if not bwire:
                all_lengths.append(lengths[:len(chunk_names)])
            bytes_wire += wire_arr.nbytes + lengths.nbytes
            bytes_padded += padded_chunk_bytes + lengths.nbytes
            _trace("upload", ci)
            with obs.span("dispatch", chunk=ci,
                          bytes=int(wire_arr.nbytes + lengths.nbytes)):
                if cache_bytes + chunk_cache_bytes <= cache_budget:
                    # Sort once, keep the triples: pass B scores these
                    # directly (_phase_b_cached) — no host cache, no
                    # re-pack, no re-sort for this chunk.
                    lens_dev = jax.device_put(lengths)
                    if bwire:
                        with obs.span("device_tokenize", chunk=ci,
                                      bytes=int(wire_arr.nbytes)):
                            i_, c_, h_, df_acc, lens_dev = _chunk_bytes(
                                jax.device_put(wire_arr), lens_dev,
                                df_acc, length=length,
                                vocab_size=cfg.vocab_size,
                                seed=cfg.hash_seed,
                                truncate_at=cfg.truncate_tokens_at,
                                align=align, method=tok_method)
                        lens_dev.copy_to_host_async()
                        all_lengths.append(lens_dev)
                    else:
                        i_, c_, h_, df_acc = _chunk_step(
                            jax.device_put(wire_arr), lens_dev, df_acc,
                            cfg, length, ragged=ragged)
                    trip_cache[ci] = (i_, c_, h_, lens_dev)
                    cache_bytes += chunk_cache_bytes
                    if spill == "host":
                        cached.append(None)  # pass B skips the host copy
                else:
                    if spill == "host":
                        cached.append((wire_arr, lengths))
                    if bwire:
                        with obs.span("device_tokenize", chunk=ci,
                                      bytes=int(wire_arr.nbytes)):
                            df_acc, lens_dev = _phase_a_bytes(
                                jax.device_put(wire_arr),
                                jax.device_put(lengths), df_acc,
                                length=length,
                                vocab_size=cfg.vocab_size,
                                seed=cfg.hash_seed,
                                truncate_at=cfg.truncate_tokens_at,
                                align=align, method=tok_method)
                        lens_dev.copy_to_host_async()
                        all_lengths.append(lens_dev)
                    else:
                        df_acc = phase_a_any(jax.device_put(wire_arr),
                                             jax.device_put(lengths),
                                             df_acc)
            _trace("dispatch", ci)
            in_flight.append(df_acc)
            if len(in_flight) > max_ahead:
                in_flight.pop(0).block_until_ready()
    ph["pack_host"] = packer.host_seconds
    df_acc.block_until_ready()
    ph["pass_a"] = time.perf_counter() - t_pass
    ph["triple_cached_chunks"] = float(len(trip_cache))

    if df_merge is not None:
        # Pass-A/B boundary: the one place the streaming regime's DF
        # is complete and its IDF not yet consumed — the cross-worker
        # allreduce slots in exactly here (see the resident twin).
        with obs.span("link_sync", bytes=int(df_acc.nbytes)):
            df_acc = jnp.asarray(df_merge(np.asarray(df_acc)))
    idf = _final_idf(df_acc, jnp.int32(num_docs_idf),
                     score_dtype=score_dtype)

    # Pass B: rescore each chunk against the corpus-wide IDF. Same
    # overlap structure. On the packed result wire (the default,
    # ops/downlink) each chunk's [chunk, K] selection leaves its
    # scoring program as one uint32 word buffer whose async drain
    # overlaps the NEXT chunk's scoring (_DrainAhead) — the two-pass
    # regime's whole result fetch pipelines away; the pair wire keeps
    # the legacy single device_get of the accumulated device parts.
    # spill="reread" chunks ride their own pack-ahead pipeline (only
    # the chunks the triple cache missed ever re-pack).
    if packed_wire:
        # The final [V] DF read is a plain host copy by then: start
        # its transfer now, behind pass B's scoring.
        df_acc.copy_to_host_async()
    # --finish=scan (round 8): the triple-cached chunks — a chunk-major
    # PREFIX by construction (the cache byte budget only ever ratchets
    # shut) — score in ONE donated scan dispatch instead of one
    # dispatch each; chunks past the cache keep their per-chunk
    # re-upload programs (their wire buffers arrive incrementally, so
    # a single program cannot see them all).
    scan_finish = use_scan_finish(cfg, packed_wire)
    n_scanned = len(trip_cache) if scan_finish else 0
    n_dispatches = 0
    vals_parts, ids_parts = [], []
    bytes_off = 0
    t_pass = time.perf_counter()
    reread = ([ci for ci in range(len(starts)) if ci not in trip_cache]
              if spill == "reread" else [])
    packer_b = (_PackAhead(pack_any,
                           [names[starts[ci]:starts[ci] + chunk_docs]
                            for ci in reread]) if reread else None)
    drain = (_DrainAhead(functools.partial(_unpack_words_rows,
                                           score_dtype=score_dtype))
             if packed_wire else None)
    bpos = 0
    try:
        if n_scanned:
            cidx = sorted(trip_cache)
            assert cidx == list(range(n_scanned))  # prefix by constr.
            trips = [trip_cache.pop(ci) for ci in cidx]
            with obs.device_span("phase_b", finish="scan",
                                 chunks=n_scanned):
                words = _phase_b_scan_packed(
                    tuple(t[0] for t in trips),
                    tuple(t[1] for t in trips),
                    tuple(t[2] for t in trips),
                    tuple(t[3] for t in trips), idf, topk=k)
            bytes_off += words.nbytes
            n_dispatches += 1
            drain.put(n_scanned - 1, words)
        for ci, start in enumerate(starts):
            if ci < n_scanned:
                continue  # scored by the scanned prefix dispatch
            if ci in trip_cache:
                i_, c_, h_, lens_dev = trip_cache.pop(ci)
                with obs.device_span("phase_b", chunk=ci):
                    if packed_wire:
                        words = _phase_b_cached_packed(
                            i_, c_, h_, lens_dev, idf, topk=k)
                    else:
                        v, t = _phase_b_cached(i_, c_, h_, lens_dev,
                                               idf, topk=k)
            else:
                if spill == "host":
                    wire_arr, lengths = cached[ci]
                else:
                    t0 = time.perf_counter()
                    with obs.span("pack_wait", chunk=ci):
                        wire_arr, lengths = packer_b.get(bpos)
                    bpos += 1
                    ph["pack_b"] += time.perf_counter() - t0  # stall only
                bytes_wire += wire_arr.nbytes + lengths.nbytes
                bytes_padded += padded_chunk_bytes + lengths.nbytes
                with obs.device_span("phase_b", chunk=ci):
                    out = phase_b_any(jax.device_put(wire_arr),
                                      jax.device_put(lengths), idf)
                if packed_wire:
                    words = out
                else:
                    v, t = out
            n_dispatches += 1
            if packed_wire:
                bytes_off += words.nbytes
                drain.put(ci, words)  # depth guard bounds in-flight
                continue
            vals_parts.append(v)
            ids_parts.append(t)
            if ci >= max_ahead:  # same byte-budgeted lookahead as pass A
                vals_parts[ci - max_ahead].block_until_ready()
        if packed_wire:
            ph["pass_b"] = time.perf_counter() - t_pass
            t0 = time.perf_counter()
            _trace("fetch_start")
            with obs.span("fetch_wait"):
                parts = drain.results()  # chunk-major by construction
            _trace("fetch_done")
            df_host = np.asarray(df_acc)
            ph["fetch"] = time.perf_counter() - t0  # stall only
            ph["fetch_host"] = drain.host_seconds
    finally:
        if packer_b is not None:
            packer_b.close()
            ph["pack_host"] = (ph.get("pack_host", 0.0)
                               + packer_b.host_seconds)
        if drain is not None:
            drain.close()
    if packed_wire:
        vals = np.concatenate([p[0] for p in parts])
        tids = np.concatenate([p[1] for p in parts])
    else:
        jax.block_until_ready((vals_parts, ids_parts))
        ph["pass_b"] = time.perf_counter() - t_pass
        t0 = time.perf_counter()
        _trace("fetch_start")
        with obs.span("fetch",
                      bytes=int(df_acc.nbytes
                                + sum(v.nbytes for v in vals_parts)
                                + sum(t.nbytes for t in ids_parts))):
            df_host, vals, tids = jax.device_get(
                (df_acc, jnp.concatenate(vals_parts),
                 jnp.concatenate(ids_parts)))
        _trace("fetch_done")
        ph["fetch"] = time.perf_counter() - t0
        bytes_off = vals.nbytes + tids.nbytes
    if bwire:
        # Token lengths are device truth on the bytes wire (async
        # copies started at dispatch); trim each chunk to its live docs.
        all_lengths = [np.asarray(lp)[:len(names[s:s + chunk_docs])]
                       for lp, s in zip(all_lengths, starts)]
        for key, secs in pack_stats.items():
            ph[f"{key}_host"] = secs
    return IngestResult(df=df_host, topk_vals=vals[:num_docs],
                        topk_ids=tids[:num_docs],
                        lengths=np.concatenate(all_lengths), names=names,
                        num_docs=num_docs,
                        df_occupied=int((df_host > 0).sum()),
                        path="streaming", phases=ph,
                        wire="bytes" if bwire
                        else ("ragged" if ragged else "padded"),
                        bytes_on_wire=bytes_wire,
                        bytes_on_wire_padded=bytes_padded,
                        result_wire="packed" if packed_wire else "pair",
                        bytes_off_wire=bytes_off,
                        bytes_off_wire_pair=(len(starts) * chunk_docs * k
                                             * pair_slot_bytes(score_dtype)),
                        # "scan" only when the scanned prefix actually
                        # ran (an empty triple cache leaves nothing for
                        # one program to see — pure chunked flow).
                        finish="scan" if n_scanned else "chunked",
                        n_finish_dispatches=n_dispatches)


@dataclasses.dataclass
class ExactIngest:
    """Device-exact ingest outputs: collision-free intern word ids.

    Everything here is integer-exact — (count, df) per selected slot is
    sufficient for the host to reproduce the reference's float64 score
    (``rerank.exact_topk_from_wire``). Invalid slots have count 0.
    """

    names: List[str]
    lengths: np.ndarray       # [D] truncated docSize
    topk_ids: np.ndarray      # [D, K'] exact word ids
    topk_counts: np.ndarray   # [D, K'] in-doc term counts
    df: np.ndarray            # [V] exact corpus DF (from the wire tail)
    num_docs: int
    words: List[bytes]        # id -> word bytes (the intern dictionary)
    phases: Optional[Dict[str, float]] = None


def run_overlapped_exact(input_dir: str,
                         config: Optional[PipelineConfig] = None,
                         chunk_docs: int = 8192,
                         doc_len: Optional[int] = None,
                         strict: bool = True,
                         session=None) -> ExactIngest:
    """Exact-terms fast path: overlapped resident ingest on EXACT ids.

    The native intern table (``native/intern.cc``) assigns every
    distinct token a dense corpus-global id during the single pack
    pass, so there are no hash collisions anywhere: the device's
    integer counts/DF/top-k are word-exact, and the result wire ships
    (id, count, df) per selected slot — the host rescores in float64
    and NEVER re-reads the corpus (where the hashed mode's re-rank
    engine pays a full native re-pass, ``native/rerank.cc``). This is
    the reference's string-keyed-table semantics (``TFIDF.c:26-42``)
    with O(1) interning instead of its O(V_doc) linear probes.

    Raises :class:`~tfidf_tpu.io.fast_tokenizer.ExactVocabOverflow`
    when the corpus holds more distinct words than ``cfg.vocab_size``,
    RuntimeError when the native intern table is not built, and
    ValueError past the resident budget — callers fall back to the
    hashed+margin+rerank engine (``rerank.exact_terms``).
    """
    cfg = config or PipelineConfig(vocab_mode=VocabMode.HASHED, topk=16)
    if cfg.topk is None:
        raise ValueError("exact ingest requires a topk selection")
    if cfg.tokenizer is not TokenizerKind.WHITESPACE:
        raise ValueError("exact ingest serves the whitespace tokenizer")
    if cfg.vocab_size > (1 << 22):
        # [V] df/idf arrays and the intern table stay small through
        # 2^22 (16 MB df); beyond that the hashed engine is the design.
        raise ValueError("exact ingest caps the vocab at 2^22 ids")
    if not fast_tokenizer.intern_available():
        raise RuntimeError("native intern table unavailable "
                           "(make -C native fast_tokenizer.so)")
    length = doc_len or cfg.max_doc_len
    names = discover_names(input_dir, strict)
    num_docs = len(names)
    if num_docs == 0:
        raise ValueError(f"no documents in {input_dir}")
    resident = int(os.environ.get("TFIDF_TPU_RESIDENT_ELEMS",
                                  _RESIDENT_ELEMS))
    if num_docs * length > resident:
        raise ValueError("exact ingest is resident-only; corpus exceeds "
                         "TFIDF_TPU_RESIDENT_ELEMS")
    score_dtype = jax.dtypes.canonicalize_dtype(jnp.dtype(cfg.score_dtype))
    k = min(cfg.topk, length)
    chunk_docs, starts = _resident_chunking(num_docs, chunk_docs)
    _check_chunk_fits_int32(chunk_docs, length)
    _check_total_slots_fit_int32(len(starts) * chunk_docs, length)
    # The exact-id wire is inherently ragged (the intern packer only
    # emits the flat stream); config.wire governs the hashed ingest.
    align = _wire_align()
    cap = _bucket_cap_ids(chunk_docs, length, align)

    # ``session``: an open InternSession to use and LEAVE OPEN (the
    # caller wants the table afterwards — e.g. the native exact_emit
    # finish probes it for tie fallback); default: own session.
    import contextlib
    ph = {"pack": 0.0, "put": 0.0}
    ctx = (contextlib.nullcontext(session) if session is not None
           else fast_tokenizer.InternSession(cfg.vocab_size))
    with ctx as sess:
        df_acc = jnp.zeros((cfg.vocab_size,), jnp.int32)
        trip_i, trip_c, trip_h, len_parts, all_lengths = [], [], [], [], []

        def pack_exact(chunk_names):
            flat, lengths, total = sess.pack_flat(
                [os.path.join(input_dir, n) for n in chunk_names],
                cfg.truncate_tokens_at, length, pad_docs_to=chunk_docs,
                seed=cfg.hash_seed, align=align, cap_ids=cap)
            return _bucket_pad_flat(flat, total), lengths, total

        # Same double-buffered packer thread as the hashed resident
        # path. The single worker keeps chunks in submission order,
        # which the intern table REQUIRES (ids are assigned in first-
        # appearance order across the whole corpus).
        with _PackAhead(pack_exact,
                        [names[s:s + chunk_docs] for s in starts]) \
                as packer:
            for ci in range(len(starts)):
                n_chunk = len(names[starts[ci]:starts[ci] + chunk_docs])
                t0 = time.perf_counter()
                flat, lengths, _total = packer.get(ci)
                ph["pack"] += time.perf_counter() - t0  # stall only
                all_lengths.append(lengths[:n_chunk])
                t0 = time.perf_counter()
                lens = jax.device_put(lengths)
                i_, c_, h_, df_acc = _chunk_step(
                    jax.device_put(flat), lens, df_acc, cfg, length,
                    ragged=True, fold_df=not _resident_df_mode()[1])
                trip_i.append(i_)
                trip_c.append(c_)
                trip_h.append(h_)
                len_parts.append(lens)
                ph["put"] += time.perf_counter() - t0
        ph["pack_host"] = packer.host_seconds
        t0 = time.perf_counter()
        _, wire = _finish_wire((trip_i, trip_c, trip_h), len_parts,
                               df_acc, num_docs, k, score_dtype, cfg,
                               wire_vals=False, exact_wire=True)
        buf = np.asarray(jax.device_get(wire))
        ph["fetch"] = time.perf_counter() - t0
        words = sess.words()
    tids, cnt, df_vec = _decode_wire_exact(
        buf, len(starts) * chunk_docs, k,
        wide_ids=cfg.vocab_size > (1 << 16))
    return ExactIngest(names=names, lengths=np.concatenate(all_lengths),
                       topk_ids=tids[:num_docs],
                       topk_counts=cnt[:num_docs], df=df_vec,
                       num_docs=num_docs, words=words, phases=ph)


def profile_resident(input_dir: str, config: Optional[PipelineConfig] = None,
                     chunk_docs: int = 8192, doc_len: Optional[int] = None,
                     strict: bool = True) -> Dict[str, float]:
    """Serialized phase profile of the resident fused path.

    Every phase is fenced with ``block_until_ready`` so the numbers are
    true per-phase costs — pack (host tokenize+hash into the wire
    batch), upload (host->device copy alone), compute (the fused XLA
    program alone), fetch (device->host result copy). The fenced wall
    exceeds :func:`run_overlapped`'s overlapped wall by construction;
    the delta is what the overlap buys. Callers must have warmed the
    jit cache (one prior run at the same shapes) or "compute" includes
    compilation.
    """
    cfg = config or PipelineConfig(vocab_mode=VocabMode.HASHED, topk=16)
    length = doc_len or cfg.max_doc_len
    names = discover_names(input_dir, strict)
    num_docs = len(names)
    # Canonicalized: without jax_enable_x64 a float64 request computes
    # (and ships) float32 — decode must agree with what XLA emits.
    score_dtype = jax.dtypes.canonicalize_dtype(jnp.dtype(cfg.score_dtype))
    k = min(cfg.topk, length)
    chunk_docs, starts = _resident_chunking(num_docs, chunk_docs)
    bwire = use_bytes_wire(cfg, chunk_docs, length)
    ragged = (not bwire) and use_ragged_wire(cfg, chunk_docs, length)
    pack_stats: Dict[str, float] = {}
    if bwire:
        pack = make_bytes_packer(input_dir, cfg, chunk_docs, length,
                                 stats=pack_stats)
        tok_method = tokenize_method()
    elif ragged:
        pack = make_flat_packer(input_dir, cfg, chunk_docs, length)
    else:
        pack = make_chunk_packer(input_dir, cfg, chunk_docs, length)

    ph: Dict[str, float] = {}
    t0 = time.perf_counter()
    packed = [pack(names[s:s + chunk_docs]) for s in starts]
    ph["pack"] = time.perf_counter() - t0
    for key, secs in pack_stats.items():
        ph[f"pack_{key}"] = secs  # bytes wire: pack = load + slab
    # Actual wire payload of the serialized profile (same buffers the
    # upload phase stages) and the padded-format equivalent — the
    # bench's bytes_on_wire fields for the fenced protocol.
    use_native = (cfg.tokenizer is TokenizerKind.WHITESPACE
                  and fast_tokenizer.loader_available())
    itemsize = 2 if (use_native and cfg.vocab_size <= (1 << 16)) else 4
    ph["bytes_on_wire"] = float(sum(p[0].nbytes + p[1].nbytes
                                    for p in packed))
    ph["bytes_on_wire_padded"] = float(
        len(packed) * chunk_docs * length * itemsize
        + sum(p[1].nbytes for p in packed))

    # The tunneled link stages device_put data and only moves it when a
    # consuming program runs (tools/link_probe.py vs the ab probes), so
    # "upload" here is mostly staging cost; the true transfer shows up
    # in "compute". The split is still reported for cross-checking.
    t0 = time.perf_counter()
    tok_parts = [jax.device_put(p[0]) for p in packed]
    len_parts = [jax.device_put(p[1]) for p in packed]
    jax.block_until_ready((tok_parts, len_parts))
    ph["upload"] = time.perf_counter() - t0

    # Compute fenced as one block: the production per-chunk programs
    # plus the finish — the same executables the resident path
    # dispatches, so "compute" is its true device cost (plus the lazy
    # transfers, see above). On the packed result wire the finish
    # mirrors the resolved --finish structure: ONE scanned dispatch
    # (_phase_b_scan_packed) or the per-chunk scoring dispatches
    # (_phase_b_cached_packed); the pair wire keeps the fused
    # _finish_wire — the profiler always mirrors the production
    # program structure (cache-sharing doctrine, tests/test_ingest.py
    # profiler test).
    packed_wire = use_packed_result_wire(cfg)
    scan_finish = use_scan_finish(cfg, packed_wire)
    ph["n_phase_b_dispatches"] = float(1 if (scan_finish
                                             or not packed_wire)
                                       else len(starts))

    def compute_once():
        df_acc = jnp.zeros((cfg.vocab_size,), jnp.int32)
        trip_i, trip_c, trip_h = [], [], []
        tok_lens = len_parts
        if bwire:
            # The bytes wire's finish consumes the DEVICE-derived token
            # lengths (len_parts staged above are byte lengths).
            tok_lens = []
            for slab, blens in zip(tok_parts, len_parts):
                i_, c_, h_, df_acc, lens = _chunk_bytes(
                    slab, blens, df_acc, length=length,
                    vocab_size=cfg.vocab_size, seed=cfg.hash_seed,
                    truncate_at=cfg.truncate_tokens_at,
                    align=_wire_align(),
                    fold_df=not _resident_df_mode()[1],
                    method=tok_method)
                trip_i.append(i_)
                trip_c.append(c_)
                trip_h.append(h_)
                tok_lens.append(lens)
        else:
            for toks, lens in zip(tok_parts, len_parts):
                i_, c_, h_, df_acc = _chunk_step(
                    toks, lens, df_acc, cfg, length, ragged=ragged,
                    fold_df=not _resident_df_mode()[1])
                trip_i.append(i_)
                trip_c.append(c_)
                trip_h.append(h_)
        if packed_wire:
            df_dev = (_df_from_trips(tuple(trip_i), tuple(trip_h),
                                     vocab_size=cfg.vocab_size)
                      if _resident_df_mode()[1] else df_acc)
            idf = _final_idf(df_dev, jnp.int32(num_docs),
                             score_dtype=score_dtype)
            if scan_finish:
                return _phase_b_scan_packed(
                    tuple(trip_i), tuple(trip_c), tuple(trip_h),
                    tuple(tok_lens), idf, topk=k)
            return [_phase_b_cached_packed(i_, c_, h_, lens, idf, topk=k)
                    for i_, c_, h_, lens in zip(trip_i, trip_c, trip_h,
                                                tok_lens)]
        _, wire = _finish_wire((trip_i, trip_c, trip_h), tok_lens,
                               df_acc, num_docs, k, score_dtype, cfg,
                               wire_vals=True)
        return wire

    t0 = time.perf_counter()
    wire = compute_once()
    jax.block_until_ready(wire)
    ph["compute"] = time.perf_counter() - t0

    # Pipelined marginal: re-dispatch the same program chain 4x and
    # fence once (device executes in-order). Two baselines matter:
    # "compute" above includes the lazily-staged input transfer (the
    # tunnel moves device_put bytes at first consumption) plus a full
    # ~100 ms round trip, so subtracting IT would underestimate the
    # marginal (review r5). The chain is differenced against a second
    # fenced one-shot ("compute_warm", inputs now resident) instead;
    # the floor guards against link jitter making the difference
    # negative, never letting a garbage huge rate into the artifact.
    t0 = time.perf_counter()
    jax.block_until_ready(compute_once())
    warm = time.perf_counter() - t0
    ph["compute_warm"] = warm
    t0 = time.perf_counter()
    last = None
    for _ in range(4):
        last = compute_once()
    jax.block_until_ready(last)
    chain = time.perf_counter() - t0
    ph["compute_marginal"] = max((chain - warm) / 3, warm / 16)

    t0 = time.perf_counter()
    jax.device_get(wire)
    ph["fetch"] = time.perf_counter() - t0
    # Steady-state drain cost: a second fetch of the identical result
    # buffers — the link/transfer component alone, with any first-touch
    # staging amortized (the downlink twin of compute_warm; the bench
    # reports both next to the overlapped run's fetch stall).
    t0 = time.perf_counter()
    jax.device_get(wire)
    ph["fetch_warm"] = time.perf_counter() - t0
    ph["bytes_off_wire"] = float(
        sum(w.nbytes for w in wire) if isinstance(wire, list)
        else wire.nbytes)
    ph["bytes_off_wire_pair"] = float(
        len(starts) * chunk_docs * k * pair_slot_bytes(score_dtype))
    return ph
