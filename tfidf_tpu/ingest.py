"""Overlapped corpus ingest: host packing pipelined against device compute.

The reference interleaves file IO and compute on the same rank, serially
per document (``TFIDF.c:130-205``) — every byte of IO stalls compute.
Here ingest is a two-phase chunked pipeline built on JAX's async
dispatch: the host thread packs chunk ``i+1`` (native parallel loader)
while the device is still executing chunk ``i``'s program — ``device_put``
and jitted calls return before the work completes, so the Python loop
runs ahead of the device and the transfer/compute of one chunk hides the
host tokenize/hash of the next.

Because DF is corpus-global but chunks stream, the run is two device
passes (same shape as classic out-of-core TF-IDF, and of the reference's
own reduce-then-rebroadcast choreography, ``TFIDF.c:215-220``):

  A. per chunk: partial DF, folded into a single device-resident [V]
     accumulator. Nothing else survives the chunk.
  B. per chunk: re-derive the row-sparse triples and score them against
     the final corpus-wide IDF; keep only the [chunk, K] top-k.

Both passes run ONE compiled program each, reused for every chunk
(static [chunk, L] shapes; the last chunk is padded with empty docs), so
compile time and device memory are FLAT in the number of chunks: device
residency is one [chunk, L] batch + the [V] DF + the accumulated
[D, K] top-k. Pass B re-sorts each chunk instead of keeping pass-A
triples resident — sort is cheap on device next to the transfer it
would take to spill triples, and it is what makes 1M-doc corpora fit.

Between passes the packed host arrays are either kept in host RAM
(``spill="host"``) or re-packed from disk in pass B (``spill="reread"``,
the reference's own two-scan idiom, ``TFIDF.c:141-147`` — it fseeks and
re-reads every doc). ``spill="auto"`` keeps chunks in RAM up to a byte
budget and re-reads beyond it.
"""

from __future__ import annotations

import dataclasses
import functools
import os
from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from tfidf_tpu.config import PipelineConfig, TokenizerKind, VocabMode
from tfidf_tpu.io import fast_tokenizer
from tfidf_tpu.io.corpus import discover_names, pack_corpus
from tfidf_tpu.ops.scoring import idf_from_df
from tfidf_tpu.ops.sparse import (sorted_term_counts, sparse_df,
                                  sparse_scores, sparse_topk)

# spill="auto": keep packed chunks in host RAM up to this many bytes,
# re-read from disk beyond. Read at call time (TFIDF_TPU_SPILL_BYTES)
# so tests/tuning can override after import, like TFIDF_TPU_DF_METHOD.
_DEFAULT_SPILL_BYTES = 1 << 30

# Host-ahead bound: how many chunks the dispatch loops may run ahead of
# the device before blocking. Keeps HBM residency at O(lookahead) chunk
# buffers even when host packing outpaces device compute.
_LOOKAHEAD = 2


@functools.partial(jax.jit, static_argnames=("vocab_size",))
def _phase_a(token_ids, lengths, df_acc, *, vocab_size: int):
    """Fold one chunk's partial DF into the device-resident accumulator."""
    ids, _, head = sorted_term_counts(token_ids, lengths)
    return df_acc + sparse_df(ids, head, vocab_size)


@functools.partial(jax.jit, static_argnames=("topk",))
def _phase_b(token_ids, lengths, idf, *, topk: int):
    """Score one chunk against the final corpus-wide IDF -> top-k."""
    ids, counts, head = sorted_term_counts(token_ids, lengths)
    scores = sparse_scores(ids, counts, head, lengths, idf)
    return sparse_topk(scores, ids, head, topk)


@functools.partial(jax.jit, static_argnames=("score_dtype",))
def _final_idf(df_total, num_docs, *, score_dtype):
    return idf_from_df(df_total, num_docs, score_dtype)


@dataclasses.dataclass
class IngestResult:
    """Corpus-wide outputs of an overlapped ingest run."""

    df: np.ndarray            # [V] corpus document frequencies
    topk_vals: np.ndarray     # [D, K] per-doc top-k TF-IDF scores
    topk_ids: np.ndarray      # [D, K] matching vocab ids (-1 = no term)
    lengths: np.ndarray       # [D] docSize per document
    names: List[str]
    num_docs: int


def make_chunk_packer(input_dir: str, cfg: PipelineConfig, chunk_docs: int,
                      length: int):
    """The host packing path of one chunk: names -> (token_ids, lengths).

    Native parallel loader when built (document bytes never enter
    Python), else the Python pack path — the exact code
    :func:`run_overlapped` runs, exposed so benchmarks/diagnostics time
    the same workload instead of re-implementing it.
    """
    use_native = (cfg.tokenizer is TokenizerKind.WHITESPACE
                  and fast_tokenizer.loader_available())

    def pack_chunk_native(chunk_names: List[str]
                          ) -> Tuple[np.ndarray, np.ndarray]:
        packed = fast_tokenizer.load_pack_paths(
            [os.path.join(input_dir, n) for n in chunk_names],
            cfg.vocab_size, cfg.hash_seed, cfg.truncate_tokens_at,
            min_len=length, chunk=length, fixed_len=length,
            pad_docs_to=chunk_docs)
        assert packed is not None  # loader_available() checked above
        return packed

    def pack_chunk_python(chunk_names: List[str]
                          ) -> Tuple[np.ndarray, np.ndarray]:
        from tfidf_tpu.io.corpus import Corpus
        docs = []
        for n in chunk_names:
            with open(os.path.join(input_dir, n), "rb") as f:
                docs.append(f.read())
        batch = pack_corpus(Corpus(names=list(chunk_names), docs=docs),
                            cfg, pad_docs_to=chunk_docs, want_words=False)
        ids = batch.token_ids[:, :length]
        if batch.token_ids.shape[1] < length:
            pad = np.zeros((ids.shape[0], length - ids.shape[1]), ids.dtype)
            ids = np.concatenate([ids, pad], axis=1)
        return ids, np.minimum(batch.lengths, length).astype(np.int32)

    return pack_chunk_native if use_native else pack_chunk_python


def run_overlapped(input_dir: str, config: Optional[PipelineConfig] = None,
                   chunk_docs: int = 8192, doc_len: Optional[int] = None,
                   strict: bool = True, spill: str = "auto") -> IngestResult:
    """Stream a directory through the overlapped two-pass pipeline.

    ``doc_len`` fixes the static token length L for every chunk (defaults
    to ``config.max_doc_len``); documents longer than L are truncated to
    L tokens — the fixed-shape tradeoff for never recompiling. Use
    ``TfidfPipeline`` (single batch, L grows to the longest doc) when
    truncation is unacceptable, or ``parallel.longdoc`` for documents
    beyond any single chip.

    ``spill`` controls where packed chunks live between pass A and B:
    ``"host"`` (RAM), ``"reread"`` (re-pack from disk), or ``"auto"``
    (RAM up to a budget). Device memory is flat in corpus size either
    way; see the module docstring.

    Requires HASHED vocab (fixed id space across chunks) and a top-k
    selection (full per-term output would defeat the streaming design).
    Works with or without the native loader; the native path keeps
    document bytes out of Python entirely.
    """
    cfg = config or PipelineConfig(vocab_mode=VocabMode.HASHED, topk=16)
    if cfg.vocab_mode is not VocabMode.HASHED:
        raise ValueError("overlapped ingest requires VocabMode.HASHED")
    if cfg.topk is None:
        raise ValueError("overlapped ingest requires a topk selection")
    if spill not in ("auto", "host", "reread"):
        raise ValueError(f"unknown spill policy {spill!r}")
    length = doc_len or cfg.max_doc_len
    names = discover_names(input_dir, strict)
    num_docs = len(names)
    if num_docs == 0:
        raise ValueError(f"no documents in {input_dir}")

    use_native = (cfg.tokenizer is TokenizerKind.WHITESPACE
                  and fast_tokenizer.loader_available())
    score_dtype = jnp.dtype(cfg.score_dtype)
    k = min(cfg.topk, length)
    if spill == "auto":
        itemsize = 2 if (use_native and cfg.vocab_size <= (1 << 16)) else 4
        est = num_docs * length * itemsize
        budget = int(os.environ.get("TFIDF_TPU_SPILL_BYTES",
                                    _DEFAULT_SPILL_BYTES))
        spill = "host" if est <= budget else "reread"

    pack_chunk = make_chunk_packer(input_dir, cfg, chunk_docs, length)
    starts = list(range(0, num_docs, chunk_docs))

    # Pass A: fold every chunk's partial DF into one device accumulator.
    # The loop packs chunk i+1 while the device still runs chunk i
    # (async dispatch), but never runs more than _LOOKAHEAD chunks
    # ahead — blocking on chunk i-_LOOKAHEAD's result bounds HBM
    # residency at O(lookahead) [chunk, L] buffers even when host
    # packing outpaces the device.
    df_acc = jnp.zeros((cfg.vocab_size,), jnp.int32)
    cached: List[Tuple[np.ndarray, np.ndarray]] = []
    all_lengths: List[np.ndarray] = []
    in_flight: List[jax.Array] = []
    for start in starts:
        chunk_names = names[start:start + chunk_docs]
        token_ids, lengths = pack_chunk(chunk_names)
        all_lengths.append(lengths[:len(chunk_names)])
        if spill == "host":
            cached.append((token_ids, lengths))
        toks = jax.device_put(token_ids)
        lens = jax.device_put(lengths)
        df_acc = _phase_a(toks, lens, df_acc, vocab_size=cfg.vocab_size)
        in_flight.append(df_acc)
        if len(in_flight) > _LOOKAHEAD:
            in_flight.pop(0).block_until_ready()

    idf = _final_idf(df_acc, jnp.int32(num_docs), score_dtype=score_dtype)

    # Pass B: rescore each chunk against the corpus-wide IDF. Same
    # overlap structure; only the [chunk, K] selections accumulate on
    # device, fetched in one transfer at the end.
    vals_parts, ids_parts = [], []
    for ci, start in enumerate(starts):
        if spill == "host":
            token_ids, lengths = cached[ci]
        else:
            token_ids, lengths = pack_chunk(names[start:start + chunk_docs])
        toks = jax.device_put(token_ids)
        lens = jax.device_put(lengths)
        v, t = _phase_b(toks, lens, idf, topk=k)
        vals_parts.append(v)
        ids_parts.append(t)
        if ci >= _LOOKAHEAD:  # same bounded lookahead as pass A
            vals_parts[ci - _LOOKAHEAD].block_until_ready()

    df_host, vals, tids = jax.device_get(
        (df_acc, jnp.concatenate(vals_parts), jnp.concatenate(ids_parts)))
    return IngestResult(df=df_host, topk_vals=vals[:num_docs],
                        topk_ids=tids[:num_docs],
                        lengths=np.concatenate(all_lengths), names=names,
                        num_docs=num_docs)
