"""Overlapped corpus ingest: host packing pipelined against device compute.

The reference interleaves file IO and compute on the same rank, serially
per document (``TFIDF.c:130-205``) — every byte of IO stalls compute.
Here ingest is a two-phase chunked pipeline built on JAX's async
dispatch: the host thread packs chunk ``i+1`` (native parallel loader)
while the device is still executing chunk ``i``'s program — ``device_put``
and jitted calls return before the work completes, so the Python loop
runs ahead of the device and the transfer/compute of one chunk hides the
host tokenize/hash of the next.

Because DF is corpus-global but chunks stream, the run is two device
phases (same shape as classic out-of-core TF-IDF, and of the reference's
own reduce-then-rebroadcast choreography, ``TFIDF.c:215-220``):

  A. per chunk: sort + run-length term triples, partial DF — triples
     stay resident on device; only the [V] partial DF accumulates.
  B. per chunk: score the resident triples against the final corpus-wide
     IDF and select per-doc top-k.

All chunks share one compiled program per phase (static [chunk, L]
shapes; the last chunk is padded with empty docs).
"""

from __future__ import annotations

import dataclasses
import functools
import os
from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from tfidf_tpu.config import PipelineConfig, TokenizerKind, VocabMode
from tfidf_tpu.io import fast_tokenizer
from tfidf_tpu.io.corpus import discover_names, pack_corpus
from tfidf_tpu.ops.scoring import idf_from_df
from tfidf_tpu.ops.sparse import (sorted_term_counts, sparse_df,
                                  sparse_scores, sparse_topk)


@functools.partial(jax.jit, static_argnames=("vocab_size",))
def _phase_a(token_ids, lengths, *, vocab_size: int):
    """Chunk -> (row-sparse triples, partial DF). Triples stay on device."""
    ids, counts, head = sorted_term_counts(token_ids, lengths)
    return ids, counts, head, sparse_df(ids, head, vocab_size)


@functools.partial(jax.jit, static_argnames=("score_dtype", "topk"))
def _phase_b(ids, counts, head, lengths, df_total, num_docs, *,
             score_dtype, topk: int):
    idf = idf_from_df(df_total, num_docs, score_dtype)
    scores = sparse_scores(ids, counts, head, lengths, idf)
    return sparse_topk(scores, ids, head, topk)


@functools.partial(jax.jit,
                   static_argnames=("score_dtype", "topk", "n_chunks"))
def _phase_b_all(flat, df_parts, num_docs, *, score_dtype, topk: int,
                 n_chunks: int):
    """All chunks' phase B in ONE program: df reduce + score + top-k.

    ``flat`` is the per-chunk (ids, counts, head, lengths) tuples
    flattened in order. One dispatch and one (vals, ids) result for the
    whole corpus instead of per-chunk calls — dispatch/transfer round
    trips, not FLOPs, dominate phase B.
    """
    df_total = functools.reduce(jnp.add, df_parts)
    idf = idf_from_df(df_total, num_docs, score_dtype)
    vals, out_ids = [], []
    for c in range(n_chunks):
        ids, counts, head, lengths = flat[4 * c:4 * c + 4]
        scores = sparse_scores(ids, counts, head, lengths, idf)
        v, t = sparse_topk(scores, ids, head, topk)
        vals.append(v)
        out_ids.append(t)
    return df_total, jnp.concatenate(vals), jnp.concatenate(out_ids)


@dataclasses.dataclass
class IngestResult:
    """Corpus-wide outputs of an overlapped ingest run."""

    df: np.ndarray            # [V] corpus document frequencies
    topk_vals: np.ndarray     # [D, K] per-doc top-k TF-IDF scores
    topk_ids: np.ndarray      # [D, K] matching vocab ids (-1 = no term)
    lengths: np.ndarray       # [D] docSize per document
    names: List[str]
    num_docs: int


def run_overlapped(input_dir: str, config: Optional[PipelineConfig] = None,
                   chunk_docs: int = 8192, doc_len: Optional[int] = None,
                   strict: bool = True) -> IngestResult:
    """Stream a directory through the overlapped two-phase pipeline.

    ``doc_len`` fixes the static token length L for every chunk (defaults
    to ``config.max_doc_len``); documents longer than L are truncated to
    L tokens — the fixed-shape tradeoff for never recompiling. Use
    ``TfidfPipeline`` (single batch, L grows to the longest doc) when
    truncation is unacceptable, or ``parallel.longdoc`` for documents
    beyond any single chip.

    Requires HASHED vocab (fixed id space across chunks) and a top-k
    selection (full per-term output would defeat the resident-triple
    design). Works with or without the native loader; the native path
    keeps document bytes out of Python entirely.
    """
    cfg = config or PipelineConfig(vocab_mode=VocabMode.HASHED, topk=16)
    if cfg.vocab_mode is not VocabMode.HASHED:
        raise ValueError("overlapped ingest requires VocabMode.HASHED")
    if cfg.topk is None:
        raise ValueError("overlapped ingest requires a topk selection")
    length = doc_len or cfg.max_doc_len
    names = discover_names(input_dir, strict)
    num_docs = len(names)
    if num_docs == 0:
        raise ValueError(f"no documents in {input_dir}")

    use_native = (cfg.tokenizer is TokenizerKind.WHITESPACE
                  and fast_tokenizer.loader_available())
    score_dtype = jnp.dtype(cfg.score_dtype)
    k = min(cfg.topk, length)

    def pack_chunk_native(chunk_names: List[str]
                          ) -> Tuple[np.ndarray, np.ndarray]:
        packed = fast_tokenizer.load_pack_paths(
            [os.path.join(input_dir, n) for n in chunk_names],
            cfg.vocab_size, cfg.hash_seed, cfg.truncate_tokens_at,
            min_len=length, chunk=length, fixed_len=length,
            pad_docs_to=chunk_docs)
        assert packed is not None  # loader_available() checked above
        return packed

    def pack_chunk_python(chunk_names: List[str]
                          ) -> Tuple[np.ndarray, np.ndarray]:
        from tfidf_tpu.io.corpus import Corpus
        docs = []
        for n in chunk_names:
            with open(os.path.join(input_dir, n), "rb") as f:
                docs.append(f.read())
        batch = pack_corpus(Corpus(names=list(chunk_names), docs=docs),
                            cfg, pad_docs_to=chunk_docs, want_words=False)
        ids = batch.token_ids[:, :length]
        if batch.token_ids.shape[1] < length:
            pad = np.zeros((ids.shape[0], length - ids.shape[1]), ids.dtype)
            ids = np.concatenate([ids, pad], axis=1)
        return ids, np.minimum(batch.lengths, length).astype(np.int32)

    pack_chunk = pack_chunk_native if use_native else pack_chunk_python

    # Phase A: launch every chunk; the loop packs chunk i+1 while the
    # device still runs chunk i (async dispatch — no block in the loop).
    resident = []
    df_parts = []
    all_lengths: List[np.ndarray] = []
    for start in range(0, num_docs, chunk_docs):
        chunk_names = names[start:start + chunk_docs]
        token_ids, lengths = pack_chunk(chunk_names)
        all_lengths.append(lengths[:len(chunk_names)])
        toks = jax.device_put(token_ids)
        lens = jax.device_put(lengths)
        ids, counts, head, df_part = _phase_a(toks, lens,
                                              vocab_size=cfg.vocab_size)
        resident.append((ids, counts, head, lens))
        df_parts.append(df_part)

    # Phase B: rescore all resident triples against corpus-wide IDF in
    # one program — a single dispatch and one fetched result.
    flat = tuple(a for chunk in resident for a in chunk)
    df_total, vals_d, tids_d = _phase_b_all(
        flat, tuple(df_parts), jnp.int32(num_docs),
        score_dtype=score_dtype, topk=k, n_chunks=len(resident))
    df_host, vals, tids = jax.device_get((df_total, vals_d, tids_d))
    return IngestResult(df=df_host, topk_vals=vals[:num_docs],
                        topk_ids=tids[:num_docs],
                        lengths=np.concatenate(all_lengths), names=names,
                        num_docs=num_docs)
