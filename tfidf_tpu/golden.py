"""Pure-Python golden oracle: byte-identical output to the C reference.

This is the executable specification of the reference's semantics
(SURVEY §2-§3), used to validate both the TPU pipeline and the native
bit-reference under ``native/``. It deliberately runs the same double
operations in the same order as the C code:

* ``TF = 1.0 * wordCount / docSize``            (``TFIDF.c:202``)
* ``IDF = log(1.0 * numDocs / numDocsWithWord)``(``TFIDF.c:243``) —
  natural log, no smoothing; a word in every doc scores exactly 0.
* ``score = TF * IDF``                          (``TFIDF.c:244``)
* line = ``"%s@%s\\t%.16f" % (document, word, score)`` — note the output
  key order is document@word while the debug prints are word@document
  (SURVEY §2.5 C9).
* final ordering: ``qsort`` with ``strcmp`` (``TFIDF.c:273``) — raw-byte
  lexicographic, so ``doc10@...`` sorts before ``doc2@...``.

Python's float is the same IEEE double and ``%.16f`` performs the same
correctly-rounded decimal conversion as glibc, so lines match byte for
byte. Valid only inside the reference's envelope (SURVEY §2.5): the
oracle does NOT reproduce the 32-record silent overflows or the >=16-char
token buffer overflow — those are bugs, not semantics.
"""

from __future__ import annotations

import math
from typing import Dict, List

from tfidf_tpu.io.corpus import Corpus
from tfidf_tpu.ops.tokenize import whitespace_tokenize


def golden_lines(corpus: Corpus) -> List[bytes]:
    """TF-IDF output lines for a corpus, bit-identical to the reference.

    One line per (document, word) pair in which the word occurs, sorted
    raw-byte lexicographically, no trailing newline per element.
    """
    token_docs = [whitespace_tokenize(doc) for doc in corpus.docs]
    num_docs = len(corpus)

    # DF: number of documents containing each word (dedup within doc —
    # the reference's currDoc mechanism, TFIDF.c:171-188).
    df: Dict[bytes, int] = {}
    for toks in token_docs:
        for w in set(toks):
            df[w] = df.get(w, 0) + 1

    lines: List[bytes] = []
    for name, toks in zip(corpus.names, token_docs):
        doc_size = len(toks)
        counts: Dict[bytes, int] = {}
        for w in toks:
            counts[w] = counts.get(w, 0) + 1
        for w, c in counts.items():
            tf = 1.0 * c / doc_size
            idf = math.log(1.0 * num_docs / df[w])
            score = tf * idf
            lines.append(b"%s@%s\t%s" % (
                name.encode(), w, (b"%.16f" % score)))
    lines.sort()  # bytes compare == strcmp ordering (TFIDF.c:47-50,273)
    return lines


def golden_output(corpus: Corpus) -> bytes:
    """The full ``output.txt`` byte stream (one line per record,
    ``\\n``-terminated, ``TFIDF.c:278-281``)."""
    return b"".join(line + b"\n" for line in golden_lines(corpus))


def inspect_tables(corpus: Corpus) -> bytes:
    """The reference's per-phase debug tables (``--inspect``).

    Mirrors the eyeball-diff prints of the reference — the "TF Job"
    table (``word@document\\twordCount/docSize``, ``TFIDF.c:199-205``)
    and the "IDF Job" table (``word@document\\tnumDocs/numDocsWithWord``,
    ``TFIDF.c:236-239``) — in the same formats, including the
    word@document key order that is REVERSED from the final output's
    document@word (SURVEY §2.5 C9). Record order is per-document in
    discovery order, first-seen word order within a document; the
    reference's own interleaving depends on its rank schedule and is
    not a contract. Intended for toy corpora, exactly like the
    original prints.
    """
    token_docs = [whitespace_tokenize(doc) for doc in corpus.docs]
    num_docs = len(corpus)
    df: Dict[bytes, int] = {}
    for toks in token_docs:
        for w in set(toks):
            df[w] = df.get(w, 0) + 1
    per_doc = []
    for name, toks in zip(corpus.names, token_docs):
        counts: Dict[bytes, int] = {}
        for w in toks:
            counts[w] = counts.get(w, 0) + 1
        per_doc.append((name.encode(), len(toks), counts))
    out: List[bytes] = [b"-------------TF Job-------------"]
    for name, size, counts in per_doc:
        for w, c in counts.items():
            out.append(b"%s@%s\t%d/%d" % (w, name, c, size))
    out.append(b"------------IDF Job-------------")
    for name, size, counts in per_doc:
        for w in counts:
            out.append(b"%s@%s\t%d/%d" % (w, name, num_docs, df[w]))
    return b"".join(l + b"\n" for l in out)
