"""Configuration for the TPU TF-IDF pipeline.

The reference has *no* config system: ``argc/argv`` are ignored
(``TFIDF.c:52``) and every knob is a compile-time ``#define``
(``TFIDF.c:16-20``). Here every knob the reference hardcodes — plus the
TPU-era ones it lacks — is an explicit dataclass field.
"""

from __future__ import annotations

import dataclasses
import enum
import os
from typing import Optional, Tuple


class VocabMode(str, enum.Enum):
    """How words map to integer vocabulary ids.

    EXACT builds a host-side string->id dictionary over the corpus — the
    moral equivalent of the reference's string-keyed tables
    (``TFIDF.c:26-42``), collision-free, used for golden-parity runs.

    HASHED maps words through FNV-1a into a fixed-size vocab (default
    2^16 per BASELINE config 2). Collisions are possible; this is the
    scalable path: the DF "set union by string" of the reference's
    CustomReduce (``TFIDF.c:291-319``) becomes a dense vector add.
    """

    EXACT = "exact"
    HASHED = "hashed"


class TokenizerKind(str, enum.Enum):
    """Tokenizer family.

    WHITESPACE mirrors the reference's ``fscanf("%s")`` splitting
    (``TFIDF.c:142-147``). CHARGRAM is the char n-gram mode of BASELINE
    config 4 (wide-vocab stress); n-gram ids are computed on device.
    """

    WHITESPACE = "whitespace"
    CHARGRAM = "chargram"


@dataclasses.dataclass(frozen=True)
class PipelineConfig:
    """All knobs for a TF-IDF run.

    Attributes:
      vocab_mode: EXACT (golden parity) or HASHED (scalable).
      vocab_size: vocabulary size for HASHED mode (ignored for EXACT,
        where the corpus determines it). 2^16 per BASELINE config 2.
      hash_seed: FNV-1a seed perturbation, so collision structure can be
        varied between runs.
      tokenizer: WHITESPACE or CHARGRAM.
      ngram_range: inclusive (lo, hi) n-gram sizes for CHARGRAM
        (BASELINE config 4 uses 3..5).
      chargram_on_device: HASHED chargram mode computes n-gram ids on
        device from raw bytes (no host n-gram materialization; see
        ops/hashing.device_ngram_ids). False forces the host path
        (FNV over materialized n-gram strings). EXACT mode always uses
        the host path (it needs the strings for the vocabulary).
      truncate_tokens_at: if set, tokens are truncated to this many
        bytes before vocab lookup — replicates the reference's 16-char
        scan-buffer quirk (``MAX_WORD_LENGTH 16``, ``TFIDF.c:18``; see
        SURVEY §2.5-6) for bit-parity runs. None = no truncation.
      max_doc_len: packed token-axis length per document. Documents are
        padded/chunked to this static shape so XLA sees fixed shapes.
      doc_chunk: when a document exceeds max_doc_len, it is split into
        chunks of this many tokens whose histograms are summed — the
        long-document path (SURVEY §5 long-context).
      mesh_shape: logical device mesh, e.g. ``{"docs": 8}`` or
        ``{"docs": 4, "vocab": 2}``. Empty = single device.
      use_pallas: route the TF histogram through the Pallas TPU kernel
        instead of the XLA scatter-add.
      score_dtype: dtype for on-device score math. Exact byte parity
        with the C reference's double math (``TFIDF.c:243-245``) is
        achieved on host in float64 by the golden formatter, so the
        device side can stay float32/bfloat16.
      topk: if set, only the top-k (by score) records per document are
        gathered to host — the scalable replacement for the reference's
        full serial gather (``TFIDF.c:256-270``).
      wire: host→device wire format for the overlapped chunked ingest.
        "ragged" (default) ships one concatenated uint16 token stream
        per chunk (CSR-style, granule-aligned — bytes scale with real
        tokens, not D×L) and rebuilds the padded batch on device;
        "bytes" ships the RAW document bytes (one space-filled slab
        per chunk — the host never tokenizes, hashes or packs ids at
        all) and performs whitespace tokenization + FNV-1a64 +
        fold-to-vocab ON DEVICE (``ops/device_tokenize.py``), emitting
        ids bit-identical to the host packers; "padded" forces the
        dense [D, L] wire — the bit-identical parity fallback.
        "bytes" degrades to "ragged" when the device tokenizer cannot
        carry the run (vocab > 2^16, non-whitespace tokenizer, or a
        mesh plan — ``ingest.use_bytes_wire``), and "ragged" in turn
        degrades to "padded" per ``ingest.use_ragged_wire`` (vocab
        past 2^16, or a chunk whose aligned flat stream would
        overflow the int32/``_FLAT_BUCKET`` offset bound). Env
        override ``TFIDF_TPU_WIRE``.
      pack_threads: host packer thread count for the native loader's
        tokenize+hash fill (the reference's OpenMP move done on the
        shared ``ParallelFor`` pool). None = ``--pack-threads`` /
        ``TFIDF_TPU_PACK_THREADS`` / every core
        (``io.fast_tokenizer.resolve_pack_threads``).
      result_wire: device→host result wire for top-k selections.
        "packed" (default) ships one uint32 word per selected slot —
        16-bit score in the high half, uint16 vocab id in the low half
        (half the pair wire's bytes; scores round to fp16/bf16, ids
        stay bit-exact) and lets the chunked ingest drain results
        asynchronously while later chunks score; "pair" forces the
        full-precision (id, score) pair wire — the bit-identical
        parity fallback, also selected automatically when the word
        cannot carry the run (no topk, vocab > 2^16, or a 64-bit
        score ask — see ``ops.downlink.use_packed_result_wire``).
      finish: structure of the packed-wire phase-B finish for the
        overlapped ingest. "scan" (default) scores the whole resident
        corpus (and the streaming triple-cache prefix) in ONE donated
        ``lax.scan`` dispatch that emits the full [n_chunks, D, K]
        word buffer — one program, one async drain, no per-chunk
        dispatch tax; "chunked" keeps the round-7 per-chunk scoring
        dispatches with the interleaved async drain — the
        bit-identical fallback, also what effectively runs whenever
        the packed result wire cannot carry the run (the pair wire's
        fused finish is already a single dispatch). Env override
        ``TFIDF_TPU_FINISH``; see ``ingest.use_scan_finish``.
      compile_cache: directory for jax's persistent XLA compilation
        cache (``apply_compile_cache``); None leaves it off. CLI
        cold-starts re-pay every compile the warm bench never sees —
        with the cache, a repeat run at the same wire shapes (the
        bucketed flat sizes of ``ingest._FLAT_BUCKET`` exist exactly
        so there are few of them) loads executables from disk
        instead. Env override ``TFIDF_TPU_COMPILE_CACHE``.
      trace: output path for the span tracer's Chrome trace-event
        JSON (``tfidf_tpu.obs``) — the run's host timeline (main /
        packer / drainer / batcher lanes), loadable in Perfetto.
        None leaves tracing off (near-zero overhead). The library
        entry points arm the tracer (``obs.configure``); exporting is
        the caller's final step (the CLI's ``--trace`` does both).
        Env override ``TFIDF_TPU_TRACE``; ring capacity
        ``TFIDF_TPU_TRACE_CAP``. See docs/OBSERVABILITY.md.
    """

    vocab_mode: VocabMode = VocabMode.EXACT
    vocab_size: int = 1 << 16
    # "dense" ([D,V] histograms) | "sparse" (row-sparse) | None = choose
    # by vocab mode from the measured engine bench (docs/ENGINES.md):
    # sort+RLE wins every cell and its margin grows with vocab, so
    # HASHED (large-vocab) runs default to "sparse"; EXACT golden-parity
    # runs keep "dense" (tiny corpus-derived V, dense counts for byte-
    # exact full output).
    engine: Optional[str] = None
    hash_seed: int = 0
    tokenizer: TokenizerKind = TokenizerKind.WHITESPACE
    ngram_range: Tuple[int, int] = (3, 5)
    chargram_on_device: bool = True
    truncate_tokens_at: Optional[int] = None
    max_doc_len: int = 256
    doc_chunk: int = 256
    mesh_shape: dict = dataclasses.field(default_factory=dict)
    use_pallas: bool = False
    score_dtype: str = "float32"
    topk: Optional[int] = None
    wire: str = "ragged"
    pack_threads: Optional[int] = None
    result_wire: str = "packed"
    finish: str = "scan"
    compile_cache: Optional[str] = None
    trace: Optional[str] = None

    def __post_init__(self):
        if self.wire not in ("ragged", "padded", "bytes"):
            raise ValueError(f"unknown wire format {self.wire!r} "
                             f"(choose 'ragged', 'padded' or 'bytes')")
        if self.pack_threads is not None and self.pack_threads < 1:
            raise ValueError("pack_threads must be >= 1")
        if self.result_wire not in ("packed", "pair"):
            raise ValueError(f"unknown result wire {self.result_wire!r} "
                             f"(choose 'packed' or 'pair')")
        if self.finish not in ("scan", "chunked"):
            raise ValueError(f"unknown finish {self.finish!r} "
                             f"(choose 'scan' or 'chunked')")
        if self.vocab_size <= 0:
            raise ValueError("vocab_size must be positive")
        lo, hi = self.ngram_range
        if not (0 < lo <= hi):
            raise ValueError(f"bad ngram_range {self.ngram_range}")
        if self.max_doc_len <= 0 or self.doc_chunk <= 0:
            raise ValueError("max_doc_len/doc_chunk must be positive")
        # _engine_defaulted: True when the engine came from the measured
        # default rather than the caller. A defaulted "sparse" may be
        # swapped for "dense" by capability (the sparse lowering shards
        # the docs axis only); an explicit "sparse" never is.
        object.__setattr__(self, "_engine_defaulted", self.engine is None)
        if self.engine is None:
            # use_pallas is a dense-engine feature: an explicit --pallas
            # must not be silently discarded by the measured default.
            object.__setattr__(
                self, "engine",
                "sparse" if (self.vocab_mode is VocabMode.HASHED
                             and not self.use_pallas) else "dense")
        if self.engine not in ("dense", "sparse"):
            raise ValueError(f"unknown engine {self.engine!r}")

    @staticmethod
    def golden() -> "PipelineConfig":
        """Config whose output is byte-identical to the C reference.

        EXACT vocab, no truncation: golden corpora must stay inside the
        reference's *valid envelope* (SURVEY §2.5) — tokens shorter than
        16 bytes, since past that the reference's ``fscanf("%s")`` into
        ``char word[16]`` (``TFIDF.c:18,59``) is undefined behaviour, not
        a semantics to reproduce.
        """
        return PipelineConfig(vocab_mode=VocabMode.EXACT)


@dataclasses.dataclass(frozen=True)
class ServeConfig:
    """Knobs for the online serving layer (``tfidf_tpu/serve``).

    Attributes:
      max_batch: most queries one coalesced device batch carries; a
        single request larger than this stays atomic (one batch).
        Default 256 (round 21): tiled scoring made one wide dispatch
        cheaper per query than many narrow ones, so the batcher's
        coalescing is now a throughput lever, not a memory liability
        (under ``--score-tiling=off`` the search path re-splits
        internally at the legacy 64 block). CLI ``--max-batch`` / env
        ``TFIDF_TPU_MAX_BATCH``.
      max_wait_ms: deadline-bounded coalescing window — the oldest
        queued request never waits longer than this for the batch to
        fill before it is flushed. CLI ``--max-wait-ms`` / env
        ``TFIDF_TPU_MAX_WAIT_MS``.
      queue_depth: admission bound in QUERIES across all in-flight
        requests; past it :meth:`TfidfServer.submit` sheds with the
        typed ``Overloaded`` error instead of growing an unbounded
        backlog. CLI ``--queue-depth`` / env ``TFIDF_TPU_QUEUE_DEPTH``.
      cache_entries: LRU result-cache capacity in per-query rows
        (0 disables the cache). CLI ``--cache-entries`` / env
        ``TFIDF_TPU_CACHE_ENTRIES``.
      default_deadline_ms: per-request deadline applied when a submit
        names none; None = requests without a deadline never expire.
      health_period_ms: background watchdog cadence — every period the
        server's :class:`~tfidf_tpu.obs.health.HealthMonitor`
        re-derives ``ok | degraded | unhealthy`` from worker
        heartbeats, queue saturation and windowed shed rates, and
        publishes the health gauges. None = no background thread (the
        ``healthz`` op still evaluates on demand) — the library
        default, so embedded test servers carry no timer; the serve
        CLI arms it (default 250 ms). CLI ``--health-period-ms`` (0
        disables) / env ``TFIDF_TPU_HEALTH_PERIOD_MS``.
      stall_after_ms: a worker with pending work that has not
        heartbeat for this long marks the server ``unhealthy``.
        Env ``TFIDF_TPU_STALL_AFTER_MS``.
      degraded_admission_factor: while degraded/unhealthy the
        admission bound shrinks to ``queue_depth * factor`` (floor 1)
        — backpressure that drains the backlog instead of compounding
        it. Env ``TFIDF_TPU_DEGRADED_FACTOR``.
      devmon_period_ms: background device-monitor cadence — every
        period the server's :class:`~tfidf_tpu.obs.devmon.
        DeviceMonitor` samples per-device ``memory_stats()`` into
        registry gauges, checks the HBM watermarks
        (``TFIDF_TPU_HBM_WATERMARKS``) and refreshes the
        ``memory_pressure`` health signal, so admission sheds BEFORE
        the allocator OOMs. None = no monitor thread (the library
        default; backends with no memory stats — CPU — run the same
        path with gauges absent). CLI ``--devmon-period-ms`` (0
        disables) / env ``TFIDF_TPU_DEVMON_PERIOD_MS``.
      dispatch_retries: transient dispatch failures retried per batch
        before bisection/failure (total attempts = 1 + retries).
        Env ``TFIDF_TPU_DISPATCH_RETRIES``.
      retry_backoff_ms: base of the jittered exponential backoff
        between dispatch retries (x2 per attempt, capped at 1 s).
        Env ``TFIDF_TPU_RETRY_BACKOFF_MS``.
      breaker_threshold: consecutive dispatch failures that trip the
        circuit breaker OPEN — a degraded health reason shrinking the
        admission bound until a dispatch succeeds after the cooldown.
        Env ``TFIDF_TPU_BREAKER_THRESHOLD``.
      breaker_cooldown_ms: how long an open breaker pauses dispatch
        attempts before the half-open recovery probe.
        Env ``TFIDF_TPU_BREAKER_COOLDOWN_MS``.
      restart_budget: crashed-worker restarts tolerated (batcher
        loop; the ingest pack/drain workers honor the same env) —
        past it the batcher declares itself dead and the server
        refuses work instead of serving as a zombie.
        Env ``TFIDF_TPU_RESTART_BUDGET``.
      snapshot_dir: checkpoint root for the resident-index snapshot
        (``TfidfServer.snapshot`` / restore-on-start; ``swap_index``
        snapshots the incoming epoch before flipping). None disables.
        CLI ``--snapshot-dir`` / env ``TFIDF_TPU_SNAPSHOT_DIR``.
      faults: fault-injection plan spec armed by the server on
        construction and disarmed on close (chaos testing —
        ``tfidf_tpu/faults.py`` has the grammar). None = no
        injection. Env ``TFIDF_TPU_FAULTS``.
      fault_seed: seed for the plan's probabilistic rules and the
        retry jitter, so chaos runs replay deterministically.
        Env ``TFIDF_TPU_FAULT_SEED``.
      slow_ms: slow-query threshold — a resolved request whose total
        latency exceeds this emits a ``slow_query`` flight event with
        its per-phase breakdown, batch id, co-occupant count and
        overlapping anomalies (``obs/reqtrace.py``; ``tools/doctor.py
        --request RID`` renders the timeline). None = no slow-query
        log. CLI ``--slow-ms`` / env ``TFIDF_TPU_SLOW_MS``.
      slow_sample: 1-in-N tail sample — every Nth resolved request
        emits the same event (``sampled: true``) even under the
        threshold, so the forensic pipeline stays exercised when
        nothing is slow. 0 disables. Env ``TFIDF_TPU_SLOW_SAMPLE``.
      slo_ms: latency objective for the SLO burn gauges
        (``obs/slo.py``): requests over this are "bad"; windowed
        fast/slow burn rates publish as gauges and a fast burn feeds
        the degraded-admission path. None = no SLO tracking. CLI
        ``--slo-ms`` / env ``TFIDF_TPU_SLO_MS``.
      slo_target: fraction of requests that must meet ``slo_ms``
        (error budget = 1 - target). CLI ``--slo-target`` / env
        ``TFIDF_TPU_SLO_TARGET``.
      delta_docs: delta-segment capacity of the LSM-style segmented
        index (``tfidf_tpu/index``) — serving with this set builds a
        :class:`~tfidf_tpu.index.SegmentedIndex` instead of a
        monolithic retriever, turning the ``add_docs`` /
        ``delete_docs`` JSONL ops on; a full delta seals into an
        immutable segment. None = classic immutable-except-full-swap
        serving. CLI ``--delta-docs`` / env ``TFIDF_TPU_DELTA_DOCS``.
      compact_at: sealed-segment count at which the background
        compactor merges them into one (dropping tombstones). CLI
        ``--compact-at`` / env ``TFIDF_TPU_COMPACT_AT``.
      query_slab: the zero-allocation query hot path (round 19): a
        donated, persistently-recycled device query block per pow2
        bucket fed by a pinned host staging ring, so steady-state
        serving performs zero Python-side array allocations and
        exactly ONE H2D copy per batch (byte-stamped ``h2d`` trace
        spans are the receipt; ``serve_bench --ab-slab`` measures
        it). None resolves the env (``TFIDF_TPU_QUERY_SLAB``,
        default on); False forces the legacy per-batch allocation —
        the bit-identical fallback (one packing implementation,
        ``models.retrieval.fill_query_matrix``). CLI
        ``--query-slab``. Mesh-sharded serving keeps the legacy
        packing either way (its query block replicates under
        shard_map — a different staging contract).
      mesh_shards: serve ONE logical index doc-sharded across this
        many devices (``0`` = every visible device): the resident
        index's BCOO blocks live block-sharded over the mesh's
        ``docs`` axis, queries broadcast to all shards, each shard
        runs the fused score/top-k over its rows and a device-side
        top-k-of-top-k merge rides one collective back — responses
        BIT-identical to single-device serving
        (``tfidf_tpu/parallel/serving.py``). Every index install
        (swap, mutation, restore) re-shards through the same
        transform. None = classic single-device serving. CLI
        ``--mesh-shards`` / env ``TFIDF_TPU_MESH_SHARDS``.
      pipeline_depth: pipelined serve execution (round 22): the
        batcher's bounded in-flight window — up to this many
        dispatched batches overlap with coalescing and with each
        other's drains (one ordered drain worker materializes results
        batch-major), so the device never idles between dispatches.
        1 = the bit-identical legacy path (dispatch and materialize
        one batch at a time, no drain worker). Default 2: one batch
        in flight while the next forms closes the pipeline bubble
        tiling/slab left, and responses stay bit-identical at every
        depth (docs/SERVING.md "Pipelined execution"). CLI
        ``--serve-pipeline-depth`` / env ``TFIDF_TPU_SERVE_PIPELINE``.
      replicas: run the REPLICATED serving tier: N worker processes
        each owning a full :class:`TfidfServer`, behind one in-process
        front that hash-routes queries (cache affinity) and drives
        index visibility changes through a two-phase epoch bump
        (``tfidf_tpu/serve/front.py``; docs/SERVING.md "Replicated
        tier"). Requires ``snapshot_dir`` — replicas boot and restart
        from the shared snapshot. None = classic single-process
        serving. CLI ``--replicas`` / env ``TFIDF_TPU_REPLICAS``.
      replica_timeout_s: how long the front waits for one replica to
        boot to ready (jax import + snapshot restore + warm) or to
        ack a control op before declaring it dead. CLI
        ``--replica-timeout-s`` / env ``TFIDF_TPU_REPLICA_TIMEOUT_S``.
      scorer: default scoring-family member for requests that name
        none (round 23): ``"tfidf"`` (the bit-identical legacy
        default) or ``"bm25"`` / ``"bm25:k1=1.5,b=0.6"``
        (``tfidf_tpu/scoring``). Per-request ``"scorer"`` JSONL
        fields override it. None = tfidf. CLI ``--scorer`` / env
        ``TFIDF_TPU_SCORER``.
      bm25_k1: BM25 term-frequency saturation for the default scorer
        when ``scorer`` is bare ``"bm25"`` (ignored otherwise — an
        inline ``k1=`` in the spec wins). None = 1.2. CLI
        ``--bm25-k1`` / env ``TFIDF_TPU_BM25_K1``.
      bm25_b: BM25 length-normalization strength, same resolution
        rules as ``bm25_k1``. None = 0.75. CLI ``--bm25-b`` / env
        ``TFIDF_TPU_BM25_B``.
      disttrace: fleet-wide distributed tracing (round 23): the
        replicated front mints one ``t<16hex>`` trace id per admitted
        request and propagates it on the data plane (the ``"trace"``
        JSONL field, echoed on responses) and the two-phase control
        plane (``txn_phase`` spans), with a per-replica clock-offset
        handshake so ``tools/trace_merge.py`` renders one aligned
        tier timeline (docs/OBSERVABILITY.md "Trace a slow query
        across the tier"). None resolves the env
        (``TFIDF_TPU_DISTTRACE``, default on); False is the A/B off
        lever ``serve_bench --replicas`` measures propagation
        overhead against. CLI ``--disttrace``.
    """

    max_batch: int = 256
    max_wait_ms: float = 2.0
    queue_depth: int = 256
    cache_entries: int = 4096
    default_deadline_ms: Optional[float] = None
    health_period_ms: Optional[float] = None
    stall_after_ms: float = 1000.0
    degraded_admission_factor: float = 0.5
    devmon_period_ms: Optional[float] = None
    dispatch_retries: int = 2
    retry_backoff_ms: float = 10.0
    breaker_threshold: int = 5
    breaker_cooldown_ms: float = 1000.0
    restart_budget: int = 3
    snapshot_dir: Optional[str] = None
    faults: Optional[str] = None
    fault_seed: int = 0
    slow_ms: Optional[float] = None
    slow_sample: int = 0
    slo_ms: Optional[float] = None
    slo_target: float = 0.99
    delta_docs: Optional[int] = None
    compact_at: int = 4
    mesh_shards: Optional[int] = None
    query_slab: Optional[bool] = None
    pipeline_depth: int = 2
    replicas: Optional[int] = None
    replica_timeout_s: float = 120.0
    scorer: Optional[str] = None
    bm25_k1: Optional[float] = None
    bm25_b: Optional[float] = None
    disttrace: Optional[bool] = None

    def __post_init__(self):
        if self.max_batch < 1:
            raise ValueError("max_batch must be >= 1")
        if self.max_wait_ms < 0:
            raise ValueError("max_wait_ms must be >= 0")
        if self.queue_depth < 1:
            raise ValueError("queue_depth must be >= 1")
        if self.cache_entries < 0:
            raise ValueError("cache_entries must be >= 0")
        if (self.default_deadline_ms is not None
                and self.default_deadline_ms < 0):
            raise ValueError("default_deadline_ms must be >= 0")
        if (self.health_period_ms is not None
                and self.health_period_ms <= 0):
            raise ValueError("health_period_ms must be positive "
                             "(None disables the watchdog thread)")
        if (self.devmon_period_ms is not None
                and self.devmon_period_ms <= 0):
            raise ValueError("devmon_period_ms must be positive "
                             "(None disables the device monitor)")
        if self.stall_after_ms <= 0:
            raise ValueError("stall_after_ms must be positive")
        if not 0 < self.degraded_admission_factor <= 1:
            raise ValueError(
                "degraded_admission_factor must be in (0, 1]")
        if self.dispatch_retries < 0:
            raise ValueError("dispatch_retries must be >= 0")
        if self.retry_backoff_ms < 0:
            raise ValueError("retry_backoff_ms must be >= 0")
        if self.breaker_threshold < 1:
            raise ValueError("breaker_threshold must be >= 1")
        if self.breaker_cooldown_ms <= 0:
            raise ValueError("breaker_cooldown_ms must be positive")
        if self.restart_budget < 0:
            raise ValueError("restart_budget must be >= 0")
        if self.slow_ms is not None and self.slow_ms < 0:
            raise ValueError("slow_ms must be >= 0")
        if self.slow_sample < 0:
            raise ValueError("slow_sample must be >= 0")
        if self.slo_ms is not None and self.slo_ms <= 0:
            raise ValueError("slo_ms must be positive")
        if not 0 < self.slo_target < 1:
            raise ValueError("slo_target must be in (0, 1)")
        if self.delta_docs is not None and self.delta_docs < 1:
            raise ValueError("delta_docs must be >= 1 "
                             "(None disables segmented serving)")
        if self.compact_at < 2:
            raise ValueError("compact_at must be >= 2")
        if self.mesh_shards is not None and self.mesh_shards < 0:
            raise ValueError("mesh_shards must be >= 0 (0 = all "
                             "devices; None disables mesh serving)")
        if self.pipeline_depth < 1:
            raise ValueError("pipeline_depth must be >= 1 "
                             "(1 = unpipelined legacy execution)")
        if self.replicas is not None and self.replicas < 1:
            raise ValueError("replicas must be >= 1 "
                             "(None disables the replicated front)")
        if self.replica_timeout_s <= 0:
            raise ValueError("replica_timeout_s must be positive")
        if self.replicas is not None and not self.snapshot_dir:
            raise ValueError("replicas requires snapshot_dir — the "
                             "replicas spin up from (and restart "
                             "from) the shared snapshot")
        if self.bm25_k1 is not None and self.bm25_k1 < 0:
            raise ValueError("bm25_k1 must be >= 0")
        if self.bm25_b is not None and not 0 <= self.bm25_b <= 1:
            raise ValueError("bm25_b must be in [0, 1]")
        if self.scorer is not None:
            # Validate eagerly (jax-free): a typo'd --scorer fails at
            # config time, not at the first request.
            from tfidf_tpu.scoring.family import spec_from_parts
            spec_from_parts(self.scorer, self.bm25_k1, self.bm25_b)

    @staticmethod
    def from_env(**overrides) -> "ServeConfig":
        """Defaults from the ``TFIDF_TPU_*`` env mirrors, keyword
        overrides winning — the CLI's resolution order (flag > env >
        default)."""
        def pick(key, env, cast):
            if key in overrides and overrides[key] is not None:
                return overrides[key]
            raw = os.environ.get(env)
            return cast(raw) if raw else None
        kw = {}
        for key, env, cast in (
                ("max_batch", "TFIDF_TPU_MAX_BATCH", int),
                ("max_wait_ms", "TFIDF_TPU_MAX_WAIT_MS", float),
                ("queue_depth", "TFIDF_TPU_QUEUE_DEPTH", int),
                ("cache_entries", "TFIDF_TPU_CACHE_ENTRIES", int),
                ("stall_after_ms", "TFIDF_TPU_STALL_AFTER_MS", float),
                ("degraded_admission_factor",
                 "TFIDF_TPU_DEGRADED_FACTOR", float),
                ("dispatch_retries", "TFIDF_TPU_DISPATCH_RETRIES", int),
                ("retry_backoff_ms", "TFIDF_TPU_RETRY_BACKOFF_MS",
                 float),
                ("breaker_threshold", "TFIDF_TPU_BREAKER_THRESHOLD",
                 int),
                ("breaker_cooldown_ms",
                 "TFIDF_TPU_BREAKER_COOLDOWN_MS", float),
                ("restart_budget", "TFIDF_TPU_RESTART_BUDGET", int),
                ("snapshot_dir", "TFIDF_TPU_SNAPSHOT_DIR", str),
                ("faults", "TFIDF_TPU_FAULTS", str),
                ("fault_seed", "TFIDF_TPU_FAULT_SEED", int),
                ("slow_ms", "TFIDF_TPU_SLOW_MS", float),
                ("slow_sample", "TFIDF_TPU_SLOW_SAMPLE", int),
                ("slo_ms", "TFIDF_TPU_SLO_MS", float),
                ("slo_target", "TFIDF_TPU_SLO_TARGET", float),
                ("delta_docs", "TFIDF_TPU_DELTA_DOCS", int),
                ("compact_at", "TFIDF_TPU_COMPACT_AT", int),
                ("mesh_shards", "TFIDF_TPU_MESH_SHARDS", int),
                ("pipeline_depth", "TFIDF_TPU_SERVE_PIPELINE", int),
                ("replicas", "TFIDF_TPU_REPLICAS", int),
                ("replica_timeout_s", "TFIDF_TPU_REPLICA_TIMEOUT_S",
                 float),
                ("scorer", "TFIDF_TPU_SCORER", str),
                ("bm25_k1", "TFIDF_TPU_BM25_K1", float),
                ("bm25_b", "TFIDF_TPU_BM25_B", float),
                ("query_slab", "TFIDF_TPU_QUERY_SLAB",
                 lambda raw: raw.strip().lower() not in
                 ("0", "off", "false", "no")),
                ("disttrace", "TFIDF_TPU_DISTTRACE",
                 lambda raw: raw.strip().lower() not in
                 ("0", "off", "false", "no"))):
            val = pick(key, env, cast)
            if val is not None:
                kw[key] = val
        if overrides.get("default_deadline_ms") is not None:
            kw["default_deadline_ms"] = overrides["default_deadline_ms"]
        # health/devmon periods: an explicit 0 means "thread off"
        # (None), distinct from "not set" (fall through to the env).
        for key, env in (("health_period_ms",
                          "TFIDF_TPU_HEALTH_PERIOD_MS"),
                         ("devmon_period_ms",
                          "TFIDF_TPU_DEVMON_PERIOD_MS")):
            val = overrides.get(key)
            if val is None:
                raw = os.environ.get(env)
                val = float(raw) if raw else None
            if val is not None:
                kw[key] = val if val > 0 else None
        return ServeConfig(**kw)


def apply_compile_cache(path: Optional[str] = None) -> Optional[str]:
    """Point jax's persistent XLA compilation cache at ``path`` (or
    ``TFIDF_TPU_COMPILE_CACHE`` when ``path`` is None) and floor the
    persistence thresholds so EVERY program persists — this pipeline's
    executables are small but numerous (one per wire-shape bucket), and
    jax's defaults would skip most of them as too-fast compiles.

    The entry points that build jitted programs call this with their
    config's ``compile_cache`` (cli, ``ingest.run_overlapped``,
    ``TfidfPipeline``); repeat calls with the same directory are
    no-ops. Returns the resolved directory, or None when caching stays
    off. Threshold knobs missing from older jax versions are skipped
    silently — the cache dir alone already persists the big programs.
    """
    resolved = path or os.environ.get("TFIDF_TPU_COMPILE_CACHE")
    if not resolved:
        return None
    import jax
    os.makedirs(resolved, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", resolved)
    for knob, val in (("jax_persistent_cache_min_compile_time_secs", 0.0),
                      ("jax_persistent_cache_min_entry_size_bytes", -1)):
        try:
            jax.config.update(knob, val)
        except (AttributeError, ValueError):  # older jax: knob absent
            pass
    return resolved
