"""Deterministic fault injection: named seams, typed faults, one plan.

The reference program's failure story is ``exit()`` everywhere (SURVEY
§5); our batch layer earned checkpoint/resume (``checkpoint.py``) and
the serving layer earned *eyes* (health, canary, flight recorder) —
but nothing could *rehearse* a failure. Every robustness test so far
invented its own ad-hoc injection (a monkeypatched search fn, a fake
worker that never beats, a forced watermark), which means the
production code paths that faults traverse were never themselves
exercised. This module is the one injection mechanism for all of them:

* **Seams** — named call sites in the real pipeline that consult the
  registry before doing work: ``device_dispatch`` (the batcher's
  device call, inside the retry loop), ``pack_worker`` / ``drain``
  (the ingest worker jobs), ``batcher_loop`` (the serve batcher's
  supervision loop), ``swap`` (``TfidfServer.swap_index``). A seam
  check costs one global load + ``is None`` test when no plan is
  armed — the tracer/health hot-path discipline.
* **Typed faults** — :class:`TransientFault` (retryable: the
  supervisor's retry/backoff path must absorb it) and
  :class:`FatalFault` (not retryable: dispatch bisection / worker
  restart budgets must contain it). Both subclass
  :class:`InjectedFault`; nothing outside a test or chaos run should
  ever catch the base class.
* **One plan, armed from a spec + seed** — ``TFIDF_TPU_FAULTS`` (or
  ``ServeConfig.faults`` / ``tools/serve_bench.py --chaos``) parses
  into :class:`FaultPlan` rules; randomness (``p=``) draws from a
  ``random.Random(seed)`` per rule, so a chaos run is replayable
  bit-for-bit.

Spec grammar (rules joined by ``;``, fields by ``:``)::

    seam:kind[:key=val[:key=val...]]

    device_dispatch:transient:n=2      # first 2 checks raise, then pass
    device_dispatch:fatal:match=zzz    # every batch containing "zzz"
    pack_worker:transient:at=2         # fire on the 2nd check only
    batcher_loop:fatal:n=1             # kill the loop once
    swap:transient:p=0.5               # coin-flip (seeded)
    batcher_loop:sleep:s=0.4           # stall the seam, don't raise

Keys: ``n`` max fires (default 1; ``match`` rules default unlimited —
a poison query stays poison), ``at`` first firing check (1-based),
``p`` per-check probability, ``match`` substring the seam's text must
contain (the poison-query selector), ``s`` sleep seconds for the
``sleep`` kind. Every firing logs a ``fault_injected`` flight event
and counts in :meth:`FaultRegistry.snapshot` — the chaos artifact's
receipts.

Stdlib-only (no jax): importable by tools and the ingest/serve layers
alike without a backend.
"""

from __future__ import annotations

import random
import threading
import time
from typing import Dict, List, Optional

__all__ = [
    "InjectedFault", "TransientFault", "FatalFault",
    "FaultRule", "FaultPlan", "FaultRegistry",
    "get_registry", "arm", "disarm", "fire", "configure", "backoff_s",
    "SEAMS",
]

SEAMS = ("device_dispatch", "drain", "pack_worker", "batcher_loop",
         "swap", "replica_prepare")
_KINDS = ("transient", "fatal", "sleep")


class InjectedFault(RuntimeError):
    """Base class of registry-raised faults. Carries the seam name."""

    def __init__(self, msg: str, seam: str = "?"):
        super().__init__(msg)
        self.seam = seam


class TransientFault(InjectedFault):
    """A retryable injected failure — the supervisor's retry/backoff
    path is expected to absorb it."""


class FatalFault(InjectedFault):
    """A non-retryable injected failure — bisection / restart budgets
    must contain it, retries must not."""


class FaultRule:
    """One armed rule: fires at a seam under its trigger conditions.

    State (``checked``/``fired``) advances only on matching checks, so
    ``at=``/``n=`` count what the rule could have hit, which keeps a
    plan deterministic regardless of unrelated traffic at the seam.
    """

    __slots__ = ("seam", "kind", "n", "at", "p", "match", "sleep_s",
                 "checked", "fired", "_rng", "spec")

    def __init__(self, seam: str, kind: str, n: Optional[int] = None,
                 at: int = 1, p: float = 1.0,
                 match: Optional[str] = None, sleep_s: float = 0.0,
                 seed: int = 0, spec: str = ""):
        if seam not in SEAMS:
            raise ValueError(f"unknown fault seam {seam!r} "
                             f"(choose from {SEAMS})")
        if kind not in _KINDS:
            raise ValueError(f"unknown fault kind {kind!r} "
                             f"(choose from {_KINDS})")
        if at < 1:
            raise ValueError("at= must be >= 1")
        if not 0.0 < p <= 1.0:
            raise ValueError("p= must be in (0, 1]")
        self.seam = seam
        self.kind = kind
        # match-rules model a poison input: poison stays poison, so
        # their fire budget defaults to unlimited (-1).
        self.n = (-1 if match is not None else 1) if n is None else n
        self.at = at
        self.p = p
        self.match = match
        self.sleep_s = sleep_s
        self.checked = 0
        self.fired = 0
        self._rng = random.Random(f"{seed}:{seam}:{kind}:{match}:{at}")
        self.spec = spec or f"{seam}:{kind}"

    def should_fire(self, text: Optional[str]) -> bool:
        if self.match is not None and (text is None
                                       or self.match not in text):
            return False
        self.checked += 1
        if self.checked < self.at:
            return False
        if self.n >= 0 and self.fired >= self.n:
            return False
        if self.p < 1.0 and self._rng.random() >= self.p:
            return False
        self.fired += 1
        return True


class FaultPlan:
    """A parsed set of :class:`FaultRule` — what one chaos run arms."""

    def __init__(self, rules: List[FaultRule], spec: str = "",
                 seed: int = 0):
        self.rules = rules
        self.spec = spec
        self.seed = seed

    @staticmethod
    def parse(spec: str, seed: int = 0) -> "FaultPlan":
        rules: List[FaultRule] = []
        for part in spec.split(";"):
            part = part.strip()
            if not part:
                continue
            fields = part.split(":")
            if len(fields) < 2:
                raise ValueError(
                    f"bad fault rule {part!r}: want seam:kind[:k=v...]")
            seam, kind = fields[0].strip(), fields[1].strip()
            kw: dict = {}
            for field in fields[2:]:
                key, sep, val = field.partition("=")
                if not sep:
                    raise ValueError(f"bad fault rule field {field!r} "
                                     f"in {part!r} (want key=value)")
                key = key.strip()
                val = val.strip()
                if key == "n":
                    kw["n"] = int(val)
                elif key == "at":
                    kw["at"] = int(val)
                elif key == "p":
                    kw["p"] = float(val)
                elif key == "match":
                    kw["match"] = val
                elif key == "s":
                    kw["sleep_s"] = float(val)
                else:
                    raise ValueError(
                        f"unknown fault rule key {key!r} in {part!r}")
            rules.append(FaultRule(seam, kind, seed=seed, spec=part,
                                   **kw))
        if not rules:
            raise ValueError(f"fault plan {spec!r} parsed to no rules")
        return FaultPlan(rules, spec=spec, seed=seed)

    def rules_for(self, seam: str) -> List[FaultRule]:
        return [r for r in self.rules if r.seam == seam]


class FaultRegistry:
    """Holds the armed plan and fires it at seam checks.

    One registry per process (module singleton below): the seams live
    in worker threads spread across ingest and serve, and a chaos run
    arms them all with one call.
    """

    def __init__(self) -> None:
        self._plan: Optional[FaultPlan] = None
        self._lock = threading.Lock()

    @property
    def armed(self) -> bool:
        return self._plan is not None

    @property
    def plan(self) -> Optional[FaultPlan]:
        return self._plan

    def arm(self, plan: FaultPlan) -> "FaultRegistry":
        self._plan = plan
        return self

    def disarm(self) -> None:
        self._plan = None

    def fire(self, seam: str, text: Optional[str] = None,
             **info) -> None:
        """The seam check: no-op unless an armed rule triggers, else
        raises the rule's typed fault (or sleeps, for ``sleep``
        rules). ``text`` is the seam's match surface — e.g. the
        coalesced batch's query text at ``device_dispatch``."""
        plan = self._plan
        if plan is None:
            return
        with self._lock:
            due = [r for r in plan.rules_for(seam)
                   if r.should_fire(text)]
        for rule in due:
            from tfidf_tpu.obs import log as obs_log
            obs_log.log_event(
                "warning", "fault_injected",
                msg=f"fault injected at {seam}: {rule.spec} "
                    f"(firing {rule.fired})",
                # fault_kind, not "kind": the flight-dump protocol
                # reserves "kind" as its event/digest discriminator
                # (obs/log.py dump) — a payload field named "kind"
                # would clobber it and tear every dump that carries a
                # fault event.
                seam=seam, fault_kind=rule.kind, rule=rule.spec,
                firing=rule.fired, **info)
            if rule.kind == "sleep":
                time.sleep(rule.sleep_s)
                continue
            cls = TransientFault if rule.kind == "transient" else FatalFault
            raise cls(f"injected {rule.kind} fault at seam "
                      f"{seam!r} ({rule.spec}, firing {rule.fired})",
                      seam=seam)

    def snapshot(self) -> Dict[str, dict]:
        """Per-rule receipts: checks seen, faults fired."""
        plan = self._plan
        if plan is None:
            return {}
        with self._lock:
            return {r.spec: {"seam": r.seam, "kind": r.kind,
                             "checked": r.checked, "fired": r.fired}
                    for r in plan.rules}


# --- module-level singleton -----------------------------------------
#
# Product seams call faults.fire(...); disabled cost is one global
# load + None test (the same discipline as obs.health.beat).

_registry = FaultRegistry()


def get_registry() -> FaultRegistry:
    return _registry


def arm(plan: FaultPlan) -> FaultRegistry:
    return _registry.arm(plan)


def disarm() -> None:
    _registry.disarm()


def fire(seam: str, text: Optional[str] = None, **info) -> None:
    if _registry._plan is not None:
        _registry.fire(seam, text=text, **info)


def configure(spec: Optional[str] = None,
              seed: Optional[int] = None) -> Optional[FaultPlan]:
    """Arm from an explicit spec or the ``TFIDF_TPU_FAULTS`` /
    ``TFIDF_TPU_FAULT_SEED`` env mirrors; no-op (returns None) when
    neither names a plan."""
    import os
    resolved = spec or os.environ.get("TFIDF_TPU_FAULTS")
    if not resolved:
        return None
    if seed is None:
        seed = int(os.environ.get("TFIDF_TPU_FAULT_SEED", "0"))
    plan = FaultPlan.parse(resolved, seed=seed)
    _registry.arm(plan)
    return plan


def backoff_s(attempt: int, base_ms: float = 10.0, mult: float = 2.0,
              cap_ms: float = 1000.0, jitter: float = 0.5,
              rng: Optional[random.Random] = None) -> float:
    """Jittered exponential backoff: ``base * mult^(attempt-1)`` capped
    at ``cap``, +- ``jitter`` fraction drawn from ``rng`` (deterministic
    when the caller seeds it). Shared by the dispatch retry loop and
    the worker restart paths so every backoff in the system has the
    same shape."""
    if attempt < 1:
        attempt = 1
    delay = min(cap_ms, base_ms * (mult ** (attempt - 1))) / 1e3
    if jitter > 0.0:
        r = rng.random() if rng is not None else random.random()
        delay *= 1.0 + jitter * (2.0 * r - 1.0)
    return max(0.0, delay)
