"""Phase wall-clock timing, throughput counters, and profiler regions."""

from __future__ import annotations

import contextlib
import time
from typing import Dict, Iterator, List, Optional, Tuple


class PhaseTimer:
    """Accumulates wall-clock per named phase.

    The reference's phases are implicit between ``MPI_Barrier``s with no
    timing (SURVEY §6: no timing calls anywhere). Usage::

        timer = PhaseTimer()
        with timer.phase("pack"):
            batch = pipe.pack(corpus)
        with timer.phase("device"):
            result = pipe.run_packed(batch)
        print(timer.report())
    """

    def __init__(self) -> None:
        self._acc: Dict[str, float] = {}
        self._order: List[str] = []

    @contextlib.contextmanager
    def phase(self, name: str) -> Iterator[None]:
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.add(name, time.perf_counter() - t0)

    def add(self, name: str, seconds: float) -> None:
        """Fold an externally-measured duration in (e.g. the phase dict
        an :class:`~tfidf_tpu.ingest.IngestResult` carries)."""
        if name not in self._acc:
            self._order.append(name)
            self._acc[name] = 0.0
        self._acc[name] += seconds

    def seconds(self, name: str) -> float:
        return self._acc.get(name, 0.0)

    def items(self) -> List[Tuple[str, float]]:
        return [(n, self._acc[n]) for n in self._order]

    def as_dict(self, ndigits: int = 3) -> Dict[str, float]:
        """Rounded phase dict — bench/JSON artifact form."""
        return {n: round(s, ndigits) for n, s in self.items()}

    def reset(self) -> None:
        self._acc.clear()
        self._order.clear()

    def report(self) -> str:
        total = sum(self._acc.values()) or 1.0
        rows = [f"{n:>12}: {s * 1e3:9.1f} ms ({100 * s / total:4.1f}%)"
                for n, s in self.items()]
        return "\n".join(rows)


class Throughput:
    """docs/sec counter — the north-star metric (BASELINE.json)."""

    def __init__(self) -> None:
        self._docs = 0
        self._seconds = 0.0

    @contextlib.contextmanager
    def measure(self, num_docs: int) -> Iterator[None]:
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.record(num_docs, time.perf_counter() - t0)

    def record(self, num_docs: int, seconds: float) -> None:
        """Fold an externally-measured run in (doc count unknown until
        the run returns, e.g. overlapped ingest discovery)."""
        self._docs += num_docs
        self._seconds += seconds

    @property
    def docs_per_sec(self) -> float:
        return self._docs / self._seconds if self._seconds else 0.0

    @property
    def docs(self) -> int:
        return self._docs


def phase_or_null(timer: Optional["PhaseTimer"], name: str):
    """``timer.phase(name)`` when a timer is attached, else a no-op.

    Lets product code sprinkle phase markers unconditionally; without a
    timer the only cost is a nullcontext enter/exit.
    """
    return timer.phase(name) if timer is not None else contextlib.nullcontext()


class PhaseTimedMixin:
    """Shared phase/fence plumbing for pipeline classes with a ``timer``.

    ``_phase`` marks a named phase on the attached :class:`PhaseTimer`
    (no-op without one); ``_fence`` blocks on device work only when
    timing, so phases measure completion, not dispatch — and untimed
    runs keep XLA's async overlap.
    """

    timer: Optional["PhaseTimer"] = None

    def _phase(self, name: str):
        return phase_or_null(self.timer, name)

    def _fence(self, tree) -> None:
        if self.timer is not None:
            import jax
            jax.block_until_ready(tree)


@contextlib.contextmanager
def trace_region(name: str, enabled: bool = True) -> Iterator[None]:
    """jax.profiler TraceAnnotation wrapper (no-op when disabled).

    Regions named here show up on the TPU timeline in a
    ``jax.profiler.trace`` capture — the replacement for the reference's
    debug printf stage markers (``TFIDF.c:200,237``).
    """
    if not enabled:
        yield
        return
    import jax.profiler
    with jax.profiler.TraceAnnotation(name):
        yield
