"""Phase wall-clock timing, throughput counters, and profiler regions."""

from __future__ import annotations

import contextlib
import math
import time
from typing import Dict, Iterator, List, Optional, Tuple


class PhaseTimer:
    """Accumulates wall-clock per named phase.

    The reference's phases are implicit between ``MPI_Barrier``s with no
    timing (SURVEY §6: no timing calls anywhere). Usage::

        timer = PhaseTimer()
        with timer.phase("pack"):
            batch = pipe.pack(corpus)
        with timer.phase("device"):
            result = pipe.run_packed(batch)
        print(timer.report())
    """

    def __init__(self) -> None:
        self._acc: Dict[str, float] = {}
        self._order: List[str] = []

    @contextlib.contextmanager
    def phase(self, name: str) -> Iterator[None]:
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.add(name, time.perf_counter() - t0)

    def add(self, name: str, seconds: float) -> None:
        """Fold an externally-measured duration in (e.g. the phase dict
        an :class:`~tfidf_tpu.ingest.IngestResult` carries)."""
        if name not in self._acc:
            self._order.append(name)
            self._acc[name] = 0.0
        self._acc[name] += seconds

    def seconds(self, name: str) -> float:
        return self._acc.get(name, 0.0)

    def items(self) -> List[Tuple[str, float]]:
        return [(n, self._acc[n]) for n in self._order]

    def as_dict(self, ndigits: int = 3) -> Dict[str, float]:
        """Rounded phase dict — bench/JSON artifact form."""
        return {n: round(s, ndigits) for n, s in self.items()}

    def reset(self) -> None:
        self._acc.clear()
        self._order.clear()

    def report(self) -> str:
        total = sum(self._acc.values()) or 1.0
        rows = [f"{n:>12}: {s * 1e3:9.1f} ms ({100 * s / total:4.1f}%)"
                for n, s in self.items()]
        return "\n".join(rows)


class Throughput:
    """docs/sec counter — the north-star metric (BASELINE.json)."""

    def __init__(self) -> None:
        self._docs = 0
        self._seconds = 0.0

    @contextlib.contextmanager
    def measure(self, num_docs: int) -> Iterator[None]:
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.record(num_docs, time.perf_counter() - t0)

    def record(self, num_docs: int, seconds: float) -> None:
        """Fold an externally-measured run in (doc count unknown until
        the run returns, e.g. overlapped ingest discovery)."""
        self._docs += num_docs
        self._seconds += seconds

    @property
    def docs_per_sec(self) -> float:
        return self._docs / self._seconds if self._seconds else 0.0

    @property
    def docs(self) -> int:
        return self._docs


class LatencyHistogram:
    """Geometric-bucket latency histogram with percentile queries.

    Samples land in buckets whose bounds grow by ``1 + resolution``
    per step (default 2%), so ``percentile(p)`` is accurate to the
    bucket resolution over the whole [lo, hi) range at O(1) memory —
    the shape a long-running server needs (the serving layer records
    every request into one of these; ``serve/metrics.py``). Count,
    sum, min and max are tracked exactly; out-of-range samples clamp
    into the edge buckets but still carry exact min/max.

    Not thread-safe by itself; :class:`~tfidf_tpu.serve.metrics.
    ServeMetrics` serializes access under its own lock.
    """

    def __init__(self, lo: float = 1e-6, hi: float = 1e3,
                 resolution: float = 0.02,
                 exemplars: bool = False) -> None:
        if not (0 < lo < hi):
            raise ValueError("need 0 < lo < hi")
        if resolution <= 0:
            raise ValueError("resolution must be positive")
        self._lo = lo
        self._hi = hi
        self._resolution = resolution
        self._log_step = math.log1p(resolution)
        n = int(math.ceil(math.log(hi / lo) / self._log_step)) + 1
        self._counts = [0] * (n + 1)  # +1: underflow bucket at index 0
        self._count = 0
        self._sum = 0.0
        self._min = math.inf
        self._max = -math.inf
        # Exemplars (round 16): the LAST request id to land in each
        # bucket, kept as {bucket_idx: (exemplar, seconds)} — O(live
        # buckets) memory, and what links "p99 got worse" to one
        # replayable trace (OpenMetrics exemplar exposition in
        # obs/registry.py). None = feature off (zero cost).
        self._exemplars: Optional[Dict[int, Tuple[str, float]]] = (
            {} if exemplars else None)

    def record(self, seconds: float,
               exemplar: Optional[str] = None) -> None:
        if seconds < 0:
            seconds = 0.0
        self._count += 1
        self._sum += seconds
        self._min = min(self._min, seconds)
        self._max = max(self._max, seconds)
        if seconds < self._lo:
            idx = 0
        else:
            idx = 1 + int(math.log(seconds / self._lo) / self._log_step)
            idx = min(idx, len(self._counts) - 1)
        self._counts[idx] += 1
        if self._exemplars is not None and exemplar is not None:
            self._exemplars[idx] = (exemplar, seconds)

    @property
    def count(self) -> int:
        return self._count

    @property
    def sum_seconds(self) -> float:
        """Exact sum of every recorded sample (Prometheus ``_sum``)."""
        return self._sum

    @property
    def mean(self) -> float:
        return self._sum / self._count if self._count else 0.0

    @property
    def min(self) -> float:
        return self._min if self._count else 0.0

    @property
    def max(self) -> float:
        return self._max if self._count else 0.0

    def percentile(self, p: float) -> float:
        """Latency at percentile ``p`` in [0, 100] (nearest-rank over
        buckets; within-bucket values report the bucket's geometric
        midpoint, clamped to the exact observed min/max)."""
        if not 0 <= p <= 100:
            raise ValueError(f"percentile {p} outside [0, 100]")
        if not self._count:
            return 0.0
        rank = max(1, int(math.ceil(p / 100.0 * self._count)))
        # The extreme ranks are tracked exactly — no bucket rounding.
        if rank <= 1:
            return self._min
        if rank >= self._count:
            return self._max
        seen = 0
        for idx, c in enumerate(self._counts):
            seen += c
            if seen >= rank:
                if idx == 0:
                    mid = self._lo / 2
                else:
                    mid = self._lo * math.exp((idx - 0.5) * self._log_step)
                return min(max(mid, self._min), self._max)
        return self._max  # unreachable: ranks are <= count

    def cumulative(self, bounds: List[float]) -> List[int]:
        """Cumulative sample counts at each upper bound — the shape a
        Prometheus histogram exposition needs (``le`` buckets). A
        sample counts toward bound ``b`` when its geometric bucket's
        upper edge is <= ``b``, so counts are monotone in ``bounds``
        and accurate to the bucket resolution; the clamped top bucket
        (and the exact total) only ever land on ``+Inf``, which the
        caller appends itself (``obs.registry``)."""
        uppers = [self._lo * math.exp(i * self._log_step)
                  for i in range(len(self._counts) - 1)]
        out = []
        for b in bounds:
            out.append(sum(c for up, c in zip(uppers, self._counts)
                           if up <= b))
        return out

    def merge(self, other: "LatencyHistogram") -> "LatencyHistogram":
        """Fold ``other``'s samples into this histogram in place — the
        aggregation primitive per-replica metrics need (ROADMAP item 5:
        N server processes each keep their own histogram; a front
        merges them into one distribution) and what lets the perf gate
        pool samples across runs. Exact for count/sum/min/max; bucket
        counts add elementwise, so percentiles of the merge are as
        accurate as either input's bucket resolution. Requires
        identical bucket geometry (same lo/resolution/range) — merging
        across geometries would need resampling, which silently loses
        resolution, so it raises instead. Returns ``self``."""
        if (self._lo != other._lo
                or self._log_step != other._log_step
                or len(self._counts) != len(other._counts)):
            raise ValueError(
                "cannot merge LatencyHistograms with different bucket "
                f"geometry (lo {self._lo} vs {other._lo}, step "
                f"{self._log_step:.6g} vs {other._log_step:.6g}, "
                f"buckets {len(self._counts)} vs {len(other._counts)})")
        for i, c in enumerate(other._counts):
            self._counts[i] += c
        self._count += other._count
        self._sum += other._sum
        self._min = min(self._min, other._min)
        self._max = max(self._max, other._max)
        # Exemplars survive aggregation: the other side's (newer, in
        # the replica-poll sense) entries win per bucket — one
        # replayable rid per bucket is the contract, not a history.
        if self._exemplars is not None and other._exemplars:
            self._exemplars.update(other._exemplars)
        return self

    def exemplars(self) -> List[Tuple[float, str]]:
        """``(seconds, rid)`` per live exemplar bucket, ascending by
        latency — empty when the feature is off."""
        if not self._exemplars:
            return []
        return sorted((secs, rid)
                      for rid, secs in self._exemplars.values())

    def state_dict(self) -> Dict:
        """Wire-format state for cross-process aggregation (the
        ``obs_export`` bundle): geometry + sparse bucket counts +
        exact count/sum/min/max + exemplars. :meth:`from_state`
        rebuilds an identical histogram, so ``merge`` federates
        replicas without sharing memory."""
        state = {
            "lo": self._lo, "hi": self._hi,
            "resolution": self._resolution,
            "n_buckets": len(self._counts),
            "counts": {str(i): c for i, c in enumerate(self._counts)
                       if c},
            "count": self._count, "sum": self._sum,
        }
        if self._count:
            state["min"] = self._min
            state["max"] = self._max
        if self._exemplars:
            state["exemplars"] = {
                str(i): [rid, secs]
                for i, (rid, secs) in self._exemplars.items()}
        return state

    @classmethod
    def from_state(cls, state: Dict) -> "LatencyHistogram":
        h = cls(lo=state["lo"], hi=state["hi"],
                resolution=state["resolution"],
                exemplars="exemplars" in state)
        if len(h._counts) != state["n_buckets"]:
            raise ValueError(
                f"histogram state geometry mismatch: rebuilt "
                f"{len(h._counts)} buckets, state carries "
                f"{state['n_buckets']}")
        for i, c in state.get("counts", {}).items():
            h._counts[int(i)] = int(c)
        h._count = int(state["count"])
        h._sum = float(state["sum"])
        if h._count:
            h._min = float(state["min"])
            h._max = float(state["max"])
        for i, (rid, secs) in state.get("exemplars", {}).items():
            h._exemplars[int(i)] = (rid, float(secs))
        return h

    def as_dict(self, ndigits: int = 6) -> Dict[str, float]:
        """JSON-artifact form: count/mean/min/max plus p50/p95/p99."""
        return {
            "count": self._count,
            "mean": round(self.mean, ndigits),
            "min": round(self.min, ndigits),
            "max": round(self.max, ndigits),
            "p50": round(self.percentile(50), ndigits),
            "p95": round(self.percentile(95), ndigits),
            "p99": round(self.percentile(99), ndigits),
        }

    def reset(self) -> None:
        self._counts = [0] * len(self._counts)
        self._count = 0
        self._sum = 0.0
        self._min = math.inf
        self._max = -math.inf
        if self._exemplars is not None:
            self._exemplars.clear()


class _TimedSpan:
    """One context, two sinks: the phase's wall-clock accumulates into
    the :class:`PhaseTimer` AND the same interval records as a tracer
    span — so ``--timing`` phase reports and ``--trace`` timelines can
    never drift apart (they are one measurement)."""

    __slots__ = ("_timer", "_name", "_sp", "_t0")

    def __init__(self, timer, name, sp):
        self._timer = timer
        self._name = name
        self._sp = sp

    def __enter__(self):
        self._sp.__enter__()
        if self._timer is not None:
            self._t0 = time.perf_counter()
        return self

    def __exit__(self, et, ev, tb):
        if self._timer is not None:
            self._timer.add(self._name, time.perf_counter() - self._t0)
        return self._sp.__exit__(et, ev, tb)


def phase_or_null(timer: Optional["PhaseTimer"], name: str):
    """``timer.phase(name)`` when a timer is attached, a tracer span
    when the global tracer is armed (``obs.configure``), both when
    both — else a no-op.

    Lets product code sprinkle phase markers unconditionally; with
    neither sink armed the only cost is one enabled-check and a shared
    no-op context enter/exit.
    """
    from tfidf_tpu import obs
    if obs.enabled():
        return _TimedSpan(timer, name, obs.span(name))
    return timer.phase(name) if timer is not None else contextlib.nullcontext()


class PhaseTimedMixin:
    """Shared phase/fence plumbing for pipeline classes with a ``timer``.

    ``_phase`` marks a named phase on the attached :class:`PhaseTimer`
    (no-op without one); ``_fence`` blocks on device work only when
    timing, so phases measure completion, not dispatch — and untimed
    runs keep XLA's async overlap.
    """

    timer: Optional["PhaseTimer"] = None

    def _phase(self, name: str):
        return phase_or_null(self.timer, name)

    def _fence(self, tree) -> None:
        if self.timer is not None:
            import jax
            jax.block_until_ready(tree)


@contextlib.contextmanager
def trace_region(name: str, enabled: bool = True) -> Iterator[None]:
    """jax.profiler TraceAnnotation wrapper (no-op when disabled).

    Regions named here show up on the TPU timeline in a
    ``jax.profiler.trace`` capture — the replacement for the reference's
    debug printf stage markers (``TFIDF.c:200,237``).
    """
    if not enabled:
        yield
        return
    import jax.profiler
    with jax.profiler.TraceAnnotation(name):
        yield
