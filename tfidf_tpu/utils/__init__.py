"""Observability utilities: phase timing, throughput, profiler hooks.

The reference has no tracing/metrics at all — stage ``printf``s were its
only observability (SURVEY §5, ``TFIDF.c:200,237``). Here every pipeline
phase can be timed (:class:`PhaseTimer`), throughput is first-class
(docs/sec — it IS the north-star metric), and ``jax.profiler`` traces
can wrap any region for TPU timeline inspection.
"""

from tfidf_tpu.utils.timing import (LatencyHistogram, PhaseTimer,
                                    Throughput, trace_region)

__all__ = ["LatencyHistogram", "PhaseTimer", "Throughput", "trace_region"]
