"""Command-line driver: ``tfidf run --input DIR --backend {tpu,mpi}``.

The reference ignores ``argc/argv`` entirely and hardcodes its input dir,
output path, and limits as ``#define``s (``TFIDF.c:16-20,52,101,133,274``).
This driver exposes every knob, per the BASELINE north star: the MPI-
semantics native path stays available as ``--backend=mpi`` (the oracle),
the TPU path is ``--backend=tpu``.
"""

from __future__ import annotations

import argparse
import os
import subprocess
import sys
import time
from typing import List, Optional

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
NATIVE_BIN = os.path.join(REPO_ROOT, "native", "tfidf_ref")


# --help epilog of the run subcommand: the --inspect-style quick map
# of the round-8 dispatch/compile knobs (full table: docs/CONFIG.md).
_RUN_EPILOG = """\
dispatch & compile knobs (round 8):
  --finish scan|chunked   packed-wire phase-B finish: 'scan' (default)
                          scores every resident chunk (and the
                          streaming triple-cache prefix) in ONE
                          donated lax.scan dispatch — no per-chunk
                          launch tax; 'chunked' keeps the round-7
                          per-chunk dispatches with the interleaved
                          async drain (bit-identical fallback). Runs
                          on the pair result wire ignore it (their
                          fused finish is already one dispatch).
                          Env: TFIDF_TPU_FINISH
  --compile-cache DIR     persistent XLA compilation cache: repeat
                          runs at the same (bucketed) wire shapes
                          load executables from DIR instead of
                          re-paying cold-start compiles.
                          Env: TFIDF_TPU_COMPILE_CACHE
  TFIDF_TPU_SCORE         xla|pallas — phase-B score+top-k lowering
                          (pallas = the fused Mosaic kernel, A/B
                          probe; ids bit-exact either way)

wire & pack knobs (round 14):
  --wire bytes            ship RAW document bytes; tokenize+hash ON
                          DEVICE (ids bit-identical to the host
                          packers; host pack becomes read+memcpy).
                          Degrades bytes->ragged->padded when the
                          device tokenizer cannot carry the run.
                          Env: TFIDF_TPU_WIRE
  --pack-threads N        native host packer thread count (default
                          every core) — threads the ragged fill's
                          per-doc tokenize+hash loop (the reference's
                          OpenMP move, race-free, bit-identical).
                          Env: TFIDF_TPU_PACK_THREADS
  TFIDF_TPU_DEVICE_TOKENIZE  xla|pallas — bytes-wire hash lowering
                          (pallas = Mosaic doc-tile kernel, A/B probe)

link knobs (round 19):
  --ingest-workers N      multi-PROCESS sharded ingest: N workers
                          rendezvous over mpi_lite-style channels,
                          each packs+uploads its contiguous shard
                          over its own link; one [V] DF allreduce
                          merges — index bit-identical to a single
                          process, upload wall divided by N.
                          Env: TFIDF_TPU_INGEST_WORKERS
"""


# --help epilog of the serve subcommand: the JSONL wire protocol.
_SERVE_EPILOG = """\
protocol (one JSON object per line):
  {"id": 1, "queries": ["apple pie"], "k": 5}
      -> {"id": 1, "results": [[["doc3", 0.81], ...]], "rid": "r..-1"}
      ("rid" is the request's end-to-end forensic id: the same key is
      stamped on its spans, its flight digest and any slow_query
      event — tools/doctor.py --request RID renders the timeline)
  {"id": 2, "queries": [...], "deadline_ms": 50}
      -> {"id": 2, "error": "deadline_exceeded"} when shed
  {"id": 3, "queries": [...], "scorer": "bm25:k1=1.5,b=0.6",
   "filter": {"prefix": "tenant-a/"}}
      -> per-request scoring-family member + candidate filter
      (scorer: "tfidf" | "bm25" | "bm25:k1=...,b=..." | {"kind": ...};
      filter: {"ids": [...]} row ids | {"id_range": [lo, hi)} |
      {"prefix": "..."} on doc names; omitted = the server default
      scorer, unfiltered. Requests only batch with same-scorer /
      same-filter peers; cache rows key on both)
  {"op": "set_scorer", "scorer": "bm25"}
      -> {"scorer": "bm25:b=0.75,k1=1.2", "epoch": N}  (change the
      DEFAULT scorer live: epoch bump + cache clear + canary oracle
      re-capture under the new default — a scorer change is a
      visibility change)
  {"op": "metrics"}            -> {"metrics": {...}}  (SLO snapshot —
      the "slo" object carries windowed objective compliance and
      fast/slow burn rates when --slo-ms is set — plus uptime_s /
      epoch / build fingerprint — self-describing for the perf
      ledger, tools/perf_ledger.py)
  {"op": "metrics_prom"}       -> {"metrics_prom": "..."}  (Prometheus
      text exposition incl. request-latency histogram buckets)
  {"op": "healthz"}            -> {"healthz": {"status": "ok" |
      "degraded" | "unhealthy", "reasons": [...], "checks": {...},
      "admission_bound": N}}  (one watchdog evaluation; the bound
      shrinks below queue_depth while degraded)
  {"op": "readyz"}             -> {"readyz": {"ready": true, ...}}
  {"op": "canary"}             -> {"canary": {"parity": 1.0}}  (one
      parity probe vs the swap-time oracle; "skipped": true when shed
      under load or raced by a swap)
  {"op": "devmon"}             -> {"devmon": {"devices": [...],
      "memory_pressure": 0.12, "census": {...}}}  (one device-monitor
      sample + live-buffer census by owner; device entries carry HBM
      stats only on backends that report them)
  {"op": "obs_export"}         -> {"obs_export": {"schema":
      "tfidf-obs/1", "registry": {...}, "flight_tail": [...], ...}}
      (the cross-process federation bundle: full metric state incl.
      histogram buckets + exemplars; tools/obs_agg.py polls N serve
      processes and renders one merged Prometheus/JSON view)
  {"op": "swap_index", "input": DIR}
      -> {"swapped": true, "epoch": N}  (hot re-index, no downtime;
      the canary oracle re-captures inside the swap; with
      --snapshot-dir the NEW epoch is snapshotted before the flip)
  {"op": "snapshot"}           -> {"snapshot": DIR, "epoch": N}
      (persist the resident index now; needs --snapshot-dir)
  {"op": "add_docs", "docs": [{"name": N, "text": T}, ...]}
      -> {"added": 2, "updated": 1, "sealed": 0, "epoch": N}
      (live mutation — needs --delta-docs; an existing name updates in
      place; the new epoch is visible before the response line)
  {"op": "delete_docs", "names": [N, ...]}
      -> {"deleted": 1, "missing": 0, "epoch": N}
      (tombstone by name; a deleted doc can never be served again,
      cached or not — the epoch bump invalidates the cache)
  {"op": "shutdown"}           -> drains in-flight work and exits
overload responses carry {"error": "overloaded"}; back off and retry.
quarantined queries answer {"error": "poison_query"} — the request
named a query isolated as poison by dispatch bisection (4xx: do not
retry it).
"""


def _build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="tfidf", description=__doc__)
    sub = p.add_subparsers(dest="cmd", required=True)
    run = sub.add_parser(
        "run", help="run the TF-IDF pipeline", epilog=_RUN_EPILOG,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    run.add_argument("--input", required=True, help="document directory")
    run.add_argument("--output", default="output.txt",
                     help="output file (reference format)")
    run.add_argument("--backend", choices=["tpu", "mpi"], default="tpu")
    run.add_argument("--engine", choices=["dense", "sparse"], default=None,
                     help="dense [D,V] histograms or row-sparse O(D*L); "
                          "default: sparse for hashed vocab, dense for "
                          "exact (measured choice, docs/ENGINES.md)")
    run.add_argument("--pallas", action="store_true",
                     help="use the Pallas TPU histogram kernel")
    run.add_argument("--vocab-mode", choices=["exact", "hashed"],
                     default="exact")
    run.add_argument("--vocab-size", type=int, default=1 << 16,
                     help="hashed vocabulary size")
    run.add_argument("--tokenizer", choices=["whitespace", "chargram"],
                     default="whitespace")
    run.add_argument("--ngram", type=str, default="3,5",
                     help="chargram n range, e.g. 3,5")
    run.add_argument("--topk", type=int, default=None,
                     help="emit only top-k terms per document")
    run.add_argument("--doc-len", type=int, default=None,
                     help="static tokens per document: opts hashed top-k "
                          "runs into the overlapped chunked ingest (native "
                          "loader, flat memory in corpus size — the bench "
                          "pipeline). Trades: docs longer than this are "
                          "truncated, and terms emit as id:N (no host "
                          "word materialization; combine with "
                          "--exact-terms for real words). Default: no "
                          "truncation, whole-corpus batch path")
    run.add_argument("--chunk-docs", type=int, default=None,
                     help="documents per ingest chunk "
                          "(--doc-len runs; default 8192)")
    run.add_argument("--spill", choices=["auto", "host", "reread"],
                     default=None,
                     help="beyond-HBM streaming regime (--doc-len runs "
                          "only): keep packed chunks in host RAM between "
                          "passes, re-read from disk, or pick by byte "
                          "budget (default auto)")
    run.add_argument("--wire", choices=["ragged", "padded", "bytes"],
                     default="ragged",
                     help="host->device chunk wire format (--doc-len "
                          "runs): 'ragged' ships one flat uint16 token "
                          "stream per chunk (bytes scale with real "
                          "tokens) and rebuilds [D, L] on device; "
                          "'bytes' ships RAW document bytes and "
                          "tokenizes+hashes ON DEVICE (the host never "
                          "hashes at all; ids bit-identical to the "
                          "host packers — ops/device_tokenize.py), "
                          "degrading to 'ragged' when the device "
                          "tokenizer cannot carry the run (vocab past "
                          "2^16, chargram, mesh, --exact-terms); "
                          "'padded' forces the dense wire — the bit-"
                          "identical parity fallback, also selected "
                          "automatically for vocabs past 2^16 or chunks "
                          "whose flat stream would overflow the int32 "
                          "bucket bound. Env: TFIDF_TPU_WIRE")
    run.add_argument("--pack-threads", type=int, default=None,
                     help="host packer thread count for the native "
                          "tokenize+hash fill (the reference's OpenMP "
                          "move on the shared ParallelFor pool); "
                          "default every core (env "
                          "TFIDF_TPU_PACK_THREADS)")
    run.add_argument("--ingest-workers", type=int, default=None,
                     help="multi-PROCESS sharded ingest (--doc-len "
                          "runs): N worker processes rendezvous over "
                          "mpi_lite-style socketpair channels, each "
                          "packs+uploads its contiguous document "
                          "shard over its own link concurrently, and "
                          "local DF merges through one allreduce — "
                          "the merged index is bit-identical to a "
                          "single-process run while the upload wall "
                          "divides by worker count (the reference's "
                          "rank-partitioned loop, TFIDF.c:130; "
                          "docs/SCALING.md round 19). Default 1; env "
                          "TFIDF_TPU_INGEST_WORKERS. Excludes --mesh "
                          "and --exact-terms")
    run.add_argument("--result-wire", choices=["packed", "pair"],
                     default="packed",
                     help="device->host top-k result wire: 'packed' "
                          "(default) ships one uint32 word per slot "
                          "(16-bit score + uint16 id — half the bytes, "
                          "chunked async drain on --doc-len runs; ids "
                          "bit-exact, scores within fp16 rounding); "
                          "'pair' forces the full-precision (id, score) "
                          "pair wire — the bit-identical parity "
                          "fallback, also selected automatically for "
                          "vocabs past 2^16 or 64-bit score runs")
    run.add_argument("--finish", choices=["scan", "chunked"],
                     default=None,
                     help="packed-wire phase-B finish structure "
                          "(--doc-len runs): 'scan' (default) scores "
                          "the whole resident corpus in ONE donated "
                          "lax.scan dispatch — one program, one async "
                          "drain, no per-chunk dispatch tax; 'chunked' "
                          "keeps the round-7 per-chunk scoring "
                          "dispatches with the interleaved async "
                          "drain — the bit-identical fallback (also "
                          "what effectively runs on the pair result "
                          "wire, whose fused finish is already one "
                          "dispatch)")
    run.add_argument("--compile-cache", metavar="DIR", default=None,
                     help="persistent XLA compilation cache directory: "
                          "repeat runs at the same (bucketed) wire "
                          "shapes load executables from disk instead "
                          "of re-paying every cold-start compile "
                          "(config.apply_compile_cache)")
    run.add_argument("--exact-terms", action="store_true",
                     help="hashed+topk mode: re-rank the device top-k "
                          "on host with exact strings and DF, emitting "
                          "exact words instead of bucket representatives")
    run.add_argument("--exact-margin", type=int, default=4,
                     help="candidate margin multiplier for --exact-terms' "
                          "HASHED fallback engine: the chip keeps "
                          "margin*k buckets so collisions cannot push "
                          "true top-k words out of reach (4 is the "
                          "measured recall-1.0 knee, docs/EXACT.md; the "
                          "run warns when occupancy suggests raising "
                          "it). The default device-exact engine has no "
                          "collisions and clamps its own margin to k+8")
    run.add_argument("--mesh", type=str, default=None,
                     help="mesh shape docs,seq,vocab (e.g. 4,1,2); "
                          "default: single device")
    run.add_argument("--no-strict", action="store_true",
                     help="accept any filenames, not just doc<i>")
    run.add_argument("--nranks", type=int, default=4,
                     help="ranks for --backend=mpi")
    run.add_argument("--comm", choices=["thread", "process"],
                     default="thread",
                     help="--backend=mpi rank backend: threads in one "
                          "process, or fork+socketpair OS processes "
                          "(the reference's mpirun deployment model; "
                          "byte-identical output)")
    run.add_argument("--inspect", action="store_true",
                     help="print the reference's per-phase debug tables "
                          "(TF Job / IDF Job, TFIDF.c:199-205,236-239) to "
                          "stdout before running — an eyeball-diff aid "
                          "for toy corpora")
    run.add_argument("--timing", action="store_true",
                     help="print per-phase wall-clock (discover/pack/"
                          "transfer/compute/fetch/emit) and docs/sec "
                          "to stderr")
    _add_trace_flag(run)

    st = sub.add_parser(
        "stream",
        help="stream the corpus in minibatches with checkpoint/resume")
    st.add_argument("--input", required=True, help="document directory")
    st.add_argument("--output", default="output.txt",
                    help="top-k output file")
    st.add_argument("--batch-docs", type=int, default=256,
                    help="documents per minibatch")
    st.add_argument("--doc-len", type=int, default=256,
                    help="static tokens per document (longer docs are "
                         "truncated; one compiled program for the whole "
                         "stream)")
    st.add_argument("--vocab-size", type=int, default=1 << 16)
    st.add_argument("--topk", type=int, default=8)
    st.add_argument("--mesh-docs", type=int, default=None,
                    help="shard each minibatch over this many devices "
                         "(0 = all); the DF update becomes the "
                         "incremental psum of BASELINE config 5")
    st.add_argument("--checkpoint", default=None,
                    help="checkpoint directory; state is saved after "
                         "every minibatch")
    st.add_argument("--resume", action="store_true",
                    help="restore from --checkpoint and skip the "
                         "documents already folded into the DF state")
    st.add_argument("--no-strict", action="store_true")
    st.add_argument("--timing", action="store_true",
                    help="print per-phase wall-clock (pass1/pass2/emit) "
                         "and docs/sec to stderr")
    _add_trace_flag(st)

    q = sub.add_parser(
        "query", help="index a corpus and run ranked cosine retrieval")
    q.add_argument("--input", required=True, help="document directory")
    q.add_argument("--query", action="append", required=True,
                   help="query text (repeatable)")
    q.add_argument("-k", type=int, default=5, help="results per query")
    q.add_argument("--vocab-size", type=int, default=1 << 16)
    q.add_argument("--mesh-docs", type=int, default=None,
                   help="shard the index over this many devices")
    q.add_argument("--doc-len", type=int, default=None,
                   help="static tokens per document: index via the "
                        "overlapped chunked ingest (native loader; "
                        "longer docs truncated). Single-device only")
    q.add_argument("--compile-cache", metavar="DIR", default=None,
                   help="persistent XLA compilation cache directory "
                        "(also env TFIDF_TPU_COMPILE_CACHE): repeat "
                        "query cold-starts load the index/search "
                        "executables from disk")
    q.add_argument("--no-strict", action="store_true")
    _add_trace_flag(q)

    sv = sub.add_parser(
        "serve",
        help="index a corpus and serve ranked retrieval online "
             "(JSONL request loop; docs/SERVING.md)",
        epilog=_SERVE_EPILOG,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    sv.add_argument("--input", required=True, help="document directory")
    sv.add_argument("--vocab-size", type=int, default=1 << 16)
    sv.add_argument("--doc-len", type=int, default=None,
                    help="static tokens per document: index via the "
                         "overlapped chunked ingest (longer docs "
                         "truncated); default whole-corpus batch path")
    sv.add_argument("-k", type=int, default=10,
                    help="default results per query (requests may "
                         "override per line)")
    sv.add_argument("--max-batch", type=int, default=None,
                    help="most queries one coalesced device batch "
                         "carries (default 256; env "
                         "TFIDF_TPU_MAX_BATCH)")
    sv.add_argument("--max-wait-ms", type=float, default=None,
                    help="micro-batching window: the oldest queued "
                         "request never waits longer than this for the "
                         "batch to fill (default 2; env "
                         "TFIDF_TPU_MAX_WAIT_MS)")
    sv.add_argument("--queue-depth", type=int, default=None,
                    help="admission bound in queries; past it requests "
                         "shed with an 'overloaded' error (default "
                         "256; env TFIDF_TPU_QUEUE_DEPTH)")
    sv.add_argument("--cache-entries", type=int, default=None,
                    help="LRU result-cache capacity in per-query rows; "
                         "0 disables (default 4096; env "
                         "TFIDF_TPU_CACHE_ENTRIES)")
    sv.add_argument("--deadline-ms", type=float, default=None,
                    help="default per-request deadline; requests still "
                         "queued past it shed with 'deadline_exceeded' "
                         "(default: no deadline)")
    sv.add_argument("--health-period-ms", type=float, default=250.0,
                    help="watchdog cadence: every period the server "
                         "re-derives ok|degraded|unhealthy from worker "
                         "heartbeats, queue saturation and shed rates "
                         "(healthz/readyz ops; degraded shrinks the "
                         "admission bound). 0 disables the background "
                         "thread (default 250; env "
                         "TFIDF_TPU_HEALTH_PERIOD_MS)")
    sv.add_argument("--devmon-period-ms", type=float, default=1000.0,
                    help="device-monitor cadence: every period the "
                         "server samples per-device memory_stats() "
                         "into gauges, checks the HBM watermarks "
                         "(TFIDF_TPU_HBM_WATERMARKS) and refreshes "
                         "the memory_pressure health signal, so "
                         "admission sheds before OOM. 0 disables the "
                         "thread (default 1000; env "
                         "TFIDF_TPU_DEVMON_PERIOD_MS). Backends "
                         "without memory stats (CPU) run the same "
                         "path with gauges absent")
    sv.add_argument("--slow-ms", type=float, default=None,
                    help="slow-query threshold: a resolved request "
                         "over this total latency emits a slow_query "
                         "flight event carrying its per-phase "
                         "breakdown (queue/batch/device/drain/cache), "
                         "batch id, co-occupant count and overlapping "
                         "anomalies — the record doctor --request RID "
                         "renders (env TFIDF_TPU_SLOW_MS; sampling "
                         "mirror TFIDF_TPU_SLOW_SAMPLE = 1-in-N even "
                         "when fast; default: off)")
    sv.add_argument("--slo-ms", type=float, default=None,
                    help="latency objective for the SLO burn gauges: "
                         "requests slower than this are 'bad'; "
                         "windowed fast/slow error-budget burn rates "
                         "publish as serve_slo_* gauges, ride the "
                         "metrics op's slo object, and a fast burn "
                         "degrades health -> admission sheds at the "
                         "gate (env TFIDF_TPU_SLO_MS; default: off)")
    sv.add_argument("--slo-target", type=float, default=None,
                    help="fraction of requests that must meet "
                         "--slo-ms (error budget = 1 - target; "
                         "default 0.99; env TFIDF_TPU_SLO_TARGET)")
    sv.add_argument("--no-warm", action="store_true",
                    help="skip the power-of-two query-bucket warm-up "
                         "(and its mark_warm() line): the compile "
                         "watchdog then never flags steady-state "
                         "recompiles")
    sv.add_argument("--canary-period-ms", type=float, default=5000.0,
                    help="canary parity-probe cadence: replay pinned "
                         "golden queries through the batched path and "
                         "bit-compare against the swap-time oracle "
                         "(serve_canary_parity gauge — the live index-"
                         "corruption detector). 0 disables (default "
                         "5000)")
    sv.add_argument("--canary-queries", type=int, default=8,
                    help="pinned golden queries drawn from the corpus "
                         "(first tokens of the first N docs)")
    sv.add_argument("--snapshot-dir", metavar="DIR", default=None,
                    help="crash-fast index snapshot root (also env "
                         "TFIDF_TPU_SNAPSHOT_DIR): on start, a "
                         "committed snapshot with a matching config "
                         "fingerprint restores in seconds instead of "
                         "re-ingesting --input; after a fresh build "
                         "(and before every swap_index flip) the "
                         "index is snapshotted there atomically "
                         "(checkpoint.py seq+LATEST protocol). "
                         "JSONL op {\"op\": \"snapshot\"} snapshots "
                         "on demand")
    sv.add_argument("--mesh-shards", type=int, default=None,
                    help="serve ONE logical index doc-sharded across "
                         "this many devices (0 = all): per-shard "
                         "fused score/top-k under shard_map, a "
                         "device-side top-k-of-top-k merge riding one "
                         "collective back — responses BIT-identical "
                         "to single-device serving; swap/mutation/"
                         "snapshot installs re-shard automatically "
                         "(default: off — single device; env "
                         "TFIDF_TPU_MESH_SHARDS; docs/SERVING.md "
                         "'Sharded serving')")
    sv.add_argument("--query-slab", choices=["on", "off"], default=None,
                    help="zero-allocation query hot path: a donated, "
                         "persistently-recycled device query block "
                         "per pow2 bucket fed by a pinned host "
                         "staging ring — steady-state serving does "
                         "zero Python-side array allocations and "
                         "exactly ONE H2D copy per batch (byte-"
                         "stamped h2d trace spans; serve_bench "
                         "--ab-slab measures it). 'off' forces the "
                         "legacy per-batch allocation, bit-identical "
                         "(default on; env TFIDF_TPU_QUERY_SLAB)")
    sv.add_argument("--disttrace", choices=["on", "off"], default=None,
                    help="fleet-wide distributed tracing: the front "
                         "mints a compact trace context per admitted "
                         "request and every hop (route, replica "
                         "request/queued/batched/device, two-phase "
                         "txn_phase) carries the same t<16hex> id; "
                         "replica span rings pull over the data plane "
                         "({\"op\": \"trace_export\"}) and "
                         "tools/trace_merge.py aligns the clocks into "
                         "one Perfetto timeline. 'off' drops the "
                         "context at admission — requests degrade to "
                         "local rids, never fail (default on; env "
                         "TFIDF_TPU_DISTTRACE; docs/OBSERVABILITY.md "
                         "'Trace a slow query across the tier')")
    sv.add_argument("--serve-pipeline-depth", type=int, default=None,
                    metavar="D",
                    help="pipelined serve execution: up to D dispatched "
                         "batches stay in flight while the batcher "
                         "coalesces the next — a dispatch stage issues "
                         "the async search + D2H copy and an ordered "
                         "drain worker materializes results batch-"
                         "major, so the device never idles between "
                         "dispatches. 1 = unpipelined legacy path, "
                         "bit-identical responses at every depth "
                         "(default 2; env TFIDF_TPU_SERVE_PIPELINE; "
                         "docs/SERVING.md 'Pipelined execution')")
    sv.add_argument("--score-tiling", choices=["on", "off"], default=None,
                    help="tiled sparse scoring: the document axis is "
                         "chunked into fixed tiles scored against the "
                         "full query block inside ONE lax.scan "
                         "dispatch, streaming top-k folded across "
                         "tiles on device — per-tile intermediates "
                         "stay bounded however wide the batch grows "
                         "(tile width: env TFIDF_TPU_QUERY_BLOCK, "
                         "default 4096 doc rows; serve_bench "
                         "--ab-tiled measures it). 'off' forces the "
                         "legacy whole-corpus dot with serial 64-"
                         "query block splitting, bit-identical "
                         "(default on; env TFIDF_TPU_SCORE_TILING)")
    sv.add_argument("--delta-docs", type=int, default=None,
                    help="serve an LSM-style SEGMENTED index with a "
                         "delta segment of this capacity: the "
                         "add_docs/delete_docs JSONL ops mutate the "
                         "live index (tombstone masks, epoch-bumped "
                         "visibility, bit-identical to a full "
                         "rebuild); a full delta seals into an "
                         "immutable segment (default: off — classic "
                         "immutable-except-swap serving; env "
                         "TFIDF_TPU_DELTA_DOCS; docs/SERVING.md "
                         "'Live mutation')")
    sv.add_argument("--compact-at", type=int, default=None,
                    help="sealed-segment count at which the "
                         "supervised background compactor merges "
                         "them into one, dropping tombstones "
                         "(default 4; env TFIDF_TPU_COMPACT_AT; "
                         "needs --delta-docs)")
    sv.add_argument("--replicas", type=int, default=None,
                    metavar="N",
                    help="replicated serving tier: run N full server "
                         "processes behind a lightweight front that "
                         "owns this JSONL protocol — queries route "
                         "by hash of their normalized form (cache "
                         "affinity) with least-loaded fallback; index "
                         "changes commit tier-wide via a two-phase "
                         "epoch bump; dead replicas restart from "
                         "--snapshot-dir (REQUIRED with --replicas) "
                         "under the --restart budget (env "
                         "TFIDF_TPU_REPLICAS; docs/SERVING.md "
                         "'Replicated tier')")
    sv.add_argument("--replica-timeout-s", type=float, default=None,
                    metavar="S",
                    help="front-side patience per replica: boot-to-"
                         "ready wait, per-request response wait, and "
                         "the two-phase control round-trip bound — "
                         "past it the replica is declared dead and "
                         "restarted (default 120; env "
                         "TFIDF_TPU_REPLICA_TIMEOUT_S)")
    sv.add_argument("--scorer", metavar="SPEC", default=None,
                    help="default scoring-family member for requests "
                         "that name none: 'tfidf' (bit-identical "
                         "legacy default) or 'bm25' / "
                         "'bm25:k1=1.5,b=0.6'. Per-request \"scorer\" "
                         "JSONL fields override; the set_scorer op "
                         "changes it live (epoch bump + cache clear + "
                         "canary re-capture). BM25 scores through the "
                         "SAME tiled kernel — weights precompute into "
                         "the sparse face (default tfidf; env "
                         "TFIDF_TPU_SCORER; docs/SERVING.md "
                         "'Scoring family')")
    sv.add_argument("--bm25-k1", type=float, default=None,
                    metavar="K1",
                    help="BM25 term-frequency saturation for a bare "
                         "--scorer bm25 (an inline k1= in the spec "
                         "wins; default 1.2; env TFIDF_TPU_BM25_K1)")
    sv.add_argument("--bm25-b", type=float, default=None, metavar="B",
                    help="BM25 length-normalization strength, same "
                         "resolution rules as --bm25-k1 (default "
                         "0.75; env TFIDF_TPU_BM25_B)")
    sv.add_argument("--faults", metavar="PLAN", default=None,
                    help="arm a deterministic fault-injection plan "
                         "(chaos testing; also env TFIDF_TPU_FAULTS; "
                         "grammar in tfidf_tpu/faults.py), e.g. "
                         "'device_dispatch:transient:n=2;"
                         "device_dispatch:fatal:match=zz'")
    sv.add_argument("--fault-seed", type=int, default=None,
                    help="seed for the fault plan's probabilistic "
                         "rules + retry jitter (replayable chaos; "
                         "env TFIDF_TPU_FAULT_SEED)")
    sv.add_argument("--flight", metavar="OUT.jsonl", default=None,
                    help="flight-recorder dump path: the structured "
                         "event ring + last-N request digests write "
                         "here atomically on shutdown, crash or "
                         "SIGTERM (also env TFIDF_TPU_FLIGHT; with "
                         "--trace and no --flight the dump lands next "
                         "to the trace as <trace>.flight.jsonl). "
                         "Validate with tools/trace_check.py --flight")
    sv.add_argument("--port", type=int, default=None,
                    help="serve JSONL over TCP on this port instead of "
                         "stdin/stdout (one request per line, "
                         "responses in completion order)")
    sv.add_argument("--compile-cache", metavar="DIR", default=None,
                    help="persistent XLA compilation cache directory "
                         "(also env TFIDF_TPU_COMPILE_CACHE): serve "
                         "cold-starts load the warmed search "
                         "executables from disk")
    sv.add_argument("--no-strict", action="store_true")
    _add_trace_flag(sv)
    return p


def _add_trace_flag(sub) -> None:
    sub.add_argument("--trace", metavar="OUT.json", default=None,
                     help="record a host span timeline and write "
                          "Chrome trace-event JSON here on exit (open "
                          "in Perfetto / chrome://tracing; lanes: "
                          "main, packer, drainer, batcher). Also env "
                          "TFIDF_TPU_TRACE; validate with "
                          "tools/trace_check.py; docs/OBSERVABILITY.md")


def _run_mpi(args) -> int:
    """Dispatch to the native bit-reference (the --backend=mpi oracle)."""
    if not os.path.exists(NATIVE_BIN):
        rc = subprocess.run(["make", "-C", os.path.dirname(NATIVE_BIN)],
                            capture_output=True)
        if rc.returncode != 0 or not os.path.exists(NATIVE_BIN):
            sys.stderr.write("error: native backend not built "
                             "(make -C native failed)\n")
            return 1
    proc = subprocess.run(
        [NATIVE_BIN, args.input, args.output, str(args.nranks),
         getattr(args, "comm", "thread")])
    return proc.returncode


def _run_tpu(args) -> int:
    # Deferred: importing jax is slow and unnecessary for --backend=mpi.
    from tfidf_tpu.config import PipelineConfig, TokenizerKind, VocabMode
    from tfidf_tpu.formatter import write_output
    from tfidf_tpu.io.corpus import discover_corpus
    from tfidf_tpu.pipeline import TfidfPipeline

    lo, hi = (int(x) for x in args.ngram.split(","))
    mesh_shape = {}
    if args.mesh:
        docs, seq, vocab = (int(x) for x in args.mesh.split(","))
        mesh_shape = {"docs": docs, "seq": seq, "vocab": vocab}
    exact_terms = getattr(args, "exact_terms", False)
    if exact_terms:
        if args.topk is None or args.vocab_mode != "hashed" \
                or args.tokenizer != "whitespace":
            sys.stderr.write("error: --exact-terms needs --topk, "
                             "--vocab-mode hashed, and the whitespace "
                             "tokenizer\n")
            return 2
    cfg = PipelineConfig(
        vocab_mode=VocabMode(args.vocab_mode),
        vocab_size=args.vocab_size,
        tokenizer=TokenizerKind(args.tokenizer),
        ngram_range=(lo, hi),
        # exact-terms re-rank: the device keeps a margin*k candidate
        # selection so a collision partner cannot push a true top-k
        # word's bucket out of reach (tfidf_tpu/rerank.py docstring).
        topk=(max(2, args.exact_margin) * args.topk if exact_terms
              else args.topk),
        engine=args.engine,
        use_pallas=args.pallas,
        mesh_shape=mesh_shape,
        wire=getattr(args, "wire", "ragged"),
        pack_threads=getattr(args, "pack_threads", None),
        result_wire=getattr(args, "result_wire", "packed"),
        finish=getattr(args, "finish", None) or "scan",
        compile_cache=getattr(args, "compile_cache", None),
        trace=getattr(args, "trace", None),
    )
    # Arm the persistent compile cache BEFORE any jitted work — the
    # library entry points re-apply it idempotently.
    from tfidf_tpu.config import apply_compile_cache
    apply_compile_cache(cfg.compile_cache)
    # Device-truth sampling (TFIDF_TPU_DEVMON): when armed, a global
    # DeviceMonitor samples HBM stats in the background and the run's
    # epilog takes a final sample + live-buffer census into the
    # flight-recorder ring (tools/doctor.py reads it from the dump).
    from tfidf_tpu.obs import devmon as obs_devmon
    obs_devmon.configure()
    from tfidf_tpu.utils.timing import PhaseTimer, Throughput, phase_or_null
    timer = PhaseTimer() if args.timing else None
    throughput = Throughput()

    # --inspect's discovery is kept and REUSED by whichever run path
    # follows (ADVICE round 5: the old flow discovered the corpus
    # twice, doubling I/O on anything beyond a toy input).
    corpus_dbg = None
    if getattr(args, "inspect", False):
        # The reference's debugging affordance: dump the TF/IDF phase
        # tables in its exact print formats (golden.inspect_tables).
        # Host-side by design — it is the EXPECTED tables the device
        # run is then eyeball-diffed against, like the original's
        # stdout vs its output file.
        from tfidf_tpu.golden import inspect_tables
        corpus_dbg = discover_corpus(args.input,
                                     strict=not args.no_strict)
        if len(corpus_dbg) > 200:
            sys.stderr.write(f"warning: --inspect prints every record "
                             f"({len(corpus_dbg)} docs) — meant for toy "
                             f"corpora\n")
        sys.stdout.buffer.write(inspect_tables(corpus_dbg))
        sys.stdout.buffer.flush()

    # Scalable route (explicit opt-in via --doc-len): hashed-vocab
    # top-k runs on a single device go through the overlapped chunked
    # ingest (native loader, ragged wire, flat memory in corpus size)
    # — the same pipeline bench.py measures, instead of packing the
    # whole corpus in Python first. Opt-in because the static doc
    # length TRUNCATES longer documents — the fixed-shape trade the
    # batch path (L grows to the longest doc) never makes. Everything
    # else (golden full-output, meshes, chargram, pallas) keeps the
    # TfidfPipeline batch path.
    if args.doc_len is not None and args.doc_len < 1:
        sys.stderr.write("error: --doc-len must be >= 1\n")
        return 2
    if args.chunk_docs is not None and args.chunk_docs < 1:
        sys.stderr.write("error: --chunk-docs must be >= 1\n")
        return 2
    if args.doc_len is None and (args.spill is not None
                                 or args.chunk_docs is not None):
        sys.stderr.write("error: --spill/--chunk-docs only apply to "
                         "--doc-len (overlapped ingest) runs\n")
        return 2
    # (a defaulted engine is always "sparse" under HASHED vocab, so
    # checking the resolved value covers both spellings)
    # --mesh composes with --doc-len for docs-only meshes: the
    # overlapped ingest runs docs-sharded under shard_map with the DF
    # fold as one psum (ingest._run_overlapped_mesh). seq/vocab meshes
    # stay on the batch path (sparse-engine doctrine).
    mesh_ok = (not mesh_shape
               or (mesh_shape.get("seq", 1) == 1
                   and mesh_shape.get("vocab", 1) == 1))
    overlapped = (args.doc_len is not None
                  and cfg.vocab_mode is VocabMode.HASHED
                  and cfg.topk is not None
                  and cfg.tokenizer is TokenizerKind.WHITESPACE
                  and mesh_ok and not args.pallas
                  and cfg.engine == "sparse")
    # An EXPLICIT --finish=scan that cannot run warns once, mirroring
    # the wire auto-fallback messages: the scan emits packed words, so
    # a pair-wire run (forced or auto-degraded, e.g. vocab > 2^16)
    # takes the fused _finish_wire program instead — already a single
    # dispatch, but not the structure the flag named.
    if getattr(args, "finish", None) == "scan" and overlapped:
        from tfidf_tpu.ops.downlink import use_packed_result_wire
        if not use_packed_result_wire(cfg) or exact_terms:
            sys.stderr.write(
                "warning: --finish=scan needs the packed result wire; "
                "falling back to the chunked/fused finish (the pair "
                "and exact wires' fused finish program is already one "
                "dispatch)\n")
    # An EXPLICIT --wire=bytes that cannot run warns once too: the
    # device tokenizer serves single-device hashed whitespace runs
    # within the uint16 vocab bound; everything else degrades down the
    # bytes -> ragged -> padded chain silently only when NOT asked for.
    if getattr(args, "wire", None) == "bytes":
        from tfidf_tpu.ingest import use_bytes_wire
        chunk_guess = args.chunk_docs or 8192
        if (not overlapped or exact_terms or mesh_shape
                or not use_bytes_wire(cfg, chunk_guess,
                                      args.doc_len or cfg.max_doc_len)):
            sys.stderr.write(
                "warning: --wire=bytes needs a single-device hashed "
                "whitespace --doc-len run with vocab <= 2^16; falling "
                "back to the ragged/padded id wire\n")
    # Multi-process sharded ingest (round 19): flag > env > 1. The
    # worker processes re-run this config through run_overlapped with
    # shard + DF-allreduce hooks — bit-identical merge, divided link.
    ingest_workers = getattr(args, "ingest_workers", None)
    if ingest_workers is None:
        ingest_workers = int(os.environ.get("TFIDF_TPU_INGEST_WORKERS",
                                            "1") or 1)
    if ingest_workers < 1:
        sys.stderr.write("error: --ingest-workers must be >= 1\n")
        return 2
    if ingest_workers > 1 and (mesh_shape or exact_terms
                               or not overlapped):
        sys.stderr.write(
            "warning: --ingest-workers needs a single-device hashed "
            "--doc-len run (no --mesh, no --exact-terms); running "
            "single-process\n")
        ingest_workers = 1
    if overlapped and exact_terms and not mesh_shape:
        # Exact-terms with automatic engine choice (rerank.exact_terms):
        # device-exact intern ids when the corpus fits the vocab (no
        # collisions, no corpus re-pass), else hashed margin + native
        # re-rank. Emits the same byte format either way.
        import time

        from tfidf_tpu.io.corpus import discover_names
        from tfidf_tpu.rerank import exact_terms_lines
        n_docs = (len(corpus_dbg) if corpus_dbg is not None
                  else len(discover_names(args.input,
                                          strict=not args.no_strict)))
        t0 = time.perf_counter()
        lines, engine, _ = exact_terms_lines(
            args.input, cfg, k=args.topk, doc_len=args.doc_len,
            chunk_docs=args.chunk_docs or 8192,
            strict=not args.no_strict, spill=args.spill or "auto")
        throughput.record(n_docs, time.perf_counter() - t0)
        with phase_or_null(timer, "emit"):
            # lines arrive already in the reference's strcmp order
            # (TFIDF.c:273) — write-through.
            with open(args.output, "wb") as f:
                f.write(lines)
        if timer is not None:
            sys.stderr.write(
                timer.report() + "\n"
                f"{'docs/sec':>12}: {throughput.docs_per_sec:9.1f}\n"
                f"{'engine':>12}: {engine}\n")
        print(f"wrote {args.output} ({n_docs} docs)")
        return 0
    if overlapped:
        import time
        import types

        from tfidf_tpu.ingest import run_overlapped
        plan = None
        if mesh_shape:
            import jax

            from tfidf_tpu.parallel.mesh import MeshPlan
            # Like `query --mesh-docs`: docs=N takes the first N
            # devices (0 = all), so a sub-mesh works on any host.
            n = mesh_shape.get("docs", 0)
            plan = MeshPlan.create(docs=n,
                                   devices=jax.devices()[:n] if n else None)
        t0 = time.perf_counter()
        # Exact-terms runs read only candidate buckets from the device,
        # so they take the ids-only wire (no score fetch bytes).
        if ingest_workers > 1 and plan is None:
            from tfidf_tpu.parallel.multihost import run_sharded_ingest
            r, mh_info = run_sharded_ingest(
                args.input, cfg, n_workers=ingest_workers,
                chunk_docs=args.chunk_docs or 8192,
                doc_len=args.doc_len, strict=not args.no_strict,
                spill=args.spill or "auto")
            sys.stderr.write(
                f"sharded ingest: {mh_info.n_workers} workers, "
                f"upload {mh_info.upload_s:.3f}s (max over links), "
                f"utilization {mh_info.link_utilization}\n")
        else:
            r = run_overlapped(args.input, cfg, doc_len=args.doc_len,
                               chunk_docs=args.chunk_docs or 8192,
                               strict=not args.no_strict,
                               spill=args.spill or "auto",
                               wire_vals=not exact_terms, plan=plan)
        throughput.record(r.num_docs, time.perf_counter() - t0)
        result = types.SimpleNamespace(
            num_docs=r.num_docs, names=r.names, df=r.df,
            topk_vals=r.topk_vals, topk_ids=r.topk_ids, id_to_word={},
            df_occupied=r.df_occupied)
        if timer is not None and r.phases:
            for name, secs in r.phases.items():
                timer.add(name, secs)
    elif args.doc_len is not None:
        sys.stderr.write("error: --doc-len (overlapped ingest) needs "
                         "--vocab-mode hashed, --topk, the whitespace "
                         "tokenizer, the sparse engine, no --pallas, "
                         "and a docs-only --mesh (seq=1, vocab=1) if "
                         "any\n")
        return 2
    else:
        with phase_or_null(timer, "discover"):
            corpus = (corpus_dbg if corpus_dbg is not None else
                      discover_corpus(args.input, strict=not args.no_strict))
        # --mesh flows through config.mesh_shape: TfidfPipeline
        # dispatches to ShardedPipeline over the described device mesh.
        with throughput.measure(len(corpus)):
            result = TfidfPipeline(cfg, timer=timer).run(corpus)

    with phase_or_null(timer, "emit"):
        if args.topk is None:
            write_output(args.output, result.output_lines())
        elif exact_terms:
            from tfidf_tpu.rerank import exact_topk
            # Passing df arms the library-level collision-pressure
            # warning (rerank.margin_check, docs/EXACT.md). max_tokens
            # mirrors the ingest truncation when --doc-len routed the
            # run through it — candidate/TF parity with what the device
            # actually scored (rerank.py docstring).
            # Overlapped runs hand over the wire's occupancy scalar so
            # the warning never fetches the device-resident DF vector.
            occ = getattr(result, "df_occupied", None)
            reranked = exact_topk(args.input, result.names,
                                  result.topk_ids, result.num_docs, cfg,
                                  k=args.topk,
                                  df=None if occ is not None else result.df,
                                  df_occupied=occ,
                                  max_tokens=args.doc_len if overlapped
                                  else None)
            lines = [b"%s@%s\t%.16f" % (name.encode(), w, s)
                     for name in result.names if name
                     for w, s in reranked[name]]
            # Reference ordering contract: raw-line strcmp sort
            # (TFIDF.c:273) — every emit path is diff-stable.
            lines.sort()
            with open(args.output, "wb") as f:
                f.write(b"".join(l + b"\n" for l in lines))
        else:
            _write_topk(args.output, result)
    mon = obs_devmon.get_monitor()
    if mon is not None:
        mon.sample()
        mon.log_census()
    if timer is not None:
        sys.stderr.write(timer.report() + "\n"
                         f"{'docs/sec':>12}: {throughput.docs_per_sec:9.1f}\n")
    print(f"wrote {args.output} ({result.num_docs} docs)")
    return 0


def _write_topk(path: str, result) -> None:
    """Top-k report: doc@word\\tscore lines in raw-line strcmp order —
    the reference's global ordering contract (``TFIDF.c:273``), so two
    runs (or two backends) diff cleanly regardless of discovery order."""
    lines: List[bytes] = []
    for d in range(result.num_docs):
        name = result.names[d].encode()
        for v, s in zip(result.topk_ids[d], result.topk_vals[d]):
            if s <= 0:
                continue  # padding / sub-k docs
            word = result.id_to_word.get(int(v), b"id:%d" % int(v))
            lines.append(b"%s@%s\t%.16f" % (name, word, float(s)))
    lines.sort()
    with open(path, "wb") as f:
        f.write(b"".join(l + b"\n" for l in lines))


def _run_stream(args) -> int:
    """Two-pass streaming job: fold DF per minibatch (checkpointing as it
    goes), then score every minibatch against the final corpus-wide DF.

    Resume contract: documents stream in the deterministic discovery
    order, so ``docs_seen`` from a restored checkpoint identifies the
    exact restart position — the capability the single-shot reference
    lacks entirely (SURVEY §5: any failure = full rerun).
    """
    import numpy as np

    from tfidf_tpu import checkpoint as ckpt
    from tfidf_tpu.config import PipelineConfig, VocabMode
    from tfidf_tpu.ingest import make_chunk_packer
    from tfidf_tpu.io.corpus import PackedBatch, discover_names
    from tfidf_tpu.streaming import StreamingTfidf

    cfg = PipelineConfig(vocab_mode=VocabMode.HASHED,
                         vocab_size=args.vocab_size, topk=args.topk,
                         max_doc_len=args.doc_len, doc_chunk=args.doc_len)
    plan = None
    if args.mesh_docs is not None:
        import jax

        from tfidf_tpu.parallel import MeshPlan
        devs = jax.devices()[:args.mesh_docs] if args.mesh_docs else None
        plan = MeshPlan.create(docs=args.mesh_docs, devices=devs)
        if args.batch_docs % plan.n_docs_shards:
            sys.stderr.write("error: --batch-docs must be a multiple of "
                             "--mesh-docs (rows block-shard evenly)\n")
            return 2
    stream = StreamingTfidf(cfg, plan)
    names = discover_names(args.input, strict=not args.no_strict)
    if not names:
        sys.stderr.write(f"error: no documents in {args.input}\n")
        return 1

    start = 0
    if args.resume and args.checkpoint and ckpt.exists(args.checkpoint):
        stream.load_state(ckpt.restore_state(args.checkpoint))
        start = stream.docs_seen
        print(f"resumed at doc {start} ({args.checkpoint})")

    # Minibatches come off the native parallel loader when built (bytes
    # never enter Python; uint16 wire), else the Python pack path — the
    # same packer the ingest pipeline uses. Every batch is padded to
    # batch_docs x doc_len, so the whole stream reuses one compiled
    # update program and one score program.
    packer = make_chunk_packer(args.input, cfg, args.batch_docs,
                               args.doc_len)

    def batches(from_doc: int):
        for lo in range(from_doc, len(names), args.batch_docs):
            batch_names = names[lo:lo + args.batch_docs]
            token_ids, lengths = packer(batch_names)
            # PackedBatch invariant: one name per row, '' for padding.
            padded = batch_names + [""] * (token_ids.shape[0]
                                           - len(batch_names))
            yield PackedBatch(
                token_ids=token_ids, lengths=lengths,
                num_docs=len(batch_names), names=padded,
                vocab_size=cfg.vocab_size, id_to_word=None)

    from tfidf_tpu.utils.timing import PhaseTimer, Throughput, phase_or_null
    timer = PhaseTimer() if getattr(args, "timing", False) else None
    throughput = Throughput()

    # Pass 1: fold DF, checkpoint after every minibatch.
    with phase_or_null(timer, "pass1_df"):
        for batch in batches(start):
            stream.update(batch)
            if args.checkpoint:
                ckpt.save_state(args.checkpoint, stream.state_dict())
    print(f"df folded over {stream.docs_seen} docs")

    # Pass 2: score all minibatches against the final DF snapshot.
    import types
    all_names: List[str] = []
    all_vals, all_ids = [], []
    with phase_or_null(timer, "pass2_score"):
        for batch in batches(0):
            vals, ids = stream.score(batch)
            all_names.extend(batch.names[:batch.num_docs])
            all_vals.append(np.asarray(vals)[:batch.num_docs])
            all_ids.append(np.asarray(ids)[:batch.num_docs])
    report = types.SimpleNamespace(
        num_docs=len(all_names), names=all_names,
        topk_vals=np.concatenate(all_vals), topk_ids=np.concatenate(all_ids),
        id_to_word={})
    with phase_or_null(timer, "emit"):
        _write_topk(args.output, report)  # same format as `run --topk`
    if timer is not None:
        total = sum(s for _, s in timer.items())
        throughput.record(len(all_names), total)
        sys.stderr.write(timer.report() + "\n"
                         f"{'docs/sec':>12}: "
                         f"{throughput.docs_per_sec:9.1f}\n")
    print(f"wrote {args.output} ({stream.docs_seen} docs)")
    return 0


def _run_query(args) -> int:
    """Index + search: `doc<i>\\tscore` per result line, tab-separated."""
    from tfidf_tpu.config import (PipelineConfig, VocabMode,
                                  apply_compile_cache)
    from tfidf_tpu.models import TfidfRetriever

    # Arm the persistent compile cache BEFORE any jitted work — query
    # cold-starts re-paid the index/search compiles until round 9.
    apply_compile_cache(getattr(args, "compile_cache", None))
    cfg = PipelineConfig(vocab_mode=VocabMode.HASHED,
                         vocab_size=args.vocab_size)
    plan = None
    if args.mesh_docs is not None:
        import jax

        from tfidf_tpu.parallel import MeshPlan
        # 0 = all devices (MeshPlan.create's docs=0 contract); else take
        # the first N so a sub-mesh works on any device count.
        devs = jax.devices()[:args.mesh_docs] if args.mesh_docs else None
        plan = MeshPlan.create(docs=args.mesh_docs, devices=devs)
    if args.doc_len is not None and plan is not None:
        sys.stderr.write("error: query --doc-len (chunked indexing) is "
                         "single-device; drop --mesh-docs\n")
        return 2
    r = TfidfRetriever(cfg, plan=plan).index_dir(
        args.input, strict=not args.no_strict, doc_len=args.doc_len)
    vals, idx = r.search(args.query, k=args.k)
    for qi, text in enumerate(args.query):
        print(f"query: {text}")
        for v, d in zip(vals[qi], idx[qi]):
            if d < 0:
                continue
            print(f"  {r.names[int(d)]}\t{float(v):.6f}")
    return 0


def _serve_handle_line(server, line, write, default_k, build_retriever,
                       canary=None):
    """One JSONL request -> one JSON response line (written via
    ``write``, possibly from a batcher callback thread). Returns False
    when the line asked for shutdown."""
    import json

    from tfidf_tpu.serve import (DeadlineExceeded, Overloaded,
                                 PoisonQuery, ServeError)

    line = line.strip()
    if not line:
        return True
    try:
        req = json.loads(line)
        if not isinstance(req, dict):
            raise ValueError("request must be a JSON object")
    except ValueError as e:
        write({"error": f"bad request: {e}"})
        return True
    op = req.get("op")
    if op == "shutdown":
        return False
    if op == "metrics":
        write({"id": req.get("id"), "metrics": server.metrics_snapshot()})
        return True
    if op == "metrics_prom":
        write({"id": req.get("id"),
               "metrics_prom": server.metrics_prom()})
        return True
    if op == "obs_export":
        write({"id": req.get("id"), "obs_export": server.obs_export()})
        return True
    if op == "trace_export":
        # The replica half of the fleet span pull: the front's
        # trace_export() collects this bundle over the SAME data plane
        # as obs_export and stamps identity + clock offset on each
        # entry. A process with no armed tracer answers an empty
        # bundle (never an error — the merge just has one fewer lane).
        from tfidf_tpu import obs
        t = obs.get_tracer()
        procs = ([{**t.export_meta(), "traceEvents": t.chrome_events()}]
                 if t is not None else [])
        write({"id": req.get("id"),
               "trace_export": {"schema": "tfidf-trace/1",
                                "pid": os.getpid(),
                                "processes": procs}})
        return True
    if op == "healthz":
        write({"id": req.get("id"), "healthz": server.healthz()})
        return True
    if op == "readyz":
        write({"id": req.get("id"), "readyz": server.readyz()})
        return True
    if op == "devmon":
        if server.devmon is None:
            write({"id": req.get("id"),
                   "error": "device monitor disabled "
                            "(--devmon-period-ms 0)"})
        else:
            snap = server.devmon.sample()
            snap["census"] = server.devmon.census()
            write({"id": req.get("id"), "devmon": snap})
        return True
    if op == "canary":
        if canary is None:
            write({"id": req.get("id"),
                   "error": "canary prober disabled "
                            "(--canary-period-ms 0)"})
        else:
            parity = canary.probe()
            write({"id": req.get("id"), "canary": (
                {"skipped": True} if parity is None
                else {"parity": parity})})
        return True
    if op == "swap_index":
        try:
            epoch = server.swap_index(build_retriever(req["input"]))
            write({"id": req.get("id"), "swapped": True, "epoch": epoch})
        except (KeyError, ValueError, OSError) as e:
            write({"id": req.get("id"), "error": f"swap failed: {e}"})
        return True
    if op == "snapshot":
        try:
            path = server.snapshot()
            write({"id": req.get("id"), "snapshot": path,
                   "epoch": server.epoch})
        except (ValueError, OSError, RuntimeError) as e:
            write({"id": req.get("id"), "error": f"snapshot failed: {e}"})
        return True
    if op == "add_docs":
        docs = req.get("docs")
        if (not isinstance(docs, list) or not docs or not all(
                isinstance(d, dict) and isinstance(d.get("name"), str)
                and isinstance(d.get("text"), str) for d in docs)):
            write({"id": req.get("id"),
                   "error": "bad request: 'docs' must be a non-empty "
                            "list of {\"name\": str, \"text\": str}"})
            return True
        try:
            out = server.add_docs([d["name"] for d in docs],
                                  [d["text"] for d in docs])
            write({"id": req.get("id"), "added": out["added"],
                   "updated": out["updated"], "sealed": out["sealed"],
                   "epoch": out["epoch"]})
        except (RuntimeError, ValueError) as e:
            write({"id": req.get("id"), "error": f"add_docs failed: {e}"})
        return True
    if op == "delete_docs":
        names = req.get("names")
        if (not isinstance(names, list) or not names
                or not all(isinstance(n, str) for n in names)):
            write({"id": req.get("id"),
                   "error": "bad request: 'names' must be a non-empty "
                            "list of strings"})
            return True
        try:
            out = server.delete_docs(names)
            write({"id": req.get("id"), "deleted": out["deleted"],
                   "missing": out["missing"], "epoch": out["epoch"]})
        except (RuntimeError, ValueError) as e:
            write({"id": req.get("id"),
                   "error": f"delete_docs failed: {e}"})
        return True
    if op == "set_scorer":
        try:
            epoch = server.set_scorer(req.get("scorer"))
            write({"id": req.get("id"),
                   "scorer": server.default_scorer_key(),
                   "epoch": epoch})
        except (ValueError, TypeError) as e:
            write({"id": req.get("id"),
                   "error": f"set_scorer failed: {e}"})
        return True
    if op is not None:
        write({"id": req.get("id"), "error": f"unknown op {op!r}"})
        return True

    line_id = req.get("id")
    queries = req.get("queries")
    if not isinstance(queries, list) or not all(
            isinstance(q, str) for q in queries):
        write({"id": line_id, "error": "bad request: 'queries' must be a "
                                   "list of strings"})
        return True
    k = int(req.get("k", default_k))
    names = server.doc_names()
    # Fleet trace adoption (round 23): a front-routed request arrives
    # with a compact trace context; malformed/missing/disabled all
    # degrade to None — the request proceeds rid-only, never fails.
    from tfidf_tpu.obs import disttrace
    tctx = disttrace.from_wire(req.get("trace"))

    def on_done(f):
        # The request id (round 16) rides every response line — the
        # client-visible half of the forensic join: the same rid is
        # on the request's spans, its flight digest and any
        # slow_query event.
        extra = ({"rid": f.rid}
                 if getattr(f, "rid", None) is not None else {})
        if getattr(f, "trace", None) is not None:
            # The fleet trace id echoes next to the rid: the front
            # (and doctor --request) join this response to the spans
            # every process recorded under the same t<16hex> key.
            extra["trace"] = f.trace
        if getattr(f, "epoch", None) is not None:
            # The admitted epoch on every response line: the
            # replicated front's mixed-epoch audit (and any client's
            # consistency check) reads it straight off the protocol.
            extra["epoch"] = f.epoch
        err = f.exception()
        if isinstance(err, Overloaded):
            write({"id": line_id, "error": "overloaded", **extra})
        elif isinstance(err, DeadlineExceeded):
            write({"id": line_id, "error": "deadline_exceeded", **extra})
        elif isinstance(err, PoisonQuery):
            write({"id": line_id, "error": "poison_query",
                   "detail": str(err), **extra})
        elif err is not None:
            write({"id": line_id, "error": str(err), **extra})
        else:
            vals, idx = f.result()
            write({"id": line_id, "results": [
                [[names[int(d)], float(v)]
                 for v, d in zip(vrow, irow) if d >= 0]
                for vrow, irow in zip(vals, idx)], **extra})

    try:
        server.submit(queries, k,
                      deadline_ms=req.get("deadline_ms"),
                      use_cache=bool(req.get("use_cache", True)),
                      scorer=req.get("scorer"),
                      filter=req.get("filter"),
                      trace=(tctx.trace if tctx is not None else None)
                      ).add_done_callback(on_done)
    except (ValueError, TypeError) as e:  # malformed scorer/filter spec
        write({"id": line_id, "error": f"bad request: {e}"})
    except PoisonQuery as e:     # quarantined: the protocol's 4xx
        write({"id": line_id, "error": "poison_query", "detail": str(e),
               **({"rid": e.rid} if getattr(e, "rid", None) else {})})
    except (Overloaded, ServeError) as e:
        write({"id": line_id,
               "error": "overloaded" if isinstance(e, Overloaded)
               else str(e),
               **({"rid": e.rid} if getattr(e, "rid", None) else {})})
    return True


def _run_serve(args) -> int:
    """Online serving loop: JSONL requests over stdin/stdout (or TCP
    with --port) against a TfidfServer (docs/SERVING.md). Responses
    come back in COMPLETION order — clients correlate by "id"."""
    import json
    import threading

    from tfidf_tpu.config import (PipelineConfig, ServeConfig, VocabMode,
                                  apply_compile_cache)
    from tfidf_tpu.models import TfidfRetriever
    from tfidf_tpu.serve import TfidfServer

    apply_compile_cache(args.compile_cache)
    if args.score_tiling is not None:
        # CLI mirror of TFIDF_TPU_SCORE_TILING: the knob is read at
        # dispatch time, so the env var is the single source of truth
        # for every consumer (flat, segmented, mesh, serve).
        os.environ["TFIDF_TPU_SCORE_TILING"] = args.score_tiling
    cfg = PipelineConfig(vocab_mode=VocabMode.HASHED,
                         vocab_size=args.vocab_size,
                         compile_cache=args.compile_cache)

    def build_retriever(input_dir: str) -> TfidfRetriever:
        return TfidfRetriever(cfg).index_dir(
            input_dir, strict=not args.no_strict, doc_len=args.doc_len)

    serve_cfg = ServeConfig.from_env(
        max_batch=args.max_batch, max_wait_ms=args.max_wait_ms,
        queue_depth=args.queue_depth, cache_entries=args.cache_entries,
        default_deadline_ms=args.deadline_ms,
        health_period_ms=args.health_period_ms,
        devmon_period_ms=args.devmon_period_ms,
        snapshot_dir=args.snapshot_dir, faults=args.faults,
        fault_seed=args.fault_seed, slow_ms=args.slow_ms,
        slo_ms=args.slo_ms, slo_target=args.slo_target,
        delta_docs=args.delta_docs, compact_at=args.compact_at,
        mesh_shards=args.mesh_shards,
        query_slab=(None if args.query_slab is None
                    else args.query_slab == "on"),
        disttrace=(None if args.disttrace is None
                   else args.disttrace == "on"),
        pipeline_depth=args.serve_pipeline_depth,
        replicas=args.replicas,
        replica_timeout_s=args.replica_timeout_s,
        scorer=args.scorer, bm25_k1=args.bm25_k1, bm25_b=args.bm25_b)

    if serve_cfg.disttrace is not None:
        # Resolve the fleet-tracing verdict once for this process
        # (flag > env > default-on); a plain single server still
        # ADOPTS inbound trace contexts — a front one hop up may be
        # doing the minting.
        from tfidf_tpu.obs import disttrace
        disttrace.configure(serve_cfg.disttrace)

    if serve_cfg.replicas:
        # Replicated tier: this process becomes the FRONT — it owns
        # the protocol and the replicas own the indexes; nothing
        # below (restore, warm, canary, compactor) happens here.
        return _run_serve_front(args, cfg, serve_cfg)

    # Crash-fast start: a committed snapshot with a matching config
    # fingerprint restores the resident index from disk — seconds, no
    # corpus read at all (the restart acceptance pin deletes the
    # corpus to prove it). A mismatched/corrupt snapshot falls back
    # to the normal build, loudly.
    from tfidf_tpu import checkpoint as ckpt
    from tfidf_tpu.obs import log as obs_log
    retriever = None
    restored_meta = None
    segments = None
    if serve_cfg.delta_docs:
        # Segmented serving (round 17): the resident index is an
        # LSM-style SegmentedIndex; the server holds its current VIEW
        # and the add_docs/delete_docs ops mutate it live.
        from tfidf_tpu.index import SegmentedIndex
        if serve_cfg.snapshot_dir and ckpt.exists(serve_cfg.snapshot_dir):
            t0 = time.monotonic()
            try:
                segments, restored_meta = SegmentedIndex.restore(
                    serve_cfg.snapshot_dir, cfg)
            except ckpt.SnapshotMismatch as e:
                sys.stderr.write(
                    f"snapshot at {serve_cfg.snapshot_dir} unusable "
                    f"({e}); rebuilding from --input\n")
            else:
                obs_log.log_event(
                    "info", "index_restored",
                    msg=f"segmented index restored from "
                        f"{serve_cfg.snapshot_dir} "
                        f"(epoch {restored_meta.get('epoch', 0)}, "
                        f"{segments.num_docs} live docs) in "
                        f"{time.monotonic() - t0:.3f}s",
                    epoch=restored_meta.get("epoch", 0),
                    docs=segments.num_docs,
                    restore_s=round(time.monotonic() - t0, 4))
        if segments is None:
            segments = SegmentedIndex.from_dir(
                args.input, cfg, delta_docs=serve_cfg.delta_docs,
                compact_at=serve_cfg.compact_at,
                strict=not args.no_strict)
        retriever = segments.view()
    elif serve_cfg.snapshot_dir and ckpt.exists(serve_cfg.snapshot_dir):
        t0 = time.monotonic()
        try:
            retriever, restored_meta = TfidfRetriever.restore(
                serve_cfg.snapshot_dir, cfg)
        except ckpt.SnapshotMismatch as e:
            sys.stderr.write(f"snapshot at {serve_cfg.snapshot_dir} "
                             f"unusable ({e}); rebuilding from "
                             f"--input\n")
        else:
            obs_log.log_event(
                "info", "index_restored",
                msg=f"index restored from {serve_cfg.snapshot_dir} "
                    f"(epoch {restored_meta.get('epoch', 0)}, "
                    f"{retriever._num_docs} docs) in "
                    f"{time.monotonic() - t0:.3f}s — corpus not "
                    f"re-ingested",
                epoch=restored_meta.get("epoch", 0),
                docs=retriever._num_docs,
                restore_s=round(time.monotonic() - t0, 4))
    if retriever is None:
        retriever = build_retriever(args.input)
    server = TfidfServer(
        retriever, serve_cfg,
        initial_epoch=(int(restored_meta.get("epoch", 0))
                       if restored_meta else 0))
    compactor = None
    if segments is not None:
        from tfidf_tpu.index import Compactor
        server.attach_segments(segments)
        compactor = Compactor(
            server.compact_now,
            restart_budget=serve_cfg.restart_budget).start()
    if serve_cfg.snapshot_dir and restored_meta is None:
        # First boot on this snapshot root: persist the fresh build
        # so the NEXT start (or a crash one second from now) restores.
        server.snapshot()
    if not args.no_warm:
        # Touch every power-of-two query bucket steady state can see
        # (empty queries compile the same Q-shaped programs), then
        # draw the warm line: from here the compile watchdog flags
        # any fresh search program as a steady-state recompile —
        # flight event + windowed degraded health reason. Warm the
        # INSTALLED index (the server may have mesh-sharded it):
        # warming the single-device program under --mesh-shards would
        # leave every sharded program cold, to surface as a
        # steady-state recompile on the first real batch.
        _, installed = server.current_index()
        warm_targets = [installed]
        # A mesh-sharded index keeps its single-device source as the
        # canary parity oracle; its buckets must be warm too, or the
        # first oracle capture would read as a steady-state recompile.
        oracle = getattr(installed, "parity_oracle", lambda: None)()
        if oracle is not None:
            warm_targets.append(oracle)
        b = 1
        while b <= serve_cfg.max_batch:
            for target in warm_targets:
                target.search([""] * b, k=args.k)
            b *= 2
        server.mark_warm()
    # The serve process's monitor is THE process monitor: reindex
    # pack/drain workers (swap_index) heartbeat into the same health
    # view as the batcher (obs/health.py module hook).
    from tfidf_tpu.obs import health as obs_health
    obs_health.set_monitor(server.health)
    canary = None
    if args.canary_period_ms and args.canary_period_ms > 0:
        from tfidf_tpu.serve import CanaryProber, pinned_queries_from_dir
        try:
            pinned = pinned_queries_from_dir(args.input,
                                             n=args.canary_queries,
                                             strict=not args.no_strict)
        except (OSError, ValueError):
            # Snapshot-restored server without the corpus on disk:
            # no pinned queries to derive — serve without the canary.
            pinned = []
        if pinned:
            canary = CanaryProber(
                server, pinned, k=args.k,
                period_s=args.canary_period_ms / 1e3).start()
    snap_state = ("restored" if restored_meta
                  else "on" if serve_cfg.snapshot_dir else "off")
    sys.stderr.write(f"serving {server.num_docs} docs "
                     f"(max_batch={serve_cfg.max_batch}, "
                     f"max_wait_ms={serve_cfg.max_wait_ms}, "
                     f"queue_depth={serve_cfg.queue_depth}, "
                     f"cache_entries={serve_cfg.cache_entries}, "
                     f"health_period_ms={serve_cfg.health_period_ms}, "
                     f"canary={'on' if canary else 'off'}, "
                     f"snapshot={snap_state}, "
                     f"faults={'armed' if serve_cfg.faults else 'off'}, "
                     f"segments="
                     f"{'on' if segments is not None else 'off'}, "
                     f"mesh="
                     f"{serve_cfg.mesh_shards if serve_cfg.mesh_shards is not None else 'off'}"
                     f")\n")

    prev_term = _install_sigterm_dump()
    try:
        if args.port is not None:
            def handle(line, write):
                return _serve_handle_line(server, line, write, args.k,
                                          build_retriever, canary)

            def on_close():
                if canary is not None:
                    canary.close()
                server.close(drain=True)
            return _serve_tcp(handle, args.port, on_close)
        # Responses may be written from batcher callback threads while
        # the main thread blocks on the next stdin line — one lock
        # keeps the JSONL stream line-atomic.
        wlock = threading.Lock()

        def write(obj) -> None:
            with wlock:
                sys.stdout.write(json.dumps(obj) + "\n")
                sys.stdout.flush()

        try:
            for line in sys.stdin:
                if not _serve_handle_line(server, line, write, args.k,
                                          build_retriever, canary):
                    break
        finally:
            if canary is not None:
                canary.close()
            server.close(drain=True)
        return 0
    finally:
        if compactor is not None:
            compactor.stop()
        _restore_sigterm(prev_term)
        obs_health.set_monitor(None)


def _install_sigterm_dump():
    """SIGTERM must leave evidence: dump the flight recorder and the
    trace (atomic writes), then exit 143 — the crash-consistent
    shutdown the ISSUE's incident story needs. Returns the previous
    handler (restored by the caller — in-process test runs must not
    leak a handler into the host process). No-op off the main thread
    or on platforms without signals."""
    import signal
    import threading as _threading

    if _threading.current_thread() is not _threading.main_thread():
        return None

    def _on_term(signum, frame):
        from tfidf_tpu import obs
        obs.get_log().warning("sigterm",
                              msg="SIGTERM: dumping flight recorder "
                                  "and trace")
        obs.dump_flight()
        obs.export()
        os._exit(143)

    try:
        return signal.signal(signal.SIGTERM, _on_term)
    except (ValueError, OSError):  # non-main interpreter contexts
        return None


def _restore_sigterm(prev) -> None:
    if prev is None:
        return
    import signal
    try:
        signal.signal(signal.SIGTERM, prev)
    except (ValueError, OSError):
        pass


def _serve_tcp(handle_line, port, on_close) -> int:
    """--port mode: the same JSONL protocol over TCP, one thread per
    connection (socketserver), all feeding one shared backend —
    which is the point: their queries coalesce into shared batches
    (single server) or fan out across the replica tier (front).
    ``handle_line(line, write) -> bool`` is the protocol handler;
    ``on_close()`` tears the backend down after the listener stops."""
    import json
    import socketserver
    import threading

    class Handler(socketserver.StreamRequestHandler):
        def handle(self):
            wlock = threading.Lock()

            def write(obj):
                with wlock:
                    try:
                        self.wfile.write((json.dumps(obj) + "\n").encode())
                        self.wfile.flush()
                    except OSError:
                        pass  # client went away; drop the response

            for raw in self.rfile:
                if not handle_line(raw.decode("utf-8", "replace"),
                                   write):
                    threading.Thread(target=srv.shutdown,
                                     daemon=True).start()
                    return

    class Srv(socketserver.ThreadingTCPServer):
        allow_reuse_address = True
        daemon_threads = True

    with Srv(("127.0.0.1", port), Handler) as srv:
        sys.stderr.write(f"listening on 127.0.0.1:{srv.server_address[1]}\n")
        try:
            srv.serve_forever()
        except KeyboardInterrupt:
            pass
        finally:
            on_close()
    return 0


def _run_serve_front(args, cfg, serve_cfg) -> int:
    """--replicas mode: this process is the replicated tier's FRONT.
    It holds no index and no device link — it spawns N replica
    processes off --snapshot-dir, routes the JSONL protocol across
    them, and supervises restarts (docs/SERVING.md 'Replicated
    tier')."""
    import json
    import threading

    from tfidf_tpu.serve import FrontError, ReplicatedFront

    front = ReplicatedFront(args.input, cfg, serve_cfg, k=args.k,
                            no_strict=args.no_strict,
                            doc_len=args.doc_len)
    prev_term = _install_sigterm_dump()
    try:
        try:
            front.start()
        except FrontError as e:
            sys.stderr.write(f"front failed to start: {e}\n")
            front.close()
            return 3
        sys.stderr.write(
            f"front serving {front.n_replicas} replica(s) "
            f"(epoch={front.epoch}, "
            f"snapshot={serve_cfg.snapshot_dir}, "
            f"restart_budget={serve_cfg.restart_budget}, "
            f"timeout_s={serve_cfg.replica_timeout_s})\n")
        if args.port is not None:
            return _serve_tcp(front.handle_line, args.port,
                              front.close)
        wlock = threading.Lock()

        def write(obj) -> None:
            with wlock:
                sys.stdout.write(json.dumps(obj) + "\n")
                sys.stdout.flush()

        try:
            for line in sys.stdin:
                if not front.handle_line(line, write):
                    break
        finally:
            front.close()
        return 0
    finally:
        _restore_sigterm(prev_term)


def main(argv: Optional[List[str]] = None) -> int:
    args = _build_parser().parse_args(argv)
    if args.cmd == "run" and args.backend == "mpi":
        return _run_mpi(args)  # native oracle: no jax, no host spans
    # Arm the span tracer first (--trace / TFIDF_TPU_TRACE; no-op when
    # neither is set) so every span of the run lands on one timeline,
    # and export whatever was recorded on ANY exit — a crashed run's
    # partial trace is exactly when you want the timeline. The flight
    # recorder (--flight / TFIDF_TPU_FLIGHT, or derived from the trace
    # path) dumps on the same exits: trace + flight are one incident's
    # evidence (docs/OBSERVABILITY.md).
    from tfidf_tpu import obs
    obs.configure(getattr(args, "trace", None))
    obs.configure_flight(getattr(args, "flight", None))
    try:
        if args.cmd == "run":
            return _run_tpu(args)
        if args.cmd == "stream":
            return _run_stream(args)
        if args.cmd == "query":
            return _run_query(args)
        if args.cmd == "serve":
            return _run_serve(args)
        return 2
    finally:
        path = obs.export()
        if path:
            sys.stderr.write(f"trace written to {path} (open in "
                             f"Perfetto; check: tools/trace_check.py)\n")
        fpath = obs.dump_flight()
        if fpath:
            sys.stderr.write(f"flight recorder dumped to {fpath} "
                             f"(check: tools/trace_check.py "
                             f"--flight)\n")


if __name__ == "__main__":
    sys.exit(main())
