"""Command-line driver: ``tfidf run --input DIR --backend {tpu,mpi}``.

The reference ignores ``argc/argv`` entirely and hardcodes its input dir,
output path, and limits as ``#define``s (``TFIDF.c:16-20,52,101,133,274``).
This driver exposes every knob, per the BASELINE north star: the MPI-
semantics native path stays available as ``--backend=mpi`` (the oracle),
the TPU path is ``--backend=tpu``.
"""

from __future__ import annotations

import argparse
import os
import subprocess
import sys
from typing import List, Optional

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
NATIVE_BIN = os.path.join(REPO_ROOT, "native", "tfidf_ref")


def _build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="tfidf", description=__doc__)
    sub = p.add_subparsers(dest="cmd", required=True)
    run = sub.add_parser("run", help="run the TF-IDF pipeline")
    run.add_argument("--input", required=True, help="document directory")
    run.add_argument("--output", default="output.txt",
                     help="output file (reference format)")
    run.add_argument("--backend", choices=["tpu", "mpi"], default="tpu")
    run.add_argument("--engine", choices=["dense", "sparse"], default="dense",
                     help="dense [D,V] histograms or row-sparse O(D*L)")
    run.add_argument("--pallas", action="store_true",
                     help="use the Pallas TPU histogram kernel")
    run.add_argument("--vocab-mode", choices=["exact", "hashed"],
                     default="exact")
    run.add_argument("--vocab-size", type=int, default=1 << 16,
                     help="hashed vocabulary size")
    run.add_argument("--tokenizer", choices=["whitespace", "chargram"],
                     default="whitespace")
    run.add_argument("--ngram", type=str, default="3,5",
                     help="chargram n range, e.g. 3,5")
    run.add_argument("--topk", type=int, default=None,
                     help="emit only top-k terms per document")
    run.add_argument("--mesh", type=str, default=None,
                     help="mesh shape docs,seq,vocab (e.g. 4,1,2); "
                          "default: single device")
    run.add_argument("--no-strict", action="store_true",
                     help="accept any filenames, not just doc<i>")
    run.add_argument("--nranks", type=int, default=4,
                     help="ranks for --backend=mpi (thread backend)")
    return p


def _run_mpi(args) -> int:
    """Dispatch to the native bit-reference (the --backend=mpi oracle)."""
    if not os.path.exists(NATIVE_BIN):
        rc = subprocess.run(["make", "-C", os.path.dirname(NATIVE_BIN)],
                            capture_output=True)
        if rc.returncode != 0 or not os.path.exists(NATIVE_BIN):
            sys.stderr.write("error: native backend not built "
                             "(make -C native failed)\n")
            return 1
    proc = subprocess.run(
        [NATIVE_BIN, args.input, args.output, str(args.nranks)])
    return proc.returncode


def _run_tpu(args) -> int:
    # Deferred: importing jax is slow and unnecessary for --backend=mpi.
    from tfidf_tpu.config import PipelineConfig, TokenizerKind, VocabMode
    from tfidf_tpu.formatter import write_output
    from tfidf_tpu.io.corpus import discover_corpus
    from tfidf_tpu.pipeline import TfidfPipeline

    lo, hi = (int(x) for x in args.ngram.split(","))
    cfg = PipelineConfig(
        vocab_mode=VocabMode(args.vocab_mode),
        vocab_size=args.vocab_size,
        tokenizer=TokenizerKind(args.tokenizer),
        ngram_range=(lo, hi),
        topk=args.topk,
        engine=args.engine,
        use_pallas=args.pallas,
    )
    corpus = discover_corpus(args.input, strict=not args.no_strict)

    if args.mesh:
        from tfidf_tpu.parallel import MeshPlan, ShardedPipeline
        docs, seq, vocab = (int(x) for x in args.mesh.split(","))
        plan = MeshPlan.create(docs=docs, seq=seq, vocab=vocab)
        result = ShardedPipeline(plan, cfg).run(corpus)
    else:
        result = TfidfPipeline(cfg).run(corpus)

    if args.topk is None:
        write_output(args.output, result.output_lines())
    else:
        _write_topk(args.output, result)
    print(f"wrote {args.output} ({result.num_docs} docs)")
    return 0


def _write_topk(path: str, result) -> None:
    """Top-k report: doc@word\\tscore, k lines per doc, score-descending."""
    lines: List[bytes] = []
    for d in range(result.num_docs):
        name = result.names[d].encode()
        for v, s in zip(result.topk_ids[d], result.topk_vals[d]):
            if s <= 0:
                continue  # padding / sub-k docs
            word = result.id_to_word.get(int(v), b"id:%d" % int(v))
            lines.append(b"%s@%s\t%.16f" % (name, word, float(s)))
    with open(path, "wb") as f:
        f.write(b"".join(l + b"\n" for l in lines))


def main(argv: Optional[List[str]] = None) -> int:
    args = _build_parser().parse_args(argv)
    if args.cmd == "run":
        if args.backend == "mpi":
            return _run_mpi(args)
        return _run_tpu(args)
    return 2


if __name__ == "__main__":
    sys.exit(main())
