"""Streaming minibatched TF-IDF with incremental DF state.

BASELINE config 5. The reference is a single-shot batch job — its only
lifecycle is run-once, write ``output.txt``, exit (``TFIDF.c:52-287``);
corpus growth means rerunning from scratch. Here DF is *state*: an
``[V]`` int32 vector (sharded over the vocab axis when a mesh is given)
updated in place per minibatch with a donated-buffer jitted step, so a
corpus can stream through in fixed-memory minibatches.

Two-phase usage mirrors classic out-of-core TF-IDF:

  1. ``update(batch)`` per minibatch — accumulates DF and the doc count.
     On a mesh this is the incremental ``lax.psum`` of BASELINE config 5.
  2. ``score(batch)`` — scores any minibatch against the *current* DF
     snapshot (so scores after a full pass are exact corpus-wide TF-IDF;
     scores mid-stream are the online approximation).

State can be checkpointed/restored (``state_dict``/``load_state``) —
the persist-DF-between-minibatches capability noted in SURVEY §5
(checkpoint/resume).
"""

from __future__ import annotations

import functools
from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import PartitionSpec as P

from tfidf_tpu import obs
from tfidf_tpu.config import PipelineConfig, VocabMode
from tfidf_tpu.io.corpus import (Corpus, PackedBatch, RaggedBatch,
                                 pack_corpus)
from tfidf_tpu.ops.downlink import (pack_words, unpack_result_words,
                                    use_packed_result_wire)
from tfidf_tpu.ops.histogram import df_from_counts, tf_counts
from tfidf_tpu.ops.scoring import idf_from_df, tfidf_dense
from tfidf_tpu.ops.sparse import (score_topk, sorted_term_counts,
                                  sparse_df, sparse_scores, sparse_topk)
from tfidf_tpu.parallel.mesh import DOCS_AXIS, MeshPlan
from tfidf_tpu.parallel.compat import shard_map


@functools.partial(jax.jit, static_argnames=("vocab_size",), donate_argnums=(0,))
def _update_df(df_state, token_ids, lengths, *, vocab_size: int):
    """df_state += DF(minibatch), dense scatter lowering. Kept as the
    parity oracle and the vocab/seq-sharded mesh path; the default is
    the sort+RLE lowering (docs/ENGINES.md measured it 1.5-2.7x
    faster — VERDICT r3 weak-4: every engine call site follows the
    measured doctrine)."""
    counts = tf_counts(token_ids, lengths, vocab_size)
    return df_state + df_from_counts(counts)


@functools.partial(jax.jit, static_argnames=("vocab_size",), donate_argnums=(0,))
def _update_df_sparse(df_state, token_ids, lengths, *, vocab_size: int):
    """df_state += DF(minibatch), sort+RLE lowering (the measured
    default engine, docs/ENGINES.md)."""
    ids, _, head = sorted_term_counts(token_ids, lengths)
    return df_state + sparse_df(ids, head, vocab_size)


@functools.partial(jax.jit,
                   static_argnames=("vocab_size", "topk", "score_dtype"))
def _score_batch(df_state, num_docs, token_ids, lengths, *,
                 vocab_size: int, topk: Optional[int], score_dtype):
    counts = tf_counts(token_ids, lengths, vocab_size)
    scores = tfidf_dense(counts, lengths, df_state, num_docs, score_dtype)
    if topk is None:
        return scores
    return jax.lax.top_k(scores, min(topk, vocab_size))


@functools.partial(jax.jit,
                   static_argnames=("vocab_size", "topk", "score_dtype"))
def _score_batch_sparse(df_state, num_docs, token_ids, lengths, *,
                        vocab_size: int, topk: int, score_dtype):
    """Sort+RLE scoring: the [batch, V] score matrix is never built —
    per-doc candidates are the L row slots. Routed through
    ``ops.sparse.score_topk`` like the ingest phase-B kernels, so
    ``TFIDF_TPU_SCORE=pallas`` selects the fused Mosaic score/top-k
    kernel here too (mesh bodies keep the explicit XLA pair)."""
    ids, counts, head = sorted_term_counts(token_ids, lengths)
    idf = idf_from_df(df_state, num_docs, score_dtype)
    return score_topk(ids, counts, head, lengths, idf, topk)


# Docs-sharded sort+RLE minibatch kernels: DF state rides replicated,
# each shard sorts its own rows, and the update's psum over the docs
# axis is BASELINE config 5's "incremental lax.psum" made literal.
@functools.lru_cache(maxsize=32)
def _mesh_update_sparse_fn(plan: MeshPlan, vocab_size: int):
    def body(df_state, toks, lens):
        ids, _, head = sorted_term_counts(toks, lens)
        return df_state + lax.psum(sparse_df(ids, head, vocab_size),
                                   DOCS_AXIS)

    mapped = shard_map(
        body, mesh=plan.mesh,
        in_specs=(P(None), P(DOCS_AXIS, None), P(DOCS_AXIS)),
        out_specs=P(None), check_vma=False)
    return jax.jit(mapped, donate_argnums=(0,))


@functools.lru_cache(maxsize=32)
def _mesh_score_sparse_fn(plan: MeshPlan, vocab_size: int, topk: int,
                          score_dtype):
    def body(df_state, num_docs, toks, lens):
        ids, counts, head = sorted_term_counts(toks, lens)
        idf = idf_from_df(df_state, num_docs, score_dtype)
        scores = sparse_scores(ids, counts, head, lens, idf)
        return sparse_topk(scores, ids, head, topk)

    mapped = shard_map(
        body, mesh=plan.mesh,
        in_specs=(P(None), P(), P(DOCS_AXIS, None), P(DOCS_AXIS)),
        out_specs=(P(DOCS_AXIS, None), P(DOCS_AXIS, None)),
        check_vma=False)
    return jax.jit(mapped)


class StreamingTfidf:
    """Fixed-memory streaming TF-IDF over minibatches.

    Requires HASHED vocab (a fixed id space across batches — EXACT mode
    would renumber words per batch).
    """

    def __init__(self, config: Optional[PipelineConfig] = None,
                 plan: Optional[MeshPlan] = None):
        cfg = config or PipelineConfig(vocab_mode=VocabMode.HASHED)
        if cfg.vocab_mode is not VocabMode.HASHED:
            raise ValueError("streaming requires VocabMode.HASHED "
                             "(fixed vocab ids across minibatches)")
        self.config = cfg
        self.plan = plan
        # Engine doctrine (docs/ENGINES.md): sort+RLE is the measured
        # default; the dense scatter lowering serves vocab/seq-sharded
        # meshes (sparse shards the docs axis only) and stays pinned as
        # the parity oracle. Same capability-vs-preference rule as
        # ShardedPipeline: a measured default falls back silently, an
        # explicit engine="sparse" on an incompatible mesh errors.
        self._engine = cfg.engine
        if (self._engine == "sparse" and plan is not None
                and (plan.n_seq_shards != 1 or plan.n_vocab_shards != 1)):
            if getattr(cfg, "_engine_defaulted", False):
                self._engine = "dense"
            else:
                raise ValueError("sparse streaming shards the docs axis "
                                 "only; build the MeshPlan with seq=1, "
                                 "vocab=1 or use engine='dense'")
        self._vocab = (plan.pad_vocab(cfg.vocab_size) if plan
                       else cfg.vocab_size)
        df = jnp.zeros((self._vocab,), jnp.int32)
        if plan is not None:
            df = jax.device_put(df, plan.sharding(plan.df_spec()))
        self._df = df
        self._docs_seen = 0

    # --- state ---
    @property
    def docs_seen(self) -> int:
        return self._docs_seen

    def df(self) -> np.ndarray:
        return np.asarray(self._df)[: self.config.vocab_size]

    def state_dict(self) -> Dict[str, np.ndarray]:
        return {"df": np.asarray(self._df),
                "docs_seen": np.asarray(self._docs_seen)}

    def load_state(self, state: Dict[str, np.ndarray]) -> None:
        df = jnp.asarray(state["df"])
        if df.shape != (self._vocab,):
            raise ValueError(f"df shape {df.shape} != ({self._vocab},)")
        if self.plan is not None:
            df = jax.device_put(df, self.plan.sharding(self.plan.df_spec()))
        self._df = df
        self._docs_seen = int(state["docs_seen"])

    # --- packing ---
    def pack(self, corpus: Corpus,
             fixed_len: Optional[int] = None) -> PackedBatch:
        """Pack a minibatch. ``fixed_len`` pins the token axis to one
        static L (truncating longer docs) so every minibatch of a stream
        shares a single compiled update/score program — without it, L
        grows to the batch's longest doc and each new shape recompiles.
        """
        pad = (self.plan.pad_docs(len(corpus)) if self.plan else None)
        batch = pack_corpus(corpus, self.config, pad_docs_to=pad,
                            want_words=False)
        if fixed_len is None or batch.token_ids.shape[1] == fixed_len:
            return batch
        ids = batch.token_ids[:, :fixed_len]
        if ids.shape[1] < fixed_len:
            ids = np.pad(ids, ((0, 0), (0, fixed_len - ids.shape[1])))
        return PackedBatch(
            token_ids=ids,
            lengths=np.minimum(batch.lengths, fixed_len).astype(np.int32),
            num_docs=batch.num_docs, names=batch.names,
            vocab_size=batch.vocab_size, id_to_word=batch.id_to_word)

    def pack_ragged(self, corpus: Corpus,
                    fixed_len: Optional[int] = None) -> RaggedBatch:
        """Pack a minibatch in the ragged wire format (one flat aligned
        id stream — host→device bytes scale with real tokens, not
        D×L; ``io.corpus.pack_ragged``). ``fixed_len`` pins the rebuilt
        batch's static L exactly like :meth:`pack` — without it each
        new longest-doc length recompiles the update/score programs.
        ``update``/``score`` accept the result directly: single-device
        runs rebuild the padded batch ON DEVICE; mesh runs rebuild on
        host (the mesh wire stays padded by doctrine)."""
        from tfidf_tpu.io.corpus import ragged_from_packed
        return ragged_from_packed(self.pack(corpus, fixed_len=fixed_len))

    def _place(self, batch):
        if isinstance(batch, RaggedBatch):
            if self.plan is not None:
                batch = batch.to_padded()  # mesh wire stays padded
            else:
                from tfidf_tpu.ingest import rebuild_padded
                lens = jnp.asarray(batch.lengths)
                return rebuild_padded(jnp.asarray(batch.flat), lens,
                                      length=batch.length,
                                      align=batch.align), lens
        toks, lens = jnp.asarray(batch.token_ids), jnp.asarray(batch.lengths)
        if self.plan is not None:
            toks = jax.device_put(toks, self.plan.sharding(self.plan.batch_spec()))
            lens = jax.device_put(lens, self.plan.sharding(self.plan.lengths_spec()))
        return toks, lens

    # --- the two phases ---
    def update(self, batch: PackedBatch) -> None:
        """Fold one minibatch into the DF state (incremental psum)."""
        with obs.device_span("stream_update", docs=batch.num_docs):
            self._update(batch)

    def _update(self, batch: PackedBatch) -> None:
        toks, lens = self._place(batch)
        if self._engine == "sparse":
            if self.plan is not None:
                fn = _mesh_update_sparse_fn(self.plan, self._vocab)
                self._df = fn(self._df, toks, lens)
            else:
                self._df = _update_df_sparse(self._df, toks, lens,
                                             vocab_size=self._vocab)
        else:
            self._df = _update_df(self._df, toks, lens,
                                  vocab_size=self._vocab)
        self._docs_seen += batch.num_docs

    def score(self, batch: PackedBatch):
        """Score a minibatch against the current DF snapshot.

        Sparse engine + topk: per-doc candidates are the L row slots
        (never a [batch, V] matrix); invalid slots come back (0, -1)
        per the sparse_topk contract, and k clamps to L (a doc cannot
        hold more than L distinct terms). topk=None always takes the
        dense lowering — the full [batch, V] score matrix IS the ask.

        Top-k selections come back as HOST arrays, fetched over the
        packed result wire when it can carry the run (ops/downlink —
        one uint32 word per slot; ids bit-exact, scores within 16-bit
        rounding; ``result_wire="pair"`` restores the full-precision
        device-array return). On a mesh the words pack per shard
        (elementwise, no collective) before the gathering fetch.
        """
        with obs.device_span("stream_score", docs=batch.num_docs):
            return self._score(batch)

    def _score(self, batch: PackedBatch):
        toks, lens = self._place(batch)
        topk = self.config.topk
        score_dtype = jnp.dtype(self.config.score_dtype)
        if self._engine == "sparse" and topk is not None:
            k = min(topk, toks.shape[1])
            if self.plan is not None:
                fn = _mesh_score_sparse_fn(self.plan, self._vocab, k,
                                           score_dtype)
                out = fn(self._df, jnp.int32(self._docs_seen), toks, lens)
            else:
                out = _score_batch_sparse(
                    self._df, jnp.int32(self._docs_seen), toks, lens,
                    vocab_size=self._vocab, topk=k,
                    score_dtype=score_dtype)
        else:
            out = _score_batch(self._df, jnp.int32(self._docs_seen),
                               toks, lens, vocab_size=self._vocab,
                               topk=topk, score_dtype=score_dtype)
        # The padded mesh vocab is the id bound the wire must carry —
        # a tail-padded bucket can be selected by sub-k docs.
        if topk is not None and use_packed_result_wire(
                self.config, vocab_size=self._vocab):
            words = np.asarray(pack_words(*out))
            return unpack_result_words(words, score_dtype=score_dtype)
        return out
