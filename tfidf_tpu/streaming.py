"""Streaming minibatched TF-IDF with incremental DF state.

BASELINE config 5. The reference is a single-shot batch job — its only
lifecycle is run-once, write ``output.txt``, exit (``TFIDF.c:52-287``);
corpus growth means rerunning from scratch. Here DF is *state*: an
``[V]`` int32 vector (sharded over the vocab axis when a mesh is given)
updated in place per minibatch with a donated-buffer jitted step, so a
corpus can stream through in fixed-memory minibatches.

Two-phase usage mirrors classic out-of-core TF-IDF:

  1. ``update(batch)`` per minibatch — accumulates DF and the doc count.
     On a mesh this is the incremental ``lax.psum`` of BASELINE config 5.
  2. ``score(batch)`` — scores any minibatch against the *current* DF
     snapshot (so scores after a full pass are exact corpus-wide TF-IDF;
     scores mid-stream are the online approximation).

State can be checkpointed/restored (``state_dict``/``load_state``) —
the persist-DF-between-minibatches capability noted in SURVEY §5
(checkpoint/resume).
"""

from __future__ import annotations

import functools
from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from tfidf_tpu.config import PipelineConfig, VocabMode
from tfidf_tpu.io.corpus import Corpus, PackedBatch, pack_corpus
from tfidf_tpu.ops.histogram import df_from_counts, tf_counts
from tfidf_tpu.ops.scoring import tfidf_dense
from tfidf_tpu.parallel.mesh import MeshPlan


@functools.partial(jax.jit, static_argnames=("vocab_size",), donate_argnums=(0,))
def _update_df(df_state, token_ids, lengths, *, vocab_size: int):
    """df_state += DF(minibatch). Donated so the update is in-place."""
    counts = tf_counts(token_ids, lengths, vocab_size)
    return df_state + df_from_counts(counts)


@functools.partial(jax.jit,
                   static_argnames=("vocab_size", "topk", "score_dtype"))
def _score_batch(df_state, num_docs, token_ids, lengths, *,
                 vocab_size: int, topk: Optional[int], score_dtype):
    counts = tf_counts(token_ids, lengths, vocab_size)
    scores = tfidf_dense(counts, lengths, df_state, num_docs, score_dtype)
    if topk is None:
        return scores
    return jax.lax.top_k(scores, min(topk, vocab_size))


class StreamingTfidf:
    """Fixed-memory streaming TF-IDF over minibatches.

    Requires HASHED vocab (a fixed id space across batches — EXACT mode
    would renumber words per batch).
    """

    def __init__(self, config: Optional[PipelineConfig] = None,
                 plan: Optional[MeshPlan] = None):
        cfg = config or PipelineConfig(vocab_mode=VocabMode.HASHED)
        if cfg.vocab_mode is not VocabMode.HASHED:
            raise ValueError("streaming requires VocabMode.HASHED "
                             "(fixed vocab ids across minibatches)")
        self.config = cfg
        self.plan = plan
        self._vocab = (plan.pad_vocab(cfg.vocab_size) if plan
                       else cfg.vocab_size)
        df = jnp.zeros((self._vocab,), jnp.int32)
        if plan is not None:
            df = jax.device_put(df, plan.sharding(plan.df_spec()))
        self._df = df
        self._docs_seen = 0

    # --- state ---
    @property
    def docs_seen(self) -> int:
        return self._docs_seen

    def df(self) -> np.ndarray:
        return np.asarray(self._df)[: self.config.vocab_size]

    def state_dict(self) -> Dict[str, np.ndarray]:
        return {"df": np.asarray(self._df),
                "docs_seen": np.asarray(self._docs_seen)}

    def load_state(self, state: Dict[str, np.ndarray]) -> None:
        df = jnp.asarray(state["df"])
        if df.shape != (self._vocab,):
            raise ValueError(f"df shape {df.shape} != ({self._vocab},)")
        if self.plan is not None:
            df = jax.device_put(df, self.plan.sharding(self.plan.df_spec()))
        self._df = df
        self._docs_seen = int(state["docs_seen"])

    # --- packing ---
    def pack(self, corpus: Corpus,
             fixed_len: Optional[int] = None) -> PackedBatch:
        """Pack a minibatch. ``fixed_len`` pins the token axis to one
        static L (truncating longer docs) so every minibatch of a stream
        shares a single compiled update/score program — without it, L
        grows to the batch's longest doc and each new shape recompiles.
        """
        pad = (self.plan.pad_docs(len(corpus)) if self.plan else None)
        batch = pack_corpus(corpus, self.config, pad_docs_to=pad,
                            want_words=False)
        if fixed_len is None or batch.token_ids.shape[1] == fixed_len:
            return batch
        ids = batch.token_ids[:, :fixed_len]
        if ids.shape[1] < fixed_len:
            ids = np.pad(ids, ((0, 0), (0, fixed_len - ids.shape[1])))
        return PackedBatch(
            token_ids=ids,
            lengths=np.minimum(batch.lengths, fixed_len).astype(np.int32),
            num_docs=batch.num_docs, names=batch.names,
            vocab_size=batch.vocab_size, id_to_word=batch.id_to_word)

    def _place(self, batch: PackedBatch):
        toks, lens = jnp.asarray(batch.token_ids), jnp.asarray(batch.lengths)
        if self.plan is not None:
            toks = jax.device_put(toks, self.plan.sharding(self.plan.batch_spec()))
            lens = jax.device_put(lens, self.plan.sharding(self.plan.lengths_spec()))
        return toks, lens

    # --- the two phases ---
    def update(self, batch: PackedBatch) -> None:
        """Fold one minibatch into the DF state (incremental psum)."""
        toks, lens = self._place(batch)
        self._df = _update_df(self._df, toks, lens, vocab_size=self._vocab)
        self._docs_seen += batch.num_docs

    def score(self, batch: PackedBatch):
        """Score a minibatch against the current DF snapshot."""
        toks, lens = self._place(batch)
        return _score_batch(self._df, jnp.int32(self._docs_seen), toks, lens,
                            vocab_size=self._vocab, topk=self.config.topk,
                            score_dtype=jnp.dtype(self.config.score_dtype))
