"""End-to-end TF-IDF pipeline orchestration.

The reference's ``main()`` runs discover -> bcast -> map(TF) ->
reduce(DF) -> bcast -> score -> gather -> sort -> emit, with every phase
fenced by ``MPI_Barrier`` (``TFIDF.c:98-283``, six barriers). Here the
whole compute section is ONE jitted XLA program: phase ordering is data
dependence, not barriers, and XLA overlaps/fuses freely (SURVEY §2.3
"overlap of compute & comm").

Single-device and sharded execution share this module: when a
:class:`~tfidf_tpu.parallel.mesh.MeshPlan` is given, the same step
function is wrapped in ``shard_map`` with the document axis sharded and
DF aggregated via ``lax.psum`` (see ``tfidf_tpu/parallel``).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from tfidf_tpu.config import PipelineConfig
from tfidf_tpu.formatter import (format_records, format_sparse_records,
                                 to_output_bytes)
from tfidf_tpu.io.corpus import (Corpus, PackedBatch, RaggedBatch,
                                 pack_corpus)
from tfidf_tpu.ops.downlink import (pack_words, unpack_result_words,
                                    use_packed_result_wire)
from tfidf_tpu.ops.histogram import df_from_counts, tf_counts, tf_counts_chunked
from tfidf_tpu.ops.scoring import tfidf_dense
from tfidf_tpu.ops.sparse import sparse_forward
from tfidf_tpu.ops.topk import topk_per_doc
from tfidf_tpu.utils.timing import PhaseTimedMixin, PhaseTimer


@dataclasses.dataclass
class PipelineResult:
    """Integer-exact pipeline outputs plus device-side scores.

    counts/lengths/df are exact ints — the inputs to byte-parity host
    formatting. scores is the device float matrix (or None when topk-only
    was requested). topk_vals/topk_ids hold per-doc top-k when configured.
    """

    counts: Optional[np.ndarray]
    lengths: np.ndarray
    df: np.ndarray
    num_docs: int
    names: List[str]
    id_to_word: Dict[int, bytes]
    scores: Optional[np.ndarray] = None
    topk_vals: Optional[np.ndarray] = None
    topk_ids: Optional[np.ndarray] = None
    # Row-sparse engine outputs ([D, L] triples; see ops/sparse.py).
    sparse_ids: Optional[np.ndarray] = None
    sparse_counts: Optional[np.ndarray] = None
    sparse_head: Optional[np.ndarray] = None

    def output_lines(self) -> List[bytes]:
        """Reference-format lines (document@word\\t%.16f, strcmp order)."""
        if self.counts is not None:
            return format_records(self.counts, self.lengths, self.df,
                                  self.num_docs, self.names, self.id_to_word)
        if self.sparse_head is not None:
            return format_sparse_records(
                self.sparse_ids, self.sparse_counts, self.sparse_head,
                self.lengths, self.df, self.num_docs, self.names,
                self.id_to_word)
        raise ValueError(
            "full output lines need dense counts or row-sparse triples; "
            "this was a topk-only run (term data stays on device)")

    def output_bytes(self) -> bytes:
        return to_output_bytes(self.output_lines())


def _forward(token_ids, lengths, num_docs, *, vocab_size: int, chunk: int,
             score_dtype, topk: Optional[int], use_pallas: bool = False,
             pallas_interpret: bool = False):
    """The jitted compute: tokens -> (counts, df, scores | topk).

    Replaces reference phases 1-3 (``TFIDF.c:130-246``) and the
    CustomReduce (``TFIDF.c:291-319``) with two histograms and an
    elementwise score — all fused by XLA into one program. When ``topk``
    is set the dense [D, V] score matrix never leaves the device — only
    the [D, K] selection does (the scalable replacement for the
    reference's full gather, ``TFIDF.c:256-270``).

    ``use_pallas`` swaps the XLA scatter-add histogram for the Pallas
    compare-and-reduce kernel (``ops.pallas_kernels``), which also fuses
    the DF pass.
    """
    length = token_ids.shape[1]
    if use_pallas:
        from tfidf_tpu.ops.pallas_kernels import tf_df_pallas
        counts, df = tf_df_pallas(token_ids, lengths, vocab_size=vocab_size,
                                  interpret=pallas_interpret)
    elif length > chunk:
        counts = tf_counts_chunked(token_ids, lengths, vocab_size, chunk)
        df = df_from_counts(counts)
    else:
        counts = tf_counts(token_ids, lengths, vocab_size)
        df = df_from_counts(counts)
    scores = tfidf_dense(counts, lengths, df, num_docs, score_dtype)
    if topk is not None:
        tv, ti = topk_per_doc(scores, min(topk, vocab_size))
        return df, tv, ti
    return counts, df, scores


# Module-level jits keyed on the static config so repeat runs with the
# same shapes/config hit XLA's compilation cache instead of re-tracing.
_forward_jit = jax.jit(
    _forward,
    static_argnames=("vocab_size", "chunk", "score_dtype", "topk",
                     "use_pallas", "pallas_interpret"),
)


_sparse_forward_jit = jax.jit(
    sparse_forward,
    static_argnames=("vocab_size", "score_dtype", "topk"),
)


def _chargram_forward(byte_ids, byte_lengths, num_docs, *, vocab_size: int,
                      ngram_lo: int, ngram_hi: int, seed: int,
                      score_dtype, topk: Optional[int], df_reduce=None):
    """On-device char n-gram pipeline: raw bytes -> (df, scores | topk).

    N-gram ids are computed by rolling hash on device (BASELINE config 4,
    wide-vocab stress) — a length-B doc contributes (hi-lo+1) id streams
    without any host-side n-gram materialization. docSize is the total
    n-gram count, matching the host chargram tokenizer's token count.

    ``df_reduce`` (static): optional collective applied to the local DF
    vector — identity single-device, ``lax.psum`` over the docs axis
    inside a shard_map body (``parallel.collectives``) — the same
    sharing contract as :func:`ops.sparse.sparse_forward`.
    """
    from tfidf_tpu.ops.hashing import device_ngram_ids_multi
    from tfidf_tpu.ops.histogram import tf_counts_masked

    d, _ = byte_ids.shape
    total_len = jnp.zeros((d,), jnp.int32)
    # One fused Horner sweep emits every n's id stream (bit-identical
    # to per-n device_ngram_ids calls; VERDICT r4 item 6), and the
    # streams concatenate into ONE masked scatter — addition commutes,
    # so the summed per-n histograms equal the single wide one.
    streams = device_ngram_ids_multi(byte_ids, byte_lengths, ngram_lo,
                                     ngram_hi, vocab_size, seed)
    for n in range(ngram_lo, ngram_hi + 1):
        total_len = total_len + jnp.maximum(byte_lengths - (n - 1), 0)
    counts = tf_counts_masked(
        jnp.concatenate([i for i, _ in streams], axis=1),
        jnp.concatenate([v for _, v in streams], axis=1), vocab_size)
    df = df_from_counts(counts)
    if df_reduce is not None:
        df = df_reduce(df)
    scores = tfidf_dense(counts, total_len, df, num_docs, score_dtype)
    if topk is not None:
        tv, ti = topk_per_doc(scores, min(topk, vocab_size))
        return df, total_len, tv, ti
    return counts, df, total_len, scores


_chargram_forward_jit = jax.jit(
    _chargram_forward,
    static_argnames=("vocab_size", "ngram_lo", "ngram_hi", "seed",
                     "score_dtype", "topk"),
)


def _chargram_sparse_forward(byte_ids, byte_lengths, num_docs, *,
                             vocab_size: int, ngram_lo: int, ngram_hi: int,
                             seed: int, score_dtype, topk: int,
                             df_reduce=None):
    """Row-sparse device chargram: raw bytes -> (df, topk) with NO
    [D, V] histogram — the wide-vocab lowering (BASELINE config 4's
    point is vocab >> 2^16, where the dense [D, V] counts matrix is
    the thing that cannot exist: 1024 docs x 2^20 x int32 = 4 GB).

    The (hi-lo+1) rolling-hash id streams concatenate along the token
    axis with their validity masks (windows never span documents, so
    concatenation is safe), then the ordinary sort+RLE engine runs on
    the masked stream (``sorted_term_counts_masked``). docSize is the
    total n-gram count, identical to the dense path's.
    """
    from tfidf_tpu.ops.hashing import device_ngram_ids_multi
    from tfidf_tpu.ops.sparse import (sorted_term_counts_masked, sparse_df,
                                      sparse_scores, sparse_topk)

    d, _ = byte_ids.shape
    ids_parts, valid_parts = [], []
    total_len = jnp.zeros((d,), jnp.int32)
    streams = device_ngram_ids_multi(byte_ids, byte_lengths, ngram_lo,
                                     ngram_hi, vocab_size, seed)
    for n, (ids, valid) in zip(range(ngram_lo, ngram_hi + 1), streams):
        ids_parts.append(ids)
        valid_parts.append(valid)
        total_len = total_len + jnp.maximum(byte_lengths - (n - 1), 0)
    s_ids, counts, head = sorted_term_counts_masked(
        jnp.concatenate(ids_parts, axis=1),
        jnp.concatenate(valid_parts, axis=1))
    df = sparse_df(s_ids, head, vocab_size)
    if df_reduce is not None:
        df = df_reduce(df)
    from tfidf_tpu.ops.scoring import idf_from_df
    idf = idf_from_df(df, num_docs, score_dtype)
    scores = sparse_scores(s_ids, counts, head, total_len, idf)
    tv, ti = sparse_topk(scores, s_ids, head, topk)
    return df, total_len, tv, ti


_chargram_sparse_forward_jit = jax.jit(
    _chargram_sparse_forward,
    static_argnames=("vocab_size", "ngram_lo", "ngram_hi", "seed",
                     "score_dtype", "topk"),
)


class TfidfPipeline(PhaseTimedMixin):
    """Configured TF-IDF runner: corpus in, scored records out.

    ``timer`` (a :class:`~tfidf_tpu.utils.timing.PhaseTimer`) attaches
    phase observability to the product path — pack / transfer / compute /
    fetch wall-clock accumulate into it. When timing, device work is
    fenced with ``block_until_ready`` so phases measure real completion,
    not dispatch; without a timer no fence is added and XLA's async
    dispatch overlaps freely.
    """

    def __init__(self, config: Optional[PipelineConfig] = None,
                 timer: Optional["PhaseTimer"] = None):
        from tfidf_tpu import obs
        from tfidf_tpu.config import apply_compile_cache
        self.config = config or PipelineConfig()
        self.timer = timer
        # Persistent XLA compile cache (round 8): the batch path's
        # forward programs persist across CLI cold-starts too.
        apply_compile_cache(getattr(self.config, "compile_cache", None))
        # Span tracer, same wiring shape (config.trace /
        # TFIDF_TPU_TRACE): every _phase marker then lands on the
        # trace timeline as well as the PhaseTimer.
        obs.configure(getattr(self.config, "trace", None))

    def pack(self, corpus: Corpus, pad_docs_to: Optional[int] = None) -> PackedBatch:
        with self._phase("pack"):
            return pack_corpus(corpus, self.config, pad_docs_to)

    def _mesh_pipeline(self):
        """Build the ShardedPipeline described by ``config.mesh_shape``.

        The config-driven mesh entry point: ``mesh_shape={"docs": 4,
        "vocab": 2}`` dispatches the run onto a device mesh with those
        axis sizes (missing axes default to docs=all-remaining, seq=1,
        vocab=1). The handed-off config has ``mesh_shape`` cleared — the
        MeshPlan is authoritative from there down.
        """
        from tfidf_tpu.parallel.mesh import MeshPlan
        from tfidf_tpu.parallel.sharded import ShardedPipeline

        shape = dict(self.config.mesh_shape)
        unknown = set(shape) - {"docs", "seq", "vocab"}
        if unknown:
            raise ValueError(f"mesh_shape axes {sorted(unknown)} unknown; "
                             "valid axes: docs, seq, vocab")
        plan = MeshPlan.create(docs=shape.get("docs", 0),
                               seq=shape.get("seq", 1),
                               vocab=shape.get("vocab", 1))
        cfg = dataclasses.replace(self.config, mesh_shape={})
        # replace() re-runs __post_init__ with the resolved engine, which
        # would mark a measured default as explicit — carry the flag so
        # ShardedPipeline can still apply its capability fallback.
        object.__setattr__(cfg, "_engine_defaulted",
                           getattr(self.config, "_engine_defaulted", False))
        return ShardedPipeline(plan, cfg, timer=self.timer)

    def _fetch_topk(self, df_dev, tv_dev, ti_dev, vocab_size: int):
        """One-round-trip fetch of (df, topk) — the minibatch twin of
        the overlapped ingest's result wire. On the packed wire (the
        default when the word can carry the run, ops/downlink) the
        [D, K] selection crosses the link as uint32 words packed on
        device — half the pair bytes; scores land within fp16/bf16
        rounding, ids bit-exact. ``result_wire="pair"`` (or any
        fallback condition) keeps the full-precision legacy fetch."""
        if use_packed_result_wire(self.config, vocab_size=vocab_size):
            words = pack_words(tv_dev, ti_dev)
            df, words_h = jax.device_get((df_dev, words))
            tv, ti = unpack_result_words(
                words_h, score_dtype=self.config.score_dtype)
            return df, tv, ti
        return jax.device_get((df_dev, tv_dev, ti_dev))

    def _place(self, batch):
        """Device placement of either wire format. A PackedBatch ships
        the padded [D, L] ids verbatim; a RaggedBatch ships the flat
        aligned stream (bytes scale with real tokens, not D×L) and the
        padded batch is rebuilt ON DEVICE (``ingest.rebuild_padded``) —
        the minibatch twin of the overlapped ingest's ragged wire."""
        lens = jnp.asarray(batch.lengths)
        if isinstance(batch, RaggedBatch):
            from tfidf_tpu.ingest import rebuild_padded
            return rebuild_padded(jnp.asarray(batch.flat), lens,
                                  length=batch.length,
                                  align=batch.align), lens
        return jnp.asarray(batch.token_ids), lens

    def run_packed(self, batch: PackedBatch) -> PipelineResult:
        cfg = self.config
        if cfg.mesh_shape:
            # Mesh wire stays padded by doctrine (the shard_map bodies
            # take [D, L] rows); a ragged minibatch rebuilds on host.
            if isinstance(batch, RaggedBatch):
                batch = batch.to_padded()
            return self._mesh_pipeline().run_packed(batch)
        if cfg.engine == "sparse":
            return self._run_sparse(batch)
        if cfg.use_pallas:
            from tfidf_tpu.ops.pallas_kernels import default_interpret
            interpret = default_interpret()
        else:
            interpret = False
        with self._phase("transfer"):
            toks, lens = self._place(batch)
            self._fence((toks, lens))
        with self._phase("compute"):
            out = _forward_jit(
                toks, lens,
                jnp.int32(batch.num_docs), vocab_size=batch.vocab_size,
                chunk=cfg.doc_chunk, score_dtype=jnp.dtype(cfg.score_dtype),
                topk=cfg.topk, use_pallas=cfg.use_pallas,
                pallas_interpret=interpret)
            self._fence(out)
        # topk mode: neither counts nor scores cross the host boundary —
        # only DF [V] and the [D, K] selection do. One device_get for all
        # outputs: transfers pipeline into a single round trip, which
        # matters when the device link is latency-bound; the selection
        # rides the packed word wire when it can (_fetch_topk).
        with self._phase("fetch"):
            if cfg.topk is not None:
                out = self._fetch_topk(*out, vocab_size=batch.vocab_size)
            else:
                out = jax.device_get(out)
        result = PipelineResult(
            counts=None if cfg.topk is not None else out[0],
            lengths=np.asarray(batch.lengths),
            df=out[0 if cfg.topk is not None else 1],
            num_docs=batch.num_docs,
            names=batch.names,
            id_to_word=batch.id_to_word or {},
        )
        if cfg.topk is not None:
            result.topk_vals = out[1]
            result.topk_ids = out[2]
        else:
            result.scores = out[2]
        return result

    def _run_sparse(self, batch: PackedBatch) -> PipelineResult:
        """Row-sparse engine: O(D x L) memory, no [D, V] materialization."""
        cfg = self.config
        with self._phase("transfer"):
            toks, lens = self._place(batch)
            self._fence((toks, lens))
        with self._phase("compute"):
            out = _sparse_forward_jit(
                toks, lens,
                jnp.int32(batch.num_docs), vocab_size=batch.vocab_size,
                score_dtype=jnp.dtype(cfg.score_dtype), topk=cfg.topk)
            self._fence(out)
        with self._phase("fetch"):
            if cfg.topk is not None:  # packed word wire when it can
                out = self._fetch_topk(*out, vocab_size=batch.vocab_size)
            else:
                out = jax.device_get(out)  # all outputs, one round trip
        result = PipelineResult(
            counts=None,
            lengths=np.asarray(batch.lengths),
            df=out[0],
            num_docs=batch.num_docs,
            names=batch.names,
            id_to_word=batch.id_to_word or {},
        )
        if cfg.topk is not None:
            result.topk_vals = out[1]
            result.topk_ids = out[2]
        else:
            result.sparse_ids = out[1]
            result.sparse_counts = out[2]
            result.sparse_head = out[3]
            result.scores = None  # dense scores deliberately not built
        return result

    def run_bytes(self, corpus: Corpus) -> PipelineResult:
        """On-device chargram path: ship raw bytes, hash n-grams on TPU."""
        from tfidf_tpu.config import TokenizerKind, VocabMode
        from tfidf_tpu.io.corpus import pack_bytes

        cfg = self.config
        if cfg.tokenizer is not TokenizerKind.CHARGRAM:
            raise ValueError("run_bytes is the chargram device path")
        if cfg.vocab_mode is not VocabMode.HASHED:
            raise ValueError("device chargram requires HASHED vocab "
                             "(EXACT needs host-side n-gram strings)")
        lo, hi = cfg.ngram_range
        plan = None
        if cfg.mesh_shape:
            # Docs-sharded device chargram (docs axis only: n-gram
            # windows span adjacent bytes, so a seq shard would need a
            # halo exchange; vocab stays replicated like the sparse
            # engine). topk mode only — enforced by the maker.
            from tfidf_tpu.parallel.mesh import MeshPlan
            shape = dict(cfg.mesh_shape)
            if shape.get("seq", 1) != 1 or shape.get("vocab", 1) != 1:
                raise ValueError("device chargram shards docs only; use "
                                 "mesh_shape={'docs': N} (run() with the "
                                 "host tokenizer covers other meshes)")
            plan = MeshPlan.create(docs=shape.get("docs", 0))
        with self._phase("pack"):
            if plan is None:
                packed = pack_bytes(corpus)
            else:
                packed = pack_bytes(
                    corpus, pad_docs_to=plan.pad_docs(len(corpus)))
        with self._phase("transfer"):
            if plan is None:
                byte_ids = jnp.asarray(packed.byte_ids)
                byte_lens = jnp.asarray(packed.byte_lengths)
            else:
                byte_ids = jax.device_put(
                    packed.byte_ids, plan.sharding(plan.batch_spec()))
                byte_lens = jax.device_put(
                    packed.byte_lengths,
                    plan.sharding(plan.lengths_spec()))
            self._fence((byte_ids, byte_lens))
        # Lowering choice: explicit engine="sparse" always gets the
        # row-sparse chargram; a measured DEFAULT keeps the dense
        # histogram up to 2^16 (the round-3 measured configuration) and
        # switches to sparse beyond it, where the dense [D, V] counts
        # matrix is the thing that cannot exist (wide-vocab stress,
        # BASELINE config 4).
        use_sparse = (cfg.engine == "sparse"
                      and (not getattr(cfg, "_engine_defaulted", False)
                           or cfg.vocab_size > (1 << 16)))
        with self._phase("compute"):
            if plan is None:
                fwd_jit = (_chargram_sparse_forward_jit if use_sparse
                           else _chargram_forward_jit)
                out = fwd_jit(
                    byte_ids, byte_lens,
                    jnp.int32(packed.num_docs), vocab_size=cfg.vocab_size,
                    ngram_lo=lo, ngram_hi=hi, seed=cfg.hash_seed,
                    score_dtype=jnp.dtype(cfg.score_dtype), topk=cfg.topk)
            else:
                from tfidf_tpu.parallel.collectives import \
                    make_chargram_sharded_forward
                fwd = make_chargram_sharded_forward(
                    plan, cfg.vocab_size, lo, hi, cfg.hash_seed,
                    jnp.dtype(cfg.score_dtype), cfg.topk,
                    engine="sparse" if use_sparse else "dense")
                out = fwd(byte_ids, byte_lens, jnp.int32(packed.num_docs))
            self._fence(out)
        with self._phase("fetch"):
            out = jax.device_get(out)  # single transfer round trip
        if cfg.topk is not None:
            return PipelineResult(
                counts=None, lengths=out[1], df=out[0],
                num_docs=packed.num_docs, names=packed.names, id_to_word={},
                topk_vals=out[2], topk_ids=out[3])
        return PipelineResult(
            counts=out[0], lengths=out[2],
            df=out[1], num_docs=packed.num_docs,
            names=packed.names, id_to_word={}, scores=out[3])

    def run(self, corpus: Corpus) -> PipelineResult:
        from tfidf_tpu.config import TokenizerKind, VocabMode

        cfg = self.config
        # Device chargram serves topk runs only: it has no word strings
        # (id_to_word stays empty -> no full output lines). Everything
        # else takes the host tokenizer path.
        # Both engines now have device-chargram lowerings (dense
        # histogram and the round-4 row-sparse wide-vocab path), so the
        # engine no longer gates the device route — run_bytes picks the
        # lowering.
        chargram_device = (
            cfg.tokenizer is TokenizerKind.CHARGRAM
            and cfg.vocab_mode is VocabMode.HASHED
            and cfg.chargram_on_device
            and cfg.topk is not None)
        if cfg.mesh_shape:
            # Docs-only meshes keep the device chargram path (sharded
            # via shard_map, collectives.make_chargram_sharded_forward);
            # seq/vocab meshes fall back to the host tokenizer.
            shape = dict(cfg.mesh_shape)
            if (chargram_device and shape.get("seq", 1) == 1
                    and shape.get("vocab", 1) == 1):
                return self.run_bytes(corpus)
            return self._mesh_pipeline().run(corpus)
        if chargram_device:
            return self.run_bytes(corpus)
        return self.run_packed(self.pack(corpus))
