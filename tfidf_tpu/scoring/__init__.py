"""The pluggable scorer family (round 23) — ROADMAP item 4.

Every retrieval path in this repo scores documents through ONE sparse
kernel: a row-sparse ``(data, cols)`` doc block dotted against a dense
``[V, Q]`` query block, masked by a live vector, selected by a
streaming top-k (``ops.sparse.score_topk_tiled`` and its untiled
fallback ``ops.topk.segment_score_topk``). That kernel never knew it
was computing TF-IDF: the scorer lives entirely in how the doc weights
and the query columns are PRE-computed. This package makes that
explicit — a :class:`ScorerSpec` names the precomputation family:

* ``tfidf`` (default): L2-normalized ``tf * log(N/df)`` doc rows x
  cosine query columns — byte-for-byte today's arrays, so the default
  path is bit-identical to the pre-subsystem output by construction.
* ``bm25`` (k1, b): Lucene-idf saturated term weights on the doc side
  (:func:`bm25_weights`), RAW term counts on the query side — BM25 is
  the same sparse dot because the whole formula except the query's
  term count factorizes into the per-(doc, term) weight.
* field weights: title/body sub-indexes stacked along the slot axis
  sharing one vocab; the weighted sum across fields IS the single
  row's dot (``TfidfRetriever.index_fields``).

Query-time document filters (:mod:`tfidf_tpu.scoring.filters`) fold
into the same live mask tombstones already ride — a filtered-out doc
scores the sub-zero sentinel and can never surface.

:mod:`tfidf_tpu.scoring.oracle` is the NumPy reference every variant
is pinned bit-identical against (ids + tie order;
tests/test_scoring_family.py).

Import-time contract: this package imports no jax at module scope
(``config.py`` validates scorer specs without a backend); the traced
helpers import jax lazily inside jitted callers.
"""

from tfidf_tpu.scoring.family import (DEFAULT_B, DEFAULT_K1, ScorerSpec,
                                      bm25_face_trace, bm25_idf_from_df,
                                      bm25_weights, parse_scorer,
                                      resolve_scorer, scorer_key)
from tfidf_tpu.scoring.filters import (FilterSpec, filter_key,
                                       filter_mask, parse_filter)

__all__ = [
    "ScorerSpec", "parse_scorer", "scorer_key", "resolve_scorer",
    "DEFAULT_K1", "DEFAULT_B",
    "bm25_idf_from_df", "bm25_weights", "bm25_face_trace",
    "FilterSpec", "parse_filter", "filter_key", "filter_mask",
]
