"""Query-time document filters (round 23).

A filter is a per-request predicate over documents — a tenant
allowlist, an id range, a name prefix — applied BEFORE top-k by
folding into the live mask the tombstone machinery already threads
through every scoring path: a filtered-out row scores the sub-zero
``_DEAD`` sentinel (``ops/topk.py``) and can never surface, the exact
mechanism a deleted doc already uses. Composition with tombstones is
therefore a boolean AND, and the parity argument for masked scoring
carries over unchanged.

Filters are query-time VISIBILITY, not corpus mutation: corpus
statistics (df, idf, avgdl, N) deliberately stay global — two tenants
querying the same index see the same term weights, only different
candidate sets. (Tombstones are the opposite by design: a deleted doc
leaves the statistics too.)

Spec forms (the JSONL ``"filter"`` field / ``submit(filter=...)``):

* ``{"ids": [3, 17, 42]}`` — explicit doc-row allowlist;
* ``{"id_range": [lo, hi]}`` — half-open row range;
* ``{"prefix": "tenantA/"}`` — doc-NAME prefix allowlist.

:func:`filter_key` is the canonical JSON string (``""`` = no filter)
— the serve batcher's group component and result-cache key component,
and invertible via :func:`parse_filter` so a batch group round-trips.
"""

from __future__ import annotations

import dataclasses
import json
from typing import Optional, Sequence, Tuple, Union

import numpy as np

_KINDS = ("ids", "id_range", "prefix")


@dataclasses.dataclass(frozen=True)
class FilterSpec:
    """One parsed document filter (see module docstring)."""

    kind: str
    ids: Tuple[int, ...] = ()
    lo: int = 0
    hi: int = 0
    prefix: str = ""

    def __post_init__(self):
        if self.kind not in _KINDS:
            raise ValueError(f"unknown filter kind {self.kind!r} "
                             f"(choose one of {', '.join(_KINDS)})")
        if self.kind == "id_range" and self.hi < self.lo:
            raise ValueError(
                f"bad id_range [{self.lo}, {self.hi}): hi < lo")

    def key(self) -> str:
        """Canonical JSON (sorted keys, normalized values) — equal
        filters produce equal keys, and ``parse_filter(json.loads(
        key))`` round-trips."""
        if self.kind == "ids":
            body = {"ids": sorted(set(self.ids))}
        elif self.kind == "id_range":
            body = {"id_range": [self.lo, self.hi]}
        else:
            body = {"prefix": self.prefix}
        return json.dumps(body, sort_keys=True, separators=(",", ":"))


def parse_filter(spec: Union[None, str, dict, FilterSpec]
                 ) -> Optional[FilterSpec]:
    """Anything-to-spec: None/"" (no filter), a spec (pass-through), a
    dict (the JSONL form), or a canonical-JSON string (the group-key
    form)."""
    if spec is None or spec == "":
        return None
    if isinstance(spec, FilterSpec):
        return spec
    if isinstance(spec, str):
        try:
            spec = json.loads(spec)
        except ValueError as e:
            raise ValueError(f"bad filter string {spec!r}: {e}") from e
        if spec is None:
            return None
    if not isinstance(spec, dict):
        raise ValueError(f"cannot parse filter spec {spec!r}")
    unknown = set(spec) - set(_KINDS)
    if unknown:
        raise ValueError(f"unknown filter fields {sorted(unknown)} "
                         f"(choose one of {', '.join(_KINDS)})")
    if len(spec) != 1:
        raise ValueError(f"filter must name exactly one of "
                         f"{', '.join(_KINDS)} (got {sorted(spec)})")
    if "ids" in spec:
        ids = spec["ids"]
        if (not isinstance(ids, (list, tuple))
                or not all(isinstance(i, int) and not isinstance(i, bool)
                           for i in ids)):
            raise ValueError("filter 'ids' must be a list of ints")
        return FilterSpec(kind="ids", ids=tuple(int(i) for i in ids))
    if "id_range" in spec:
        rng = spec["id_range"]
        if (not isinstance(rng, (list, tuple)) or len(rng) != 2
                or not all(isinstance(i, int) and not isinstance(i, bool)
                           for i in rng)):
            raise ValueError(
                "filter 'id_range' must be [lo, hi] ints (half-open)")
        return FilterSpec(kind="id_range", lo=int(rng[0]),
                          hi=int(rng[1]))
    prefix = spec["prefix"]
    if not isinstance(prefix, str):
        raise ValueError("filter 'prefix' must be a string")
    return FilterSpec(kind="prefix", prefix=prefix)


def filter_key(spec: Union[None, str, dict, FilterSpec]) -> str:
    """Canonical key of any spec form; ``""`` = no filter."""
    fspec = parse_filter(spec)
    return "" if fspec is None else fspec.key()


def filter_mask(fspec: FilterSpec, num_docs: int,
                names: Optional[Sequence[Optional[str]]] = None
                ) -> np.ndarray:
    """``[num_docs]`` bool allow-mask of one filter over doc rows.
    ``names`` (positional, ``names[row]``) is only consulted by the
    prefix kind; rows with no name (segmented padding) never match."""
    mask = np.zeros((num_docs,), bool)
    if fspec.kind == "ids":
        rows = [i for i in fspec.ids if 0 <= i < num_docs]
        if rows:
            mask[np.asarray(rows, np.int64)] = True
    elif fspec.kind == "id_range":
        lo = max(0, fspec.lo)
        hi = min(num_docs, fspec.hi)
        if hi > lo:
            mask[lo:hi] = True
    else:
        if names is None:
            raise ValueError(
                "prefix filters need the doc-name table")
        pre = fspec.prefix
        for row in range(min(num_docs, len(names))):
            name = names[row]
            if name is not None and name.startswith(pre):
                mask[row] = True
    return mask
