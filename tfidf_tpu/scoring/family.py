"""Scorer specs + the BM25 weight math (round 23).

Two layers live here:

* **Spec parsing** (host, jax-free): :class:`ScorerSpec`,
  :func:`parse_scorer`, :func:`scorer_key` — one canonical string form
  (``"tfidf"``, ``"bm25:b=0.75,k1=1.2"``) that round-trips through the
  serve batcher's group key, the result-cache key, snapshot meta and
  the JSONL protocol's per-request ``"scorer"`` field.

* **Traced weight math** (device, shared): :func:`bm25_idf_from_df`
  and :func:`bm25_weights` are the ONE elementwise float sequence both
  the flat retriever's lazy face derivation and the segmented index's
  per-part refresh run — XLA preserves IEEE elementwise semantics, so
  flat-vs-segmented BM25 bit-parity holds the same way the tfidf
  ``refresh_weights`` parity always has.

BM25 factorization: with Lucene idf
``log1p((N - df + 0.5) / (df + 0.5))`` (always > 0 for df >= 1 — the
``vals > 0`` result-mask semantics survive) the per-(doc, term) weight

    w(d, t) = idf(t) * c * (k1 + 1) / (c + k1 * (1 - b + b * dl/avgdl))

absorbs everything except the query's raw term count, so BM25(q, d) =
``sum_t count_q(t) * w(d, t)`` — exactly the sparse dot the tiled
kernel already computes. ``k1``/``b`` enter as TRACED f32 scalars
(changing them re-derives a face, never re-compiles a program), and
``avgdl`` is computed identically everywhere as
``float32(exact-int total live length) / float32(num live docs)``.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Optional, Union

DEFAULT_K1 = 1.2
DEFAULT_B = 0.75

_KINDS = ("tfidf", "bm25")


@dataclasses.dataclass(frozen=True)
class ScorerSpec:
    """One member of the scorer family. ``k1``/``b`` are only
    meaningful for ``bm25``; they are normalized to the defaults for
    ``tfidf`` so spec equality == scoring equality."""

    kind: str = "tfidf"
    k1: float = DEFAULT_K1
    b: float = DEFAULT_B

    def __post_init__(self):
        if self.kind not in _KINDS:
            raise ValueError(f"unknown scorer {self.kind!r} "
                             f"(choose one of {', '.join(_KINDS)})")
        if self.kind == "tfidf":
            object.__setattr__(self, "k1", DEFAULT_K1)
            object.__setattr__(self, "b", DEFAULT_B)
        if not self.k1 >= 0:
            raise ValueError(f"bm25 k1 must be >= 0 (got {self.k1})")
        if not 0 <= self.b <= 1:
            raise ValueError(f"bm25 b must be in [0, 1] (got {self.b})")

    @property
    def is_default(self) -> bool:
        return self.kind == "tfidf"

    def key(self) -> str:
        """The canonical string form — parseable by
        :func:`parse_scorer`, stable under float formatting, the
        batch-group / cache-key / snapshot-meta representation."""
        if self.kind == "tfidf":
            return "tfidf"
        return f"bm25:b={self.b:g},k1={self.k1:g}"


def parse_scorer(spec: Union[None, str, dict, ScorerSpec]) -> ScorerSpec:
    """Anything-to-spec: None (default tfidf), a spec (pass-through),
    a dict (``{"kind": "bm25", "k1": 1.5}`` — the JSONL form), or a
    string (``"bm25"``, ``"bm25:k1=1.5,b=0.6"`` — the CLI/key form)."""
    if spec is None:
        return ScorerSpec()
    if isinstance(spec, ScorerSpec):
        return spec
    if isinstance(spec, dict):
        unknown = set(spec) - {"kind", "k1", "b"}
        if unknown:
            raise ValueError(f"unknown scorer fields {sorted(unknown)}")
        return ScorerSpec(kind=str(spec.get("kind", "tfidf")),
                          k1=float(spec.get("k1", DEFAULT_K1)),
                          b=float(spec.get("b", DEFAULT_B)))
    if not isinstance(spec, str):
        raise ValueError(f"cannot parse scorer spec {spec!r}")
    text = spec.strip()
    kind, _, params = text.partition(":")
    kw = {"kind": kind.strip().lower()}
    if params.strip():
        for part in params.split(","):
            name, _, val = part.partition("=")
            name = name.strip().lower()
            if name not in ("k1", "b") or not val.strip():
                raise ValueError(
                    f"bad scorer param {part!r} in {spec!r} "
                    f"(expected k1=<float> / b=<float>)")
            kw[name] = float(val)
    return ScorerSpec(**kw)


def scorer_key(spec: Union[None, str, dict, ScorerSpec]) -> str:
    """Canonical key of any spec form (``parse_scorer(x).key()``)."""
    return parse_scorer(spec).key()


def resolve_scorer(explicit: Union[None, str, dict, ScorerSpec] = None
                   ) -> ScorerSpec:
    """Resolve the index-default scorer: explicit setting >
    ``TFIDF_TPU_SCORER`` (with ``TFIDF_TPU_BM25_K1`` /
    ``TFIDF_TPU_BM25_B`` riding along for a bare ``bm25``) > tfidf."""
    if explicit is not None:
        return parse_scorer(explicit)
    raw = os.environ.get("TFIDF_TPU_SCORER", "").strip()
    if not raw:
        return ScorerSpec()
    spec = parse_scorer(raw)
    if spec.kind == "bm25" and ":" not in raw:
        k1 = os.environ.get("TFIDF_TPU_BM25_K1", "").strip()
        b = os.environ.get("TFIDF_TPU_BM25_B", "").strip()
        spec = ScorerSpec(kind="bm25",
                          k1=float(k1) if k1 else DEFAULT_K1,
                          b=float(b) if b else DEFAULT_B)
    return spec


def spec_from_parts(kind: Optional[str], k1: Optional[float],
                    b: Optional[float]) -> ScorerSpec:
    """Compose a spec from the serve config's three optional knobs
    (``--scorer`` / ``--bm25-k1`` / ``--bm25-b``). A ``--scorer``
    carrying inline params (``"bm25:k1=1.5"``) wins outright — the
    standalone knobs only flesh out a bare kind."""
    if kind and ":" in kind:
        return parse_scorer(kind)
    return ScorerSpec(kind=(kind or "tfidf").strip().lower(),
                      k1=DEFAULT_K1 if k1 is None else float(k1),
                      b=DEFAULT_B if b is None else float(b))


# --- traced BM25 weight math (jax imported lazily) --------------------


def bm25_idf_from_df(df, num_docs, dtype=None):
    """Lucene BM25 idf: ``log1p((N - df + 0.5) / (df + 0.5))``, 0
    where df == 0 (empty hashed buckets). Strictly positive for every
    present term — unlike the raw Robertson idf, which goes negative
    past df > N/2 and would break the repo-wide ``vals > 0``
    real-result mask."""
    import jax.numpy as jnp
    dtype = dtype or jnp.float32
    dff = df.astype(dtype)
    n = jnp.asarray(num_docs, dtype)
    half = jnp.asarray(0.5, dtype)
    idf = jnp.log1p((n - dff + half) / (dff + half))
    return jnp.where(df > 0, idf, jnp.zeros((), dtype))


def bm25_weights(ids, counts, head, lengths, idf, avgdl, k1, b):
    """Per-slot BM25 doc weights + dense-safe columns.

    Args (all traced): row-sparse triple ``ids/counts/head [D, L]``,
    ``lengths [D]`` (token count per doc), ``idf [V]`` (from
    :func:`bm25_idf_from_df`), scalars ``avgdl``/``k1``/``b`` (f32).

    Returns ``(data [D, L] f32, cols [D, L] i32)`` — zeros / column 0
    off-head, ready for the tiled kernel. ONE elementwise sequence:
    every face derivation (flat lazy face, segmented per-part refresh,
    fielded slices) runs exactly this, which is the whole
    cross-path bit-parity argument.
    """
    import jax.numpy as jnp
    f32 = jnp.float32
    c = counts.astype(f32)
    dl = jnp.maximum(lengths, 1).astype(f32)[:, None]
    k1 = jnp.asarray(k1, f32)
    b = jnp.asarray(b, f32)
    one = jnp.asarray(1.0, f32)
    sat = (c * (k1 + one)) / (c + k1 * (one - b + b * (dl / avgdl)))
    safe = jnp.where(head, ids, 0)
    data = jnp.where(head, idf[safe] * sat, jnp.zeros((), f32))
    return data.astype(f32), safe.astype(jnp.int32)


def bm25_face_trace(ids, head, num_docs, avgdl, k1, b, *,
                    vocab_size: int):
    """BM25 face from a STORED flat index's ``(ids, head)`` alone —
    counts/lengths/df are all re-derivable because padding slots carry
    the INT32_MAX sort sentinel: lengths = non-sentinel count, counts
    via the run-length trick (``sorted_term_counts_masked`` over the
    already-sorted rows is the identity sort), df via ``sparse_df``.
    This is what lets the snapshot format and ``_build_index`` stay
    byte-identical to round 22 — BM25 is a derived view, not a stored
    one."""
    import jax.numpy as jnp

    from tfidf_tpu.ops.sparse import sorted_term_counts_masked, sparse_df

    valid = ids != jnp.iinfo(jnp.int32).max
    _, counts, _ = sorted_term_counts_masked(ids, valid)
    lengths = valid.sum(axis=1, dtype=jnp.int32)
    df = sparse_df(ids, head, vocab_size)
    idf = bm25_idf_from_df(df, num_docs)
    return bm25_weights(ids, counts, head, lengths, idf, avgdl, k1, b)


def doc_lengths_host(ids) -> "object":
    """Host int64 per-row token counts of a stored flat index (the
    non-sentinel slot count) — the exact-integer numerator of avgdl."""
    import numpy as np
    arr = np.asarray(ids)
    return (arr != np.iinfo(np.int32).max).sum(axis=1).astype(np.int64)


def avgdl_f32(total_len: int, num_docs: int):
    """THE avgdl: float32(exact-int total) / float32(N) — a single
    correctly-rounded divide of two exactly-converted integers, so
    every path (flat, segmented, mesh, oracle) that feeds the same
    integers gets the same float32 bits."""
    import numpy as np
    n = max(1, int(num_docs))
    return np.float32(np.float32(int(total_len)) / np.float32(n))
