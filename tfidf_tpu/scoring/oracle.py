"""NumPy scoring oracle (round 23) — the reference every scorer
variant is pinned against.

Pure numpy, no jax: independent float32 mirrors of the face math
(:func:`tfidf_face`, :func:`bm25_face`) plus a dense ranked search
(:func:`oracle_topk`) with the repo's exact result conventions —
scores-desc / lowest-row tie order (``lax.top_k`` discipline), dead
rows masked by the sub-zero sentinel, non-positive results masked to
``(0.0, -1)``.

Parity contract (tests/test_scoring_family.py): doc IDS and TIE ORDER
are asserted bit-identical between the device paths and this oracle;
score values are asserted ``allclose``. Two float32 degrees of
freedom remain and are deliberately tolerated: accumulation order
across L slots, and XLA's elementwise fusion (FMA contraction puts
the derived weight arrays within 1 ulp of the numpy mirrors, not
bit-equal). Neither can reorder documents whose score gap exceeds
that noise, which the suite's seeded corpora guarantee.
"""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np

_DEAD = np.float32(-1.0)


def counts_from_sorted(ids: np.ndarray, head: np.ndarray
                       ) -> Tuple[np.ndarray, np.ndarray]:
    """Numpy mirror of the stored-index stats derivation: per-row
    ``(counts [D, L], lengths [D])`` from a SORTED row-sparse ``ids``
    (INT32_MAX padding sentinels) and its ``head`` mask — the same
    run-length trick ``ops.sparse._sorted_counts_core`` runs, in exact
    integer arithmetic."""
    ids = np.asarray(ids, np.int32)
    head = np.asarray(head, bool)
    d, length = ids.shape
    lengths = (ids != np.iinfo(np.int32).max).sum(axis=1).astype(
        np.int32)
    pos = np.arange(length, dtype=np.int32)[None, :]
    hpos = np.where(head, pos, length).astype(np.int32)
    suffix_min = np.minimum.accumulate(hpos[:, ::-1], axis=1)[:, ::-1]
    next_head = np.concatenate(
        [suffix_min[:, 1:], np.full((d, 1), length, np.int32)], axis=1)
    counts = (np.minimum(next_head, lengths[:, None]) - pos).astype(
        np.int32)
    return counts, lengths


def df_from_sorted(ids: np.ndarray, head: np.ndarray, vocab_size: int,
                   live: Optional[np.ndarray] = None) -> np.ndarray:
    """Exact-integer DF over (optionally live-masked) rows."""
    head = np.asarray(head, bool)
    if live is not None:
        head = head & np.asarray(live, bool)[:, None]
    terms = np.asarray(ids, np.int64)[head]
    return np.bincount(terms, minlength=vocab_size)[:vocab_size].astype(
        np.int64)


def tfidf_idf(df: np.ndarray, num_docs: int) -> np.ndarray:
    """float32 mirror of ``ops.scoring.idf_from_df``."""
    df = np.asarray(df)
    dff = df.astype(np.float32)
    n = np.float32(num_docs)
    with np.errstate(divide="ignore"):
        idf = np.log(n / np.maximum(dff, np.float32(1.0)))
    return np.where(df > 0, idf, np.float32(0.0)).astype(np.float32)


def bm25_idf(df: np.ndarray, num_docs: int) -> np.ndarray:
    """float32 mirror of ``scoring.family.bm25_idf_from_df``."""
    df = np.asarray(df)
    dff = df.astype(np.float32)
    n = np.float32(num_docs)
    half = np.float32(0.5)
    idf = np.log1p((n - dff + half) / (dff + half))
    return np.where(df > 0, idf, np.float32(0.0)).astype(np.float32)


def tfidf_face(ids, counts, head, lengths, df, num_docs
               ) -> Tuple[np.ndarray, np.ndarray]:
    """L2-normalized tf-idf doc face — ``_build_index``'s float
    sequence in numpy. Returns ``(data, cols)``."""
    head = np.asarray(head, bool)
    idf = tfidf_idf(df, num_docs)
    lens = np.maximum(np.asarray(lengths), 1).astype(np.float32)[:, None]
    safe = np.where(head, np.asarray(ids), 0)
    score = np.asarray(counts).astype(np.float32) / lens * idf[safe]
    score = np.where(head, score, np.float32(0.0))
    norm = np.sqrt((score * score).sum(axis=1, keepdims=True,
                                       dtype=np.float32))
    weights = score / np.maximum(norm, np.float32(1e-30))
    return (weights.astype(np.float32),
            safe.astype(np.int32))


def bm25_face(ids, counts, head, lengths, df, num_docs, avgdl, k1, b
              ) -> Tuple[np.ndarray, np.ndarray]:
    """BM25 doc face — ``scoring.family.bm25_weights`` in numpy.
    Returns ``(data, cols)``."""
    head = np.asarray(head, bool)
    idf = bm25_idf(df, num_docs)
    c = np.asarray(counts).astype(np.float32)
    dl = np.maximum(np.asarray(lengths), 1).astype(np.float32)[:, None]
    k1 = np.float32(k1)
    b = np.float32(b)
    one = np.float32(1.0)
    avgdl = np.float32(avgdl)
    # Padding slots (c == 0) divide 0/0 at k1 == 0; the where() below
    # masks them, so the transient NaN is expected, not an error.
    with np.errstate(invalid="ignore", divide="ignore"):
        sat = (c * (k1 + one)) / (c + k1 * (one - b + b * (dl / avgdl)))
    safe = np.where(head, np.asarray(ids), 0)
    data = np.where(head, idf[safe] * sat, np.float32(0.0))
    return data.astype(np.float32), safe.astype(np.int32)


def oracle_scores(data: np.ndarray, cols: np.ndarray,
                  qmat: np.ndarray) -> np.ndarray:
    """Dense ``[Q, D]`` float32 scores of a row-sparse face against a
    ``[V, Q]`` query block: ``score[q, d] = sum_l data[d, l] *
    qmat[cols[d, l], q]`` — the sparse dot, materialized."""
    data = np.asarray(data, np.float32)
    cols = np.asarray(cols)
    qmat = np.asarray(qmat, np.float32)
    q = qmat.shape[1]
    d = data.shape[0]
    out = np.empty((q, d), np.float32)
    for qi in range(q):
        contrib = data * qmat[:, qi][cols]
        out[qi] = contrib.sum(axis=1, dtype=np.float32)
    return out


def oracle_topk(data, cols, live, qmat, k: int
                ) -> Tuple[np.ndarray, np.ndarray]:
    """Ranked reference search: ``(vals, ids)`` each ``[Q, min(k, D)]``
    with the repo's exact conventions — sort by (score desc, row asc),
    dead rows (``live`` false) can never surface, and non-positive
    survivors mask to ``(0.0, -1)``."""
    scores = oracle_scores(data, cols, qmat)          # [Q, D]
    if live is not None:
        scores = np.where(np.asarray(live, bool)[None, :], scores,
                          _DEAD)
    q, d = scores.shape
    kk = min(int(k), d)
    rows = np.arange(d)
    vals = np.empty((q, kk), np.float32)
    ids = np.empty((q, kk), np.int64)
    for qi in range(q):
        order = np.lexsort((rows, -scores[qi]))[:kk]
        vals[qi] = scores[qi][order]
        ids[qi] = order
    ok = vals > 0
    return (np.where(ok, vals, np.float32(0.0)),
            np.where(ok, ids, -1))
